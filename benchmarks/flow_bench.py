"""Config-3b: L3-REALISTIC flow benchmark (VERDICT r3 next-step 6).

Same measurement methodology as the headline config 3 (utils/measure.py —
host-side op counting, synced median windows) but over engine/flow.py's
power-law/burst/deep-book streams, PLUS a separate decoded statistics pass
(apply_orders replay — never inside the timed windows, a decode readback
collapses the tunnel pipeline) reporting the flow-health figures the
uniform benchmark can't see: side-full reject rate, fill-overflow, fills
per op, and resting depth at end of replay.

Usage: python benchmarks/flow_bench.py --json-out out.json
       [--symbols 4096] [--capacity 128] [--batch 32]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--symbols", type=int, default=4096)
    p.add_argument("--capacity", type=int, default=128)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--windows", type=int, default=5)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--json-out", required=True)
    args = p.parse_args()

    import jax
    import numpy as np

    cache_dir = os.environ.get(
        "ME_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    t0 = time.perf_counter()
    devices = jax.devices()
    platform = devices[0].platform
    backend_init_s = time.perf_counter() - t0

    from matching_engine_tpu.engine.book import EngineConfig, init_book
    from matching_engine_tpu.engine.flow import realistic_order_stream
    from matching_engine_tpu.engine.harness import apply_orders, snapshot_books
    from matching_engine_tpu.engine.kernel import OP_SUBMIT, REJECTED
    from matching_engine_tpu.utils.measure import measure_device_throughput

    cfg = EngineConfig(num_symbols=args.symbols, capacity=args.capacity,
                       batch=args.batch, max_fills=1 << 17)
    streams = [
        realistic_order_stream(args.symbols, 4 * args.symbols * args.batch,
                               seed=w)
        for w in range(4)
    ]
    value, lat_us = measure_device_throughput(
        cfg, streams, windows=args.windows, iters=args.iters)

    # Decoded statistics pass — OUTSIDE the timed windows, fresh book.
    stats_stream = streams[0]
    book = init_book(cfg)
    book, results, fills = apply_orders(cfg, book, stats_stream)
    submits = sum(1 for o in stats_stream if o.op == OP_SUBMIT)
    rejects = sum(1 for r in results if r.status == REJECTED
                  and r.filled == 0 and r.remaining > 0)
    snaps = snapshot_books(book)
    depths = [len(b) + len(a) for b, a in snaps]
    depths.sort()

    try:
        import subprocess
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        rev = "unknown"
    out = {
        "metric": "l3_realistic_throughput",
        "value": round(value, 1),
        "unit": "orders/sec",
        "vs_baseline": round(value / 10_000_000, 4),
        "platform": platform,
        "n_devices": len(devices),
        "symbols": args.symbols,
        "capacity": args.capacity,
        "batch": args.batch,
        "backend_init_s": round(backend_init_s, 1),
        "mean_dispatch_latency_us": round(lat_us, 1),
        "flow": "power-law+bursts+deep-books+ioc-fok "
                "(engine/flow.py defaults)",
        "tif_p": 0.05,  # IOC/FOK share of submits (flow.py default);
                        # rows with "flow" lacking "+ioc-fok" predate it
        "stats_ops": len(stats_stream),
        "side_full_reject_rate": round(rejects / max(1, submits), 5),
        "fills_per_op": round(len(fills) / len(stats_stream), 4),
        "resting_depth_p50": depths[len(depths) // 2],
        "resting_depth_max": depths[-1],
        "git_rev": rev,
    }
    tmp = args.json_out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
    os.replace(tmp, args.json_out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
