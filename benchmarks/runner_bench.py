"""EngineRunner-level serving bench: the dispatch pipeline WITHOUT any RPC
edge or load generator (VERDICT r3 next-step 2: separate the serving
stack's own ceiling from tunnel RTT and loadgen artifacts).

Drives EngineRunner.dispatch_pipelined directly with pre-built EngineOp
batches at a serving-like shape (sparse dispatches, small batches), sweeping
the pipeline_inflight depth. Per sweep point it reports sustained orders/s
plus per-batch turnaround p50/p99 (stage -> finish callback), which is the
client-felt latency floor of the whole serving stack minus transport.

The serving-ceiling model this measures (docs/BENCH_METHOD.md):
  orders/s  ~=  batch_ops / max(host_batch_cost, sync_cost / inflight)
where sync_cost is the per-decode device round trip (~64ms tunneled, ~0
co-located with the async host-copy prefetch landing in time).

Usage: python benchmarks/runner_bench.py --json-out out.json
       [--symbols 64] [--capacity 256] [--batch 16]
       [--batch-ops 64] [--n-batches 60] [--inflight 1,2,4,8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--symbols", type=int, default=64)
    p.add_argument("--capacity", type=int, default=256)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--batch-ops", default="64",
                   help="ops per dispatched batch (the dispatcher's drain "
                        "size under load); comma list sweeps dispatch "
                        "size x inflight — under saturation the window "
                        "packs up to symbols*batch ops, so the ceiling "
                        "is a function of dispatch size, not just depth")
    p.add_argument("--n-batches", type=int, default=60)
    p.add_argument("--inflight", default="1,2,4,8")
    p.add_argument("--json-out", required=True)
    args = p.parse_args()

    import random

    import jax
    import numpy as np

    cache_dir = os.environ.get(
        "ME_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    t0 = time.perf_counter()
    platform = jax.devices()[0].platform
    backend_init_s = time.perf_counter() - t0

    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.engine.kernel import BUY, OP_SUBMIT, SELL
    from matching_engine_tpu.server.engine_runner import (
        EngineOp,
        EngineRunner,
        OrderInfo,
    )

    cfg = EngineConfig(num_symbols=args.symbols, capacity=args.capacity,
                       batch=args.batch, max_fills=1 << 15)

    def build_batches(runner: EngineRunner, seed: int,
                      n_batches: int, batch_ops: int) -> list[list[EngineOp]]:
        rng = random.Random(seed)
        batches = []
        for _ in range(n_batches):
            ops = []
            for _ in range(batch_ops):
                sym = f"S{rng.randrange(args.symbols)}"
                assert runner.slot_acquire(sym) is not None
                num, oid = runner.assign_oid()
                side = BUY if rng.random() < 0.5 else SELL
                price = 10_000 + rng.randrange(-20, 21)
                qty = rng.randrange(1, 50)
                ops.append(EngineOp(OP_SUBMIT, OrderInfo(
                    oid=num, order_id=oid, client_id=f"c{num % 97}",
                    symbol=sym, side=side, otype=0, price_q4=price,
                    quantity=qty, remaining=qty, status=0,
                    handle=runner.assign_handle())))
            batches.append(ops)
        return batches

    def sweep_point(inflight: int, batch_ops: int) -> dict:
        runner = EngineRunner(cfg, pipeline_inflight=inflight)
        batches = build_batches(runner, seed=inflight,
                                n_batches=args.n_batches,
                                batch_ops=batch_ops)
        lat: list[float] = []
        done = [0]

        def make_cb(t_start: float):
            def on_finish(result, error):
                assert error is None, error
                lat.append(time.perf_counter() - t_start)
                done[0] += 1
                return None
            return on_finish

        # Warm pass (compile both sparse bucket shapes this flow uses).
        warm = build_batches(runner, seed=999, n_batches=3,
                             batch_ops=batch_ops)
        for b in warm:
            runner.dispatch_pipelined(b, lambda r, e: None)
        runner.finish_pending()

        t_begin = time.perf_counter()
        for b in batches:
            runner.dispatch_pipelined(b, make_cb(time.perf_counter()))
        runner.finish_pending()
        dt = time.perf_counter() - t_begin
        assert done[0] == len(batches)
        lats = np.array(sorted(lat))
        n_ops = sum(len(b) for b in batches)
        return {
            "inflight": inflight,
            "orders_per_s": round(n_ops / dt, 1),
            "batch_ops": batch_ops,
            "n_batches": args.n_batches,
            "p50_ms": round(float(lats[len(lats) // 2]) * 1e3, 3),
            "p99_ms": round(float(lats[int(len(lats) * 0.99)]) * 1e3, 3),
            "mean_batch_ms": round(dt / len(batches) * 1e3, 3),
        }

    grid_cap = args.symbols * args.batch
    rows = [sweep_point(int(k), min(int(bo), grid_cap))
            for bo in str(args.batch_ops).split(",")
            for k in args.inflight.split(",")]

    try:
        import subprocess
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        rev = "unknown"
    out = {
        "metric": "runner_dispatch_throughput",
        "platform": platform,
        "symbols": args.symbols,
        "capacity": args.capacity,
        "batch": args.batch,
        "backend_init_s": round(backend_init_s, 1),
        "sweep": rows,
        "git_rev": rev,
    }
    tmp = args.json_out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
    os.replace(tmp, args.json_out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
