"""EngineRunner-level serving bench: the dispatch pipeline WITHOUT any RPC
edge or load generator (VERDICT r3 next-step 2: separate the serving
stack's own ceiling from tunnel RTT and loadgen artifacts).

Both serving paths start from pre-packed gateway-ring record batches (the
MeGwOp wire every edge pops) at a serving-like shape, sweeping dispatch
size x pipeline_inflight. --mode python charges the timed loop with the
per-op Python serving work (record decode, slot/oid/handle assignment,
EngineOp construction — what gateway_bridge._drain_batch does) before
dispatch_pipelined; --mode native hands the raw records to the C++ lane
engine (server/native_lanes.py). Per sweep point it reports sustained
orders/s plus per-batch turnaround p50/p99 (stage -> finish callback),
the client-felt latency floor of the whole serving stack minus transport.
--host-only additionally removes device compute from the timed region
(record/replay), isolating the host ceiling the serving numbers are
bounded by.

The serving-ceiling model this measures (docs/BENCH_METHOD.md):
  orders/s  ~=  batch_ops / max(host_batch_cost, sync_cost / inflight)
where sync_cost is the per-decode device round trip (~64ms tunneled, ~0
co-located with the async host-copy prefetch landing in time).

Usage: python benchmarks/runner_bench.py --json-out out.json
       [--symbols 64] [--capacity 256] [--batch 16]
       [--batch-ops 64] [--n-batches 60] [--inflight 1,2,4,8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--symbols", type=int, default=64)
    p.add_argument("--capacity", type=int, default=256)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--batch-ops", default="64",
                   help="ops per dispatched batch (the dispatcher's drain "
                        "size under load); comma list sweeps dispatch "
                        "size x inflight — under saturation the window "
                        "packs up to symbols*batch ops, so the ceiling "
                        "is a function of dispatch size, not just depth")
    p.add_argument("--n-batches", type=int, default=60)
    p.add_argument("--inflight", default="1,2,4,8")
    p.add_argument("--mode", default="python",
                   help="comma list of serving paths to sweep: 'python' "
                        "(per-op EngineOp staging/decode — the r5 path) "
                        "and/or 'native' (C++ lane build + completion "
                        "decode via server/native_lanes.py; needs the "
                        "built libme_native.so). Records are pre-packed "
                        "outside the timed loop, mirroring the gateway "
                        "edge where C++ fills the ring")
    p.add_argument("--kernel", choices=("matrix", "sorted", "levels"),
                   default="matrix")
    p.add_argument("--serve-shards", default="",
                   help="comma list of partitioned-lane counts K to sweep "
                        "(server/shards.py): each point builds K "
                        "independent (runner + dispatch) lanes over a "
                        "K-way symbol split — strided OIDs, per-lane "
                        "device pinning — and drives them from K "
                        "concurrent threads, measuring aggregate "
                        "sustained orders/s. K must divide --symbols. "
                        "Empty = the legacy single-lane sweep. Host "
                        "scaling saturates at min(K, host cores): the "
                        "native path's lane build/decode releases the "
                        "GIL, the python path mostly holds it")
    p.add_argument("--device-sweep", default="",
                   help="comma list of forced host device counts N to "
                        "sweep (e.g. 1,2,4,8): each rung boots the "
                        "shipped server subprocess under XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N with "
                        "--serve-shards N --shard-devices roundrobin "
                        "(N=1 = the single-lane baseline), drives the "
                        "SubmitOrderBatch edge, and samples the "
                        "me_lane<i>_device / me_device<d>_ops_per_s "
                        "placement gauges mid-drive. CPU rungs share "
                        "cores — expect a sublinear slope (BENCH_METHOD"
                        ".md §device-sweep)")
    p.add_argument("--device-sweep-batch", type=int, default=1024,
                   help="records per SubmitOrderBatch request on the "
                        "device-sweep rungs")
    p.add_argument("--repeats", type=int, default=1,
                   help="repetitions per sharded sweep point; the row "
                        "reports the BEST repetition (uncontended host "
                        "capability) plus the min/max spread — this "
                        "container's shared 2-CPU host shows ±40% "
                        "run-to-run noise from the platform supervisor, "
                        "which single runs cannot separate from real "
                        "scaling")
    p.add_argument("--gil-switch-us", type=int, default=500,
                   help="sys.setswitchinterval for the sharded sweep, in "
                        "microseconds. K lanes alternate short GIL-held "
                        "python sections with GIL-released native calls; "
                        "at CPython's default 5ms interval a lane "
                        "returning from C waits out the holder's full "
                        "quantum (the convoy effect) and scaling goes "
                        "NEGATIVE. 500us is the measured sweet spot on "
                        "this stack; server/main.py applies the same "
                        "tuning under --serve-shards")
    p.add_argument("--megadispatch", default="",
                   help="comma list of megadispatch wave counts M to sweep "
                        "(python path; server/engine_runner._prepare_mega): "
                        "each point drives the runner with coalesced "
                        "dispatches of M x (symbols*batch) ops, symbols "
                        "assigned round-robin so every dispatch is exactly "
                        "M full [S, B] waves — M=1 is the serial per-wave "
                        "baseline, M>1 runs kernel.engine_step_mega's "
                        "single stacked scan per dispatch. Rows add "
                        "readback_bytes_per_op (compacted vs full-plane "
                        "readback) and waves_per_step; best-of --repeats "
                        "like the shards sweep. Composes with --host-only "
                        "(the stacked step is recorded/replayed like the "
                        "serial ones)")
    p.add_argument("--edge-batch", default="",
                   help="comma list of batch sizes to sweep over a LIVE "
                        "loopback gRPC server (the batch-native edge): "
                        "boots one server subprocess per --mode entry "
                        "('python' = the default runtime layer, 'native' "
                        "= --native-lanes) with --edge-mega megadispatch "
                        "waves, then drives it closed-loop from "
                        "--edge-threads client threads — batch size 1 is "
                        "the per-op SubmitOrder baseline, larger sizes "
                        "drive SubmitOrderBatch with packed op-records "
                        "(domain/oprec.py). Per-op rejects are counted "
                        "from the positional statuses so rejects can't "
                        "masquerade as throughput. Produces the "
                        "cpu_serving_batch artifact; best-of --repeats "
                        "with spread like the other sweeps")
    p.add_argument("--edge-threads", type=int, default=4,
                   help="concurrent client threads per edge sweep point")
    p.add_argument("--edge-ops", type=int, default=16384,
                   help="orders per measured edge point (rounded down to "
                        "a batch-size multiple)")
    p.add_argument("--edge-perop-ops", type=int, default=2048,
                   help="orders per PER-OP baseline point (batch size 1): "
                        "the per-op edge runs ~two orders of magnitude "
                        "slower, so the baseline uses a smaller sample to "
                        "keep sweep wall time sane")
    p.add_argument("--edge-mega", type=int, default=4,
                   help="--megadispatch-max-waves for the edge servers: "
                        "deep batch backlogs stack into mega scans on "
                        "BOTH paths (python controller / native "
                        "wave_mega) — engagement is measured into the "
                        "row via the me_megadispatch_* counters")
    p.add_argument("--edge-window-ms", type=float, default=1.0)
    p.add_argument("--ingress", action="store_true",
                   help="zero-copy ingress rung sweep: replay ONE recorded "
                        "workload (--ingress-workload) through four edges "
                        "against a fresh server subprocess per rung — "
                        "per-op RPC, SubmitOrderBatch at "
                        "--ingress-batch-size, the client-streaming "
                        "SubmitOrderStream, and the shared-memory oprec "
                        "ring (--shm-ingress) — with the vectorized "
                        "admission screens ENABLED in every measured path "
                        "(permissive limits: the screens run, nothing "
                        "extra rejects). Produces the cpu_ingress "
                        "artifact; one row per rung, best-of --repeats")
    p.add_argument("--ingress-workload", default="",
                   help="a recorded scenario opfile for every rung to "
                        "replay (must have min_cancel_gap >= "
                        "--ingress-batch-size so batched replay can "
                        "never see a cancel before its target's batch). "
                        "Empty (default) = the bench RECORDS a synthetic "
                        "edge flow first (maker/taker alternation, "
                        "submit-only, shallow books — the r10 edge "
                        "shape) and replays THAT identical file through "
                        "every rung: scenario workloads are ENGINE-bound "
                        "on this box (BENCH_METHOD §zero-copy-ingress), "
                        "so only a light flow lets the rungs differ by "
                        "their edge cost, which is what this sweep "
                        "measures")
    p.add_argument("--ingress-synthetic-ops", type=int, default=30720,
                   help="records in the synthetic edge workload")
    p.add_argument("--ingress-rungs", default="perop,batch,stream,shm",
                   help="comma list of rungs to run")
    p.add_argument("--ingress-sections", default="real,screened",
                   help="comma list of engine sections per rung: 'real' "
                        "= the full serving pipeline (on an XLA-CPU box "
                        "every bulk rung converges at the DEVICE step's "
                        "~10k/s ceiling — the finding, not a flaw); "
                        "'screened' = the same records against a server "
                        "whose admission screens reject everything "
                        "(--admission-max-qty 1), so the measured path "
                        "is decode -> vectorized screens -> positional "
                        "responses with no device dispatch — each "
                        "edge's INTRINSIC capacity, the figure that "
                        "matters once the engine moves to hardware "
                        "(BENCH_METHOD §zero-copy-ingress)")
    p.add_argument("--ingress-batch-size", type=int, default=1024,
                   help="records per SubmitOrderBatch request / per shm "
                        "push on the batch and shm rungs")
    p.add_argument("--ingress-chunk", type=int, default=256,
                   help="records per stream chunk on the stream rung "
                        "(smaller than the batch rung BY DESIGN: the "
                        "stream exists for flow that can't batch "
                        "client-side)")
    p.add_argument("--ingress-perop-ops", type=int, default=400,
                   help="workload PREFIX replayed on the per-op rung "
                        "(~100/s: the full workload would take minutes "
                        "for a figure that is only the baseline)")
    p.add_argument("--shm-writers", default="",
                   help="comma list of concurrent shm writer PROCESS "
                        "counts (e.g. 1,2,4,8): each count W replays the "
                        "ingress workload split into W disjoint slices "
                        "through one ring via W `client submit-shm` "
                        "processes (start-barrier synchronized), one "
                        "shm_wW row per ingress section with per-writer "
                        "fairness columns. Needs a submit-only workload "
                        "(the synthetic default) — concurrent writers "
                        "interleave, so recorded cancel targets would "
                        "not resolve")
    p.add_argument("--audit-ab", action="store_true",
                   help="A/B the online auditor's overhead: run each "
                        "(mode, inflight, batch-ops) point twice through "
                        "the SAME sequenced-hub pipeline — once without "
                        "and once with the drop-copy publisher + "
                        "InvariantAuditor attached (the --audit serving "
                        "configuration, store probes excluded: the bench "
                        "has no durable store) — and emit paired rows. "
                        "The on-row asserts zero violations: a bench that "
                        "trips its own auditor measured a broken engine")
    p.add_argument("--audit-sample", type=int, default=8,
                   help="--audit-ab shadow-tracking sample (the server "
                        "flag's default, 8)")
    p.add_argument("--workload", default="",
                   help="comma list of recorded workload opfiles "
                        "(sim/record.py artifacts, manifest beside each): "
                        "replay every scenario through the serving stack "
                        "instead of synthetic flow — phase-aware (auction "
                        "call periods open/uncross via RunAuction), "
                        "in-order on one stream so the recorder's "
                        "order-id renumbering holds. One sweep row per "
                        "(scenario, path); selects the workload-replay "
                        "sweep family")
    p.add_argument("--workload-paths", default="inproc,edge",
                   help="serving paths to replay through: 'inproc' "
                        "(build_server in this process, no network — the "
                        "host-only serving figure) and/or 'edge' (server "
                        "SUBPROCESS + loopback gRPC SubmitOrderBatch — "
                        "the batch-edge figure)")
    p.add_argument("--workload-tiers", default="",
                   help="--book-tiers spec for the workload replay's "
                        "in-proc server (e.g. '4x1024:S0;S1;S2;S3,"
                        "*x256'): before driving anything, the manifest's "
                        "per-symbol max_resting_depth is checked against "
                        "the spec (sim/record.py check_tier_depth) and a "
                        "too-shallow spec fails loudly — the replay must "
                        "not depend on borrowed deep slots")
    p.add_argument("--workload-batch", type=int, default=0,
                   help="records per SubmitOrderBatch during workload "
                        "replay; 0 = min(512, the manifest's "
                        "min_cancel_gap) so intra-batch cancel targets "
                        "can never precede their submits")
    p.add_argument("--host-only", action="store_true",
                   help="isolate the serving stack's HOST work (lane "
                        "build, id/slot assignment, status decode, "
                        "completion + storage row construction): run each "
                        "sweep point twice with an identical op stream — "
                        "an untimed pass records every device step's "
                        "outputs, the timed pass replays them through a "
                        "stubbed step. On a CPU backend the real step "
                        "dominates both paths and hides the host ceiling "
                        "this repo's serving numbers are bounded by; this "
                        "mode is how the native-vs-python host ratio is "
                        "measured off-TPU (docs/BENCH_METHOD.md)")
    p.add_argument("--capacity-sweep", default="",
                   help="comma list of book capacities (e.g. "
                        "'128,1024,8192'): selects the kernel capacity "
                        "sweep — per (kernel, capacity), prefill every "
                        "book to --sweep-depth-frac of capacity with "
                        "price-level ladders, then time a steady-state "
                        "churn stream (takers + replenishing rests + "
                        "cancels) straight through engine_step_packed "
                        "(no serving stack, no decode: the KERNEL cost "
                        "of depth). Matrix rows beyond its 1024 bound "
                        "record supported=false — that inadmissibility "
                        "is the point of the sweep")
    p.add_argument("--sweep-kernels", default="matrix,sorted,levels",
                   help="kernels for --capacity-sweep")
    p.add_argument("--sweep-ops", type=int, default=2048,
                   help="measured churn ops per --capacity-sweep point")
    p.add_argument("--sweep-symbols", type=int, default=4,
                   help="symbol-axis size for --capacity-sweep (small on "
                        "purpose: the sweep isolates per-book depth cost, "
                        "not symbol-axis width)")
    p.add_argument("--sweep-depth-frac", type=float, default=0.5,
                   help="prefilled resting depth per side as a fraction "
                        "of capacity")
    p.add_argument("--json-out", required=True)
    args = p.parse_args()

    import random

    import jax
    import numpy as np

    cache_dir = os.environ.get(
        "ME_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    t0 = time.perf_counter()
    platform = jax.devices()[0].platform
    backend_init_s = time.perf_counter() - t0

    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.engine.kernel import BUY, OP_SUBMIT, SELL
    from matching_engine_tpu.server.engine_runner import (
        EngineOp,
        EngineRunner,
        OrderInfo,
    )

    cfg = EngineConfig(num_symbols=args.symbols, capacity=args.capacity,
                       batch=args.batch, max_fills=1 << 15,
                       kernel=args.kernel)

    def records_to_ops(runner: EngineRunner, recs, n: int) -> list[EngineOp]:
        """The per-op Python serving work this bench charges the python
        path with — a faithful transcription of what the serving edges do
        per popped ring record (gateway_bridge._drain_batch / the
        SubmitOrder tail): field decode, slot/oid/handle assignment,
        OrderInfo+EngineOp construction. Runs INSIDE the timed loop; the
        native path does the equivalent inside lanes.build()."""
        ops = []
        for i in range(n):
            rec = recs[i]
            sym = bytes(rec.symbol[:rec.symbol_len]).decode()
            cid = bytes(rec.client_id[:rec.client_id_len]).decode()
            slot = runner.slot_acquire(sym)  # not inside assert: -O strips
            assert slot is not None
            num, oid = runner.assign_oid()
            qty = rec.quantity
            ops.append(EngineOp(OP_SUBMIT, OrderInfo(
                oid=num, order_id=oid, client_id=cid, symbol=sym,
                side=rec.side, otype=rec.otype, price_q4=rec.price_q4,
                quantity=qty, remaining=qty, status=0,
                handle=runner.assign_handle())))
        return ops

    def build_record_batches(seed: int, n_batches: int,
                             batch_ops: int) -> list:
        """The native twin of build_batches: the same rng stream packed as
        (MeGwOp * n) arrays — the gateway-ring wire the lane engine pops.
        oid/handle/slot assignment happens INSIDE the timed dispatch (it
        moved native); packing is the edge's work (C++ on the gateway
        path) and stays outside the loop, like build_batches' EngineOp
        construction."""
        from matching_engine_tpu.server.native_lanes import pack_record_batch

        rng = random.Random(seed)
        batches = []
        tag = 1
        for _ in range(n_batches):
            recs = []
            for _ in range(batch_ops):
                sym = f"S{rng.randrange(args.symbols)}"
                side = BUY if rng.random() < 0.5 else SELL
                price = 10_000 + rng.randrange(-20, 21)
                qty = rng.randrange(1, 50)
                recs.append((tag, 1, side, 0, price, qty, sym,
                             f"c{tag % 97}", ""))
                tag += 1
            batches.append(pack_record_batch(recs))
        return batches

    import contextlib
    from collections import deque

    @contextlib.contextmanager
    def patched_steps(sparse_fn, packed_fn, mega_fn=None):
        """Swap the engine step at every site the serving runners call it
        through: the sparse/kernel modules (imported per call inside the
        hot paths) and engine_runner's import-time binding. The mega step
        is reached through the kernel module attribute
        (engine_runner._prepare_mega imports the module), so patching
        kmod covers it."""
        import matching_engine_tpu.engine.kernel as kmod
        import matching_engine_tpu.engine.sparse as smod
        import matching_engine_tpu.server.engine_runner as rmod

        saved = (smod.engine_step_sparse, kmod.engine_step_packed,
                 rmod.engine_step_packed, kmod.engine_step_mega)
        smod.engine_step_sparse = sparse_fn
        kmod.engine_step_packed = packed_fn
        rmod.engine_step_packed = packed_fn
        if mega_fn is not None:
            kmod.engine_step_mega = mega_fn
        try:
            yield
        finally:
            (smod.engine_step_sparse, kmod.engine_step_packed,
             rmod.engine_step_packed, kmod.engine_step_mega) = saved

    def make_point(mode: str, inflight: int, batch_ops: int,
                   audit: str | None = None):
        """Fresh (runner, batches, dispatch) triple for one measured pass —
        host-only mode runs this twice with an identical op stream. By
        default both runners get a subscriber-less, sequencer-less
        StreamHub (stream protos gated off — the max-throughput
        configuration build_server wires under --feed-depth 0; the
        default sequenced feed always materializes events for its
        retransmission store, and hub=None would force the same per-op
        proto materialization).

        --audit-ab passes audit="off"/"on": BOTH arms run the sequenced
        hub (the production default the auditor ships under), and the
        "on" arm additionally publishes the drop-copy and feeds the
        InvariantAuditor from the dispatch callback — exactly the
        serving drain loops' call shape — so the pair isolates the
        auditor's cost."""
        from matching_engine_tpu.server.streams import StreamHub

        if audit is None:
            hub = StreamHub()
        else:
            from matching_engine_tpu.feed import FeedSequencer
            from matching_engine_tpu.utils.metrics import Metrics

            reg = Metrics()
            hub = StreamHub(metrics=reg,
                            sequencer=FeedSequencer(metrics=reg))
        batches = build_record_batches(seed=inflight,
                                       n_batches=args.n_batches,
                                       batch_ops=batch_ops)
        if mode == "native":
            from matching_engine_tpu.server.native_lanes import (
                NativeLanesRunner,
            )

            runner = NativeLanesRunner(cfg, hub=hub,
                                       pipeline_inflight=inflight)
            dispatch = lambda b, cb: runner.dispatch_records(b[0], b[1], cb)  # noqa: E731
        else:
            runner = EngineRunner(cfg, hub=hub, pipeline_inflight=inflight)

            def dispatch(b, cb, _r=runner):
                _r.dispatch_pipelined(records_to_ops(_r, b[0], b[1]), cb)
        if audit == "on":
            from matching_engine_tpu.audit import (
                AuditPump,
                DropCopyPublisher,
                InvariantAuditor,
            )

            auditor = InvariantAuditor(reg, sample=args.audit_sample)
            pump = AuditPump(reg)
            dc = DropCopyPublisher(hub, reg, auditor=auditor, runner=runner,
                                   pump=pump)
            runner._bench_auditor = auditor
            runner._bench_audit_pump = pump
            raw = dispatch

            def dispatch(b, cb, _raw=raw, _dc=dc):  # noqa: F811
                def wrap(result, error, _cb=cb):
                    if error is None:
                        _dc.publish(result, None)
                    return _cb(result, error)
                _raw(b, wrap)
        return runner, batches, dispatch

    def sweep_point(mode: str, inflight: int, batch_ops: int,
                    audit: str | None = None) -> dict:
        lat: list[float] = []
        done = [0]

        def make_cb(t_start: float):
            def on_finish(result, error):
                assert error is None, error
                lat.append(time.perf_counter() - t_start)
                done[0] += 1
                return None
            return on_finish

        ctx = contextlib.nullcontext()
        if args.host_only:
            # Record pass: the REAL pipeline over the same stream a fresh
            # runner will see, keeping every device step's (book, out) in
            # call order. Decode never reads the book and lane build never
            # reads device state, so replaying `out` through a stubbed
            # step leaves all host work bit-identical while the timed
            # region contains no device compute.
            from matching_engine_tpu.engine.kernel import (
                engine_step_packed as real_packed,
            )
            from matching_engine_tpu.engine.sparse import (
                engine_step_sparse as real_sparse,
            )

            outs: deque = deque()

            def rec_sparse(c, book, sp):
                book, out = real_sparse(c, book, sp)
                outs.append(out)
                return book, out

            def rec_packed(c, book, arr):
                book, out = real_packed(c, book, arr)
                outs.append(out)
                return book, out

            runner, batches, dispatch = make_point(mode, inflight, batch_ops)
            with patched_steps(rec_sparse, rec_packed):
                for b in batches:
                    dispatch(b, lambda r, e: None)
                runner.finish_pending()
            ctx = patched_steps(lambda c, book, sp: (book, outs.popleft()),
                                lambda c, book, arr: (book, outs.popleft()))

        runner, batches, dispatch = make_point(mode, inflight, batch_ops,
                                               audit=audit)
        with ctx:
            if not args.host_only:
                # Warm pass (compile both sparse bucket shapes this flow
                # uses). Host-only replays need no warmup — and would
                # desync the recorded output queue.
                warm = build_record_batches(seed=999, n_batches=3,
                                            batch_ops=batch_ops)
                for b in warm:
                    dispatch(b, lambda r, e: None)
                runner.finish_pending()
                if audit == "on":
                    # Drain the WARM batches' audit work before the
                    # timed region opens — the in-region flush must
                    # charge the measured batches only.
                    runner._bench_audit_pump.flush()

            t_begin = time.perf_counter()
            for b in batches:
                dispatch(b, make_cb(time.perf_counter()))
            runner.finish_pending()
            if audit == "on":
                # The pump runs out of band; the honest throughput figure
                # still charges the arm for ALL of its work — the barrier
                # sits inside the timed region (overlap is the win being
                # measured, backlog is not free).
                runner._bench_audit_pump.flush()
            dt = time.perf_counter() - t_begin
        assert done[0] == len(batches)
        lats = np.array(sorted(lat))
        n_ops = args.n_batches * batch_ops
        row = {
            "mode": mode + ("-host" if args.host_only else ""),
            "inflight": inflight,
            "orders_per_s": round(n_ops / dt, 1),
            "batch_ops": batch_ops,
            "n_batches": args.n_batches,
            "p50_ms": round(float(lats[len(lats) // 2]) * 1e3, 3),
            "p99_ms": round(float(lats[int(len(lats) * 0.99)]) * 1e3, 3),
            "mean_batch_ms": round(dt / len(batches) * 1e3, 3),
        }
        if audit is not None:
            row["audit"] = audit
            if audit == "on":
                snap = runner._bench_auditor.snapshot()
                # A bench arm that trips its own auditor measured a
                # broken engine, not the auditor's cost.
                assert snap["violations"] == 0, snap["by_kind"]
                row["audit_records"] = snap["records"]
                row["audit_sample"] = args.audit_sample
                # Per-point pump: close it or a long sweep accumulates
                # one idle thread + its runner/hub graph per point.
                runner._bench_audit_pump.close()
        return row

    # -- partitioned-lane sweep (server/shards.py) -------------------------

    import threading

    _tls = threading.local()

    def _stub_sparse(c, book, sp):
        return book, _tls.outs.popleft()

    def _stub_packed(c, book, arr):
        return book, _tls.outs.popleft()

    class _HostOut:
        """A recorded step output with its packed readbacks ALREADY on
        host as numpy. The replay must contain zero device interaction:
        np.asarray on a jax Array re-enters the jax runtime, whose
        cross-thread serialization dwarfs the host work K lanes are
        trying to overlap (measured: K=2 collapsed ~4x through it)."""

        __slots__ = ("small", "fills")

        def __init__(self, out):
            self.small = np.asarray(out.small)
            self.fills = np.asarray(out.fills)

    def make_shard_lanes(mode: str, inflight: int, batch_ops: int, K: int):
        """K (runner, batches, dispatch) lanes over a K-way split of the
        bench config — the build_serving_shards cut minus the dispatcher
        threads (the bench's worker threads ARE the per-lane drain
        loops, so the timed region contains exactly the serving host
        work and no queue hand-off)."""
        from matching_engine_tpu.server.shards import (
            ShardRouter,
            make_lane_runner,
        )
        from matching_engine_tpu.server.streams import StreamHub

        router = ShardRouter(K)
        hub = StreamHub()
        shard_syms = args.symbols // K
        lanes = []
        for i in range(K):
            runner = make_lane_runner(
                cfg, router, i, hub=hub, pipeline_inflight=inflight,
                native_lanes=(mode == "native"))
            # Lane-local symbol namespace sized to the lane's axis: the
            # router is exercised by the serving tests; here each lane
            # is driven directly, as its dispatcher thread would.
            batches = build_lane_record_batches(
                seed=1000 * K + i, n_batches=args.n_batches,
                batch_ops=batch_ops, lane=i, lane_symbols=shard_syms)
            if mode == "native":
                dispatch = (lambda b, cb, _r=runner:
                            _r.dispatch_records(b[0], b[1], cb))
            else:
                dispatch = (lambda b, cb, _r=runner:
                            _r.dispatch_pipelined(
                                records_to_ops(_r, b[0], b[1]), cb))
            lanes.append({"runner": runner, "batches": batches,
                          "dispatch": dispatch})
        return lanes

    def build_lane_record_batches(seed, n_batches, batch_ops, lane,
                                  lane_symbols):
        from matching_engine_tpu.server.native_lanes import pack_record_batch

        rng = random.Random(seed)
        batches = []
        tag = 1
        for _ in range(n_batches):
            recs = []
            for _ in range(batch_ops):
                sym = f"L{lane}S{rng.randrange(lane_symbols)}"
                side = BUY if rng.random() < 0.5 else SELL
                price = 10_000 + rng.randrange(-20, 21)
                qty = rng.randrange(1, 50)
                recs.append((tag, 1, side, 0, price, qty, sym,
                             f"c{tag % 97}", ""))
                tag += 1
            batches.append(pack_record_batch(recs))
        return batches

    def sweep_point_sharded(mode: str, inflight: int, batch_ops: int,
                            K: int) -> dict:
        lat: list[float] = []
        lat_lock = threading.Lock()

        def run_lane(lane, barrier):
            if args.host_only:
                _tls.outs = lane["outs"]
            local_lat = []
            barrier.wait()
            for b in lane["batches"]:
                t_start = time.perf_counter()

                def cb(result, error, _t=t_start):
                    assert error is None, error
                    local_lat.append(time.perf_counter() - _t)
                lane["dispatch"](b, cb)
            lane["runner"].finish_pending()
            with lat_lock:
                lat.extend(local_lat)

        ctx = contextlib.nullcontext()
        if args.host_only:
            # Record pass: the real pipeline per lane, sequentially; the
            # timed pass replays each lane's recorded step outputs
            # through a THREAD-LOCAL stub, so K lanes replay unsynchron-
            # ized while all host work stays bit-identical.
            from matching_engine_tpu.engine.kernel import (
                engine_step_packed as real_packed,
            )
            from matching_engine_tpu.engine.sparse import (
                engine_step_sparse as real_sparse,
            )

            rec_lanes = make_shard_lanes(mode, inflight, batch_ops, K)
            per_lane_outs = []
            for lane in rec_lanes:
                outs: deque = deque()

                def rec_sparse(c, book, sp, _o=outs):
                    book, out = real_sparse(c, book, sp)
                    _o.append(_HostOut(out))
                    return book, out

                def rec_packed(c, book, arr, _o=outs):
                    book, out = real_packed(c, book, arr)
                    _o.append(_HostOut(out))
                    return book, out

                with patched_steps(rec_sparse, rec_packed):
                    for b in lane["batches"]:
                        lane["dispatch"](b, lambda r, e: None)
                    lane["runner"].finish_pending()
                per_lane_outs.append(outs)
            ctx = patched_steps(_stub_sparse, _stub_packed)

        lanes = make_shard_lanes(mode, inflight, batch_ops, K)
        if args.host_only:
            for lane, outs in zip(lanes, per_lane_outs):
                lane["outs"] = outs
        with ctx:
            if not args.host_only:
                # Sequential warm pass: compile the step shapes (and, on
                # a multi-device host, each lane's device executable)
                # outside the timed region.
                for i, lane in enumerate(lanes):
                    warm = build_lane_record_batches(
                        seed=555 + i, n_batches=2, batch_ops=batch_ops,
                        lane=i, lane_symbols=args.symbols // K)
                    for b in warm:
                        lane["dispatch"](b, lambda r, e: None)
                    lane["runner"].finish_pending()

            barrier = threading.Barrier(K + 1)
            threads = [threading.Thread(target=run_lane,
                                        args=(lane, barrier), daemon=True)
                       for lane in lanes]
            for t in threads:
                t.start()
            barrier.wait()
            t_begin = time.perf_counter()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t_begin
        assert len(lat) == K * args.n_batches
        lats = np.array(sorted(lat))
        n_ops = K * args.n_batches * batch_ops
        return {
            "mode": mode + ("-host" if args.host_only else ""),
            "serve_shards": K,
            "inflight": inflight,
            "orders_per_s": round(n_ops / dt, 1),
            "batch_ops": batch_ops,
            "n_batches": args.n_batches,
            "p50_ms": round(float(lats[len(lats) // 2]) * 1e3, 3),
            "p99_ms": round(float(lats[int(len(lats) * 0.99)]) * 1e3, 3),
            "mean_batch_ms": round(dt / args.n_batches * 1e3, 3),
        }

    # -- megadispatch sweep (engine_runner._prepare_mega) ------------------

    def build_mega_record_batches(seed: int, n_batches: int, m: int):
        """Coalesced-dispatch streams: m x (symbols*batch) submits per
        dispatch, symbols assigned round-robin so the wave builder packs
        EXACTLY m full [S, B] waves — the deep-queue backlog shape the
        dispatcher's controller coalesces, with a deterministic wave
        count so M=1 (the serial per-wave schedule over the same
        backlog) and M>1 (one stacked scan per m waves) compare like
        for like."""
        from matching_engine_tpu.server.native_lanes import pack_record_batch

        rng = random.Random(seed)
        ops_per = m * args.symbols * args.batch
        batches = []
        tag = 1
        for _ in range(n_batches):
            recs = []
            for j in range(ops_per):
                sym = f"S{j % args.symbols}"
                side = BUY if rng.random() < 0.5 else SELL
                price = 10_000 + rng.randrange(-20, 21)
                qty = rng.randrange(1, 50)
                recs.append((tag, 1, side, 0, price, qty, sym,
                             f"c{tag % 97}", ""))
                tag += 1
            batches.append(pack_record_batch(recs))
        return batches

    def sweep_point_mega(m: int, inflight: int) -> dict:
        from matching_engine_tpu.server.streams import StreamHub

        lat: list[float] = []

        def make():
            hub = StreamHub()
            runner = EngineRunner(cfg, hub=hub, pipeline_inflight=inflight,
                                  megadispatch_max_waves=m)
            batches = build_mega_record_batches(
                seed=97 + m, n_batches=args.n_batches, m=m)

            def dispatch(b, cb, _r=runner):
                _r.dispatch_pipelined(records_to_ops(_r, b[0], b[1]), cb)
            return runner, batches, dispatch

        ctx = contextlib.nullcontext()
        if args.host_only:
            # Same record/replay scheme as the single-lane sweep, with
            # the stacked mega step recorded too (its outputs converted
            # to host numpy so the replay touches no device arrays).
            from matching_engine_tpu.engine.kernel import (
                engine_step_mega as real_mega,
            )
            from matching_engine_tpu.engine.kernel import (
                engine_step_packed as real_packed,
            )
            from matching_engine_tpu.engine.sparse import (
                engine_step_sparse as real_sparse,
            )

            outs: deque = deque()

            def rec_sparse(c, book, sp):
                book, out = real_sparse(c, book, sp)
                outs.append(out)
                return book, out

            def rec_packed(c, book, arr):
                book, out = real_packed(c, book, arr)
                outs.append(out)
                return book, out

            def rec_mega(c, book, lanes, rcap):
                book, out = real_mega(c, book, lanes, rcap)
                outs.append(_HostOut(out))
                return book, out

            runner, batches, dispatch = make()
            with patched_steps(rec_sparse, rec_packed, rec_mega):
                for b in batches:
                    dispatch(b, lambda r, e: None)
                runner.finish_pending()
            ctx = patched_steps(
                lambda c, book, sp: (book, outs.popleft()),
                lambda c, book, arr: (book, outs.popleft()),
                lambda c, book, lanes, rcap: (book, outs.popleft()))

        runner, batches, dispatch = make()
        with ctx:
            if not args.host_only:
                warm = build_mega_record_batches(seed=7, n_batches=2, m=m)
                for b in warm:
                    dispatch(b, lambda r, e: None)
                runner.finish_pending()
            c0 = dict(runner.metrics.snapshot()[0])
            t_begin = time.perf_counter()
            for b in batches:
                t0 = time.perf_counter()

                def cb(r, e, _t=t0):
                    assert e is None, e
                    lat.append(time.perf_counter() - _t)
                dispatch(b, cb)
            runner.finish_pending()
            dt = time.perf_counter() - t_begin
        c1 = dict(runner.metrics.snapshot()[0])
        assert len(lat) == len(batches)
        lats = np.array(sorted(lat))
        ops_per = m * args.symbols * args.batch
        n_ops = args.n_batches * ops_per
        steps = c1.get("megadispatch_steps", 0) - c0.get(
            "megadispatch_steps", 0)
        waves = c1.get("megadispatch_stacked_waves", 0) - c0.get(
            "megadispatch_stacked_waves", 0)
        return {
            "mode": "python-mega" + ("-host" if args.host_only else ""),
            "megadispatch": m,
            "inflight": inflight,
            "orders_per_s": round(n_ops / dt, 1),
            "ops_per_dispatch": ops_per,
            "n_batches": args.n_batches,
            "p50_ms": round(float(lats[len(lats) // 2]) * 1e3, 3),
            "p99_ms": round(float(lats[int(len(lats) * 0.99)]) * 1e3, 3),
            "readback_bytes_per_op": round(
                (c1.get("readback_bytes", 0) - c0.get("readback_bytes", 0))
                / n_ops, 1),
            "mega_steps": steps,
            "waves_per_step": round(waves / steps, 2) if steps else 1.0,
        }

    # -- batch edge sweep (SubmitOrderBatch vs per-op, live gRPC) ----------

    def edge_server(mode: str, tmp: str, audit: str | None = None):
        """Boot one serving subprocess (the real edge: loopback gRPC, its
        own GIL) and return (proc, port, logpath). mode 'python' is the
        default runtime layer; 'native' adds --native-lanes. An audit
        arm ('off'/'on') keeps the sequenced feed ON for BOTH arms (the
        production default the auditor ships under) and adds --audit to
        the on arm — the pair isolates the auditor through the full
        shipped server."""
        import subprocess

        tag = mode if audit is None else f"{mode}_audit_{audit}"
        log_path = os.path.join(tmp, f"server_{tag}.log")
        argv = [sys.executable, "-m", "matching_engine_tpu.server.main",
                "--addr", "127.0.0.1:0",
                "--db", os.path.join(tmp, f"edge_{tag}.db"),
                "--symbols", str(args.symbols),
                "--capacity", str(args.capacity),
                "--batch", str(args.batch),
                "--window-ms", str(args.edge_window_ms),
                "--megadispatch-max-waves", str(args.edge_mega)]
        if audit is None:
            argv += ["--feed-depth", "0"]
        elif audit == "on":
            argv += ["--audit", "--audit-sample", str(args.audit_sample)]
        if mode == "native":
            argv.append("--native-lanes")
        env = dict(os.environ, PYTHONUNBUFFERED="1")
        logf = open(log_path, "w")
        proc = subprocess.Popen(argv, stdout=logf, stderr=subprocess.STDOUT,
                                env=env)
        port = None
        deadline = time.time() + 180
        import re as _re

        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"edge server ({mode}) died at boot; see {log_path}")
            m = _re.search(r"listening on port (\d+)",
                           open(log_path).read())
            if m:
                port = int(m.group(1))
                break
            time.sleep(0.25)
        if port is None:
            proc.kill()
            raise RuntimeError(f"edge server ({mode}) never bound a port")
        return proc, port, log_path

    def edge_sweep() -> list:
        import threading as _th

        import grpc

        from matching_engine_tpu.domain import oprec
        from matching_engine_tpu.proto import pb2
        from matching_engine_tpu.proto.rpc import MatchingEngineStub

        sizes = [int(x) for x in args.edge_batch.split(",") if x.strip()]
        T = max(1, args.edge_threads)
        rows = []

        def gen_ops(n: int, thread: int):
            """Maker/taker alternation per symbol (SELL rests, the next
            BUY crosses it out) so books stay shallow however long the
            sweep runs — rejects stay a counted anomaly, not the load.
            ONE symbol namespace sized to the engine's axis, shared by
            every thread: per-thread namespaces would demand T*symbols
            live slots and reject half the load as axis overflow."""
            ops = []
            for i in range(n):
                sym = f"E{i % args.symbols}"
                maker = ((i // args.symbols) % 2) == 0
                ops.append((oprec.OPREC_SUBMIT, 2 if maker else 1, 0,
                            10_000, 5, sym,
                            f"em{thread}" if maker else f"et{thread}", ""))
            return ops

        def scrape(stub):
            resp = stub.GetMetrics(pb2.MetricsRequest(), timeout=30)
            return dict(resp.counters)

        def run_point(stubs, bs: int, measured: bool,
                      n_override: int | None = None) -> dict:
            budget = n_override or (args.edge_perop_ops if bs == 1
                                    else args.edge_ops)
            n_ops = max(bs * T, budget - budget % max(bs, 1))
            per_thread = n_ops // T
            work = []
            for t in range(T):
                ops = gen_ops(per_thread, t)
                if bs == 1:
                    work.append([
                        pb2.OrderRequest(
                            client_id=cid.decode()
                            if isinstance(cid, bytes) else cid,
                            symbol=sym, order_type=pb2.LIMIT, side=side,
                            price=price, scale=4, quantity=qty)
                        for (_op, side, _ot, price, qty, sym, cid, _oid)
                        in ops])
                else:
                    arr = oprec.pack_records(ops)
                    work.append([oprec.slice_payload(arr, s, bs)
                                 for s in range(0, per_thread, bs)])
            counts = [None] * T
            barrier = _th.Barrier(T + 1)

            def worker(t):
                stub = stubs[t]
                acc = rej = err = 0
                barrier.wait()
                if bs == 1:
                    for req in work[t]:
                        try:
                            r = stub.SubmitOrder(req, timeout=60)
                            if r.success:
                                acc += 1
                            else:
                                rej += 1
                        except grpc.RpcError:
                            err += 1
                else:
                    for payload in work[t]:
                        try:
                            r = stub.SubmitOrderBatch(
                                pb2.OrderBatchRequest(ops=payload),
                                timeout=120)
                        except grpc.RpcError:
                            err += bs
                            continue
                        if not r.success:
                            err += bs
                            continue
                        a = sum(r.ok)
                        acc += a
                        rej += len(r.ok) - a
                counts[t] = (acc, rej, err)

            c0 = scrape(stubs[0]) if measured else {}
            threads = [_th.Thread(target=worker, args=(t,), daemon=True)
                       for t in range(T)]
            for th in threads:
                th.start()
            barrier.wait()
            t_begin = time.perf_counter()
            for th in threads:
                th.join()
            dt = time.perf_counter() - t_begin
            if not measured:
                return {}
            c1 = scrape(stubs[0])
            acc = sum(c[0] for c in counts)
            rej = sum(c[1] for c in counts)
            err = sum(c[2] for c in counts)
            steps = c1.get("megadispatch_steps", 0) - c0.get(
                "megadispatch_steps", 0)
            waves = c1.get("megadispatch_stacked_waves", 0) - c0.get(
                "megadispatch_stacked_waves", 0)
            return {
                "batch_size": bs,
                "threads": T,
                "n_ops": n_ops,
                "orders_per_s": round(n_ops / dt, 1),
                "accepted_per_s": round(acc / dt, 1),
                "accepted": acc,
                "rejected": rej,
                "rpc_errors": err,
                "wall_s": round(dt, 3),
                "edge_batches": c1.get("edge_batches", 0) - c0.get(
                    "edge_batches", 0),
                "mega_steps": steps,
                "mega_waves_per_step": round(waves / steps, 2) if steps
                else 0.0,
            }

        import tempfile

        tmp = tempfile.mkdtemp(prefix="edge_bench_")
        arms = ["off", "on"] if args.audit_ab else [None]
        for mode in [m.strip() for m in args.mode.split(",") if m.strip()]:
            if mode == "native":
                from matching_engine_tpu import native as me_native

                if not me_native.available():
                    print("[edge] native runtime not built; skipping "
                          "native mode", file=sys.stderr)
                    continue
            for arm in arms:
                proc, port, log_path = edge_server(mode, tmp, audit=arm)
                try:
                    stubs = [MatchingEngineStub(
                        grpc.insecure_channel(f"127.0.0.1:{port}"))
                        for _ in range(T)]
                    # Warm: compile the dispatch shapes (per-op sparse
                    # buckets + the largest batch's dense/mega stack)
                    # outside every measured point, with small op budgets
                    # — warming is about shape coverage, not duration.
                    run_point(stubs, 1, measured=False, n_override=64 * T)
                    run_point(stubs, max(sizes), measured=False,
                              n_override=2 * max(sizes) * T)
                    for bs in sizes:
                        reps = [run_point(stubs, bs, measured=True)
                                for _ in range(max(1, args.repeats))]
                        rates = [r["orders_per_s"] for r in reps]
                        best = max(reps, key=lambda r: r["orders_per_s"])
                        best["mode"] = mode
                        best["edge"] = ("grpc-perop" if bs == 1
                                        else "grpc-batch")
                        if arm is not None:
                            best["audit"] = arm
                            if arm == "on":
                                best["audit_sample"] = args.audit_sample
                        best["repeats"] = len(reps)
                        best["orders_per_s_spread"] = [min(rates),
                                                       max(rates)]
                        rows.append(best)
                        print(f"[edge] {mode}"
                              f"{'' if arm is None else ' audit=' + arm} "
                              f"bs={bs}: {best['orders_per_s']} orders/s "
                              f"(acc {best['accepted']}, rej "
                              f"{best['rejected']}, err "
                              f"{best['rpc_errors']}, megaM "
                              f"{best['mega_waves_per_step']})",
                              file=sys.stderr)
                finally:
                    proc.terminate()
                    try:
                        proc.wait(timeout=20)
                    except Exception:  # noqa: BLE001
                        proc.kill()
        # Paired overhead annotation on the audit arms.
        if args.audit_ab:
            for on in rows:
                if on.get("audit") != "on":
                    continue
                off = next((r for r in rows
                            if r.get("audit") == "off"
                            and r["mode"] == on["mode"]
                            and r["batch_size"] == on["batch_size"]), None)
                if off is not None and off["orders_per_s"]:
                    on["audit_overhead_pct"] = round(
                        100.0 * (1.0 - on["orders_per_s"]
                                 / off["orders_per_s"]), 1)
        return rows

    # -- device sweep (forced host devices × sharded serving) --------------

    def device_sweep() -> list:
        """Linear-scaling probe for mesh-scale serving: for each forced
        host device count N, boot the shipped server subprocess under
        ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` with
        ``--serve-shards N --shard-devices roundrobin`` (N=1 boots the
        single-lane server — the scaling baseline), drive the
        SubmitOrderBatch edge from T client threads, and sample the
        lane/device placement gauges MID-DRIVE (a sampler thread in this
        bench process scrapes GetMetrics while load runs, keeping the
        busiest sample — post-drive gauges would show the idle tail).

        Forced host devices share the box's physical cores, so the CPU
        slope is expected SUBLINEAR (BENCH_METHOD.md §device-sweep);
        what the rungs isolate is the per-lane shape win ([S/N, B]
        grids dispatch cheaper than one [S, B]) plus the placement
        plumbing itself — the slope approaching N belongs to real
        multi-chip hosts, where each lane's jit lands on its own
        silicon."""
        import subprocess
        import tempfile
        import threading as _th

        import grpc

        from matching_engine_tpu.domain import oprec
        from matching_engine_tpu.proto import pb2
        from matching_engine_tpu.proto.rpc import MatchingEngineStub

        counts = [int(x) for x in args.device_sweep.split(",")
                  if x.strip()]
        T = max(1, args.edge_threads)
        bs = args.device_sweep_batch
        tmp = tempfile.mkdtemp(prefix="device_sweep_")
        rows = []

        def boot(n_dev: int):
            log_path = os.path.join(tmp, f"server_dev{n_dev}.log")
            argv = [sys.executable, "-m",
                    "matching_engine_tpu.server.main",
                    "--addr", "127.0.0.1:0",
                    "--db", os.path.join(tmp, f"dev{n_dev}.db"),
                    "--symbols", str(args.symbols),
                    "--capacity", str(args.capacity),
                    "--batch", str(args.batch),
                    "--window-ms", str(args.edge_window_ms),
                    "--megadispatch-max-waves", str(args.edge_mega),
                    "--feed-depth", "0"]
            if n_dev > 1:
                argv += ["--serve-shards", str(n_dev),
                         "--shard-devices", "roundrobin"]
            env = dict(os.environ, PYTHONUNBUFFERED="1",
                       JAX_PLATFORMS="cpu")
            kept = [f for f in env.get("XLA_FLAGS", "").split()
                    if "xla_force_host_platform_device_count" not in f]
            env["XLA_FLAGS"] = " ".join(
                kept + ["--xla_force_host_platform_device_count="
                        f"{n_dev}"]).strip()
            logf = open(log_path, "w")
            proc = subprocess.Popen(argv, stdout=logf,
                                    stderr=subprocess.STDOUT, env=env)
            port = None
            deadline = time.time() + 180
            import re as _re

            while time.time() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(f"device-sweep server (N={n_dev}) "
                                       f"died at boot; see {log_path}")
                m = _re.search(r"listening on port (\d+)",
                               open(log_path).read())
                if m:
                    port = int(m.group(1))
                    break
                time.sleep(0.25)
            if port is None:
                proc.kill()
                raise RuntimeError(
                    f"device-sweep server (N={n_dev}) never bound a port")
            return proc, port, log_path

        def gen_ops(n: int, thread: int):
            # Maker/taker alternation per symbol (the edge_sweep shape):
            # books stay shallow for the whole drive.
            ops = []
            for i in range(n):
                sym = f"E{i % args.symbols}"
                maker = ((i // args.symbols) % 2) == 0
                ops.append((oprec.OPREC_SUBMIT, 2 if maker else 1, 0,
                            10_000, 5, sym,
                            f"dm{thread}" if maker else f"dt{thread}", ""))
            return ops

        def run_rung(n_dev: int) -> dict:
            proc, port, log_path = boot(n_dev)
            try:
                stubs = [MatchingEngineStub(
                    grpc.insecure_channel(f"127.0.0.1:{port}"))
                    for _ in range(T + 1)]
                scr = stubs[T]

                def drive(n_ops: int, measured: bool) -> dict:
                    per_thread = max(bs, n_ops // T)
                    per_thread -= per_thread % bs
                    work = []
                    for t in range(T):
                        arr = oprec.pack_records(gen_ops(per_thread, t))
                        work.append([oprec.slice_payload(arr, s, bs)
                                     for s in range(0, per_thread, bs)])
                    acc = [0] * T
                    barrier = _th.Barrier(T + 1)

                    def worker(t):
                        stub = stubs[t]
                        barrier.wait()
                        for payload in work[t]:
                            try:
                                r = stub.SubmitOrderBatch(
                                    pb2.OrderBatchRequest(ops=payload),
                                    timeout=300)
                                acc[t] += sum(r.ok)
                            except grpc.RpcError:
                                pass

                    # The device-sweep sampler: scrape the lane/device
                    # gauges while the drive runs; keep the busiest
                    # sample (max summed lane rate).
                    stop = _th.Event()
                    best_sample: dict = {}

                    def sampler():
                        while not stop.wait(0.3):
                            try:
                                resp = scr.GetMetrics(
                                    pb2.MetricsRequest(), timeout=10)
                            except grpc.RpcError:
                                continue
                            g = dict(resp.gauges)
                            rate = g.get("lane_dispatch_rate", 0.0)
                            if rate >= best_sample.get(
                                    "lane_dispatch_rate", 0.0):
                                best_sample.clear()
                                best_sample.update(g)

                    threads = [_th.Thread(target=worker, args=(t,),
                                          daemon=True) for t in range(T)]
                    samp = None
                    if measured and n_dev > 1:
                        samp = _th.Thread(target=sampler, daemon=True)
                        samp.start()
                    for th in threads:
                        th.start()
                    barrier.wait()
                    t0 = time.perf_counter()
                    for th in threads:
                        th.join()
                    dt = time.perf_counter() - t0
                    if samp is not None:
                        stop.set()
                        samp.join(timeout=5)
                        if not best_sample:
                            # Drive finished before the first sampler
                            # tick (toy sizes): the placement identity
                            # gauges are static, so a post-drive scrape
                            # still answers "which lane on which
                            # device" (rates show the idle tail).
                            try:
                                resp = scr.GetMetrics(
                                    pb2.MetricsRequest(), timeout=10)
                                best_sample.update(dict(resp.gauges))
                            except grpc.RpcError:
                                pass
                    if not measured:
                        return {}
                    n_total = per_thread * T
                    row = {
                        "device_count": n_dev,
                        "serve_shards": n_dev if n_dev > 1 else 1,
                        "batch_size": bs,
                        "threads": T,
                        "n_ops": n_total,
                        "accepted": sum(acc),
                        "orders_per_s": round(n_total / dt, 1),
                        "wall_s": round(dt, 3),
                    }
                    if n_dev > 1 and best_sample:
                        lanes = {}
                        devices = {}
                        for k, v in best_sample.items():
                            if k.startswith("lane") and \
                                    k.endswith("_device"):
                                lanes[k] = int(v)
                            if k.startswith("device") and \
                                    k.endswith("_ops_per_s"):
                                devices[k] = round(v, 1)
                        row["lane_devices"] = lanes
                        row["device_ops_per_s"] = devices
                        row["lane_imbalance"] = round(
                            best_sample.get("lane_imbalance", 0.0), 2)
                        row["sampled_lane_rate"] = round(
                            best_sample.get("lane_dispatch_rate", 0.0), 1)
                    return row

                drive(2 * bs * T, measured=False)   # compile the shapes
                reps = [drive(args.edge_ops, measured=True)
                        for _ in range(max(1, args.repeats))]
                rates = [r["orders_per_s"] for r in reps]
                best = max(reps, key=lambda r: r["orders_per_s"])
                best["repeats"] = len(reps)
                best["orders_per_s_spread"] = [min(rates), max(rates)]
                print(f"[device-sweep] N={n_dev}: "
                      f"{best['orders_per_s']} orders/s "
                      f"(imbalance {best.get('lane_imbalance', '-')}, "
                      f"devices {best.get('device_ops_per_s', '-')})",
                      file=sys.stderr)
                return best
            finally:
                proc.terminate()
                try:
                    proc.wait(timeout=20)
                except Exception:  # noqa: BLE001
                    proc.kill()

        for n_dev in counts:
            rows.append(run_rung(n_dev))
        base = next((r["orders_per_s"] for r in rows
                     if r["device_count"] == 1), None)
        for r in rows:
            if base:
                r["speedup_vs_1"] = round(r["orders_per_s"] / base, 3)
        return rows

    # -- zero-copy ingress rung sweep --------------------------------------

    def ingress_sweep() -> list:
        """One recorded workload through four ingress rungs, each
        against a fresh server subprocess (fresh OID line — the
        recorder's cancel renumbering must hold per rung), with the
        vectorized admission screens enabled in every measured path.
        Throughput is ops-through-the-edge per second (accepted +
        replay-expected rejects — a recorded cancel whose maker already
        filled rejects 'order not open' by design; the rung comparison
        is about the EDGE, and every rung replays the identical
        stream)."""
        import json as _json
        import subprocess as _sp
        import tempfile

        import grpc

        from matching_engine_tpu import native as me_native
        from matching_engine_tpu.domain import oprec
        from matching_engine_tpu.proto import pb2
        from matching_engine_tpu.proto.rpc import MatchingEngineStub

        bs = args.ingress_batch_size
        tmpd = tempfile.mkdtemp(prefix="ingress_bench_")
        if args.ingress_workload:
            arr = oprec.read_opfile(args.ingress_workload)
            man_path = args.ingress_workload.split(".opfile")[0] \
                + ".manifest.json"
            man = _json.load(open(man_path))
            gap = man.get("min_cancel_gap") or 0
            if gap and bs > gap:
                raise SystemExit(
                    f"--ingress-batch-size {bs} > the workload's "
                    f"min_cancel_gap {gap}: an intra-batch cancel could "
                    f"precede its target (pick a workload with a larger "
                    f"gap or a smaller batch)")
            workload_name = args.ingress_workload
            srv_symbols = man["symbols"]
            srv_capacity = man["capacity"]
            srv_batch = man["batch"]
        else:
            # Record the synthetic edge flow ONCE (a real opfile —
            # every rung replays the identical bytes): per-symbol
            # maker/taker alternation so books stay shallow (the SELL
            # rests, the next BUY crosses it out) — the engine stays
            # cheap and the rung comparison isolates the EDGE.
            n = args.ingress_synthetic_ops
            srv_symbols, srv_capacity, srv_batch = 16, 128, 8
            rows_syn = []
            for i in range(n):
                sym = f"E{i % srv_symbols}"
                maker = ((i // srv_symbols) % 2) == 0
                rows_syn.append(
                    (oprec.OPREC_SUBMIT, 2 if maker else 1, 0, 10_000, 5,
                     sym, "im" if maker else "it", ""))
            arr = oprec.pack_records(rows_syn)
            workload_name = os.path.join(tmpd, "synthetic_edge.opfile")
            oprec.write_opfile(workload_name, arr)
            gap = 0
        rungs = [r.strip() for r in args.ingress_rungs.split(",")
                 if r.strip()]
        if not me_native.available() and "shm" in rungs:
            print("[ingress] native runtime not built; skipping shm rung",
                  file=sys.stderr)
            rungs = [r for r in rungs if r != "shm"]

        def boot(tag: str, shm_path: str | None, screened: bool = False):
            log_path = os.path.join(tmpd, f"server_{tag}.log")
            argv = [sys.executable, "-m",
                    "matching_engine_tpu.server.main",
                    "--addr", "127.0.0.1:0",
                    "--db", os.path.join(tmpd, f"ingress_{tag}.db"),
                    "--symbols", str(srv_symbols),
                    "--capacity", str(srv_capacity),
                    "--batch", str(srv_batch),
                    "--window-ms", str(args.edge_window_ms),
                    "--megadispatch-max-waves", str(args.edge_mega),
                    "--feed-depth", "0",
                    # Screens ON in every measured path. 'real': the
                    # permissive limits run the vectorized passes
                    # without adding rejects. 'screened': max-qty 1
                    # rejects every submit AT the screen — the edge +
                    # admission pipeline in isolation, no dispatch.
                    "--admission-rate", "1000000000",
                    "--admission-window-s", "1.0",
                    "--admission-max-qty",
                    "1" if screened else "2000000"]
            if me_native.available():
                argv.append("--native-lanes")
            if shm_path is not None:
                argv += ["--shm-ingress", shm_path]
            logf = open(log_path, "w")
            proc = _sp.Popen(argv, stdout=logf, stderr=_sp.STDOUT,
                             env=dict(os.environ, PYTHONUNBUFFERED="1"))
            import re as _re

            port = None
            deadline = time.time() + 180
            while time.time() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"ingress server ({tag}) died; see {log_path}")
                m = _re.search(r"listening on port (\d+)",
                               open(log_path).read())
                if m:
                    port = int(m.group(1))
                    break
                time.sleep(0.25)
            if port is None:
                proc.kill()
                raise RuntimeError(f"ingress server ({tag}) never bound")
            return proc, port

        def scrape(stub):
            resp = stub.GetMetrics(pb2.MetricsRequest(), timeout=30)
            return dict(resp.counters)

        def replay_perop(stub) -> tuple[int, int, int]:
            n = min(len(arr), args.ingress_perop_ops)
            acc = rej = 0
            _OT = {0: (pb2.LIMIT, 0), 1: (pb2.MARKET, 0),
                   2: (pb2.LIMIT, pb2.TIF_IOC), 3: (pb2.LIMIT, pb2.TIF_FOK),
                   4: (pb2.MARKET, pb2.TIF_FOK)}
            for i in range(n):
                (op, side, otype, price_q4, qty, sym, cid,
                 oid) = oprec.record_fields(arr[i])
                if op == oprec.OPREC_SUBMIT:
                    ot, tif = _OT[otype]
                    r = stub.SubmitOrder(pb2.OrderRequest(
                        client_id=cid.decode(), symbol=sym.decode(),
                        side=side, order_type=ot, tif=tif,
                        price=price_q4, scale=4, quantity=qty),
                        timeout=60)
                elif op == oprec.OPREC_CANCEL:
                    r = stub.CancelOrder(pb2.CancelRequest(
                        client_id=cid.decode(), order_id=oid.decode()),
                        timeout=60)
                else:
                    r = stub.AmendOrder(pb2.AmendRequest(
                        client_id=cid.decode(), order_id=oid.decode(),
                        new_quantity=qty), timeout=60)
                if r.success:
                    acc += 1
                else:
                    rej += 1
            return n, acc, rej

        def replay_batch(stub) -> tuple[int, int, int]:
            acc = rej = 0
            for s0 in range(0, len(arr), bs):
                resp = stub.SubmitOrderBatch(pb2.OrderBatchRequest(
                    ops=oprec.slice_payload(arr, s0, bs)), timeout=300)
                if not resp.success:
                    raise RuntimeError(
                        f"batch rejected: {resp.error_message}")
                a = sum(resp.ok)
                acc += a
                rej += len(resp.ok) - a
            return len(arr), acc, rej

        def replay_stream(stub) -> tuple[int, int, int]:
            def chunks():
                for s0 in range(0, len(arr), args.ingress_chunk):
                    yield pb2.OrderBatchRequest(
                        ops=oprec.slice_payload(arr, s0,
                                                args.ingress_chunk))

            resp = stub.SubmitOrderStream(chunks(), timeout=600)
            if not resp.success:
                raise RuntimeError(
                    f"stream rejected: {resp.error_message}")
            a = sum(resp.ok)
            return len(resp.ok), a, len(resp.ok) - a

        def replay_shm(shm_path: str) -> tuple[int, int, int]:
            ring = me_native.ShmRing(shm_path)
            # Cancel-gap flow control for recorded scenarios: the poller
            # dispatches whatever run it pops, and a cancel landing in
            # the SAME dispatch as its target resolves against the
            # pre-batch directory ('unknown order id'). Bounding the
            # in-flight backlog below min_cancel_gap keeps a target's
            # dispatch strictly ahead of its cancel's. Submit-only
            # synthetic flow needs no bound beyond the ring itself.
            max_inflight = max(bs, gap - bs) if gap else (1 << 30)
            try:
                acc = rej = pending = pushed = 0

                def drain(wait_us):
                    nonlocal acc, rej, pending
                    raw = ring.resp_poll_raw(4096, wait_us)
                    if raw is None:
                        raise RuntimeError(
                            "shm segment shut down mid-replay (server "
                            "died?)")
                    if not raw:
                        return
                    rs = np.frombuffer(raw, dtype=oprec.SHM_RESP_DTYPE)
                    pending -= len(rs)
                    a = int(np.count_nonzero(rs["ok"]))
                    acc += a
                    rej += len(rs) - a

                push_deadline = time.perf_counter() + 300
                while pushed < len(arr):
                    if time.perf_counter() > push_deadline:
                        raise RuntimeError(
                            f"shm replay stalled ({pushed}/{len(arr)} "
                            f"pushed)")
                    n = min(bs, len(arr) - pushed)
                    if pending + n > max_inflight:
                        drain(2_000)
                        continue
                    base = ring.push_payload(
                        arr[pushed:pushed + n].tobytes(), n)
                    if base == -2:
                        raise RuntimeError(
                            "shm segment shut down mid-replay")
                    if base < 0:
                        drain(5_000)  # full: let the poller catch up
                        continue
                    pushed += n
                    pending += n
                    drain(0)
                deadline = time.perf_counter() + 120
                while pending > 0 and time.perf_counter() < deadline:
                    drain(100_000)
                if pending:
                    raise RuntimeError(
                        f"shm replay: {pending} responses missing")
                return pushed, acc, rej
            finally:
                ring.close()

        # One THROWAWAY boot warms the persistent jax compile cache with
        # this workload's dispatch shapes. Warming inside a measured
        # server would consume OIDs and break the recorder's cancel
        # renumbering (every id shifts); warming a server nobody
        # measures leaves each rung's OID line pristine while its first
        # dispatch hits the compile cache instead of a cold trace.
        proc, port = boot("cachewarm", None)
        try:
            stub = MatchingEngineStub(grpc.insecure_channel(
                f"127.0.0.1:{port}"))
            for s0 in (0, bs):
                stub.SubmitOrderBatch(pb2.OrderBatchRequest(
                    ops=oprec.slice_payload(arr, s0, bs)), timeout=300)
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=20)
            except Exception:  # noqa: BLE001
                proc.kill()

        rows = []
        sections = [s.strip() for s in args.ingress_sections.split(",")
                    if s.strip()]
        for section, rung in [(s, r) for s in sections for r in rungs]:
            screened = section == "screened"
            reps = []
            for rep in range(max(1, args.repeats)):
                shm_path = (os.path.join(tmpd,
                                         f"ring_{section}_{rung}_{rep}")
                            if rung == "shm" else None)
                proc, port = boot(f"{section}_{rung}_{rep}", shm_path,
                                  screened)
                try:
                    stub = MatchingEngineStub(grpc.insecure_channel(
                        f"127.0.0.1:{port}"))
                    c0 = scrape(stub)
                    t0 = time.perf_counter()
                    if rung == "perop":
                        n, acc, rej = replay_perop(stub)
                    elif rung == "batch":
                        n, acc, rej = replay_batch(stub)
                    elif rung == "stream":
                        n, acc, rej = replay_stream(stub)
                    elif rung == "shm":
                        n, acc, rej = replay_shm(shm_path)
                    else:
                        raise SystemExit(f"unknown rung {rung!r}")
                    dt = time.perf_counter() - t0
                    c1 = scrape(stub)
                    row = {
                        "rung": rung,
                        "engine": section,
                        "n_ops": n,
                        "orders_per_s": round(n / dt, 1),
                        "accepted": acc,
                        "rejected": rej,
                        "wall_s": round(dt, 3),
                        # Proof the screens ran in the measured path:
                        # the admission counters exist on the scrape
                        # (zero rejects — the limits are permissive).
                        "screens_active":
                            "admission_rate_rejects" in c1,
                        "screen_rejects": sum(
                            c1.get(k, 0) - c0.get(k, 0)
                            for k in ("admission_rate_rejects",
                                      "admission_qty_rejects",
                                      "admission_band_rejects",
                                      "admission_stp_rejects")),
                        "mega_steps": c1.get("megadispatch_steps", 0)
                        - c0.get("megadispatch_steps", 0),
                    }
                    if rung == "shm":
                        row["ingress_records"] = (
                            c1.get("ingress_records", 0)
                            - c0.get("ingress_records", 0))
                        row["ingress_torn_recoveries"] = c1.get(
                            "ingress_torn_recoveries", 0)
                    if rung == "batch":
                        row["batch_size"] = bs
                    if rung == "stream":
                        row["chunk"] = args.ingress_chunk
                    reps.append(row)
                finally:
                    proc.terminate()
                    try:
                        proc.wait(timeout=20)
                    except Exception:  # noqa: BLE001
                        proc.kill()
            rates = [r["orders_per_s"] for r in reps]
            best = max(reps, key=lambda r: r["orders_per_s"])
            best["repeats"] = len(reps)
            best["orders_per_s_spread"] = [min(rates), max(rates)]
            rows.append(best)
            print(f"[ingress] {section}/{rung}: "
                  f"{best['orders_per_s']} orders/s "
                  f"(n {best['n_ops']}, acc {best['accepted']}, rej "
                  f"{best['rejected']}, wall {best['wall_s']}s)",
                  file=sys.stderr)
        # -- multi-writer saturation sweep (shm_wW rows) -------------------
        def replay_shm_multi(section: str, W: int, rep: int) -> dict:
            """W concurrent `client submit-shm` PROCESSES over disjoint
            slices of the workload into one ring: spawn, wait for every
            writer to attach + register, release a start barrier, and
            measure the aggregate window from the release to the last
            exit (python startup excluded on every writer equally)."""
            tag = f"{section}_shm_w{W}_{rep}"
            shm_path = os.path.join(tmpd, f"ring_{tag}")
            proc, port = boot(tag, shm_path, section == "screened")
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PYTHONUNBUFFERED="1")
            writers = []
            try:
                stub = MatchingEngineStub(grpc.insecure_channel(
                    f"127.0.0.1:{port}"))
                c0 = scrape(stub)
                barrier = os.path.join(tmpd, f"go_{tag}")
                per = len(arr) // W
                for i in range(W):
                    cnt = per if i < W - 1 else len(arr) - per * (W - 1)
                    summ = os.path.join(tmpd, f"w_{tag}_{i}.json")
                    ready = os.path.join(tmpd, f"ready_{tag}_{i}")
                    writers.append((summ, ready, _sp.Popen(
                        [sys.executable, "-m",
                         "matching_engine_tpu.client.cli", "submit-shm",
                         shm_path, workload_name,
                         "--offset", str(i * per), "--count", str(cnt),
                         "--chunk", str(bs), "--timeout", "300",
                         "--quiet", "--summary-json", summ,
                         "--ready-file", ready,
                         "--start-barrier", barrier],
                        env=env, stdout=_sp.DEVNULL,
                        stderr=_sp.DEVNULL)))
                deadline = time.time() + 120
                while (not all(os.path.exists(r) for _s, r, _p in writers)
                       and time.time() < deadline):
                    time.sleep(0.01)
                open(barrier, "w").write("go")
                t0 = time.perf_counter()
                for _s, _r, p_ in writers:
                    # Exit 3 = replay completed with zero accepts — the
                    # screened section rejects every submit BY DESIGN.
                    if p_.wait(timeout=600) not in (0, 3):
                        raise RuntimeError(
                            f"shm writer exited {p_.returncode} "
                            f"({tag})")
                spawn_wall = time.perf_counter() - t0
                sums = [_json.load(open(s)) for s, _r, _p in writers]
                c1 = scrape(stub)
                # The aggregate window: barrier release to the LAST
                # writer's final drain — max over the (barrier-
                # synchronized) per-writer windows, which excludes each
                # interpreter's teardown (spawn_to_exit_s keeps the
                # raw parent-side figure for comparison).
                wall = max(s["wall_s"] for s in sums)
                # Per-writer fairness over each writer's OWN post-
                # barrier window: ops-through-the-edge per second.
                rates = [(s["accepted"] + s["rejected"]) / s["wall_s"]
                         for s in sums if s["wall_s"] > 0]
                wids = [s["writer_id"] for s in sums]
                perw = {w: c1.get(f"ingress_writer{w}_records", 0)
                        - c0.get(f"ingress_writer{w}_records", 0)
                        for w in wids}
                return {
                    "rung": f"shm_w{W}",
                    "engine": section,
                    "writers": W,
                    "n_ops": len(arr),
                    "orders_per_s": round(len(arr) / wall, 1),
                    "accepted": sum(s["accepted"] for s in sums),
                    "rejected": sum(s["rejected"] for s in sums),
                    "wall_s": round(wall, 3),
                    "spawn_to_exit_s": round(spawn_wall, 3),
                    "per_writer_ops_per_s": [round(r, 1)
                                             for r in sorted(rates)],
                    "fairness_min_over_max": round(
                        min(rates) / max(rates), 3) if rates else 0.0,
                    # The poller's per-writer series must account for
                    # every record, attributed to a registered lane.
                    "per_writer_records": perw,
                    "per_writer_records_ok":
                        all(w > 0 for w in wids)
                        and sum(perw.values()) == len(arr),
                    "ingress_torn_recoveries":
                        c1.get("ingress_torn_recoveries", 0),
                }
            finally:
                for _s, _r, p_ in writers:
                    if p_.poll() is None:
                        p_.kill()
                proc.terminate()
                try:
                    proc.wait(timeout=20)
                except Exception:  # noqa: BLE001
                    proc.kill()

        wlist = [int(x) for x in args.shm_writers.split(",")
                 if x.strip()]
        if wlist and "shm" in rungs and gap:
            print("[ingress] --shm-writers needs a submit-only workload "
                  "(recorded cancel targets do not survive concurrent "
                  "interleaving); skipping the multi-writer sweep",
                  file=sys.stderr)
            wlist = []
        if wlist and "shm" in rungs:
            for section in sections:
                base_rate = None
                for W in wlist:
                    reps = [replay_shm_multi(section, W, rep)
                            for rep in range(max(1, args.repeats))]
                    rates = [r["orders_per_s"] for r in reps]
                    best = max(reps, key=lambda r: r["orders_per_s"])
                    best["repeats"] = len(reps)
                    best["orders_per_s_spread"] = [min(rates),
                                                   max(rates)]
                    if W == 1 or base_rate is None:
                        base_rate = best["orders_per_s"]
                    best["vs_1writer_x"] = round(
                        best["orders_per_s"] / base_rate, 2)
                    rows.append(best)
                    print(f"[ingress] {section}/shm_w{W}: "
                          f"{best['orders_per_s']} orders/s "
                          f"({best['vs_1writer_x']}x vs w1, fairness "
                          f"{best['fairness_min_over_max']}, wall "
                          f"{best['wall_s']}s)", file=sys.stderr)
        # The headline ratios, per section.
        for section in sections:
            by = {r["rung"]: r for r in rows if r["engine"] == section}
            if "shm" in by and "batch" in by \
                    and by["batch"]["orders_per_s"]:
                by["shm"]["vs_batch_x"] = round(
                    by["shm"]["orders_per_s"]
                    / by["batch"]["orders_per_s"], 2)
        return rows

    # -- workload replay (sim/record.py artifacts) -------------------------

    def workload_sweep() -> list:
        """Replay recorded scenario opfiles through the live serving
        stack — in-proc (host-only serving figure) and/or the loopback
        gRPC batch edge — one row per (scenario, path). Replay is
        IN ORDER on one stream (the recorder renumbered cancel targets
        to the ids a fresh server assigns in record order), phase-aware
        (auction phases open the call period via RunAuction open_call
        and uncross at the phase end), and reconciled against the sim's
        own ground truth (fills / uncross volume from the manifest)."""
        import tempfile

        import grpc

        from matching_engine_tpu.domain import oprec
        from matching_engine_tpu.proto import pb2
        from matching_engine_tpu.proto.rpc import MatchingEngineStub
        from matching_engine_tpu.sim.record import read_manifest

        files = [f.strip() for f in args.workload.split(",") if f.strip()]
        paths = [s.strip() for s in args.workload_paths.split(",")
                 if s.strip()]
        bad = [s for s in paths if s not in ("inproc", "edge")]
        if bad:
            raise SystemExit(
                f"--workload-paths: unknown path(s) {bad} "
                f"(valid: inproc, edge)")
        rows = []

        def replay(man, arr, submit_batch, run_auction, get_metrics,
                   tag) -> dict:
            gap = man.get("min_cancel_gap") or 512
            bs = args.workload_batch or max(1, min(512, gap))
            c0, g0 = get_metrics()
            lat: list[float] = []
            acc = rej = 0
            reasons: dict[str, int] = {}
            uncross_total = 0
            t0 = time.perf_counter()
            for ph in man["phases"]:
                if ph["kind"] == "auction":
                    r = run_auction(open_call=True)
                    if not r.success:
                        raise RuntimeError(
                            f"open_call rejected: {r.error_message}")
                for s0 in range(ph["start_record"], ph["end_record"], bs):
                    n = min(bs, ph["end_record"] - s0)
                    payload = oprec.slice_payload(arr, s0, n)
                    tb = time.perf_counter()
                    resp = submit_batch(payload)
                    lat.append(time.perf_counter() - tb)
                    if not resp.success:
                        raise RuntimeError(
                            f"batch rejected: {resp.error_message}")
                    for i, ok in enumerate(resp.ok):
                        if ok:
                            acc += 1
                        else:
                            rej += 1
                            reasons[resp.error[i]] = (
                                reasons.get(resp.error[i], 0) + 1)
                if ph["kind"] == "auction":
                    r = run_auction(open_call=False)
                    if not r.success:
                        raise RuntimeError(
                            f"uncross rejected: {r.error_message}")
                    uncross_total += int(r.executed_quantity)
            wall = time.perf_counter() - t0
            c1, g1 = get_metrics()
            # Steady-state batch percentiles: the first batches carry the
            # one-time jit/trace warm costs of each dispatch shape (the
            # persistent compile cache bounds them, but the first sight
            # per process still traces) — excluded from p50/p99, with the
            # burn-in count and the all-in wall published beside them
            # (BENCH_METHOD §workload-replay).
            burn = min(len(lat) - 1, max(3, len(lat) // 20))
            steady = sorted(lat[burn:]) or [0.0]
            mega = c1.get("megadispatch_steps", 0) - c0.get(
                "megadispatch_steps", 0)
            waves = c1.get("megadispatch_stacked_waves", 0) - c0.get(
                "megadispatch_stacked_waves", 0)
            row = {
                "scenario": man["name"],
                "path": tag,
                "serve_shards": man.get("serve_shards", 1),
                "ops": man["ops"],
                "batch_records": bs,
                "orders_per_s": round(man["ops"] / wall, 1),
                "accepted": acc,
                "rejected": rej,
                "reject_rate": round(rej / max(1, man["ops"]), 4),
                "reject_reasons": reasons,
                "fills": c1.get("fills", 0) - c0.get("fills", 0),
                "sim_fills": man["sim_fills"],
                "auctions": c1.get("auctions", 0) - c0.get("auctions", 0),
                "uncross_executed": uncross_total,
                "wall_s": round(wall, 3),
                "batch_p50_ms": round(
                    steady[len(steady) // 2] * 1e3, 3),
                "batch_p99_ms": round(
                    steady[min(len(steady) - 1,
                               int(len(steady) * 0.99))] * 1e3, 3),
                "burn_in_batches": burn,
                "mega_steps": mega,
                "mega_waves_per_step": round(waves / mega, 2) if mega
                else 0.0,
            }
            lanes = {k: round(v, 2) for k, v in g1.items()
                     if k.startswith("lane")}
            if lanes:
                row["lane_gauges"] = lanes
            if row["fills"] != man["sim_fills"]:
                # The replay is expected bit-faithful (same per-symbol op
                # order, same capacity): a fill-count drift is a finding,
                # not noise — publish it loudly in the row.
                row["fill_drift"] = row["fills"] - man["sim_fills"]
            return row

        def inproc_point(man, arr, path) -> dict:
            from matching_engine_tpu.server.main import (
                build_server,
                shutdown,
            )

            tiers, pins = (), None
            if args.workload_tiers:
                from matching_engine_tpu.server.tiered_runner import (
                    parse_book_tiers,
                )
                from matching_engine_tpu.sim.record import check_tier_depth

                tiers, pins = parse_book_tiers(args.workload_tiers,
                                               man["symbols"])
                bad_depth = check_tier_depth(man, tiers, pins)
                if bad_depth:
                    raise SystemExit(
                        "--workload-tiers too shallow for this "
                        "recording:\n  " + "\n  ".join(bad_depth))
            wcfg = EngineConfig(
                num_symbols=man["symbols"],
                capacity=(max(c for _, c in tiers) if tiers
                          else man["capacity"]),
                batch=args.batch, max_fills=man["max_fills"],
                kernel=args.kernel, tiers=tiers)
            tmp = tempfile.mkdtemp(prefix="workload_inproc_")
            kw = dict(window_ms=args.edge_window_ms, log=False,
                      feed_depth=0,
                      megadispatch_max_waves=args.edge_mega,
                      tier_pins=pins)
            if man["serve_shards"] > 1:
                kw["serve_shards"] = man["serve_shards"]
            server, _port, parts = build_server(
                "127.0.0.1:0", os.path.join(tmp, "w.db"), wcfg, **kw)
            svc = parts["service"]
            try:
                def get_metrics():
                    resp = svc.GetMetrics(pb2.MetricsRequest(), None)
                    return dict(resp.counters), dict(resp.gauges)

                return replay(
                    man, arr,
                    lambda payload: svc.SubmitOrderBatch(
                        pb2.OrderBatchRequest(ops=payload), None),
                    lambda open_call: svc.RunAuction(
                        pb2.AuctionRequest(open_call=open_call), None),
                    get_metrics, "inproc-host")
            finally:
                shutdown(server, parts)

        def edge_point(man, arr, path) -> dict:
            import subprocess
            import re as _re

            tmp = tempfile.mkdtemp(prefix="workload_edge_")
            log_path = os.path.join(tmp, "server.log")
            argv = [sys.executable, "-m",
                    "matching_engine_tpu.server.main",
                    "--addr", "127.0.0.1:0",
                    "--db", os.path.join(tmp, "w.db"),
                    "--symbols", str(man["symbols"]),
                    "--capacity", str(man["capacity"]),
                    "--batch", str(args.batch),
                    "--window-ms", str(args.edge_window_ms),
                    "--megadispatch-max-waves", str(args.edge_mega),
                    "--feed-depth", "0"]
            if man["serve_shards"] > 1:
                argv += ["--serve-shards", str(man["serve_shards"])]
            logf = open(log_path, "w")
            proc = subprocess.Popen(
                argv, stdout=logf, stderr=subprocess.STDOUT,
                env=dict(os.environ, PYTHONUNBUFFERED="1"))
            port = None
            deadline = time.time() + 180
            while time.time() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"workload edge server died; see {log_path}")
                mm = _re.search(r"listening on port (\d+)",
                                open(log_path).read())
                if mm:
                    port = int(mm.group(1))
                    break
                time.sleep(0.25)
            if port is None:
                proc.kill()
                raise RuntimeError("workload edge server never bound")
            try:
                stub = MatchingEngineStub(
                    grpc.insecure_channel(f"127.0.0.1:{port}"))

                def get_metrics():
                    resp = stub.GetMetrics(pb2.MetricsRequest(),
                                           timeout=30)
                    return dict(resp.counters), dict(resp.gauges)

                return replay(
                    man, arr,
                    lambda payload: stub.SubmitOrderBatch(
                        pb2.OrderBatchRequest(ops=payload), timeout=120),
                    lambda open_call: stub.RunAuction(
                        pb2.AuctionRequest(open_call=open_call),
                        timeout=120),
                    get_metrics, "grpc-batch-edge")
            finally:
                proc.terminate()
                try:
                    proc.wait(timeout=20)
                except Exception:  # noqa: BLE001
                    proc.kill()

        for f in files:
            man = read_manifest(f)
            arr = oprec.read_opfile(f)
            assert len(arr) == man["ops"], (f, len(arr), man["ops"])
            for path in paths:
                point = inproc_point if path == "inproc" else edge_point
                row = point(man, arr, path)
                row["workload_file"] = f
                rows.append(row)
                print(f"[workload] {man['name']} {row['path']}: "
                      f"{row['orders_per_s']} orders/s, rej "
                      f"{row['rejected']} ({row['reject_rate']:.1%}), "
                      f"fills {row['fills']}/{row['sim_fills']}, p99 "
                      f"{row['batch_p99_ms']}ms, megaM "
                      f"{row['mega_waves_per_step']}", file=sys.stderr)
        return rows

    def capacity_sweep():
        """Per-(kernel, capacity) steady-state deep-book throughput:
        the O(levels)-vs-O(capacity) comparison ROADMAP item 5 asks for.
        Books are prefilled to --sweep-depth-frac of capacity as
        price-level ladders (ladder prices spread over the levels
        kernel's own L rows, so every kernel faces the identical
        stream); the timed region is a balanced churn mix — one
        single-maker IOC taker, one cancel, two replenishing rests per
        cycle — dispatched as packed dense waves with NO host decode, so
        the number is the device kernel's cost of depth, not the serving
        stack's. Each point warms the jit cache with one untimed pass,
        then takes best-of --repeats from identical device_put'd books
        (the step donates its input, so every repeat re-uploads the same
        prefilled host copy)."""
        from matching_engine_tpu.engine.book import (
            default_levels,
            init_book,
        )
        from matching_engine_tpu.engine.harness import (
            HostOrder,
            build_batch_arrays,
        )
        from matching_engine_tpu.engine.kernel import (
            LIMIT,
            LIMIT_IOC,
            OP_CANCEL,
            engine_step_packed,
        )

        S, B = args.sweep_symbols, args.batch
        frac = args.sweep_depth_frac
        rows = []
        for cap in [int(c) for c in args.capacity_sweep.split(",")]:
            lvl = default_levels(cap)
            fifo = cap // lvl
            depth = max(4, int(cap * frac))
            step_px = 10
            ask_px = [10_000 + step_px * i for i in range(lvl)]
            bid_px = [9_990 - step_px * i for i in range(lvl)]
            rng = random.Random(1234 + cap)

            # Prefill: `depth` resting orders per side per symbol,
            # round-robin over the ladder (per-price count = depth/L <=
            # frac*F, inside every kernel's structural capacity).
            oid = 0
            prefill: list = []
            # sym -> [(oid, side, price)] — the cancel pool; lvl0[s] is
            # the FIFO of best-ask (ask_px[0]) sells, the takers' prey.
            live: dict[int, list[tuple[int, int, int]]] = {
                s: [] for s in range(S)}
            lvl0: dict[int, list[int]] = {s: [] for s in range(S)}
            for s in range(S):
                for d in range(depth):
                    for side, px in ((SELL, ask_px[d % lvl]),
                                     (BUY, bid_px[d % lvl])):
                        oid += 1
                        prefill.append(HostOrder(
                            s, OP_SUBMIT, side, LIMIT, px, 5, oid=oid))
                        live[s].append((oid, side, px))
                        if side == SELL and px == ask_px[0]:
                            lvl0[s].append(oid)

            # Measured churn: DEPTH-NEUTRAL by construction — per cycle
            # one taker fully consumes the best-ask FIFO head (equal
            # quantities; the consumed oid leaves the cancel pool so
            # later cancels never target a dead order), one rest
            # restocks that exact level, one cancel removes a random
            # resting order, one rest replaces it at a random ladder
            # point. Same stream for every kernel at this capacity.
            churn: list = []
            for i in range(args.sweep_ops):
                s = i % S
                # Decoupled from s (i//S), so EVERY symbol rotates
                # through all four op kinds — s = i % S and k = i % 4
                # would lock each symbol to one kind whenever S | 4.
                k = (i // S) % 4
                if k == 0:
                    oid += 1
                    churn.append(HostOrder(
                        s, OP_SUBMIT, BUY, LIMIT_IOC, ask_px[0], 5,
                        oid=oid))
                    if lvl0[s]:
                        victim = lvl0[s].pop(0)
                        live[s] = [t for t in live[s] if t[0] != victim]
                elif k == 1:
                    oid += 1
                    churn.append(HostOrder(
                        s, OP_SUBMIT, SELL, LIMIT, ask_px[0], 5, oid=oid))
                    live[s].append((oid, SELL, ask_px[0]))
                    lvl0[s].append(oid)
                elif k == 2 and live[s]:
                    t_oid, t_side, t_px = live[s].pop(
                        rng.randrange(len(live[s])))
                    churn.append(HostOrder(s, OP_CANCEL, t_side,
                                           oid=t_oid))
                    if t_side == SELL and t_px == ask_px[0]:
                        lvl0[s] = [o for o in lvl0[s] if o != t_oid]
                else:
                    oid += 1
                    side = SELL if (i // 4) % 2 == 0 else BUY
                    px = (ask_px if side == SELL else bid_px)[
                        rng.randrange(lvl)]
                    churn.append(HostOrder(
                        s, OP_SUBMIT, side, LIMIT, px, 5, oid=oid))
                    live[s].append((oid, side, px))
                    if side == SELL and px == ask_px[0]:
                        lvl0[s].append(oid)

            for kern in [k.strip() for k in args.sweep_kernels.split(",")]:
                if kern == "matrix" and cap > 1024:
                    rows.append({
                        "kernel": kern, "capacity": cap,
                        "supported": False,
                        "reason": "matrix kernel inadmissible past 1024 "
                                  "(int32 qty-sum wrap + [C, C] "
                                  "intermediates)",
                    })
                    print(f"[capacity-sweep] {kern}@{cap}: unsupported",
                          file=sys.stderr)
                    continue
                kcfg = EngineConfig(
                    num_symbols=S, capacity=cap, batch=B,
                    max_fills=1 << 15, kernel=kern)
                p_arrays = build_batch_arrays(kcfg, prefill)
                c_arrays = build_batch_arrays(kcfg, churn)
                n_real = sum(int(np.count_nonzero(a[:, :, 0]))
                             for a in c_arrays)

                book = init_book(kcfg)
                for arr in p_arrays:
                    book, _ = engine_step_packed(kcfg, book, arr)
                jax.block_until_ready(book)
                host_book = type(book)(*(np.asarray(x) for x in book))

                def one_pass():
                    b = jax.device_put(host_book)
                    t0 = time.perf_counter()
                    out = None
                    for arr in c_arrays:
                        b, out = engine_step_packed(kcfg, b, arr)
                    jax.block_until_ready((b, out.small))
                    return n_real / (time.perf_counter() - t0)

                one_pass()  # warm the jit cache (compile excluded)
                rates = [one_pass() for _ in range(max(1, args.repeats))]
                rows.append({
                    "kernel": kern, "capacity": cap, "supported": True,
                    "levels": ([lvl, fifo] if kern == "levels" else None),
                    "depth_per_side": depth,
                    "measured_ops": n_real,
                    "orders_per_s": round(max(rates), 1),
                    "orders_per_s_spread": [round(min(rates), 1),
                                            round(max(rates), 1)],
                    "repeats": len(rates),
                })
                print(f"[capacity-sweep] {kern}@{cap} depth {depth}: "
                      f"{max(rates):,.0f} orders/s "
                      f"(spread {min(rates):,.0f}-{max(rates):,.0f})",
                      file=sys.stderr)
        return rows

    grid_cap = args.symbols * args.batch
    mega_list = [int(x) for x in args.megadispatch.split(",")
                 if x.strip()] if args.megadispatch else []
    shard_list = [int(k) for k in args.serve_shards.split(",")
                  if k.strip()] if args.serve_shards else []
    if args.capacity_sweep:
        rows = capacity_sweep()
    elif args.device_sweep:
        rows = device_sweep()
    elif args.ingress:
        rows = ingress_sweep()
    elif args.workload:
        rows = workload_sweep()
    elif args.edge_batch:
        rows = edge_sweep()
    elif args.audit_ab:
        import sys as _sys

        # The pump thread alternates pure-python slices with the main
        # thread's GIL-released device calls: at CPython's default 5ms
        # switch interval the dispatch thread convoys behind the pump's
        # quantum (the --serve-shards lesson, BENCH_METHOD §partitioned
        # serving) — restore handoff granularity for BOTH arms.
        _sys.setswitchinterval(max(1, args.gil_switch_us) / 1e6)

        # INTERLEAVED paired arms: one (off, on) pair per repeat, so both
        # arms sample the same slow drift of this shared box (block-running
        # one arm's repeats then the other's let minutes-scale load drift
        # masquerade as auditor overhead, in either direction). Best-of
        # per arm over the interleaved reps; the overhead figure is the
        # best-vs-best ratio with both spreads published.
        rows = []
        for mode in args.mode.split(","):
            for bo in str(args.batch_ops).split(","):
                for k in args.inflight.split(","):
                    point = (mode.strip(), int(k), min(int(bo), grid_cap))
                    reps = {"off": [], "on": []}
                    for _ in range(max(1, args.repeats)):
                        for arm in ("off", "on"):
                            reps[arm].append(
                                sweep_point(*point, audit=arm))
                    pair = []
                    for arm in ("off", "on"):
                        rates = [r["orders_per_s"] for r in reps[arm]]
                        best = max(reps[arm],
                                   key=lambda r: r["orders_per_s"])
                        best["repeats"] = len(rates)
                        best["orders_per_s_spread"] = [min(rates),
                                                       max(rates)]
                        pair.append(best)
                    off, on = pair
                    on["audit_overhead_pct"] = round(
                        100.0 * (1.0 - on["orders_per_s"]
                                 / off["orders_per_s"]), 1)
                    # Median-vs-median too: best-of is the noise floor,
                    # the median pair is the typical-run figure.
                    med = [sorted(r["orders_per_s"] for r in reps[a])
                           [len(reps[a]) // 2] for a in ("off", "on")]
                    on["audit_overhead_pct_median"] = round(
                        100.0 * (1.0 - med[1] / med[0]), 1)
                    rows.extend(pair)
    elif mega_list:

        def best_of_mega(m, k):
            reps = [sweep_point_mega(m, k)
                    for _ in range(max(1, args.repeats))]
            rates = [r["orders_per_s"] for r in reps]
            best = max(reps, key=lambda r: r["orders_per_s"])
            best["repeats"] = len(reps)
            best["orders_per_s_spread"] = [min(rates), max(rates)]
            return best

        rows = [best_of_mega(m, int(k))
                for k in args.inflight.split(",")
                for m in mega_list]
    elif shard_list:
        import sys as _sys

        _sys.setswitchinterval(max(1, args.gil_switch_us) / 1e6)

        def best_of(mode, k, bo, K):
            reps = [sweep_point_sharded(mode, k, bo, K)
                    for _ in range(max(1, args.repeats))]
            rates = [r["orders_per_s"] for r in reps]
            best = max(reps, key=lambda r: r["orders_per_s"])
            best["repeats"] = len(reps)
            best["orders_per_s_spread"] = [min(rates), max(rates)]
            return best

        rows = [best_of(mode.strip(), int(k),
                        min(int(bo), (args.symbols // K) * args.batch), K)
                for mode in args.mode.split(",")
                for bo in str(args.batch_ops).split(",")
                for k in args.inflight.split(",")
                for K in shard_list]
    else:
        rows = [sweep_point(mode.strip(), int(k), min(int(bo), grid_cap))
                for mode in args.mode.split(",")
                for bo in str(args.batch_ops).split(",")
                for k in args.inflight.split(",")]

    try:
        import subprocess
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        rev = "unknown"
    out = {
        "metric": ("kernel_capacity_sweep" if args.capacity_sweep
                   else "device_mesh_serving" if args.device_sweep
                   else "ingress_rungs" if args.ingress
                   else "workload_replay" if args.workload
                   else "batch_edge_audit_ab" if args.edge_batch
                   and args.audit_ab
                   else "batch_edge_throughput" if args.edge_batch
                   else "auditor_overhead_ab" if args.audit_ab
                   else "runner_dispatch_throughput"),
        "platform": platform,
        "symbols": args.symbols,
        "capacity": args.capacity,
        "batch": args.batch,
        "kernel": args.kernel,
        "backend_init_s": round(backend_init_s, 1),
        # Lane scaling is bounded by min(K, host cores): record the
        # ceiling next to the sweep so cross-machine artifacts compare.
        "host_cpus": os.cpu_count(),
        "sweep": rows,
        "git_rev": rev,
    }
    if args.edge_batch:
        out["edge_mega"] = args.edge_mega
        out["edge_window_ms"] = args.edge_window_ms
    if args.device_sweep:
        out["device_counts"] = [int(x) for x in
                                args.device_sweep.split(",") if x.strip()]
        out["device_sweep_batch"] = args.device_sweep_batch
        out["edge_mega"] = args.edge_mega
        out["edge_window_ms"] = args.edge_window_ms
    if args.workload:
        out["workloads"] = [f.strip() for f in args.workload.split(",")
                            if f.strip()]
        out["edge_mega"] = args.edge_mega
        out["edge_window_ms"] = args.edge_window_ms
    if args.ingress:
        out["ingress_workload"] = (args.ingress_workload
                                   or f"synthetic_edge "
                                      f"({args.ingress_synthetic_ops} "
                                      f"submit-only maker/taker records)")
        out["ingress_batch_size"] = args.ingress_batch_size
        out["ingress_chunk"] = args.ingress_chunk
        if args.shm_writers:
            out["shm_writers"] = [int(x) for x in
                                  args.shm_writers.split(",")
                                  if x.strip()]
        out["edge_mega"] = args.edge_mega
        out["edge_window_ms"] = args.edge_window_ms
    tmp = args.json_out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
    os.replace(tmp, args.json_out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
