"""Failover benchmark: SIGKILL the primary, promote the warm standby,
measure kill-to-first-accepted-order latency (ISSUE 11 acceptance: the
artifact pins a sub-second target on this box).

Topology per round — two REAL server subprocesses (the kill must cross a
process boundary) plus this bench process as the client population:

  primary  --oplog-ship --audit   <- load thread submits, records acks
  standby  --standby <primary>    <- applies the op log, attests

Sequence: warm both up, drive load until the standby's replication lag is
zero, then SIGKILL the primary mid-flow and run the operator's failover
script at machine speed: Promote RPC on the standby, then submit until
the first accept. The clock runs from the moment SIGKILL is issued to the
first accepted order on the promoted replica — detection time is NOT
modeled (the bench IS the supervisor; production detection cost is the
heartbeat lapse an operator configures via --standby-auto-promote-s).

Also proved per round, because latency without integrity is meaningless:
- acked-order survival: every order the primary acked that REACHED the
  standby's op log is in the promoted store; the count the standby never
  received (in-flight at the kill) is reported as `acked_lost` (target 0
  on a same-host link — the ship precedes the ack, loss means the stream
  delivery itself was cut inside that window);
- prefix bit-identity: replication/verify.py compare_stores over the dead
  primary's db and the promoted replica's db.

Usage: python benchmarks/failover_bench.py --json-out \
           benchmarks/results/failover_bench_r12.json [--rounds 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sqlite3
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import grpc  # noqa: E402

from matching_engine_tpu.proto import pb2  # noqa: E402
from matching_engine_tpu.proto.rpc import MatchingEngineStub  # noqa: E402
from matching_engine_tpu.replication.verify import compare_stores  # noqa: E402

BOOT_TIMEOUT_S = 180.0


def _spawn(work: str, name: str, extra: list[str], symbols: int,
           capacity: int, batch: int) -> tuple[subprocess.Popen, str, str]:
    log = os.path.join(work, f"{name}.log")
    proc = subprocess.Popen(
        [sys.executable, "-m", "matching_engine_tpu.server.main",
         "--addr", "127.0.0.1:0", "--db", os.path.join(work, f"{name}.db"),
         "--symbols", str(symbols), "--capacity", str(capacity),
         "--batch", str(batch), "--window-ms", "1", *extra],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONUNBUFFERED": "1"},
        cwd=REPO, stdout=open(log, "w"), stderr=subprocess.STDOUT)
    return proc, log, os.path.join(work, f"{name}.db")


def _port_of(proc: subprocess.Popen, log: str) -> int:
    deadline = time.monotonic() + BOOT_TIMEOUT_S
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"server died at boot:\n{open(log).read()[-2000:]}")
        for line in open(log):
            if "listening on port " in line:
                return int(line.split("listening on port ")[1].split()[0])
        time.sleep(0.5)
    raise RuntimeError(f"server never listened:\n{open(log).read()[-2000:]}")


def _stub(port: int) -> MatchingEngineStub:
    return MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))


def _stub_metrics(stub):
    r = stub.GetMetrics(pb2.MetricsRequest(), timeout=10)
    return dict(r.counters), dict(r.gauges)


def _order(i: int) -> pb2.OrderRequest:
    return pb2.OrderRequest(
        client_id=f"fb{i % 3}", symbol=f"S{i % 4}", order_type=pb2.LIMIT,
        side=pb2.BUY if i % 2 == 0 else pb2.SELL,
        price=10_000 + (i % 5) * 100, scale=4, quantity=5)


def _probe_order(i: int) -> pb2.OrderRequest:
    """Post-promotion acceptance probe on symbols the loader NEVER
    touches (S4..S7): the loader can leave the S0..S3 books capacity-
    full, and a book-full reject persists — probing those symbols would
    read steady rejects as "promotion failed"."""
    return pb2.OrderRequest(
        client_id="fbprobe", symbol=f"S{4 + i % 4}", order_type=pb2.LIMIT,
        side=pb2.BUY, price=9_000, scale=4, quantity=1)


def run_round(rnd: int, work: str, symbols: int, capacity: int,
              batch: int) -> dict:
    pproc, plog, pdb = _spawn(work, f"primary{rnd}",
                              ["--oplog-ship", "--audit",
                               "--audit-sample", "1"],
                              symbols, capacity, batch)
    sproc = None
    try:
        pport = _port_of(pproc, plog)
        pstub = _stub(pport)
        pstub.GetOrderBook(pb2.OrderBookRequest(symbol="S0"),
                           timeout=BOOT_TIMEOUT_S)
        sproc, slog, sdb = _spawn(
            work, f"standby{rnd}", ["--standby", f"127.0.0.1:{pport}"],
            symbols, capacity, batch)
        sport = _port_of(sproc, slog)
        sstub = _stub(sport)
        sstub.GetOrderBook(pb2.OrderBookRequest(symbol="S0"),
                           timeout=BOOT_TIMEOUT_S)

        # Load until the standby provably keeps up: it applied the warmup
        # flow and its lag gauge reads zero.
        acked: list[str] = []
        stop = threading.Event()

        def load():
            i = 0
            while not stop.is_set():
                try:
                    r = pstub.SubmitOrder(_order(i), timeout=5)
                except grpc.RpcError:
                    return  # the kill landed mid-RPC
                if r.success:
                    acked.append(r.order_id)
                i += 1

        loader = threading.Thread(target=load, daemon=True)
        loader.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            c, g = _stub_metrics(sstub)
            if (len(acked) >= 100 and g.get("repl_lag_seqs", 1) == 0
                    and c.get("repl_applied_dispatches", 0) > 0):
                break
            time.sleep(0.2)
        else:
            raise RuntimeError("standby never caught up during warmup")

        # The failover: SIGKILL mid-flow, then the operator script at
        # machine speed. The clock starts WITH the kill syscall.
        t_kill = time.perf_counter()
        pproc.kill()
        pr = sstub.Promote(pb2.PromoteRequest(), timeout=60)
        t_promoted = time.perf_counter()
        assert pr.success, pr.error_message
        first_accept = None
        attempts = 0
        acc_deadline = time.monotonic() + 30
        while time.monotonic() < acc_deadline:
            attempts += 1
            r = sstub.SubmitOrder(_probe_order(attempts), timeout=5)
            if r.success:
                first_accept = time.perf_counter()
                break
        if first_accept is None:
            raise RuntimeError("promoted standby never accepted an order")
        pproc.wait(timeout=30)
        stop.set()
        loader.join(timeout=30)

        # Integrity: graceful standby stop (drains the sink), then check
        # acked-order survival and store prefix bit-identity.
        sproc.terminate()
        sproc.wait(timeout=60)
        con = sqlite3.connect(f"file:{sdb}?mode=ro", uri=True)
        try:
            stored = {r[0] for r in
                      con.execute("SELECT order_id FROM orders")}
        finally:
            con.close()
        lost = [o for o in acked if o not in stored]
        stores = compare_stores(pdb, sdb, allow_fork=True)
        return {
            "round": rnd,
            "kill_to_promoted_ms":
                round((t_promoted - t_kill) * 1e3, 2),
            "kill_to_first_accept_ms":
                round((first_accept - t_kill) * 1e3, 2),
            "submit_attempts_until_accept": attempts,
            "acked_under_load": len(acked),
            "acked_lost": len(lost),
            "acked_lost_ids": lost[:10],
            "promoted_feed_epoch": pr.feed_epoch,
            "store_prefix_identical": stores["identical_prefix"],
            "store_report": {k: stores[k] for k in
                             ("orders_a", "orders_b", "common", "equal",
                              "a_ahead", "b_ahead", "only_a", "only_b")},
        }
    finally:
        for proc in (pproc, sproc):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--symbols", type=int, default=8)
    p.add_argument("--capacity", type=int, default=64)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--target-ms", type=float, default=1000.0)
    p.add_argument("--json-out", required=True)
    args = p.parse_args()

    rounds = []
    with tempfile.TemporaryDirectory(prefix="failover_bench_") as work:
        for rnd in range(args.rounds):
            rounds.append(run_round(rnd, work, args.symbols,
                                    args.capacity, args.batch))
            print(json.dumps(rounds[-1]))

    lat = sorted(r["kill_to_first_accept_ms"] for r in rounds)
    best = lat[0]
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5, cwd=REPO).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        rev = "unknown"
    out = {
        "metric": "failover_kill_to_first_accept_ms",
        "value": best,  # best-of-N: the promotion cost floor this box
        #                 supports, the repeats absorbing CPU contention
        "unit": "ms",
        "target_ms": args.target_ms,
        "sub_second": best <= args.target_ms,
        "median_ms": lat[len(lat) // 2],
        "worst_ms": lat[-1],
        "rounds": rounds,
        "zero_acked_loss": all(r["acked_lost"] == 0 for r in rounds),
        "prefix_identical_all_rounds":
            all(r["store_prefix_identical"] for r in rounds),
        "host_cpus": os.cpu_count(),
        "symbols": args.symbols, "capacity": args.capacity,
        "batch": args.batch,
        "git_rev": rev,
    }
    tmp = args.json_out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
    os.replace(tmp, args.json_out)
    print(json.dumps({k: out[k] for k in
                      ("metric", "value", "median_ms", "worst_ms",
                       "sub_second", "zero_acked_loss",
                       "prefix_identical_all_rounds")}))
    ok = out["sub_second"] and out["prefix_identical_all_rounds"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
