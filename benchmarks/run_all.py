"""Benchmark suite: the five BASELINE.json configs, one JSON line each.

The reference publishes no numbers (BASELINE.md), so every figure here is
measured against this repo's north-star target. `bench.py` at the repo root
stays the driver's single headline metric (config 3); this suite covers the
full matrix:

  1 smoke-replay fill parity (functional gate, not perf)
  2 64-symbol Poisson LIMIT-only flow, depth-10 books
  3 4k-symbol L3-style replay, LIMIT+CANCEL+MARKET  (same as bench.py)
  4 gRPC client fan-in through the full server stack (end-to-end, p99)
  5 agent-based market sim, closed loop on device
  6 call-auction uncross: every book cleared at its clearing price in
    one device step (engine/auction.py; beyond the BASELINE five)
  7 venue-depth uncross: config 6 at capacity 2048 on the sorted kernel
    (engine/auction_sorted.py wide-limb exact volumes)

Usage: python benchmarks/run_all.py [--full] [--configs 2,3,5]
--full uses north-star scale (4k symbols, 256 agents, 1k clients); the
default is sized to finish in ~a minute on one chip (or CPU, for CI).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import (
    HostOrder,
    apply_orders,
    random_order_stream,
)
from matching_engine_tpu.engine.kernel import OP_SUBMIT
from matching_engine_tpu.engine.oracle import OracleBook
from matching_engine_tpu.proto import BUY, LIMIT, SELL
from matching_engine_tpu.utils.measure import measure_device_throughput

NORTH_STAR = 10_000_000


def _git_rev() -> str:
    import subprocess
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        return "unknown"


_GIT_REV = _git_rev()


def emit(config: int, name: str, value: float, unit: str, extra: dict | None = None):
    line = {"config": config, "metric": name, "value": round(value, 1), "unit": unit,
            "vs_baseline": round(value / NORTH_STAR, 4) if unit == "orders/sec" else None,
            "platform": jax.devices()[0].platform, "git_rev": _GIT_REV}
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)


# -- config 1: smoke-replay parity -----------------------------------------

def config1_parity():
    """The reference smoke script's flow (scales 8/9/2/0, crossing + MARKET),
    replayed through kernel and oracle; value = 1.0 iff fills identical."""
    cfg = EngineConfig(num_symbols=1, capacity=32, batch=4, max_fills=1024)
    # The reference smoke submits the same displayed price at scales 8/9/2/0
    # (Q4: 1, 0->rejected pre-kernel, 100500, 10050*10^4); extended like
    # scripts/smoke.sh with a crossing SELL and a MARKET order.
    stream = [
        HostOrder(sym=0, op=OP_SUBMIT, side=BUY, otype=LIMIT, price=1, qty=10, oid=1),
        HostOrder(sym=0, op=OP_SUBMIT, side=BUY, otype=LIMIT, price=100500, qty=10, oid=2),
        HostOrder(sym=0, op=OP_SUBMIT, side=BUY, otype=LIMIT, price=10050 * 10000, qty=10, oid=3),
        HostOrder(sym=0, op=OP_SUBMIT, side=SELL, otype=LIMIT, price=100500, qty=15, oid=4),
        HostOrder(sym=0, op=OP_SUBMIT, side=SELL, otype=1, price=0, qty=5, oid=5),
    ]
    book = init_book(cfg)
    book, _, d_fills = apply_orders(cfg, book, stream)
    oracle = OracleBook(capacity=cfg.capacity)
    o_fills = []
    for o in stream:
        r = oracle.submit(o.oid, o.side, o.otype, o.price, o.qty)
        o_fills.extend((f.taker_oid, f.maker_oid, f.price_q4, f.quantity) for f in r.fills)
    d = [(f.taker_oid, f.maker_oid, f.price_q4, f.quantity) for f in d_fills]
    emit(1, "smoke_replay_fill_parity", float(d == o_fills), "bool",
         {"fills": len(d)})


# -- config 2: Poisson LIMIT-only flow ---------------------------------------

def config2_poisson(full: bool):
    s = 64
    cfg = EngineConfig(num_symbols=s, capacity=64, batch=32 if full else 16,
                       max_fills=1 << 15)
    rng = np.random.default_rng(0)
    streams = []
    for w in range(2):
        # Poisson arrivals across symbols; LIMIT-only around a depth-10 ladder.
        n = 4 * s * cfg.batch
        syms = rng.poisson(lam=s / 2, size=n) % s
        stream = []
        for i, sym in enumerate(syms):
            side = BUY if rng.random() < 0.5 else SELL
            level = int(rng.integers(0, 10))
            price = 10_000 + (level if side == SELL else -level)
            stream.append(HostOrder(sym=int(sym), op=OP_SUBMIT, side=side,
                                    otype=LIMIT, price=price,
                                    qty=int(rng.integers(1, 100)),
                                    oid=w * n + i + 1))
        streams.append(stream)
    rate, lat_us = measure_device_throughput(cfg, streams)
    emit(2, "poisson_limit_throughput", rate, "orders/sec",
         {"mean_dispatch_latency_us": round(lat_us, 1), "symbols": s})


# -- config 3: L3-style replay (bench.py's configuration) --------------------

def config3_l3(full: bool):
    s = 4096 if full else 512
    cfg = EngineConfig(num_symbols=s, capacity=128, batch=32, max_fills=1 << 17)
    streams = [
        random_order_stream(s, 4 * s * cfg.batch, seed=w, cancel_p=0.10,
                            market_p=0.15, price_base=9_950, price_levels=100,
                            price_step=1, qty_max=100)
        for w in range(2)
    ]
    rate, lat_us = measure_device_throughput(cfg, streams)
    emit(3, "l3_replay_throughput", rate, "orders/sec",
         {"mean_dispatch_latency_us": round(lat_us, 1), "symbols": s})


# -- config 4: gRPC fan-in through the full server stack ---------------------

def config4_grpc(full: bool):
    import tempfile
    import threading

    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    clients = 64 if full else 16
    per_client = 200 if full else 50
    cfg = EngineConfig(num_symbols=64, capacity=64, batch=16, max_fills=1 << 15)
    db = tempfile.mkdtemp() + "/bench.db"
    server, port, parts = build_server("127.0.0.1:0", db, cfg, window_ms=2.0, log=False)
    server.start()
    addr = f"127.0.0.1:{port}"

    # Warm the jit before timing.
    ch = grpc.insecure_channel(addr)
    MatchingEngineStub(ch).SubmitOrder(pb2.OrderRequest(
        client_id="warm", symbol="S0", order_type=pb2.LIMIT, side=pb2.BUY,
        price=1, scale=0, quantity=1), timeout=60)

    lat_all: list[list[float]] = [[] for _ in range(clients)]

    def worker(w: int):
        chan = grpc.insecure_channel(addr)
        stub = MatchingEngineStub(chan)
        rng = np.random.default_rng(w)
        for i in range(per_client):
            side = pb2.BUY if rng.random() < 0.5 else pb2.SELL
            req = pb2.OrderRequest(
                client_id=f"c{w}", symbol=f"S{int(rng.integers(0, 64))}",
                order_type=pb2.LIMIT, side=side,
                price=int(10_000 + rng.integers(-20, 20)), scale=4,
                quantity=int(rng.integers(1, 50)))
            t0 = time.perf_counter()
            stub.SubmitOrder(req, timeout=30)
            lat_all[w].append(time.perf_counter() - t0)
        chan.close()

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    ch.close()
    shutdown(server, parts)

    lats = np.array(sorted(x for per in lat_all for x in per))
    emit(4, "grpc_end_to_end_throughput", clients * per_client / dt, "orders/sec",
         {"clients": clients,
          "p50_ms": round(float(lats[len(lats) // 2] * 1e3), 2),
          "p99_ms": round(float(lats[int(len(lats) * 0.99)] * 1e3), 2)})


def config4_native_gateway(full: bool):
    """Config 4 through the C++ serving edge, driven by the native
    pipelined load generator (me_client bench) — a GIL-free client, so the
    figure measures the server, not the loadgen. Emits one line per edge
    (native gateway, then grpcio for the same-process comparison)."""
    import subprocess
    import tempfile

    from matching_engine_tpu import native as me_native
    from matching_engine_tpu.server.main import build_server, shutdown

    cli = me_native.client_binary()
    if cli is None or not me_native.gateway_available():
        emit(4, "native_edge_skipped", 0.0, "bool",
             {"reason": "native gateway/client not built"})
        return
    clients = 32 if full else 8
    per_client = 2000 if full else 250
    inflight = 8
    # 128 symbol slots / 64 per edge under a disjoint prefix: the second
    # edge must measure against fresh books, not the first edge's resting
    # depth (same fix as scripts/tpu_e2e_r4.sh).
    cfg = EngineConfig(num_symbols=128, capacity=256, batch=16,
                       max_fills=1 << 15)
    db = tempfile.mkdtemp() + "/bench_native.db"
    server, port, parts = build_server(
        "127.0.0.1:0", db, cfg, window_ms=2.0, log=False,
        gateway_addr="127.0.0.1:0",
    )
    server.start()
    try:
        for edge, eport, prefix in (
                ("native_gateway", parts["gateway_port"], "N"),
                ("grpcio", port, "G")):
            try:
                out = subprocess.run(
                    [cli, "bench", f"127.0.0.1:{eport}", str(clients),
                     str(per_client), "64", str(inflight), prefix],
                    capture_output=True, text=True, timeout=900,
                )
            except subprocess.TimeoutExpired:
                emit(4, f"e2e_{edge}_failed", 0.0, "bool",
                     {"reason": "bench client timed out (900s)"})
                continue
            try:
                row = json.loads(out.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                emit(4, f"e2e_{edge}_failed", 0.0, "bool",
                     {"stderr": out.stderr[-200:]})
                continue
            # A run with dropped connections is NOT a clean figure: surface
            # the error count and the client's exit code alongside it.
            emit(4, f"e2e_{edge}", row["value"], "orders/sec",
                 {"clients": clients, "per_client": per_client,
                  "inflight": inflight, "p50_ms": row["p50_ms"],
                  "p99_ms": row["p99_ms"], "ok": row["ok"],
                  "rejected": row["rejected"],
                  "transport_errors": row.get("transport_errors", 0),
                  "degraded": out.returncode != 0})
    finally:
        shutdown(server, parts)


# -- config 5: agent-based market sim ----------------------------------------

def config5_sim(full: bool):
    from matching_engine_tpu.sim import SimConfig, run_sim

    s = 4096 if full else 256
    scfg = SimConfig(agents=256 if full else 32, refresh=8, markets=4)
    # Capacity must hold every agent's bid+ask per side, or the books
    # saturate and the sim measures a mostly-rejecting engine.
    cfg = EngineConfig(num_symbols=s, capacity=512 if full else 64,
                       batch=scfg.batch_for(), max_fills=1 << 17)
    steps = 50
    # Warmup: same static (cfg, scfg, steps) hits the module-level jit cache.
    _, _, stats, _ = run_sim(cfg, scfg, steps=steps, seed=0)
    jax.block_until_ready(stats)
    t0 = time.perf_counter()
    book, state, stats, _ = run_sim(cfg, scfg, steps=steps, seed=1)
    jax.block_until_ready(stats)
    dt = time.perf_counter() - t0
    # Count real (non-padding) ops, same convention as configs 2/3.
    ops = int(np.sum(np.asarray(stats.real_ops)))
    emit(5, "agent_sim_throughput", ops / dt, "orders/sec",
         {"symbols": s, "agents": scfg.agents,
          "traded_volume": int(np.sum(np.asarray(stats.volume)))})


def config6_auction(full: bool, config_id: int = 6, kernel: str = "matrix",
                    cap: int = 128, s_full: int = 4096, s_small: int = 512,
                    metric: str = "auction_uncross_throughput"):
    """Call-auction uncross throughput (engine/auction.py): every book
    pre-filled CROSSED to full depth (the worst-case pre-open state), one
    device step clears all of them at per-symbol clearing prices. K
    auctions are timed pipelined (fresh books placed per iteration, one
    sync at the end); fills stay on device during timing.

    Config 7 reuses this harness at venue depth (sorted kernel, capacity
    2048, wide-limb exact volumes — engine/auction_sorted.py): fewer
    symbols because the bilateral-record count scales with S * 2*cap and
    must fit the [max_fills] log."""
    from matching_engine_tpu.engine.auction import auction_step, decode_auction

    s = s_full if full else s_small
    # Bilateral records bound: <= S * (2*cap - 1); size the log to fit.
    cfg = EngineConfig(num_symbols=s, capacity=cap, batch=32,
                       max_fills=1 << 20, kernel=kernel)
    rng = np.random.default_rng(0)

    def host_book():
        shape = (s, cap)
        return {
            "bid_price": rng.integers(9_990, 10_051, shape, dtype=np.int32),
            "bid_qty": rng.integers(1, 100, shape, dtype=np.int32),
            "bid_oid": np.arange(1, s * cap + 1, dtype=np.int32).reshape(shape),
            "bid_seq": np.tile(np.arange(cap, dtype=np.int32), (s, 1)),
            "bid_owner": np.zeros(shape, dtype=np.int32),
            "ask_price": rng.integers(9_950, 10_011, shape, dtype=np.int32),
            "ask_qty": rng.integers(1, 100, shape, dtype=np.int32),
            "ask_oid": np.arange(s * cap + 1, 2 * s * cap + 1,
                                 dtype=np.int32).reshape(shape),
            "ask_seq": np.tile(np.arange(cap, dtype=np.int32), (s, 1)),
            "ask_owner": np.zeros(shape, dtype=np.int32),
            "next_seq": np.full((s,), cap, dtype=np.int32),
        }

    from matching_engine_tpu.engine.book import BookBatch

    mask = np.ones((s,), dtype=bool)
    books = [BookBatch(**{k: jax.device_put(v) for k, v in host_book().items()})
             for _ in range(4)]
    # Warm compile.
    _, out = auction_step(cfg, books[0], mask)
    jax.block_until_ready(out.small)

    k = 3
    t0 = time.perf_counter()
    outs = [auction_step(cfg, books[1 + i], mask)[1] for i in range(k)]
    jax.block_until_ready([o.small for o in outs])
    dt = time.perf_counter() - t0

    dec, fills = decode_auction(cfg, outs[-1])
    executed = int(np.sum(dec.executed))
    crossed = int(np.sum(dec.executed > 0))
    assert not dec.aborted
    emit(config_id, metric, k * s / dt, "symbols/sec",
         {"symbols": s, "capacity": cap, "kernel": kernel,
          "uncross_ms": round(dt / k * 1e3, 2),
          "symbols_crossed": crossed, "executed_qty": executed,
          "records": dec.fill_count})


def run_one(config: int, full: bool) -> None:
    if config == 1:
        config1_parity()
    elif config == 2:
        config2_poisson(full)
    elif config == 3:
        config3_l3(full)
    elif config == 4:
        config4_grpc(full)
        config4_native_gateway(full)
    elif config == 6:
        config6_auction(full)
    elif config == 7:
        config6_auction(full, config_id=7, kernel="sorted", cap=2048,
                        s_full=64, s_small=16,
                        metric="auction_uncross_venue_depth")
    elif config == 5:
        config5_sim(full)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--full", action="store_true", help="north-star scale")
    p.add_argument("--configs", default="1,2,3,4,5,6,7")
    p.add_argument("--no-fork", action="store_true",
                   help="run all configs in THIS process (debug only)")
    args = p.parse_args()
    picked = sorted({int(c) for c in args.configs.split(",")})

    if args.no_fork or len(picked) == 1:
        for c in picked:
            run_one(c, args.full)
        return

    # One subprocess per config: a single device->host decode readback
    # (config 1's parity replay, config 4's serving decode) permanently
    # collapses the axon tunnel's async dispatch pipeline for the REST of
    # the process — measured ~1000x on the timed configs (85ms/step
    # in-suite vs 84.5us/step isolated, same code). Process isolation is
    # the only reliable reset.
    import subprocess

    rc = 0
    for c in picked:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--configs", str(c)]
        if args.full:
            cmd.append("--full")
        r = subprocess.run(cmd)
        if r.returncode != 0:
            print(json.dumps({"config": c, "metric": "config_failed",
                              "value": r.returncode, "unit": "rc",
                              "vs_baseline": None}), flush=True)
            rc = 1
    sys.exit(rc)


if __name__ == "__main__":
    main()
