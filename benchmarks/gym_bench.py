"""Many-venue gym throughput: agent-steps/s vs venue count (ISSUE 18).

Sweeps the venue axis of gym/env.py — V independent heterogeneous
markets (scenario programs cycle over venues; seeds differ per venue)
stepped in ONE jit'd lax.scan — and reports sustained venue-steps/s and
agent-steps/s per sweep point. Each point compiles its own program
(V is a shape), so compile time is reported separately and the timed
region is rollout-only, best-of --best-of repeats with the min..max
spread alongside (the JAX-LOB comparison convention, arXiv:2308.13289:
their headline is steps/s scaling vs parallel-env count on one device).

An agent-step is one agent population member observing one venue step:
  agent_steps/s = venues * steps * symbols * population / wall
where population = mm_agents + momentum + noise + takers (the per-symbol
agent head-count of the mix; mm_refresh re-quotes existing agents).

Usage: python benchmarks/gym_bench.py --json-out out.json
       [--venues 1,4,16,64,256,1024] [--steps 32] [--symbols 4]
       [--scenario auction_day,flash_crash,bursts,hot_symbols]
       [--kernel matrix] [--best-of 3] [--seed 0]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--venues", default="1,4,16,64,256,1024",
                   help="comma list of venue counts to sweep; each point "
                        "is its own jit program (V is a shape)")
    p.add_argument("--steps", type=int, default=32)
    p.add_argument("--symbols", type=int, default=4)
    p.add_argument("--scenario",
                   default="auction_day,flash_crash,bursts,hot_symbols",
                   help="scenario programs cycled over the venue axis — "
                        "the heterogeneity of the population (phase "
                        "programs, zipf skew, episode lengths differ "
                        "across venues)")
    p.add_argument("--kernel", choices=("matrix", "sorted", "levels"),
                   default="matrix")
    p.add_argument("--best-of", type=int, default=3,
                   help="timed rollout repeats per point; best is the "
                        "headline, min..max spread rides along")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json-out", required=True)
    args = p.parse_args()

    import jax
    import numpy as np

    cache_dir = os.environ.get(
        "ME_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    t0 = time.perf_counter()
    devices = jax.devices()
    platform = devices[0].platform
    backend_init_s = time.perf_counter() - t0

    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.gym import VenueGym
    from matching_engine_tpu.sim.scenarios import (
        default_mix,
        make_scenario,
        recording_capacity,
    )

    names = [n for n in args.scenario.split(",") if n]
    scens = [make_scenario(n) for n in names]
    mix = default_mix(names[0])
    population = mix.mm_agents + mix.momentum + mix.noise + mix.takers
    cap = max(recording_capacity(mix, n) for n in names)
    cfg = EngineConfig(num_symbols=args.symbols, capacity=cap,
                       batch=mix.batch_for(), max_fills=1 << 15,
                       kernel=args.kernel)

    sweep = []
    for v in [int(x) for x in args.venues.split(",") if x]:
        env = VenueGym.from_scenarios(cfg, mix, v, scens)
        state0, _ = env.reset([args.seed + i for i in range(v)])
        # First rollout pays compilation; timed repeats replay the same
        # initial state so every repeat measures identical work.
        tc = time.perf_counter()
        _, stats, _, _ = env.rollout(state0, args.steps)
        jax.block_until_ready(stats.fills)
        compile_s = time.perf_counter() - tc
        walls = []
        for _ in range(max(1, args.best_of)):
            tr = time.perf_counter()
            _, stats, _, _ = env.rollout(state0, args.steps)
            jax.block_until_ready(stats.fills)
            walls.append(time.perf_counter() - tr)
        best = min(walls)
        venue_steps = v * args.steps
        sweep.append({
            "venues": v,
            "steps": args.steps,
            "wall_s_best": round(best, 5),
            "wall_s_spread": [round(min(walls), 5), round(max(walls), 5)],
            "compile_s": round(compile_s, 2),
            "venue_steps_per_s": round(venue_steps / best, 1),
            "agent_steps_per_s": round(
                venue_steps * args.symbols * population / best, 1),
            "ops": int(np.asarray(stats.real_ops).sum()),
            "fills": int(np.asarray(stats.fills).sum()),
        })
        print(f"[gym_bench] V={v}: {sweep[-1]['venue_steps_per_s']:.0f} "
              f"venue-steps/s ({sweep[-1]['agent_steps_per_s']:.0f} "
              f"agent-steps/s), compile {compile_s:.1f}s",
              file=sys.stderr, flush=True)

    try:
        import subprocess
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        rev = "unknown"
    peak = max(sweep, key=lambda r: r["agent_steps_per_s"])
    out = {
        "metric": "gym_agent_steps_per_s",
        "value": peak["agent_steps_per_s"],
        "unit": "agent-steps/sec",
        "at_venues": peak["venues"],
        "platform": platform,
        "n_devices": len(devices),
        "symbols": args.symbols,
        "capacity": cap,
        "batch": mix.batch_for(),
        "kernel": args.kernel,
        "population_per_symbol": population,
        "scenarios": names,
        "best_of": args.best_of,
        "backend_init_s": round(backend_init_s, 1),
        "sweep": sweep,
        "git_rev": rev,
    }
    tmp = args.json_out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
    os.replace(tmp, args.json_out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
