"""Round-4 TPU capture list: every artifact VERDICT r3 asked for, as a
RESUMABLE prioritized step list. scripts/tpu_r4_watch.sh runs this on each
healthy tunnel probe; a step whose artifact already exists is skipped, so a
window that closes mid-list costs only the unfinished tail — the next
healthy window continues from there.

Steps (priority order — most valuable first when the window is short):
  headline        4k-symbol staged bench (also primes the jax compile
                  cache bench.py's driver-time staged attempt reuses)
  suite           full-scale configs 1,2,3,5,6 (incl. the pending
                  config-6 auction TPU row, VERDICT r3 next-step 5)
  batch64/128     batch-axis scaling rows (next-step 5)
  syms64/256/1024 symbol-count sweep (next-step 7; 4096 = headline)
  cap256/512/1024 capacity sweep at S=256 (next-step 4; cap128 row too,
                  so the curve is same-S end to end; the sorted-kernel
                  rows extend it to 4096 at the same S)
  runner_sweep    RPC-less EngineRunner inflight sweep (next-step 2)
  e2e_pi2/pi4     full-stack dual-edge serving at pipeline inflight 2/4
  l3flow          config-3b realistic flow + reject/depth stats (step 6)
  profile         kernel phase breakdown + roofline + device trace (3)

Exit codes: 0 = all steps done, 10 = some steps still missing (watcher
retries next window), 1 = unexpected driver error.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(REPO, "benchmarks", "results")
LOCK = os.path.join(RESULTS, ".capture.lock")
LOG = os.path.join(RESULTS, "r4_capture.log")
PY = sys.executable


def log(msg: str) -> None:
    line = f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def bench_child(out: str, *args: str) -> list[str]:
    return [PY, os.path.join(REPO, "benchmarks", "bench_child.py"),
            "--json-out", os.path.join(RESULTS, out), *args]


def suite(out: str, configs: str) -> dict:
    """run_all.py writes rows to stdout; capture to a .tmp then rename."""
    return {
        "cmd": [PY, os.path.join(REPO, "benchmarks", "run_all.py"),
                "--full", "--configs", configs],
        "stdout_to": os.path.join(RESULTS, out),
    }


STEPS: list[dict] = [
    {"name": "headline", "artifact": "tpu_r4_headline.json", "timeout": 1500,
     "cmd": bench_child("tpu_r4_headline.json", "--symbols", "4096",
                        "--capacity", "128", "--batch", "32",
                        "--stage-symbols", "512")},
    {"name": "suite_full", "artifact": "tpu_suite_full_r4.jsonl",
     "timeout": 1800, **suite("tpu_suite_full_r4.jsonl", "1,2,3,5,6")},
    {"name": "batch64", "artifact": "tpu_r4_batch64.json", "timeout": 900,
     "cmd": bench_child("tpu_r4_batch64.json", "--symbols", "4096",
                        "--capacity", "128", "--batch", "64")},
    {"name": "batch128", "artifact": "tpu_r4_batch128.json", "timeout": 900,
     "cmd": bench_child("tpu_r4_batch128.json", "--symbols", "4096",
                        "--capacity", "128", "--batch", "128")},
    {"name": "syms64", "artifact": "tpu_r4_syms64.json", "timeout": 600,
     "cmd": bench_child("tpu_r4_syms64.json", "--symbols", "64",
                        "--capacity", "128", "--batch", "32")},
    {"name": "syms256", "artifact": "tpu_r4_syms256.json", "timeout": 600,
     "cmd": bench_child("tpu_r4_syms256.json", "--symbols", "256",
                        "--capacity", "128", "--batch", "32")},
    {"name": "syms1024", "artifact": "tpu_r4_syms1024.json", "timeout": 900,
     "cmd": bench_child("tpu_r4_syms1024.json", "--symbols", "1024",
                        "--capacity", "128", "--batch", "32")},
    # Capacity curve at fixed S=256 (the [CAP, CAP] priority matrix is
    # O(CAP^2) work and O(S*CAP^2) intermediate — S=256*CAP=1024 peaks at
    # ~1GB of bool/int32 temps, well inside one v5e's HBM).
    {"name": "cap128", "artifact": "tpu_r4_cap128.json", "timeout": 600,
     "cmd": bench_child("tpu_r4_cap128.json", "--symbols", "256",
                        "--capacity", "128", "--batch", "32")},
    {"name": "cap256", "artifact": "tpu_r4_cap256.json", "timeout": 900,
     "cmd": bench_child("tpu_r4_cap256.json", "--symbols", "256",
                        "--capacity", "256", "--batch", "32")},
    {"name": "cap512", "artifact": "tpu_r4_cap512.json", "timeout": 900,
     "cmd": bench_child("tpu_r4_cap512.json", "--symbols", "256",
                        "--capacity", "512", "--batch", "32")},
    {"name": "cap1024", "artifact": "tpu_r4_cap1024.json", "timeout": 1200,
     "cmd": bench_child("tpu_r4_cap1024.json", "--symbols", "256",
                        "--capacity", "1024", "--batch", "32")},
    # Sorted-book kernel (engine/kernel_sorted.py, O(CAP) per order) at
    # the same sweep points — the head-to-head that decides which
    # formulation serves at which capacity (VERDICT r3 next-step 4).
    {"name": "cap128s", "artifact": "tpu_r4_cap128_sorted.json",
     "timeout": 900,
     "cmd": bench_child("tpu_r4_cap128_sorted.json", "--symbols", "256",
                        "--capacity", "128", "--batch", "32",
                        "--kernel", "sorted")},
    {"name": "cap512s", "artifact": "tpu_r4_cap512_sorted.json",
     "timeout": 900,
     "cmd": bench_child("tpu_r4_cap512_sorted.json", "--symbols", "256",
                        "--capacity", "512", "--batch", "32",
                        "--kernel", "sorted")},
    {"name": "cap1024s", "artifact": "tpu_r4_cap1024_sorted.json",
     "timeout": 1200,
     "cmd": bench_child("tpu_r4_cap1024_sorted.json", "--symbols", "256",
                        "--capacity", "1024", "--batch", "32",
                        "--kernel", "sorted")},
    {"name": "cap4096s", "artifact": "tpu_r4_cap4096_sorted.json",
     "timeout": 1200,
     "cmd": bench_child("tpu_r4_cap4096_sorted.json", "--symbols", "256",
                        "--capacity", "4096", "--batch", "32",
                        "--kernel", "sorted")},
    {"name": "headline_sorted", "artifact": "tpu_r4_headline_sorted.json",
     "timeout": 1200,
     "cmd": bench_child("tpu_r4_headline_sorted.json", "--symbols", "4096",
                        "--capacity", "128", "--batch", "32",
                        "--kernel", "sorted", "--stage-symbols", "512")},
    # Serving-stack rows (VERDICT r3 next-step 2): the RPC-less
    # EngineRunner inflight sweep, then full-stack e2e at pipeline
    # inflight 2 and 4 (r3's artifacts measured the old single-slot
    # pipeline = inflight 1).
    {"name": "runner_sweep", "artifact": "tpu_r4_runner.json",
     "timeout": 1200,
     "cmd": [PY, os.path.join(REPO, "benchmarks", "runner_bench.py"),
             "--json-out", os.path.join(RESULTS, "tpu_r4_runner.json"),
             "--inflight", "1,2,4,8"]},
    {"name": "e2e_pi2", "artifact": "tpu_e2e_r4_native_pi2.json",
     "timeout": 1500,
     "cmd": ["bash", os.path.join(REPO, "scripts", "tpu_e2e_r4.sh"), "2"]},
    {"name": "e2e_pi4", "artifact": "tpu_e2e_r4_native_pi4.json",
     "timeout": 1500,
     "cmd": ["bash", os.path.join(REPO, "scripts", "tpu_e2e_r4.sh"), "4"]},
    # Config-3b: realistic L3 flow (power-law/bursts/deep books) with
    # reject + overflow + depth statistics (VERDICT r3 next-step 6).
    {"name": "l3flow", "artifact": "tpu_r4_l3flow.json", "timeout": 1500,
     "cmd": [PY, os.path.join(REPO, "benchmarks", "flow_bench.py"),
             "--json-out", os.path.join(RESULTS, "tpu_r4_l3flow.json")]},
    # Kernel efficiency story: phase breakdown + cost-analysis roofline +
    # device trace (VERDICT r3 next-step 3).
    {"name": "profile", "artifact": "tpu_r4_profile.json", "timeout": 1500,
     "cmd": [PY, os.path.join(REPO, "benchmarks", "profile_kernel.py"),
             "--json-out", os.path.join(RESULTS, "tpu_r4_profile.json"),
             "--trace-dir", os.path.join(RESULTS, "profile_r4")]},
    # Round-5 additions: the efficiency story for the formulation that
    # WINS the headline (sorted, 2.2B/s at 4k symbols), and its
    # venue-depth point (S=256 is the sweep's fixed S; CAP=8192 is the
    # max the sorted kernel supports).
    {"name": "profile_sorted", "artifact": "tpu_r5_profile_sorted.json",
     "timeout": 1500,
     "cmd": [PY, os.path.join(REPO, "benchmarks", "profile_kernel.py"),
             "--kernel", "sorted",
             "--json-out", os.path.join(RESULTS,
                                        "tpu_r5_profile_sorted.json"),
             "--trace-dir", os.path.join(RESULTS, "profile_r5_sorted")]},
    {"name": "cap8192s", "artifact": "tpu_r5_cap8192_sorted.json",
     "timeout": 1500,
     "cmd": bench_child("tpu_r5_cap8192_sorted.json", "--symbols", "256",
                        "--capacity", "8192", "--batch", "32",
                        "--kernel", "sorted")},
    # grpcio edge re-measure after the rpc-worker fix (VERDICT r4 weak
    # #3): the 306/s deficit fit thread-pool starvation exactly (256
    # concurrent client requests / 32 workers x ~100ms batched dispatch
    # = ~8-deep queueing, p50 ~800ms); 256 workers removes the cap.
    {"name": "e2e_pi2_w256", "artifact": "tpu_e2e_r4_native_pi2_w256.json",
     "timeout": 1500,
     "cmd": ["bash", os.path.join(REPO, "scripts", "tpu_e2e_r4.sh"), "2"],
     "env": {"TPU_E2E_SUFFIX": "_w256", "TPU_E2E_RPC_WORKERS": "256"}},
    # Venue-depth auction on hardware (config 7: sorted kernel, cap 2048).
    {"name": "suite7", "artifact": "tpu_suite7_r5.jsonl", "timeout": 900,
     **suite("tpu_suite7_r5.jsonl", "7")},
    # Saturation ceiling: the r4 runner sweep fixed 64-op dispatches; the
    # serving ceiling under load is a function of dispatch SIZE (the
    # window packs up to symbols*batch ops per drain) — sweep it.
    {"name": "runner_sat", "artifact": "tpu_r5_runner_sat.json",
     "timeout": 1200,
     "cmd": [PY, os.path.join(REPO, "benchmarks", "runner_bench.py"),
             "--json-out", os.path.join(RESULTS, "tpu_r5_runner_sat.json"),
             "--batch-ops", "64,256,1024", "--inflight", "4"]},
    # Full-stack serving at the saturation sweet spot the runner_sat sweep
    # found (~256-op dispatches): the pi2/pi4 rows above were CLIENT-
    # concurrency-bound (32 clients x inflight 8 = 256 outstanding ~=
    # 2.4k/s at ~105ms RTT, Little's law) — quadruple the outstanding
    # orders so the server, not the loadgen, sets the ceiling.
    {"name": "e2e_sat", "artifact": "tpu_e2e_r4_native_pi4_sat.json",
     "timeout": 1500,
     "cmd": ["bash", os.path.join(REPO, "scripts", "tpu_e2e_r4.sh"), "4"],
     "env": {"TPU_E2E_SUFFIX": "_sat", "TPU_E2E_CLIENTS": "64",
             "TPU_E2E_INFLIGHT": "16", "TPU_E2E_PER_CLIENT": "4000"}},
    # Lesson from e2e_sat: throughput was WINDOW-bound, not concurrency-
    # bound — the 2ms default window packs ~5 ops/dispatch at 2.4k/s,
    # nowhere near the 256-op saturation sweet spot, and every further
    # client just queues (p50 425ms) or hits book-full rejects. Widen the
    # window toward the sweep's 24ms optimum so dispatches pack properly.
    {"name": "e2e_w25", "artifact": "tpu_e2e_r4_native_pi4_w25.json",
     "timeout": 1500,
     "cmd": ["bash", os.path.join(REPO, "scripts", "tpu_e2e_r4.sh"), "4"],
     "env": {"TPU_E2E_SUFFIX": "_w25", "TPU_E2E_WINDOW_MS": "25",
             "TPU_E2E_CLIENTS": "64", "TPU_E2E_INFLIGHT": "16",
             "TPU_E2E_PER_CLIENT": "2000"}},
    # Second window point: w25 reached 3.5k/s at ~88 ops/dispatch, still
    # under the 256-op sweet spot — probe the knee from the other side.
    # l3flow re-capture under the ioc-fok flow mix (flow.py tif_p=0.05,
    # aggressively priced) — rows labeled without "+ioc-fok" predate it.
    {"name": "l3flow_v2", "artifact": "tpu_r5_l3flow_iocfok.json",
     "timeout": 2400,
     "cmd": [PY, os.path.join(REPO, "benchmarks", "flow_bench.py"),
             "--json-out", os.path.join(RESULTS, "tpu_r5_l3flow_iocfok.json")]},
    {"name": "e2e_w60", "artifact": "tpu_e2e_r4_native_pi4_w60.json",
     "timeout": 1500,
     "cmd": ["bash", os.path.join(REPO, "scripts", "tpu_e2e_r4.sh"), "4"],
     "env": {"TPU_E2E_SUFFIX": "_w60", "TPU_E2E_WINDOW_MS": "60",
             "TPU_E2E_CLIENTS": "64", "TPU_E2E_INFLIGHT": "16",
             "TPU_E2E_PER_CLIENT": "2000"}},
]


# Round-5 reorder (VERDICT r4 next-step 1): value density first, so a
# short healthy window lands the DECISION data (headline figure, the
# sorted-vs-matrix capacity head-to-head, the efficiency profile, the
# runner sweep) before the bulk sweeps. The list stays resumable either
# way; this only changes which artifacts a truncated window produces.
_R5_ORDER = [
    "headline", "cap512", "cap512s", "profile", "runner_sweep",
    "headline_sorted", "cap128", "cap128s", "cap1024", "cap1024s",
    "cap4096s", "cap256", "e2e_pi2", "e2e_pi4", "suite_full",
    "batch64", "batch128", "syms64", "syms256", "syms1024", "l3flow",
    "profile_sorted", "cap8192s", "e2e_pi2_w256", "suite7", "runner_sat",
    "e2e_sat", "e2e_w25", "e2e_w60", "l3flow_v2",
]
_RANK = {n: i for i, n in enumerate(_R5_ORDER)}
STEPS.sort(key=lambda s: _RANK.get(s["name"], len(_R5_ORDER)))


def _run_bounded(cmd: list[str], timeout: float, stdout_f,
                 env: dict | None = None) -> tuple:
    """subprocess with a HARD kill deadline: SIGKILL on timeout, then at
    most 10s to reap — a child wedged in D-state inside the axon tunnel
    is abandoned, never waited on unboundedly (subprocess.run's
    post-timeout cleanup blocks forever on exactly that; the watcher must
    keep looping). Kills the whole process GROUP: the e2e steps are bash
    wrappers whose backgrounded server would otherwise survive a wrapper
    SIGKILL holding the device and its ports (the EXIT trap never fires
    on SIGKILL). Returns (rc | None on timeout, stderr_tail)."""
    import signal

    proc = subprocess.Popen(cmd, cwd=REPO, stdout=stdout_f,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True,
                            env={**os.environ, **env} if env else None)
    try:
        _, stderr = proc.communicate(timeout=timeout)
        return proc.returncode, (stderr or "")
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            proc.kill()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass  # unkillable: abandon
        return None, ""


def run_step(step: dict) -> bool:
    art = os.path.join(RESULTS, step["artifact"])
    if os.path.exists(art):
        return True
    log(f"step {step['name']}: running (timeout {step['timeout']}s)")
    stdout_to = step.get("stdout_to")
    t0 = time.monotonic()
    if stdout_to:
        with open(stdout_to + ".tmp", "w") as out_f:
            rc, stderr = _run_bounded(step["cmd"], step["timeout"], out_f,
                                      env=step.get("env"))
    else:
        rc, stderr = _run_bounded(step["cmd"], step["timeout"],
                                  subprocess.DEVNULL, env=step.get("env"))
    dt = time.monotonic() - t0
    if rc is None:
        log(f"step {step['name']}: TIMEOUT after {step['timeout']}s")
        # bench_child's staged/atomic writes mean a partial artifact is
        # still a valid salvage — keep it if it parses, else remove.
        _keep_if_valid(art)
        if stdout_to:
            _promote_suite_tmp(stdout_to)
        return os.path.exists(art)
    if rc != 0:
        tail = stderr.strip().splitlines()[-3:]
        log(f"step {step['name']}: rc={rc} after {dt:.0f}s: "
            f"{' | '.join(tail)[-300:]}")
        _keep_if_valid(art)
        if stdout_to:
            _promote_suite_tmp(stdout_to)
        return os.path.exists(art)
    if stdout_to:
        os.replace(stdout_to + ".tmp", stdout_to)
    log(f"step {step['name']}: ok in {dt:.0f}s")
    return True


def _keep_if_valid(art: str) -> None:
    try:
        with open(art) as f:
            row = json.load(f)
    except (OSError, ValueError):
        try:
            os.unlink(art)
        except OSError:
            pass
        return
    if isinstance(row, dict) and row.get("stage") == "small":
        # A staged child that died before the FULL config only wrote its
        # small-stage row — real hardware evidence, but it must not
        # satisfy the full-config step (the step would never retry).
        # Park it under a distinct name; the step stays missing.
        side = art[:-len(".json")] + ".small.json"
        os.replace(art, side)
        log(f"  small-stage salvage parked as {os.path.basename(side)}; "
            f"step will retry")
        return
    log(f"  salvaged valid partial artifact {os.path.basename(art)}")


def _promote_suite_tmp(path: str) -> None:
    """A suite interrupted mid-run still emitted complete JSON lines for
    the configs it finished — keep them (each row is independently valid
    and carries its own config id + git_rev). Only rows that parse are
    promoted; an empty salvage leaves no artifact so the step retries."""
    tmp = path + ".tmp"
    rows = []
    try:
        with open(tmp) as f:
            for ln in f:
                try:
                    json.loads(ln)
                    rows.append(ln if ln.endswith("\n") else ln + "\n")
                except ValueError:
                    pass
    except OSError:
        return
    if rows:
        # Salvage to .partial — the step stays "missing" and retries whole
        # next window (config rows are cheap to re-measure; a complete
        # suite file is worth more than avoiding the re-run), but the
        # evidence from this window is preserved either way.
        with open(path + ".partial", "a") as f:
            f.writelines(rows)
        log(f"  salvaged {len(rows)} suite rows into "
            f"{os.path.basename(path)}.partial")
    try:
        os.unlink(tmp)
    except OSError:
        pass


def probe_healthy(timeout_s: float = 45) -> bool:
    """Cheap backend probe (healthy init is sub-second; wedged hangs)."""
    rc, _ = _run_bounded(
        [PY, "-c", "import jax; assert jax.devices()"], timeout_s,
        subprocess.DEVNULL)
    return rc == 0


def main() -> int:
    os.makedirs(RESULTS, exist_ok=True)
    # Single-instance lock: a manual run racing the watcher's run doubles
    # up the same TPU bench and can push a step past its timeout (observed
    # 12:42Z 07-31 — two concurrent l3flow benches both timed out). flock
    # releases on process exit, crash included.
    import fcntl

    lock_f = open(LOCK, "w")
    try:
        fcntl.flock(lock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        log("another capture run holds the lock; exiting")
        return 10
    missing = [s for s in STEPS if not os.path.exists(
        os.path.join(RESULTS, s["artifact"]))]
    if not missing:
        log("all steps already captured")
        return 0
    log(f"{len(missing)} steps to capture: {[s['name'] for s in missing]}")
    for step in STEPS:
        if not run_step(step) and not probe_healthy():
            # The step burned its full timeout with nothing to show and
            # the tunnel is wedged — grinding through every remaining
            # step's timeout would waste HOURS of window time; bail and
            # let the watcher retry on the next healthy probe.
            log("tunnel unhealthy after step failure; bailing until "
                "the next healthy window")
            return 10
    still = [s["name"] for s in STEPS if not os.path.exists(
        os.path.join(RESULTS, s["artifact"]))]
    if still:
        log(f"incomplete, remaining: {still}")
        return 10
    log("capture list complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
