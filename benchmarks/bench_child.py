"""Benchmark child process: one device-throughput measurement, JSON to a file.

Run by bench.py (the orchestrator) in a subprocess so that a wedged TPU
tunnel — the failure mode that ate round 1's bench (BENCH_r01.json rc=1, and
a judge rerun that hung >9 minutes) — can be bounded by a parent-side
timeout and retried or downgraded to CPU, instead of hanging the driver.

Everything that can touch the backend lives here: backend init, compile,
the timed windows. The parent never imports jax.

Method: utils/measure.py — host-side op counting, one warm pass, median of
post-warm fully-synced windows (see docs/BENCH_METHOD.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--symbols", type=int, default=4096)
    p.add_argument("--capacity", type=int, default=128)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--windows", type=int, default=5)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--kernel", choices=("matrix", "sorted"),
                   default="matrix",
                   help="match formulation: the production [CAP,CAP] "
                        "priority matrix, or the O(CAP) sorted-book "
                        "prototype (engine/kernel_sorted.py) — the "
                        "capacity sweep compares them")
    p.add_argument("--stage-symbols", type=int, default=0,
                   help="staged mode: measure this (small) symbol count "
                        "first and WRITE that result before the full "
                        "config runs — a parent that must kill this child "
                        "mid-run salvages a real-TPU figure instead of "
                        "falling back to CPU (VERDICT r3 next-step 1)")
    p.add_argument("--json-out", required=True)
    args = p.parse_args()

    import jax

    # Persistent compile cache: the driver's end-of-round bench re-runs the
    # same (config, jaxlib) compile this process already paid for. A cache
    # hit also shrinks the window in which a parent-side timeout could kill
    # us mid-compile (which is what wedges the axon tunnel).
    cache_dir = os.environ.get(
        "ME_JAX_CACHE", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache")
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the knobs: run uncached

    t0 = time.perf_counter()
    devices = jax.devices()  # backend init — the step that hangs when wedged
    platform = devices[0].platform
    backend_init_s = time.perf_counter() - t0

    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.utils.measure import (
        headline_streams,
        measure_device_throughput,
        result_row,
    )

    try:
        import subprocess
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        rev = "unknown"

    def run_config(symbols: int, capacity: int, batch: int,
                   windows: int, iters: int) -> dict:
        cfg = EngineConfig(
            num_symbols=symbols, capacity=capacity, batch=batch,
            max_fills=1 << 17, kernel=args.kernel,
        )
        value, mean_lat_us = measure_device_throughput(
            cfg, headline_streams(cfg), windows=windows, iters=iters,
        )
        return result_row(cfg, value, mean_lat_us, platform=platform,
                          n_devices=len(devices),
                          backend_init_s=backend_init_s, git_rev=rev)

    small = None
    if args.stage_symbols and args.stage_symbols < args.symbols:
        small = run_config(args.stage_symbols, args.capacity, args.batch,
                           windows=3, iters=8)
        small["stage"] = "small"
        tmp = args.json_out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(small, f)
        os.replace(tmp, args.json_out)

    result = run_config(args.symbols, args.capacity, args.batch,
                        args.windows, args.iters)
    if small is not None:
        result["stage"] = "full"
        result["stage_small_value"] = round(small["value"], 1)
    # Atomic replace: a parent salvaging on timeout must never read a
    # half-written file (it would discard the staged small result too).
    tmp = args.json_out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f)
    os.replace(tmp, args.json_out)


if __name__ == "__main__":
    main()
