"""Benchmark child process: one device-throughput measurement, JSON to a file.

Run by bench.py (the orchestrator) in a subprocess so that a wedged TPU
tunnel — the failure mode that ate round 1's bench (BENCH_r01.json rc=1, and
a judge rerun that hung >9 minutes) — can be bounded by a parent-side
timeout and retried or downgraded to CPU, instead of hanging the driver.

Everything that can touch the backend lives here: backend init, compile,
the timed windows. The parent never imports jax.

Method: utils/measure.py — host-side op counting, one warm pass, median of
post-warm fully-synced windows (see docs/BENCH_METHOD.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--symbols", type=int, default=4096)
    p.add_argument("--capacity", type=int, default=128)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--windows", type=int, default=5)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--json-out", required=True)
    args = p.parse_args()

    import jax

    # Persistent compile cache: the driver's end-of-round bench re-runs the
    # same (config, jaxlib) compile this process already paid for. A cache
    # hit also shrinks the window in which a parent-side timeout could kill
    # us mid-compile (which is what wedges the axon tunnel).
    cache_dir = os.environ.get(
        "ME_JAX_CACHE", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache")
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the knobs: run uncached

    t0 = time.perf_counter()
    devices = jax.devices()  # backend init — the step that hangs when wedged
    platform = devices[0].platform
    backend_init_s = time.perf_counter() - t0

    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.engine.harness import random_order_stream
    from matching_engine_tpu.utils.measure import measure_device_throughput

    cfg = EngineConfig(
        num_symbols=args.symbols, capacity=args.capacity, batch=args.batch,
        max_fills=1 << 17,
    )
    streams = [
        random_order_stream(
            cfg.num_symbols, 4 * cfg.num_symbols * cfg.batch, seed=w,
            cancel_p=0.10, market_p=0.15, price_base=9_950, price_levels=100,
            price_step=1, qty_max=100,
        )
        for w in range(4)
    ]
    value, mean_lat_us = measure_device_throughput(
        cfg, streams, windows=args.windows, iters=args.iters
    )
    try:
        import subprocess
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        rev = "unknown"
    result = {
        "value": value,
        "platform": platform,
        "n_devices": len(devices),
        "symbols": args.symbols,
        "capacity": args.capacity,
        "batch": args.batch,
        "backend_init_s": round(backend_init_s, 1),
        "mean_dispatch_latency_us": round(mean_lat_us, 1),
        "git_rev": rev,
    }
    with open(args.json_out, "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
