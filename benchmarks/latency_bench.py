"""Tail-latency bench: OPEN-LOOP (fixed-rate) load against the serving
stack, reporting per-stage and end-to-end p50/p99/p99.9.

Every throughput artifact in this repo drives the pipeline CLOSED-loop
(issue, wait, issue) — which measures capacity but silently hides the
tail: a stalled dispatch pauses the load generator too, so the stall is
charged to one op instead of the dozens that WOULD have arrived during
it (coordinated omission; docs/BENCH_METHOD.md §tail-latency). This
bench does what a latency SLO needs instead:

1. measure peak throughput closed-loop (same submission machinery);
2. replay open-loop at a FRACTION of that peak: ops are issued on a
   fixed schedule regardless of completions, and each op's latency is
   measured from its SCHEDULED time — a stall bills every op it delays;
3. report exact (non-bucketed) end-to-end p50/p99/p99.9 from the raw
   recorder, plus the registry's per-stage histogram quantiles, sweeping
   the tail levers (--busy-poll-us) on/off, best-of --repeats with the
   spread.

Two drive modes:
- in-proc (default): the dispatch pipeline without an RPC edge — ops
  enter dispatcher.submit exactly as the grpcio edge would push them
  (per-op slot/oid/handle assignment in the timed path). Isolates the
  serving stack's own tail from transport.
- --addr HOST:PORT: open-loop SubmitOrder RPCs against a LIVE server
  (scripts/soak.sh's latency round) — the client-felt tail including
  the gRPC edge; --scrape URL pulls the server's /metrics after the run
  so the artifact carries the server-side stage quantiles too.

Usage:
  python benchmarks/latency_bench.py --json-out benchmarks/results/cpu_latency_r9.json \
      [--load-fractions 0.5,0.8] [--levers off,on] [--busy-poll-us 100] \
      [--repeats 3] [--duration-s 4] [--mode python]
  python benchmarks/latency_bench.py --addr 127.0.0.1:50051 \
      --load-fractions 0.5 --scrape http://127.0.0.1:9100/metrics --json-out out.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The per-stage quantiles each row carries (utils/obs.py stage ledger +
# the per-dispatch end-to-end histogram the trace sampler thresholds on).
_STAGES = (
    "stage_queue_wait_us", "stage_lane_build_us", "stage_device_dispatch_us",
    "stage_completion_decode_us", "stage_stream_publish_us",
    "dispatch_e2e_us", "dispatch_us",
)


def _pctls(lats_s: list[float]) -> dict:
    import numpy as np

    if not lats_s:
        # A degraded target can pass the peak-phase gates with a near-
        # zero peak, making n == 0 here; fail with the diagnostic, not
        # an IndexError traceback.
        print("[latency_bench] FATAL: zero completions in the open-loop "
              "window (measured peak too low?)", file=sys.stderr)
        raise SystemExit(1)
    a = np.asarray(sorted(lats_s))
    return {
        "p50_ms": round(float(a[int(len(a) * 0.50)]) * 1e3, 3),
        "p99_ms": round(float(a[min(len(a) - 1, int(len(a) * 0.99))]) * 1e3, 3),
        "p999_ms": round(
            float(a[min(len(a) - 1, int(len(a) * 0.999))]) * 1e3, 3),
    }


def _stage_quantiles(metrics) -> dict:
    out = {}
    for name in _STAGES:
        row = {}
        for q, label in ((0.5, "p50"), (0.99, "p99"), (0.999, "p999")):
            v = metrics.percentile(name, q)
            if v is not None:
                row[label] = round(v, 1)
        if row:
            out[name] = row
    return out


def _failed(fut) -> bool:
    """Did this completion actually succeed? Covers the three future
    flavors the bench drives: grpc (response has .success), native lanes
    (LaneOutcome.ok), python pipeline (OpOutcome — no flag; a raised
    future is the failure signal)."""
    if fut is None:
        return False
    try:
        if fut.exception(timeout=0) is not None:
            return True
        res = fut.result(timeout=0)
    except Exception:  # noqa: BLE001
        return True
    oks = getattr(res, "ok", None)
    if oks is not None and not isinstance(oks, bool):
        # OrderBatchResponse: `ok` is the positional status array — any
        # rejected position fails the sample (per-op reject counting; a
        # reject completes fast and must not pose as a quick success).
        if getattr(res, "success", True) is False:
            return True
        try:
            return not all(oks)
        except TypeError:
            return False
    ok = getattr(res, "success", None)
    if ok is None:
        ok = oks if oks is not None else True
    if not ok:
        return True
    # OpOutcome (python pipeline) has no flag; a non-empty error string
    # is its reject signal ("book side at capacity", ...).
    return bool(getattr(res, "error", ""))


def _open_loop(submit_one, rate_ops_s: float, duration_s: float,
               failed=_failed):
    """Issue ops on a fixed schedule for `duration_s`, latency measured
    from each op's SCHEDULED time (the open-loop/coordinated-omission
    contract: a pipeline stall bills every op it delays, not just the
    one in flight). Returns (latencies_s, issued, wall_s, errors) once
    every completion landed — errors counted so a dead server can never
    masquerade as a fast one (failed RPCs complete quickly)."""
    lats: list[float] = []
    lock = threading.Lock()
    outstanding: dict[int, float] = {}  # issue seq -> scheduled time
    errors = [0]
    interval = 1.0 / rate_ops_s
    t0 = time.perf_counter()
    n = int(rate_ops_s * duration_s)

    def on_done(seq, t_sched):
        def cb(fut=None):
            t = time.perf_counter() - t_sched
            bad = failed(fut)
            with lock:
                if outstanding.pop(seq, None) is None:
                    return  # already written off at the drain deadline
                lats.append(t)
                errors[0] += bad
        return cb

    # Burst issuance: everything whose slot has passed goes out, then the
    # generator SLEEPS to the next slot — a busy-wait here would hold the
    # GIL against the drain thread and measure the generator's own
    # convoy, not the pipeline's tail. Sleep overshoot delays issuance,
    # and the latency clock starts at the SCHEDULED slot either way, so
    # generator jitter is charged to the run honestly, never hidden.
    i = 0
    while i < n:
        sched = t0 + i * interval
        now = time.perf_counter()
        if sched <= now:
            with lock:
                outstanding[i] = sched
            submit_one(on_done(i, sched))
            i += 1
            continue
        # Always a real sleep, never a yield-spin: at sub-ms intervals a
        # sleep(0) loop competes for a core against the drain thread and
        # contaminates exactly the high-rate rows the gate reads. Kernel
        # timer overshoot (~50-100µs) just delays issuance, and the
        # latency clock starts at the scheduled slot regardless.
        time.sleep(sched - now)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with lock:
            if not outstanding:
                break
        time.sleep(0.005)
    with lock:
        # Ops still pending at the drain deadline are the WORST tail —
        # silently excluding them would be coordinated omission by
        # another door (a wedged server would report a healthy p99 from
        # the ops that happened to complete). Record each at its
        # clamped age and count it as an error.
        if outstanding:
            now = time.perf_counter()
            for t_sched in outstanding.values():
                lats.append(now - t_sched)
                errors[0] += 1
            outstanding.clear()
    wall = time.perf_counter() - t0
    return lats, n, wall, errors[0]


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--symbols", type=int, default=16)
    p.add_argument("--capacity", type=int, default=64)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--window-ms", type=float, default=1.0)
    p.add_argument("--kernel", choices=("matrix", "sorted"), default="matrix")
    p.add_argument("--mode", default="python",
                   help="comma list of in-proc serving paths: 'python' "
                        "(BatchDispatcher + EngineRunner) and/or 'native' "
                        "(LaneRingDispatcher + the C++ lane engine; needs "
                        "the built runtime). Ignored with --addr")
    p.add_argument("--load-fractions", default="0.5,0.8",
                   help="comma list of open-loop rates as fractions of "
                        "the measured closed-loop peak")
    p.add_argument("--levers", default="off,on",
                   help="tail-lever sweep: 'off' (busy-poll 0) and/or "
                        "'on' (--busy-poll-us). In --addr mode the "
                        "levers live server-side; this sweep is ignored")
    p.add_argument("--busy-poll-us", type=float, default=100.0,
                   help="the 'on' lever's spin budget (dispatcher drain "
                        "+ completion wait)")
    p.add_argument("--duration-s", type=float, default=4.0,
                   help="open-loop run length per point")
    p.add_argument("--peak-s", type=float, default=2.0,
                   help="closed-loop peak measurement length")
    p.add_argument("--repeats", type=int, default=3,
                   help="repetitions per point; the row reports the BEST "
                        "(lowest e2e p99) with the p99 min/max spread — "
                        "this container's shared 2-CPU host shows large "
                        "run-to-run scheduler noise")
    p.add_argument("--addr", default=None,
                   help="drive a LIVE server's SubmitOrder instead of the "
                        "in-proc pipeline (open-loop RPCs)")
    p.add_argument("--shm", default=None, metavar="SEGMENT",
                   help="drive a LIVE server's shared-memory ingress "
                        "segment (--shm-ingress on the server) instead of "
                        "RPCs: each scheduled slot pushes ONE record into "
                        "the ring and its latency runs from the scheduled "
                        "time to the positional ack on this writer's "
                        "response lane — the zero-copy edge's tail, no "
                        "proto or HTTP/2 in the path")
    p.add_argument("--batch-size", type=int, default=1, metavar="N",
                   help="with --addr: drive SubmitOrderBatch with N packed "
                        "op-records per RPC instead of per-op SubmitOrder "
                        "(the batch edge; domain/oprec.py codec). Rates "
                        "stay in ORDERS/s — the scheduler issues rate/N "
                        "batches per second — and each latency sample is "
                        "one batch's turnaround (every op in it completes "
                        "with the batch). A batch with ANY positional "
                        "reject counts as an error, so rejects can't "
                        "masquerade as fast completions. 1 = per-op "
                        "(default)")
    p.add_argument("--peak", type=float, default=0.0,
                   help="skip peak measurement and use this orders/s")
    p.add_argument("--workload", default=None, metavar="OPFILE",
                   help="recorded workload opfile (sim/record.py): the "
                        "open-loop stream draws its submits from the "
                        "recording's SUBMIT records (cyclic) instead of "
                        "the synthetic maker/taker alternation, so the "
                        "tail is measured under recorded sizes/symbol "
                        "skew/side mix. Cancels and auction phases are "
                        "dropped — open-loop slots cannot serialize "
                        "against server id assignment — and positional "
                        "rejects count as backpressure, not errors "
                        "(BENCH_METHOD §workload-replay). --addr mode "
                        "only")
    p.add_argument("--scrape", default=None,
                   help="with --addr: GET this /metrics URL after the run "
                        "and embed the me_stage_* quantile gauges")
    p.add_argument("--json-out", required=True)
    args = p.parse_args()

    if args.workload and not args.addr:
        p.error("--workload drives a live server: pass --addr")
    if args.shm and args.addr:
        p.error("--shm and --addr are alternative drive modes")
    if args.shm:
        out = run_shm(args)
    elif args.addr:
        out = run_grpc(args)
    else:
        out = run_inproc(args)

    try:
        import subprocess
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        rev = "unknown"
    out["git_rev"] = rev
    out["host_cpus"] = os.cpu_count()
    tmp = args.json_out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
    os.replace(tmp, args.json_out)
    print(json.dumps(out))


# -- in-proc pipeline drive ---------------------------------------------------


def run_inproc(args) -> dict:
    import jax  # noqa: F401 — backend init before the timed region

    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.engine.kernel import BUY, OP_SUBMIT, SELL
    from matching_engine_tpu.server.dispatcher import (
        BatchDispatcher,
        LaneRingDispatcher,
    )
    from matching_engine_tpu.server.engine_runner import (
        EngineOp,
        EngineRunner,
        OrderInfo,
    )
    from matching_engine_tpu.server.streams import StreamHub
    from matching_engine_tpu.utils.metrics import Metrics

    cache_dir = os.environ.get(
        "ME_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    try:
        import jax as _jax

        _jax.config.update("jax_compilation_cache_dir", cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001
        pass

    # K alternating GIL-held python sections (generator, drain) with
    # GIL-released jit calls between them: at CPython's default 5ms
    # switch interval the drain waits out the generator's whole quantum
    # (the convoy effect PR 4 measured; server/main.py applies the same
    # tuning under --serve-shards).
    sys.setswitchinterval(500 / 1e6)

    cfg = EngineConfig(num_symbols=args.symbols, capacity=args.capacity,
                       batch=args.batch, max_fills=1 << 15,
                       kernel=args.kernel)

    def make_column(mode: str, busy_poll_us: float):
        """One serving column (runner + dispatcher + per-op submit fn).
        The hub is subscriber-less and sequencer-less (the max-throughput
        configuration — stream proto construction gated off), sink=None:
        the bench measures the dispatch pipeline, not SQLite."""
        metrics = Metrics()
        hub = StreamHub()
        if mode == "native":
            from matching_engine_tpu.server.native_lanes import (
                NativeLanesRunner,
            )

            runner = NativeLanesRunner(cfg, metrics, hub=hub)
            dispatcher = LaneRingDispatcher(
                runner, hub=hub, window_ms=args.window_ms,
                busy_poll_us=busy_poll_us)
            # Maker/taker pairs per symbol: the maker rests, the taker
            # crosses it out, so books never fill up however long the
            # run.
            state = {"i": 0}

            def submit_one(done_cb):
                i = state["i"]
                state["i"] += 1
                sym = f"S{(i // 2) % args.symbols}".encode()
                maker = (i % 2) == 0
                fut = dispatcher.submit_record(
                    1, side=SELL if maker else BUY, otype=0,
                    price_q4=10_000, quantity=5, symbol=sym,
                    client_id=b"m" if maker else b"t")
                fut.add_done_callback(done_cb)
        else:
            runner = EngineRunner(cfg, metrics, hub=hub)
            dispatcher = BatchDispatcher(
                runner, hub=hub, window_ms=args.window_ms,
                busy_poll_us=busy_poll_us)
            state = {"i": 0}

            def submit_one(done_cb):
                # The grpcio edge's per-op work, in the timed path: slot/
                # oid/handle assignment + OrderInfo/EngineOp construction.
                i = state["i"]
                state["i"] += 1
                sym = f"S{(i // 2) % args.symbols}"
                maker = (i % 2) == 0
                slot = runner.slot_acquire(sym)
                if slot is None:
                    # Open-loop in-flight is unbounded by design: a long
                    # stall can pile >capacity live orders on a symbol.
                    # Surface it the way the edge would — a counted
                    # reject — never a crashed generator mid-sweep.
                    from concurrent.futures import Future

                    f: Future = Future()
                    f.set_exception(
                        RuntimeError("symbol capacity exhausted"))
                    done_cb(f)
                    return
                num, oid = runner.assign_oid()
                info = OrderInfo(
                    oid=num, order_id=oid,
                    client_id="m" if maker else "t", symbol=sym,
                    side=SELL if maker else BUY, otype=0, price_q4=10_000,
                    quantity=5, remaining=5, status=0,
                    handle=runner.assign_handle())
                fut = dispatcher.submit(EngineOp(OP_SUBMIT, info))
                fut.add_done_callback(done_cb)

        return metrics, runner, dispatcher, submit_one

    def closed_loop_peak(mode: str) -> float:
        """Max sustained rate through the SAME per-op submission path,
        with bounded in-flight (the closed-loop part): the reference the
        open-loop fractions are fractions OF. In-flight is capped below
        the book's maker capacity (symbols*capacity/2): running ahead of
        the pipeline would otherwise pile >capacity makers on a symbol
        and the 'peak' would count fast book-capacity REJECTs as served
        throughput — the error gate below backstops the same bug."""
        metrics, runner, dispatcher, submit_one = make_column(mode, 0.0)
        max_inflight = min(4096, max(64, args.symbols * args.capacity // 2))
        sem = threading.Semaphore(max_inflight)
        done = [0]
        errs = [0]
        lock = threading.Lock()

        def cb(fut=None):
            bad = _failed(fut)
            sem.release()
            with lock:
                done[0] += 1
                errs[0] += bad

        # Warm pass: compile the sparse/dense step shapes this flow uses.
        for _ in range(256):
            sem.acquire()
            submit_one(cb)
        runner.finish_pending()
        t0 = time.perf_counter()
        n0, e0 = done[0], errs[0]
        deadline = t0 + args.peak_s
        while time.perf_counter() < deadline:
            sem.acquire()
            submit_one(cb)
        runner.finish_pending()
        dt = time.perf_counter() - t0
        rate = (done[0] - n0) / dt
        dispatcher.close()
        if errs[0] - e0 > (done[0] - n0) * 0.01:
            print(f"[latency_bench] FATAL: {errs[0] - e0}/{done[0] - n0} "
                  f"peak-phase ops rejected — peak would be inflated by "
                  f"reject throughput", file=sys.stderr)
            raise SystemExit(1)
        return rate

    modes = [m.strip() for m in args.mode.split(",") if m.strip()]
    levers = [lv.strip() for lv in args.levers.split(",") if lv.strip()]
    fractions = [float(f) for f in args.load_fractions.split(",")]

    rows = []
    peaks = {}
    for mode in modes:
        if mode == "native":
            from matching_engine_tpu import native as me_native

            if not me_native.available():
                print("[latency_bench] native runtime not built; "
                      "skipping native mode", file=sys.stderr)
                continue
        peak = args.peak or closed_loop_peak(mode)
        peaks[mode] = round(peak, 1)
        warmed: set[float] = set()
        for lever in levers:
            busy = args.busy_poll_us if lever == "on" else 0.0
            for frac in fractions:
                rate = peak * frac
                if frac not in warmed:
                    # Warm on a THROWAWAY column: open-loop arrivals
                    # produce many distinct dispatch sizes, each a
                    # sparse-bucket shape that jit-compiles on first
                    # sight. The jit cache is process-global, so one
                    # discarded run per rate compiles them all without
                    # the ~100ms compile stalls landing in any measured
                    # column's stage histograms.
                    _m, _r, _d, _s = make_column(mode, 0.0)
                    _open_loop(_s, rate, min(1.5, args.duration_s))
                    _r.finish_pending()
                    _d.close()
                    warmed.add(frac)
                reps = []
                for _ in range(max(1, args.repeats)):
                    metrics, runner, dispatcher, submit_one = make_column(
                        mode, busy)
                    lats, n, wall, errs = _open_loop(
                        submit_one, rate, args.duration_s)
                    runner.finish_pending()
                    e2e = _pctls(lats)
                    reps.append({
                        "e2e": e2e,
                        "stages_us": _stage_quantiles(metrics),
                        "achieved_ops_s": round(len(lats) / wall, 1),
                        "n_ops": n,
                        "errors": errs,
                    })
                    dispatcher.close()
                best = min(reps, key=lambda r: r["e2e"]["p99_ms"])
                p99s = [r["e2e"]["p99_ms"] for r in reps]
                rows.append({
                    "mode": mode,
                    "levers": lever,
                    "busy_poll_us": busy,
                    "load_fraction": frac,
                    "target_ops_s": round(peak * frac, 1),
                    "achieved_ops_s": best["achieved_ops_s"],
                    "n_ops": best["n_ops"],
                    "e2e": best["e2e"],
                    "p99_over_p50": round(
                        best["e2e"]["p99_ms"] / best["e2e"]["p50_ms"], 2),
                    "stages_us": best["stages_us"],
                    "repeats": len(reps),
                    "p99_ms_spread": [min(p99s), max(p99s)],
                    "errors": best["errors"],
                })
                print(f"[latency_bench] {mode} levers={lever} "
                      f"frac={frac} p50={best['e2e']['p50_ms']}ms "
                      f"p99={best['e2e']['p99_ms']}ms "
                      f"p999={best['e2e']['p999_ms']}ms")

    import jax as _jax

    return {
        "metric": "serving_latency_tail",
        "drive": "in-proc open-loop",
        "platform": _jax.devices()[0].platform,
        "symbols": args.symbols, "capacity": args.capacity,
        "batch": args.batch, "kernel": args.kernel,
        "window_ms": args.window_ms,
        "duration_s": args.duration_s,
        "peak_ops_s": peaks,
        "rows": rows,
    }


# -- live-server drive (scripts/soak.sh latency round) ------------------------


def run_grpc(args) -> dict:
    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub

    channel = grpc.insecure_channel(args.addr)
    stub = MatchingEngineStub(channel)
    state = {"i": int(time.time()) % 1000000 * 1000}
    bs = max(1, args.batch_size)

    workload = None
    failed = _failed
    if args.workload:
        # Recorded-flow drive: cycle the workload's SUBMIT records. The
        # open-loop generator cannot serialize against the server's id
        # assignment, so cancels (renumbered-target records) and auction
        # phases are dropped here — the faithful in-order replay is
        # runner_bench --workload; this mode measures the TAIL under the
        # recording's sizes, symbol skew, and side mix. Positional
        # rejects under recorded stress are backpressure (counted by the
        # server's orders_rejected), not sample errors.
        from matching_engine_tpu.domain import oprec

        from matching_engine_tpu.proto import split_otype as _split_otype

        _record_fields = oprec.record_fields
        arr = oprec.read_opfile(args.workload)
        workload = arr[arr["op"] == oprec.OPREC_SUBMIT]
        if len(workload) == 0:
            print("[latency_bench] FATAL: workload has no submit records",
                  file=sys.stderr)
            raise SystemExit(1)
        state["i"] = 0

        def failed(fut):  # noqa: F811 — workload-aware error gate
            if fut is None:
                return False
            try:
                if fut.exception(timeout=0) is not None:
                    return True
                res = fut.result(timeout=0)
            except Exception:  # noqa: BLE001
                return True
            oks = getattr(res, "ok", None)
            if oks is not None and not isinstance(oks, bool):
                # Batch response: success=False means the PAYLOAD was
                # undecodable — a real error; positional rejects are
                # recorded-stress backpressure, never sample errors.
                return getattr(res, "success", True) is False
            # Per-op response: an app-level reject (success=False, gRPC
            # OK) is the same backpressure — cycling resting LIMIT flow
            # without its cancels drives books to capacity by design.
            # Dead/refusing servers still fail via the RpcError path.
            return False

    def make_req():
        i = state["i"]
        state["i"] += 1
        if workload is not None:
            (_op, side, otype, price_q4, qty, sym, cid,
             _oid) = _record_fields(workload[i % len(workload)])
            order_type, tif = _split_otype(otype)
            return pb2.OrderRequest(
                client_id=cid.decode(), symbol=sym.decode(),
                order_type=order_type, side=side, price=price_q4,
                scale=4, quantity=qty, tif=tif)
        maker = (i % 2) == 0
        return pb2.OrderRequest(
            client_id="lat-m" if maker else "lat-t",
            symbol=f"LAT{(i // 2) % 4}", order_type=pb2.LIMIT,
            side=pb2.SELL if maker else pb2.BUY,
            price=10_000, scale=4, quantity=5)

    if bs > 1:
        # Batch edge: each scheduled slot is ONE SubmitOrderBatch of bs
        # maker/taker records (domain/oprec.py payload); rates stay in
        # orders/s — the caller divides by bs when scheduling slots.
        from matching_engine_tpu.domain import oprec

        def make_payload():
            i = state["i"]
            state["i"] += bs
            if workload is not None:
                idx = [(i + j) % len(workload) for j in range(bs)]
                return oprec.encode_payload(workload[idx])
            ops = []
            for j in range(i, i + bs):
                maker = (j % 2) == 0
                ops.append((oprec.OPREC_SUBMIT, 2 if maker else 1, 0,
                            10_000, 5, f"LAT{(j // 2) % 4}",
                            "lat-m" if maker else "lat-t", ""))
            return oprec.encode_payload(oprec.pack_records(ops))

        def submit_one(done_cb):
            fut = stub.SubmitOrderBatch.future(
                pb2.OrderBatchRequest(ops=make_payload()), timeout=30)
            fut.add_done_callback(done_cb)
    else:
        def submit_one(done_cb):
            fut = stub.SubmitOrder.future(make_req(), timeout=30)
            fut.add_done_callback(done_cb)

    if args.peak:
        peak = args.peak / bs  # --peak is orders/s; slots carry bs each
    else:
        # Closed-loop peak with bounded in-flight RPCs. A dead/refusing
        # server fails futures FAST — without the error gate it would
        # "measure" a spectacular peak of connection errors.
        sem = threading.Semaphore(64)
        done = [0]
        errs = [0]

        def cb(fut=None):
            bad = failed(fut)
            sem.release()
            done[0] += 1
            errs[0] += bad
        # Warm phase (discarded): a cold server jit-compiles each
        # dispatch shape on first sight — those stalls belong outside
        # the measured peak. Drain the warm in-flight window BEFORE
        # resetting the counters, or its completions (and any cold-start
        # errors) would land inside the measured window.
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < max(1.0, args.peak_s / 2):
            sem.acquire()
            submit_one(cb)
        for _ in range(64):
            sem.acquire()
        sem = threading.Semaphore(64)
        done[0] = 0
        errs[0] = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < args.peak_s:
            sem.acquire()
            submit_one(cb)
        for _ in range(64):  # drain
            sem.acquire()
        peak = done[0] / (time.perf_counter() - t0)
        if done[0] == 0 or errs[0] > done[0] * 0.01:
            print(f"[latency_bench] FATAL: {errs[0]}/{done[0]} peak-phase "
                  f"RPCs failed — is {args.addr} serving?", file=sys.stderr)
            raise SystemExit(1)

    rows = []
    for frac in [float(f) for f in args.load_fractions.split(",")]:
        reps = []
        for _ in range(max(1, args.repeats)):
            lats, n, wall, errors = _open_loop(submit_one, peak * frac,
                                               args.duration_s,
                                               failed=failed)
            e2e = _pctls(lats)
            reps.append({"e2e": e2e,
                         "achieved_ops_s": round(len(lats) / wall, 1),
                         "n_ops": n, "errors": errors})
        best = min(reps, key=lambda r: r["e2e"]["p99_ms"])
        p99s = [r["e2e"]["p99_ms"] for r in reps]
        if best["errors"] > best["n_ops"] * 0.01:
            print(f"[latency_bench] FATAL: {best['errors']}/{best['n_ops']} "
                  f"open-loop RPCs failed", file=sys.stderr)
            raise SystemExit(1)
        rows.append({
            "mode": "grpc" if bs == 1 else "grpc-batch",
            "batch_size": bs,
            "load_fraction": frac,
            "target_ops_s": round(peak * bs * frac, 1),
            "achieved_ops_s": round(best["achieved_ops_s"] * bs, 1),
            "n_ops": best["n_ops"] * bs,
            # Each latency sample is one SLOT's turnaround: a single RPC
            # (bs=1) or a whole batch (every op completes with it).
            "e2e": best["e2e"],
            "p99_over_p50": round(
                best["e2e"]["p99_ms"] / best["e2e"]["p50_ms"], 2),
            "repeats": len(reps), "p99_ms_spread": [min(p99s), max(p99s)],
            "errors": best["errors"],
        })
        print(f"[latency_bench] grpc bs={bs} frac={frac} "
              f"p50={best['e2e']['p50_ms']}ms p99={best['e2e']['p99_ms']}ms")

    out = {
        "metric": "serving_latency_tail",
        "drive": f"grpc open-loop @ {args.addr}"
                 + (f" (SubmitOrderBatch x{bs})" if bs > 1 else "")
                 + (f" [workload {args.workload}]" if args.workload
                    else ""),
        "batch_size": bs,
        "peak_ops_s": {"grpc": round(peak * bs, 1)},
        "rows": rows,
    }
    if args.workload:
        out["workload"] = args.workload
    if args.scrape:
        import urllib.request

        try:
            body = urllib.request.urlopen(args.scrape, timeout=10) \
                .read().decode()
            # Quantile/EMA gauges only: the stage histograms also export
            # native _bucket{le=}/_sum/_count series, which are lifetime
            # cumulative counts, not latency figures.
            out["server_stage_gauges"] = {
                parts[0]: float(parts[1])
                for parts in (ln.split() for ln in body.splitlines())
                if len(parts) == 2 and parts[0].startswith("me_stage_")
                and parts[0].endswith(("_p50", "_p99", "_p999", "_ema"))
            }
            out["server_p999_gauges"] = sorted(
                k for k in out["server_stage_gauges"] if k.endswith("_p999"))
        except Exception as e:  # noqa: BLE001
            out["scrape_error"] = f"{type(e).__name__}: {e}"
    return out


# -- live-server shm drive (the zero-copy edge's tail) ------------------------


def run_shm(args) -> dict:
    """Open-loop single-record pushes into a live server's shm ingress
    ring. Same two-phase protocol as run_grpc — closed-loop peak through
    the identical per-record path, then fixed-rate fractions with
    latency from each op's SCHEDULED slot to its positional ack — so the
    rows land next to the RPC rungs in one artifact. A drain thread owns
    this writer's response lane and resolves completions by ring
    sequence; a push finding the ring full retries briefly and then
    counts as an error (open-loop backpressure must not silently thin
    the schedule)."""
    import numpy as np

    from matching_engine_tpu import native as me_native
    from matching_engine_tpu.domain import oprec

    if not me_native.available():
        print("[latency_bench] FATAL: --shm needs the native runtime",
              file=sys.stderr)
        raise SystemExit(1)
    ring = me_native.ShmRing(args.shm)
    writer_id = ring.register_writer()

    # Maker/taker alternation over 4 symbols (the grpc drive's synthetic
    # flow, packed as oprec records): makers rest, takers cross them out,
    # books stay shallow however long the run.
    recs = []
    for j in range(8):
        maker = j % 2 == 0
        recs.append(oprec.pack_records([
            (oprec.OPREC_SUBMIT, 2 if maker else 1, 0, 10_000, 5,
             f"LAT{(j // 2) % 4}", "lat-m" if maker else "lat-t", ""),
        ]).tobytes())

    lock = threading.Lock()
    cbs: dict[int, object] = {}      # ring seq -> completion callback
    orphans: dict[int, bool] = {}    # ack arrived before registration
    stop = threading.Event()

    def drain_loop():
        while not stop.is_set():
            raw = ring.resp_poll_raw(4096, 20_000)
            if raw is None:
                break  # server shut the segment down
            if not raw:
                continue
            rs = np.frombuffer(raw, dtype=oprec.SHM_RESP_DTYPE)
            fire = []
            with lock:
                for seq, ok in zip(rs["seq"].tolist(),
                                   (rs["ok"] != 0).tolist()):
                    cb = cbs.pop(seq, None)
                    if cb is None:
                        # Push→ack can beat push→register: stash it.
                        orphans[seq] = ok
                    else:
                        fire.append((cb, ok))
            for cb, ok in fire:
                cb(ok)

    drainer = threading.Thread(target=drain_loop, name="shm-lat-drain",
                               daemon=True)
    drainer.start()
    state = {"i": 0}

    def submit_one(done_cb):
        i = state["i"]
        state["i"] += 1
        body = recs[i % 8]
        base = ring.push_payload(body, 1)
        tries = 0
        while base == -1 and tries < 200:
            time.sleep(0.0005)
            base = ring.push_payload(body, 1)
            tries += 1
        if base < 0:
            done_cb(False)  # sustained-full / shutdown: a counted error
            return
        seq = int(base)
        with lock:
            if seq in orphans:
                ok, direct = orphans.pop(seq), True
            else:
                cbs[seq] = done_cb
                ok, direct = False, False
        if direct:
            done_cb(ok)

    def failed(ok) -> bool:
        # Completions carry the positional ack's ok flag directly (no
        # future object on this edge).
        return not ok

    if args.peak:
        peak = args.peak
    else:
        sem = threading.Semaphore(64)
        done = [0]
        errs = [0]

        def cb(ok=None):
            bad = failed(ok)
            sem.release()
            done[0] += 1
            errs[0] += bad

        # Warm phase (discarded): first-sight dispatch shapes compile
        # outside the measured window; drain the in-flight window before
        # resetting counters.
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < max(1.0, args.peak_s / 2):
            sem.acquire()
            submit_one(cb)
        for _ in range(64):
            sem.acquire()
        sem = threading.Semaphore(64)
        done[0] = 0
        errs[0] = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < args.peak_s:
            sem.acquire()
            submit_one(cb)
        for _ in range(64):  # drain
            sem.acquire()
        peak = done[0] / (time.perf_counter() - t0)
        if done[0] == 0 or errs[0] > done[0] * 0.01:
            print(f"[latency_bench] FATAL: {errs[0]}/{done[0]} peak-phase "
                  f"shm pushes failed — is the segment served?",
                  file=sys.stderr)
            raise SystemExit(1)

    rows = []
    for frac in [float(f) for f in args.load_fractions.split(",")]:
        reps = []
        for _ in range(max(1, args.repeats)):
            lats, n, wall, errors = _open_loop(submit_one, peak * frac,
                                               args.duration_s,
                                               failed=failed)
            e2e = _pctls(lats)
            reps.append({"e2e": e2e,
                         "achieved_ops_s": round(len(lats) / wall, 1),
                         "n_ops": n, "errors": errors})
        best = min(reps, key=lambda r: r["e2e"]["p99_ms"])
        p99s = [r["e2e"]["p99_ms"] for r in reps]
        if best["errors"] > best["n_ops"] * 0.01:
            print(f"[latency_bench] FATAL: {best['errors']}/"
                  f"{best['n_ops']} open-loop shm ops failed",
                  file=sys.stderr)
            raise SystemExit(1)
        rows.append({
            "mode": "shm",
            "load_fraction": frac,
            "target_ops_s": round(peak * frac, 1),
            "achieved_ops_s": best["achieved_ops_s"],
            "n_ops": best["n_ops"],
            "e2e": best["e2e"],
            "p99_over_p50": round(
                best["e2e"]["p99_ms"] / best["e2e"]["p50_ms"], 2),
            "repeats": len(reps),
            "p99_ms_spread": [min(p99s), max(p99s)],
            "errors": best["errors"],
        })
        print(f"[latency_bench] shm frac={frac} "
              f"p50={best['e2e']['p50_ms']}ms p99={best['e2e']['p99_ms']}ms "
              f"p999={best['e2e']['p999_ms']}ms")

    stop.set()
    ring.close()
    out = {
        "metric": "serving_latency_tail",
        "drive": f"shm open-loop @ {args.shm}",
        "writer_id": writer_id,
        "peak_ops_s": {"shm": round(peak, 1)},
        "rows": rows,
    }
    if args.scrape:
        import urllib.request

        try:
            body = urllib.request.urlopen(args.scrape, timeout=10) \
                .read().decode()
            out["server_stage_gauges"] = {
                parts[0]: float(parts[1])
                for parts in (ln.split() for ln in body.splitlines())
                if len(parts) == 2 and parts[0].startswith("me_stage_")
                and parts[0].endswith(("_p50", "_p99", "_p999", "_ema"))
            }
        except Exception as e:  # noqa: BLE001
            out["scrape_error"] = f"{type(e).__name__}: {e}"
    return out


if __name__ == "__main__":
    main()
