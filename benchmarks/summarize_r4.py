"""Render the round-4 capture artifacts into one markdown summary table.

Reads benchmarks/results/tpu_r4_*.json, tpu_suite_full_r4.jsonl,
tpu_e2e_r4_*.json, and the resident log; prints markdown to stdout
(written into ROUND4.md / BENCH_METHOD.md once captures land). Missing
artifacts are listed as pending — safe to run at any point.

Usage: python benchmarks/summarize_r4.py
"""

from __future__ import annotations

import json
import os

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")


def load(name: str):
    try:
        with open(os.path.join(RESULTS, name)) as f:
            if ".jsonl" in name:  # incl. .jsonl.partial salvage files
                return [json.loads(ln) for ln in f if ln.strip()]
            return json.load(f)
    except (OSError, ValueError):
        return None


def fmt(v):
    if v is None:
        return "—"
    if isinstance(v, (int, float)) and v >= 1e6:
        return f"{v / 1e6:,.1f}M"
    return f"{v:,.0f}" if isinstance(v, (int, float)) else str(v)


def row(name, art, *cols):
    print(f"| {name} | " + " | ".join(fmt(c) for c in cols) + f" | `{art}` |")


def main() -> None:
    print("## Device throughput (orders/sec, single tunneled v5e)\n")
    print("| config | value | µs/step | kernel | artifact |")
    print("|---|---|---|---|---|")
    for name, art in [
        ("4096 syms (headline)", "tpu_r4_headline.json"),
        ("4096 syms, sorted", "tpu_r4_headline_sorted.json"),
        ("batch 64", "tpu_r4_batch64.json"),
        ("batch 128", "tpu_r4_batch128.json"),
        ("64 syms", "tpu_r4_syms64.json"),
        ("256 syms", "tpu_r4_syms256.json"),
        ("1024 syms", "tpu_r4_syms1024.json"),
        ("cap 128 (S=256)", "tpu_r4_cap128.json"),
        ("cap 256", "tpu_r4_cap256.json"),
        ("cap 512", "tpu_r4_cap512.json"),
        ("cap 1024", "tpu_r4_cap1024.json"),
        ("cap 128 sorted", "tpu_r4_cap128_sorted.json"),
        ("cap 512 sorted", "tpu_r4_cap512_sorted.json"),
        ("cap 1024 sorted", "tpu_r4_cap1024_sorted.json"),
        ("cap 4096 sorted", "tpu_r4_cap4096_sorted.json"),
        ("L3 realistic (3b)", "tpu_r4_l3flow.json"),
        ("cap 8192 sorted", "tpu_r5_cap8192_sorted.json"),
        ("cap 512 v2", "tpu_r5_cap512_v2.json"),
        ("cap 512 sorted v2", "tpu_r5_cap512_sorted_v2.json"),
        ("batch 128 sorted", "tpu_r5_batch128_sorted.json"),
        ("batch 256 sorted", "tpu_r5_batch256_sorted.json"),
        ("batch 512 sorted", "tpu_r5_batch512_sorted.json"),
    ]:
        d = load(art)
        if d is None:
            row(name, art, None, None, None)
        else:
            row(name, art, d.get("value"),
                d.get("mean_dispatch_latency_us"),
                d.get("kernel", "matrix"))

    print("\n## Suite (full scale)\n")
    suite = load("tpu_suite_full_r4.jsonl") or load(
        "tpu_suite_full_r4.jsonl.partial") or []
    suite += load("tpu_suite7_r5.jsonl") or []  # venue-depth auction row
    if suite:
        print("| config | metric | value | unit |")
        print("|---|---|---|---|")
        for r in suite:
            print(f"| {r.get('config')} | {r.get('metric')} | "
                  f"{fmt(r.get('value'))} | {r.get('unit')} |")
    else:
        print("pending")

    print("\n## Serving stack\n")
    any_rb = False
    for art in ("tpu_r4_runner.json", "tpu_r5_runner_sat.json"):
        rb = load(art)
        if not rb:
            continue
        if any_rb:
            print()
        any_rb = True
        print(f"`{art}`:\n")
        print("| batch_ops | inflight | orders/s | p50 ms | p99 ms |")
        print("|---|---|---|---|---|")
        for p in rb.get("sweep", []):
            print(f"| {p.get('batch_ops')} | {p['inflight']} | "
                  f"{fmt(p['orders_per_s'])} | "
                  f"{p['p50_ms']} | {p['p99_ms']} |")
    if not any_rb:
        print("runner sweep pending")
    print()
    print("| edge | pi | orders/s | p50 ms | p99 ms | p99/p50 |")
    print("|---|---|---|---|---|---|")
    for edge in ("native", "grpcio"):
        for pi, sfx in ((2, ""), (4, ""), (2, "_w256"), (4, "_sat"),
                        (4, "_w25"), (4, "_w60"), (4, "_w60_best")):
            if sfx == "_w60_best" and edge != "native":
                continue  # native-only preserved peak; not a pending row
            d = load(f"tpu_e2e_r4_{edge}_pi{pi}{sfx}.json")
            label = f"{pi}{sfx}"
            if d is None:
                print(f"| {edge} | {label} | — | — | — | — |")
            else:
                ratio = (d["p99_ms"] / d["p50_ms"]) if d.get("p50_ms") else 0
                print(f"| {edge} | {label} | {fmt(d.get('value'))} | "
                      f"{d.get('p50_ms')} | {d.get('p99_ms')} | "
                      f"{ratio:.1f}x |")

    soaks = sorted(
        f for f in os.listdir(RESULTS)
        if f.startswith("soak_") and f.endswith(".json"))
    if soaks:
        print("\n## Soaks (sustained dual-edge serving, audit-gated)\n")
        print("| artifact | platform | min | orders ok | cancels | "
              "auction quiesces | audit violations | server args |")
        print("|---|---|---|---|---|---|---|---|")
        for f in soaks:
            s = load(f)
            if not s:
                continue
            print(f"| `{f}` | {s.get('platform', '—')} | "
                  f"{s.get('minutes', '—')} | {fmt(s.get('orders_ok'))} | "
                  f"{s.get('cancels', '—')} | {s.get('rounds', '—')} | "
                  f"{s.get('audit_violations', '—')} | "
                  f"`{s.get('server_args', '')}` |")

    print("\n## Kernel profiles\n")
    any_profile = False
    for label, art in [("matrix", "tpu_r4_profile.json"),
                       ("sorted", "tpu_r5_profile_sorted.json")]:
        pk = load(art)
        if not pk:
            continue
        if any_profile:
            print()  # blank line between blocks: keep markdown lists apart
        any_profile = True
        print(f"**{pk.get('kernel', label)}** (`{art}`):")
        print(f"- full step: {pk['full_step_us']}µs "
              f"({fmt(pk['orders_per_s'])} orders/s at "
              f"{pk['ops_per_step']} ops/step)")
        print(f"- phases: scan {pk['phase_scan_us']}µs + finalize "
              f"{pk['phase_finalize_us']}µs (sum/full = "
              f"{pk['phase_sum_vs_full']})")
        rl = pk.get("roofline") or {}
        if rl:
            gbps = rl.get("logical_bytes_gbps",
                          rl.get("achieved_hbm_gbps", 0.0))
            print(f"- roofline: {fmt(rl['bytes_per_step'])} bytes/step, "
                  f"{rl['bytes_per_op']} bytes/op, "
                  f"{gbps} GB/s logical = "
                  f"{rl['fraction_of_hbm_peak']:.1%} of v5e HBM peak "
                  f"(>100% => fused on-chip traffic, not HBM-bound)")
        print(f"- device trace: {pk.get('device_trace')}")
    if not any_profile:
        print("pending")

    res = load("tpu_resident_log.jsonl")
    if res:
        # The log is mixed (cpu fallback rows, and matrix rows from
        # before the sorted-headline decision): report the best per
        # (platform, kernel) so no figure is attributed to the wrong
        # formulation.
        best_by = {}
        for r in res:
            key = (r.get("platform"), r.get("kernel", "matrix"))
            if key not in best_by or r["value"] > best_by[key]:
                best_by[key] = r["value"]
        parts = ", ".join(
            f"{p}/{k} {fmt(v)}" for (p, k), v in sorted(best_by.items()))
        print(f"\n## Resident: {len(res)} warm measurements; "
              f"best by platform/kernel: {parts} orders/s")


if __name__ == "__main__":
    main()
