"""Kernel efficiency story (VERDICT r3 next-step 3): device trace +
per-phase device-time breakdown + bytes/op roofline for the headline
config, so the measured orders/sec is EXPLAINED, not just measured.

Three independent evidence sources, all in one artifact:

1. **Per-phase timing**: the full engine step vs its two phases jitted
   separately — the vmap×scan match loop (the O(CAP^2) priority matrix)
   and the finalize epilogue (fill compaction + top-of-book). Synced
   median windows, same methodology as every other bench here.
2. **XLA cost analysis** of the compiled full step: flops + bytes
   accessed per step, giving bytes/op and achieved HBM bandwidth at the
   measured step latency — the roofline coordinate. (v5e reference peak:
   ~819 GB/s HBM per chip, the usual bound for int32 vector work; the
   MXU plays no part in this integer kernel by design.)
3. **jax.profiler device trace** of a short annotated run (TensorBoard-
   loadable, checked in under profile_r4/) — best-effort: a tunneled
   backend may refuse tracing; the breakdown above stands alone.

Usage: python benchmarks/profile_kernel.py --json-out out.json
       [--symbols 4096] [--capacity 128] [--batch 32] [--trace-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5E_HBM_PEAK_GBPS = 819.0  # public v5e spec: ~819 GB/s HBM BW per chip


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--symbols", type=int, default=4096)
    p.add_argument("--capacity", type=int, default=128)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--kernel", choices=("matrix", "sorted"),
                   default="matrix")
    p.add_argument("--windows", type=int, default=4)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--trace-dir", default=None)
    p.add_argument("--json-out", required=True)
    args = p.parse_args()

    import jax
    import numpy as np

    cache_dir = os.environ.get(
        "ME_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    t0 = time.perf_counter()
    devices = jax.devices()
    platform = devices[0].platform
    backend_init_s = time.perf_counter() - t0

    from matching_engine_tpu.engine.book import (
        BookBatch,
        EngineConfig,
        init_book,
    )
    from matching_engine_tpu.engine.kernel import (
        _SymBook,
        _sym_scan,
        engine_step,
        finalize_step,
    )
    from matching_engine_tpu.utils.measure import (
        headline_streams,
        prepare_waves,
    )

    cfg = EngineConfig(num_symbols=args.symbols, capacity=args.capacity,
                       batch=args.batch, max_fills=1 << 17,
                       kernel=args.kernel)
    if args.kernel == "sorted":
        # Same phase boundary for the sorted formulation: its vmap x scan
        # match loop (dense-sorted-prefix vector ops) vs the SHARED
        # finalize epilogue (VERDICT r4 weak #4 — the profiler previously
        # covered only the matrix formulation).
        from matching_engine_tpu.engine.kernel_sorted import (
            _sym_scan_sorted as _scan_fn,
        )
    else:
        _scan_fn = _sym_scan
    waves, wave_ops = prepare_waves(cfg, headline_streams(cfg, n_streams=2))
    ops_per_step = wave_ops[0]

    def timed(fn, *a, n_args_donated=0):
        """Median synced per-call latency (µs) over windows of iters."""
        out = fn(*a)
        jax.block_until_ready(out)
        lats = []
        for _ in range(args.windows):
            t1 = time.perf_counter()
            for _ in range(args.iters):
                out = fn(*a)
            jax.block_until_ready(out)
            lats.append((time.perf_counter() - t1) / args.iters * 1e6)
        lats.sort()
        return lats[len(lats) // 2], out

    # -- phase 1: the vmap x scan match loop only (no epilogue) ------------
    def scan_only(book: BookBatch, orders):
        sym_book = _SymBook(*book[:-1], next_seq=book.next_seq)
        new_sym_book, outs = jax.vmap(_scan_fn)(sym_book, orders)
        new_book = BookBatch(*new_sym_book[:-1],
                             next_seq=new_sym_book.next_seq)
        return new_book, outs

    scan_jit = jax.jit(scan_only)
    book = init_book(cfg)
    scan_us, (scanned_book, scan_outs) = timed(scan_jit, book, waves[0])

    # -- phase 2: finalize epilogue (fill compaction + top-of-book) --------
    finalize_jit = jax.jit(finalize_step, static_argnums=0)
    status, filled, remaining, f_oid, f_qty, f_price = scan_outs
    fin_us, _ = timed(finalize_jit, cfg, scanned_book, waves[0], status,
                      filled, remaining, f_oid, f_qty, f_price)

    # -- full step (the real entry point, donated book) --------------------
    full_book = init_book(cfg)
    full = None
    full_lats = []
    b = full_book
    out = None
    b, out = engine_step(cfg, b, waves[0])
    jax.block_until_ready(out)
    for _ in range(args.windows):
        t1 = time.perf_counter()
        for i in range(args.iters):
            b, out = engine_step(cfg, b, waves[i % len(waves)])
        jax.block_until_ready(out)
        full_lats.append((time.perf_counter() - t1) / args.iters * 1e6)
    full_lats.sort()
    full_us = full_lats[len(full_lats) // 2]

    # -- XLA cost analysis -------------------------------------------------
    cost: dict = {}
    try:
        lowered = jax.jit(
            lambda bb, oo: engine_step.__wrapped__(cfg, bb, oo)
        ).lower(init_book(cfg), waves[0])
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else {}
        cost = {k: float(v) for k, v in ca.items()
                if k in ("flops", "bytes accessed")}
    except Exception as e:  # noqa: BLE001 — cost analysis is optional
        cost = {"error": f"{type(e).__name__}: {e}"}

    bytes_per_step = cost.get("bytes accessed")
    roofline = {}
    if bytes_per_step:
        achieved_gbps = bytes_per_step / (full_us / 1e6) / 1e9
        roofline = {
            "bytes_per_step": bytes_per_step,
            "bytes_per_op": round(bytes_per_step / ops_per_step, 1),
            "logical_bytes_gbps": round(achieved_gbps, 1),
            "hbm_peak_gbps": V5E_HBM_PEAK_GBPS,
            "fraction_of_hbm_peak": round(
                achieved_gbps / V5E_HBM_PEAK_GBPS, 3),
            # XLA cost analysis counts LOGICAL accesses (pre-fusion);
            # a fraction >> 1 means most of that traffic never reaches
            # HBM — it lives in VMEM/registers inside fused loops, i.e.
            # the kernel is on-chip/VPU-bound, not HBM-bound. The
            # resident book state is the true HBM floor:
            "book_bytes": int(sum(
                np.prod(x.shape) * 4 for x in init_book(cfg))),
        }

    # -- best-effort device trace -----------------------------------------
    trace_note = "skipped (no --trace-dir)"
    if args.trace_dir:
        try:
            from matching_engine_tpu.utils.tracing import (
                step_annotation,
                trace,
            )

            os.makedirs(args.trace_dir, exist_ok=True)
            with trace(args.trace_dir):
                for i in range(5):
                    with step_annotation("engine_step", i):
                        b, out = engine_step(cfg, b, waves[i % len(waves)])
                jax.block_until_ready(out)
            names = []
            for root, _, files in os.walk(args.trace_dir):
                names += [os.path.join(os.path.relpath(root, args.trace_dir),
                                       f) for f in files]
            trace_note = f"captured {len(names)} file(s)"
        except Exception as e:  # noqa: BLE001
            trace_note = f"trace failed: {type(e).__name__}: {e}"

    try:
        import subprocess
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        rev = "unknown"

    out_row = {
        "metric": "kernel_profile",
        "platform": platform,
        "symbols": args.symbols,
        "capacity": args.capacity,
        "batch": args.batch,
        "kernel": args.kernel,
        "backend_init_s": round(backend_init_s, 1),
        "ops_per_step": ops_per_step,
        "full_step_us": round(full_us, 1),
        "orders_per_s": round(ops_per_step / (full_us / 1e6), 1),
        "phase_scan_us": round(scan_us, 1),
        "phase_finalize_us": round(fin_us, 1),
        "phase_sum_vs_full": round((scan_us + fin_us) / full_us, 3),
        "cost_analysis": cost,
        "roofline": roofline,
        "device_trace": trace_note,
        "git_rev": rev,
    }
    tmp = args.json_out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out_row, f, indent=1)
    os.replace(tmp, args.json_out)
    print(json.dumps(out_row))


if __name__ == "__main__":
    main()
