"""Warm resident bench process: keeps a compiled engine + device-resident
waves alive so an end-of-round `bench.py` run can obtain a real-TPU figure
in seconds instead of paying backend init + stream build + compile inside
the driver's wall budget (VERDICT r3 next-step 1: "a watcher-kept warm
resident process bench.py can signal").

Protocol (file-based, under benchmarks/.resident/):
  state.json      — {"pid", "heartbeat_ts", "platform", "symbols", ...};
                    heartbeat_ts is refreshed ONLY after a successful tiny
                    device op, so a wedged tunnel makes it stale and
                    bench.py knows not to wait on us.
  req-<nonce>     — written by bench.py; we run a fresh measurement and
                    write out-<nonce>.json, then delete the request.
  out-<nonce>.json— {"value", "platform", "measured_at", ...} (the same
                    row shape bench_child.py writes).

Every measurement (requested or periodic self-measure) is also appended to
benchmarks/results/tpu_resident_log.jsonl for provenance.

Run by scripts/tpu_r4_watch.sh once the round's capture list completes;
exits on its own after MAX_LIFETIME_S or when the state dir is deleted.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STATE_DIR = os.path.join(REPO, "benchmarks", ".resident")
RESULTS_LOG = os.path.join(REPO, "benchmarks", "results",
                           "tpu_resident_log.jsonl")
HEARTBEAT_EVERY_S = 30.0
SELF_MEASURE_EVERY_S = 1800.0
MAX_LIFETIME_S = float(os.environ.get("RESIDENT_MAX_LIFETIME_S", 12 * 3600))


def _git_rev() -> str:
    import subprocess
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5, cwd=REPO,
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        return "unknown"


def _write_state(state: dict) -> None:
    tmp = os.path.join(STATE_DIR, "state.json.tmp")
    with open(tmp, "w") as f:
        json.dump(state, f)
    os.replace(tmp, os.path.join(STATE_DIR, "state.json"))


def main() -> None:
    os.makedirs(STATE_DIR, exist_ok=True)
    symbols = int(os.environ.get("RESIDENT_SYMBOLS", 4096))
    capacity = int(os.environ.get("RESIDENT_CAPACITY", 128))
    batch = int(os.environ.get("RESIDENT_BATCH", 32))
    # Default matches bench.py TPU_ARGS: the sorted kernel is the decided
    # headline formulation (2.21B/s vs matrix 1.26B measured 2026-07-31;
    # DESIGN.md 6d) — a resident serving the wrong formulation would hand
    # the driver a mislabeled record.
    kernel = os.environ.get("RESIDENT_KERNEL", "sorted")

    import jax

    cache_dir = os.environ.get("ME_JAX_CACHE", os.path.join(REPO, ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    t0 = time.perf_counter()
    devices = jax.devices()
    platform = devices[0].platform
    init_s = time.perf_counter() - t0

    from matching_engine_tpu.engine.book import EngineConfig, init_book
    from matching_engine_tpu.engine.kernel import engine_step
    from matching_engine_tpu.utils.measure import (
        headline_streams,
        measure_windows,
        prepare_waves,
        result_row,
    )

    # Purge protocol residue from previous/abandoned runs: an orphaned
    # req-* would make this fresh resident burn its first seconds serving
    # a request nobody reads; stale out-* just accumulate.
    for name in os.listdir(STATE_DIR):
        if name.startswith(("req-", "out-")):
            try:
                os.unlink(os.path.join(STATE_DIR, name))
            except OSError:
                pass

    cfg = EngineConfig(num_symbols=symbols, capacity=capacity, batch=batch,
                       max_fills=1 << 17, kernel=kernel)
    waves, wave_ops = prepare_waves(cfg, headline_streams(cfg))
    book = init_book(cfg)
    book, out = engine_step(cfg, book, waves[0])
    jax.block_until_ready(out)
    rev = _git_rev()

    state = {
        "pid": os.getpid(),
        "platform": platform,
        "symbols": symbols,
        "capacity": capacity,
        "batch": batch,
        "kernel": kernel,
        "backend_init_s": round(init_s, 1),
        "started_ts": time.time(),
        "heartbeat_ts": time.time(),
        "git_rev": rev,
    }
    _write_state(state)
    print(f"[resident] up: platform={platform} init={init_s:.1f}s "
          f"cfg={symbols}/{capacity}/{batch}/{kernel}", flush=True)

    def measure(windows: int, iters: int) -> dict:
        nonlocal book
        value, lat_us, book = measure_windows(
            cfg, book, waves, wave_ops, windows=windows, iters=iters)
        row = result_row(cfg, round(value, 1), lat_us, platform=platform,
                         n_devices=len(devices), backend_init_s=0.0,
                         git_rev=rev)
        row["via"] = "resident"
        row["measured_at"] = time.time()
        with open(RESULTS_LOG, "a") as f:
            f.write(json.dumps(row) + "\n")
        return row

    # First self-measurement doubles as proof the warm path works.
    row = measure(windows=3, iters=10)
    state["last_value"] = row["value"]
    state["heartbeat_ts"] = time.time()
    _write_state(state)
    print(f"[resident] warm figure: {row['value']:.0f} orders/s", flush=True)

    deadline = time.monotonic() + MAX_LIFETIME_S
    next_heartbeat = 0.0
    next_self_measure = time.monotonic() + SELF_MEASURE_EVERY_S
    while time.monotonic() < deadline:
        if not os.path.isdir(STATE_DIR):
            print("[resident] state dir removed; exiting", flush=True)
            return
        # Requests first: a driver-side bench.py is on a wall budget.
        reqs = sorted(n for n in os.listdir(STATE_DIR) if n.startswith("req-"))
        for name in reqs:
            nonce = name[4:]
            try:
                try:
                    row = measure(windows=4, iters=12)
                except Exception as e:  # noqa: BLE001 — requester on a
                    # wall budget: fail it in seconds (an error out-file),
                    # never leave it polling its full timeout for a reply
                    # a dead resident can't write.
                    row = {"error": f"{type(e).__name__}: {e}"}
                out_tmp = os.path.join(STATE_DIR, f"out-{nonce}.tmp")
                with open(out_tmp, "w") as f:
                    json.dump(row, f)
                os.replace(out_tmp,
                           os.path.join(STATE_DIR, f"out-{nonce}.json"))
                if "error" in row:
                    print(f"[resident] req {nonce} failed: {row['error']}",
                          flush=True)
                    raise RuntimeError(row["error"])  # die; watcher restarts
                state["last_value"] = row["value"]
                state["heartbeat_ts"] = time.time()
                _write_state(state)
                print(f"[resident] served req {nonce}: "
                      f"{row['value']:.0f} orders/s", flush=True)
            finally:
                try:
                    os.unlink(os.path.join(STATE_DIR, name))
                except OSError:
                    pass
        now = time.monotonic()
        if now >= next_heartbeat:
            # Tiny device op; only a completed sync refreshes the
            # heartbeat (a wedged tunnel hangs here and the heartbeat
            # goes stale — the correct signal).
            book, out = engine_step(cfg, book, waves[0])
            jax.block_until_ready(out)
            state["heartbeat_ts"] = time.time()
            _write_state(state)
            next_heartbeat = time.monotonic() + HEARTBEAT_EVERY_S
        if now >= next_self_measure:
            row = measure(windows=3, iters=10)
            state["last_value"] = row["value"]
            state["heartbeat_ts"] = time.time()
            _write_state(state)
            next_self_measure = time.monotonic() + SELF_MEASURE_EVERY_S
        time.sleep(1.0)
    print("[resident] lifetime reached; exiting", flush=True)


if __name__ == "__main__":
    main()
