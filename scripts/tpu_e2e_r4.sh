#!/usr/bin/env bash
# One round-4 e2e serving capture: boot the full server (both edges) on the
# default backend with a given --pipeline-inflight, drive the native C++
# pipelined load generator against both edges, leave
#   benchmarks/results/tpu_e2e_r4_native_pi<K>.json
#   benchmarks/results/tpu_e2e_r4_grpcio_pi<K>.json
# Called by benchmarks/capture_r4.py (which bounds our runtime); exits
# nonzero if the native-edge artifact wasn't produced.
#
# Usage: scripts/tpu_e2e_r4.sh <pipeline_inflight>
set -u
K="${1:?pipeline_inflight}"
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT_DIR="$REPO/benchmarks/results"
CLI="$REPO/matching_engine_tpu/native/me_client"
LOG="$OUT_DIR/r4_capture.log"
CLIENTS="${TPU_E2E_CLIENTS:-32}"
PER_CLIENT="${TPU_E2E_PER_CLIENT:-2000}"
INFLIGHT="${TPU_E2E_INFLIGHT:-8}"
BOOT_TIMEOUT="${TPU_E2E_BOOT_TIMEOUT_S:-300}"
RPC_WORKERS="${TPU_E2E_RPC_WORKERS:-256}"
WINDOW_MS="${TPU_E2E_WINDOW_MS:-2}"   # dispatch batching window
SUFFIX="${TPU_E2E_SUFFIX:-}"   # distinguishes artifact variants (e.g. _w256)

log() { echo "[$(date -u +%Y-%m-%dT%H:%M:%SZ)] [e2e pi$K] $*" >>"$LOG"; }

work=$(mktemp -d)
# 128 symbol slots: each edge's loadgen drives 64 symbols under its OWN
# prefix (N*/G*), so the second edge measures against fresh books instead
# of inheriting the first edge's resting depth (which inflated its
# book-full rejects in the pre-prefix captures).
PYTHONUNBUFFERED=1 PYTHONPATH="${PYTHONPATH:-}:$REPO" \
  python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$work/e2e.db" --symbols 128 --capacity 256 \
  --batch 16 --pipeline-inflight "$K" --gateway-addr 127.0.0.1:0 \
  --rpc-workers "$RPC_WORKERS" --window-ms "$WINDOW_MS" \
  >"$work/server.log" 2>&1 &
srv=$!
cleanup() {
  kill -TERM "$srv" 2>/dev/null
  sleep 5
  kill -9 "$srv" 2>/dev/null
}
trap cleanup EXIT

waited=0 py_port="" gw_port=""
while [ "$waited" -lt "$BOOT_TIMEOUT" ]; do
  py_port=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$work/server.log" | head -1)
  gw_port=$(sed -n 's/.*native gateway on port \([0-9]*\).*/\1/p' "$work/server.log" | head -1)
  if [ -n "$py_port" ] && [ -n "$gw_port" ]; then break; fi
  if ! kill -0 "$srv" 2>/dev/null; then
    log "server died during boot: $(tail -3 "$work/server.log" | tr '\n' ' ')"
    exit 1
  fi
  sleep 5
  waited=$((waited + 5))
done
if [ -z "$py_port" ] || [ -z "$gw_port" ]; then
  log "server boot timed out (${BOOT_TIMEOUT}s)"
  exit 1
fi
log "server up: grpcio :$py_port native :$gw_port"

ok=0
for edge_port in "native:$gw_port:N" "grpcio:$py_port:G"; do
  edge="$(echo "$edge_port" | cut -d: -f1)"
  port="$(echo "$edge_port" | cut -d: -f2)"
  prefix="$(echo "$edge_port" | cut -d: -f3)"
  out="$OUT_DIR/tpu_e2e_r4_${edge}_pi${K}${SUFFIX}.json"
  if timeout 600 "$CLI" bench "127.0.0.1:$port" "$CLIENTS" "$PER_CLIENT" 64 "$INFLIGHT" "$prefix" \
      >"$out.tmp" 2>>"$LOG"; then
    mv "$out.tmp" "$out"
    log "$edge edge: $(cat "$out")"
  else
    log "$edge edge bench failed"
    rm -f "$out.tmp"
    [ "$edge" = native ] && ok=1
  fi
done
exit "$ok"
