#!/usr/bin/env bash
# TPU-tunnel watcher: probe backend init cheaply on an interval; on the
# first healthy probe, run the north-star 4k-symbol bench once and leave
# the artifact in benchmarks/results/ (docs/BENCH_METHOD.md artifact row).
#
# Rationale: the axon tunnel wedges at jax.devices() for long stretches
# (BENCH_r02.json, VERDICT r2 weak #1). A cheap bounded probe loop catches
# the healthy windows a fixed end-of-round bench misses. The bench child is
# given a long timeout because killing it mid-compile is itself what wedges
# the tunnel; the persistent compile cache (benchmarks/bench_child.py)
# shrinks that window on reruns.
#
# Usage: scripts/tpu_watch.sh [&]   (env knobs below)
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT_DIR="$REPO/benchmarks/results"
LOG="$OUT_DIR/tpu_watch.log"
mkdir -p "$OUT_DIR"

INTERVAL="${TPU_WATCH_INTERVAL_S:-300}"
PROBE_TIMEOUT="${TPU_WATCH_PROBE_TIMEOUT_S:-75}"
BENCH_TIMEOUT="${TPU_WATCH_BENCH_TIMEOUT_S:-1500}"
SUITE_TIMEOUT="${TPU_WATCH_SUITE_TIMEOUT_S:-900}"
MAX_LOOPS="${TPU_WATCH_MAX_LOOPS:-200}"

log() { echo "[$(date -u +%Y-%m-%dT%H:%M:%SZ)] $*" >>"$LOG"; }

log "watcher start (interval=${INTERVAL}s probe_timeout=${PROBE_TIMEOUT}s)"
for _ in $(seq 1 "$MAX_LOOPS"); do
  if timeout "$PROBE_TIMEOUT" python -c \
      "import jax; d=jax.devices(); assert d; print(d)" >>"$LOG" 2>&1; then
    ts=$(date -u +%Y%m%dT%H%M%SZ)
    log "probe healthy; running 4k-symbol bench"
    out="$OUT_DIR/tpu_${ts}.json"
    if timeout "$BENCH_TIMEOUT" python "$REPO/benchmarks/bench_child.py" \
        --json-out "$out" --symbols 4096 --capacity 128 --batch 32 \
        >>"$LOG" 2>&1; then
      log "bench ok: $(cat "$out")"
      # Same healthy window: capture the suite (configs 1/2/3/5/6 — parity
      # gate + device-side rows; config 4 is tpu_e2e_watch.sh's job) so
      # the round has more than the single headline number on hardware.
      suite="$OUT_DIR/tpu_suite_${ts}.jsonl"
      log "running benchmark suite (configs 1,2,3,5,6)"
      if timeout "$SUITE_TIMEOUT" python "$REPO/benchmarks/run_all.py" \
          --configs 1,2,3,5,6 >"$suite.tmp" 2>>"$LOG"; then
        mv "$suite.tmp" "$suite"
        log "suite ok: $(wc -l <"$suite") rows"
      else
        log "suite failed rc=$? (suite tmp removed; bench artifact $out kept)"
        rm -f "$suite.tmp"
      fi
      # Batch-axis scaling evidence: the step is HBM-bound on the book
      # arrays, so doubling the batch amortizes the same traffic over 2x
      # the ops — capture batch 64/128 at the headline symbol count.
      for b in 64 128; do
        bout="$OUT_DIR/tpu_batch${b}_${ts}.json"
        if timeout "$BENCH_TIMEOUT" python "$REPO/benchmarks/bench_child.py" \
            --json-out "$bout" --symbols 4096 --capacity 128 --batch "$b" \
            >>"$LOG" 2>&1; then
          log "batch$b ok: $(cat "$bout")"
        else
          log "batch$b bench failed rc=$? (artifact removed)"
          rm -f "$bout"
        fi
      done
      exit 0
    fi
    log "bench failed rc=$? (artifact removed; will retry next interval)"
    rm -f "$out"
  else
    log "probe unhealthy (rc=$?)"
  fi
  sleep "$INTERVAL"
done
log "watcher gave up after $MAX_LOOPS loops"
exit 1
