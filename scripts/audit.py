"""Durable-store integrity audit: cross-check the orders and fills tables.

The reference treats SQLite as the system of record but ships nothing that
validates it (SURVEY.md §5.4 — even book reconstruction is only sketched).
This tool checks the arithmetic the schema implies, per order:

  filled_as_taker + filled_as_maker == quantity - remaining_quantity
  status consistent with remaining (FILLED <=> remaining 0 with fills,
  CANCELED/REJECTED orders hold no remainder liability, NEW/PARTIAL rest)
  every fill references two known orders on opposite sides

With `--dropcopy FILE` (a JSON-lines capture from `client audit
--capture`, taken from seq 1 over the store's whole life), the audit
additionally cross-checks the FEED against the DB — the same invariant
vocabulary the online InvariantAuditor uses, applied offline:

  every fill in the store appears in the drop-copy and vice versa
  (order_id/counter_order_id/price/quantity multisets are equal)
  every order's final (status, remaining, quantity) per the drop-copy's
  last record equals its store row, and the order sets are equal

Exit 0 and a JSON summary line when clean; exit 1 with per-order violation
lines otherwise.

Usage: python scripts/audit.py <db_path> [--dropcopy FILE]
"""

from __future__ import annotations

import json
import sqlite3
import sys

NEW, PARTIALLY_FILLED, FILLED, CANCELED, REJECTED = range(5)


def audit(db_path: str, summary_out: dict | None = None) -> list[str]:
    conn = sqlite3.connect(db_path)
    orders = {
        row[0]: {"client": row[1], "symbol": row[2], "side": row[3],
                 "otype": row[4], "qty": row[5], "remaining": row[6],
                 "status": row[7]}
        for row in conn.execute(
            "SELECT order_id, client_id, symbol, side, order_type, quantity, "
            "remaining_quantity, status FROM orders")
    }
    fills = conn.execute(
        "SELECT order_id, counter_order_id, price, quantity FROM fills").fetchall()
    # Durability-gap ledger (absent on pre-recon databases): per order, the
    # quantity of fill records the store has ACKNOWLEDGED losing (kernel
    # max_fills overflow repairs, utils/checkpoint.py). Audited arithmetic
    # stays exact: table fills + acknowledged-lost must equal the executed
    # quantity. Unexplained gaps remain violations.
    recon_lost: dict[str, int] = {}
    try:
        for oid, lost in conn.execute(
                "SELECT order_id, SUM(lost_quantity) FROM recon "
                "WHERE kind = 'fills_lost' GROUP BY order_id"):
            recon_lost[oid] = int(lost)
    except sqlite3.OperationalError:
        pass  # no recon table in this database
    conn.close()

    problems: list[str] = []
    filled_total: dict[str, int] = {oid: 0 for oid in orders}
    for oid, lost in recon_lost.items():
        if oid in filled_total:
            filled_total[oid] += lost
        else:
            problems.append(f"recon references unknown order: {oid}")

    for taker_id, maker_id, price, qty in fills:
        t, m = orders.get(taker_id), orders.get(maker_id)
        if t is None or m is None:
            problems.append(f"fill references unknown order: {taker_id}/{maker_id}")
            continue
        if t["side"] == m["side"]:
            problems.append(f"fill pairs same-side orders: {taker_id}/{maker_id}")
        if t["symbol"] != m["symbol"]:
            problems.append(f"fill crosses symbols: {taker_id}/{maker_id}")
        if qty <= 0:
            problems.append(f"non-positive fill quantity: {taker_id}/{maker_id}")
        if m["status"] == REJECTED:
            # Only a TAKER can end REJECTED with fills (crossing LIMIT whose
            # remainder found the book side full). A rejected order never
            # rests, so it can never be a fill's maker.
            problems.append(f"fill has REJECTED maker: {taker_id}/{maker_id}")
        filled_total[taker_id] += qty
        filled_total[maker_id] += qty

    for oid, o in orders.items():
        filled = filled_total[oid]
        if o["status"] == REJECTED:
            # May carry taker fills (partial-fill-then-capacity-reject,
            # engine/kernel.py submit_status); storage persists the true
            # rejected remainder, so the fill arithmetic still must hold.
            if filled != o["qty"] - o["remaining"]:
                problems.append(
                    f"{oid}: REJECTED fills {filled} != quantity {o['qty']} "
                    f"- remaining {o['remaining']}")
            continue
        if o["status"] == CANCELED:
            # Canceled orders may have partial fills, but hold no liability.
            if filled > o["qty"]:
                problems.append(f"{oid}: overfilled ({filled} > {o['qty']})")
            continue
        if filled != o["qty"] - o["remaining"]:
            problems.append(
                f"{oid}: fills {filled} != quantity {o['qty']} - "
                f"remaining {o['remaining']}")
        if o["status"] == FILLED and o["remaining"] != 0:
            problems.append(f"{oid}: FILLED but remaining={o['remaining']}")
        if o["status"] == NEW and filled != 0:
            problems.append(f"{oid}: NEW but has fills")
        if o["status"] == PARTIALLY_FILLED and (filled == 0 or o["remaining"] == 0):
            problems.append(f"{oid}: PARTIALLY_FILLED but filled={filled} "
                            f"remaining={o['remaining']}")

    summary = {
        "orders": len(orders),
        "fills": len(fills),
        "violations": len(problems),
    }
    if summary_out is None:
        print(json.dumps(summary))
    else:  # --dropcopy mode merges everything into ONE summary line
        summary_out.update(summary)
    return problems


def _load_dropcopy(path: str):
    """Replay a capture's records (in seq/line order) into the final
    per-order view + the fills multiset — the offline twin of the online
    auditor's shadow state."""
    orders: dict[str, dict] = {}
    fills: list[tuple] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            kind = r.get("kind")
            if kind == "order":
                orders[r["order_id"]] = {
                    "status": r["status"], "remaining": r["remaining"],
                    "qty": r["quantity"], "client": r.get("client_id", ""),
                    "symbol": r.get("symbol", ""), "side": r.get("side", 0),
                }
            elif kind == "update":
                o = orders.get(r["order_id"])
                if o is None:
                    continue  # pre-capture order: can't be cross-checked
                o["status"] = r["status"]
                o["remaining"] = r["remaining"]
                if r.get("quantity"):  # amend carries the reduced quantity
                    o["qty"] = r["quantity"]
            elif kind == "fill":
                fills.append((r["order_id"], r["counter_order_id"],
                              r["fill_price"], r["fill_quantity"]))
    return orders, fills


def cross_check_dropcopy(db_path: str, capture_path: str,
                         summary_out: dict | None = None) -> list[str]:
    """The feed<->store reconciliation: orders/fills/status multisets of
    the drop-copy capture against the durable tables. Requires a capture
    spanning the store's whole life (fresh db + `client audit --capture`
    from boot) — a partial capture reports the store's surplus as
    violations, which is the point for soak/CI use."""
    from collections import Counter

    cap_orders, cap_fills = _load_dropcopy(capture_path)
    conn = sqlite3.connect(db_path)
    db_orders = {
        row[0]: {"status": row[3], "remaining": row[2], "qty": row[1]}
        for row in conn.execute(
            "SELECT order_id, quantity, remaining_quantity, status "
            "FROM orders")
    }
    db_fills = conn.execute(
        "SELECT order_id, counter_order_id, price, quantity "
        "FROM fills").fetchall()
    conn.close()

    problems: list[str] = []
    cf, df = Counter(cap_fills), Counter(tuple(f) for f in db_fills)
    for f, n in (cf - df).items():
        problems.append(f"dropcopy fill absent from store x{n}: {f}")
    for f, n in (df - cf).items():
        problems.append(f"store fill absent from dropcopy x{n}: {f}")
    for oid in sorted(set(cap_orders) - set(db_orders)):
        problems.append(f"dropcopy order absent from store: {oid}")
    for oid in sorted(set(db_orders) - set(cap_orders)):
        problems.append(f"store order absent from dropcopy: {oid}")
    for oid in sorted(set(cap_orders) & set(db_orders)):
        c, d = cap_orders[oid], db_orders[oid]
        if (c["status"], c["remaining"], c["qty"]) != \
                (d["status"], d["remaining"], d["qty"]):
            problems.append(
                f"{oid}: dropcopy final (status {c['status']}, remaining "
                f"{c['remaining']}, qty {c['qty']}) != store (status "
                f"{d['status']}, remaining {d['remaining']}, qty "
                f"{d['qty']})")
    summary = {
        "dropcopy_orders": len(cap_orders),
        "dropcopy_fills": len(cap_fills),
        "store_orders": len(db_orders),
        "store_fills": len(db_fills),
        "cross_violations": len(problems),
    }
    if summary_out is None:
        print(json.dumps(summary))
    else:
        summary_out.update(summary)
    return problems


def main() -> int:
    argv = sys.argv[1:]
    dropcopy = None
    if "--dropcopy" in argv:
        i = argv.index("--dropcopy")
        try:
            dropcopy = argv[i + 1]
        except IndexError:
            print("usage: audit.py <db_path> [--dropcopy FILE]",
                  file=sys.stderr)
            return 2
        del argv[i:i + 2]
    if len(argv) != 1:
        print("usage: audit.py <db_path> [--dropcopy FILE]", file=sys.stderr)
        return 2
    if dropcopy is None:
        problems = audit(argv[0])
    else:
        # One merged JSON summary line — the documented stdout contract
        # holds whether or not the cross-check runs.
        summary: dict = {}
        problems = audit(argv[0], summary_out=summary)
        problems += cross_check_dropcopy(argv[0], dropcopy,
                                         summary_out=summary)
        print(json.dumps(summary))
    for p in problems:
        print(f"[audit] {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
