"""Durable-store integrity audit: cross-check the orders and fills tables.

The reference treats SQLite as the system of record but ships nothing that
validates it (SURVEY.md §5.4 — even book reconstruction is only sketched).
This tool checks the arithmetic the schema implies, per order:

  filled_as_taker + filled_as_maker == quantity - remaining_quantity
  status consistent with remaining (FILLED <=> remaining 0 with fills,
  CANCELED/REJECTED orders hold no remainder liability, NEW/PARTIAL rest)
  every fill references two known orders on opposite sides

Exit 0 and a JSON summary line when clean; exit 1 with per-order violation
lines otherwise.

Usage: python scripts/audit.py <db_path>
"""

from __future__ import annotations

import json
import sqlite3
import sys

NEW, PARTIALLY_FILLED, FILLED, CANCELED, REJECTED = range(5)


def audit(db_path: str) -> list[str]:
    conn = sqlite3.connect(db_path)
    orders = {
        row[0]: {"client": row[1], "symbol": row[2], "side": row[3],
                 "otype": row[4], "qty": row[5], "remaining": row[6],
                 "status": row[7]}
        for row in conn.execute(
            "SELECT order_id, client_id, symbol, side, order_type, quantity, "
            "remaining_quantity, status FROM orders")
    }
    fills = conn.execute(
        "SELECT order_id, counter_order_id, price, quantity FROM fills").fetchall()
    # Durability-gap ledger (absent on pre-recon databases): per order, the
    # quantity of fill records the store has ACKNOWLEDGED losing (kernel
    # max_fills overflow repairs, utils/checkpoint.py). Audited arithmetic
    # stays exact: table fills + acknowledged-lost must equal the executed
    # quantity. Unexplained gaps remain violations.
    recon_lost: dict[str, int] = {}
    try:
        for oid, lost in conn.execute(
                "SELECT order_id, SUM(lost_quantity) FROM recon "
                "WHERE kind = 'fills_lost' GROUP BY order_id"):
            recon_lost[oid] = int(lost)
    except sqlite3.OperationalError:
        pass  # no recon table in this database
    conn.close()

    problems: list[str] = []
    filled_total: dict[str, int] = {oid: 0 for oid in orders}
    for oid, lost in recon_lost.items():
        if oid in filled_total:
            filled_total[oid] += lost
        else:
            problems.append(f"recon references unknown order: {oid}")

    for taker_id, maker_id, price, qty in fills:
        t, m = orders.get(taker_id), orders.get(maker_id)
        if t is None or m is None:
            problems.append(f"fill references unknown order: {taker_id}/{maker_id}")
            continue
        if t["side"] == m["side"]:
            problems.append(f"fill pairs same-side orders: {taker_id}/{maker_id}")
        if t["symbol"] != m["symbol"]:
            problems.append(f"fill crosses symbols: {taker_id}/{maker_id}")
        if qty <= 0:
            problems.append(f"non-positive fill quantity: {taker_id}/{maker_id}")
        if m["status"] == REJECTED:
            # Only a TAKER can end REJECTED with fills (crossing LIMIT whose
            # remainder found the book side full). A rejected order never
            # rests, so it can never be a fill's maker.
            problems.append(f"fill has REJECTED maker: {taker_id}/{maker_id}")
        filled_total[taker_id] += qty
        filled_total[maker_id] += qty

    for oid, o in orders.items():
        filled = filled_total[oid]
        if o["status"] == REJECTED:
            # May carry taker fills (partial-fill-then-capacity-reject,
            # engine/kernel.py submit_status); storage persists the true
            # rejected remainder, so the fill arithmetic still must hold.
            if filled != o["qty"] - o["remaining"]:
                problems.append(
                    f"{oid}: REJECTED fills {filled} != quantity {o['qty']} "
                    f"- remaining {o['remaining']}")
            continue
        if o["status"] == CANCELED:
            # Canceled orders may have partial fills, but hold no liability.
            if filled > o["qty"]:
                problems.append(f"{oid}: overfilled ({filled} > {o['qty']})")
            continue
        if filled != o["qty"] - o["remaining"]:
            problems.append(
                f"{oid}: fills {filled} != quantity {o['qty']} - "
                f"remaining {o['remaining']}")
        if o["status"] == FILLED and o["remaining"] != 0:
            problems.append(f"{oid}: FILLED but remaining={o['remaining']}")
        if o["status"] == NEW and filled != 0:
            problems.append(f"{oid}: NEW but has fills")
        if o["status"] == PARTIALLY_FILLED and (filled == 0 or o["remaining"] == 0):
            problems.append(f"{oid}: PARTIALLY_FILLED but filled={filled} "
                            f"remaining={o['remaining']}")

    summary = {
        "orders": len(orders),
        "fills": len(fills),
        "violations": len(problems),
    }
    print(json.dumps(summary))
    return problems


def main() -> int:
    if len(sys.argv) != 2:
        print("usage: audit.py <db_path>", file=sys.stderr)
        return 2
    problems = audit(sys.argv[1])
    for p in problems:
        print(f"[audit] {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
