#!/usr/bin/env python3
"""Regenerate matching_engine_pb2.py WITHOUT protoc (descriptor surgery).

This environment ships the protobuf runtime but not grpcio-tools/protoc
(proto/__init__.py), so additive wire-contract changes cannot go through
codegen. Instead this script:

1. reads the serialized FileDescriptorProto out of the checked-in pb2
   module (via ast — no import, so the descriptor pool stays clean),
2. applies the declarative ADDITIVE_FIELDS below (idempotent: fields
   already present are skipped),
3. re-serializes and emits a pb2 module in the same builder style,
   recomputing every _serialized_start/_end offset by locating each
   descriptor's serialized bytes inside the file serialization (the
   sub-message serialization of a descriptor is a contiguous slice of
   its parent's), and
4. verifies the result in a SUBPROCESS (a fresh descriptor pool) by
   importing the new module and round-tripping each added field.

matching_engine.proto remains the human-readable source of truth — keep
it in sync by hand; this script exists because the bytes, not the text,
are what the runtime loads. Only additive edits are supported: renames
or removals would break wire compatibility and are refused by design.
"""

from __future__ import annotations

import ast
import os
import subprocess
import sys

from google.protobuf import descriptor_pb2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PB2 = os.path.join(REPO, "matching_engine_tpu", "proto",
                   "matching_engine_pb2.py")

F = descriptor_pb2.FieldDescriptorProto

# (message, field name, field number, type) — additive only.
ADDITIVE_FIELDS = [
    # Sequenced feed (feed/): per-(channel,key) monotonic event sequence
    # stamped at dispatch-publish time; 0 = unsequenced (legacy server).
    ("MarketDataUpdate", "seq", 7, F.TYPE_UINT64),
    ("OrderUpdate", "seq", 9, F.TYPE_UINT64),
    # Reconnect/recovery: replay stored events with seq > resume_from_seq
    # from the retransmission store before going live. 0 = live-only.
    ("MarketDataRequest", "resume_from_seq", 2, F.TYPE_UINT64),
    ("OrderUpdatesRequest", "resume_from_seq", 2, F.TYPE_UINT64),
    # Conflated latest-state channel for slow L2 consumers: intermediate
    # states may be skipped (seq jumps are expected, not gaps).
    ("MarketDataRequest", "conflate", 3, F.TYPE_BOOL),
    # Boot epoch of the seq domain: seqs restart at 1 every server boot,
    # so a resume cursor is only meaningful within one epoch. Events
    # carry the epoch; resume requests echo it so the server (and the
    # client, on the events) can distinguish a same-epoch replay from a
    # cross-restart rebase even when the new head has outrun the stale
    # cursor. 0 = unknown/unsequenced.
    ("MarketDataUpdate", "feed_epoch", 8, F.TYPE_UINT64),
    ("OrderUpdate", "feed_epoch", 10, F.TYPE_UINT64),
    ("MarketDataRequest", "feed_epoch", 4, F.TYPE_UINT64),
    ("OrderUpdatesRequest", "feed_epoch", 3, F.TYPE_UINT64),
    # Drop-copy audit stream (matching_engine_tpu/audit/): lifecycle
    # records ride OrderUpdate on the sequenced `audit` channel
    # (StreamOrderUpdates with the reserved client_id). audit_kind != 0
    # marks a drop-copy record: 1 = order row (submit decoded; carries
    # the original quantity in audit_quantity and side/otype), 2 = status
    # update row (audit_quantity = new quantity on amends), 3 = fill row
    # (order_id = aggressor, counter_order_id = maker, fill_price/
    # fill_quantity = the execution). The envelope names the dispatch the
    # record was decoded from: trace_id (flight-recorder/trace-export
    # correlation), dispatch shape/waves, and the dispatch's oldest-op
    # edge-ingress wall clock in µs (0 when the edge recorded none).
    ("OrderUpdate", "audit_kind", 11, F.TYPE_UINT32),
    ("OrderUpdate", "trace_id", 12, F.TYPE_UINT64),
    ("OrderUpdate", "dispatch_shape", 13, F.TYPE_STRING),
    ("OrderUpdate", "dispatch_waves", 14, F.TYPE_UINT32),
    ("OrderUpdate", "counter_order_id", 15, F.TYPE_STRING),
    ("OrderUpdate", "ingress_ts_us", 16, F.TYPE_UINT64),
    ("OrderUpdate", "audit_side", 17, F.TYPE_UINT32),
    ("OrderUpdate", "audit_otype", 18, F.TYPE_UINT32),
    ("OrderUpdate", "audit_quantity", 19, F.TYPE_INT64),
    # Warm-standby replication (matching_engine_tpu/replication/): op-log
    # records ride OrderUpdate on the sequenced `oplog` channel
    # (StreamOrderUpdates with the reserved __oplog__ client_id).
    # oplog_kind != 0 marks one: 1 = dispatch (oplog_ops carries the
    # dispatch's packed flat op-records — domain/oprec.py wire, submits
    # with their primary-assigned order ids — oplog_count the record
    # count, oplog_lane the serving lane, trace_id the primary dispatch's
    # trace id for attestation alignment), 2 = heartbeat (empty payload;
    # the standby's liveness/lag signal).
    ("OrderUpdate", "oplog_kind", 20, F.TYPE_UINT32),
    ("OrderUpdate", "oplog_ops", 21, F.TYPE_BYTES),
    ("OrderUpdate", "oplog_count", 22, F.TYPE_UINT32),
    ("OrderUpdate", "oplog_lane", 23, F.TYPE_UINT32),
    # Scenario/workload replay (sim/scenarios.py): (re)open the venue-wide
    # auction call period over RPC WITHOUT uncrossing — submits rest
    # unmatched until a later all-symbols RunAuction clears them. Before
    # this field a call period could only open at boot (--auction-open),
    # so a recorded auction-day workload (open -> continuous -> halt ->
    # reopen -> close) could not replay through a live server. symbol
    # must be empty (a call period is venue-wide, the --auction-open
    # rule).
    ("AuctionRequest", "open_call", 2, F.TYPE_BOOL),
]

# Whole new messages (name, [(field, number, type[, label])]) — additive:
# a message already present is field-merged through the same rules.
ADDITIVE_MESSAGES = [
    # Batch-native edge (SubmitOrderBatch): `ops` carries packed flat
    # binary op-records (domain/oprec.py wire — magic + fixed 384-byte
    # records); the response reports per-op status POSITIONALLY as
    # parallel arrays (ok/order_id/error/remaining align with the
    # request's record order) so one bad op never fails the batch and
    # the response costs O(1) proto messages, not one per op.
    ("OrderBatchRequest", [
        ("ops", 1, F.TYPE_BYTES),
    ]),
    ("OrderBatchResponse", [
        # False only when the PAYLOAD was undecodable (bad magic /
        # truncated / over the cap) — per-op rejects ride the arrays.
        ("success", 1, F.TYPE_BOOL),
        ("error_message", 2, F.TYPE_STRING),
        ("ok", 3, F.TYPE_BOOL, F.LABEL_REPEATED),
        ("order_id", 4, F.TYPE_STRING, F.LABEL_REPEATED),
        ("error", 5, F.TYPE_STRING, F.LABEL_REPEATED),
        ("remaining", 6, F.TYPE_INT64, F.LABEL_REPEATED),
    ]),
    # Warm-standby promotion (replication/standby.py): flips a --standby
    # replica into the serving primary — bumps the feed epoch, re-seeds
    # the per-residue-class OID floors from the durable store, and opens
    # the mutation RPCs. Application-level failure semantics match
    # SubmitOrder (success=false + error_message, gRPC OK).
    ("PromoteRequest", []),
    ("PromoteResponse", [
        ("success", 1, F.TYPE_BOOL),
        ("error_message", 2, F.TYPE_STRING),
        # The promoted server's NEW feed epoch: clients carrying cursors
        # from the dead primary (or the pre-promotion replica) rebase.
        ("feed_epoch", 3, F.TYPE_UINT64),
    ]),
]

# New service methods (service, method, input message, output message
# [, streaming]) — additive; an unknown method on an old server answers
# UNIMPLEMENTED. `streaming` is "client_streaming" / "server_streaming"
# (or both, comma-separated); absent = unary-unary.
ADDITIVE_METHODS = [
    ("MatchingEngine", "SubmitOrderBatch",
     "OrderBatchRequest", "OrderBatchResponse"),
    ("MatchingEngine", "Promote", "PromoteRequest", "PromoteResponse"),
    # Zero-copy ingress (ROADMAP Open item 3b): client-streaming ingest
    # for remote flow that can't batch client-side — chunks of the same
    # oprec payload, one positional OrderBatchResponse for the stream.
    ("MatchingEngine", "SubmitOrderStream",
     "OrderBatchRequest", "OrderBatchResponse", "client_streaming"),
]

HEADER = '''\
# -*- coding: utf-8 -*-
# Generated by scripts/regen_pb2.py (descriptor surgery; this environment
# has no protoc). Source of truth: matching_engine.proto.  DO NOT EDIT!
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'matching_engine_pb2', globals())
if _descriptor._USE_C_DESCRIPTORS == False:

  DESCRIPTOR._options = None
  _METRICSRESPONSE_GAUGESENTRY._options = None
  _METRICSRESPONSE_GAUGESENTRY._serialized_options = b'8\\001'
  _METRICSRESPONSE_COUNTERSENTRY._options = None
  _METRICSRESPONSE_COUNTERSENTRY._serialized_options = b'8\\001'
{offsets}
# @@protoc_insertion_point(module_scope)
'''


def read_serialized_pb(path: str) -> bytes:
    """Extract the AddSerializedFile(b'...') literal from the pb2 source."""
    tree = ast.parse(open(path).read())
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "AddSerializedFile"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, bytes)):
            return node.args[0].value
    raise SystemExit(f"no AddSerializedFile bytes literal in {path}")


def _add_field(msg, name, number, ftype, label, added) -> None:
    existing = {f.name: f for f in msg.field}
    if name in existing:
        if (existing[name].number != number or existing[name].type != ftype
                or existing[name].label != label):
            raise SystemExit(
                f"{msg.name}.{name} exists with different number/type/label "
                f"— refusing a non-additive edit")
        return
    if any(f.number == number for f in msg.field):
        raise SystemExit(f"{msg.name} field number {number} is taken")
    f = msg.field.add()
    f.name = name
    f.number = number
    f.label = label
    f.type = ftype
    added.append((msg.name, name))


def apply_fields(fdp: descriptor_pb2.FileDescriptorProto) -> list:
    msgs = {m.name: m for m in fdp.message_type}
    added = []
    for msg_name, name, number, ftype in ADDITIVE_FIELDS:
        _add_field(msgs[msg_name], name, number, ftype, F.LABEL_OPTIONAL,
                   added)
    for msg_name, fields in ADDITIVE_MESSAGES:
        msg = msgs.get(msg_name)
        if msg is None:
            msg = fdp.message_type.add()
            msg.name = msg_name
            msgs[msg_name] = msg
            added.append((msg_name, "(message)"))
        for spec in fields:
            name, number, ftype = spec[0], spec[1], spec[2]
            label = spec[3] if len(spec) > 3 else F.LABEL_OPTIONAL
            _add_field(msg, name, number, ftype, label, added)
    for spec in ADDITIVE_METHODS:
        svc_name, method, in_msg, out_msg = spec[:4]
        streaming = spec[4] if len(spec) > 4 else ""
        client_streaming = "client_streaming" in streaming
        server_streaming = "server_streaming" in streaming
        svc = next((s for s in fdp.service if s.name == svc_name), None)
        if svc is None:
            raise SystemExit(f"service {svc_name} not found")
        pkg = fdp.package
        in_t, out_t = f".{pkg}.{in_msg}", f".{pkg}.{out_msg}"
        existing = next((m for m in svc.method if m.name == method), None)
        if existing is not None:
            if (existing.input_type != in_t or existing.output_type != out_t
                    or existing.client_streaming != client_streaming
                    or existing.server_streaming != server_streaming):
                raise SystemExit(
                    f"{svc_name}.{method} exists with different types — "
                    f"refusing a non-additive edit")
            continue
        m = svc.method.add()
        m.name = method
        m.input_type = in_t
        m.output_type = out_t
        if client_streaming:
            m.client_streaming = True
        if server_streaming:
            m.server_streaming = True
        added.append((svc_name, method))
    return added


def offset_lines(fdp: descriptor_pb2.FileDescriptorProto,
                 blob: bytes) -> str:
    """Recompute the pure-python _serialized_start/_end attributes by
    locating each descriptor's serialization inside the file's. Enum
    offsets come first in the generated block (protoc's ordering)."""

    def locate(py_name: str, sub: bytes, out: list) -> None:
        idx = blob.find(sub)
        if idx < 0:
            raise SystemExit(f"{py_name}: serialized bytes not found")
        if blob.find(sub, idx + 1) >= 0:
            raise SystemExit(f"{py_name}: serialized bytes ambiguous")
        out.append(f"  _{py_name}._serialized_start={idx}")
        out.append(f"  _{py_name}._serialized_end={idx + len(sub)}")

    def walk_msg(prefix: str, msg, enums_out: list, msgs_out: list) -> None:
        py = (prefix + "_" if prefix else "") + msg.name.upper()
        locate(py, msg.SerializeToString(), msgs_out)
        for nested in msg.nested_type:
            walk_msg(py, nested, enums_out, msgs_out)
        for enum in msg.enum_type:
            locate(py + "_" + enum.name.upper(), enum.SerializeToString(),
                   enums_out)

    enums, msgs = [], []
    for enum in fdp.enum_type:
        locate(enum.name.upper(), enum.SerializeToString(), enums)
    for msg in fdp.message_type:
        walk_msg("", msg, enums, msgs)
    for svc in fdp.service:
        locate(svc.name.upper(), svc.SerializeToString(), msgs)
    return "\n".join(enums + msgs)


VERIFY = """
import sys
sys.path.insert(0, {repo!r})
from matching_engine_tpu.proto import pb2
u = pb2.MarketDataUpdate(symbol="S", best_bid=1, seq=7)
assert pb2.MarketDataUpdate.FromString(u.SerializeToString()).seq == 7
o = pb2.OrderUpdate(order_id="OID-1", seq=9)
assert pb2.OrderUpdate.FromString(o.SerializeToString()).seq == 9
r = pb2.MarketDataRequest(symbol="S", resume_from_seq=5, conflate=True)
r2 = pb2.MarketDataRequest.FromString(r.SerializeToString())
assert r2.resume_from_seq == 5 and r2.conflate
q = pb2.OrderUpdatesRequest(client_id="c", resume_from_seq=3)
assert pb2.OrderUpdatesRequest.FromString(q.SerializeToString()).resume_from_seq == 3
e = pb2.OrderUpdate(order_id="OID-2", seq=4, feed_epoch=77)
assert pb2.OrderUpdate.FromString(e.SerializeToString()).feed_epoch == 77
assert pb2.MarketDataRequest.FromString(
    pb2.MarketDataRequest(feed_epoch=88).SerializeToString()).feed_epoch == 88
b = pb2.OrderBatchRequest(ops=b"MEOPREC1" + b"x" * 8)
assert pb2.OrderBatchRequest.FromString(b.SerializeToString()).ops[:8] == b"MEOPREC1"
br = pb2.OrderBatchResponse(success=True, ok=[True, False],
                            order_id=["OID-1", ""], error=["", "nope"],
                            remaining=[0, 3])
br2 = pb2.OrderBatchResponse.FromString(br.SerializeToString())
assert list(br2.ok) == [True, False] and list(br2.remaining) == [0, 3]
assert list(br2.order_id) == ["OID-1", ""] and br2.success
a = pb2.OrderUpdate(order_id="OID-3", audit_kind=3, trace_id=12,
                    dispatch_shape="mega", dispatch_waves=4,
                    counter_order_id="OID-2", ingress_ts_us=99,
                    audit_side=1, audit_otype=0, audit_quantity=5)
a2 = pb2.OrderUpdate.FromString(a.SerializeToString())
assert (a2.audit_kind == 3 and a2.trace_id == 12
        and a2.dispatch_shape == "mega" and a2.dispatch_waves == 4
        and a2.counter_order_id == "OID-2" and a2.ingress_ts_us == 99
        and a2.audit_side == 1 and a2.audit_quantity == 5)
g = pb2.OrderUpdate(oplog_kind=1, oplog_ops=b"MEOPREC1" + b"r" * 8,
                    oplog_count=3, oplog_lane=2, trace_id=44, seq=5)
g2 = pb2.OrderUpdate.FromString(g.SerializeToString())
assert (g2.oplog_kind == 1 and g2.oplog_ops[:8] == b"MEOPREC1"
        and g2.oplog_count == 3 and g2.oplog_lane == 2 and g2.trace_id == 44)
pr = pb2.PromoteResponse(success=True, feed_epoch=123)
pr2 = pb2.PromoteResponse.FromString(pr.SerializeToString())
assert pr2.success and pr2.feed_epoch == 123
assert pb2.PromoteRequest.FromString(
    pb2.PromoteRequest().SerializeToString()) is not None
# Old readers must still parse new writers (additive compatibility).
assert pb2.OrderRequest.FromString(
    pb2.OrderRequest(client_id="c", symbol="S").SerializeToString()
).symbol == "S"
print("pb2 verify OK")
"""


def main() -> int:
    fdp = descriptor_pb2.FileDescriptorProto.FromString(
        read_serialized_pb(PB2))
    added = apply_fields(fdp)
    blob = fdp.SerializeToString()
    content = HEADER.format(blob=blob, offsets=offset_lines(fdp, blob))
    with open(PB2, "w") as f:
        f.write(content)
    print(f"wrote {PB2} (+{len(added)} fields: "
          f"{', '.join('.'.join(a) for a in added) or 'none — up to date'})")
    r = subprocess.run([sys.executable, "-c", VERIFY.format(repo=REPO)])
    if r.returncode != 0:
        raise SystemExit("verification failed — regenerated pb2 is broken")
    return 0


if __name__ == "__main__":
    sys.exit(main())
