#!/usr/bin/env bash
# The one-shot static gate: every invariant checker this repo ships,
# chained, exit nonzero on any violation.
#
#   scripts/check.sh [--json FILE] [--sanitize]
#
# Steps (each independently skippable only by missing toolchain, never
# silently):
#   1. the static-analysis suite (matching_engine_tpu/analysis/), all
#      seven analyzers: lock-order vs the declared hierarchy, the
#      Eraser-style lockset race detector, the determinism-taint
#      analyzer over the replay surfaces, the four-way order-lifecycle
#      equivalence checker, jit-purity/donation, py<->C++ ABI layouts,
#      metric/flag <-> docs coherence
#   2. docs/CONCURRENCY.md freshness (generated from the same graphs)
#   3. the tier-1 doc-lint (tests/test_obs.py) — the original
#      metric-table drift guard the suite generalizes
#   4. ruff, pinned in pyproject.toml and scoped to matching_engine_tpu/,
#      tests/, benchmarks/, and scripts/ (skipped with a notice when the
#      image lacks ruff), plus a compileall syntax gate over the same
#      trees that always runs
#   5. [--sanitize] the ASan/UBSan codec-fuzz smokes and the TSan
#      concurrent ring/lane-build smoke
#      (tests/test_build_native.py; needs g++ + sanitizer runtimes)
#
# --json FILE writes a machine-readable summary artifact (per-step
# status + every analyzer violation) for CI to archive.
set -uo pipefail
cd "$(dirname "$0")/.."

JSON_OUT=""
SANITIZE=0
while [ $# -gt 0 ]; do
  case "$1" in
    --json) shift; JSON_OUT="$1" ;;
    --sanitize) SANITIZE=1 ;;
    *) echo "unknown arg: $1" >&2; exit 2 ;;
  esac
  shift
done

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
FAIL=0
declare -A STATUS
ANALYSIS_JSON="$(mktemp /tmp/me_analysis.XXXXXX.json)"
trap 'rm -f "$ANALYSIS_JSON"' EXIT

step() {  # step <name> <cmd...>
  local name="$1"; shift
  echo "==> $name"
  if "$@"; then
    STATUS[$name]=pass
  else
    STATUS[$name]=fail
    FAIL=1
  fi
}

step analysis python -m matching_engine_tpu.analysis run \
  --json "$ANALYSIS_JSON"
step concurrency-doc python -m matching_engine_tpu.analysis \
  render-concurrency --check
step doc-lint python -m pytest tests/test_obs.py \
  -k operations_doc -q -p no:cacheprovider
step syntax python -m compileall -q matching_engine_tpu tests \
  benchmarks scripts

if command -v ruff >/dev/null; then
  step ruff ruff check matching_engine_tpu tests benchmarks scripts
else
  echo "==> ruff: not in this image, skipping (pyproject.toml pins the"
  echo "    rule set; any image with ruff runs the identical gate)"
  STATUS[ruff]=skipped
fi

if [ "$SANITIZE" = 1 ]; then
  if command -v g++ >/dev/null && command -v make >/dev/null; then
    step sanitizer-smoke python -m pytest tests/test_build_native.py \
      -k sanitized -q -p no:cacheprovider
  else
    echo "==> sanitizer-smoke: no C++ toolchain, skipping"
    STATUS[sanitizer-smoke]=skipped
  fi
fi

if [ -n "$JSON_OUT" ]; then
  STATUS_DUMP=""
  for k in "${!STATUS[@]}"; do STATUS_DUMP+="$k=${STATUS[$k]} "; done
  STEPS="$STATUS_DUMP" ANALYSIS="$ANALYSIS_JSON" OUT="$JSON_OUT" \
  python - <<'EOF'
import json, os
steps = dict(kv.split("=") for kv in os.environ["STEPS"].split())
with open(os.environ["ANALYSIS"]) as f:
    analysis = json.load(f)
with open(os.environ["OUT"], "w") as f:
    json.dump({"steps": steps, "analysis": analysis,
               "ok": all(v != "fail" for v in steps.values())},
              f, indent=2, sort_keys=True)
print(f"summary: {os.environ['OUT']}")
EOF
fi

if [ "$FAIL" = 0 ]; then
  echo "check.sh: all gates green"
else
  echo "check.sh: FAILED (see above)" >&2
fi
exit $FAIL
