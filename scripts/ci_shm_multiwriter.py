"""CI smoke for the multi-producer shm ring (ring v2): two registered
`client submit-shm` writer processes replay disjoint submit-only slices
through a LIVE server while a third registered writer is SIGKILLed
mid-record, and the run must show

  - every record both writers pushed admitted exactly once
    (me_ingress_records == the summed pushes; the victim's torn claim is
    recovered, never admitted);
  - per-writer attribution: me_ingress_writer<i>_records equals each
    writer's own push count, on distinct non-zero lanes;
  - at least one torn recovery (the kill really left a claim behind);
  - each client's summary shows its own acks complete (pushed == ops and
    no missing responses — the submit-shm exit code covers that).

Writes the JSON artifact `--out` (archived by CI) and exits non-zero on
any violation. Run locally: python scripts/ci_shm_multiwriter.py --out /tmp/x.json
"""

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Claims one slot, writes half a record, then parks: the parent SIGKILLs
# it mid-record, so the poller must attribute the torn claim to this
# registered-but-dead lane and recover it.
_VICTIM = r"""
import sys, time
from matching_engine_tpu import native as me
ring = me.ShmRing(sys.argv[1])
wid = ring.register_writer()
seq = ring.claim(1)
assert seq >= 0, seq
ring.write_slot(seq, b"\x01" * 100)
open(sys.argv[2], "w").write(f"{wid} {seq}")
time.sleep(120)
"""


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--ops", type=int, default=1024,
                    help="submit records per writer")
    args = ap.parse_args()

    from matching_engine_tpu import native as me_native
    from matching_engine_tpu.domain import oprec

    if not me_native.available():
        print("[shm-mw-smoke] FATAL: native runtime not built",
              file=sys.stderr)
        return 1

    tmpd = tempfile.mkdtemp(prefix="ci_shm_mw_")
    ring_path = os.path.join(tmpd, "ring")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))

    # Submit-only maker/taker flow (books stay shallow), split in two
    # disjoint halves — one per writer.
    rows = []
    for i in range(2 * args.ops):
        maker = (i // 8) % 2 == 0
        rows.append((oprec.OPREC_SUBMIT, 2 if maker else 1, 0, 10_000, 5,
                     f"S{i % 8}", "m" if maker else "t", ""))
    opfile = os.path.join(tmpd, "submits.opfile")
    oprec.write_opfile(opfile, oprec.pack_records(rows))

    log_path = os.path.join(tmpd, "server.log")
    srv = subprocess.Popen(
        [sys.executable, "-m", "matching_engine_tpu.server.main",
         "--addr", "127.0.0.1:0", "--db", os.path.join(tmpd, "db.sqlite"),
         "--symbols", "8", "--capacity", "64", "--batch", "8",
         "--feed-depth", "0", "--shm-ingress", ring_path,
         "--shm-torn-ms", "25"],
        env=env, stdout=open(log_path, "w"), stderr=subprocess.STDOUT)
    failures: list[str] = []
    summary: dict = {"metric": "shm_multiwriter_smoke", "ops_per_writer":
                     args.ops}
    writers = []
    victim = None
    try:
        port = None
        deadline = time.time() + 180
        while time.time() < deadline and port is None:
            if srv.poll() is not None:
                print(open(log_path).read()[-3000:], file=sys.stderr)
                print("[shm-mw-smoke] FATAL: server died at boot",
                      file=sys.stderr)
                return 1
            m = re.search(r"listening on port (\d+)",
                          open(log_path).read())
            if m:
                port = int(m.group(1))
            else:
                time.sleep(0.25)
        if port is None:
            print("[shm-mw-smoke] FATAL: server never bound",
                  file=sys.stderr)
            return 1

        # The kill-one: a registered writer dies holding a claim.
        vready = os.path.join(tmpd, "victim.ready")
        victim = subprocess.Popen([sys.executable, "-c", _VICTIM,
                                   ring_path, vready], env=env,
                                  cwd=REPO, stdout=subprocess.DEVNULL,
                                  stderr=subprocess.DEVNULL)
        deadline = time.time() + 60
        while not os.path.exists(vready) and time.time() < deadline:
            if victim.poll() is not None:
                print("[shm-mw-smoke] FATAL: victim writer died before "
                      "claiming", file=sys.stderr)
                return 1
            time.sleep(0.02)
        if not os.path.exists(vready):
            print("[shm-mw-smoke] FATAL: victim never claimed",
                  file=sys.stderr)
            return 1
        victim_wid = int(open(vready).read().split()[0])
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait()  # reap: a zombie pid still probes alive
        summary["victim_writer_id"] = victim_wid

        # Two concurrent registered writers over disjoint halves,
        # start-barrier synchronized.
        barrier = os.path.join(tmpd, "go")
        for i in range(2):
            summ = os.path.join(tmpd, f"w{i}.json")
            ready = os.path.join(tmpd, f"ready.{i}")
            writers.append((summ, ready, subprocess.Popen(
                [sys.executable, "-m", "matching_engine_tpu.client.cli",
                 "submit-shm", ring_path, opfile,
                 "--offset", str(i * args.ops), "--count", str(args.ops),
                 "--chunk", "128", "--timeout", "120", "--quiet",
                 "--summary-json", summ, "--ready-file", ready,
                 "--start-barrier", barrier],
                env=env, cwd=REPO, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)))
        deadline = time.time() + 120
        while (not all(os.path.exists(r) for _s, r, _p in writers)
               and time.time() < deadline):
            time.sleep(0.02)
        open(barrier, "w").write("go")
        for _s, _r, p in writers:
            if p.wait(timeout=300) != 0:
                failures.append(f"writer exited {p.returncode}")
        sums = [json.load(open(s)) for s, _r, _p in writers
                if os.path.exists(s)]
        summary["writers"] = sums

        import grpc

        from matching_engine_tpu.proto import pb2
        from matching_engine_tpu.proto.rpc import MatchingEngineStub

        stub = MatchingEngineStub(
            grpc.insecure_channel(f"127.0.0.1:{port}"))
        counters = dict(stub.GetMetrics(pb2.MetricsRequest(),
                                        timeout=30).counters)
        summary["ingress_counters"] = {
            k: v for k, v in counters.items() if k.startswith("ingress")}

        wids = [s.get("writer_id", 0) for s in sums]
        if len(sums) != 2:
            failures.append("a writer produced no summary")
        if len(set(wids)) != len(wids) or any(w <= 0 for w in wids):
            failures.append(f"writer lanes not distinct/registered: "
                            f"{wids}")
        for s in sums:
            if s["pushed"] != s["ops"]:
                failures.append(f"writer {s.get('writer_id')}: pushed "
                                f"{s['pushed']} != ops {s['ops']}")
            got = counters.get(
                f"ingress_writer{s.get('writer_id')}_records", 0)
            if got != s["ops"]:
                failures.append(
                    f"per-writer attribution: lane "
                    f"{s.get('writer_id')} records {got} != pushed "
                    f"{s['ops']}")
        if counters.get("ingress_records", 0) != 2 * args.ops:
            failures.append(
                f"ingress_records {counters.get('ingress_records')} != "
                f"{2 * args.ops} (lost/duplicated admit, or the "
                f"victim's torn claim was admitted)")
        if counters.get("ingress_torn_recoveries", 0) < 1:
            failures.append("no torn recovery — the victim's claim was "
                            "never reclaimed")
    finally:
        for _s, _r, p in writers:
            if p.poll() is None:
                p.kill()
        if victim is not None and victim.poll() is None:
            victim.kill()
        srv.terminate()
        try:
            srv.wait(timeout=20)
        except Exception:  # noqa: BLE001
            srv.kill()

    summary["failures"] = failures
    summary["ok"] = not failures
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    if failures:
        for msg in failures:
            print(f"[shm-mw-smoke] FAIL: {msg}", file=sys.stderr)
        return 1
    print(f"[shm-mw-smoke] OK: 2x{args.ops} records on lanes "
          f"{[s['writer_id'] for s in sums]}, victim lane "
          f"{victim_wid} recovered "
          f"({counters.get('ingress_torn_recoveries')} torn)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
