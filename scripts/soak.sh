#!/usr/bin/env bash
# Sustained-serving soak: boot the full dual-edge stack in an auction call
# period (checkpoint daemon on a short interval), perform a real opening
# cross, then hammer BOTH edges with the native load generator in a loop,
# interleaving cancel traffic and RunAuction quiesces (under continuous
# load these are usually no-op clears — books rarely stand crossed — but
# each one exercises the dispatch-lock + pending-pipeline + checkpoint
# interplay). Ends by asserting real throughput happened, the server is
# still alive, and the durable store audits clean; writes one JSON
# artifact to benchmarks/results/soak_<ts>.json.
#
# Usage: scripts/soak.sh [minutes]   (default 3; CPU platform)
#   SOAK_PLATFORM=tpu scripts/soak.sh 12   — run the server on the real
#   tunneled chip instead (pre-probes the tunnel so a wedged window fails
#   fast; boot budget widened for on-device compile).
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH="${PYTHONPATH:+$PYTHONPATH:}$PWD"
SOAK_PLATFORM="${SOAK_PLATFORM:-cpu}"
if [ "$SOAK_PLATFORM" = "tpu" ]; then
  # Keep the inherited axon env (JAX_PLATFORMS=axon + pool IPs); a dead
  # tunnel must fail the soak in seconds, not hang the server boot.
  timeout -s KILL 60 python -c "import jax; assert jax.devices()" \
    >/dev/null 2>&1 || { echo "FAIL: tpu tunnel probe"; exit 1; }
else
  export JAX_PLATFORMS=cpu
  unset PALLAS_AXON_POOL_IPS  # a wedged axon tunnel must not hang the soak
fi

MINUTES="${1:-3}"
SOAK_SERVER_ARGS="${SOAK_SERVER_ARGS:-}"
# Online surveillance rides EVERY round: each server boots with the
# drop-copy stream + InvariantAuditor at full shadow sampling, and each
# round's verdict includes /auditz staying green with
# me_audit_violations_total == 0. A dedicated corruption-injection round
# at the end asserts the INVERSE (the auditor must fire) so a soak can
# never "pass" with a lobotomized auditor.
AUDIT_ARGS="--audit --audit-sample 1"
WORK=$(mktemp -d)
DB="$WORK/soak.db"
OUT_DIR="$PWD/benchmarks/results"
TS=$(date -u +%Y%m%dT%H%M%SZ)
mkdir -p "$OUT_DIR"
make -s -C native || { echo "FAIL: native build"; exit 1; }

PYTHONUNBUFFERED=1 python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$DB" --symbols 16 --capacity 64 --batch 8 \
  --window-ms 1 --gateway-addr 127.0.0.1:0 --auction-open \
  --metrics-port 0 --flight-dir "$WORK/flight" \
  $AUDIT_ARGS ${SOAK_SERVER_ARGS:-} \
  --checkpoint-dir "$WORK/ckpts" --checkpoint-interval-s 5 \
  > "$WORK/server.log" 2>&1 &
SRV=$!
trap 'kill $SRV 2>/dev/null' EXIT

PY_PORT=""; GW_PORT=""; OBS_PORT=""
BOOT_WAIT=120
[ "$SOAK_PLATFORM" = "tpu" ] && BOOT_WAIT=240   # on-device compile at boot
for i in $(seq 1 "$BOOT_WAIT"); do
  PY_PORT=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$WORK/server.log" | head -1)
  GW_PORT=$(sed -n 's/.*native gateway on port \([0-9]*\).*/\1/p' "$WORK/server.log" | head -1)
  OBS_PORT=$(sed -n 's/.*metrics on port \([0-9]*\).*/\1/p' "$WORK/server.log" | head -1)
  [ -n "$PY_PORT" ] && [ -n "$GW_PORT" ] && [ -n "$OBS_PORT" ] && break
  kill -0 $SRV 2>/dev/null || { echo "FAIL: server died at boot"; tail -5 "$WORK/server.log"; exit 1; }
  sleep 1
done
if [ -z "$PY_PORT" ] || [ -z "$GW_PORT" ] || [ -z "$OBS_PORT" ]; then
  echo "FAIL: server ports never appeared"; tail -5 "$WORK/server.log"; exit 1
fi

# Periodic /metrics scrapes accumulate the per-stage latency series next
# to the soak's JSON artifact (one "# scrape <epoch>" block per round).
METRICS_OUT="$OUT_DIR/soak_${TS}_metrics.prom"
scrape_metrics() {
  python - "$OBS_PORT" >> "$METRICS_OUT" <<'EOF'
import sys, time, urllib.request
try:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{sys.argv[1]}/metrics", timeout=5).read().decode()
    print(f"# scrape {time.time():.3f}")
    print(body)
except Exception as e:
    print(f"# scrape-failed {time.time():.3f} {type(e).__name__}: {e}")
EOF
}
CLI=matching_engine_tpu/native/me_client
GW="127.0.0.1:$GW_PORT"; PY="127.0.0.1:$PY_PORT"

# Per-round surveillance verdict: /auditz must answer 200 with zero
# violations (the JSON is kept for the artifact's auditz section).
AUDITZ_DIR="$WORK/auditz"; mkdir -p "$AUDITZ_DIR"
check_audit() {  # $1 = obs port, $2 = section name; non-zero on red
  python - "$1" "$2" "$AUDITZ_DIR" <<'EOF'
import json, os, sys, urllib.request, urllib.error
port, name, outdir = sys.argv[1:4]
try:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/auditz", timeout=5).read().decode()
    code = 200
except urllib.error.HTTPError as e:
    body, code = e.read().decode(), e.code
except Exception as e:
    print(f"auditz {name}: unreachable ({type(e).__name__}: {e})")
    sys.exit(1)
try:
    doc = json.loads(body)
except ValueError:
    print(f"auditz {name}: non-JSON answer ({body[:80]!r})")
    sys.exit(1)
open(os.path.join(outdir, f"{name}.json"), "w").write(body)
if code != 200 or not doc.get("ok") or doc.get("violations", -1) != 0:
    print(f"auditz {name}: RED code={code} "
          f"violations={doc.get('violations')} by={doc.get('by_kind')} "
          f"recent={doc.get('recent')}")
    sys.exit(1)
print(f"auditz {name}: ok records={doc.get('records')} "
      f"store_checks={doc.get('store', {}).get('checks')}")
EOF
}

# Real opening cross: crossing flow RESTS in the call period, a per-symbol
# uncross clears it (call period holds), then all-symbols opens trading.
"$CLI" "$GW" soak-b SOAK BUY LIMIT 1020000 4 5 >/dev/null || { echo "FAIL: call-period submit"; exit 1; }
"$CLI" "$GW" soak-a SOAK SELL LIMIT 1000000 4 3 >/dev/null || { echo "FAIL: call-period submit"; exit 1; }
"$CLI" auction "$GW" SOAK | grep -q "cleared 1000000@Q4 x3" || { echo "FAIL: opening cross"; exit 1; }
"$CLI" auction "$GW" >/dev/null || { echo "FAIL: all-symbols uncross"; exit 1; }

DEADLINE=$(( $(date +%s) + MINUTES * 60 ))
# AMENDS must be initialized with its siblings: the loop runs under
# `set -u`, and the first `AMENDS=$((AMENDS + 1))` on an unset variable
# would kill the soak with "unbound variable".
ROUNDS=0; OK_TOTAL=0; CANCELS=0; AMENDS=0
# Sequenced-feed integrity: one background subscriber per round on the
# SOAK market-data domain, resuming from the previous round's last seq
# (exercises reconnect + retransmission-store replay every round). A
# round FAILS on any unrecovered sequence gap (subscriber exit code 4).
FEED_DIR="$WORK/feed"; mkdir -p "$FEED_DIR"
FEED_FROM=0; FEED_EPOCH=0; FEED_EVENTS=0; FEED_GAPS=0; FEED_FILLED=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  kill -0 $SRV 2>/dev/null || { echo "FAIL: server died mid-soak"; exit 1; }
  FEED_SUMMARY="$FEED_DIR/round_$ROUNDS.json"
  python -m matching_engine_tpu.client.cli subscribe "127.0.0.1:$PY_PORT" \
    md SOAK --from-seq "$FEED_FROM" --epoch "$FEED_EPOCH" --idle-exit 60 \
    --quiet \
    --summary-json "$FEED_SUMMARY" >/dev/null 2>"$FEED_DIR/round_$ROUNDS.err" &
  FEED_PID=$!
  for ADDR in "$GW" "$PY"; do
    LINE=$("$CLI" bench "$ADDR" 8 100 12 4 2>/dev/null) || true
    OK=$(echo "$LINE" | python -c "import json,sys
try: print(json.loads(sys.stdin.read())['ok'])
except Exception: print(0)")
    OK_TOTAL=$((OK_TOTAL + OK))
  done
  # Amend + cancel traffic: rest far from the market, amend the quantity
  # down (priority-preserving), then cancel the amended remainder.
  OID=$("$CLI" "$GW" soak-c SOAK BUY LIMIT 10000 4 5 2>/dev/null \
        | sed -n 's/.*order_id=\(OID-[0-9]*\).*/\1/p')
  if [ -n "$OID" ]; then
    if "$CLI" amend "$GW" soak-c "$OID" 2 2>/dev/null \
        | grep -q "remaining=2"; then
      AMENDS=$((AMENDS + 1))
    fi
    if "$CLI" cancel "$GW" soak-c "$OID" >/dev/null 2>&1; then
      CANCELS=$((CANCELS + 1))
    fi
  fi
  # Auction quiesce under load (usually a no-op clear; exercises the
  # dispatch-lock/pending/checkpoint interplay concurrently with traffic).
  "$CLI" auction "$GW" >/dev/null 2>&1 || true
  scrape_metrics
  # Surveillance verdict for the round: any invariant violation so far
  # fails the soak NOW, naming the kind and the offending record.
  check_audit "$OBS_PORT" "round_$ROUNDS" \
    || { echo "FAIL: audit violations in round $ROUNDS"; exit 1; }
  # Round verdict from the feed subscriber: SIGINT makes it finalize
  # (summary JSON + integrity exit code). 4 = unrecovered gap -> fail.
  kill -INT $FEED_PID 2>/dev/null || true
  wait $FEED_PID; FEED_RC=$?
  if [ "$FEED_RC" -eq 4 ]; then
    echo "FAIL: unrecovered feed sequence gap in round $ROUNDS"
    cat "$FEED_DIR/round_$ROUNDS.err"; exit 1
  fi
  # Any other non-zero exit means the integrity probe itself broke (RPC
  # failure, usage error) — a soak that "passes" with a dead subscriber
  # verified nothing.
  if [ "$FEED_RC" -ne 0 ] || [ ! -s "$FEED_SUMMARY" ]; then
    echo "FAIL: feed subscriber broke in round $ROUNDS (rc=$FEED_RC)"
    cat "$FEED_DIR/round_$ROUNDS.err"; exit 1
  fi
  FEED_STATE=$(python -c 'import json, sys
s = json.load(open(sys.argv[1]))
print(s["last_seq"], s["epoch"], s["events"], s["gaps_detected"],
      s["gap_filled_events"])' "$FEED_SUMMARY")
  read -r FEED_FROM FEED_EPOCH FE FG FF <<< "$FEED_STATE"
  FEED_EVENTS=$((FEED_EVENTS + FE))
  FEED_GAPS=$((FEED_GAPS + FG))
  FEED_FILLED=$((FEED_FILLED + FF))
  ROUNDS=$((ROUNDS + 1))
done
[ "$OK_TOTAL" -gt 0 ] || { echo "FAIL: no orders succeeded"; exit 1; }
[ "$CANCELS" -gt 0 ] || { echo "FAIL: no cancels succeeded"; exit 1; }
[ "$FEED_EVENTS" -gt 0 ] || { echo "FAIL: feed subscribers saw zero events"; exit 1; }
grep -q "^me_stage_queue_wait_us_p99" "$METRICS_OUT" \
  || { echo "FAIL: stage ledger absent from /metrics scrapes"; exit 1; }
# The auditor must have actually consumed records (a soak whose auditor
# saw nothing verified nothing), and NO scrape may ever have shown a
# nonzero violation count (a "zero exists somewhere" grep would pass
# vacuously on the round-0 scrape).
grep -q "^me_audit_violations_total " "$METRICS_OUT" \
  || { echo "FAIL: me_audit_violations_total absent from scrapes"; exit 1; }
if grep -qE "^me_audit_violations_total [1-9]" "$METRICS_OUT"; then
  echo "FAIL: a scrape recorded nonzero me_audit_violations_total"; exit 1
fi
AUDIT_RECORDS=$(sed -n 's/^me_audit_records_total \([0-9]*\).*/\1/p' "$METRICS_OUT" | sort -n | tail -1)
[ -n "$AUDIT_RECORDS" ] && [ "$AUDIT_RECORDS" -gt 0 ] \
  || { echo "FAIL: auditor consumed no drop-copy records (records=${AUDIT_RECORDS:-absent})"; exit 1; }

# ---- sharded round: K=2 partitioned serving lanes, one per device ---------
# Boots a second server with --serve-shards 2 on a fresh store — under a
# FORCED 2-device host (XLA_FLAGS=--xla_force_host_platform_device_count=2)
# with --shard-devices roundrobin, so each lane's book and jits commit to
# their own device. Reuses the per-round bench + sequenced subscriber +
# metrics scrape, then fails the round on ANY cross-lane order-id collision
# in the durable store (the strided-allocation invariant), on missing
# per-lane metrics, or on missing per-device placement gauges
# (me_lane<i>_device / me_device<d>_ops_per_s).
SH_DB="$WORK/soak_sharded.db"
SH_XLA_KEPT=$(echo "${XLA_FLAGS:-}" | tr ' ' '\n' \
  | grep -v xla_force_host_platform_device_count | tr '\n' ' ')
PYTHONUNBUFFERED=1 \
XLA_FLAGS="$SH_XLA_KEPT--xla_force_host_platform_device_count=2" \
python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$SH_DB" --symbols 16 --capacity 64 --batch 8 \
  --window-ms 1 --serve-shards 2 --shard-devices roundrobin \
  --metrics-port 0 \
  $AUDIT_ARGS ${SOAK_SERVER_ARGS:-} \
  > "$WORK/server_sharded.log" 2>&1 &
SH_SRV=$!
trap 'kill $SRV $SH_SRV 2>/dev/null' EXIT
SH_PY=""; SH_OBS=""
for i in $(seq 1 "$BOOT_WAIT"); do
  SH_PY=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$WORK/server_sharded.log" | head -1)
  SH_OBS=$(sed -n 's/.*metrics on port \([0-9]*\).*/\1/p' "$WORK/server_sharded.log" | head -1)
  [ -n "$SH_PY" ] && [ -n "$SH_OBS" ] && break
  kill -0 $SH_SRV 2>/dev/null || { echo "FAIL: sharded server died at boot"; tail -5 "$WORK/server_sharded.log"; exit 1; }
  sleep 1
done
[ -n "$SH_PY" ] && [ -n "$SH_OBS" ] || { echo "FAIL: sharded server ports never appeared"; exit 1; }
SH_FEED="$FEED_DIR/sharded.json"
python -m matching_engine_tpu.client.cli subscribe "127.0.0.1:$SH_PY" \
  md SOAK --idle-exit 60 --quiet \
  --summary-json "$SH_FEED" >/dev/null 2>"$FEED_DIR/sharded.err" &
SH_FEED_PID=$!
SH_OK=$("$CLI" bench "127.0.0.1:$SH_PY" 8 100 12 4 2>/dev/null \
  | python -c "import json,sys
try: print(json.loads(sys.stdin.read())['ok'])
except Exception: print(0)")
python - "$SH_OBS" >> "$METRICS_OUT" <<'EOF'
import sys, time, urllib.request
try:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{sys.argv[1]}/metrics", timeout=5).read().decode()
    print(f"# scrape-sharded {time.time():.3f}")
    print(body)
except Exception as e:
    print(f"# scrape-failed {time.time():.3f} {type(e).__name__}: {e}")
EOF
check_audit "$SH_OBS" "sharded" \
  || { echo "FAIL: audit violations in the sharded round"; exit 1; }
kill -INT $SH_FEED_PID 2>/dev/null || true
wait $SH_FEED_PID; SH_FEED_RC=$?
if [ "$SH_FEED_RC" -eq 4 ]; then
  echo "FAIL: unrecovered feed gap in the sharded round"
  cat "$FEED_DIR/sharded.err"; exit 1
fi
kill $SH_SRV 2>/dev/null; wait $SH_SRV 2>/dev/null
trap 'kill $SRV 2>/dev/null' EXIT
[ "$SH_OK" -gt 0 ] || { echo "FAIL: sharded round served no orders"; exit 1; }
grep -q "^me_lane_dispatch_rate" "$METRICS_OUT" \
  || { echo "FAIL: me_lane_* metrics absent from the sharded scrape"; exit 1; }
# Placement identity: both lanes must report which forced device they
# committed to, and each device's throughput gauge must exist (the
# lanes were placed roundrobin on a 2-device host, so device ordinals
# 0 AND 1 must both appear).
for G in me_lane0_device me_lane1_device \
         me_device0_ops_per_s me_device1_ops_per_s; do
  grep -q "^$G" "$METRICS_OUT" \
    || { echo "FAIL: $G absent from the sharded scrape (per-device placement gauges missing)"; exit 1; }
done
SH_COLLISIONS=$(python - "$SH_DB" <<'EOF'
import sqlite3, sys
con = sqlite3.connect(sys.argv[1])
n = con.execute("SELECT COUNT(*) FROM (SELECT order_id FROM orders "
                "GROUP BY order_id HAVING COUNT(*) > 1)").fetchone()[0]
print(n)
EOF
)
SH_COLLISIONS=$(echo "$SH_COLLISIONS" | tail -1 | tr -d '[:space:]')
[ "$SH_COLLISIONS" = "0" ] \
  || { echo "FAIL: $SH_COLLISIONS cross-lane order-id collision(s) in the sharded store"; exit 1; }

# ---- megadispatch round: coalesced device scans ---------------------------
# Boots a third server with --megadispatch-max-waves 4 on a fresh store
# (python dispatch route: the coalescing controller + stacked scan live
# there), reuses the per-round bench + sequenced subscriber + metrics
# scrape, then fails the round on a broken subscriber, a store that
# fails the integrity audit, or missing me_megadispatch_* metrics.
MD_DB="$WORK/soak_mega.db"
PYTHONUNBUFFERED=1 python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$MD_DB" --symbols 16 --capacity 64 --batch 8 \
  --window-ms 1 --no-native --megadispatch-max-waves 4 --metrics-port 0 \
  $AUDIT_ARGS ${SOAK_SERVER_ARGS:-} \
  > "$WORK/server_mega.log" 2>&1 &
MD_SRV=$!
trap 'kill $SRV $MD_SRV 2>/dev/null' EXIT
MD_PY=""; MD_OBS=""
for i in $(seq 1 "$BOOT_WAIT"); do
  MD_PY=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$WORK/server_mega.log" | head -1)
  MD_OBS=$(sed -n 's/.*metrics on port \([0-9]*\).*/\1/p' "$WORK/server_mega.log" | head -1)
  [ -n "$MD_PY" ] && [ -n "$MD_OBS" ] && break
  kill -0 $MD_SRV 2>/dev/null || { echo "FAIL: megadispatch server died at boot"; tail -5 "$WORK/server_mega.log"; exit 1; }
  sleep 1
done
[ -n "$MD_PY" ] && [ -n "$MD_OBS" ] || { echo "FAIL: megadispatch server ports never appeared"; exit 1; }
MD_FEED="$FEED_DIR/mega.json"
python -m matching_engine_tpu.client.cli subscribe "127.0.0.1:$MD_PY" \
  md SOAK --idle-exit 60 --quiet \
  --summary-json "$MD_FEED" >/dev/null 2>"$FEED_DIR/mega.err" &
MD_FEED_PID=$!
MD_OK=$("$CLI" bench "127.0.0.1:$MD_PY" 8 100 12 4 2>/dev/null \
  | python -c "import json,sys
try: print(json.loads(sys.stdin.read())['ok'])
except Exception: print(0)")
python - "$MD_OBS" >> "$METRICS_OUT" <<'EOF'
import sys, time, urllib.request
try:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{sys.argv[1]}/metrics", timeout=5).read().decode()
    print(f"# scrape-megadispatch {time.time():.3f}")
    print(body)
except Exception as e:
    print(f"# scrape-failed {time.time():.3f} {type(e).__name__}: {e}")
EOF
check_audit "$MD_OBS" "megadispatch" \
  || { echo "FAIL: audit violations in the megadispatch round"; exit 1; }
kill -INT $MD_FEED_PID 2>/dev/null || true
wait $MD_FEED_PID; MD_FEED_RC=$?
if [ "$MD_FEED_RC" -eq 4 ]; then
  echo "FAIL: unrecovered feed gap in the megadispatch round"
  cat "$FEED_DIR/mega.err"; exit 1
fi
# Any other non-zero exit (or a missing summary) means the integrity
# probe itself broke — a round that "passes" with a dead subscriber
# verified nothing (same contract as the main loop's rounds).
if [ "$MD_FEED_RC" -ne 0 ] || [ ! -s "$MD_FEED" ]; then
  echo "FAIL: feed subscriber broke in the megadispatch round (rc=$MD_FEED_RC)"
  cat "$FEED_DIR/mega.err"; exit 1
fi
kill $MD_SRV 2>/dev/null; wait $MD_SRV 2>/dev/null
trap 'kill $SRV 2>/dev/null' EXIT
[ "$MD_OK" -gt 0 ] || { echo "FAIL: megadispatch round served no orders"; exit 1; }
grep -q "^me_megadispatch_" "$METRICS_OUT" \
  || { echo "FAIL: me_megadispatch_* metrics absent from the scrape"; exit 1; }
MD_AUDIT=$(python - "$MD_DB" <<'EOF'
import sys
sys.path.insert(0, "scripts")
from audit import audit
print(len(audit(sys.argv[1])))
EOF
)
MD_AUDIT=$(echo "$MD_AUDIT" | tail -1 | tr -d '[:space:]')
[ "$MD_AUDIT" = "0" ] \
  || { echo "FAIL: $MD_AUDIT store integrity violation(s) in the megadispatch round"; exit 1; }

# ---- batch round: the batch-native edge -----------------------------------
# Boots a server on the native-lane path with native megadispatch engaged
# (--native-lanes --megadispatch-max-waves 4), replays a RECORDED op file
# through `client submit-batch` (the same domain/oprec.py codec reader the
# bench replay uses) alongside a sequenced subscriber, then fails the
# round on any positional-status/store mismatch (accepted count from the
# positional responses must equal the store's order rows) or on missing
# me_edge_* metrics in the scrape.
BE_DB="$WORK/soak_batch.db"
PYTHONUNBUFFERED=1 python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$BE_DB" --symbols 16 --capacity 64 --batch 8 \
  --window-ms 1 --native-lanes --megadispatch-max-waves 4 --metrics-port 0 \
  $AUDIT_ARGS ${SOAK_SERVER_ARGS:-} \
  > "$WORK/server_batch.log" 2>&1 &
BE_SRV=$!
trap 'kill $SRV $BE_SRV 2>/dev/null' EXIT
BE_PY=""; BE_OBS=""
for i in $(seq 1 "$BOOT_WAIT"); do
  BE_PY=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$WORK/server_batch.log" | head -1)
  BE_OBS=$(sed -n 's/.*metrics on port \([0-9]*\).*/\1/p' "$WORK/server_batch.log" | head -1)
  [ -n "$BE_PY" ] && [ -n "$BE_OBS" ] && break
  kill -0 $BE_SRV 2>/dev/null || { echo "FAIL: batch server died at boot"; tail -5 "$WORK/server_batch.log"; exit 1; }
  sleep 1
done
[ -n "$BE_PY" ] && [ -n "$BE_OBS" ] || { echo "FAIL: batch server ports never appeared"; exit 1; }
# Recorded flow: maker/taker GTC pairs over the SOAK symbols — every
# record should accept, so positional statuses reconcile exactly with
# the store.
BE_OPS="$WORK/batch_flow.ops"
python - "$BE_OPS" <<'EOF'
import sys
from matching_engine_tpu.domain import oprec
ops = []
for i in range(2048):
    sym = f"BK{i % 16}"
    maker = ((i // 16) % 2) == 0
    ops.append((oprec.OPREC_SUBMIT, 2 if maker else 1, 0, 10_000, 5, sym,
                "bk-m" if maker else "bk-t", ""))
oprec.write_opfile(sys.argv[1], oprec.pack_records(ops))
EOF
BE_FEED="$FEED_DIR/batch.json"
python -m matching_engine_tpu.client.cli subscribe "127.0.0.1:$BE_PY" \
  md BK0 --idle-exit 60 --quiet \
  --summary-json "$BE_FEED" >/dev/null 2>"$FEED_DIR/batch.err" &
BE_FEED_PID=$!
BE_SUMMARY="$WORK/batch_replay.json"
python -m matching_engine_tpu.client.cli submit-batch "127.0.0.1:$BE_PY" \
  "$BE_OPS" --batch-size 256 --quiet --summary-json "$BE_SUMMARY" \
  >/dev/null 2>"$WORK/batch_replay.err" \
  || { echo "FAIL: submit-batch replay failed"; cat "$WORK/batch_replay.err"; exit 1; }
# Scrape to the round's OWN file first: the me_edge_*/me_megadispatch_*
# gates below must read THIS server's scrape — grepping the shared
# accumulator would match the earlier megadispatch round's series and
# could never fail (the dead-probe false-pass class).
BE_SCRAPE="$WORK/batch_scrape.prom"
python - "$BE_OBS" > "$BE_SCRAPE" <<'EOF'
import sys, time, urllib.request
try:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{sys.argv[1]}/metrics", timeout=5).read().decode()
    print(f"# scrape-batch {time.time():.3f}")
    print(body)
except Exception as e:
    print(f"# scrape-failed {time.time():.3f} {type(e).__name__}: {e}")
EOF
cat "$BE_SCRAPE" >> "$METRICS_OUT"
check_audit "$BE_OBS" "batch" \
  || { echo "FAIL: audit violations in the batch round"; exit 1; }
kill -INT $BE_FEED_PID 2>/dev/null || true
wait $BE_FEED_PID; BE_FEED_RC=$?
if [ "$BE_FEED_RC" -eq 4 ]; then
  echo "FAIL: unrecovered feed gap in the batch round"
  cat "$FEED_DIR/batch.err"; exit 1
fi
if [ "$BE_FEED_RC" -ne 0 ] || [ ! -s "$BE_FEED" ]; then
  echo "FAIL: feed subscriber broke in the batch round (rc=$BE_FEED_RC)"
  cat "$FEED_DIR/batch.err"; exit 1
fi
# Drain the durable sink before reconciling the store (SIGTERM path
# flushes; give the async writer its window first).
sleep 2
kill -TERM $BE_SRV 2>/dev/null; wait $BE_SRV 2>/dev/null
trap 'kill $SRV 2>/dev/null' EXIT
BE_CHECK=$(python - "$BE_SUMMARY" "$BE_DB" <<'EOF'
import json, sqlite3, sys
s = json.load(open(sys.argv[1]))
con = sqlite3.connect(sys.argv[2])
rows = con.execute("SELECT COUNT(*) FROM orders").fetchone()[0]
# Positional-status/store reconciliation: every positionally-accepted
# submit must be a store row, and nothing else may be.
ok = (s["rejected"] == 0 and s["accepted"] == s["ops"]
      and rows == s["accepted"])
print(f"{int(ok)} {s['accepted']} {s['rejected']} {rows}")
EOF
)
read -r BE_OK BE_ACC BE_REJ BE_ROWS <<< "$(echo "$BE_CHECK" | tail -1)"
if [ "$BE_OK" != "1" ]; then
  echo "FAIL: batch round positional-status/store mismatch (accepted=$BE_ACC rejected=$BE_REJ store_rows=$BE_ROWS)"
  exit 1
fi
grep -q "^me_edge_batches_total" "$BE_SCRAPE" \
  || { echo "FAIL: me_edge_* metrics absent from the batch scrape"; exit 1; }
# Engagement, not presence: the counter exists from boot; the round must
# have actually stacked waves.
BE_MEGA=$(sed -n 's/^me_megadispatch_steps_total \([0-9]*\).*/\1/p' "$BE_SCRAPE" | head -1)
[ -n "$BE_MEGA" ] && [ "$BE_MEGA" -gt 0 ] \
  || { echo "FAIL: native megadispatch never engaged in the batch round (steps=${BE_MEGA:-absent})"; exit 1; }

# ---- flash-crash round: recorded scenario workload under full audit -------
# Scenario stress through the REAL stack (ISSUE 12): record a flash-crash
# cascade with the on-device agent market (`client simulate` — momentum
# agents amplifying an injected sell shock), replay the opfile through
# `client submit-batch` against a server running the auditor at sample 1,
# and FAIL on any auditor violation or on rejects past a metered
# threshold. Rejects ARE expected under stress (cancels racing fills,
# capacity backpressure) — the round asserts they are counted and
# bounded, never fatal and never an invariant break.
FC_OPS_FILE="$WORK/flash_crash.opfile.gz"
FC_SIM_SUMMARY="$WORK/flash_crash_sim.json"
python -m matching_engine_tpu.client.cli simulate \
  --scenario flash_crash --steps 80 --symbols 16 --seed 13 \
  --out "$FC_OPS_FILE" --summary-json "$FC_SIM_SUMMARY" \
  >/dev/null 2>"$WORK/flash_crash_sim.err" \
  || { echo "FAIL: flash-crash scenario recording failed"; cat "$WORK/flash_crash_sim.err"; exit 1; }
FC_DB="$WORK/soak_flash.db"
# Tiered books (PR 14): the Zipf-hot head symbols get deep books, the
# tail standard ones — the 128-capacity wall that used to meter ~13%
# rejects in this round is now a tier-spec decision, so the reject
# budget below drops to 10% and any full-book reject that remains shows
# up in me_book_capacity_rejects_total instead of being inevitable.
PYTHONUNBUFFERED=1 python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$FC_DB" --symbols 16 --batch 8 \
  --book-tiers "4x512:S0;S1;S2;S3,*x256" \
  --window-ms 1 --megadispatch-max-waves 4 --metrics-port 0 \
  --flight-dir "$WORK/flash_flight" \
  $AUDIT_ARGS ${SOAK_SERVER_ARGS:-} \
  > "$WORK/server_flash.log" 2>&1 &
FC_SRV=$!
trap 'kill $SRV $FC_SRV 2>/dev/null' EXIT
FC_PY=""; FC_OBS=""
for i in $(seq 1 "$BOOT_WAIT"); do
  FC_PY=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$WORK/server_flash.log" | head -1)
  FC_OBS=$(sed -n 's/.*metrics on port \([0-9]*\).*/\1/p' "$WORK/server_flash.log" | head -1)
  [ -n "$FC_PY" ] && [ -n "$FC_OBS" ] && break
  kill -0 $FC_SRV 2>/dev/null || { echo "FAIL: flash-crash server died at boot"; tail -5 "$WORK/server_flash.log"; exit 1; }
  sleep 1
done
[ -n "$FC_PY" ] && [ -n "$FC_OBS" ] || { echo "FAIL: flash-crash server ports never appeared"; exit 1; }
FC_SUMMARY="$WORK/flash_crash_replay.json"
python -m matching_engine_tpu.client.cli submit-batch "127.0.0.1:$FC_PY" \
  "$FC_OPS_FILE" --batch-size 256 --quiet --summary-json "$FC_SUMMARY" \
  >/dev/null 2>"$WORK/flash_crash_replay.err" \
  || { echo "FAIL: flash-crash replay failed"; cat "$WORK/flash_crash_replay.err"; exit 1; }
FC_SCRAPE="$WORK/flash_scrape.prom"
python - "$FC_OBS" > "$FC_SCRAPE" <<'EOF'
import sys, time, urllib.request
try:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{sys.argv[1]}/metrics", timeout=5).read().decode()
    print(f"# scrape-flash {time.time():.3f}")
    print(body)
except Exception as e:
    print(f"# scrape-failed {time.time():.3f} {type(e).__name__}: {e}")
EOF
cat "$FC_SCRAPE" >> "$METRICS_OUT"
# The auditor must stay green through the cascade — a crash scenario
# that trips conservation/lifecycle invariants is an engine bug, not
# acceptable stress.
check_audit "$FC_OBS" "flash_crash" \
  || { echo "FAIL: audit violations in the flash-crash round"; exit 1; }
kill -TERM $FC_SRV 2>/dev/null; wait $FC_SRV 2>/dev/null
trap 'kill $SRV 2>/dev/null' EXIT
# Metered rejects: counted, bounded, never fatal. The structural reject
# class (market-maker cancels of quotes the cascade already filled —
# measured 13.4% on this recording) rides every crash replay; with the
# tiered books full-book rejects are no longer inevitable, so the budget
# drops from 25% to 15% (just above the structural floor) and
# book-capacity rejects specifically must EQUAL the positional
# "book side at capacity" count — every one metered in
# me_book_capacity_rejects_total, zero on a spec as deep as this one.
FC_CHECK=$(python - "$FC_SUMMARY" "$FC_SCRAPE" <<'EOF'
import json, re, sys
s = json.load(open(sys.argv[1]))
scrape = open(sys.argv[2]).read()
# Capacity-full submits land in me_orders_rejected_total (absent series
# = the counter never fired = zero); cancel-of-terminal rejects are
# positional-only and ride the summary's reject_reasons.
m = re.search(r"^me_orders_rejected_total (\d+)", scrape, re.M)
counted = int(m.group(1)) if m else 0
m = re.search(r"^me_book_capacity_rejects_total (\d+)", scrape, re.M)
cap_rejects = int(m.group(1)) if m else 0
book_full = sum(n for reason, n in s.get("reject_reasons", {}).items()
                if "book side at capacity" in reason)
ok = (s["accepted"] > 0 and s["rejected"] <= 0.15 * s["ops"]
      and counted <= s["rejected"]
      and cap_rejects == book_full)  # every full-book reject is metered
print(f"{int(ok)} {s['accepted']} {s['rejected']} {s['ops']} {counted} "
      f"{cap_rejects}")
EOF
)
read -r FC_OK FC_ACC FC_REJ FC_TOTAL FC_COUNTED FC_CAP <<< "$(echo "$FC_CHECK" | tail -1)"
if [ "$FC_OK" != "1" ]; then
  echo "FAIL: flash-crash round rejects unmetered or past threshold (accepted=$FC_ACC rejected=$FC_REJ ops=$FC_TOTAL counter=$FC_COUNTED book_capacity=$FC_CAP)"
  exit 1
fi
echo "flash-crash round: $FC_ACC/$FC_TOTAL accepted, $FC_REJ rejects metered (counter=$FC_COUNTED, book_capacity=$FC_CAP), auditor green"

# ---- ingress round: zero-copy shm ring under full audit --------------------
# The shared-memory edge through the REAL stack (ISSUE 15): replay the
# flash-crash recording (reused from the round above) through `client
# submit-shm` — a separate process writing 384-byte records straight
# into the server's mapped ring — against a server running the auditor
# at sample 1. FAIL on any auditor violation, on a store/positional-
# status mismatch (orders rows MUST equal the client's accepted-submit
# acks — a lost or doubled admit is exactly what the ring's commit-word
# protocol exists to prevent), or on missing me_ingress_* series.
IN_DB="$WORK/soak_ingress.db"
IN_RING="$WORK/ingress.ring"
PYTHONUNBUFFERED=1 python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$IN_DB" --symbols 16 --batch 8 \
  --window-ms 1 --megadispatch-max-waves 4 --metrics-port 0 \
  --shm-ingress "$IN_RING" --shm-torn-ms 25 \
  --admission-rate 1000000000 --admission-max-qty 2000000 \
  --flight-dir "$WORK/ingress_flight" \
  $AUDIT_ARGS ${SOAK_SERVER_ARGS:-} \
  > "$WORK/server_ingress.log" 2>&1 &
IN_SRV=$!
trap 'kill $SRV $IN_SRV 2>/dev/null' EXIT
IN_PY=""; IN_OBS=""
for i in $(seq 1 "$BOOT_WAIT"); do
  IN_PY=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$WORK/server_ingress.log" | head -1)
  IN_OBS=$(sed -n 's/.*metrics on port \([0-9]*\).*/\1/p' "$WORK/server_ingress.log" | head -1)
  [ -n "$IN_PY" ] && [ -n "$IN_OBS" ] && break
  kill -0 $IN_SRV 2>/dev/null || { echo "FAIL: ingress server died at boot"; tail -5 "$WORK/server_ingress.log"; exit 1; }
  sleep 1
done
[ -n "$IN_PY" ] && [ -n "$IN_OBS" ] || { echo "FAIL: ingress server ports never appeared"; exit 1; }
# Cancel-gap flow control: the poller dispatches whatever run it pops,
# so the un-acked backlog must stay below the recording's
# min_cancel_gap (a cancel landing in the same dispatch as its target
# resolves against the pre-batch directory).
IN_GAP=$(python -c "import json,sys; print(json.load(open(sys.argv[1])).get('min_cancel_gap') or 512)" "${FC_OPS_FILE%.opfile.gz}.manifest.json")
IN_CHUNK=128
IN_INFLIGHT=$(( IN_GAP - IN_CHUNK > IN_CHUNK ? IN_GAP - IN_CHUNK : IN_CHUNK ))
IN_SUMMARY="$WORK/ingress_replay.json"
python -m matching_engine_tpu.client.cli submit-shm "$IN_RING" \
  "$FC_OPS_FILE" --chunk "$IN_CHUNK" --max-inflight "$IN_INFLIGHT" \
  --timeout 300 --quiet --summary-json "$IN_SUMMARY" \
  >/dev/null 2>"$WORK/ingress_replay.err" \
  || { echo "FAIL: shm ingress replay failed"; cat "$WORK/ingress_replay.err"; exit 1; }
IN_SCRAPE="$WORK/ingress_scrape.prom"
python - "$IN_OBS" > "$IN_SCRAPE" <<'EOF'
import sys, time, urllib.request
try:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{sys.argv[1]}/metrics", timeout=5).read().decode()
    print(f"# scrape-ingress {time.time():.3f}")
    print(body)
except Exception as e:
    print(f"# scrape-failed {time.time():.3f} {type(e).__name__}: {e}")
EOF
cat "$IN_SCRAPE" >> "$METRICS_OUT"
check_audit "$IN_OBS" "ingress" \
  || { echo "FAIL: audit violations in the ingress round"; exit 1; }
# Store/positional-status agreement + the me_ingress_* contract.
IN_CHECK=$(python - "$IN_SUMMARY" "$IN_SCRAPE" "$IN_DB" <<'EOF'
import json, re, sqlite3, sys
s = json.load(open(sys.argv[1]))
scrape = open(sys.argv[2]).read()
# Engine-rejected submits also land in the store (status REJECTED=4, the
# decode-path semantics) — the bit-identity claim is accepted submits ==
# non-REJECTED order rows.
orders = sqlite3.connect(sys.argv[3]).execute(
    "SELECT COUNT(*) FROM orders WHERE status != 4").fetchone()[0]
m = re.search(r"^me_ingress_records_total (\d+)", scrape, re.M)
ing_records = int(m.group(1)) if m else -1
have_series = all(
    re.search(rf"^me_ingress_{n}", scrape, re.M)
    for n in ("records_total", "batches_total", "rejects_total",
              "torn_recoveries_total", "ring_depth", "doorbell_wakes",
              "resp_dropped"))
ok = (s["accepted"] > 0
      and s["pushed"] == s["ops"]              # everything entered the ring
      and ing_records == s["ops"]              # ...and was admitted off it
      and orders == s["accepted_submits"]      # store == positional acks
      and have_series)
print(f"{int(ok)} {s['accepted']} {s['rejected']} {s['ops']} "
      f"{orders} {s['accepted_submits']} {ing_records} {int(have_series)}")
EOF
)
read -r IN_OK IN_ACC IN_REJ IN_TOTAL IN_ORDERS IN_SUBMITS IN_RECORDS IN_SERIES <<< "$(echo "$IN_CHECK" | tail -1)"
if [ "$IN_OK" != "1" ]; then
  echo "FAIL: ingress round mismatch (accepted=$IN_ACC rejected=$IN_REJ ops=$IN_TOTAL store_orders=$IN_ORDERS accepted_submits=$IN_SUBMITS me_ingress_records=$IN_RECORDS series_ok=$IN_SERIES)"
  exit 1
fi
echo "ingress round (1 writer): $IN_ACC/$IN_TOTAL accepted via shm ring, store rows == positional submit acks ($IN_ORDERS), me_ingress_* green"

# ---- 4 concurrent writers into the SAME ring (ring v2) ---------------------
# Four `client submit-shm` processes, each a registered writer lane,
# replay disjoint slices of the recording's SUBMIT records concurrently
# (submits only: the server assigns OIDs globally, so a recording's
# cancel targets do not survive concurrent interleaving — the in-order
# phase above already exercised cancels/amends). FAIL on store rows !=
# phase-1 + summed per-writer accepted acks (a lost or doubled commit
# under writer concurrency), on colliding writer lanes, or on missing
# me_ingress_writer* / me_ingress_writers series.
MW_OPS="$WORK/ingress_submits.opfile"
MW_N=$(python - "$FC_OPS_FILE" "$MW_OPS" <<'EOF'
import sys
from matching_engine_tpu.domain import oprec
arr = oprec.read_opfile(sys.argv[1])
sub = arr[arr["op"] == oprec.OPREC_SUBMIT]
oprec.write_opfile(sys.argv[2], sub)
print(len(sub))
EOF
)
MW_PER=$(( MW_N / 4 ))
MW_BARRIER="$WORK/ingress_go"
MW_PIDS=()
for i in 0 1 2 3; do
  MW_OFF=$(( i * MW_PER ))
  MW_CNT=$MW_PER
  [ "$i" = "3" ] && MW_CNT=$(( MW_N - MW_PER * 3 ))
  python -m matching_engine_tpu.client.cli submit-shm "$IN_RING" "$MW_OPS" \
    --offset "$MW_OFF" --count "$MW_CNT" --chunk 128 --timeout 300 --quiet \
    --summary-json "$WORK/ingress_w$i.json" \
    --ready-file "$WORK/ingress_ready.$i" --start-barrier "$MW_BARRIER" \
    >/dev/null 2>"$WORK/ingress_w$i.err" &
  MW_PIDS+=($!)
done
for i in 0 1 2 3; do
  for t in $(seq 1 120); do [ -f "$WORK/ingress_ready.$i" ] && break; sleep 0.5; done
  [ -f "$WORK/ingress_ready.$i" ] || { echo "FAIL: concurrent shm writer $i never attached"; cat "$WORK/ingress_w$i.err" 2>/dev/null; exit 1; }
done
: > "$MW_BARRIER"
MW_FAIL=0
for p in "${MW_PIDS[@]}"; do
  wait "$p"; rc=$?
  # Exit 3 = replay completed with zero accepts (books at capacity under
  # concurrent re-submission) — the store identity below still holds.
  [ "$rc" = "0" ] || [ "$rc" = "3" ] || MW_FAIL=1
done
[ "$MW_FAIL" = "0" ] || { echo "FAIL: a concurrent shm writer failed"; cat "$WORK"/ingress_w*.err; exit 1; }
IN_SCRAPE2="$WORK/ingress_scrape_mw.prom"
python - "$IN_OBS" > "$IN_SCRAPE2" <<'EOF'
import sys, time, urllib.request
try:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{sys.argv[1]}/metrics", timeout=5).read().decode()
    print(f"# scrape-ingress-mw {time.time():.3f}")
    print(body)
except Exception as e:
    print(f"# scrape-failed {time.time():.3f} {type(e).__name__}: {e}")
EOF
cat "$IN_SCRAPE2" >> "$METRICS_OUT"
check_audit "$IN_OBS" "ingress-mw" \
  || { echo "FAIL: audit violations in the multi-writer ingress phase"; exit 1; }
MW_CHECK=$(python - "$WORK" "$IN_SCRAPE2" "$IN_DB" "$IN_SUBMITS" <<'EOF'
import glob, json, re, sqlite3, sys
work, scrape_p, db = sys.argv[1], sys.argv[2], sys.argv[3]
base_submits = int(sys.argv[4])
sums = [json.load(open(p))
        for p in sorted(glob.glob(f"{work}/ingress_w[0-3].json"))]
scrape = open(scrape_p).read()
mw_sum = sum(s["accepted_submits"] for s in sums)
pushed_ok = (len(sums) == 4
             and all(s["pushed"] == s["ops"] for s in sums))
wids = [s["writer_id"] for s in sums]
distinct = len(set(wids)) == 4 and all(w > 0 for w in wids)
orders = sqlite3.connect(db).execute(
    "SELECT COUNT(*) FROM orders WHERE status != 4").fetchone()[0]
have_w = all(
    re.search(rf"^me_ingress_writer{w}_records_total ", scrape, re.M)
    for w in wids)
have_gauge = re.search(r"^me_ingress_writers ", scrape, re.M) is not None
ok = (pushed_ok and distinct and mw_sum > 0
      and orders == base_submits + mw_sum and have_w and have_gauge)
print(f"{int(ok)} {mw_sum} {orders} {base_submits} {int(have_w)} "
      f"{int(have_gauge)} {','.join(map(str, wids))}")
EOF
)
read -r MW_OK MW_SUM MW_ORDERS MW_BASE MW_HAVEW MW_GAUGE MW_WIDS <<< "$(echo "$MW_CHECK" | tail -1)"
kill -TERM $IN_SRV 2>/dev/null; wait $IN_SRV 2>/dev/null
trap 'kill $SRV 2>/dev/null' EXIT
if [ "$MW_OK" != "1" ]; then
  echo "FAIL: multi-writer ingress mismatch (summed_writer_acks=$MW_SUM store_orders=$MW_ORDERS phase1_acks=$MW_BASE writer_series_ok=$MW_HAVEW writers_gauge_ok=$MW_GAUGE wids=$MW_WIDS)"
  exit 1
fi
echo "ingress round (4 writers): store rows == phase-1 + summed per-writer acks ($MW_ORDERS == $MW_BASE + $MW_SUM), lanes $MW_WIDS, me_ingress_writer* green"

# ---- corruption-injection round: the auditor must fire --------------------
# Boots a server with ME_AUDIT_FAULT=fill_qty (one fill record's quantity
# mutated between decode and publish), drives crossing flow, and asserts
# the INVERSE of every other round: /auditz must go red with
# me_audit_violations_total > 0 naming the conservation class, and the
# violation must flight-dump. A soak whose auditor cannot be made to fire
# proves nothing about the rounds where it stayed quiet.
CI_DB="$WORK/soak_corrupt.db"
PYTHONUNBUFFERED=1 ME_AUDIT_FAULT=fill_qty python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$CI_DB" --symbols 16 --capacity 64 --batch 8 \
  --window-ms 1 --metrics-port 0 --flight-dir "$WORK/corrupt_flight" \
  $AUDIT_ARGS ${SOAK_SERVER_ARGS:-} \
  > "$WORK/server_corrupt.log" 2>&1 &
CI_SRV=$!
trap 'kill $SRV $CI_SRV 2>/dev/null' EXIT
CI_PY=""; CI_OBS=""
for i in $(seq 1 "$BOOT_WAIT"); do
  CI_PY=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$WORK/server_corrupt.log" | head -1)
  CI_OBS=$(sed -n 's/.*metrics on port \([0-9]*\).*/\1/p' "$WORK/server_corrupt.log" | head -1)
  [ -n "$CI_PY" ] && [ -n "$CI_OBS" ] && break
  kill -0 $CI_SRV 2>/dev/null || { echo "FAIL: corruption server died at boot"; tail -5 "$WORK/server_corrupt.log"; exit 1; }
  sleep 1
done
[ -n "$CI_PY" ] && [ -n "$CI_OBS" ] || { echo "FAIL: corruption server ports never appeared"; exit 1; }
# Crossing flow guarantees fill records for the injector to corrupt.
"$CLI" bench "127.0.0.1:$CI_PY" 8 50 12 4 >/dev/null 2>&1 || true
sleep 2
CI_VERDICT=$(python - "$CI_OBS" <<'EOF'
import json, sys, urllib.request, urllib.error
port = sys.argv[1]
try:
    urllib.request.urlopen(f"http://127.0.0.1:{port}/auditz", timeout=5)
    code, doc = 200, {}
except urllib.error.HTTPError as e:
    code, doc = e.code, json.loads(e.read().decode())
viol = doc.get("violations", 0)
kinds = doc.get("by_kind", {})
ok = code == 500 and viol > 0 and "conservation" in kinds
# Compact JSON (no spaces): the caller word-splits this line.
print(f"{int(ok)} {code} {viol} {json.dumps(kinds, separators=(',', ':'))}")
EOF
)
read -r CI_OK CI_CODE CI_VIOL CI_KINDS <<< "$(echo "$CI_VERDICT" | tail -1)"
if [ "$CI_OK" != "1" ]; then
  echo "FAIL: injected corruption went UNDETECTED (auditz code=$CI_CODE violations=$CI_VIOL kinds=$CI_KINDS)"
  exit 1
fi
kill -TERM $CI_SRV 2>/dev/null; wait $CI_SRV 2>/dev/null
trap 'kill $SRV 2>/dev/null' EXIT
CI_DUMP=$(grep -l "audit_violation" "$WORK"/corrupt_flight/flight_*.json 2>/dev/null | head -1)
[ -n "$CI_DUMP" ] || { echo "FAIL: corruption fired but produced no flight dump"; exit 1; }
echo "corruption round: auditor fired as required (violations=$CI_VIOL kinds=$CI_KINDS)"

# ---- failover round: kill the primary, promote the standby ----------------
# Warm-standby HA under fire (replication/, ISSUE 11): an --oplog-ship
# primary + a --standby replica + the native bench as concurrent load +
# a sequenced subscriber riding the STANDBY's own feed line. SIGKILL the
# primary mid-flow, `client promote` the standby, and FAIL on:
#   - store bit-identity mismatch between the promoted replica and the
#     dead primary's db for the acknowledged prefix (replication/verify),
#   - any unrecovered client gap or != 1 epoch rebase at the subscriber,
#   - missing me_repl_* metrics on either side,
#   - /replz red (the replica must stay provably clean through the kill).
HA_PDB="$WORK/soak_ha_primary.db"
HA_SDB="$WORK/soak_ha_standby.db"
PYTHONUNBUFFERED=1 python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$HA_PDB" --symbols 16 --capacity 64 --batch 8 \
  --window-ms 1 --metrics-port 0 --oplog-ship \
  $AUDIT_ARGS ${SOAK_SERVER_ARGS:-} \
  > "$WORK/server_ha_primary.log" 2>&1 &
HA_PSRV=$!
trap 'kill $SRV $HA_PSRV 2>/dev/null' EXIT
HA_PPY=""; HA_POBS=""
for i in $(seq 1 "$BOOT_WAIT"); do
  HA_PPY=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$WORK/server_ha_primary.log" | head -1)
  HA_POBS=$(sed -n 's/.*metrics on port \([0-9]*\).*/\1/p' "$WORK/server_ha_primary.log" | head -1)
  [ -n "$HA_PPY" ] && [ -n "$HA_POBS" ] && break
  kill -0 $HA_PSRV 2>/dev/null || { echo "FAIL: HA primary died at boot"; tail -5 "$WORK/server_ha_primary.log"; exit 1; }
  sleep 1
done
[ -n "$HA_PPY" ] && [ -n "$HA_POBS" ] || { echo "FAIL: HA primary ports never appeared"; exit 1; }
PYTHONUNBUFFERED=1 python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$HA_SDB" --symbols 16 --capacity 64 --batch 8 \
  --window-ms 1 --metrics-port 0 --standby "127.0.0.1:$HA_PPY" \
  --flight-dir "$WORK/ha_flight" ${SOAK_SERVER_ARGS:-} \
  > "$WORK/server_ha_standby.log" 2>&1 &
HA_SSRV=$!
trap 'kill $SRV $HA_PSRV $HA_SSRV 2>/dev/null' EXIT
HA_SPY=""; HA_SOBS=""
for i in $(seq 1 "$BOOT_WAIT"); do
  HA_SPY=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$WORK/server_ha_standby.log" | head -1)
  HA_SOBS=$(sed -n 's/.*metrics on port \([0-9]*\).*/\1/p' "$WORK/server_ha_standby.log" | head -1)
  [ -n "$HA_SPY" ] && [ -n "$HA_SOBS" ] && break
  kill -0 $HA_SSRV 2>/dev/null || { echo "FAIL: HA standby died at boot"; tail -5 "$WORK/server_ha_standby.log"; exit 1; }
  sleep 1
done
[ -n "$HA_SPY" ] && [ -n "$HA_SOBS" ] || { echo "FAIL: HA standby ports never appeared"; exit 1; }
# Sequenced subscriber on the STANDBY's feed line: it must cross the
# promotion with zero unrecovered gaps and exactly one epoch rebase.
HA_FEED="$FEED_DIR/ha.json"
python -m matching_engine_tpu.client.cli subscribe "127.0.0.1:$HA_SPY" \
  md S1 --idle-exit 120 --quiet \
  --summary-json "$HA_FEED" >/dev/null 2>"$FEED_DIR/ha.err" &
HA_FEED_PID=$!
# Concurrent load at the primary; the kill lands while it still runs.
"$CLI" bench "127.0.0.1:$HA_PPY" 4 4000 8 1 \
  > "$WORK/ha_bench.json" 2>/dev/null &
HA_LOAD=$!
HA_SYNC=$(python - "$HA_SOBS" <<'EOF'
import sys, time, urllib.request
port = sys.argv[1]
deadline = time.monotonic() + 120
applied = -1.0
while time.monotonic() < deadline:
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
    except Exception:
        time.sleep(0.5); continue
    m = {l.split()[0]: float(l.split()[1]) for l in body.splitlines()
         if l.startswith("me_repl_")}
    applied = m.get("me_repl_applied_dispatches_total", 0)
    # Mid-flow, not drained: some dispatches applied and the replica
    # keeps up (bounded lag), while the bench is still submitting.
    if applied >= 20 and m.get("me_repl_lag_seqs", 1e9) <= 64:
        print(f"1 {int(applied)}"); sys.exit(0)
    time.sleep(0.2)
print(f"0 {int(applied)}")
EOF
)
read -r HA_SYNCED HA_APPLIED <<< "$(echo "$HA_SYNC" | tail -1)"
[ "$HA_SYNCED" = "1" ] || { echo "FAIL: standby never synced under load (applied=$HA_APPLIED)"; exit 1; }
# Primary-side me_repl_* must exist BEFORE the kill (after it there is
# nothing left to scrape).
HA_PSCRAPE="$WORK/ha_primary_scrape.prom"
python - "$HA_POBS" > "$HA_PSCRAPE" <<'EOF'
import sys, urllib.request
print(urllib.request.urlopen(
    f"http://127.0.0.1:{sys.argv[1]}/metrics", timeout=5).read().decode())
EOF
grep -q "^me_repl_oplog_dispatches_total" "$HA_PSCRAPE" \
  || { echo "FAIL: me_repl_oplog_* metrics absent from the primary scrape"; exit 1; }
# The kill: SIGKILL, no drain, no flush, load still in flight.
kill -9 $HA_PSRV 2>/dev/null; wait $HA_PSRV 2>/dev/null
trap 'kill $SRV $HA_SSRV 2>/dev/null' EXIT
python -m matching_engine_tpu.client.cli promote "127.0.0.1:$HA_SPY" \
  || { echo "FAIL: promote RPC failed"; exit 1; }
# Fresh flow must be accepted by the promoted replica (on the
# subscriber's symbol so the feed line provably carries the new epoch).
python -m matching_engine_tpu.client.cli "127.0.0.1:$HA_SPY" \
  ha-post S1 BUY LIMIT 9000 4 1 | grep -q accepted \
  || { echo "FAIL: promoted replica rejected fresh flow"; exit 1; }
wait $HA_LOAD 2>/dev/null || true  # died with the primary mid-RPC: expected
# Standby-side me_repl_* + /replz verdict (must be green: promoted,
# zero divergences, no poison).
HA_SSCRAPE="$WORK/ha_standby_scrape.prom"
python - "$HA_SOBS" > "$HA_SSCRAPE" <<'EOF'
import sys, urllib.request
print(urllib.request.urlopen(
    f"http://127.0.0.1:{sys.argv[1]}/metrics", timeout=5).read().decode())
EOF
cat "$HA_PSCRAPE" "$HA_SSCRAPE" >> "$METRICS_OUT"
for series in me_repl_applied_dispatches_total me_repl_attested_dispatches_total \
    me_repl_divergences_total me_repl_heartbeat_age_s me_repl_lag_seqs \
    me_repl_lag_bytes me_repl_promotions_total; do
  grep -q "^$series" "$HA_SSCRAPE" \
    || { echo "FAIL: $series absent from the standby scrape"; exit 1; }
done
HA_REPLZ=$(python - "$HA_SOBS" <<'EOF'
import json, sys, urllib.request, urllib.error
try:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{sys.argv[1]}/replz", timeout=5).read().decode()
    code = 200
except urllib.error.HTTPError as e:
    body, code = e.read().decode(), e.code
doc = json.loads(body)
ok = (code == 200 and doc.get("ok") and doc.get("promoted")
      and doc.get("divergences") == 0 and not doc.get("poisoned"))
print(f"{int(ok)} {code} {doc.get('divergences')} {doc.get('applied_dispatches')}")
EOF
)
read -r HA_ROK HA_RCODE HA_DIVERGENCES HA_SAPPLIED <<< "$(echo "$HA_REPLZ" | tail -1)"
[ "$HA_ROK" = "1" ] || { echo "FAIL: /replz red after promotion (code=$HA_RCODE divergences=$HA_DIVERGENCES)"; exit 1; }
# Subscriber crossed the epoch bump: zero unrecovered gaps (exit 4 is
# the cli's unrecovered-gap verdict), exactly one rebase in the summary.
kill -INT $HA_FEED_PID 2>/dev/null || true
wait $HA_FEED_PID; HA_FEED_RC=$?
if [ "$HA_FEED_RC" -eq 4 ]; then
  echo "FAIL: unrecovered feed gap across the failover"
  cat "$FEED_DIR/ha.err"; exit 1
fi
if [ "$HA_FEED_RC" -ne 0 ] || [ ! -s "$HA_FEED" ]; then
  echo "FAIL: feed subscriber broke in the failover round (rc=$HA_FEED_RC)"
  cat "$FEED_DIR/ha.err"; exit 1
fi
HA_REBASES=$(python - "$HA_FEED" <<'EOF'
import json, sys
print(json.load(open(sys.argv[1])).get("epoch_rebases", -1))
EOF
)
[ "$HA_REBASES" = "1" ] \
  || { echo "FAIL: subscriber saw $HA_REBASES epoch rebases across promotion (want exactly 1)"; exit 1; }
# Graceful stop drains the promoted replica's sink, then the store
# bit-identity verdict: the dead primary's db and the promoted
# replica's db must be prefix-consistent cuts of one history.
kill -TERM $HA_SSRV 2>/dev/null; wait $HA_SSRV 2>/dev/null
trap 'kill $SRV 2>/dev/null' EXIT
python -m matching_engine_tpu.replication.verify --promoted "$HA_PDB" "$HA_SDB" \
  > "$WORK/ha_verify.json" \
  || { echo "FAIL: store bit-identity mismatch between dead primary and promoted replica"; \
       cat "$WORK/ha_verify.json"; exit 1; }
echo "failover round: promoted after SIGKILL (applied=$HA_SAPPLIED divergences=$HA_DIVERGENCES rebases=$HA_REBASES), stores prefix-identical"

# ---- latency round: open-loop tail gate -----------------------------------
# Boots a fourth server with the tail levers ON (--busy-poll-us,
# --book-cache-ms, --proto-reuse) and --trace-dir, runs latency_bench's
# open-loop gRPC mode at 50% of its measured peak, and fails the round
# if end-to-end p99 > 10x p50 or the scrape lacks the _p999 gauges.
# The trace file (finalized at clean shutdown) lands beside the artifact.
LT_DB="$WORK/soak_latency.db"
LT_TRACE="$WORK/latrace"
PYTHONUNBUFFERED=1 python -m matching_engine_tpu.server.main \
  --addr 127.0.0.1:0 --db "$LT_DB" --symbols 16 --capacity 64 --batch 8 \
  --window-ms 1 --metrics-port 0 --busy-poll-us 50 --book-cache-ms 5 \
  --proto-reuse --trace-dir "$LT_TRACE" --trace-sample 32 \
  $AUDIT_ARGS ${SOAK_SERVER_ARGS:-} \
  > "$WORK/server_latency.log" 2>&1 &
LT_SRV=$!
trap 'kill $SRV $LT_SRV 2>/dev/null' EXIT
LT_PY=""; LT_OBS=""
for i in $(seq 1 "$BOOT_WAIT"); do
  LT_PY=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$WORK/server_latency.log" | head -1)
  LT_OBS=$(sed -n 's/.*metrics on port \([0-9]*\).*/\1/p' "$WORK/server_latency.log" | head -1)
  [ -n "$LT_PY" ] && [ -n "$LT_OBS" ] && break
  kill -0 $LT_SRV 2>/dev/null || { echo "FAIL: latency server died at boot"; tail -5 "$WORK/server_latency.log"; exit 1; }
  sleep 1
done
[ -n "$LT_PY" ] && [ -n "$LT_OBS" ] || { echo "FAIL: latency server ports never appeared"; exit 1; }
LT_OUT="$WORK/latency_round.json"
python benchmarks/latency_bench.py --addr "127.0.0.1:$LT_PY" \
  --load-fractions 0.5 --repeats 2 --duration-s 4 --peak-s 2 \
  --scrape "http://127.0.0.1:$LT_OBS/metrics" --json-out "$LT_OUT" \
  >/dev/null 2>"$WORK/latency_bench.err" \
  || { echo "FAIL: latency_bench failed"; cat "$WORK/latency_bench.err"; exit 1; }
LT_GATE=$(python - "$LT_OUT" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
row = doc["rows"][0]
p999 = doc.get("server_p999_gauges", [])
ok = row["p99_over_p50"] < 10 and bool(p999)
print(f"{int(ok)} {row['e2e']['p50_ms']} {row['e2e']['p99_ms']} "
      f"{row['p99_over_p50']} {len(p999)}")
EOF
)
read -r LT_OK LT_P50 LT_P99 LT_RATIO LT_NP999 <<< "$(echo "$LT_GATE" | tail -1)"
if [ "$LT_OK" != "1" ]; then
  echo "FAIL: latency round gate (p50=${LT_P50}ms p99=${LT_P99}ms ratio=${LT_RATIO} p999_gauges=${LT_NP999})"
  exit 1
fi
check_audit "$LT_OBS" "latency" \
  || { echo "FAIL: audit violations in the latency round"; exit 1; }
# Clean shutdown finalizes the trace JSON; keep it beside the artifact.
kill -TERM $LT_SRV 2>/dev/null; wait $LT_SRV 2>/dev/null
trap 'kill $SRV 2>/dev/null' EXIT
LT_TRACE_FILE=$(ls -t "$LT_TRACE"/trace_*.json 2>/dev/null | head -1)
[ -n "$LT_TRACE_FILE" ] || { echo "FAIL: latency round produced no trace file"; exit 1; }
cp "$LT_TRACE_FILE" "$OUT_DIR/soak_${TS}_trace.json"

sleep 2
AUDIT=$(python - "$DB" <<'EOF'
import sys
sys.path.insert(0, "scripts")
from audit import audit
problems = audit(sys.argv[1])
print(len(problems))
EOF
)
AUDIT=$(echo "$AUDIT" | tail -1)
kill $SRV 2>/dev/null; wait $SRV 2>/dev/null; trap - EXIT
# Clean shutdown dumps the flight recorder; keep the post-mortem with
# the artifact (ls -t: newest dump wins if an error dumped earlier too).
FLIGHT=$(ls -t "$WORK"/flight/flight_*.json 2>/dev/null | head -1)
[ -n "$FLIGHT" ] && cp "$FLIGHT" "$OUT_DIR/soak_${TS}_flight.json"

python - "$OUT_DIR/soak_${TS}.json" <<EOF
import glob, json, os, subprocess, sys
rev = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                     capture_output=True, text=True).stdout.strip()
# Surveillance verdicts: one /auditz snapshot per round. A round whose
# section is MISSING fails the soak — an artifact without the audit
# evidence proves nothing about the rounds it claims were clean.
auditz = {}
for path in sorted(glob.glob(os.path.join("$AUDITZ_DIR", "*.json"))):
    name = os.path.basename(path)[:-5]
    try:
        doc = json.load(open(path))
    except ValueError:
        print(f"FAIL: unreadable auditz section {name}"); sys.exit(1)
    auditz[name] = {"ok": doc.get("ok"), "records": doc.get("records"),
                    "violations": doc.get("violations"),
                    "store_checks": doc.get("store", {}).get("checks")}
required = ["round_0", "sharded", "megadispatch", "batch", "latency"]
missing = [n for n in required if n not in auditz]
if missing:
    print(f"FAIL: /auditz section(s) missing from the artifact: {missing}")
    sys.exit(1)
# Max subscriber lag over the whole soak, from the per-round scrapes.
max_lag = 0.0
try:
    for line in open("$METRICS_OUT"):
        if line.startswith("me_feed_subscriber_lag_max "):
            max_lag = max(max_lag, float(line.split()[1]))
except OSError:
    max_lag = -1.0
artifact = {
    "metric": "soak", "minutes": $MINUTES, "rounds": $ROUNDS,
    "orders_ok": $OK_TOTAL, "cancels": $CANCELS, "amends": $AMENDS,
    "audit_violations": int("$AUDIT".strip() or -1),
    "platform": "$SOAK_PLATFORM", "git_rev": rev,
    "server_args": "$SOAK_SERVER_ARGS",
    "feed": {"events": $FEED_EVENTS, "gaps_detected": $FEED_GAPS,
             "gap_filled_events": $FEED_FILLED,
             "max_subscriber_lag": max_lag},
    "sharded_round": {"serve_shards": 2, "orders_ok": $SH_OK,
                      "id_collisions": int("$SH_COLLISIONS" or -1)},
    "megadispatch_round": {"max_waves": 4, "orders_ok": $MD_OK,
                           "audit_violations": int("$MD_AUDIT" or -1)},
    "batch_round": {"batch_size": 256, "accepted": int("$BE_ACC" or -1),
                    "rejected": int("$BE_REJ" or -1),
                    "store_rows": int("$BE_ROWS" or -1),
                    "native_lanes": True, "megadispatch_max_waves": 4},
    "latency_round": {"load_fraction": 0.5, "p50_ms": $LT_P50,
                      "p99_ms": $LT_P99, "p99_over_p50": $LT_RATIO,
                      "p999_gauges": $LT_NP999,
                      "levers": "busy-poll+book-cache+proto-reuse"},
    "flash_crash_round": {"scenario": "flash_crash", "batch_size": 256,
                          "accepted": int("$FC_ACC" or -1),
                          "rejected": int("$FC_REJ" or -1),
                          "ops": int("$FC_TOTAL" or -1),
                          "rejects_counter": int("$FC_COUNTED" or -1),
                          "reject_threshold": 0.25,
                          "audit_sample": 1},
    "ingress_round": {"edge": "shm-ring", "scenario": "flash_crash",
                      "accepted": int("$IN_ACC" or -1),
                      "rejected": int("$IN_REJ" or -1),
                      "ops": int("$IN_TOTAL" or -1),
                      "store_rows": int("$IN_ORDERS" or -1),
                      "accepted_submits": int("$IN_SUBMITS" or -1),
                      "ingress_records": int("$IN_RECORDS" or -1),
                      "audit_sample": 1},
    "auditz": auditz,
    "corruption_round": {"fault": "fill_qty", "detected": True,
                         "violations": int("$CI_VIOL" or -1),
                         "by_kind": json.loads('$CI_KINDS' or "{}")},
    "failover_round": {
        "killed": "SIGKILL mid-flow", "promoted": True,
        "applied_dispatches": int("$HA_SAPPLIED" or -1),
        "divergences": int("$HA_DIVERGENCES" or -1),
        "subscriber_epoch_rebases": int("$HA_REBASES" or -1),
        "stores_prefix_identical": True,
    },
}
json.dump(artifact, open(sys.argv[1], "w"))
print(json.dumps(artifact))
EOF
[ "$(echo "$AUDIT" | tr -d '[:space:]')" = "0" ]
