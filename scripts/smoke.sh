#!/usr/bin/env bash
# End-to-end smoke: spawn the server, fire orders through the real client,
# pattern-match the output. Bash port of the reference's scripts/smoke.ps1
# (4 LIMIT BUY submissions at scales 8/9/2/0, grep `accepted order_id=`,
# kill server) extended with a crossing SELL, a MARKET order, a book query,
# and a cancel.
#
# Usage: scripts/smoke.sh [--tpu] [--native]
#   default: CPU platform, Python grpcio edge + Python CLI client
#   --native: same flow through the C++ gateway (native/me_gateway.cpp)
#             driven by the C++ client (native/me_client.cpp)
set -u

cd "$(dirname "$0")/.."
export PYTHONPATH="${PYTHONPATH:+$PYTHONPATH:}$PWD"
NATIVE=0
for arg in "$@"; do
  case "$arg" in
    --native) NATIVE=1 ;;
    --tpu) TPU=1 ;;
  esac
done
if [ "${TPU:-0}" != "1" ]; then
  export JAX_PLATFORMS=cpu
  # JAX_PLATFORMS alone is not enough in the axon container: sitecustomize
  # registers the relay + forces the axon platform whenever the pool IPs
  # are set, and a wedged tunnel then hangs interpreter start.
  unset PALLAS_AXON_POOL_IPS
fi

DB=$(mktemp -d)/smoke.db
PORT=$(( ( RANDOM % 10000 ) + 40000 ))
ADDR="127.0.0.1:$PORT"
GW_FLAGS=""
CLIENT=(python -m matching_engine_tpu.client.cli)
if [ "$NATIVE" = "1" ]; then
  make -s -C native   # builds gateway lib + me_client
  GW_PORT=$(( ( RANDOM % 10000 ) + 30000 ))
  GW_FLAGS="--gateway-addr 127.0.0.1:$GW_PORT"
fi

# shellcheck disable=SC2086
python -m matching_engine_tpu.server.main --addr "$ADDR" --db "$DB" \
  --symbols 16 --capacity 32 --batch 4 --window-ms 1 --auction-open \
  $GW_FLAGS &
SERVER_PID=$!
trap 'kill $SERVER_PID 2>/dev/null' EXIT

# wait for the port (the reference sleeps 800ms; jit warmup needs longer)
for i in $(seq 1 120); do
  python - "$ADDR" <<'EOF' 2>/dev/null && break
import socket, sys
host, port = sys.argv[1].rsplit(":", 1)
s = socket.create_connection((host, int(port)), timeout=0.5); s.close()
EOF
  sleep 0.5
done
if [ "$NATIVE" = "1" ]; then
  # The grpcio port binds before the gateway thread starts: wait for the
  # gateway port too before pointing the client at it.
  for i in $(seq 1 120); do
    python - "127.0.0.1:$GW_PORT" <<'EOF2' 2>/dev/null && break
import socket, sys
host, port = sys.argv[1].rsplit(":", 1)
s = socket.create_connection((host, int(port)), timeout=0.5); s.close()
EOF2
    sleep 0.5
  done
  # Submit/cancel flow through the C++ edge with the C++ client; the
  # book/metrics queries stay on the Python CLI (same server, both edges).
  ADDR="127.0.0.1:$GW_PORT"
  CLIENT=(matching_engine_tpu/native/me_client)
fi

PASS=0; FAIL=0
run_case() {
  local desc="$1"; shift
  local want="$1"; shift
  case "${1:-}" in
    book|metrics|watch-*)  # query subcommands: Python CLI on either edge
      out=$(python -m matching_engine_tpu.client.cli "$@" 2>&1) ;;
    *)
      out=$("${CLIENT[@]}" "$@" 2>&1) ;;
  esac
  if echo "$out" | grep -q "$want"; then
    echo "PASS: $desc"
    PASS=$((PASS+1))
  else
    echo "FAIL: $desc"
    echo "  want: $want"
    echo "  got:  $out"
    FAIL=$((FAIL+1))
  fi
}

# Opening call auction (engine/auction.py): the server booted with
# --auction-open, so crossing submits REST (continuous matching would
# fill them instantly), MARKET is rejected, and the all-symbols uncross
# clears the book at one price and opens continuous trading for the
# reference cases below.
run_case "call period: bid rests" "accepted order_id=" "$ADDR" a1 AUC BUY LIMIT 1020 2 4
run_case "call period: crossing ask rests" "accepted order_id=" "$ADDR" a2 AUC SELL LIMIT 1000 2 4
run_case "call period: MARKET rejected" "auction call period" "$ADDR" a3 AUC BUY MARKET 0 0 1
run_case "opening uncross" "cleared 100000@Q4 x4" auction "$ADDR" AUC
run_case "all-symbols uncross opens trading" "0 symbol(s) crossed" auction "$ADDR"

# The reference's four scale cases (smoke.ps1:24-27): LIMIT BUYs at scales 8/9/2/0.
run_case "LIMIT BUY scale 8" "accepted order_id=" "$ADDR" c1 SYM BUY LIMIT 100500000 8 10
run_case "LIMIT BUY scale 9" "accepted order_id=" "$ADDR" c1 SYM BUY LIMIT 1005000000 9 10
run_case "LIMIT BUY scale 2" "accepted order_id=" "$ADDR" c1 SYM BUY LIMIT 1005 2 10
run_case "LIMIT BUY scale 0" "accepted order_id=" "$ADDR" c1 SYM BUY LIMIT 10 0 10

# Beyond the reference: real matching.
run_case "crossing SELL fills" "accepted order_id=" "$ADDR" c2 SYM SELL LIMIT 1005 2 15
run_case "MARKET SELL" "accepted order_id=" "$ADDR" c2 SYM SELL MARKET 0 0 5
run_case "book query" "book SYM" book "127.0.0.1:$PORT" SYM
run_case "reject bad qty" "rejected" "$ADDR" c1 SYM BUY LIMIT 1005 2 0
run_case "cancel unknown" "cancel rejected" cancel "$ADDR" c1 OID-999

# Time-in-force (additive extension): an IOC against an empty level
# cancels instead of resting; a FOK larger than the book cancels
# untouched. Both are ACCEPTED orders whose outcome is the tif semantics.
run_case "LIMIT:IOC accepted" "accepted order_id=" "$ADDR" t1 TIF SELL LIMIT:IOC 1005 2 3
run_case "LIMIT:FOK accepted" "accepted order_id=" "$ADDR" t1 TIF BUY LIMIT:FOK 1005 2 3

# Amend (priority-preserving qty reduction): rest, amend down, reject the
# infeasible non-reduction.
AMEND_OID=$("${CLIENT[@]}" "$ADDR" am AMD BUY LIMIT 1000 2 9 2>&1 \
            | sed -n 's/.*order_id=\(OID-[0-9]*\).*/\1/p')
run_case "amend down" "remaining=4" amend "$ADDR" am "$AMEND_OID" 4
run_case "amend up rejected" "amend rejected" amend "$ADDR" am "$AMEND_OID" 50

# Out-of-band DB assert (the reference pattern, scripted).
sleep 0.5
ORDERS=$(python -c "
import sqlite3
c = sqlite3.connect('$DB')
print(c.execute('SELECT COUNT(*) FROM orders').fetchone()[0])
")
FILLS=$(python -c "
import sqlite3
c = sqlite3.connect('$DB')
print(c.execute('SELECT COUNT(*) FROM fills').fetchone()[0])
")
if [ "$ORDERS" -eq 11 ] && [ "$FILLS" -ge 3 ]; then
  echo "PASS: DB has $ORDERS orders, $FILLS fills"
  PASS=$((PASS+1))
else
  echo "FAIL: DB has $ORDERS orders (want 11), $FILLS fills (want >=3)"
  FAIL=$((FAIL+1))
fi

kill $SERVER_PID 2>/dev/null
wait $SERVER_PID 2>/dev/null
trap - EXIT

echo "smoke: $PASS passed, $FAIL failed"
[ "$FAIL" -eq 0 ]
