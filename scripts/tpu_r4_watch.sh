#!/usr/bin/env bash
# Round-4 TPU watcher: probe the axon tunnel cheaply on an interval; on a
# healthy probe, run the round-4 capture list (benchmarks/capture_r4.py —
# resumable, artifact-existence-checked), and once the list completes,
# keep a warm resident bench process (benchmarks/resident.py) alive so the
# driver's end-of-round bench.py lands a real-TPU figure in seconds
# (VERDICT r3 next-step 1).
#
# Usage: scripts/tpu_r4_watch.sh [&]
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT_DIR="$REPO/benchmarks/results"
LOG="$OUT_DIR/tpu_watch.log"
mkdir -p "$OUT_DIR"

INTERVAL="${TPU_WATCH_INTERVAL_S:-180}"
PROBE_TIMEOUT="${TPU_WATCH_PROBE_TIMEOUT_S:-60}"
MAX_LOOPS="${TPU_WATCH_MAX_LOOPS:-400}"
RESIDENT_LOG="$OUT_DIR/resident.log"

log() { echo "[$(date -u +%Y-%m-%dT%H:%M:%SZ)] $*" >>"$LOG"; }

resident_healthy() {
  # Alive AND fresh: a resident wedged inside a device sync stays
  # pid-alive forever with a stale heartbeat — it must be killed and
  # replaced once the tunnel recovers, or the warm phase-0 path is
  # permanently lost to the first wedge event.
  python - "$REPO" <<'EOF'
import json, os, sys, time
state = os.path.join(sys.argv[1], "benchmarks", ".resident", "state.json")
try:
    s = json.load(open(state))
    os.kill(int(s["pid"]), 0)
except Exception:
    sys.exit(1)
age = time.time() - s.get("heartbeat_ts", 0)
if age > 180:
    try:
        os.kill(int(s["pid"]), 9)
    except OSError:
        pass
    sys.exit(1)
sys.exit(0)
EOF
}

log "r4 watcher start (interval=${INTERVAL}s probe_timeout=${PROBE_TIMEOUT}s)"
for _ in $(seq 1 "$MAX_LOOPS"); do
  if timeout -s KILL "$PROBE_TIMEOUT" python -c \
      "import jax; assert jax.devices()" >>"$LOG" 2>&1; then
    log "probe healthy"
    if python "$REPO/benchmarks/capture_r4.py" >>"$LOG" 2>&1; then
      log "capture list complete"
      if ! resident_healthy; then
        log "starting warm resident"
        nohup python "$REPO/benchmarks/resident.py" >>"$RESIDENT_LOG" 2>&1 &
        sleep 5
      fi
    else
      log "capture list incomplete (rc=$?); retry next window"
    fi
  else
    log "probe unhealthy (rc=$?)"
  fi
  sleep "$INTERVAL"
done
log "r4 watcher exhausted $MAX_LOOPS loops"
