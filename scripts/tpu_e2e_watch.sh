#!/usr/bin/env bash
# E2E-edge watcher: when the axon tunnel is healthy, run one full-stack
# serving capture (both edges) and exit. The experiment body lives in
# scripts/tpu_e2e_r4.sh (one copy of the boot/port-discovery/bench
# protocol); this wrapper only adds the probe loop. Superseded for
# round-4 captures by scripts/tpu_r4_watch.sh + benchmarks/capture_r4.py,
# which include the same experiment as steps e2e_pi2/e2e_pi4 — kept for
# ad-hoc single runs.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOG="$REPO/benchmarks/results/tpu_e2e_watch.log"
INTERVAL="${TPU_WATCH_INTERVAL_S:-300}"
PROBE_TIMEOUT="${TPU_WATCH_PROBE_TIMEOUT_S:-75}"
MAX_LOOPS="${TPU_WATCH_MAX_LOOPS:-200}"
PIPELINE_INFLIGHT="${TPU_E2E_PIPELINE_INFLIGHT:-2}"

log() { echo "[$(date -u +%Y-%m-%dT%H:%M:%SZ)] $*" >>"$LOG"; }

log "e2e watcher start (interval=${INTERVAL}s pi=$PIPELINE_INFLIGHT)"
for _ in $(seq 1 "$MAX_LOOPS"); do
  if timeout -s KILL "$PROBE_TIMEOUT" python -c \
      "import jax; assert jax.devices()" >>"$LOG" 2>&1; then
    log "probe healthy; running e2e experiment"
    if bash "$REPO/scripts/tpu_e2e_r4.sh" "$PIPELINE_INFLIGHT" >>"$LOG" 2>&1; then
      log "e2e experiment complete"
      exit 0
    fi
    log "e2e experiment failed; retry next interval"
  else
    log "probe unhealthy (rc=$?)"
  fi
  sleep "$INTERVAL"
done
log "e2e watcher gave up after $MAX_LOOPS loops"
exit 1
