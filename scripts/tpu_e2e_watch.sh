#!/usr/bin/env bash
# E2E-edge watcher: when the axon tunnel is healthy, boot the full server on
# the real TPU with BOTH serving edges (grpcio + C++ gateway), drive each
# with the native pipelined load generator (me_client bench), and leave the
# two artifacts in benchmarks/results/. Companion to scripts/tpu_watch.sh
# (device-throughput artifact); this one captures the serving-stack
# comparison VERDICT r2 asked for (e2e orders/sec + p50/p99 per edge).
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT_DIR="$REPO/benchmarks/results"
LOG="$OUT_DIR/tpu_e2e_watch.log"
CLI="$REPO/matching_engine_tpu/native/me_client"
mkdir -p "$OUT_DIR"

INTERVAL="${TPU_WATCH_INTERVAL_S:-300}"
PROBE_TIMEOUT="${TPU_WATCH_PROBE_TIMEOUT_S:-75}"
BOOT_TIMEOUT="${TPU_E2E_BOOT_TIMEOUT_S:-300}"
CLIENTS="${TPU_E2E_CLIENTS:-32}"
PER_CLIENT="${TPU_E2E_PER_CLIENT:-2000}"
INFLIGHT="${TPU_E2E_INFLIGHT:-8}"
MAX_LOOPS="${TPU_WATCH_MAX_LOOPS:-200}"

log() { echo "[$(date -u +%Y-%m-%dT%H:%M:%SZ)] $*" >>"$LOG"; }

run_experiment() {
  local ts work
  ts=$(date -u +%Y%m%dT%H%M%SZ)
  work=$(mktemp -d)
  # PYTHONUNBUFFERED: the port-discovery loop below greps the log; without
  # it the '[SERVER] listening' lines sit in the stdio buffer forever.
  PYTHONUNBUFFERED=1 PYTHONPATH="${PYTHONPATH:-}:$REPO" \
    python -m matching_engine_tpu.server.main \
    --addr 127.0.0.1:0 --db "$work/e2e.db" --symbols 64 --capacity 256 \
    --batch 16 --gateway-addr 127.0.0.1:0 >"$work/server.log" 2>&1 &
  local srv=$!
  local waited=0 py_port="" gw_port=""
  while [ "$waited" -lt "$BOOT_TIMEOUT" ]; do
    py_port=$(sed -n 's/.*listening on port \([0-9]*\).*/\1/p' "$work/server.log" | head -1)
    gw_port=$(sed -n 's/.*native gateway on port \([0-9]*\).*/\1/p' "$work/server.log" | head -1)
    if [ -n "$py_port" ] && [ -n "$gw_port" ]; then break; fi
    if ! kill -0 "$srv" 2>/dev/null; then
      log "server died during boot: $(tail -3 "$work/server.log" | tr '\n' ' ')"
      return 1
    fi
    sleep 5
    waited=$((waited + 5))
  done
  if [ -z "$py_port" ] || [ -z "$gw_port" ]; then
    log "server boot timed out (${BOOT_TIMEOUT}s) — tunnel likely re-wedged"
    kill -9 "$srv" 2>/dev/null
    return 1
  fi
  log "server up: grpcio :$py_port native :$gw_port — benching"
  local ok=0
  if timeout 600 "$CLI" bench "127.0.0.1:$gw_port" "$CLIENTS" "$PER_CLIENT" 64 "$INFLIGHT" \
      >"$OUT_DIR/tpu_e2e_native_${ts}.json" 2>>"$LOG"; then
    log "native edge: $(cat "$OUT_DIR/tpu_e2e_native_${ts}.json")"
  else
    log "native edge bench failed"
    rm -f "$OUT_DIR/tpu_e2e_native_${ts}.json"
    ok=1
  fi
  if timeout 600 "$CLI" bench "127.0.0.1:$py_port" "$CLIENTS" "$PER_CLIENT" 64 "$INFLIGHT" \
      >"$OUT_DIR/tpu_e2e_grpcio_${ts}.json" 2>>"$LOG"; then
    log "grpcio edge: $(cat "$OUT_DIR/tpu_e2e_grpcio_${ts}.json")"
  else
    log "grpcio edge bench failed"
    rm -f "$OUT_DIR/tpu_e2e_grpcio_${ts}.json"
    ok=1
  fi
  kill -TERM "$srv" 2>/dev/null
  sleep 5
  kill -9 "$srv" 2>/dev/null
  return "$ok"
}

log "e2e watcher start (interval=${INTERVAL}s clients=$CLIENTS per_client=$PER_CLIENT inflight=$INFLIGHT)"
for _ in $(seq 1 "$MAX_LOOPS"); do
  if timeout "$PROBE_TIMEOUT" python -c \
      "import jax; d=jax.devices(); assert d" >>"$LOG" 2>&1; then
    log "probe healthy; running e2e experiment"
    if run_experiment; then
      log "e2e experiment complete"
      exit 0
    fi
  else
    log "probe unhealthy (rc=$?)"
  fi
  sleep "$INTERVAL"
done
log "e2e watcher gave up after $MAX_LOOPS loops"
exit 1
