#!/usr/bin/env bash
# Rebuild the native runtime from source with the same flags the
# checked-in Makefile uses — the .so files are gitignored build
# artifacts, and this script is the reproducible path to them
# (matching_engine_tpu/native/ensure_built auto-builds only the
# protobuf-free native-lib target; this is the full entry point).
#
#   scripts/build_native.sh [--lib-only] [--force] [--out-dir DIR]
#
# --lib-only   build just libme_native.so (lane engine + ring + sink;
#              needs only a C++20 compiler, sqlite3 and zlib sonames)
# --force      rebuild even if targets look fresh (make -B)
# --out-dir    emit artifacts into DIR instead of the package tree
#              (the smoke test builds into a scratch dir so a test run
#              never swaps the .so under a live process)
#
# The gateway library + CLI client additionally need protoc and the
# protobuf C++ headers; when they are absent those targets are skipped
# with a notice — the grpcio edge still serves, only the C++ edge is
# unavailable.
set -euo pipefail

cd "$(dirname "$0")/../native"

LIB_ONLY=0
FORCE=()
PKG_OVERRIDE=()
while [ $# -gt 0 ]; do
  case "$1" in
    --lib-only) LIB_ONLY=1 ;;
    --force) FORCE=(-B) ;;
    --out-dir)
      shift
      mkdir -p "$1"
      # Command-line make variables override the Makefile's PKG :=.
      PKG_OVERRIDE=("PKG=$(cd "$1" && pwd)")
      ;;
    *) echo "unknown arg: $1" >&2; exit 2 ;;
  esac
  shift
done

CXX="${CXX:-g++}"
command -v "$CXX" >/dev/null || { echo "no C++ compiler ($CXX)" >&2; exit 1; }

make "${FORCE[@]}" "${PKG_OVERRIDE[@]}" native-lib
echo "built: libme_native.so"

if [ "$LIB_ONLY" = 1 ]; then
  exit 0
fi

if command -v protoc >/dev/null; then
  make "${FORCE[@]}" "${PKG_OVERRIDE[@]}"
  echo "built: libme_gateway.so me_client"
else
  echo "protoc not found: skipping libme_gateway.so / me_client" \
       "(grpcio edge still serves; install protobuf + protoc to" \
       "build the C++ gateway edge)" >&2
fi
