#!/usr/bin/env bash
# Rebuild the native runtime from source with the same flags the
# checked-in Makefile uses — the .so files are gitignored build
# artifacts, and this script is the reproducible path to them
# (matching_engine_tpu/native/ensure_built auto-builds only the
# protobuf-free native-lib target; this is the full entry point).
#
#   scripts/build_native.sh [--lib-only] [--force] [--out-dir DIR]
#                           [--sanitize={address,undefined,thread}]
#
# --lib-only   build just libme_native.so (lane engine + ring + sink;
#              needs only a C++20 compiler, sqlite3 and zlib sonames)
# --force      rebuild even if targets look fresh (make -B)
# --out-dir    emit artifacts into DIR instead of the package tree
#              (the smoke test builds into a scratch dir so a test run
#              never swaps the .so under a live process)
# --sanitize   build a sanitizer-instrumented lane library instead:
#              libme_native.<asan|ubsan|tsan>.so (implies --lib-only,
#              always -B; -O1 -g, frame pointers kept). Load it into a
#              python process via ME_NATIVE_LIB=<path> with the matching
#              runtime LD_PRELOADed (an uninstrumented interpreter needs
#              the sanitizer runtime resident first) — that is exactly
#              what the skip-guarded codec-fuzz smoke in
#              tests/test_build_native.py does.
#
# The gateway library + CLI client additionally need protoc and the
# protobuf C++ headers; when they are absent those targets are skipped
# with a notice — the grpcio edge still serves, only the C++ edge is
# unavailable.
set -euo pipefail

cd "$(dirname "$0")/../native"

LIB_ONLY=0
FORCE=()
PKG_OVERRIDE=()
OUT_DIR=""
SANITIZE=""
while [ $# -gt 0 ]; do
  case "$1" in
    --lib-only) LIB_ONLY=1 ;;
    --force) FORCE=(-B) ;;
    --out-dir)
      shift
      mkdir -p "$1"
      # Command-line make variables override the Makefile's PKG :=.
      OUT_DIR="$(cd "$1" && pwd)"
      PKG_OVERRIDE=("PKG=$OUT_DIR")
      ;;
    --sanitize=*) SANITIZE="${1#--sanitize=}" ;;
    --sanitize) shift; SANITIZE="$1" ;;
    *) echo "unknown arg: $1" >&2; exit 2 ;;
  esac
  shift
done

CXX="${CXX:-g++}"
command -v "$CXX" >/dev/null || { echo "no C++ compiler ($CXX)" >&2; exit 1; }

if [ -n "$SANITIZE" ]; then
  case "$SANITIZE" in
    address)   SUFFIX=asan ;;
    undefined) SUFFIX=ubsan ;;
    thread)    SUFFIX=tsan ;;
    *) echo "unknown sanitizer: $SANITIZE (address|undefined|thread)" >&2
       exit 2 ;;
  esac
  if [ -z "$OUT_DIR" ]; then
    # Building in-tree would first overwrite the production .so and
    # then rename it away — a sanitized build always goes to a scratch
    # dir and is loaded explicitly via ME_NATIVE_LIB.
    echo "--sanitize requires --out-dir DIR (never builds in-tree)" >&2
    exit 2
  fi
  DIR="$OUT_DIR"
  # Same recipe as the Makefile's native-lib target (the make run below
  # IS that recipe, with the hardening flags layered on): -O1 keeps the
  # sanitizer's line info honest, frame pointers keep its stacks whole.
  # -fsanitize=thread subsumes nothing: each variant is its own build.
  make -B "${PKG_OVERRIDE[@]}" native-lib \
    CXXFLAGS="-O1 -g -std=c++20 -fPIC -Wall -Wextra -pthread \
-fno-omit-frame-pointer -fsanitize=$SANITIZE"
  mv "$DIR/libme_native.so" "$DIR/libme_native.$SUFFIX.so"
  echo "built: libme_native.$SUFFIX.so (-fsanitize=$SANITIZE)"
  exit 0
fi

make "${FORCE[@]}" "${PKG_OVERRIDE[@]}" native-lib
echo "built: libme_native.so"

if [ "$LIB_ONLY" = 1 ]; then
  exit 0
fi

if command -v protoc >/dev/null; then
  make "${FORCE[@]}" "${PKG_OVERRIDE[@]}"
  echo "built: libme_gateway.so me_client"
else
  echo "protoc not found: skipping libme_gateway.so / me_client" \
       "(grpcio edge still serves; install protobuf + protoc to" \
       "build the C++ gateway edge)" >&2
fi
