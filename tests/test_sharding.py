"""Multi-chip parity: the shard_map'd engine vs single-device vs oracle.

Runs on the virtual 8-device CPU mesh (tests/conftest.py). The symbol-sharded
step must produce bit-identical statuses, fills, and resting books to the
single-device kernel — sharding is a layout choice, never a semantics choice.
"""

import jax
import numpy as np
import pytest

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import (
    HostOrder,
    apply_orders,
    build_batches,
    random_order_stream,
    snapshot_books,
)
from matching_engine_tpu.engine.kernel import OP_CANCEL, OP_SUBMIT
from matching_engine_tpu.parallel import ShardedEngine, make_mesh
from matching_engine_tpu.proto import BUY, LIMIT, MARKET, SELL


def _run_sharded(cfg, mesh, host_orders):
    eng = ShardedEngine(cfg, mesh)
    book = eng.init_book()
    results, fills = [], []
    for batch in build_batches(cfg, host_orders):
        batch = eng.place_orders(batch)
        book, out = eng.step(book, batch)
        r, f, overflow = eng.decode(batch, out)
        assert not overflow
        results.extend(r)
        fills.extend(f)
    # Pull the sharded book back to host for snapshot comparison.
    host_book = jax.tree.map(np.asarray, book)
    return results, fills, snapshot_books(host_book), out


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def test_sharded_matches_single_device(mesh8):
    cfg = EngineConfig(num_symbols=16, capacity=32, batch=4, max_fills=256)
    orders = random_order_stream(
        cfg.num_symbols, 400, seed=7, price_base=9_900, price_levels=200,
        price_step=1, qty_max=50,
    )

    book = init_book(cfg)
    book, s_results, s_fills = apply_orders(cfg, book, orders)
    s_snaps = snapshot_books(book)

    d_results, d_fills, d_snaps, _ = _run_sharded(cfg, mesh8, orders)

    key = lambda r: (r.oid, r.sym, r.status, r.filled, r.remaining)
    assert sorted(map(key, d_results)) == sorted(map(key, s_results))
    fkey = lambda f: (f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
    # Per symbol, fills must match exactly in order.
    for s in range(cfg.num_symbols):
        assert [fkey(f) for f in d_fills if f.sym == s] == [
            fkey(f) for f in s_fills if f.sym == s
        ], f"fill mismatch sym {s}"
    assert d_snaps == s_snaps


def test_sharded_top_of_book_gather(mesh8):
    cfg = EngineConfig(num_symbols=8, capacity=8, batch=2, max_fills=64)
    eng = ShardedEngine(cfg, mesh8)
    book = eng.init_book()
    orders = [
        HostOrder(sym=s, op=OP_SUBMIT, side=BUY, otype=LIMIT,
                  price=1000 + s, qty=5, oid=s + 1)
        for s in range(cfg.num_symbols)
    ]
    for batch in build_batches(cfg, orders):
        book, out = eng.step(book, eng.place_orders(batch))
    bb, bs, ba, as_ = eng.all_top_of_book(
        out.best_bid, out.bid_size, out.best_ask, out.ask_size
    )
    np.testing.assert_array_equal(
        np.asarray(bb), np.arange(1000, 1000 + cfg.num_symbols, dtype=np.int32)
    )
    np.testing.assert_array_equal(np.asarray(bs), np.full(cfg.num_symbols, 5))
    np.testing.assert_array_equal(np.asarray(ba), np.zeros(cfg.num_symbols))


def test_sharded_book_stays_sharded(mesh8):
    cfg = EngineConfig(num_symbols=8, capacity=8, batch=2, max_fills=64)
    eng = ShardedEngine(cfg, mesh8)
    book = eng.init_book()
    batch = eng.place_orders(build_batches(
        cfg, [HostOrder(sym=0, op=OP_SUBMIT, side=BUY, otype=LIMIT,
                        price=100, qty=1, oid=1)]
    )[0])
    book, _ = eng.step(book, batch)
    # The updated book must still live sharded across all 8 devices.
    shards = book.bid_qty.sharding.device_set
    assert len(shards) == 8


def test_mesh_size_must_divide_symbols(mesh8):
    with pytest.raises(ValueError):
        ShardedEngine(EngineConfig(num_symbols=12), mesh8)


def test_sharded_sorted_kernel_matches_single_device(mesh8):
    """EngineConfig(kernel='sorted') on the mesh: the shard_map path
    dispatches through the same engine_step_impl switch, so the sorted
    formulation must match its own single-device run shard-for-shard."""
    cfg = EngineConfig(num_symbols=16, capacity=32, batch=4, max_fills=256,
                      kernel="sorted")
    orders = random_order_stream(
        cfg.num_symbols, 300, seed=11, price_base=9_900, price_levels=50,
        price_step=1, qty_max=50,
    )

    book = init_book(cfg)
    book, s_results, s_fills = apply_orders(cfg, book, orders)
    s_snaps = snapshot_books(book)

    d_results, d_fills, d_snaps, _ = _run_sharded(cfg, mesh8, orders)

    key = lambda r: (r.oid, r.sym, r.status, r.filled, r.remaining)
    assert sorted(map(key, d_results)) == sorted(map(key, s_results))
    fkey = lambda f: (f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
    for s in range(cfg.num_symbols):
        assert [fkey(f) for f in d_fills if f.sym == s] == [
            fkey(f) for f in s_fills if f.sym == s
        ], f"fill mismatch sym {s}"
    assert d_snaps == s_snaps
