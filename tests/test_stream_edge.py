"""SubmitOrderStream — the client-streaming ingest rung between
batch RPCs and the shm ring (ROADMAP Open item 3b)."""

from __future__ import annotations

import grpc
import pytest

from matching_engine_tpu.domain import oprec
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown


@pytest.fixture()
def server(tmp_path):
    cfg = EngineConfig(num_symbols=8, capacity=32, batch=4)
    srv, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "db.sqlite"), cfg, log=False)
    srv.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))
    yield stub, parts
    shutdown(srv, parts)


def _flow(n):
    return oprec.pack_records(
        [(1, 1 + i % 2, 0, 10000 + 100 * (i % 3), 1 + i,
          f"S{i % 4}".encode(), b"c%d" % (i % 3), b"")
         for i in range(n)])


def test_stream_positional_parity_with_batch(server):
    """The same records through one stream of 1-record chunks and
    through one SubmitOrderBatch produce the same positional accept/
    reject pattern and the same number of store rows."""
    stub, parts = server
    arr = _flow(12)
    # Poison two positions structurally.
    arr["side"][3] = 9
    arr["quantity"][7] = 0
    resp_b = stub.SubmitOrderBatch(
        pb2.OrderBatchRequest(ops=oprec.encode_payload(arr)), timeout=30)
    assert resp_b.success

    def chunks():
        for i in range(len(arr)):
            yield pb2.OrderBatchRequest(ops=oprec.slice_payload(arr, i, 1))

    resp_s = stub.SubmitOrderStream(chunks(), timeout=60)
    assert resp_s.success
    assert list(resp_s.ok) == list(resp_b.ok)
    assert list(resp_s.error) == list(resp_b.error)
    # Both runs admitted the same 10 submits -> 20 store rows.
    assert parts["storage"].count("orders") == 20
    counters, _ = parts["metrics"].snapshot()
    assert counters["edge_streams"] == 1
    assert counters["edge_stream_ops"] == 12


def test_stream_chunked_multi_record(server):
    """Chunks bigger than one record dispatch as they arrive; the one
    response spans the whole stream in arrival order."""
    stub, _parts = server
    arr = _flow(10)

    def chunks():
        for start in range(0, 10, 4):
            yield pb2.OrderBatchRequest(
                ops=oprec.slice_payload(arr, start, 4))

    resp = stub.SubmitOrderStream(chunks(), timeout=60)
    assert resp.success and len(resp.ok) == 10 and all(resp.ok)
    assert len({oid for oid in resp.order_id}) == 10


def test_stream_codec_reject_fails_stream(server):
    stub, _parts = server

    def chunks():
        yield pb2.OrderBatchRequest(
            ops=oprec.slice_payload(_flow(2), 0, 2))
        yield pb2.OrderBatchRequest(ops=b"NOTMAGIC" + b"\x00" * 384)

    resp = stub.SubmitOrderStream(chunks(), timeout=60)
    assert not resp.success
    assert "magic" in resp.error_message


def test_stream_respects_admission(tmp_path):
    from matching_engine_tpu.server.admission import AdmissionConfig

    cfg = EngineConfig(num_symbols=8, capacity=32, batch=4)
    srv, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "db.sqlite"), cfg, log=False,
        admission_cfg=AdmissionConfig(rate_limit=3, rate_window_s=60.0))
    srv.start()
    try:
        stub = MatchingEngineStub(
            grpc.insecure_channel(f"127.0.0.1:{port}"))
        arr = oprec.pack_records(
            [(1, 1, 0, 10000, 5, b"S0", b"one-client", b"")] * 5)

        def chunks():
            yield pb2.OrderBatchRequest(ops=oprec.encode_payload(arr))

        resp = stub.SubmitOrderStream(chunks(), timeout=60)
        assert resp.success
        assert list(resp.ok) == [True] * 3 + [False] * 2
        assert resp.error[3] == oprec.REASON_MESSAGES[oprec.REASON_RATE]
        counters, _ = parts["metrics"].snapshot()
        assert counters["admission_rate_rejects"] == 2
    finally:
        shutdown(srv, parts)


def test_stream_on_standby_rejects(tmp_path):
    """A read-only standby answers the stream app-level, like every
    other mutation RPC."""
    cfg = EngineConfig(num_symbols=8, capacity=32, batch=4)
    srv, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "db.sqlite"), cfg, log=False)
    parts["service"].read_only = True
    srv.start()
    try:
        stub = MatchingEngineStub(
            grpc.insecure_channel(f"127.0.0.1:{port}"))

        def chunks():
            yield pb2.OrderBatchRequest(
                ops=oprec.encode_payload(_flow(1)))

        resp = stub.SubmitOrderStream(chunks(), timeout=30)
        assert not resp.success
        assert "read-only" in resp.error_message
    finally:
        shutdown(srv, parts)
