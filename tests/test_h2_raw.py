"""Raw-socket HTTP/2 robustness tests against the C++ gateway.

grpc C-core exercises the happy path (tests/test_gateway.py); these drive
the frame handling the RFC requires but well-behaved clients rarely send:
padded frames, CONTINUATION-split header blocks, unknown frame types,
malformed padding, HPACK garbage, oversized frames. Contract: valid-but-
unusual frames still serve the RPC; malformed input closes THAT connection
cleanly while the server keeps serving new ones. The gateway must never
crash — every test ends by proving the server is still alive.
"""

import socket
import struct

import pytest

from matching_engine_tpu import native as me_native
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.proto import pb2

from tests.test_gateway import GwHarness

pytestmark = pytest.mark.skipif(
    not me_native.gateway_available(), reason="native gateway not built"
)

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
SUBMIT_PATH = "/matching_engine.v1.MatchingEngine/SubmitOrder"


@pytest.fixture(scope="module")
def hs(tmp_path_factory):
    h = GwHarness(str(tmp_path_factory.mktemp("h2raw") / "h2raw.db"),
                  cfg=EngineConfig(num_symbols=8, capacity=16, batch=4))
    yield h
    h.close()


def frame(ftype: int, flags: int, stream: int, payload: bytes) -> bytes:
    return struct.pack(">I", len(payload))[1:] + bytes([ftype, flags]) + \
        struct.pack(">I", stream & 0x7FFFFFFF) + payload


def hpack_literal(name: bytes, value: bytes) -> bytes:
    assert len(name) < 127 and len(value) < 127
    return b"\x00" + bytes([len(name)]) + name + bytes([len(value)]) + value


def request_headers() -> bytes:
    return (hpack_literal(b":method", b"POST")
            + hpack_literal(b":scheme", b"http")
            + hpack_literal(b":path", SUBMIT_PATH.encode())
            + hpack_literal(b"te", b"trailers")
            + hpack_literal(b"content-type", b"application/grpc"))


def grpc_body(symbol=b"RAW", client=b"raw", qty=3) -> bytes:
    req = pb2.OrderRequest(client_id=client.decode(), symbol=symbol.decode(),
                           order_type=pb2.LIMIT, side=pb2.BUY, price=10_000,
                           scale=4, quantity=qty)
    msg = req.SerializeToString()
    return b"\x00" + struct.pack(">I", len(msg)) + msg


def connect(port: int) -> socket.socket:
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.settimeout(10)
    s.sendall(PREFACE + frame(0x4, 0, 0, b""))  # empty SETTINGS
    return s


def read_until_stream_end(s: socket.socket, stream_id: int = 1) -> bytes:
    """Collects frame payloads until `stream_id` sees END_STREAM; returns
    every byte received (headers blocks + data) for loose content asserts."""
    got = b""
    while True:
        hdr = b""
        while len(hdr) < 9:
            chunk = s.recv(9 - len(hdr))
            if not chunk:
                raise ConnectionError("closed before stream end")
            hdr += chunk
        length = int.from_bytes(hdr[:3], "big")
        ftype, flags = hdr[3], hdr[4]
        sid = int.from_bytes(hdr[5:9], "big") & 0x7FFFFFFF
        payload = b""
        while len(payload) < length:
            chunk = s.recv(length - len(payload))
            if not chunk:
                raise ConnectionError("closed mid-frame")
            payload += chunk
        got += payload
        if ftype == 0x4 and not flags & 0x1:
            s.sendall(frame(0x4, 0x1, 0, b""))  # SETTINGS ack
        if sid == stream_id and ftype in (0x0, 0x1) and flags & 0x1:
            return got


def assert_server_alive(hs):
    r = hs.stub.SubmitOrder(
        pb2.OrderRequest(client_id="alive", symbol="LIVE",
                         order_type=pb2.LIMIT, side=pb2.BUY, price=10_000,
                         scale=4, quantity=1), timeout=10)
    assert r.success


def test_plain_raw_request(hs):
    s = connect(hs.gw_port)
    hb = request_headers()
    s.sendall(frame(0x1, 0x4, 1, hb))                       # END_HEADERS
    s.sendall(frame(0x0, 0x1, 1, grpc_body()))              # END_STREAM
    got = read_until_stream_end(s)
    assert b"OID-" in got and b"grpc-status" in got
    s.close()


def test_padded_frames_and_priority(hs):
    s = connect(hs.gw_port)
    hb = request_headers()
    # HEADERS: PADDED(0x8) + PRIORITY(0x20) + END_HEADERS(0x4).
    pad = 5
    payload = bytes([pad]) + b"\x00\x00\x00\x02\x10" + hb + b"\x00" * pad
    s.sendall(frame(0x1, 0x4 | 0x8 | 0x20, 1, payload))
    body = grpc_body(symbol=b"PADD")
    s.sendall(frame(0x0, 0x1 | 0x8, 1, bytes([pad]) + body + b"\x00" * pad))
    got = read_until_stream_end(s)
    assert b"OID-" in got
    s.close()


def test_continuation_split_headers(hs):
    s = connect(hs.gw_port)
    hb = request_headers()
    third = len(hb) // 3
    s.sendall(frame(0x1, 0x0, 1, hb[:third]))               # no END_HEADERS
    s.sendall(frame(0x9, 0x0, 1, hb[third:2 * third]))      # CONTINUATION
    s.sendall(frame(0x9, 0x4, 1, hb[2 * third:]))           # END_HEADERS
    s.sendall(frame(0x0, 0x1, 1, grpc_body(symbol=b"CONT")))
    got = read_until_stream_end(s)
    assert b"OID-" in got
    s.close()


def test_unknown_frame_type_ignored(hs):
    s = connect(hs.gw_port)
    s.sendall(frame(0xBB, 0x7, 0, b"junk-payload"))
    s.sendall(frame(0x1, 0x4, 1, request_headers()))
    s.sendall(frame(0x0, 0x1, 1, grpc_body(symbol=b"UNKF")))
    got = read_until_stream_end(s)
    assert b"OID-" in got
    s.close()


def recv_exact(s: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("closed mid-read")
        buf += chunk
    return buf


def test_ping_gets_acked(hs):
    s = connect(hs.gw_port)
    s.sendall(frame(0x6, 0x0, 0, b"12345678"))
    while True:
        hdr = recv_exact(s, 9)
        length = int.from_bytes(hdr[:3], "big")
        payload = recv_exact(s, length) if length else b""
        if hdr[3] == 0x4 and not hdr[4] & 0x1:
            s.sendall(frame(0x4, 0x1, 0, b""))
            continue
        if hdr[3] == 0x6:
            assert hdr[4] & 0x1 and payload == b"12345678"
            break
    s.close()


def test_malformed_padding_closes_connection(hs):
    s = connect(hs.gw_port)
    # pad length (200) > payload: connection error, clean close.
    s.sendall(frame(0x1, 0x4 | 0x8, 1, bytes([200]) + b"xx"))
    with pytest.raises((ConnectionError, socket.timeout, OSError)):
        read_until_stream_end(s)
    s.close()
    assert_server_alive(hs)


def test_hpack_garbage_closes_connection(hs):
    s = connect(hs.gw_port)
    # 0x80 = indexed field, index 0 — always an HPACK decode error.
    s.sendall(frame(0x1, 0x4, 1, b"\x80\xff\xff\xff\xff"))
    with pytest.raises((ConnectionError, socket.timeout, OSError)):
        read_until_stream_end(s)
    s.close()
    assert_server_alive(hs)


def test_oversized_frame_closes_connection(hs):
    s = connect(hs.gw_port)
    # Declared length 0xFFFFFF (16MB-1) exceeds our sanity cap? The cap is
    # 1<<24; 0xFFFFFF == (1<<24)-1 passes the cap but the peer never sends
    # the body — the gateway must not block other connections meanwhile.
    s.sendall(frame(0x1, 0x4, 1, b"")[:3].replace(b"\x00\x00\x00", b"\xff\xff\xff")
              + bytes([0x1, 0x4]) + struct.pack(">I", 1))
    assert_server_alive(hs)  # other connections unaffected
    s.close()
    assert_server_alive(hs)


def test_immediate_disconnect_mid_frame(hs):
    s = connect(hs.gw_port)
    s.sendall(frame(0x1, 0x4, 1, request_headers())[:7])  # truncated header
    s.close()
    assert_server_alive(hs)


def test_zero_window_client_fail_fast(hs):
    """A client advertising INITIAL_WINDOW_SIZE=0 blocks the server's
    response DATA; the gateway must fail fast (bounded ~3s wait, then
    close THAT connection) rather than head-of-line-block the shared
    drain thread forever."""
    import time as _time

    s = socket.create_connection(("127.0.0.1", hs.gw_port), timeout=30)
    s.settimeout(30)
    # SETTINGS: INITIAL_WINDOW_SIZE (0x4) = 0.
    s.sendall(PREFACE + frame(0x4, 0, 0, b"\x00\x04\x00\x00\x00\x00"))
    s.sendall(frame(0x1, 0x4, 1, request_headers()))
    s.sendall(frame(0x0, 0x1, 1, grpc_body(symbol=b"ZWIN")))
    t0 = _time.monotonic()
    with pytest.raises((ConnectionError, socket.timeout, OSError)):
        read_until_stream_end(s)
    dt = _time.monotonic() - t0
    assert dt < 15, f"fail-fast took {dt:.1f}s"
    s.close()
    assert_server_alive(hs)


def test_window_update_spray_is_bounded(hs):
    """WINDOW_UPDATE frames for streams that were never opened must not
    accumulate server-side state (me_gateway.cpp window_update ignores
    unknown/closed streams); the connection keeps serving afterwards."""
    s = connect(hs.gw_port)
    spray = b"".join(
        frame(0x8, 0, sid, struct.pack(">I", 1 << 16))
        for sid in range(3, 4099, 2)  # 2048 idle client-stream ids
    )
    s.sendall(spray)
    hb = request_headers()
    s.sendall(frame(0x1, 0x4, 1, hb))
    s.sendall(frame(0x0, 0x1, 1, grpc_body(symbol=b"WUSP")))
    got = read_until_stream_end(s)
    assert b"OID-" in got
    s.close()
    assert_server_alive(hs)
