"""Scenario-workload subsystem: determinism, phase semantics, oracle
parity on heterogeneous flow, Zipf skew, and serving-stack replay.

The strongest checks close two loops:
- device -> oracle: the heterogeneous agent flow (all four classes, call
  phases included) replays through the host oracle bit-identically on
  BOTH kernels — continuous fills, rested call-period interest, and the
  call-auction uncross all match (test_sim.py's pattern, generalized).
- device -> serving stack: a recorded opfile replays through a real
  in-proc server (build_server + SubmitOrderBatch + RunAuction
  open_call/uncross) with the recorder's order-id renumbering holding —
  the server's fill count and every uncross's executed volume equal the
  sim's own ground truth. This test is also CI's workload smoke.
"""

import dataclasses

import numpy as np
import pytest

from matching_engine_tpu.domain import oprec
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.harness import snapshot_books
from matching_engine_tpu.engine.kernel import (
    OP_CANCEL,
    OP_REST,
    OP_SUBMIT,
)
from matching_engine_tpu.engine.oracle import OracleBook
from matching_engine_tpu.sim.agents import AgentMix
from matching_engine_tpu.sim.record import (
    read_manifest,
    record_scenario,
)
from matching_engine_tpu.sim.scenarios import (
    Phase,
    Scenario,
    make_scenario,
    run_scenario,
    zipf_weights_q15,
)

MIX = AgentMix(mm_agents=8, mm_refresh=2, momentum=2, noise=3, takers=2,
               half_spread=2, spread_jitter=4, qty_max=50, fair_init=1_000,
               noise_qty_cap=120)
CFG = EngineConfig(num_symbols=4, capacity=48, batch=MIX.batch_for(),
                   max_fills=1 << 14)


def _total(phases, field):
    return sum(int(np.sum(np.asarray(getattr(p.stats, field))))
               for p in phases)


# -- determinism ---------------------------------------------------------------


def test_same_seed_records_byte_identical_opfile(tmp_path):
    sc = make_scenario("auction_day", steps=40)
    a, b, c = (str(tmp_path / f"{n}.opfile.gz") for n in "abc")
    ma = record_scenario(CFG, MIX, sc, seed=11, out_path=a)
    mb = record_scenario(CFG, MIX, sc, seed=11, out_path=b)
    mc = record_scenario(CFG, MIX, sc, seed=12, out_path=c)
    assert open(a, "rb").read() == open(b, "rb").read(), \
        "one seed must reproduce the workload artifact byte-for-byte"
    assert ma == mb
    assert open(a, "rb").read() != open(c, "rb").read()
    assert mc["ops"] != ma["ops"] or \
        oprec.read_opfile(c).tobytes() != oprec.read_opfile(a).tobytes()
    # The artifact round-trips through the shared reader (gzip sniffed).
    arr = oprec.read_opfile(a)
    assert len(arr) == ma["ops"] > 0
    assert all(m is None for m in oprec.record_flaws(arr))
    # Manifest rides beside it.
    man = read_manifest(a)
    assert man["name"] == "auction_day" and len(man["phases"]) == 6


# -- phase semantics -----------------------------------------------------------
#
# One auction_day run (the same static phase shapes as the determinism
# and parity tests, so the in-process jit cache is hit, not recompiled)
# covers the halt AND call-period assertions.


def test_auction_day_phase_transitions():
    sc = make_scenario("auction_day", steps=40)
    book, _, phases = run_scenario(CFG, MIX, sc, seed=5,
                                   collect_orders=True)
    kinds = [p.phase.kind for p in phases]
    assert kinds == ["auction", "continuous", "halt", "auction",
                     "continuous", "auction"]
    open_call, cont1, halt, reopen = phases[0], phases[1], phases[2], \
        phases[3]

    # Call periods admit no fills; flow is OP_REST/OP_CANCEL only.
    for call in (open_call, reopen):
        assert int(np.sum(np.asarray(call.stats.fills))) == 0
        ops = np.asarray(call.orders.op)
        assert set(np.unique(ops)) <= {0, OP_CANCEL, OP_REST}
        assert (ops == OP_REST).sum() > 0
        # The accumulated interest crossed and the uncross executed.
        assert call.uncross is not None
        assert int(np.sum(call.uncross.executed)) > 0

    # The halt admits NOTHING: zero ops, zero fills, books frozen.
    assert int(np.sum(np.asarray(halt.stats.real_ops))) == 0
    assert int(np.sum(np.asarray(halt.stats.fills))) == 0
    resting = np.asarray(halt.stats.resting)
    pre = np.asarray(cont1.stats.resting)[-1]
    assert (resting == pre).all()
    # Trading resumes at the reopen (rests) and after it (fills).
    assert int(np.sum(np.asarray(phases[4].stats.fills))) > 0

    # Post-close books are never crossed.
    for bids, asks in snapshot_books(book):
        if bids and asks:
            assert bids[0][1] < asks[0][1]


def test_flash_crash_momentum_amplifies_shock():
    sc = make_scenario("flash_crash", steps=60)
    _, _, phases = run_scenario(CFG, MIX, sc, seed=9, collect_orders=True)
    shock_phase = sc.phases[1]
    assert shock_phase.shock_len > 0
    # Momentum lanes (MARKET ops in the momentum columns) fire more
    # during/after the shock window than in the calm warm-up.
    k = MIX.mm_refresh
    mom_cols = slice(4 * k, 4 * k + MIX.momentum)
    calm = np.asarray(phases[0].orders.op)[:, :, mom_cols]
    crash = np.asarray(phases[1].orders.op)[:, :, mom_cols]
    assert (crash == OP_SUBMIT).sum() > (calm == OP_SUBMIT).sum()
    # The shock actually moves the market down: min mid in the shock
    # phase sits well below the warm-up's last mid.
    assert int(np.asarray(phases[1].stats.volume).sum()) > 0


def test_zipf_skew_skews_per_symbol_op_counts(tmp_path):
    w = zipf_weights_q15(8, int(1.2 * 256))
    assert w[0] == 1 << 15 and w[-1] < w[0] // 8
    sc = make_scenario("hot_symbols", steps=80)
    out = str(tmp_path / "hot.opfile.gz")
    man = record_scenario(CFG, MIX, sc, seed=2, out_path=out)
    per_sym = man["per_symbol_ops"]
    assert per_sym[0] > 3 * min(per_sym[1:]), per_sym
    assert per_sym[0] == max(per_sym), per_sym


def test_bursts_gate_flow_on_and_off():
    sc = Scenario("t", (Phase("continuous", 20, burst_period=10,
                              burst_on=3),))
    _, _, phases = run_scenario(CFG, MIX, sc, seed=4)
    ops = np.asarray(phases[0].stats.real_ops)
    # Off-steps admit nothing; on-steps trade.
    for t in range(20):
        if t % 10 < 3:
            assert ops[t] > 0, t
        else:
            assert ops[t] == 0, t


# -- oracle parity on heterogeneous flow --------------------------------------


@pytest.mark.parametrize("kernel", ["matrix", "sorted"])
def test_heterogeneous_flow_oracle_parity(kernel):
    """Device scenario run == host oracle replay of its own flow, on both
    kernels: continuous fills, call-period rests, and every call-auction
    uncross."""
    cfg = dataclasses.replace(CFG, kernel=kernel)
    sc = make_scenario("auction_day", steps=40)
    book, _, phases = run_scenario(cfg, MIX, sc, seed=13,
                                   collect_orders=True)

    oracles = [OracleBook(capacity=cfg.capacity)
               for _ in range(cfg.num_symbols)]
    o_volume = 0
    o_auction_volume = 0
    for pr in phases:
        op = np.asarray(pr.orders.op)
        side = np.asarray(pr.orders.side)
        otype = np.asarray(pr.orders.otype)
        price = np.asarray(pr.orders.price)
        qty = np.asarray(pr.orders.qty)
        oid = np.asarray(pr.orders.oid)
        t_steps, s_syms, b = op.shape
        for t in range(t_steps):
            for s in range(s_syms):
                for j in range(b):
                    o = int(op[t, s, j])
                    if o == OP_SUBMIT:
                        r = oracles[s].submit(
                            int(oid[t, s, j]), int(side[t, s, j]),
                            int(otype[t, s, j]), int(price[t, s, j]),
                            int(qty[t, s, j]))
                        o_volume += sum(f.quantity for f in r.fills)
                    elif o == OP_REST:
                        oracles[s].rest(
                            int(oid[t, s, j]), int(side[t, s, j]),
                            int(price[t, s, j]), int(qty[t, s, j]))
                    elif o == OP_CANCEL:
                        oracles[s].cancel(int(oid[t, s, j]))
        if pr.uncross is not None:
            dev_exec = np.asarray(pr.uncross.executed)
            dev_price = np.asarray(pr.uncross.clear_price)
            for s in range(s_syms):
                p_star, q, fills = oracles[s].auction()
                assert q == int(dev_exec[s]), f"sym {s} auction volume"
                assert p_star == int(dev_price[s]), f"sym {s} clearing px"
                o_auction_volume += q

    snaps = snapshot_books(book)
    for s in range(cfg.num_symbols):
        ob = oracles[s].snapshot()
        assert snaps[s][0] == ob[0], f"bid book mismatch sym {s}"
        assert snaps[s][1] == ob[1], f"ask book mismatch sym {s}"
    dev_volume = _total(phases, "volume")
    dev_auction = sum(int(np.sum(np.asarray(pr.uncross.executed)))
                      for pr in phases if pr.uncross is not None)
    assert o_volume == dev_volume
    assert o_auction_volume == dev_auction > 0


# -- serving-stack replay (also CI's workload smoke) --------------------------


def test_record_replay_through_inproc_server(tmp_path):
    """A recorded auction-day workload replays through a REAL server —
    call periods opened via RunAuction open_call, uncrossed at phase
    ends, cancels landing on the renumbered ids — and the serving
    stack's fills/uncross volumes equal the sim's ground truth."""
    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.server.main import build_server, shutdown

    sc = make_scenario("auction_day", steps=40)
    out = str(tmp_path / "ad.opfile.gz")
    man = record_scenario(CFG, MIX, sc, seed=7, out_path=out)
    arr = oprec.read_opfile(out)

    scfg = EngineConfig(num_symbols=CFG.num_symbols, capacity=CFG.capacity,
                        batch=8, max_fills=CFG.max_fills)
    server, _port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "w.db"), scfg, window_ms=1.0,
        log=False, feed_depth=0)
    svc = parts["service"]
    try:
        bs = max(1, min(128, man["min_cancel_gap"] or 128))
        acc = rej = 0
        reasons = {}
        uncross = []
        for ph in man["phases"]:
            if ph["kind"] == "auction":
                r = svc.RunAuction(pb2.AuctionRequest(open_call=True),
                                   None)
                assert r.success, r.error_message
                # Venue-wide only: a symbol-scoped open_call refuses.
                bad = svc.RunAuction(
                    pb2.AuctionRequest(symbol="S0", open_call=True), None)
                assert not bad.success
            for s0 in range(ph["start_record"], ph["end_record"], bs):
                payload = oprec.slice_payload(
                    arr, s0, min(bs, ph["end_record"] - s0))
                resp = svc.SubmitOrderBatch(
                    pb2.OrderBatchRequest(ops=payload), None)
                assert resp.success, resp.error_message
                for i, ok in enumerate(resp.ok):
                    if ok:
                        acc += 1
                    else:
                        rej += 1
                        reasons[resp.error[i]] = (
                            reasons.get(resp.error[i], 0) + 1)
            if ph["kind"] == "auction":
                r = svc.RunAuction(pb2.AuctionRequest(), None)
                assert r.success, r.error_message
                uncross.append(int(r.executed_quantity))
        gm = svc.GetMetrics(pb2.MetricsRequest(), None)
        # Bit-faithful replay: the serving stack produced exactly the
        # sim's fills, and every uncross cleared the sim's volume.
        assert gm.counters.get("fills") == man["sim_fills"] > 0
        assert uncross == [p["uncross_executed"] for p in man["phases"]
                           if p["kind"] == "auction"]
        assert acc > 0
        # Rejects are only the structural classes the sim itself rejects
        # (cancels of already-terminal orders) — never codec/ownership/
        # unknown-symbol trouble.
        assert set(reasons) <= {"unknown order id", "order not open"}, \
            reasons
    finally:
        shutdown(server, parts)


def test_simulate_cli_verb(tmp_path):
    """The simulate verb records without any server and reports per-class
    op counts (the workload-artifact production path the soak and CI
    drive)."""
    import json

    from matching_engine_tpu.client.cli import main as cli_main

    out = str(tmp_path / "fc.opfile.gz")
    summary = str(tmp_path / "fc.json")
    rc = cli_main(["simulate", "--scenario", "flash_crash", "--steps",
                   "30", "--seed", "4", "--symbols", "4", "--out", out,
                   "--summary-json", summary])
    assert rc == 0
    s = json.load(open(summary))
    assert s["ops"] > 0 and s["scenario"] == "flash_crash"
    assert set(s["per_class_ops"]) == {"mm", "mom", "nz", "tk"}
    assert s["per_class_ops"]["mm"]["submits"] > 0
    arr = oprec.read_opfile(out)
    assert len(arr) == s["ops"]
    # Unknown scenario: usage-style failure, not a stack trace.
    assert cli_main(["simulate", "--scenario", "nope", "--out",
                     str(tmp_path / "x")]) == 1
