"""Pallas match-kernel parity: bit-identical to the XLA scan path.

Runs in interpret mode on the CPU test platform (conftest forces cpu); the
same kernel compiled on TPU hardware was verified bit-identical against the
XLA path as part of the perf evaluation (see pallas_kernel.py docstring).
The oracle chain is transitive: XLA path == oracle (test_kernel_parity),
pallas == XLA path (here) => pallas == oracle.
"""

import dataclasses

import numpy as np
import pytest

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import build_batches, random_order_stream
from matching_engine_tpu.engine.kernel import engine_step


def _run_parity(cfg, n_orders, seed, **stream_kw):
    cfgp = dataclasses.replace(cfg, pallas=True)
    stream = random_order_stream(cfg.num_symbols, n_orders, seed=seed, **stream_kw)
    batches = build_batches(cfg, stream)
    book_x, book_p = init_book(cfg), init_book(cfgp)
    for i, ob in enumerate(batches):
        book_x, out_x = engine_step(cfg, book_x, ob)
        book_p, out_p = engine_step(cfgp, book_p, ob)
        for f in out_x._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(out_x, f)), np.asarray(getattr(out_p, f)),
                err_msg=f"step {i} output field {f}",
            )
        for f in book_x._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(book_x, f)), np.asarray(getattr(book_p, f)),
                err_msg=f"step {i} book field {f}",
            )
    return len(batches)


def test_pallas_parity_mixed_stream():
    cfg = EngineConfig(num_symbols=8, capacity=16, batch=4, max_fills=1024)
    n = _run_parity(
        cfg, 400, seed=7, cancel_p=0.12, market_p=0.2,
        price_base=9_950, price_levels=30, price_step=1, qty_max=40,
    )
    assert n > 5


def test_pallas_parity_deep_books_and_sweeps():
    # Market sweeps across many levels; books deep enough to overflow a side.
    cfg = EngineConfig(num_symbols=4, capacity=8, batch=8, max_fills=512)
    _run_parity(
        cfg, 600, seed=11, cancel_p=0.05, market_p=0.35,
        price_base=10_000, price_levels=10, price_step=3, qty_max=25,
    )


def test_pallas_parity_odd_symbol_axis():
    # num_symbols not divisible by 8 exercises the smaller symbol blocks.
    cfg = EngineConfig(num_symbols=6, capacity=16, batch=4, max_fills=512)
    _run_parity(
        cfg, 300, seed=13, cancel_p=0.1, market_p=0.1,
        price_base=5_000, price_levels=20, price_step=2, qty_max=30,
    )


@pytest.mark.parametrize("s,expected", [(8, 8), (12, 4), (6, 2), (7, 1), (1024, 8)])
def test_symbol_block_choice(s, expected):
    from matching_engine_tpu.engine.pallas_kernel import _symbol_block

    assert _symbol_block(s) == expected
