"""Multi-host layer: mesh construction, symbol ownership, gated bootstrap.

True multi-process DCN runs need a cluster; these tests exercise the logic
on the virtual 8-device CPU platform (tests/conftest.py) — mesh device
order, ownership slices, divisibility errors, and that the single-process
path of initialize() never touches jax.distributed.
"""

import jax
import pytest

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.parallel import ShardedEngine
from matching_engine_tpu.parallel.multihost import (
    initialize,
    local_symbol_slice,
    make_multihost_mesh,
)


def test_initialize_noops_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    called = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: called.append(kw))
    assert initialize() is False
    assert called == []


def test_initialize_dispatches_when_configured(monkeypatch):
    called = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: called.append(kw))
    assert initialize("coord:1234", num_processes=4, process_id=1) is True
    assert called == [dict(coordinator_address="coord:1234",
                           num_processes=4, process_id=1)]


def test_multihost_mesh_covers_all_devices_and_runs_engine():
    mesh = make_multihost_mesh()
    assert mesh.devices.size == len(jax.devices())
    cfg = EngineConfig(num_symbols=16, capacity=16, batch=4)
    eng = ShardedEngine(cfg, mesh)
    book = eng.init_book()
    assert book.bid_qty.shape == (16, 16)


def test_local_symbol_slice_single_process_owns_everything():
    mesh = make_multihost_mesh()
    sl = local_symbol_slice(mesh, 64)
    assert (sl.start, sl.stop) == (0, 64)


def test_local_symbol_slice_divisibility():
    mesh = make_multihost_mesh()
    with pytest.raises(ValueError, match="not divisible"):
        local_symbol_slice(mesh, 10)


def test_local_symbol_slice_host_major_ranges():
    """Simulate 2 hosts x 4 devices by faking process indices."""

    class FakeDev:
        def __init__(self, pid, did):
            self.process_index = pid
            self.id = did

        def __repr__(self):
            return f"d{self.process_index}.{self.id}"

    import numpy as np

    from jax.sharding import Mesh

    devs = [FakeDev(p, d) for p in range(2) for d in range(4)]

    class FakeMesh:
        devices = np.array(devs)

    # Host 0 owns symbols [0, 32), host 1 owns [32, 64) for 64 symbols.
    import matching_engine_tpu.parallel.multihost as mh

    orig = jax.process_index
    try:
        jax.process_index = lambda: 0
        sl0 = mh.local_symbol_slice(FakeMesh, 64)
        jax.process_index = lambda: 1
        sl1 = mh.local_symbol_slice(FakeMesh, 64)
    finally:
        jax.process_index = orig
    assert (sl0.start, sl0.stop) == (0, 32)
    assert (sl1.start, sl1.stop) == (32, 64)


def test_local_symbol_slice_rejects_interleaved_order():
    class FakeDev:
        def __init__(self, pid, did):
            self.process_index = pid
            self.id = did

    import numpy as np

    devs = [FakeDev(d % 2, d) for d in range(4)]  # interleaved hosts

    class FakeMesh:
        devices = np.array(devs)

    import matching_engine_tpu.parallel.multihost as mh

    orig = jax.process_index
    try:
        jax.process_index = lambda: 0
        with pytest.raises(ValueError, match="host-contiguous"):
            mh.local_symbol_slice(FakeMesh, 64)
    finally:
        jax.process_index = orig


def test_aggregate_host_stores_namespaces_colliding_oids(tmp_path):
    """Two home hosts independently issue OID-1; the aggregator keeps
    both under host namespaces, namespaces fill references consistently,
    and flags a symbol served by two stores (a routing violation) instead
    of silently merging it (VERDICT r4 next-step 9 — the caveat in
    parallel/multihost.py is now code, not prose)."""
    from matching_engine_tpu.parallel.multihost import aggregate_host_stores
    from matching_engine_tpu.storage import Storage
    from matching_engine_tpu.storage.storage import FillRow

    paths = []
    for host, syms in (("h0", ("AAA", "DUP")), ("h1", ("BBB", "DUP"))):
        db = str(tmp_path / f"{host}.db")
        st = Storage(db)
        assert st.init()
        # Both hosts issue the SAME order ids for different orders.
        assert st.insert_new_order("OID-1", f"{host}-cli", syms[0], 1, 0,
                                   10_000, 5, status=2, remaining=0)
        assert st.insert_new_order("OID-2", f"{host}-cli", syms[1], 2, 0,
                                   10_000, 5)
        assert st.add_fill(FillRow("OID-1", "OID-2", 10_000, 5))
        st.close()
        paths.append((host, db))

    agg = aggregate_host_stores(paths)
    assert set(agg["orders"]) == {"h0/OID-1", "h0/OID-2",
                                  "h1/OID-1", "h1/OID-2"}
    assert agg["orders"]["h0/OID-1"]["symbol"] == "AAA"
    assert agg["orders"]["h1/OID-1"]["symbol"] == "BBB"
    assert len(agg["fills"]) == 2
    for f in agg["fills"]:
        assert f["order_id"] in agg["orders"]
        assert f["counter_order_id"] in agg["orders"]
    assert agg["symbol_conflicts"] == [("DUP", ["h0", "h1"])]
