"""Warm-standby replication tests (matching_engine_tpu/replication/).

Layers under test:
- unit: the op-log codec round trip (EngineOps -> flat op records ->
  applier tuples, submits carrying their primary-assigned ids) and the
  prefix-consistency store verifier (identical, legally-advanced, and
  corrupted store pairs).
- e2e (in-proc, the ci.yaml fast smoke): a --standby replica of a live
  --oplog-ship primary applies the identical dispatch sequence, attests
  byte-identity per dispatch against the drop-copy channel, rejects
  every mutation RPC app-level while standby, serves reads, and
  promotes: feed-epoch bump, OID floors past the replicated history,
  mutation RPCs open.
- fault injection: ME_REPL_FAULT=row corrupts exactly one standby-side
  row — the attestor must count a divergence within one dispatch,
  /replz must go red, and the flight recorder must dump both sides.
- promotion hygiene: stale-epoch spill segments purge at the epoch bump,
  and a sequenced subscriber riding across promotion (or resuming after
  it with a pre-promotion cursor — the restart shape) observes exactly
  one epoch rebase and zero unrecovered gaps.
- kill-the-primary: SIGKILL a real primary subprocess under concurrent
  load, promote the in-proc standby, and prove the two stores are
  prefix-consistent cuts of one history (bit-identical rows for every
  dispatch both applied), the promoted server accepts fresh flow with
  collision-free order ids, and a live subscriber crossed the epoch
  bump with zero loss.
"""

from __future__ import annotations

import os
import pathlib
import re
import sqlite3
import subprocess
import sys
import threading
import time
import urllib.request

import grpc
import pytest

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.kernel import OP_AMEND, OP_CANCEL, OP_SUBMIT
from matching_engine_tpu.feed.client import SequencedSubscriber
from matching_engine_tpu.feed.sequencer import CHANNEL_MD
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.replication import ops_from_oprec, ops_to_oprec
from matching_engine_tpu.replication.verify import compare_stores
from matching_engine_tpu.server.engine_runner import EngineOp, OrderInfo
from matching_engine_tpu.server.main import build_server, shutdown

REPO = str(pathlib.Path(__file__).resolve().parent.parent)
CFG = EngineConfig(num_symbols=8, capacity=32, batch=8)

NEW, PARTIAL, FILLED, CANCELED = 0, 1, 2, 3


# -- unit: the op-log codec ---------------------------------------------------


def _info(oid, **kw):
    d = dict(oid=oid, order_id=f"OID-{oid}", client_id="c1", symbol="AAA",
             side=2, otype=0, price_q4=10_000, quantity=5, remaining=5,
             status=NEW, handle=0)
    d.update(kw)
    return OrderInfo(**d)


def test_oplog_codec_round_trip():
    ops = [
        EngineOp(OP_SUBMIT, _info(7, side=1, otype=1, price_q4=0,
                                  quantity=3, client_id="mk")),
        EngineOp(OP_CANCEL, _info(4), cancel_requester="other"),
        EngineOp(OP_AMEND, _info(5, quantity=9), amend_qty=2),
    ]
    payload, n = ops_to_oprec(ops)
    assert n == 3
    recs = ops_from_oprec(payload)
    # Submits carry the PRIMARY-assigned id — the log is authoritative
    # for identity; a replica re-assigning in dispatch order would
    # diverge under concurrent edge handlers.
    op, side, otype, price_q4, qty, sym, cid, oid = recs[0]
    assert (side, otype, price_q4, qty, sym, cid, oid) == \
        (1, 1, 0, 3, "AAA", "mk", "OID-7")
    # Cancels ship the requester (STP ownership check replays too).
    assert (recs[1][6], recs[1][7]) == ("other", "OID-4")
    # Amends ship the new quantity in the qty box.
    assert (recs[2][4], recs[2][7]) == (2, "OID-5")


def test_oplog_codec_empty_dispatch():
    payload, n = ops_to_oprec([])
    assert n == 0
    assert ops_from_oprec(payload) == []


# -- unit: the prefix-consistency verifier -----------------------------------


def _mkstore(path, orders, fills=()):
    con = sqlite3.connect(path)
    con.execute("CREATE TABLE orders (order_id TEXT PRIMARY KEY, client_id "
                "TEXT, symbol TEXT, side INT, order_type INT, price INT, "
                "quantity INT, remaining_quantity INT, status INT, tif INT)")
    con.execute("CREATE TABLE fills (order_id TEXT, counter_order_id TEXT, "
                "price INT, quantity INT)")
    con.executemany("INSERT INTO orders VALUES (?,?,?,?,?,?,?,?,?,?)", orders)
    con.executemany("INSERT INTO fills VALUES (?,?,?,?)", fills)
    con.commit()
    con.close()
    return path


def _row(oid, rem=5, status=NEW, qty=5, price=10_000):
    return (oid, "c", "AAA", 2, 0, price, qty, rem, status, 0)


def test_verify_identical_stores(tmp_path):
    rows = [_row("OID-1"), _row("OID-2", rem=0, status=FILLED)]
    fills = [("OID-2", "OID-1", 10_000, 5)]
    a = _mkstore(str(tmp_path / "a.db"), rows, fills)
    b = _mkstore(str(tmp_path / "b.db"), rows, fills)
    rep = compare_stores(a, b)
    assert rep["identical_prefix"] and rep["equal"] == 2


def test_verify_one_sided_advance_is_prefix(tmp_path):
    # B applied one more dispatch: OID-1 canceled + a new OID-3. Legal.
    a = _mkstore(str(tmp_path / "a.db"), [_row("OID-1")])
    b = _mkstore(str(tmp_path / "b.db"),
                 [_row("OID-1", rem=0, status=CANCELED), _row("OID-3")])
    rep = compare_stores(a, b)
    assert rep["identical_prefix"]
    assert rep["b_ahead"] == 1 and rep["only_b"] == 1


def test_verify_catches_corruption(tmp_path):
    # Same order, different immutable column (price): neither equal nor
    # a legal advance — corruption, never an async-cut artifact.
    a = _mkstore(str(tmp_path / "a.db"), [_row("OID-1", price=10_000)])
    b = _mkstore(str(tmp_path / "b.db"), [_row("OID-1", price=10_001)])
    rep = compare_stores(a, b)
    assert not rep["identical_prefix"]
    assert rep["mismatched_orders"] == ["OID-1"]


def test_verify_catches_mixed_direction(tmp_path):
    # OID-1 ahead in A while OID-2 is ahead in B: impossible for two
    # cuts of one totally-ordered history.
    a = _mkstore(str(tmp_path / "a.db"),
                 [_row("OID-1", rem=0, status=CANCELED), _row("OID-2")])
    b = _mkstore(str(tmp_path / "b.db"),
                 [_row("OID-1"), _row("OID-2", rem=0, status=CANCELED)])
    rep = compare_stores(a, b)
    assert not rep["identical_prefix"] and rep["mixed_direction"]


def test_verify_catches_terminal_flip(tmp_path):
    # CANCELED in one cut, FILLED in the other: terminal statuses are
    # absorbing, so two cuts of ONE history can never disagree on WHICH
    # terminal an order reached — this is divergence even though
    # remaining/status "advance" monotonically in isolation (and even
    # under the --promoted fork contract: the row is common).
    a = _mkstore(str(tmp_path / "a.db"),
                 [_row("OID-1", rem=10, qty=10, status=CANCELED)])
    b = _mkstore(str(tmp_path / "b.db"),
                 [_row("OID-1", rem=0, qty=10, status=FILLED)],
                 [("OID-1", "OID-9", 10_000, 10)])
    for kw in ({}, {"allow_fork": True}):
        rep = compare_stores(a, b, **kw)
        assert not rep["identical_prefix"]
        assert rep["mismatched_orders"] == ["OID-1"]


def test_verify_promoted_fork_tolerated(tmp_path):
    # Post-promotion: a (the dead primary) holds a durable tail that
    # never shipped (only_a) while b (the promoted replica) accepted
    # fresh flow (only_b). Two-sided exclusives are the legal promotion
    # fork under allow_fork, and corruption for two cuts of ONE line.
    a = _mkstore(str(tmp_path / "a.db"), [_row("OID-1"), _row("OID-2")])
    b = _mkstore(str(tmp_path / "b.db"), [_row("OID-1"), _row("OID-3")])
    assert not compare_stores(a, b)["identical_prefix"]
    assert compare_stores(a, b, allow_fork=True)["identical_prefix"]
    # Disagreement on a COMMON row stays divergence even when forked.
    c = _mkstore(str(tmp_path / "c.db"), [_row("OID-1", price=10_001),
                                          _row("OID-3")])
    assert not compare_stores(a, c, allow_fork=True)["identical_prefix"]


def test_verify_catches_fill_conflict(tmp_path):
    rows = [_row("OID-1", rem=0, status=FILLED)]
    a = _mkstore(str(tmp_path / "a.db"), rows,
                 [("OID-1", "OID-9", 10_000, 5)])
    b = _mkstore(str(tmp_path / "b.db"), rows,
                 [("OID-1", "OID-8", 10_000, 5)])
    rep = compare_stores(a, b)
    assert not rep["identical_prefix"]
    assert rep["fill_mismatches"] == ["OID-1"]


# -- e2e plumbing -------------------------------------------------------------


def _boot_pair(tmp_path, *, fault=None, spill=False, standby_kw=None):
    """In-proc primary (--oplog-ship --audit) + standby replica pair."""
    if fault is not None:
        os.environ["ME_REPL_FAULT"] = fault
    try:
        psrv, pport, pparts = build_server(
            "127.0.0.1:0", str(tmp_path / "primary.db"), CFG, window_ms=1.0,
            log=False, oplog_ship=True, audit=True, audit_sample=1)
        psrv.start()
        kw = dict(standby_kw or {})
        kw.setdefault("flight_dir", str(tmp_path / "flight"))
        if spill:
            kw["feed_spill_dir"] = str(tmp_path / "spill")
        ssrv, sport, sparts = build_server(
            "127.0.0.1:0", str(tmp_path / "standby.db"), CFG, window_ms=1.0,
            log=False, standby_addr=f"127.0.0.1:{pport}", **kw)
        ssrv.start()
    finally:
        if fault is not None:
            del os.environ["ME_REPL_FAULT"]
    return (psrv, pport, pparts), (ssrv, sport, sparts)


def _stub(port):
    return MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))


def _drive(stub, n=20, cancel_every=5, start=0):
    """Deterministic mixed flow: resting + crossing limits, sprinkled
    cancels. Returns the acked order ids."""
    acked = []
    for i in range(start, start + n):
        side = pb2.BUY if i % 2 == 0 else pb2.SELL
        r = stub.SubmitOrder(pb2.OrderRequest(
            client_id=f"c{i % 3}", symbol=f"S{i % 4}", order_type=pb2.LIMIT,
            side=side, price=10_000 + (i % 5) * 100, scale=4, quantity=5),
            timeout=30)
        assert r.success, r.error_message
        acked.append(r.order_id)
        if cancel_every and i % cancel_every == cancel_every - 1:
            stub.CancelOrder(pb2.CancelRequest(
                client_id=f"c{i % 3}", order_id=r.order_id), timeout=30)
    return acked


def _wait(pred, timeout_s=30.0, interval=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _settle_stores(pparts, sparts, replica, min_applied):
    assert _wait(lambda: replica.snapshot()["applied_ops"] >= min_applied
                 and replica.snapshot()["lag_seqs"] == 0), replica.snapshot()
    pparts["sink"].flush()
    sparts["sink"].flush()


# -- e2e: the in-proc smoke (ci.yaml runs exactly this test) ------------------


def test_standby_replicates_attests_and_promotes(tmp_path):
    (psrv, pport, pparts), (ssrv, sport, sparts) = _boot_pair(tmp_path)
    try:
        pstub, sstub = _stub(pport), _stub(sport)
        replica = sparts["replica"]

        # Read-only: every mutation RPC rejects app-level while standby.
        ro = sstub.SubmitOrder(pb2.OrderRequest(
            client_id="x", symbol="S0", order_type=pb2.LIMIT, side=pb2.BUY,
            price=10_000, scale=4, quantity=1), timeout=30)
        assert not ro.success and "read-only" in ro.error_message
        assert not sstub.CancelOrder(pb2.CancelRequest(
            client_id="x", order_id="OID-1"), timeout=30).success
        assert not sstub.AmendOrder(pb2.AmendRequest(
            client_id="x", order_id="OID-1", new_quantity=1),
            timeout=30).success
        assert not sstub.RunAuction(pb2.AuctionRequest(), timeout=30).success
        # Promote against a non-standby rejects app-level too.
        assert not pstub.Promote(pb2.PromoteRequest(), timeout=30).success
        # RunAuction rejects on the PRIMARY as well: the uncross bypasses
        # the drain loops the op-log shipper rides, so running it would
        # silently diverge the standby.
        ra = pstub.RunAuction(pb2.AuctionRequest(), timeout=30)
        assert not ra.success and "op log" in ra.error_message

        acked = _drive(pstub, n=20)
        _settle_stores(pparts, sparts, replica, min_applied=24)

        snap = replica.snapshot()
        assert snap["applied_dispatches"] >= 1
        assert snap["apply_errors"] == 0 and snap["divergences"] == 0
        assert snap["oplog_lost_records"] == 0 and snap["ok"]
        # Attestation ran (every fully-paired dispatch matched); the
        # in-flight last group may still be pending its idle flush.
        assert _wait(lambda: replica.snapshot()["attested"]
                     >= snap["applied_dispatches"] - 2)
        assert replica.snapshot()["divergences"] == 0

        # The standby serves reads: its book mirrors the primary's.
        pbook = pstub.GetOrderBook(
            pb2.OrderBookRequest(symbol="S1"), timeout=30)
        sbook = sstub.GetOrderBook(
            pb2.OrderBookRequest(symbol="S1"), timeout=30)
        assert [(b.price, b.quantity) for b in pbook.bids] == \
            [(b.price, b.quantity) for b in sbook.bids]
        assert [(a.price, a.quantity) for a in pbook.asks] == \
            [(a.price, a.quantity) for a in sbook.asks]

        # Both durable stores are bit-identical cuts of one history.
        rep = compare_stores(str(tmp_path / "primary.db"),
                             str(tmp_path / "standby.db"))
        assert rep["identical_prefix"], rep
        assert rep["orders_a"] == rep["orders_b"] == len(acked)

        # Promote: epoch bumps, mutation RPCs open, ids collision-free.
        old_epoch = sparts["sequencer"].epoch
        pr = sstub.Promote(pb2.PromoteRequest(), timeout=60)
        assert pr.success and pr.feed_epoch != old_epoch
        assert replica.snapshot()["promotions"] == 1
        r = sstub.SubmitOrder(pb2.OrderRequest(
            client_id="post", symbol="S0", order_type=pb2.LIMIT,
            side=pb2.BUY, price=9_000, scale=4, quantity=1), timeout=30)
        assert r.success
        assert r.order_id not in acked
        assert int(r.order_id[4:]) > max(int(o[4:]) for o in acked)
    finally:
        shutdown(ssrv, sparts)
        shutdown(psrv, pparts)


# -- e2e: fault injection proves the detection path ---------------------------


def test_attestation_divergence_flips_replz_and_flight_dumps(tmp_path):
    (psrv, pport, pparts), (ssrv, sport, sparts) = \
        _boot_pair(tmp_path, fault="row")
    try:
        pstub = _stub(pport)
        replica = sparts["replica"]
        # ONE dispatch: the corrupted row must be detected without any
        # further flow (the idle-group flush closes the pairing window).
        r = pstub.SubmitOrder(pb2.OrderRequest(
            client_id="c", symbol="S0", order_type=pb2.LIMIT, side=pb2.BUY,
            price=10_000, scale=4, quantity=5), timeout=30)
        assert r.success
        assert _wait(lambda: replica.snapshot()["divergences"] >= 1), \
            replica.snapshot()
        snap = replica.snapshot()
        assert snap["diverged"] and not snap["ok"]

        # /replz is red: 500 + the same snapshot JSON.
        from matching_engine_tpu.utils.obs import ObsServer

        obs = ObsServer(sparts["metrics"], recorder=sparts["recorder"],
                        port=0, repl=replica)
        obs.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{obs.port}/replz", timeout=10)
            assert ei.value.code == 500
            body = ei.value.read().decode()
            assert '"diverged": true' in body
        finally:
            obs.close()

        # The divergence flight-dumped both sides' rows.
        flight_dir = tmp_path / "flight"
        assert _wait(lambda: list(flight_dir.glob("flight_*.json")),
                     timeout_s=10)
        dump = max(flight_dir.glob("flight_*.json"),
                   key=lambda p: p.stat().st_mtime).read_text()
        assert "repl_divergence" in dump
    finally:
        shutdown(ssrv, sparts)
        shutdown(psrv, pparts)


# -- e2e: a LATE-attaching standby attests the replayed history ---------------


def test_late_attach_standby_attests_replayed_history(tmp_path):
    """Boot the standby AFTER the primary already served traffic: the
    applier full-replays the op log from the epoch start, and the
    attestor must replay the audit channel over the SAME range (the
    __dropcopy_all__ from-start grant) — a live-only audit attach would
    leave the whole replayed prefix unattested while its local groups
    churn the pairing store as unmatched."""
    psrv, pport, pparts = build_server(
        "127.0.0.1:0", str(tmp_path / "primary.db"), CFG, window_ms=1.0,
        log=False, oplog_ship=True, audit=True, audit_sample=1)
    psrv.start()
    ssrv = sparts = None
    try:
        pstub = _stub(pport)
        _drive(pstub, n=12, cancel_every=0)
        ssrv, sport, sparts = build_server(
            "127.0.0.1:0", str(tmp_path / "standby.db"), CFG,
            window_ms=1.0, log=False,
            standby_addr=f"127.0.0.1:{pport}",
            flight_dir=str(tmp_path / "flight"))
        ssrv.start()
        replica = sparts["replica"]
        assert _wait(lambda: replica.snapshot()["applied_dispatches"] >= 1
                     and replica.snapshot()["lag_seqs"] == 0)
        # The replayed prefix attests (the in-flight last group may
        # still be pending its idle flush).
        assert _wait(lambda: replica.snapshot()["attested"]
                     >= replica.snapshot()["applied_dispatches"] - 1), \
            replica.snapshot()
        assert replica.snapshot()["divergences"] == 0
        assert replica.snapshot()["ok"]
    finally:
        if ssrv is not None:
            shutdown(ssrv, sparts)
        shutdown(psrv, pparts)


# -- boot: the runbook's fresh-db rule is enforced ----------------------------


def test_standby_refuses_non_empty_db(tmp_path):
    """A standby booted onto a used store would recover it into the
    books and then re-apply the same history via the from-start op-log
    replay (double-applied fills) — build_server must refuse at boot,
    before any engine threads start."""
    db = _mkstore(str(tmp_path / "used.db"), [_row("OID-1")], [])
    with pytest.raises(SystemExit):
        build_server("127.0.0.1:0", db, CFG, window_ms=1.0, log=False,
                     standby_addr="127.0.0.1:1")


# -- e2e: a known-bad replica must not SELF-promote ---------------------------


def test_standby_never_heard_refuses_auto_promotion(tmp_path):
    """A standby that never received ANYTHING from its configured
    primary (wrong --standby address, primary never up) must not
    self-promote on heartbeat lapse: auto-promoting an empty replica
    while the real primary may be serving elsewhere is split-brain by
    typo. (rx retries every 0.2s, the watcher polls every 0.2s, so an
    unguarded watcher would promote within a poll or two.)"""
    srv, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "s.db"), CFG, window_ms=1.0,
        log=False, standby_addr="127.0.0.1:1",
        standby_auto_promote_s=0.05)
    srv.start()
    try:
        replica = parts["replica"]
        time.sleep(1.0)
        snap = replica.snapshot()
        assert not snap["promoted"] and snap["promotions"] == 0, snap
        assert parts["service"].read_only
    finally:
        shutdown(srv, parts)


def test_poisoned_replica_refuses_auto_promotion(tmp_path):
    """Heartbeat-lapse auto-promotion is guarded: a replica with a known
    hole (poisoned) never self-promotes into the serving primary — only
    the explicit operator Promote (eyes open on a red /replz) can."""
    (psrv, pport, pparts), (ssrv, sport, sparts) = _boot_pair(tmp_path)
    try:
        pstub = _stub(pport)
        replica = sparts["replica"]
        _drive(pstub, n=4, cancel_every=0)
        assert _wait(lambda: replica.snapshot()["applied_dispatches"] >= 1)
        replica._poison("test: simulated unrecoverable oplog gap")
        # Heartbeats land every 0.25s, the watcher polls every 0.2s: with
        # this threshold nearly every poll observes a "lapse", so an
        # unguarded watcher would promote within a poll or two.
        replica.auto_promote_s = 0.01
        time.sleep(1.0)
        snap = replica.snapshot()
        assert not snap["promoted"] and snap["promotions"] == 0, snap
        assert sparts["service"].read_only
        # The explicit operator path stays available.
        pr = _stub(sport).Promote(pb2.PromoteRequest(), timeout=60)
        assert pr.success
        assert replica.snapshot()["promotions"] == 1
    finally:
        shutdown(ssrv, sparts)
        shutdown(psrv, pparts)


# -- e2e: promotion hygiene (spill purge + exactly one rebase) ----------------


def test_promotion_purges_stale_spill_and_rebases_once(tmp_path):
    (psrv, pport, pparts), (ssrv, sport, sparts) = \
        _boot_pair(tmp_path, spill=True)
    try:
        pstub, sstub = _stub(pport), _stub(sport)
        replica = sparts["replica"]
        seq = sparts["sequencer"]
        spill_base = tmp_path / "spill"
        old_epoch = seq.epoch
        assert (spill_base / f"epoch-{old_epoch}").is_dir()
        # A leftover segment dir from an older line (the restart shape:
        # a standby rebooted into the same spill dir) must also purge.
        stale = spill_base / "epoch-123"
        stale.mkdir()
        (stale / "seg-1").write_bytes(b"stale payload")

        acked = _drive(pstub, n=8, cancel_every=0)
        _settle_stores(pparts, sparts, replica, min_applied=8)

        # A live sequenced subscriber on the STANDBY's own feed line
        # rides across the promotion.
        rebases = []
        sub = SequencedSubscriber(
            sstub, CHANNEL_MD, key="S1",
            on_rebase=lambda cur, seq_: rebases.append((cur, seq_)))
        got: list = []
        t = threading.Thread(
            target=lambda: [got.append(e) for e in sub], daemon=True)
        t.start()
        # More pre-promotion flow so the subscriber holds a live cursor.
        _drive(pstub, n=8, cancel_every=0, start=100)
        _settle_stores(pparts, sparts, replica, min_applied=16)
        assert _wait(lambda: any(e.feed_epoch == old_epoch for e in got))

        # A subscriber attached with a REPLAY cursor before promotion
        # (server-side overlap filter armed with last > 0) must still
        # receive the new epoch's first events after the in-place
        # rebase: the filter is epoch-aware, not seq-only — a seq-only
        # filter would silently swallow every new-epoch event whose seq
        # is below the old epoch's replay cursor.
        mid_cursor = max(e.seq for e in got if e.feed_epoch == old_epoch)
        rebases3 = []
        sub3 = SequencedSubscriber(
            sstub, CHANNEL_MD, key="S1", from_seq=max(1, mid_cursor - 2),
            epoch=old_epoch,
            on_rebase=lambda cur, seq_: rebases3.append((cur, seq_)))
        got3: list = []
        t3 = threading.Thread(
            target=lambda: [got3.append(e) for e in sub3], daemon=True)
        t3.start()

        pr = sstub.Promote(pb2.PromoteRequest(), timeout=60)
        assert pr.success and pr.feed_epoch != old_epoch

        # Stale-epoch spill segments are gone; the new line's dir stands.
        assert _wait(lambda: not stale.exists(), timeout_s=10)
        assert not (spill_base / f"epoch-{old_epoch}").exists()
        assert (spill_base / f"epoch-{pr.feed_epoch}").is_dir()

        # Post-promotion flow reaches the SAME live subscriber with the
        # new epoch: exactly one rebase, zero unrecovered gaps.
        r = sstub.SubmitOrder(pb2.OrderRequest(
            client_id="post", symbol="S1", order_type=pb2.LIMIT,
            side=pb2.BUY, price=9_000, scale=4, quantity=1), timeout=30)
        assert r.success
        assert _wait(lambda: any(e.feed_epoch == pr.feed_epoch for e in got))
        assert len(rebases) == 1
        assert sub.gaps_detected == sub.unrecovered_events == 0
        sub.cancel()
        t.join(timeout=10)
        # The replay-cursor subscriber crossed the rebase too: the new
        # epoch's events (seqs BELOW its old-epoch cursor) arrived.
        assert _wait(lambda: any(e.feed_epoch == pr.feed_epoch
                                 for e in got3)), \
            (len(got3), [e.seq for e in got3])
        assert len(rebases3) == 1 and sub3.unrecovered_events == 0
        sub3.cancel()
        t3.join(timeout=10)

        # The restart shape: a subscriber RESUMING with its pre-promotion
        # cursor + epoch sees exactly one rebase too, then live events —
        # never the old line's payloads replayed as the new epoch's range.
        old_cursor = max(e.seq for e in got if e.feed_epoch == old_epoch)
        rebases2 = []
        sub2 = SequencedSubscriber(
            sstub, CHANNEL_MD, key="S1", from_seq=old_cursor,
            epoch=old_epoch,
            on_rebase=lambda cur, seq_: rebases2.append((cur, seq_)))
        got2: list = []
        t2 = threading.Thread(
            target=lambda: [got2.append(e) for e in sub2], daemon=True)
        t2.start()
        r = sstub.SubmitOrder(pb2.OrderRequest(
            client_id="post2", symbol="S1", order_type=pb2.LIMIT,
            side=pb2.BUY, price=9_100, scale=4, quantity=1), timeout=30)
        assert r.success
        assert _wait(lambda: len(got2) >= 1)
        assert len(rebases2) == 1 and sub2.unrecovered_events == 0
        assert all(e.feed_epoch == pr.feed_epoch for e in got2)
        sub2.cancel()
        t2.join(timeout=10)
        assert len(acked) == 8
    finally:
        shutdown(ssrv, sparts)
        shutdown(psrv, pparts)


# -- e2e: kill the primary ----------------------------------------------------


def _spawn_primary(tmp_path, db: str):
    """A REAL primary subprocess (SIGKILL needs a process boundary). The
    bound port is parsed from the boot log (--addr :0) — pre-binding a
    probe socket and reusing its port races other tests for the bind."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU; never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    env["PYTHONPATH"] = f"{env.get('PYTHONPATH', '')}:{REPO}"
    log_path = tmp_path / "primary.log"
    proc = subprocess.Popen(
        [sys.executable, "-m", "matching_engine_tpu.server.main",
         "--addr", "127.0.0.1:0", "--db", db,
         "--symbols", "8", "--capacity", "32", "--batch", "8",
         "--window-ms", "1", "--oplog-ship", "--audit",
         "--audit-sample", "1"],
        env=env, cwd=REPO,
        stdout=log_path.open("w"), stderr=subprocess.STDOUT)
    return proc, log_path


def _primary_port(proc, log_path, timeout_s=240.0) -> int:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        assert proc.poll() is None, \
            f"primary died at boot:\n{log_path.read_text()}"
        m = re.search(r"listening on port (\d+)", log_path.read_text())
        if m:
            return int(m.group(1))
        time.sleep(0.5)
    raise AssertionError(
        f"primary never listened:\n{log_path.read_text()}")


def test_kill_primary_promote_standby_prefix_identical(tmp_path):
    pdb = str(tmp_path / "primary.db")
    proc, log_path = _spawn_primary(tmp_path, pdb)
    ssrv = sparts = None
    try:
        pport = _primary_port(proc, log_path)
        pstub = _stub(pport)
        assert _wait(lambda: _ping(pstub), timeout_s=60), \
            log_path.read_text()
        # Pre-existing history BEFORE the standby attaches: the standby
        # must bootstrap via the full oplog replay, not just live flow.
        pre = _drive(pstub, n=10)

        ssrv, sport, sparts = build_server(
            "127.0.0.1:0", str(tmp_path / "standby.db"), CFG, window_ms=1.0,
            log=False, standby_addr=f"127.0.0.1:{pport}")
        ssrv.start()
        sstub = _stub(sport)
        replica = sparts["replica"]

        # Concurrent load until the kill; acks collected up to the cut.
        acked: list[str] = []
        stop = threading.Event()

        def load():
            i = 1000
            while not stop.is_set():
                try:
                    r = pstub.SubmitOrder(pb2.OrderRequest(
                        client_id=f"c{i % 3}", symbol=f"S{i % 4}",
                        order_type=pb2.LIMIT,
                        side=pb2.BUY if i % 2 == 0 else pb2.SELL,
                        price=10_000 + (i % 5) * 100, scale=4, quantity=5),
                        timeout=5)
                except grpc.RpcError:
                    return  # the kill landed mid-RPC
                if r.success:
                    acked.append(r.order_id)
                i += 1

        loader = threading.Thread(target=load, daemon=True)
        loader.start()
        assert _wait(lambda: len(acked) >= 30
                     and replica.snapshot()["applied_ops"] >= 20)

        proc.kill()  # SIGKILL: no drain, no flush, mid-flow
        proc.wait(timeout=30)
        stop.set()
        loader.join(timeout=30)

        # Promote. Everything already received is drained and applied;
        # fresh flow is accepted with ids past the replicated history.
        pr = sstub.Promote(pb2.PromoteRequest(), timeout=60)
        assert pr.success
        sparts["sink"].flush()

        r = sstub.SubmitOrder(pb2.OrderRequest(
            client_id="post", symbol="S0", order_type=pb2.LIMIT,
            side=pb2.BUY, price=9_000, scale=4, quantity=1), timeout=30)
        assert r.success
        all_acked = pre + acked
        assert r.order_id not in all_acked
        assert int(r.order_id[4:]) > max(int(o[4:]) for o in all_acked)

        # (a) Bit-identity for the acknowledged prefix: the dead
        # primary's WAL and the promoted replica's store are two cuts of
        # one deterministic history — every common row identical, every
        # difference a one-sided legal advance (the async tails).
        rep = compare_stores(pdb, str(tmp_path / "standby.db"),
                             allow_fork=True)
        assert rep["identical_prefix"], rep
        assert rep["common"] >= len(pre)

        # Every order the standby applied from the log landed (the
        # promoted store can't be missing applied history; the post-
        # promotion order rides on top).
        con = sqlite3.connect(str(tmp_path / "standby.db"))
        try:
            n_orders = con.execute(
                "SELECT COUNT(*) FROM orders").fetchone()[0]
        finally:
            con.close()
        assert n_orders >= rep["common"]
    finally:
        if proc.poll() is None:
            proc.kill()
        if ssrv is not None:
            shutdown(ssrv, sparts)


def _ping(stub) -> bool:
    try:
        stub.GetOrderBook(pb2.OrderBookRequest(symbol="S0"),
                          timeout=2)
        return True
    except grpc.RpcError:
        return False
