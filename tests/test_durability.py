"""Durability-gap closure tests (VERDICT r2 weak #7 / round-1 task #5).

Two loss paths used to leave SQLite silently behind the device book:
  1. a full sink queue dropped whole storage batches (dispatcher submit
     with block=False) — now deferred through SpillingSink and drained at
     the flush barrier;
  2. kernel fill-record overflow (max_fills) dropped fill rows and maker
     updates — now detected at decode (taker side), repaired from the
     device book at checkpoint time (maker side), and acknowledged in the
     `recon` ledger that scripts/audit.py folds into exact arithmetic.
"""

import sys

import grpc
import pytest

sys.path.insert(0, "scripts")
from audit import audit  # noqa: E402

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.storage import Storage
from matching_engine_tpu.storage.async_sink import AsyncStorageSink, SpillingSink


class RecordingSink:
    """Scripted inner sink: refuses while `accept` is False, records batch
    arrival order otherwise."""

    def __init__(self):
        self.accept = True
        self.batches = []
        self.flushes = 0

    def submit(self, orders=None, updates=None, fills=None, block=True):
        if not self.accept and not block:
            return False
        self.batches.append((list(orders or []), list(updates or []),
                             list(fills or [])))
        return True

    def flush(self):
        self.flushes += 1

    def close(self):
        pass


def _batch(i):
    return dict(orders=[(f"OID-{i}", "c", "S", 1, 0, 1, 1, 1, 0)],
                updates=[], fills=[])


def test_spilling_sink_defers_and_preserves_order():
    inner = RecordingSink()
    sink = SpillingSink(inner)
    assert sink.submit(**_batch(1), block=False)
    inner.accept = False
    # These would have been DROPPED pre-spill; now they defer.
    for i in (2, 3, 4):
        assert sink.submit(**_batch(i), block=False)
    assert sink.spilled == 3 and sink.lost == 0
    assert [b[0][0][0] for b in inner.batches] == ["OID-1"]
    inner.accept = True
    # The next submit drains the spill FIRST — FIFO across the boundary.
    assert sink.submit(**_batch(5), block=False)
    assert [b[0][0][0] for b in inner.batches] == [
        "OID-1", "OID-2", "OID-3", "OID-4", "OID-5"]


def test_spilling_sink_flush_drains_blocking():
    inner = RecordingSink()
    sink = SpillingSink(inner)
    inner.accept = False
    sink.submit(**_batch(1), block=False)
    sink.submit(**_batch(2), block=False)
    inner.accept = True
    sink.flush()
    assert [b[0][0][0] for b in inner.batches] == ["OID-1", "OID-2"]
    assert inner.flushes == 1


def test_spilling_sink_bounded_loss():
    inner = RecordingSink()
    sink = SpillingSink(inner, max_spill=2)
    inner.accept = False
    assert sink.submit(**_batch(1), block=False)
    assert sink.submit(**_batch(2), block=False)
    assert not sink.submit(**_batch(3), block=False)  # true loss, counted
    assert sink.lost == 1 and sink.dropped == 1


def test_stalled_storage_spills_then_recovers(tmp_path):
    """Real path: SQLite writer wedged -> queue fills -> spill -> barrier
    drains -> audit-clean database with every batch present."""
    db = str(tmp_path / "stall.db")
    storage = Storage(db)
    assert storage.init()
    inner = AsyncStorageSink(storage, max_queue=1)
    sink = SpillingSink(inner, max_spill=64)
    # Wedge the writer: hold the storage lock so apply_batch blocks.
    storage._lock.acquire()
    try:
        accepted = 0
        for i in range(1, 9):
            assert sink.submit(**_batch(i), block=False)
            accepted += 1
        assert sink.spilled > 0  # the queue really did fill
    finally:
        storage._lock.release()
    sink.flush()
    check = Storage(db)
    assert check.count("orders") == 8
    assert audit(db) == []
    sink.close()
    storage.close()
    check.close()


CFG = EngineConfig(num_symbols=8, capacity=16, batch=16, max_fills=2)


def submit(stub, client, symbol, side, price, qty):
    return stub.SubmitOrder(
        pb2.OrderRequest(client_id=client, symbol=symbol, order_type=pb2.LIMIT,
                         side=side, price=price, scale=4, quantity=qty),
        timeout=10,
    )


@pytest.fixture
def overflow_stack(tmp_path):
    db = str(tmp_path / "ovf.db")
    server, port, parts = build_server(
        "127.0.0.1:0", db, CFG, window_ms=1.0, log=False,
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_interval_s=3600,
    )
    server.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield db, MatchingEngineStub(channel), parts, tmp_path
    channel.close()
    shutdown(server, parts)


def test_fill_overflow_repaired_at_checkpoint(overflow_stack):
    db, stub, parts, tmp_path = overflow_stack
    runner = parts["runner"]
    # 8 resting makers, then one taker sweeping all 8 in a single dispatch:
    # 8 fill records against max_fills=2 -> 6 records lost on device.
    makers = [submit(stub, f"m{i}", "OVF", pb2.SELL, 10_000, 1).order_id
              for i in range(8)]
    taker = submit(stub, "t", "OVF", pb2.BUY, 10_000, 8)
    assert taker.success
    counters = runner.metrics.snapshot()[0]
    assert counters.get("fill_buffer_overflows", 0) >= 1
    assert runner.pending_recon  # taker-side loss detected at decode

    # Before the repair, SQLite is inconsistent (missing fills + stale
    # makers); the checkpoint repairs and acknowledges the gap.
    parts["checkpointer"].checkpoint_now()
    parts["sink"].flush()
    assert audit(db) == []

    st = Storage(db)
    taker_row = st.get_order(taker.order_id)
    assert taker_row[7] == 0 and taker_row[8] == 2  # FILLED, remaining 0
    maker_rows = [st.get_order(m) for m in makers]
    assert all(r[7] == 0 and r[8] == 2 for r in maker_rows)
    # The ledger quantifies exactly the six lost records (both sides).
    import sqlite3
    conn = sqlite3.connect(db)
    taker_lost = conn.execute(
        "SELECT SUM(lost_quantity) FROM recon WHERE order_id = ?",
        (taker.order_id,)).fetchone()[0]
    assert taker_lost == 8 - st.count("fills")
    conn.close()
    st.close()
    # Host directory evicted the silently-consumed makers.
    assert not runner.orders_by_id


def test_fill_overflow_survives_restart(overflow_stack):
    db, stub, parts, tmp_path = overflow_stack
    for i in range(8):
        submit(stub, f"m{i}", "RST", pb2.SELL, 10_000, 1)
    assert submit(stub, "t", "RST", pb2.BUY, 10_000, 8).success
    parts["checkpointer"].checkpoint_now()

    server2, port2, parts2 = build_server(
        "127.0.0.1:0", db, CFG, window_ms=1.0, log=False,
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_interval_s=3600,
    )
    server2.start()
    try:
        # Restored book must hold nothing: everything matched out.
        assert not parts2["runner"].orders_by_id
        parts2["sink"].flush()
        assert audit(db) == []
    finally:
        shutdown(server2, parts2)


def test_spilling_sink_concurrent_submitters(tmp_path):
    """Many threads submit while the inner sink flaps between refusing and
    accepting: every batch must reach SQLite exactly once, in order within
    each submitter (global FIFO across the spill boundary is asserted by
    the per-thread sequence check)."""
    import threading

    db = str(tmp_path / "conc.db")
    storage = Storage(db)
    assert storage.init()
    inner = AsyncStorageSink(storage, max_queue=2)
    sink = SpillingSink(inner, max_spill=10_000)
    threads, n_threads, per = [], 8, 100

    def submitter(t):
        for i in range(per):
            oid = f"OID-{t * per + i + 1}"
            assert sink.submit(
                orders=[(oid, f"c{t}", f"S{t}", 1, 0, 1, 1, 1, 0)],
                updates=[], fills=[], block=False)

    # Wedge the writer for the first half of the run.
    storage._lock.acquire()
    for t in range(n_threads):
        th = threading.Thread(target=submitter, args=(t,))
        th.start()
        threads.append(th)
    import time as _t
    _t.sleep(0.2)
    storage._lock.release()
    for th in threads:
        th.join(timeout=30)
    assert not any(th.is_alive() for th in threads)
    sink.flush()

    import sqlite3
    conn = sqlite3.connect(db)
    rows = conn.execute(
        "SELECT client_id, order_id FROM orders ORDER BY created_ts, rowid"
    ).fetchall()
    conn.close()
    assert len(rows) == n_threads * per  # exactly once, nothing lost
    # Per-submitter arrival order preserved (FIFO through the spill).
    seen: dict[str, int] = {}
    for client, oid in rows:
        n = int(oid.split("-")[1])
        assert seen.get(client, -1) < n, (client, oid)
        seen[client] = n
    sink.close()
    storage.close()


def test_failed_repair_carries_to_next_checkpoint(tmp_path):
    """A failed apply_repairs (e.g. SQLITE_BUSY) must not lose the drained
    ledger rows: they carry to the next checkpoint_now and persist then."""
    from matching_engine_tpu.engine.book import EngineConfig as _Cfg
    from matching_engine_tpu.server.engine_runner import EngineRunner
    from matching_engine_tpu.utils.checkpoint import CheckpointDaemon

    runner = EngineRunner(_Cfg(num_symbols=4, capacity=8, batch=4,
                               max_fills=64))
    runner.pending_recon.append(("OID-7", "fills_lost", 3))

    class FlakyStorage:
        def __init__(self):
            self.calls = []
            self.fail_first = True

        def apply_repairs(self, repairs, recon):
            self.calls.append((list(repairs), list(recon)))
            if self.fail_first:
                self.fail_first = False
                return False
            return True

    class NullSink:
        def flush(self):
            pass

    storage = FlakyStorage()
    daemon = CheckpointDaemon(runner, NullSink(), str(tmp_path / "ck"),
                              interval_s=3600, storage=storage)
    daemon.checkpoint_now()   # repair write fails -> carried
    assert storage.calls[0][1] == [("OID-7", "fills_lost", 3)]
    assert not runner.pending_recon          # drained from the runner...
    assert daemon._carry_recon               # ...but held by the daemon
    daemon.checkpoint_now()   # retried and persisted
    assert storage.calls[1][1] == [("OID-7", "fills_lost", 3)]
    assert not daemon._carry_recon
