"""Bit-parity of the C++ lane-engine serving path (server/native_lanes.py
+ native/me_lanes.cpp) against the Python serving path it replaces.

The native fast path moves ALL per-op host work native: ring-record
decode, host checks (auction mode, ownership, slot capacity, directory
lookups), oid/handle/slot assignment, lane build + wave placement, status
decode, completion building, storage-row packing. The Python path
(EngineRunner + the gateway_bridge._drain_batch per-op machinery) stays
the oracle: this module replays IDENTICAL lifecycle-fuzz record streams
(submits across all five collapsed (order_type, tif) codes, cancels,
amends — valid and invalid, auction call periods with an uncross in the
middle) through both and asserts the native path is indistinguishable:

  - the [K, 9] sparse / [S, B, 7] dense lane buffers each wave device_puts
    (captured at the engine-step boundary), wave count and order included
  - per-op completions on the gateway wire (tag, kind, ok, order_id,
    error) and amend completions (tag, ok, order_id, remaining, error)
  - storage rows (orders, updates, fills — exact tuples, exact order)
  - stream protos (OrderUpdate / MarketDataUpdate)
  - final device books, order directory, and EVERY allocator (next oid/
    handle/slot, free lists) — so all future behavior stays identical too
"""

from __future__ import annotations

import contextlib
import random

import numpy as np
import pytest

from matching_engine_tpu import native as me_native
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.harness import snapshot_books
from matching_engine_tpu.engine.kernel import (
    CANCELED,
    NEW,
    OP_AMEND,
    OP_CANCEL,
    OP_SUBMIT,
    REJECTED,
)
from matching_engine_tpu.server.engine_runner import (
    EngineOp,
    EngineRunner,
    OrderInfo,
)

pytestmark = pytest.mark.skipif(
    not me_native.available(), reason="native runtime not built"
)

S, CAP, B = 4, 16, 8


def make_cfg(kernel: str) -> EngineConfig:
    return EngineConfig(num_symbols=S, capacity=CAP, batch=B,
                        max_fills=1 << 12, kernel=kernel)


# -- stream generation -------------------------------------------------------

def gen_stream(seed: int, with_auction: bool):
    """One lifecycle-fuzz record stream as a list of phases; each phase is
    ('dispatch', [record tuple ...]) or ('auction_mode', bool) or
    ('uncross',). Record tuples are pack_record_batch's input shape.

    Cancel/amend targets use PREDICTED order ids: ids are consumed by
    exactly the submits that pass host checks (everything in continuous
    mode; only GTC LIMIT during a call period) — itself part of the
    parity surface under test."""
    rng = random.Random(seed)
    tag = [0]
    next_oid = [1]
    auction = [False]
    # (order_id, client) of LIMIT submits — cancel/amend targets; stale
    # (filled/canceled) targets are fair game: both paths must reject
    # identically.
    targets: list[tuple[str, str]] = []

    def t() -> int:
        tag[0] += 1
        return tag[0]

    def submit(call_period_mix: bool):
        sym = f"S{rng.randrange(S)}"
        cid = f"c{rng.randrange(5)}"
        side = 1 if rng.random() < 0.5 else 2
        otype = 0
        if rng.random() < 0.25:
            otype = rng.choice((1, 2, 3, 4))  # MKT / IOC / FOK / MKT_FOK
        price = 0 if otype in (1, 4) else 10_000 + rng.randrange(-8, 9)
        qty = rng.randrange(1, 20)
        rec = (t(), 1, side, otype, price, qty, sym, cid, "")
        if not auction[0] or otype == 0:
            oid = f"OID-{next_oid[0]}"
            next_oid[0] += 1
            if otype == 0:
                targets.append((oid, cid))
        # else: rejected at the host check, no id consumed
        if call_period_mix and otype != 0:
            pass  # non-GTC during a call period: edge-rejected, kept in
        return rec

    def cancel():
        if targets and rng.random() < 0.8:
            oid, cid = rng.choice(targets)
            if rng.random() < 0.15:
                cid = "mallory"  # wrong client
        else:
            oid, cid = f"OID-{9000 + rng.randrange(100)}", "c0"  # unknown
        return (t(), 2, 0, 0, 0, 0, "", cid, oid)

    def amend():
        if targets and rng.random() < 0.8:
            oid, cid = rng.choice(targets)
            if rng.random() < 0.15:
                cid = "mallory"
        else:
            oid, cid = f"OID-{9000 + rng.randrange(100)}", "c0"
        # qty: mostly a plausible reduction, sometimes an invalid raise
        qty = rng.randrange(1, 25)
        return (t(), 3, 0, 0, 0, qty, "", cid, oid)

    def batch(n, call_period=False):
        recs = []
        for _ in range(n):
            r = rng.random()
            if r < 0.70 or not targets:
                recs.append(submit(call_period))
            elif r < 0.88:
                recs.append(cancel())
            else:
                recs.append(amend())
        return recs

    phases = []
    # Continuous: small (sparse-shaped) and large (dense-shaped)
    # dispatches interleaved.
    for _ in range(3):
        phases.append(("dispatch", batch(6)))
        phases.append(("dispatch", batch(20)))
    if with_auction:
        phases.append(("auction_mode", True))
        auction[0] = True
        phases.append(("dispatch", batch(12, call_period=True)))
        phases.append(("uncross",))
        auction[0] = False
        phases.append(("dispatch", batch(6)))
        phases.append(("dispatch", batch(20)))
    return phases


# -- lane capture at the engine-step boundary --------------------------------

@contextlib.contextmanager
def capture_lanes(sink: list):
    """Record every lane buffer crossing into the device step — the wave
    split and buffer CONTENT both runs must produce identically."""
    import matching_engine_tpu.engine.kernel as kmod
    import matching_engine_tpu.engine.sparse as smod
    import matching_engine_tpu.server.engine_runner as rmod

    real_sparse, real_packed = smod.engine_step_sparse, kmod.engine_step_packed

    def rec_sparse(cfg, book, sp):
        sink.append(("sparse", np.asarray(sp.lanes).copy()))
        return real_sparse(cfg, book, sp)

    def rec_packed(cfg, book, arr):
        sink.append(("dense", np.asarray(arr).copy()))
        return real_packed(cfg, book, arr)

    saved = (smod.engine_step_sparse, kmod.engine_step_packed,
             rmod.engine_step_packed)
    smod.engine_step_sparse = rec_sparse
    kmod.engine_step_packed = rec_packed
    rmod.engine_step_packed = rec_packed
    try:
        yield
    finally:
        (smod.engine_step_sparse, kmod.engine_step_packed,
         rmod.engine_step_packed) = saved


# -- the Python serving path (the parity oracle) -----------------------------

def py_drain(runner: EngineRunner, recs) -> dict:
    """One dispatch through the Python path, transcribed from
    gateway_bridge._drain_batch: per-record decode, host checks with
    immediate edge completions, OrderInfo/EngineOp construction, pipelined
    dispatch, then the bridge's completion building from the outcomes.
    Returns the same observable surface NativeDispatchResult carries."""
    ops: list[EngineOp] = []
    tags: dict[int, int] = {}
    comp: list[tuple] = []   # (tag, kind, ok, order_id, error)
    amends: list[tuple] = []  # (tag, ok, order_id, remaining, error)
    for (tag, op, side, otype, price_q4, qty, symbol, client_id,
         order_id) in recs:
        if op == 1:
            if runner.auction_mode and otype != 0:
                comp.append((tag, 0, False, "",
                             "only GTC LIMIT orders are accepted during an "
                             "auction call period"))
                continue
            if not runner.owns_symbol(symbol):
                comp.append((tag, 0, False, "",
                             f"symbol {symbol} is homed on another host"))
                continue
            if runner.slot_acquire(symbol) is None:
                comp.append((tag, 0, False, "",
                             "symbol capacity exhausted (engine symbol "
                             "axis is full)"))
                continue
            oid_num, oid_str = runner.assign_oid()
            info = OrderInfo(
                oid=oid_num, order_id=oid_str, client_id=client_id,
                symbol=symbol, side=side, otype=otype, price_q4=price_q4,
                quantity=qty, remaining=qty, status=0,
                handle=runner.assign_handle(),
            )
            e = EngineOp(OP_SUBMIT, info)
        elif op == 3:
            info = runner.orders_by_id.get(order_id)
            if info is None:
                amends.append((tag, False, order_id, 0, "unknown order id"))
                continue
            if info.client_id != client_id:
                amends.append((tag, False, order_id, 0,
                               "order belongs to a different client"))
                continue
            e = EngineOp(OP_AMEND, info, amend_qty=qty)
        else:
            info = runner.orders_by_id.get(order_id)
            if info is None:
                comp.append((tag, 1, False, order_id, "unknown order id"))
                continue
            if info.client_id != client_id:
                comp.append((tag, 1, False, order_id,
                             "order belongs to a different client"))
                continue
            e = EngineOp(OP_CANCEL, info, cancel_requester=client_id)
        ops.append(e)
        tags[id(e)] = tag

    box = {}

    def on_finish(result, error):
        assert error is None, error
        box["result"] = result
        return None

    runner.dispatch_pipelined(ops, on_finish)
    runner.finish_pending()
    result = box["result"]
    for outcome in result.outcomes:
        tag = tags.pop(id(outcome.op), None)
        if tag is None:
            continue
        info = outcome.op.info
        if outcome.op.op == OP_AMEND:
            ok = outcome.status == NEW
            amends.append((tag, ok, info.order_id, outcome.remaining,
                           "" if ok else (outcome.error or "amend rejected")))
        elif outcome.op.op != OP_CANCEL:
            if outcome.status == REJECTED and outcome.error:
                comp.append((tag, 0, False, info.order_id, outcome.error))
            else:
                comp.append((tag, 0, True, info.order_id, ""))
        else:
            if outcome.status == CANCELED:
                comp.append((tag, 1, True, info.order_id, ""))
            else:
                comp.append((tag, 1, False, info.order_id,
                             outcome.error or "order not open"))
    assert not tags, "op produced no outcome"
    return {
        "comp": comp,
        "amends": amends,
        "orders": list(result.storage_orders),
        "updates": list(result.storage_updates),
        "fills": list(result.storage_fills),
        "ou": [m.SerializeToString() for m in result.order_updates],
        "md": [m.SerializeToString() for m in result.market_data],
    }


def native_drain(runner, recs) -> dict:
    from matching_engine_tpu.server.native_lanes import pack_record_batch

    buf, n = pack_record_batch(recs)
    box = {}

    def on_finish(result, error):
        assert error is None, error
        box["result"] = result
        return None

    runner.dispatch_records(buf, n, on_finish)
    runner.finish_pending()
    r = box["result"]
    orders, updates, fills = me_native.unpack_store_buf(r.store_buf)
    return {
        "comp": me_native.parse_comp_buf(r.comp_buf),
        "amends": [(tag, ok, oid, rem, err)
                   for (tag, ok, rem, oid, err) in r.amends],
        "orders": orders,
        "updates": updates,
        "fills": fills,
        "ou": [m.SerializeToString() for m in r.order_updates],
        "md": [m.SerializeToString() for m in r.market_data],
    }


def assert_dispatch_parity(i, py: dict, nat: dict):
    assert sorted(nat["comp"]) == sorted(py["comp"]), f"dispatch {i}: comp"
    assert sorted(nat["amends"]) == sorted(py["amends"]), \
        f"dispatch {i}: amends"
    for key in ("orders", "updates", "fills"):
        assert nat[key] == py[key], f"dispatch {i}: storage {key}"
    assert sorted(nat["ou"]) == sorted(py["ou"]), f"dispatch {i}: OU stream"
    assert sorted(nat["md"]) == sorted(py["md"]), f"dispatch {i}: MD stream"


def assert_directory_parity(py_r: EngineRunner, nat_r):
    """Full hot-path state: directory, symbol table, every allocator."""
    nat_r.refresh_directory_mirror_locked()
    key = lambda i: (i.handle, i.oid, i.order_id, i.client_id, i.symbol,  # noqa: E731
                     i.side, i.otype, i.price_q4, i.quantity, i.remaining,
                     i.status)
    assert sorted(map(key, nat_r.orders_by_handle.values())) == \
        sorted(map(key, py_r.orders_by_handle.values()))
    assert nat_r.symbols == py_r.symbols
    assert nat_r.slot_symbols == py_r.slot_symbols
    assert nat_r.next_oid_num == py_r.next_oid_num
    assert nat_r._next_handle == py_r._next_handle
    assert nat_r._free_handles == py_r._free_handles
    assert nat_r._next_slot == py_r._next_slot
    assert nat_r._free_slots == py_r._free_slots
    assert nat_r._owner_by_client == py_r._owner_by_client


@pytest.mark.parametrize("kernel", ["matrix", "sorted"])
@pytest.mark.parametrize("seed", [0])
def test_lane_parity_lifecycle_fuzz(kernel, seed):
    from matching_engine_tpu.server.native_lanes import NativeLanesRunner

    cfg = make_cfg(kernel)
    py_r = EngineRunner(cfg)
    nat_r = NativeLanesRunner(cfg)
    py_lanes: list = []
    nat_lanes: list = []

    for phases_seen, phase in enumerate(gen_stream(seed, with_auction=True)):
        if phase[0] == "auction_mode":
            py_r.set_auction_mode(phase[1])
            nat_r.set_auction_mode(phase[1])
            continue
        if phase[0] == "uncross":
            ps = py_r.run_auction(None, sink=None)
            ns = nat_r.run_auction(None, sink=None)
            assert not ps["error"] and not ns["error"]
            assert sorted(ps["crossed"]) == sorted(ns["crossed"])
            py_r.set_auction_mode(False)
            nat_r.set_auction_mode(False)
            continue
        recs = phase[1]
        with capture_lanes(py_lanes):
            py = py_drain(py_r, recs)
        with capture_lanes(nat_lanes):
            nat = native_drain(nat_r, recs)
        assert_dispatch_parity(phases_seen, py, nat)

    # Wave-for-wave lane parity: same count, same shape kind, same bytes.
    assert len(py_lanes) == len(nat_lanes)
    for w, ((pk, pa), (nk, na)) in enumerate(zip(py_lanes, nat_lanes)):
        assert pk == nk, f"wave {w}: shape kind"
        assert pa.shape == na.shape, f"wave {w}: lane shape"
        assert np.array_equal(pa, na), f"wave {w}: lane content"

    # Books, directory, allocators.
    assert snapshot_books(py_r.book) == snapshot_books(nat_r.book)
    assert_directory_parity(py_r, nat_r)


@pytest.mark.slow
@pytest.mark.parametrize("kernel", ["matrix", "sorted"])
@pytest.mark.parametrize("seed", [1, 2])
def test_lane_parity_lifecycle_fuzz_more_seeds(kernel, seed):
    test_lane_parity_lifecycle_fuzz(kernel, seed)


def test_native_path_capacity_reject_metered():
    """Full-book backpressure on the NATIVE path: the C++ decode stamps
    the positional 'book side at capacity' reject reason, and the runner
    feeds me_book_capacity_rejects_total from the aux completions —
    never a silent drop. Bit-63 tags = the grpcio LaneRingDispatcher
    route, whose completions ride the aux local section the meter
    scans."""
    from matching_engine_tpu.server.native_lanes import NativeLanesRunner

    from matching_engine_tpu.server.native_lanes import pack_record_batch

    cfg = make_cfg("matrix")
    runner = NativeLanesRunner(cfg)
    hi = 1 << 63
    recs = [(hi | (i + 1), 1, 2, 0, 10_000 + i, 3, "S0", "c1", "")
            for i in range(CAP + 3)]  # 3 past the side's capacity
    buf, n = pack_record_batch(recs)
    box = {}
    runner.dispatch_records(
        buf, n, lambda result, error: box.update(result=result, err=error))
    runner.finish_pending()
    assert box["err"] is None
    errs = [loc for loc in box["result"].local if loc[5]]
    assert len(errs) == 3
    assert all("book side at capacity" in loc[5] for loc in errs)
    counters, _ = runner.metrics.snapshot()
    assert counters["book_capacity_rejects"] == 3
    assert counters["book_capacity_rejects_tier0"] == 3

    # Same overflow via LOW tags — the C++ GATEWAY batch completion
    # route, whose rejects ride the comp wire buffer instead of the aux
    # local section. The meter must count those too.
    runner2 = NativeLanesRunner(make_cfg("matrix"))
    recs2 = [(i + 1, 1, 2, 0, 10_000 + i, 3, "S0", "c1", "")
             for i in range(CAP + 2)]
    buf2, n2 = pack_record_batch(recs2)
    box2 = {}
    runner2.dispatch_records(
        buf2, n2,
        lambda result, error: box2.update(result=result, err=error))
    runner2.finish_pending()
    assert box2["err"] is None
    comp = me_native.parse_comp_buf(box2["result"].comp_buf)
    assert sum("book side at capacity" in c[4] for c in comp) == 2
    counters2, _ = runner2.metrics.snapshot()
    assert counters2["book_capacity_rejects"] == 2


# -- full-stack e2e: build_server(native_lanes=True), grpcio edge ------------

def test_native_lanes_full_stack_e2e(tmp_path):
    """The whole serving stack through the lane engine: grpcio RPCs ->
    MatchingEngineService native tails -> LaneRingDispatcher ->
    NativeLanesRunner -> storage, with a restart leg proving recovery
    replay (Python path) hands the directory to the C++ engine
    (adopt_from_python) cleanly."""
    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.dispatcher import LaneRingDispatcher
    from matching_engine_tpu.server.main import build_server, shutdown
    from matching_engine_tpu.storage import Storage

    db = str(tmp_path / "lanes_e2e.db")
    cfg = EngineConfig(num_symbols=4, capacity=8, batch=4)
    server, port, parts = build_server(
        "127.0.0.1:0", db, cfg, window_ms=1.0, log=False,
        native_lanes=True,
    )
    assert isinstance(parts["dispatcher"], LaneRingDispatcher)
    server.start()
    channel = None
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        stub = MatchingEngineStub(channel)

        def sub(client, side, qty, price=10000):
            return stub.SubmitOrder(pb2.OrderRequest(
                client_id=client, symbol="S", order_type=pb2.LIMIT,
                side=side, price=price, scale=4, quantity=qty), timeout=10)

        r1 = sub("a", pb2.BUY, 5)
        r2 = sub("b", pb2.SELL, 3)       # matches 3 of r1
        assert r1.success and r2.success

        # Amend the partially-filled rest down, then cancel it.
        am = stub.AmendOrder(pb2.AmendRequest(
            client_id="a", order_id=r1.order_id, new_quantity=1),
            timeout=10)
        assert am.success and am.remaining_quantity == 1
        # Invalid amend (raise) rejected through the native host checks.
        bad = stub.AmendOrder(pb2.AmendRequest(
            client_id="a", order_id=r1.order_id, new_quantity=50),
            timeout=10)
        assert not bad.success
        # Wrong-client cancel rejected; right-client cancel lands.
        assert not stub.CancelOrder(pb2.CancelRequest(
            client_id="x", order_id=r1.order_id), timeout=10).success
        assert stub.CancelOrder(pb2.CancelRequest(
            client_id="a", order_id=r1.order_id), timeout=10).success
        # Cancel of a filled order: not open.
        assert not stub.CancelOrder(pb2.CancelRequest(
            client_id="b", order_id=r2.order_id), timeout=10).success
        # Identifiers too big for the wire record answer with the Python
        # path's lookup errors, not "engine error" (pack_gwop must never
        # see them).
        huge = stub.CancelOrder(pb2.CancelRequest(
            client_id="a", order_id="X" * 64), timeout=10)
        assert not huge.success and huge.error_message == "unknown order id"
        huge = stub.AmendOrder(pb2.AmendRequest(
            client_id="c" * 300, order_id=r1.order_id, new_quantity=1),
            timeout=10)
        assert not huge.success
        assert huge.error_message == "order belongs to a different client"

        parts["sink"].flush()
        st = Storage(db)
        assert st.count("fills") == 1
        f = st.fills_for_order(r2.order_id)[0]
        assert f[1] == r1.order_id and f[2] == 10000 and f[3] == 3
        assert st.get_order(r2.order_id)[8] == 2      # FILLED
        assert st.get_order(r1.order_id)[8] == 3      # CANCELED
        st.close()

        # A resting book for the restart leg.
        r3 = sub("c", pb2.BUY, 4, price=9990)
        assert r3.success
        book = stub.GetOrderBook(pb2.OrderBookRequest(symbol="S"),
                                 timeout=10)
        assert book.bids and book.bids[0].price == 9990
        parts["sink"].flush()
    finally:
        if channel is not None:
            channel.close()
        shutdown(server, parts)

    rest_oid = r3.order_id

    # Restart over the same DB: recovery replays through the Python
    # runner, then authority flips to the lane engine; the rest must be
    # live (cancelable) and new flow must match against it.
    server2, port2, parts2 = build_server(
        "127.0.0.1:0", db, cfg, window_ms=1.0, log=False,
        native_lanes=True,
    )
    server2.start()
    channel2 = None
    try:
        channel2 = grpc.insecure_channel(f"127.0.0.1:{port2}")
        stub2 = MatchingEngineStub(channel2)
        rs = stub2.SubmitOrder(pb2.OrderRequest(
            client_id="d", symbol="S", order_type=pb2.MARKET,
            side=pb2.SELL, quantity=4), timeout=10)
        assert rs.success
        parts2["sink"].flush()
        st = Storage(db)
        assert st.get_order(rest_oid)[8] == 2  # r3 FILLED post-restart
        st.close()
    finally:
        if channel2 is not None:
            channel2.close()
        shutdown(server2, parts2)
