"""Vectorized admission screens (server/admission.py) vs a per-op
python oracle.

The oracle below is an INDEPENDENT re-implementation of the documented
batch-boundary semantics — per-op dict-and-loop, no numpy — so the
property fuzz catches a vectorization bug in either direction (a screen
that fires where the spec says no, or sleeps where it says reject).
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from matching_engine_tpu.domain import oprec
from matching_engine_tpu.server.admission import (
    AdmissionConfig,
    AdmissionScreens,
)

R = oprec  # reason-code namespace


# -- the per-op oracle -------------------------------------------------------


class Oracle:
    """Per-op reference: same config, same batch-boundary semantics,
    implemented with plain dicts and one loop per batch."""

    def __init__(self, cfg: AdmissionConfig):
        self.cfg = cfg
        self.rate: dict[bytes, int] = {}
        self.window_start = 0.0
        self.anchors: dict[bytes, int] = {}
        self.stp: dict[tuple[bytes, bytes], list] = {}

    def screen_batch(self, records, now: float):
        """records: list of (op, side, otype, price_q4, qty, symbol,
        client_id) python tuples. Returns per-record reason codes."""
        cfg = self.cfg
        if cfg.rate_limit and now - self.window_start >= cfg.rate_window_s:
            self.rate.clear()
            self.window_start = now
        # Frozen-at-batch-entry tables (the documented semantics).
        anchors = dict(self.anchors)
        stp = {k: list(v) for k, v in self.stp.items()}
        seen_rate: dict[bytes, int] = {}
        out = []
        admitted = []
        for (op, side, otype, price, qty, sym, cid) in records:
            code = 0
            if cfg.rate_limit:
                pre = self.rate.get(cid, 0) + seen_rate.get(cid, 0)
                if pre >= cfg.rate_limit:
                    code = code or R.REASON_RATE
                seen_rate[cid] = seen_rate.get(cid, 0) + 1
            if cfg.max_quantity and op in (1, 3) \
                    and qty > cfg.max_quantity:
                code = code or R.REASON_QTY
            if cfg.price_band_bps and op == 1 and otype in (0, 2, 3):
                a = anchors.get(sym, 0)
                if a > 0 and abs(price - a) * 10000 > cfg.price_band_bps * a:
                    code = code or R.REASON_BAND
            if cfg.stp and op == 1:
                q = stp.get((cid, sym))
                if q is not None and q[2] > now:
                    bid, ask = q[0], q[1]
                    mkt = otype in (1, 4)
                    if side == 1 and ask > 0 and (mkt or price >= ask):
                        code = code or R.REASON_STP
                    if side == 2 and bid > 0 and (mkt or price <= bid):
                        code = code or R.REASON_STP
            out.append(code)
            if code == 0:
                admitted.append((op, side, otype, price, qty, sym, cid))
        # Post-batch state updates (admitted records only, in order).
        for cid, c in seen_rate.items():
            self.rate[cid] = self.rate.get(cid, 0) + c
        for (op, side, otype, price, qty, sym, cid) in admitted:
            if op == 1 and otype in (0, 2, 3):
                self.anchors[sym] = price
            if op == 1 and otype == 0:
                key = (cid, sym)
                q = self.stp.get(key)
                if q is None or q[2] <= now:
                    q = [0, 0, now + self.cfg.stp_ttl_s]
                    self.stp[key] = q
                if side == 1:
                    q[0] = max(q[0], price)
                else:
                    q[1] = min(q[1], price) if q[1] else price
                q[2] = now + self.cfg.stp_ttl_s
        return out


def _pack(records):
    """(op, side, otype, price, qty, sym, cid) tuples -> record array.
    Cancels/amends get a syntactically valid target id (the screens
    never read it; record_flaws requires it nonempty)."""
    return oprec.pack_records(
        [(op, side, otype, price, qty, sym, cid,
          b"" if op == 1 else b"OID-1") for
         (op, side, otype, price, qty, sym, cid) in records])


def _keyed(records):
    """Oracle variant of the same records with box-padded keys."""
    out = []
    for (op, side, otype, price, qty, sym, cid) in records:
        out.append((op, side, otype, price, qty,
                    sym.ljust(oprec.SYMBOL_BYTES, b"\x00"),
                    cid.ljust(oprec.CLIENT_ID_BYTES, b"\x00")))
    return out


def _random_flow(rng, n, n_clients=4, n_syms=3):
    recs = []
    for _ in range(n):
        op = rng.choice([1, 1, 1, 1, 2, 3])
        side = rng.choice([1, 2])
        otype = rng.choice([0, 0, 0, 1, 2, 3, 4])
        price = 0 if (otype in (1, 4) or op != 1) \
            else rng.randint(90, 110) * 100
        qty = rng.randint(1, 40)
        sym = f"S{rng.randrange(n_syms)}".encode()
        cid = f"c{rng.randrange(n_clients)}".encode()
        recs.append((op, side, otype, price, qty, sym, cid))
    return recs


FUZZ_CFGS = [
    AdmissionConfig(rate_limit=7, rate_window_s=10.0),
    AdmissionConfig(max_quantity=20),
    AdmissionConfig(price_band_bps=300),
    AdmissionConfig(stp=True, stp_ttl_s=100.0),
    AdmissionConfig(rate_limit=11, rate_window_s=10.0, max_quantity=25,
                    price_band_bps=500, stp=True, stp_ttl_s=100.0),
]


@pytest.mark.parametrize("cfg", FUZZ_CFGS,
                         ids=["rate", "qty", "band", "stp", "all"])
def test_vectorized_matches_oracle_fuzz(cfg):
    """Property fuzz: over random multi-batch flows the vectorized
    screens and the per-op oracle agree positionally, batch after batch
    (state carried across batches on both sides)."""
    rng = random.Random(0xA5)
    for trial in range(10):
        screens = AdmissionScreens(cfg)
        oracle = Oracle(cfg)
        now = 100.0
        for batch in range(6):
            recs = _random_flow(rng, rng.randint(1, 40))
            arr = _pack(recs)
            flaws = oprec.record_flaws(arr)
            # The fuzz generator only produces structurally-clean
            # records; the screens must see flaws=None positions.
            assert all(f is None for f in flaws)
            got = screens.screen(arr, flaws, now=now)
            want = oracle.screen_batch(_keyed(recs), now)
            assert list(got) == want, (
                f"trial {trial} batch {batch}: vectorized {list(got)} "
                f"!= oracle {want} for {recs}")
            # Reason messages landed positionally in flaws.
            for i, code in enumerate(want):
                if code:
                    assert flaws[i] == oprec.REASON_MESSAGES[code]
                else:
                    assert flaws[i] is None
            now += 0.5


def test_rate_window_rotation():
    cfg = AdmissionConfig(rate_limit=2, rate_window_s=1.0)
    s = AdmissionScreens(cfg)
    recs = [(1, 1, 0, 10000, 5, b"S", b"c")] * 3
    arr = _pack(recs)
    flaws = [None] * 3
    got = s.screen(arr, flaws, now=0.0)
    assert list(got) == [0, 0, R.REASON_RATE]
    # Same window: budget already spent.
    flaws = [None] * 3
    got = s.screen(_pack(recs), flaws, now=0.5)
    assert list(got) == [R.REASON_RATE] * 3
    # Window rotated: budget back.
    flaws = [None] * 3
    got = s.screen(_pack(recs), flaws, now=2.0)
    assert list(got) == [0, 0, R.REASON_RATE]


def test_band_anchor_is_batch_boundary():
    cfg = AdmissionConfig(price_band_bps=100)  # 1%
    s = AdmissionScreens(cfg)
    # First batch sets the anchor at its LAST admitted priced submit.
    arr = _pack([(1, 1, 0, 10000, 5, b"S", b"c"),
                 (1, 1, 0, 10050, 5, b"S", b"c")])
    flaws = [None, None]
    assert list(s.screen(arr, flaws, now=0.0)) == [0, 0]
    # Anchor is 10050 now: 10050 ± 1% = [9950, 10150].
    arr = _pack([(1, 1, 0, 10150, 5, b"S", b"c"),
                 (1, 1, 0, 10200, 5, b"S", b"c"),
                 (1, 2, 0, 9900, 5, b"S", b"c")])
    flaws = [None] * 3
    got = s.screen(arr, flaws, now=0.0)
    assert list(got) == [0, R.REASON_BAND, R.REASON_BAND]
    assert flaws[1] == oprec.REASON_MESSAGES[R.REASON_BAND]


def test_stp_crosses_own_quote_only():
    cfg = AdmissionConfig(stp=True, stp_ttl_s=10.0)
    s = AdmissionScreens(cfg)
    # c1 rests a sell at 100.00; c2 rests a buy at 99.00.
    arr = _pack([(1, 2, 0, 10000, 5, b"S", b"c1"),
                 (1, 1, 0, 9900, 5, b"S", b"c2")])
    flaws = [None, None]
    assert list(s.screen(arr, flaws, now=0.0)) == [0, 0]
    arr = _pack([
        (1, 1, 0, 10000, 5, b"S", b"c1"),   # c1 buy at own ask: STP
        (1, 1, 0, 9950, 5, b"S", b"c1"),    # below own ask: fine
        (1, 1, 1, 0, 5, b"S", b"c1"),       # c1 MARKET buy: STP
        (1, 1, 0, 10000, 5, b"S", b"c2"),   # c2 has no ask: fine
        (1, 2, 0, 9900, 5, b"S", b"c2"),    # c2 sell at own bid: STP
    ])
    flaws = [None] * 5
    got = s.screen(arr, flaws, now=1.0)
    assert list(got) == [R.REASON_STP, 0, R.REASON_STP, 0, R.REASON_STP]
    # TTL expiry clears the table.
    arr = _pack([(1, 1, 0, 10000, 5, b"S", b"c1")])
    flaws = [None]
    assert list(s.screen(arr, flaws, now=30.0)) == [0]


def test_screen_one_matches_batch_of_one():
    cfg = AdmissionConfig(max_quantity=10, rate_limit=3,
                          rate_window_s=100.0)
    s = AdmissionScreens(cfg)
    assert s.screen_one(1, 1, 0, 10000, 5, b"S", b"c") is None
    assert s.screen_one(1, 1, 0, 10000, 50, b"S", b"c") == \
        oprec.REASON_MESSAGES[R.REASON_QTY]
    # Two ops spent (rejects spend budget too); third passes, fourth
    # hits the rate wall.
    assert s.screen_one(1, 1, 0, 10000, 5, b"S", b"c") is None
    assert s.screen_one(2, 0, 0, 0, 0, b"", b"c") == \
        oprec.REASON_MESSAGES[R.REASON_RATE]


def test_disabled_config_is_noop():
    s = AdmissionScreens(AdmissionConfig())
    assert not s.enabled
    arr = _pack([(1, 1, 0, 10000, 5, b"S", b"c")])
    flaws = [None]
    assert list(s.screen(arr, flaws)) == [0]
    assert flaws == [None]


def test_screens_skip_flawed_records():
    """Structurally flawed positions keep their record_flaws message and
    never touch screen state (a malformed record must not spend rate
    budget or move an anchor)."""
    cfg = AdmissionConfig(rate_limit=1, rate_window_s=100.0)
    s = AdmissionScreens(cfg)
    arr = _pack([(9, 1, 0, 10000, 5, b"S", b"c"),   # bad op
                 (1, 1, 0, 10000, 5, b"S", b"c")])
    flaws = oprec.record_flaws(arr)
    assert flaws[0] is not None
    got = s.screen(arr, flaws, now=0.0)
    # The flawed record spent nothing: the clean one is op 1 of 1.
    assert list(got) == [0, 0]
    assert flaws[0] == "invalid op code (1=submit, 2=cancel, 3=amend)"


def test_native_flaw_codes_match_python_messages():
    """me_oprec_flaws (the C++ structural screen the gateway's native
    batch path runs) agrees code-for-message with record_flaws over a
    fuzzed mix of clean and flawed records."""
    me = pytest.importorskip("matching_engine_tpu.native")
    if not me.available():
        pytest.skip("native library unavailable")
    from matching_engine_tpu.domain.order import MAX_QUANTITY
    from matching_engine_tpu.domain.price import MAX_DEVICE_PRICE_Q4

    rng = random.Random(7)
    rows = []
    for _ in range(300):
        op = rng.choice([0, 1, 1, 1, 2, 3, 9])
        side = rng.choice([0, 1, 2, 7])
        otype = rng.choice([0, 1, 2, 3, 4, 9])
        price = rng.choice([0, -5, 100, MAX_DEVICE_PRICE_Q4])
        qty = rng.choice([-1, 0, 1, 50, MAX_QUANTITY, MAX_QUANTITY + 1])
        sym = rng.choice([b"", b"SYM"])
        cid = rng.choice([b"", b"cli"])
        oid = rng.choice([b"", b"OID-3"])
        rows.append((op, side, otype, price, qty, sym, cid, oid))
    arr = oprec.pack_records(rows)
    # Flag fuzz: a few records with reserved flags set.
    arr["flags"][::17] = 1
    msgs = oprec.record_flaws(arr)
    codes = me.oprec_flaw_codes(arr.tobytes(), len(arr),
                                MAX_DEVICE_PRICE_Q4, MAX_QUANTITY)
    for i, (msg, code) in enumerate(zip(msgs, codes)):
        assert oprec.flaw_message(code, int(arr[i]["op"])) == msg, (
            f"record {i} ({rows[i]}, flags={arr[i]['flags']}): "
            f"python {msg!r} vs native code {code}")


def test_screen_one_clamps_oversized_identifiers():
    """Cancel/Amend reach screen_one with only a non-empty check behind
    them: an id over the record box must screen by its box-sized prefix,
    never raise out of the RPC (review fix, PR 16)."""
    s = AdmissionScreens(AdmissionConfig(rate_limit=1, rate_window_s=100.0))
    big = b"x" * 700  # > CLIENT_ID_BYTES
    assert s.screen_one(2, 0, 0, 0, 0, b"", big) is None
    # Same client (same clamped prefix): second op hits the rate wall.
    assert s.screen_one(2, 0, 0, 0, 0, b"", big) == \
        oprec.REASON_MESSAGES[R.REASON_RATE]
    # Oversized symbols clamp too (band/STP key by the box).
    assert s.screen_one(1, 1, 0, 10000, 5, b"s" * 99, b"other") is None
