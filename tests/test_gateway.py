"""Native (C++) gRPC gateway tests.

The serving edge under test is native/me_gateway.cpp + native/h2.cpp: a
hand-rolled HTTP/2 + HPACK gRPC server (no grpc++/nghttp2 in this image).
Interop is the point — every test here drives the C++ gateway with the
grpc C-core client (grpcio), the strictest HTTP/2 peer available, plus the
native CLI client. The reference's oracle pattern (SURVEY.md §4: black-box
RPC in, white-box SQLite assert out) carries over: behavior must be
indistinguishable from the grpcio edge bar the port.
"""

import subprocess
import threading
import time

import grpc
import pytest

from matching_engine_tpu import native as me_native
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.storage import Storage

pytestmark = pytest.mark.skipif(
    not me_native.gateway_available(), reason="native gateway not built"
)

# Symbol axis sized for the whole module: tests use distinct symbols and
# several leave resting orders that pin their slots.
CFG = EngineConfig(num_symbols=16, capacity=16, batch=4)

# Mirror of h2::kMaxFrameSize (native/h2.h) — the gateway splits DATA at
# this size; test_large_book_response asserts it crosses the boundary.
H2_MAX_FRAME = 16384


class GwHarness:
    """Full stack with BOTH edges: grpcio on .port, C++ gateway on .gw_port."""

    def __init__(self, db_path, cfg=CFG):
        self.db_path = db_path
        self.server, self.port, self.parts = build_server(
            "127.0.0.1:0", db_path, cfg, window_ms=1.0, log=False,
            gateway_addr="127.0.0.1:0",
        )
        self.gw_port = self.parts["gateway_port"]
        self.server.start()
        self.gw_channel = grpc.insecure_channel(f"127.0.0.1:{self.gw_port}")
        self.stub = MatchingEngineStub(self.gw_channel)     # native edge
        self.py_channel = grpc.insecure_channel(f"127.0.0.1:{self.port}")
        self.py_stub = MatchingEngineStub(self.py_channel)  # grpcio edge

    def flush(self):
        self.parts["sink"].flush()

    def close(self):
        self.gw_channel.close()
        self.py_channel.close()
        shutdown(self.server, self.parts)


@pytest.fixture(scope="module")
def hs(tmp_path_factory):
    h = GwHarness(str(tmp_path_factory.mktemp("gw") / "gw.db"))
    yield h
    h.close()


def submit(stub, client="c1", symbol="SYM", otype=pb2.LIMIT, side=pb2.BUY,
           price=10000, scale=4, qty=5, tif=pb2.TIF_GTC):
    return stub.SubmitOrder(
        pb2.OrderRequest(client_id=client, symbol=symbol, order_type=otype,
                         side=side, price=price, scale=scale, quantity=qty,
                         tif=tif),
        timeout=10,
    )


def test_hpack_vectors():
    """The transport's HPACK codec passes the RFC 7541 Appendix C vectors."""
    import os
    native_dir = os.path.join(os.path.dirname(me_native.__file__), "..", "..",
                              "native")
    subprocess.run(["make", "-s", "h2_test"], cwd=native_dir, check=True)
    out = subprocess.run([os.path.join(native_dir, "h2_test")],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_submit_normalizes_and_persists(hs):
    resp = submit(hs.stub, symbol="NORM", price=10000, scale=8, qty=3)
    assert resp.success and resp.order_id.startswith("OID-")
    hs.flush()
    row = Storage(hs.db_path).get_order(resp.order_id)
    assert row is not None
    assert row[5] == 1   # Q4-normalized price
    assert row[8] == 0   # NEW


def test_match_through_gateway(hs):
    r1 = submit(hs.stub, client="a", symbol="MTCH", side=pb2.BUY,
                price=50000, qty=10)
    r2 = submit(hs.stub, client="b", symbol="MTCH", side=pb2.SELL,
                price=50000, qty=4)
    assert r1.success and r2.success
    hs.flush()
    st = Storage(hs.db_path)
    maker = st.get_order(r1.order_id)
    taker = st.get_order(r2.order_id)
    assert maker[7] == 6    # remaining 10-4
    assert maker[8] == 1    # PARTIALLY_FILLED
    assert taker[7] == 0 and taker[8] == 2  # FILLED
    fills = st.fills_for_order(r2.order_id)
    assert len(fills) == 1 and fills[0][3] == 4


def test_tif_through_both_edges(hs):
    """IOC/FOK ride the native edge's collapsed otype byte and the grpcio
    edge's mapping identically: an IOC remainder cancels (never rests),
    a failed FOK leaves the maker untouched, and the storage rows keep
    order_type in the reference's 0/1 domain with tif in its own column."""
    r1 = submit(hs.stub, client="a", symbol="TIF", side=pb2.BUY,
                price=50000, qty=10)
    # FOK for more than the book holds: canceled untouched (native edge).
    r2 = submit(hs.stub, client="b", symbol="TIF", side=pb2.SELL,
                price=50000, qty=11, tif=pb2.TIF_FOK)
    # IOC for more than the book holds: partial fill, remainder canceled
    # (grpcio edge).
    r3 = submit(hs.py_stub, client="b", symbol="TIF", side=pb2.SELL,
                price=50000, qty=12, tif=pb2.TIF_IOC)
    assert r1.success and r2.success and r3.success
    hs.flush()
    st = Storage(hs.db_path)
    maker = st.get_order(r1.order_id)
    fok = st.get_order(r2.order_id)
    ioc = st.get_order(r3.order_id)
    assert maker[7] == 0 and maker[8] == 2            # fully taken by IOC
    assert fok[7] == 11 and fok[8] == 3               # CANCELED untouched
    assert ioc[7] == 2 and ioc[8] == 3                # 10 filled, 2 canceled
    assert fok[4] == 0 and ioc[4] == 0                # order_type stays LIMIT
    assert fok[11] == 2 and ioc[11] == 1              # tif column FOK/IOC
    assert len(st.fills_for_order(r3.order_id)) == 1
    assert not st.fills_for_order(r2.order_id)


def test_amend_through_both_edges(hs):
    """AmendOrder (priority-preserving qty reduction) via the native
    gateway's C++ route AND the grpcio edge: success updates quantity and
    remaining together in the store; infeasible/foreign amends reject
    with identical messages on both edges."""
    r = submit(hs.stub, client="am", symbol="AMD", side=pb2.BUY,
               price=40000, qty=10)
    assert r.success
    ok = hs.stub.AmendOrder(pb2.AmendRequest(
        client_id="am", order_id=r.order_id, new_quantity=6), timeout=10)
    assert ok.success and ok.remaining_quantity == 6
    # qty up / not-a-reduction / foreign client / unknown id — identical
    # app-level rejects on both edges.
    cases = [
        (dict(client_id="am", order_id=r.order_id, new_quantity=6),
         "amend rejected (must strictly reduce an open order's quantity)"),
        (dict(client_id="am", order_id=r.order_id, new_quantity=99),
         "amend rejected (must strictly reduce an open order's quantity)"),
        (dict(client_id="other", order_id=r.order_id, new_quantity=3),
         "order belongs to a different client"),
        (dict(client_id="am", order_id="OID-424242", new_quantity=3),
         "unknown order id"),
        (dict(client_id="am", order_id=r.order_id, new_quantity=0),
         "new_quantity must be positive"),
        (dict(client_id="", order_id=r.order_id, new_quantity=3),
         "client_id is required"),
    ]
    for kw, want in cases:
        via_gw = hs.stub.AmendOrder(pb2.AmendRequest(**kw), timeout=10)
        via_py = hs.py_stub.AmendOrder(pb2.AmendRequest(**kw), timeout=10)
        assert not via_gw.success and not via_py.success, kw
        assert via_gw.error_message == want, (kw, via_gw.error_message)
        assert via_py.error_message == want, (kw, via_py.error_message)
    # A second reduction through the OTHER edge; then the store shows
    # quantity moving with remaining (filled == quantity - remaining).
    ok2 = hs.py_stub.AmendOrder(pb2.AmendRequest(
        client_id="am", order_id=r.order_id, new_quantity=2), timeout=10)
    assert ok2.success and ok2.remaining_quantity == 2
    hs.flush()
    st = Storage(hs.db_path)
    row = st.get_order(r.order_id)
    assert row[6] == 2 and row[7] == 2  # quantity == remaining == 2
    # Amended order still fills at its original time priority.
    r2 = submit(hs.stub, client="tk", symbol="AMD", side=pb2.SELL,
                price=40000, qty=2)
    assert r2.success
    hs.flush()
    st = Storage(hs.db_path)
    assert st.get_order(r.order_id)[8] == 2  # FILLED


def test_cross_edge_visibility(hs):
    """An order submitted on the grpcio edge matches one from the native
    edge — both edges drive the same books."""
    r1 = submit(hs.py_stub, client="py", symbol="XEDG", side=pb2.BUY,
                price=70000, qty=5)
    r2 = submit(hs.stub, client="cc", symbol="XEDG", side=pb2.SELL,
                price=70000, qty=5)
    assert r1.success and r2.success
    hs.flush()
    st = Storage(hs.db_path)
    assert st.get_order(r1.order_id)[8] == 2  # FILLED
    assert st.get_order(r2.order_id)[8] == 2


def test_book_query(hs):
    submit(hs.stub, client="bk", symbol="BOOK", side=pb2.BUY, price=11000, qty=7)
    submit(hs.stub, client="bk", symbol="BOOK", side=pb2.BUY, price=12000, qty=2)
    book = hs.stub.GetOrderBook(pb2.OrderBookRequest(symbol="BOOK"), timeout=10)
    assert [(o.price, o.quantity) for o in book.bids] == [(12000, 2), (11000, 7)]
    assert book.asks == []


def test_cancel_lifecycle(hs):
    r = submit(hs.stub, client="cx", symbol="CNCL", side=pb2.BUY,
               price=30000, qty=9)
    wrong = hs.stub.CancelOrder(
        pb2.CancelRequest(client_id="other", order_id=r.order_id), timeout=10)
    assert not wrong.success and "different client" in wrong.error_message
    ok = hs.stub.CancelOrder(
        pb2.CancelRequest(client_id="cx", order_id=r.order_id), timeout=10)
    assert ok.success
    again = hs.stub.CancelOrder(
        pb2.CancelRequest(client_id="cx", order_id=r.order_id), timeout=10)
    assert not again.success
    missing = hs.stub.CancelOrder(
        pb2.CancelRequest(client_id="cx", order_id="OID-424242"), timeout=10)
    assert not missing.success and missing.error_message == "unknown order id"
    empty = hs.stub.CancelOrder(
        pb2.CancelRequest(client_id="", order_id=r.order_id), timeout=10)
    assert not empty.success and empty.error_message == "client_id is required"


def test_validate_message_parity(hs):
    """Both edges must produce byte-identical app-level reject messages
    (C++ validate_submit_msg vs domain.validate_submit)."""
    bad_requests = [
        dict(client="v", symbol="", price=1, qty=1),
        dict(client="v", symbol="V" * 65, price=1, qty=1),
        dict(client="v" * 257, symbol="VAL", price=1, qty=1),
        dict(client="v", symbol="VAL", price=1, qty=0),
        dict(client="v", symbol="VAL", price=1, qty=-3),
        dict(client="v", symbol="VAL", price=1, qty=3_000_000),
        dict(client="v", symbol="VAL", side=5, price=1, qty=1),
        dict(client="v", symbol="VAL", otype=7, price=1, qty=1),
        dict(client="v", symbol="VAL", price=0, qty=1),
        dict(client="v", symbol="VAL", price=-10, qty=1),
        dict(client="v", symbol="VAL", price=10, scale=19, qty=1),
        dict(client="v", symbol="VAL", price=10, scale=-1, qty=1),
        dict(client="v", symbol="VAL", price=10**18, scale=0, qty=1),
        dict(client="v", symbol="VAL", price=5, scale=9, qty=1),     # ->0 at Q4
        dict(client="v", symbol="VAL", price=10**12, scale=2, qty=1),  # > int32 lane
        dict(client="v", symbol="VAL", otype=pb2.MARKET, price=0, scale=19, qty=1),
        dict(client="v", symbol="VAL", price=1, qty=1, tif=9),  # junk tif
    ]
    for kw in bad_requests:
        via_gw = submit(hs.stub, **kw)
        via_py = submit(hs.py_stub, **kw)
        assert not via_gw.success and not via_py.success, kw
        assert via_gw.error_message == via_py.error_message, (
            kw, via_gw.error_message, via_py.error_message)


def test_market_data_stream(hs):
    got = []
    done = threading.Event()

    def watch():
        try:
            for upd in hs.stub.StreamMarketData(
                    pb2.MarketDataRequest(symbol="STRM"), timeout=8):
                got.append((upd.best_bid, upd.best_ask))
                if len(got) >= 2:
                    break
        except grpc.RpcError:
            pass
        done.set()

    t = threading.Thread(target=watch)
    t.start()
    time.sleep(0.4)
    submit(hs.stub, client="s1", symbol="STRM", side=pb2.BUY, price=40000, qty=1)
    time.sleep(0.2)
    submit(hs.stub, client="s2", symbol="STRM", side=pb2.SELL, price=41000, qty=2)
    assert done.wait(10)
    assert got[0] == (40000, 0)
    assert got[-1] == (40000, 41000)


def test_order_updates_stream(hs):
    got = []
    done = threading.Event()

    def watch():
        try:
            for upd in hs.stub.StreamOrderUpdates(
                    pb2.OrderUpdatesRequest(client_id="flw"), timeout=8):
                got.append((upd.status, upd.fill_quantity, upd.remaining_quantity))
                if len(got) >= 2:
                    break
        except grpc.RpcError:
            pass
        done.set()

    t = threading.Thread(target=watch)
    t.start()
    time.sleep(0.4)
    r = submit(hs.stub, client="flw", symbol="UPDS", side=pb2.BUY,
               price=60000, qty=5)
    assert r.success
    submit(hs.stub, client="ctr", symbol="UPDS", side=pb2.SELL,
           price=60000, qty=5)
    assert done.wait(10)
    # NEW ack then the FILLED execution report.
    assert got[0][0] == 0
    assert got[-1] == (2, 5, 0)


def test_metrics_through_gateway(hs):
    m = hs.stub.GetMetrics(pb2.MetricsRequest(), timeout=10)
    assert m.counters.get("orders_accepted", 0) > 0
    assert m.counters.get("dispatches", 0) > 0


def test_unknown_method_unimplemented(hs):
    ch = grpc.insecure_channel(f"127.0.0.1:{hs.gw_port}")
    call = ch.unary_unary(
        "/matching_engine.v1.MatchingEngine/NoSuchMethod",
        request_serializer=lambda m: m,
        response_deserializer=lambda b: b,
    )
    with pytest.raises(grpc.RpcError) as e:
        call(b"", timeout=10)
    assert e.value.code() == grpc.StatusCode.UNIMPLEMENTED
    ch.close()


def test_native_client_binary(hs):
    cli = me_native.client_binary()
    assert cli is not None
    addr = f"127.0.0.1:{hs.gw_port}"
    r = subprocess.run([cli, addr, "ncli", "NCLI", "BUY", "LIMIT", "10050",
                        "2", "5"], capture_output=True, text=True, timeout=30)
    assert r.returncode == 0 and "accepted order_id=" in r.stdout
    oid = r.stdout.strip().rsplit("=", 1)[1]
    rc = subprocess.run([cli, "cancel", addr, "ncli", oid],
                        capture_output=True, text=True, timeout=30)
    assert rc.returncode == 0 and "canceled" in rc.stdout
    # rejected submit -> exit 3 (reference client.cpp exit contract)
    r3 = subprocess.run([cli, addr, "ncli", "NCLI", "BUY", "LIMIT", "0",
                        "2", "5"], capture_output=True, text=True, timeout=30)
    assert r3.returncode == 3 and "rejected" in r3.stdout
    # usage -> exit 1
    r4 = subprocess.run([cli], capture_output=True, text=True, timeout=30)
    assert r4.returncode == 1


def test_native_client_against_grpcio_server(hs):
    """Interop in the other direction: our HTTP/2 client against the
    grpc C-core server edge."""
    cli = me_native.client_binary()
    addr = f"127.0.0.1:{hs.port}"
    r = subprocess.run([cli, addr, "nc2", "NC2", "SELL", "LIMIT", "777",
                        "4", "2"], capture_output=True, text=True, timeout=30)
    assert r.returncode == 0 and "accepted order_id=" in r.stdout


def test_gateway_stats(hs):
    bridge = hs.parts["bridge"]
    stats = bridge.gateway.stats()
    assert stats["requests"] > 0
    assert stats["conns"] > 0


def test_dual_edge_stress(hs):
    """Concurrency stress across BOTH serving edges at once: submits,
    cancels, and book reads race through the native gateway and grpcio
    against the same runner, with checkpoint-style quiesces (dispatch-lock
    + sink flush) hammering in between. Invariants: every RPC completes,
    no torn responses, directories stay consistent, DB audits clean."""
    import random
    import sys

    sys.path.insert(0, "scripts")
    from audit import audit

    errors = []
    done = threading.Event()

    def trader(stub, tag):
        rng = random.Random(tag)
        live = []
        try:
            for i in range(60):
                sym = f"ST{rng.randrange(3)}"
                side = pb2.BUY if rng.random() < 0.5 else pb2.SELL
                r = stub.SubmitOrder(
                    pb2.OrderRequest(
                        client_id=f"s{tag}", symbol=sym, order_type=pb2.LIMIT,
                        side=side, price=10_000 + rng.randrange(-5, 5),
                        scale=4, quantity=rng.randrange(1, 9)),
                    timeout=30)
                if r.success:
                    live.append(r.order_id)
                if live and rng.random() < 0.4:
                    stub.CancelOrder(
                        pb2.CancelRequest(client_id=f"s{tag}",
                                          order_id=live.pop(0)), timeout=30)
                if rng.random() < 0.2:
                    stub.GetOrderBook(
                        pb2.OrderBookRequest(symbol=sym), timeout=30)
        except Exception as e:  # noqa: BLE001
            errors.append(f"trader {tag}: {type(e).__name__}: {e}")

    def quiescer():
        runner = hs.parts["runner"]
        while not done.is_set():
            # Checkpoint-style quiesce: a pipelined staged dispatch is
            # book-applied but not yet published — it must decode before
            # the flush barrier (mirrors CheckpointDaemon.checkpoint_now).
            posts = []
            with runner._dispatch_lock:
                runner._finish_pending_locked(posts)
                hs.parts["sink"].flush()
            for p in posts:
                p()
            time.sleep(0.02)

    threads = [threading.Thread(target=trader, args=(hs.stub, i))
               for i in range(4)]
    threads += [threading.Thread(target=trader, args=(hs.py_stub, 10 + i))
                for i in range(4)]
    q = threading.Thread(target=quiescer)
    q.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    done.set()
    q.join(timeout=10)
    stuck = [t.name for t in threads + [q] if t.is_alive()]
    assert not stuck, f"threads still running: {stuck}"
    assert not errors, errors
    hs.flush()
    assert audit(hs.db_path) == []


def test_native_client_book_and_metrics(hs):
    cli = me_native.client_binary()
    addr = f"127.0.0.1:{hs.gw_port}"
    r = subprocess.run([cli, addr, "qb", "QBOOK", "BUY", "LIMIT", "4200",
                        "4", "7"], capture_output=True, text=True, timeout=30)
    assert r.returncode == 0
    b = subprocess.run([cli, "book", addr, "QBOOK"],
                       capture_output=True, text=True, timeout=30)
    assert b.returncode == 0
    assert "book QBOOK: 1 bids / 0 asks" in b.stdout
    assert "bid 4200@Q4 x7" in b.stdout
    m = subprocess.run([cli, "metrics", addr],
                       capture_output=True, text=True, timeout=30)
    assert m.returncode == 0 and "counter orders_accepted" in m.stdout


def test_unicode_round_trip(hs):
    """Non-ASCII client ids / symbols through the C++ edge: UTF-8 bytes in
    the protobuf payload must round-trip through the C++ parser, the wide
    ring record, and the directory identically to the grpcio edge."""
    r = submit(hs.stub, client="客户-θ", symbol="SÝM€", price=31000, qty=2)
    assert r.success
    hs.flush()
    row = Storage(hs.db_path).get_order(r.order_id)
    assert row[1] == "客户-θ" and row[2] == "SÝM€"
    book = hs.stub.GetOrderBook(pb2.OrderBookRequest(symbol="SÝM€"), timeout=10)
    assert [o.client_id for o in book.bids] == ["客户-θ"]
    ok = hs.stub.CancelOrder(
        pb2.CancelRequest(client_id="客户-θ", order_id=r.order_id), timeout=10)
    assert ok.success


def test_large_book_response(tmp_path_factory):
    """A book response bigger than one HTTP/2 frame (16KB) must arrive
    intact through the gateway's DATA splitting + send-window accounting."""
    cfg = EngineConfig(num_symbols=4, capacity=512, batch=16, max_fills=1 << 14)
    h = GwHarness(str(tmp_path_factory.mktemp("big") / "big.db"), cfg=cfg)
    try:
        for i in range(480):
            r = submit(h.stub, client=f"deep-client-{i:04d}", symbol="DEEP",
                       side=pb2.BUY, price=10_000 - i, qty=1 + i % 7)
            assert r.success, i
        book = h.stub.GetOrderBook(pb2.OrderBookRequest(symbol="DEEP"),
                                   timeout=30)
        assert len(book.bids) == 480
        assert book.ByteSize() > H2_MAX_FRAME
        # Priority order preserved end to end.
        prices = [o.price for o in book.bids]
        assert prices == sorted(prices, reverse=True)
    finally:
        h.close()


def test_gateway_metrics_surfaced(hs):
    submit(hs.stub, client="gm", symbol="GMTR", price=15000, qty=1)
    m = hs.stub.GetMetrics(pb2.MetricsRequest(), timeout=10)
    assert m.gauges.get("gateway_requests", 0) > 0
    assert m.gauges.get("gateway_connections", 0) > 0


def test_native_client_watch_md(hs):
    """The C++ client's server-streaming watcher against the C++ gateway."""
    cli = me_native.client_binary()
    addr = f"127.0.0.1:{hs.gw_port}"
    proc = subprocess.Popen([cli, "watch-md", addr, "WTCH", "2"],
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True)
    try:
        time.sleep(0.5)
        submit(hs.stub, client="w1", symbol="WTCH", side=pb2.BUY,
               price=21000, qty=3)
        time.sleep(0.3)
        submit(hs.stub, client="w2", symbol="WTCH", side=pb2.SELL,
               price=22000, qty=4)
        out, _ = proc.communicate(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()  # a missed update must not leak a blocked watcher
    assert proc.returncode == 0, out
    lines = [ln for ln in out.splitlines() if ln.startswith("[md]")]
    assert len(lines) == 2
    assert "WTCH bid=21000 x3" in lines[0]
    assert "ask=22000 x4" in lines[1]


def test_native_client_watch_orders(hs):
    cli = me_native.client_binary()
    addr = f"127.0.0.1:{hs.gw_port}"
    proc = subprocess.Popen([cli, "watch-orders", addr, "flw2", "2"],
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True)
    try:
        time.sleep(0.5)
        r = submit(hs.stub, client="flw2", symbol="WORD", side=pb2.BUY,
                   price=33000, qty=6)
        assert r.success
        submit(hs.stub, client="ctr2", symbol="WORD", side=pb2.SELL,
               price=33000, qty=6)
        out, _ = proc.communicate(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out
    lines = [ln for ln in out.splitlines() if ln.startswith("[order]")]
    assert len(lines) == 2
    assert f"{r.order_id} status=0" in lines[0]          # NEW ack
    assert "status=2" in lines[1] and "remaining=0" in lines[1]  # FILLED


def test_native_client_queries_against_grpcio_server(hs):
    """book/metrics via our HTTP/2 client against the grpc C-core server —
    its HPACK encoder Huffman-codes response headers, exercising the
    client-side decoder the gateway tests don't."""
    cli = me_native.client_binary()
    addr = f"127.0.0.1:{hs.port}"
    r = subprocess.run([cli, addr, "qg", "QGRP", "BUY", "LIMIT", "5150",
                        "4", "9"], capture_output=True, text=True, timeout=30)
    assert r.returncode == 0
    b = subprocess.run([cli, "book", addr, "QGRP"],
                       capture_output=True, text=True, timeout=30)
    assert b.returncode == 0 and "bid 5150@Q4 x9" in b.stdout
    m = subprocess.run([cli, "metrics", addr],
                       capture_output=True, text=True, timeout=30)
    assert m.returncode == 0 and "counter orders_accepted" in m.stdout


def test_concurrent_streams_one_channel(hs):
    """64 in-flight unary calls multiplexed on ONE grpc C-core channel:
    interleaved HEADERS/DATA frames and concurrent C++-side completions on
    a single connection must all resolve correctly."""
    from concurrent.futures import ThreadPoolExecutor

    def one(i):
        r = submit(hs.stub, client=f"mx{i}", symbol="MUXD",
                   side=pb2.BUY if i % 2 else pb2.SELL,
                   price=10_000, qty=1)
        return r.success

    with ThreadPoolExecutor(max_workers=64) as ex:
        assert all(ex.map(one, range(64)))


def test_auction_through_native_edge(tmp_path):
    """The full open-auction flow entirely through the C++ gateway: rests
    accumulate a crossed book, RunAuction (forwarded method M_AUCTION)
    uncrosses, continuous matching resumes — one implementation, both
    transports."""
    h = GwHarness(str(tmp_path / "gw-auction.db"),
                  cfg=EngineConfig(num_symbols=4, capacity=16, batch=4))
    try:
        h.parts["runner"].auction_mode = True

        def sub(client, side, price, qty):
            return h.stub.SubmitOrder(
                pb2.OrderRequest(client_id=client, symbol="GAU", side=side,
                                 order_type=pb2.LIMIT, price=price, scale=4,
                                 quantity=qty), timeout=15)

        assert sub("b", pb2.BUY, 102, 5).success
        assert sub("a", pb2.SELL, 100, 3).success
        # MARKET rejected during the call period — via the C++ edge.
        rm = h.stub.SubmitOrder(
            pb2.OrderRequest(client_id="m", symbol="GAU", side=pb2.BUY,
                             order_type=pb2.MARKET, quantity=1), timeout=15)
        assert not rm.success and "auction call period" in rm.error_message

        resp = h.stub.RunAuction(pb2.AuctionRequest(symbol="GAU"),
                                 timeout=30)
        assert resp.success, resp.error_message
        assert resp.executed_quantity == 3 and resp.symbols_crossed == 1
        # p* = 100: executable is 3 at both 100 and 102, imbalance |5-3|=2
        # at both -> tie-break takes the LOWEST price: 100.
        assert resp.clearing_price == 100

        # Per-symbol uncross keeps the call period; the all-symbols
        # uncross (still via the C++ edge) opens continuous trading.
        assert h.parts["runner"].auction_mode
        assert h.stub.RunAuction(pb2.AuctionRequest(), timeout=30).success
        assert not h.parts["runner"].auction_mode
        r = sub("c", pb2.SELL, 102, 2)   # crosses the remaining 2@102 bid
        assert r.success
        h.flush()
        import sqlite3
        db = sqlite3.connect(h.db_path)
        assert db.execute("select count(*) from fills").fetchone()[0] >= 2
        db.close()
    finally:
        h.close()


def test_complete_batch_truncation_sweeps_pending():
    """A truncated completion buffer must fail every pending unary RPC
    immediately (me_gateway_complete_batch's skew sweep) — the unparsed
    tail's clients get a prompt INTERNAL, never a hang to their RPC
    deadline. Drives a raw NativeGateway (no bridge: completions are
    injected by hand), with the well-formed prefix still delivered."""
    import struct
    from concurrent.futures import ThreadPoolExecutor

    gw = me_native.NativeGateway("127.0.0.1:0")
    port = gw.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    stub = MatchingEngineStub(channel)
    try:
        def one(i):
            t0 = time.perf_counter()
            try:
                r = stub.SubmitOrder(
                    pb2.OrderRequest(client_id=f"tr{i}", symbol="TRC",
                                     order_type=pb2.LIMIT, side=pb2.BUY,
                                     price=10_000, scale=4, quantity=1),
                    timeout=30,
                )
                return ("ok", r, time.perf_counter() - t0)
            except grpc.RpcError as e:
                return ("err", e, time.perf_counter() - t0)

        with ThreadPoolExecutor(max_workers=3) as ex:
            futs = [ex.submit(one, i) for i in range(3)]
            # All three ops must be in the ring (and their tags pending)
            # before the malformed completion goes in.
            recs = []
            deadline = time.time() + 10
            while len(recs) < 3 and time.time() < deadline:
                recs += gw.pop_batch(8, window_us=1000, first_wait_us=200_000)
            assert len(recs) == 3
            by_client = {r[7]: r[0] for r in recs}

            # n claims 3 records: [0] well-formed success for tr0, [1]
            # truncated mid-oid (oid_len runs past the buffer), [2] never
            # encoded — the sweep must fail BOTH tr1 and tr2.
            buf = struct.pack("<I", 3)
            buf += struct.pack("<QBBH", by_client["tr0"], 0, 1, 5) + b"OID-1"
            buf += struct.pack("<H", 0)
            buf += struct.pack("<QBBH", by_client["tr1"], 0, 1, 500) + b"xy"
            gw.complete_batch_raw(buf)

            res = {f"tr{i}": futs[i].result(timeout=15) for i in range(3)}

        kind, resp, _ = res["tr0"]
        assert kind == "ok" and resp.success and resp.order_id == "OID-1"
        for c in ("tr1", "tr2"):
            kind, err, elapsed = res[c]
            assert kind == "err", f"{c}: swept op must fail, got {err}"
            assert err.code() == grpc.StatusCode.INTERNAL
            assert "truncated" in err.details()
            # Prompt sweep, not an RPC-deadline hang.
            assert elapsed < 10, f"{c}: swept after {elapsed:.1f}s"
    finally:
        channel.close()
        gw.shutdown()
        gw.destroy()


def test_native_batch_path_through_gateway(tmp_path):
    """The in-gateway native M_BATCH path (no python on the payload):
    SubmitOrderBatch over the C++ edge converts + bulk-pushes records in
    the gateway itself (me_oprec_flaws + me_oprec_to_gwop + ring_push_n)
    and assembles the positional response from ring completions. The
    structural screen's messages must match record_flaws' wording, and
    the whole flow must behave exactly like the grpcio batch edge."""
    from matching_engine_tpu.domain import oprec

    hs = GwHarness(str(tmp_path / "gwbatch.db"))
    try:
        arr = oprec.pack_records([
            (1, 1, 0, 10000, 5, b"BAT-0", b"alice", b""),
            (1, 2, 0, 10000, 5, b"BAT-0", b"bob", b""),   # crosses alice
            (1, 9, 0, 10000, 5, b"BAT-1", b"carol", b""),  # bad side
            (2, 0, 0, 0, 0, b"", b"mallory", b"OID-99999"),  # unknown id
        ])
        resp = hs.stub.SubmitOrderBatch(
            pb2.OrderBatchRequest(ops=oprec.encode_payload(arr)),
            timeout=30)
        assert resp.success
        assert list(resp.ok) == [True, True, False, False]
        # The C++ structural screen answers with record_flaws' words.
        assert resp.error[2] == "side must be BUY or SELL"
        assert resp.error[3] == "unknown order id"
        assert resp.order_id[0].startswith("OID-")
        assert resp.order_id[1].startswith("OID-")
        # The matched pair landed durably, like any other edge.
        hs.flush()
        st = Storage(hs.db_path)
        st.init()
        try:
            assert st.count("fills") >= 1
        finally:
            st.close()
        # Whole-payload poisoning answers app-level, not transport.
        bad = hs.stub.SubmitOrderBatch(
            pb2.OrderBatchRequest(ops=b"NOTMAGIC" + b"\x00" * 384),
            timeout=30)
        assert not bad.success and "magic" in bad.error_message
        # An amend through the batch verb reports remaining positionally.
        sub = oprec.pack_records(
            [(1, 1, 0, 10000, 9, b"BAT-2", b"dave", b"")])
        r1 = hs.stub.SubmitOrderBatch(
            pb2.OrderBatchRequest(ops=oprec.encode_payload(sub)),
            timeout=30)
        assert r1.ok[0]
        am = oprec.pack_records(
            [(3, 0, 0, 0, 4, b"", b"dave", r1.order_id[0].encode())])
        r2 = hs.stub.SubmitOrderBatch(
            pb2.OrderBatchRequest(ops=oprec.encode_payload(am)),
            timeout=30)
        assert r2.ok[0] and r2.remaining[0] == 4
    finally:
        hs.close()
