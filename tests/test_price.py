"""Q4 normalizer goldens.

Semantics oracle: the reference's normalizer (include/domain/price.hpp:15-29)
and its unit tables (tests/test_price.cpp:6-20) — up/downscale, truncation
toward zero, scale range, int64 overflow.
"""

import pytest

from matching_engine_tpu.domain import (
    K_TARGET_SCALE,
    PriceError,
    normalize_to_q4,
    normalize_to_q4_jax,
)


@pytest.mark.parametrize(
    "price,scale,expected",
    [
        # identity at Q4
        (12345, 4, 12345),
        (0, 4, 0),
        # upscale (scale < 4): multiply by 10^(4-scale)
        (1, 0, 10000),          # 1 unit -> 1.0000
        (5, 2, 500),            # 0.05 -> 0.0500
        (123, 3, 1230),
        # downscale (scale > 4): divide, truncate toward zero
        (100500000, 8, 10050),  # 1.005 @ scale 8 -> 1.0050
        (10000, 8, 1),          # 0.0001 @ scale 8 -> Q4 1 (integration oracle:
                                #  ref tests/test_submit_order.cpp stores price=1)
        (10050, 9, 0),          # truncates to zero (ref test_price.cpp case)
        (19999, 5, 1999),       # truncation, not rounding
        (-19999, 5, -1999),     # toward zero for negatives too
        (123456789, 6, 1234567),
        # max scale
        (10**18, 18, 10**4),
    ],
)
def test_normalize_examples(price, scale, expected):
    assert normalize_to_q4(price, scale) == expected


def test_scale_out_of_range():
    with pytest.raises(PriceError):
        normalize_to_q4(1, -1)
    with pytest.raises(PriceError):
        normalize_to_q4(1, 19)


def test_overflow_rejects():
    # 2^62 at scale 0 would need *10^4 -> overflows int64
    with pytest.raises(PriceError):
        normalize_to_q4(2**62, 0)
    # just under the edge is fine
    assert normalize_to_q4((2**63 - 1) // 10**4, 0) == ((2**63 - 1) // 10**4) * 10**4


def test_target_scale_is_q4():
    assert K_TARGET_SCALE == 4


@pytest.mark.parametrize(
    "price,scale,expected",
    [(12345, 4, 12345), (5, 2, 500), (100500000, 8, 10050), (10050, 9, 0), (-19999, 5, -1999)],
)
def test_jax_mirror_matches_host(price, scale, expected):
    out, ok = normalize_to_q4_jax(price, scale)
    assert bool(ok)
    assert int(out) == expected


def test_jax_mirror_flags_bad_scale():
    _, ok = normalize_to_q4_jax(1, 19)
    assert not bool(ok)


def test_jax_mirror_deep_downscale_no_lane_wrap():
    # 10^shift for shift > 9 wraps int32; the two-step divide must not.
    out, ok = normalize_to_q4_jax(2_000_000_000, 17)  # shift 13
    assert bool(ok) and int(out) == normalize_to_q4(2_000_000_000, 17) == 0
    out, ok = normalize_to_q4_jax(2_000_000_000, 13)  # shift 9
    assert bool(ok) and int(out) == normalize_to_q4(2_000_000_000, 13) == 2
    out, ok = normalize_to_q4_jax(1_999_999_999, 18)
    assert bool(ok) and int(out) == 0


def test_jax_mirror_flags_upscale_overflow():
    # 10^6 at scale 0 -> 10^10 overflows int32 lanes: must flag, not wrap.
    out, ok = normalize_to_q4_jax(1_000_000, 0)
    assert not bool(ok) and int(out) == 0
    # At the int32 edge: 214748 * 10^4 = 2147480000 fits.
    out, ok = normalize_to_q4_jax(214748, 0)
    assert bool(ok) and int(out) == 2_147_480_000
