"""Sorted-book kernel (engine/kernel_sorted.py): bit-parity with the host
oracle AND the production matrix kernel, plus the dense-sorted-prefix
invariant the O(CAP)-per-order formulation depends on."""

import numpy as np
import pytest

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.flow import realistic_order_stream
from matching_engine_tpu.engine.harness import (
    HostOrder,
    apply_orders,
    build_batches,
    decode_step,
    random_order_stream,
    snapshot_books,
)
from matching_engine_tpu.engine.kernel import OP_SUBMIT
from matching_engine_tpu.engine.kernel_sorted import engine_step_sorted
from matching_engine_tpu.engine.oracle import OracleBook
from matching_engine_tpu.proto import BUY, LIMIT, MARKET, SELL


def apply_sorted(cfg, book, orders):
    """apply_orders for the sorted kernel (per-step decode; test-only)."""
    results, fills = [], []
    for b in build_batches(cfg, orders):
        book, out = engine_step_sorted(cfg, book, b)
        r, f, overflow = decode_step(cfg, b, out)
        assert not overflow
        results.extend(r)
        fills.extend(f)
    return book, results, fills


def run_oracle(cfg, orders):
    oracles = [OracleBook(capacity=cfg.capacity)
               for _ in range(cfg.num_symbols)]
    res, fills = [], []
    for o in orders:
        if o.op == OP_SUBMIT:
            r = oracles[o.sym].submit(o.oid, o.side, o.otype, o.price, o.qty,
                                      owner=o.owner)
        else:
            r = oracles[o.sym].cancel(o.oid)
        res.append((o.oid, o.sym, r.status, r.filled, r.remaining))
        fills.extend((o.sym, f.taker_oid, f.maker_oid, f.price_q4,
                      f.quantity) for f in r.fills)
    return res, fills, [o.snapshot() for o in oracles]


def assert_sorted_parity(cfg, orders):
    book, d_res, d_fills = apply_sorted(cfg, init_book(cfg), orders)
    o_res, o_fills, o_snaps = run_oracle(cfg, orders)
    assert sorted((r.oid, r.sym, r.status, r.filled, r.remaining)
                  for r in d_res) == sorted(o_res)
    for s in range(cfg.num_symbols):
        dev = [(f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
               for f in d_fills if f.sym == s]
        orc = [f[1:] for f in o_fills if f[0] == s]
        assert dev == orc, f"fill mismatch sym {s}"
    d_snaps = snapshot_books(book)
    for s in range(cfg.num_symbols):
        assert d_snaps[s][0] == o_snaps[s][0], f"bid book mismatch sym {s}"
        assert d_snaps[s][1] == o_snaps[s][1], f"ask book mismatch sym {s}"
    assert_sorted_invariant(book)


def assert_sorted_invariant(book):
    """Live entries are a dense prefix, priority-sorted (key asc, seq asc
    within equal price), freed slots zeroed."""
    for side, price, qty, seq, sign in (
        ("bid", book.bid_price, book.bid_qty, book.bid_seq, -1),
        ("ask", book.ask_price, book.ask_qty, book.ask_seq, +1),
    ):
        p, q, sq = (np.asarray(price), np.asarray(qty), np.asarray(seq))
        for s in range(p.shape[0]):
            live = q[s] > 0
            n = int(live.sum())
            assert live[:n].all() and not live[n:].any(), \
                f"{side} sym {s}: live entries not a dense prefix"
            keys = list(zip((sign * p[s][:n]).tolist(), sq[s][:n].tolist()))
            assert keys == sorted(keys), f"{side} sym {s}: not sorted"
            assert not q[s][n:].any() and not p[s][n:].any(), \
                f"{side} sym {s}: freed slots not zeroed"


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_fuzz_parity_uniform(seed):
    cfg = EngineConfig(num_symbols=8, capacity=32, batch=8, max_fills=1 << 14)
    stream = random_order_stream(8, 800, seed=seed, cancel_p=0.2,
                                 market_p=0.2, price_levels=6)
    assert_sorted_parity(cfg, stream)


@pytest.mark.parametrize("seed", [0, 1])
def test_fuzz_parity_realistic_flow(seed):
    cfg = EngineConfig(num_symbols=8, capacity=16, batch=8, max_fills=1 << 14)
    stream = realistic_order_stream(8, 1200, seed=seed, deep_fraction=0.3)
    assert_sorted_parity(cfg, stream)


def test_capacity_reject_and_refill():
    """Side-full REJECTED, then a cancel frees a slot and the next rest
    lands sorted."""
    cfg = EngineConfig(num_symbols=1, capacity=4, batch=4, max_fills=256)
    orders = [HostOrder(0, OP_SUBMIT, BUY, LIMIT, 100 + i, 1, oid=i + 1)
              for i in range(5)]                       # 5th: side full
    from matching_engine_tpu.engine.kernel import OP_CANCEL

    orders.append(HostOrder(0, OP_CANCEL, BUY, oid=2))
    orders.append(HostOrder(0, OP_SUBMIT, BUY, LIMIT, 99, 1, oid=6))
    assert_sorted_parity(cfg, orders)


def test_stp_and_market_through_sorted_kernel():
    cfg = EngineConfig(num_symbols=1, capacity=16, batch=8, max_fills=256)
    orders = [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 100, 3, oid=1, owner=7),
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 101, 3, oid=2, owner=8),
        HostOrder(0, OP_SUBMIT, BUY, LIMIT, 101, 3, oid=3, owner=7),  # skips own
        HostOrder(0, OP_SUBMIT, BUY, MARKET, 0, 5, oid=4, owner=9),
    ]
    assert_sorted_parity(cfg, orders)


@pytest.mark.parametrize("seed", [0, 1])
def test_sorted_matches_matrix_kernel(seed):
    """The two formulations produce identical statuses, fills, and books
    on the same stream (snapshot_books canonicalizes slot order)."""
    cfg = EngineConfig(num_symbols=4, capacity=32, batch=8, max_fills=1 << 14)
    stream = random_order_stream(4, 600, seed=seed, cancel_p=0.15,
                                 market_p=0.15)
    mb, m_res, m_fills = apply_orders(cfg, init_book(cfg), stream)
    sb, s_res, s_fills = apply_sorted(cfg, init_book(cfg), stream)
    assert [(r.oid, r.status, r.filled, r.remaining) for r in m_res] == \
           [(r.oid, r.status, r.filled, r.remaining) for r in s_res]
    assert [(f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
            for f in m_fills] == \
           [(f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
            for f in s_fills]
    assert snapshot_books(mb) == snapshot_books(sb)


def test_op_rest_crossing_accumulation_matches_matrix():
    """OP_REST (auction accumulation) through the sorted kernel: crossing
    orders REST without matching — the book stands crossed, sorted, and
    identical to the matrix kernel's book content on the same stream."""
    from matching_engine_tpu.engine.kernel import OP_REST

    cfg = EngineConfig(num_symbols=2, capacity=16, batch=4, max_fills=256)
    stream = [
        HostOrder(0, OP_REST, BUY, LIMIT, 105, 5, oid=1),
        HostOrder(0, OP_REST, SELL, LIMIT, 100, 4, oid=2),   # crosses: rests
        HostOrder(0, OP_REST, BUY, LIMIT, 103, 2, oid=3),
        HostOrder(0, OP_REST, SELL, LIMIT, 101, 3, oid=4),
        HostOrder(1, OP_REST, BUY, LIMIT, 50, 1, oid=5),
        # Same price as oid 1 — FIFO: must sort BEHIND it.
        HostOrder(0, OP_REST, BUY, LIMIT, 105, 7, oid=6),
    ]
    mb, m_res, m_fills = apply_orders(cfg, init_book(cfg), stream)
    sb, s_res, s_fills = apply_sorted(cfg, init_book(cfg), stream)
    assert m_fills == [] and s_fills == []          # nothing matches
    assert [(r.oid, r.status) for r in m_res] == \
           [(r.oid, r.status) for r in s_res]
    assert snapshot_books(mb) == snapshot_books(sb)
    assert_sorted_invariant(sb)
    # The book really stands crossed (best bid 105 >= best ask 100).
    bids, asks = snapshot_books(sb)[0]
    assert bids[0][1] == 105 and asks[0][1] == 100
    # FIFO at equal price: oid 1 ahead of oid 6.
    assert [r[0] for r in bids if r[1] == 105] == [1, 6]


def test_sparse_path_with_sorted_kernel():
    """EngineConfig(kernel='sorted') routes every dispatch shape through
    the sorted formulation: the sparse path and the dense path stay
    bit-equal on the same stream (and both carry the sorted invariant)."""
    from tests.test_sparse import run_dense, run_sparse

    cfg = EngineConfig(num_symbols=16, capacity=32, batch=8,
                       max_fills=1 << 12, kernel="sorted")
    stream = random_order_stream(16, 6 * 16 * 8, seed=2, cancel_p=0.15,
                                 market_p=0.1, price_levels=12)
    dbook, dres, dfills = run_dense(cfg, stream)
    sbook, sres, sfills = run_sparse(cfg, stream)
    for f in dbook._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(dbook, f)), np.asarray(getattr(sbook, f)), f)
    assert dres == sres and dfills == sfills
    assert_sorted_invariant(dbook)


def test_server_with_sorted_kernel(tmp_path):
    """Full serving stack on the sorted kernel (--engine-kernel sorted):
    continuous cross, cancel, book query, call auction with uncross — the
    auction compact keeps the invariant so post-auction continuous
    matching still works."""
    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    cfg = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=256,
                       kernel="sorted")
    server, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "sorted.db"), cfg, window_ms=1.0,
        log=False)
    parts["runner"].auction_mode = True
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))

    def sub(client, side, price, qty, symbol="SK"):
        r = stub.SubmitOrder(
            pb2.OrderRequest(client_id=client, symbol=symbol, side=side,
                             order_type=pb2.LIMIT, price=price, scale=4,
                             quantity=qty), timeout=15)
        assert r.success, r.error_message
        return r

    try:
        # Call period: crossing orders REST.
        sub("b1", pb2.BUY, 102, 5)
        sub("a1", pb2.SELL, 100, 4)
        sub("a2", pb2.SELL, 101, 3)
        resp = stub.RunAuction(pb2.AuctionRequest(symbol=""), timeout=30)
        assert resp.success and resp.symbols_crossed == 1
        assert resp.executed_quantity == 5  # bid 5 fills against both asks
        # Continuous trading resumed on the compacted sorted book: the
        # leftover ask (2 @ 101) fills a new taker.
        r = sub("b2", pb2.BUY, 101, 2)
        book = stub.GetOrderBook(pb2.OrderBookRequest(symbol="SK"),
                                 timeout=10)
        assert len(book.asks) == 0 and len(book.bids) == 0
        # Cancel path: rest an order, cancel it.
        r3 = sub("c", pb2.BUY, 90, 1)
        cr = stub.CancelOrder(pb2.CancelRequest(
            client_id="c", order_id=r3.order_id), timeout=10)
        assert cr.success
    finally:
        shutdown(server, parts)


def test_venue_depth_capacity_2048():
    """CAP > 1073 (where capacity * MAX_QUANTITY wraps int32): the sorted
    kernel's saturating prefix sum keeps allocations exact with
    near-MAX_QUANTITY makers stacked deep; oracle parity holds."""
    from matching_engine_tpu.engine.book import MAX_QUANTITY

    cap = 2048
    cfg = EngineConfig(num_symbols=1, capacity=cap, batch=8,
                       max_fills=1 << 13, kernel="sorted")
    orders = []
    # 1200 max-quantity asks at one price: total resting qty 2.4e9 > 2^31.
    for i in range(1200):
        orders.append(HostOrder(0, OP_SUBMIT, SELL, LIMIT, 100,
                                MAX_QUANTITY, oid=i + 1))
    # A buy that sweeps the first two makers and part of the third.
    orders.append(HostOrder(0, OP_SUBMIT, BUY, LIMIT, 100,
                            2 * MAX_QUANTITY + 5, oid=9001))
    # A buy priced away from the wall: rests.
    orders.append(HostOrder(0, OP_SUBMIT, BUY, LIMIT, 99, 7, oid=9002))
    book, d_res, d_fills = apply_sorted(cfg, init_book(cfg), orders)
    o_res, o_fills, o_snaps = run_oracle(cfg, orders)
    assert sorted((r.oid, r.sym, r.status, r.filled, r.remaining)
                  for r in d_res) == sorted(o_res)
    assert [(f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
            for f in d_fills] == [f[1:] for f in o_fills]
    # FIFO: the sweep hit makers 1, 2, then 5 units of maker 3.
    assert [(f.maker_oid, f.quantity) for f in d_fills] == [
        (1, MAX_QUANTITY), (2, MAX_QUANTITY), (3, 5)]
    assert_sorted_invariant(book)
    assert snapshot_books(book)[0] == o_snaps[0]


def test_matrix_kernel_capacity_gate_unchanged():
    import pytest as _pytest

    with _pytest.raises(AssertionError):
        EngineConfig(num_symbols=1, capacity=2048, batch=4)  # matrix
    EngineConfig(num_symbols=1, capacity=2048, batch=4, kernel="sorted")
    with _pytest.raises(AssertionError):
        EngineConfig(num_symbols=1, capacity=16384, batch=4,
                     kernel="sorted")


def test_auction_works_at_venue_depth():
    """Venue-depth sorted configs now run call auctions (the wide-sum
    uncross, engine/auction_sorted.py): the call period opens, crossed
    rested interest clears, and continuous trading reopens — the round-4
    guard that REJECTED these requests is gone (VERDICT r4 missing #4)."""
    from matching_engine_tpu.server.engine_runner import EngineRunner

    cfg = EngineConfig(num_symbols=2, capacity=2048, batch=4,
                       max_fills=1 << 12, kernel="sorted")
    from matching_engine_tpu.server.engine_runner import EngineOp, OrderInfo

    r = EngineRunner(cfg)
    r.set_auction_mode(True)  # no longer raises at venue depth
    assert r.slot_acquire("S0") is not None
    ops = []
    for side, price in ((1, 101_0000), (2, 100_0000)):  # crossed rest
        num, oid = r.assign_oid()
        ops.append(EngineOp(3, OrderInfo(  # OP_REST
            oid=num, order_id=oid, client_id=f"c{side}", symbol="S0",
            side=side, otype=0, price_q4=price, quantity=5, remaining=5,
            status=0, handle=r.assign_handle())))
    r.run_dispatch(ops)
    summary = r.run_auction()
    assert summary["error"] == ""
    assert [c[0] for c in summary["crossed"]] == ["S0"]
    assert summary["crossed"][0][2] == 5  # executed volume
    assert not r.auction_mode  # all-symbols uncross reopens continuous


def test_top_of_book_size_saturates_at_venue_depth():
    """A price level holding > 2^31 total quantity reports the saturation
    clamp (2^30-1), never a wrapped negative size (the pre-fix behavior:
    finalize_step's int32 sum wrapped and market data published negative
    sizes)."""
    from matching_engine_tpu.engine.book import MAX_QUANTITY
    from matching_engine_tpu.engine.harness import build_batches

    cfg = EngineConfig(num_symbols=1, capacity=2048, batch=8,
                       max_fills=1 << 12, kernel="sorted")
    orders = [HostOrder(0, OP_SUBMIT, SELL, LIMIT, 100, MAX_QUANTITY,
                        oid=i + 1) for i in range(1200)]
    book = init_book(cfg)
    out = None
    for b in build_batches(cfg, orders):
        book, out = engine_step_sorted(cfg, book, b)
    ask_size = int(np.asarray(out.ask_size)[0])
    assert ask_size == (1 << 30) - 1, ask_size
    assert int(np.asarray(out.best_ask)[0]) == 100
