"""Time-in-force (IOC / FOK) semantics, every layer.

The reference's wire contract has no tif concept (its OrderType enum stops
at LIMIT/MARKET, /root/reference/proto/matching_engine.proto:11-14); this is
an additive venue-parity extension. Covered here:

- the collapsed (order_type, tif) otype codes are pinned identical across
  proto/__init__.py, engine/kernel.py, and engine/oracle.py;
- oracle unit semantics: IOC cancels its remainder instead of resting;
  FOK is all-or-nothing against the liquidity the taker is eligible for
  (price-crossing, live, not self-owned);
- device-vs-oracle fill parity on directed cases and randomized mixed
  streams, over BOTH kernel formulations;
- venue-depth FOK exactness under the sorted kernel's saturating prefix
  sums (availability compare stays exact past int32 wrap territory).
"""

import pytest

from matching_engine_tpu.engine import kernel as K
from matching_engine_tpu.engine import oracle as O
from matching_engine_tpu.engine.book import EngineConfig, MAX_QUANTITY
from matching_engine_tpu.engine.harness import HostOrder, random_order_stream
from matching_engine_tpu.engine.oracle import OracleBook
from matching_engine_tpu.proto import (
    LIMIT_FOK,
    LIMIT_IOC,
    MARKET_FOK,
    TIF_FOK,
    TIF_GTC,
    TIF_IOC,
    collapse_otype,
    pb2,
    split_otype,
)

from tests.test_kernel_parity import assert_parity

BUY, SELL = K.BUY, K.SELL
LIMIT, MARKET = K.LIMIT, K.MARKET
OP_SUBMIT, OP_CANCEL = K.OP_SUBMIT, K.OP_CANCEL

NEW = O.NEW
FILLED = O.FILLED
PARTIALLY_FILLED = O.PARTIALLY_FILLED
CANCELED = O.CANCELED


# -- code pinning ------------------------------------------------------------

def test_collapsed_codes_pinned_across_layers():
    assert (K.LIMIT_IOC, K.LIMIT_FOK, K.MARKET_FOK) == (2, 3, 4)
    assert (O.LIMIT_IOC, O.LIMIT_FOK, O.MARKET_FOK) == (2, 3, 4)
    assert (LIMIT_IOC, LIMIT_FOK, MARKET_FOK) == (2, 3, 4)
    assert (K.LIMIT, K.MARKET) == (pb2.LIMIT, pb2.MARKET)


def test_collapse_split_roundtrip():
    assert collapse_otype(pb2.LIMIT, TIF_GTC) == K.LIMIT
    assert collapse_otype(pb2.MARKET, TIF_GTC) == K.MARKET
    assert collapse_otype(pb2.MARKET, TIF_IOC) == K.MARKET  # inherent IOC
    assert collapse_otype(pb2.LIMIT, TIF_IOC) == LIMIT_IOC
    assert collapse_otype(pb2.LIMIT, TIF_FOK) == LIMIT_FOK
    assert collapse_otype(pb2.MARKET, TIF_FOK) == MARKET_FOK
    assert collapse_otype(pb2.LIMIT, 7) is None  # open-enum junk rejected
    for code in (K.LIMIT, K.MARKET, LIMIT_IOC, LIMIT_FOK, MARKET_FOK):
        base, tif = split_otype(code)
        assert collapse_otype(base, tif) == code


# -- oracle unit semantics ---------------------------------------------------

def test_ioc_partial_cancels_remainder():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10_000, 5)
    r = b.submit(2, BUY, LIMIT_IOC, 10_000, 8)
    assert r.status == CANCELED and r.filled == 5 and r.remaining == 3
    assert not r.rested and len(r.fills) == 1
    assert b.snapshot() == ([], [])  # nothing rested anywhere


def test_ioc_full_fill_is_filled():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10_000, 8)
    r = b.submit(2, BUY, LIMIT_IOC, 10_000, 8)
    assert r.status == FILLED and r.filled == 8


def test_ioc_no_cross_cancels_untouched():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10_000, 5)
    r = b.submit(2, BUY, LIMIT_IOC, 9_000, 5)  # below best ask
    assert r.status == CANCELED and r.filled == 0 and r.remaining == 5
    assert r.fills == ()
    assert b.best_ask() == (10_000, 5)  # maker untouched


def test_ioc_respects_limit_price_across_levels():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10_000, 3)
    b.submit(2, SELL, LIMIT, 10_100, 3)
    r = b.submit(3, BUY, LIMIT_IOC, 10_000, 6)  # only level 1 eligible
    assert r.status == CANCELED and r.filled == 3 and r.remaining == 3
    assert b.best_ask() == (10_100, 3)


def test_fok_success_sweeps_levels():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10_000, 3)
    b.submit(2, SELL, LIMIT, 10_100, 4)
    r = b.submit(3, BUY, LIMIT_FOK, 10_100, 7)
    assert r.status == FILLED and r.filled == 7
    assert [f.quantity for f in r.fills] == [3, 4]
    assert b.snapshot() == ([], [])


def test_fok_insufficient_cancels_untouched():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10_000, 3)
    b.submit(2, SELL, LIMIT, 10_100, 4)
    r = b.submit(3, BUY, LIMIT_FOK, 10_000, 7)  # eligible = 3 < 7
    assert r.status == CANCELED and r.filled == 0 and r.remaining == 7
    assert r.fills == ()
    # Both makers still rest at full size.
    assert b.best_ask() == (10_000, 3)
    _, asks = b.snapshot()
    assert [(p, q) for (_, p, q, _) in asks] == [(10_000, 3), (10_100, 4)]


def test_market_fok_all_or_nothing():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10_000, 3)
    b.submit(2, SELL, LIMIT, 99_000, 4)
    ok = b.submit(3, BUY, MARKET_FOK, 0, 7)
    assert ok.status == FILLED and ok.filled == 7
    b2 = OracleBook()
    b2.submit(1, SELL, LIMIT, 10_000, 3)
    fail = b2.submit(2, BUY, MARKET_FOK, 0, 7)
    assert fail.status == CANCELED and fail.filled == 0
    assert b2.best_ask() == (10_000, 3)


def test_fok_excludes_self_owned_liquidity():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10_000, 5, owner=7)
    b.submit(2, SELL, LIMIT, 10_000, 4, owner=9)
    # Owner 7's own 5 units are ineligible: avail = 4 < 6 -> cancel, and
    # BOTH makers keep resting (FOK never partially consumes).
    r = b.submit(3, BUY, LIMIT_FOK, 10_000, 6, owner=7)
    assert r.status == CANCELED and r.filled == 0
    assert b.best_ask() == (10_000, 9)
    # The other owner can take the same quantity fine.
    r2 = b.submit(4, BUY, LIMIT_FOK, 10_000, 6, owner=3)
    assert r2.status == FILLED and r2.filled == 6


def test_ioc_never_self_trades():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10_000, 5, owner=7)
    r = b.submit(2, BUY, LIMIT_IOC, 10_000, 5, owner=7)
    assert r.status == CANCELED and r.filled == 0
    assert b.best_ask() == (10_000, 5)


# -- device parity (both kernels) --------------------------------------------

KERNELS = ["matrix", "sorted"]


@pytest.mark.parametrize("kernel", KERNELS)
def test_parity_directed_tif_cases(kernel):
    cfg = EngineConfig(num_symbols=2, capacity=8, batch=8, kernel=kernel)
    orders = [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_000, 5, oid=1),
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_100, 4, oid=2),
        HostOrder(0, OP_SUBMIT, BUY, LIMIT_IOC, 10_000, 8, oid=3),   # part
        HostOrder(0, OP_SUBMIT, BUY, LIMIT_FOK, 10_100, 9, oid=4),   # fail
        HostOrder(0, OP_SUBMIT, BUY, LIMIT_FOK, 10_100, 4, oid=5),   # fill
        HostOrder(1, OP_SUBMIT, BUY, LIMIT, 9_000, 6, oid=6),
        HostOrder(1, OP_SUBMIT, SELL, MARKET_FOK, 0, 7, oid=7),      # fail
        HostOrder(1, OP_SUBMIT, SELL, MARKET_FOK, 0, 6, oid=8),      # fill
        HostOrder(1, OP_SUBMIT, SELL, LIMIT_IOC, 9_000, 2, oid=9),   # empty
    ]
    assert_parity(cfg, orders)


@pytest.mark.parametrize("kernel", KERNELS)
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_parity_fuzz_with_tif(kernel, seed):
    cfg = EngineConfig(num_symbols=4, capacity=16, batch=8, kernel=kernel)
    orders = random_order_stream(
        cfg.num_symbols, 160, seed=seed, tif_p=0.35, qty_max=12,
        price_levels=6)
    assert_parity(cfg, orders)


def test_fok_exact_at_venue_depth_saturating_sums():
    """Sorted kernel, capacity 2048, resting quantities near MAX_QUANTITY:
    the FOK availability compare must stay exact even though the ahead-
    prefix accumulator saturates (kernel_sorted.py)."""
    from matching_engine_tpu.engine.harness import apply_orders
    from matching_engine_tpu.engine.book import init_book

    cfg = EngineConfig(num_symbols=1, capacity=2048, batch=32,
                       kernel="sorted", max_fills=1 << 14)
    n_makers = 1100
    orders = [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_000 + i, MAX_QUANTITY,
                  oid=1 + i)
        for i in range(n_makers)
    ]
    # Aggregate eligible quantity is far past int32 — the running prefix
    # sum saturates at 2^30-1 long before the last maker.
    assert n_makers * MAX_QUANTITY > 2**31
    # A single maximal-quantity FOK: avail (saturated) >= qty must hold
    # and the order fills exactly, entirely from the best maker.
    orders.append(HostOrder(0, OP_SUBMIT, BUY, LIMIT_FOK,
                            10_000 + n_makers, MAX_QUANTITY, oid=100_000))
    book = init_book(cfg)
    book, results, fills = apply_orders(cfg, book, orders)
    by_oid = {r.oid: r for r in results}
    assert by_oid[100_000].status == FILLED
    assert by_oid[100_000].filled == MAX_QUANTITY

    # And the infeasible twin: empty the book's eligible window by pricing
    # the FOK below every ask — cancel untouched despite saturated sums.
    orders2 = orders[:n_makers] + [
        HostOrder(0, OP_SUBMIT, BUY, LIMIT_FOK, 9_999, MAX_QUANTITY,
                  oid=100_001)
    ]
    book2 = init_book(cfg)
    book2, results2, fills2 = apply_orders(cfg, book2, orders2)
    by_oid2 = {r.oid: r for r in results2}
    assert by_oid2[100_001].status == CANCELED
    assert by_oid2[100_001].filled == 0
    assert not [f for f in fills2 if f.taker_oid == 100_001]
