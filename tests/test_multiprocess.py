"""REAL 2-process jax.distributed test (VERDICT r2 "next round" #4).

Unlike tests/test_multihost.py (which unit-tests mesh/slice logic with
monkeypatches), this spawns two actual OS processes, bootstraps the JAX
distributed runtime over a localhost coordinator with 4 virtual CPU devices
each, and runs the multi-process serving contract end to end — sharded
dispatches from both hosts (with different dispatch counts), addressable-
shard decode, local book snapshots, and the host-sharded checkpoint
round trip. See tests/multiprocess_worker.py for what each process asserts.
"""

import json
import os
import socket
import subprocess
import sys

import pytest


def _probe() -> tuple[bool, str]:
    """Capability probe: multiprocess computations on the CPU backend
    need the gloo TCP collectives (selected by
    parallel/multihost.initialize); without them every worker dies at
    compile time with "Multiprocess computations aren't implemented on
    the CPU backend". Real accelerators don't route through the CPU
    collectives at all, so this only ever skips CPU-only environments
    whose jaxlib lacks the capability — the suite runs unchanged
    elsewhere. Returns (skip, reason) with the reason DERIVED from the
    live probe result (the versions observed now, not the ones some
    past environment pinned), so an upgrade that grows the capability
    un-skips with an accurate explanation."""
    import jax
    import jaxlib

    from matching_engine_tpu.parallel.multihost import (
        cpu_collectives_available,
    )

    try:
        platform = jax.default_backend()
    except RuntimeError:
        platform = "cpu"
    have = cpu_collectives_available()
    skip = platform == "cpu" and not have
    jl_ver = getattr(jaxlib, "__version__", "unknown")
    if skip:
        reason = (
            f"CPU backend lacks multiprocess collectives: this jaxlib "
            f"({jl_ver}) exposes no gloo TCP collectives factory "
            f"(probe: parallel/multihost.cpu_collectives_available). "
            f"Runs unchanged on a jaxlib that has it, or on a real "
            f"accelerator backend.")
    else:
        reason = (
            f"not skipped: backend={platform!r}, jaxlib {jl_ver} gloo "
            f"TCP collectives available={have}")
    return skip, reason


_SKIP, _SKIP_REASON = _probe()
pytestmark = pytest.mark.skipif(_SKIP, reason=_SKIP_REASON)

_WORKER = os.path.join(os.path.dirname(__file__), "multiprocess_worker.py")
_SERVER_WORKER = os.path.join(os.path.dirname(__file__),
                              "multiprocess_server_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_distributed(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # skip the axon relay bootstrap
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # worker sets its own 4-device flag
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multiprocess worker timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"

    results = {}
    for pid in (0, 1):
        with open(tmp_path / f"ok-{pid}.json") as f:
            results[pid] = json.load(f)
    # Disjoint halves of the symbol axis; different dispatch counts ran.
    assert results[0]["slice"] == [0, 4]
    assert results[1]["slice"] == [4, 8]
    assert results[0]["fills"] == 8    # 2 dispatches x 4 symbols
    assert results[1]["fills"] == 12   # 3 dispatches x 4 symbols


def test_two_process_full_servers(tmp_path):
    """The deployment model end to end: two complete serving stacks
    (grpcio edge, dispatcher, sink, own SQLite each) over ONE distributed
    mesh — local symbols flow, remote symbols reject at admission, both
    databases audit clean. See tests/multiprocess_server_worker.py."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, _SERVER_WORKER, str(port), str(pid),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("server worker timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"server worker {pid} failed:\n{out[-4000:]}"
    for pid in (0, 1):
        with open(tmp_path / f"srv-ok-{pid}.json") as f:
            r = json.load(f)
        # 8 grpcio-edge orders, +2 from the auction leg (which runs on
        # BOTH workers unconditionally — its probe symbol is chosen homed
        # on each host), +1 via the C++ gateway edge when the library is
        # built. Back-checks keep either leg from silently skipping.
        from matching_engine_tpu import native as me_native

        assert r["auction_orders"] == 2, "auction leg skipped"
        expected = 8 + 2 + (1 if r["gateway_ran"] else 0)
        assert r["orders"] == expected and r["fills"] == 5
        if me_native.gateway_available():
            assert r["gateway_ran"], "native gateway built but leg skipped"


def test_four_process_distributed(tmp_path):
    """Scale the real-process contract past 2 hosts (VERDICT r4 next-step
    9): four coordinator-joined processes, 2 virtual devices each, over
    one 8-device mesh — disjoint symbol quarters, per-host dispatch
    rates, addressable decode, and the host-sharded checkpoint."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), str(tmp_path),
             "4", "2"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(4)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=360)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("4-process worker timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
    for pid in range(4):
        with open(tmp_path / f"ok-{pid}.json") as f:
            r = json.load(f)
        assert r["slice"] == [pid * 2, pid * 2 + 2]
        assert r["fills"] == (2 + pid) * 2  # (2+pid) dispatches x 2 syms
