"""Fault injection: storage failures, sink crashes, profile tracing.

The reference's failure-handling contract (SURVEY.md §5.3): storage methods
never throw — a DB failure becomes an order reject, not a crash. These
tests force the failures nothing in the reference ever tested.
"""

import threading

import pytest

from matching_engine_tpu.storage import AsyncStorageSink, Storage


@pytest.fixture
def store(tmp_path):
    s = Storage(str(tmp_path / "fi.db"))
    assert s.init()
    yield s
    s.close()


def test_storage_methods_never_throw_after_close(tmp_path):
    s = Storage(str(tmp_path / "x.db"))
    assert s.init()
    s.close()
    # Every write path degrades to False, read paths to empty/None.
    assert s.insert_new_order("OID-1", "c", "S", 1, 0, 100, 5) is False
    assert s.update_order_status("OID-1", 2, 0) is False
    assert s.best_bid("S") is None
    assert s.open_orders() == []


def test_storage_init_failure_path(tmp_path):
    # A directory where the DB file should be -> sqlite cannot open it.
    bad = tmp_path / "as_dir.db"
    bad.mkdir()
    s = Storage(str(bad))
    assert s.init() is False


def test_async_sink_survives_poisoned_batch(store):
    """A batch that fails mid-apply (FK violation: fill for an order that
    was never inserted) must not kill the worker thread; later batches
    still flush."""
    from matching_engine_tpu.storage.storage import FillRow

    sink = AsyncStorageSink(store)
    sink.submit(fills=[FillRow("OID-missing", "OID-ghost", 100, 5)])
    sink.flush()
    # Worker is still alive and serving.
    sink.submit(orders=[("OID-9", "c", "S", 1, 0, 100, 5, 5, 0)])
    sink.flush()
    sink.close()
    assert store.get_order("OID-9") is not None


def test_dispatch_survives_sink_death(tmp_path):
    """If the durable tail dies entirely, matching must keep running (the
    reference's equivalent: insert failure => reject, server stays up; here
    the engine is ahead of the sink, so the dispatch itself survives)."""
    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.server.dispatcher import BatchDispatcher
    from matching_engine_tpu.server.engine_runner import EngineRunner
    from matching_engine_tpu.server.streams import StreamHub

    from matching_engine_tpu.engine.kernel import OP_SUBMIT
    from matching_engine_tpu.server.engine_runner import EngineOp, OrderInfo

    class DeadSink:
        def submit(self, **kw):
            raise RuntimeError("sink is dead")

        def close(self):
            pass

    cfg = EngineConfig(num_symbols=4, capacity=16, batch=2)
    runner = EngineRunner(cfg)
    disp = BatchDispatcher(runner, sink=DeadSink(), hub=StreamHub(), window_ms=1.0)

    def submit(side):
        oid_num, order_id = runner.assign_oid()
        assert runner.slot_acquire("SYM") is not None
        info = OrderInfo(
            oid=oid_num, order_id=order_id, client_id="c1", symbol="SYM",
            side=side, otype=0, price_q4=100, quantity=5, remaining=5, status=0,
            handle=runner.assign_handle())
        return disp.submit(EngineOp(OP_SUBMIT, info)).result(timeout=10)

    try:
        out1 = submit(side=1)
        assert out1 is not None
        # A second order still round-trips (and matches) after the sink
        # exploded on the first batch.
        out2 = submit(side=2)
        assert out2 is not None
    finally:
        disp.close()


def test_trace_context_writes_profile(tmp_path):
    import jax.numpy as jnp

    from matching_engine_tpu.utils.tracing import step_annotation, trace

    d = tmp_path / "prof"
    with trace(str(d)):
        with step_annotation("unit_step", 1):
            jnp.arange(8).sum().block_until_ready()
    files = list(d.rglob("*"))
    assert files, "profiler produced no trace files"


def test_timer_feeds_gauge():
    from matching_engine_tpu.utils.metrics import Metrics, Timer

    m = Metrics()
    with Timer(m, "x_us"):
        pass
    _, gauges = m.snapshot()
    assert "x_us_ema" in gauges and gauges["x_us_ema"] >= 0


def test_gateway_bridge_rejects_undecodable_records():
    """A record whose strings failed host-side decode (pop_batch emits
    None fields) is rejected individually — the batch's other ops
    dispatch normally and nothing raises into the drain loop."""
    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.server.engine_runner import EngineRunner
    from matching_engine_tpu.server.gateway_bridge import GatewayBridge

    class FakeGateway:
        def __init__(self):
            self.completed = []

        def set_callback(self, cb):
            pass

        def complete_submit(self, tag, ok, oid, err=""):
            self.completed.append(("submit", tag, ok, err))

        def complete_cancel(self, tag, ok, oid, err=""):
            self.completed.append(("cancel", tag, ok, err))

        def complete_batch(self, items):
            for (tag, kind, ok, oid, err) in items:
                kind_s = "cancel" if kind == 1 else "submit"
                self.completed.append((kind_s, tag, ok, err))

        def stats(self):
            return {"requests": 0, "ring_rejects": 0, "conns": 0}

    gw = FakeGateway()
    runner = EngineRunner(EngineConfig(num_symbols=4, capacity=16, batch=4,
                                       max_fills=256))
    bridge = GatewayBridge(gw, runner, service=None)
    bridge._drain_batch([
        (1, 1, 1, 0, 100, 5, None, None, None),     # poisoned submit
        (2, 2, 0, 0, 0, 0, None, None, None),       # poisoned cancel
        (3, 1, 1, 0, 100, 5, "OK", "alice", ""),    # healthy submit
    ])
    runner.finish_pending()  # the healthy op's dispatch is pipelined
    by_tag = {t: (kind, ok, err) for kind, t, ok, err in gw.completed}
    assert by_tag[1] == ("submit", False, "invalid request encoding")
    assert by_tag[2] == ("cancel", False, "invalid request encoding")
    assert by_tag[3][0] == "submit" and by_tag[3][1] is True
