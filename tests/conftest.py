"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in this environment, so sharding
tests run on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: the environment's sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon (the real-TPU tunnel), so mutating os.environ here is too
late for the platform choice — use jax.config.update instead. XLA_FLAGS is
still read at backend-init time, which happens after conftest import.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
