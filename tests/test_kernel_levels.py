"""Levels-kernel parity: price-level [L, F] FIFO books vs the oracle.

The third match formulation (engine/kernel_levels.py) must be
bit-identical to the LEVEL-AWARE oracle — same matching semantics as the
other kernels, but capacity is level-structured: at most L distinct live
prices per side, at most F resting orders per price, and a rest that
finds either full REJECTS even below total capacity (the metered-
backpressure contract). OracleBook models the identical rule via its
levels/level_fifo params.
"""

import random

import numpy as np
import pytest

from matching_engine_tpu.engine.auction import auction_step, decode_auction
from matching_engine_tpu.engine.book import (
    EngineConfig,
    default_levels,
    init_book,
    level_shape,
)
from matching_engine_tpu.engine.harness import (
    HostOrder,
    apply_orders,
    random_order_stream,
    snapshot_books,
)
from matching_engine_tpu.engine.kernel import OP_CANCEL, OP_REST, OP_SUBMIT
from matching_engine_tpu.engine.oracle import OracleBook
from matching_engine_tpu.proto import BUY, LIMIT, SELL


def levels_oracles(cfg: EngineConfig) -> list[OracleBook]:
    lvl, fifo = level_shape(cfg)
    return [OracleBook(cfg.capacity, levels=lvl, level_fifo=fifo)
            for _ in range(cfg.num_symbols)]


def run_both(cfg, host_orders):
    oracles = levels_oracles(cfg)
    o_res, o_fills = [], []
    for o in host_orders:
        if o.op == OP_SUBMIT:
            r = oracles[o.sym].submit(o.oid, o.side, o.otype, o.price,
                                      o.qty, owner=o.owner)
        elif o.op == OP_REST:
            r = oracles[o.sym].rest(o.oid, o.side, o.price, o.qty,
                                    owner=o.owner)
        else:
            r = oracles[o.sym].cancel(o.oid)
        o_res.append((o.oid, o.sym, int(r.status), r.filled, r.remaining))
        o_fills.extend((o.sym, f.taker_oid, f.maker_oid, f.price_q4,
                        f.quantity) for f in r.fills)

    book = init_book(cfg)
    book, d_res, d_fills = apply_orders(cfg, book, host_orders)
    d_res = [(r.oid, r.sym, r.status, r.filled, r.remaining) for r in d_res]
    d_fills = [(f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
               for f in d_fills]
    return book, oracles, (d_res, d_fills), (o_res, o_fills)


def assert_parity(cfg, host_orders):
    book, oracles, (d_res, d_fills), (o_res, o_fills) = run_both(
        cfg, host_orders)
    assert sorted(d_res) == sorted(o_res)
    for s in range(cfg.num_symbols):
        dev = [f for f in d_fills if f[0] == s]
        orc = [f for f in o_fills if f[0] == s]
        assert dev == orc, f"fill mismatch sym {s}:\n {dev}\n {orc}"
    d_snaps = snapshot_books(book)
    for s in range(cfg.num_symbols):
        assert d_snaps[s] == oracles[s].snapshot(), f"book mismatch sym {s}"
    return book, oracles


def test_default_levels_tile_capacity():
    for cap in (6, 16, 24, 128, 1024, 8192):
        lvl = default_levels(cap)
        assert cap % lvl == 0 and 1 <= lvl <= cap
    # The headline shapes.
    assert level_shape(EngineConfig(capacity=128, kernel="levels")) == (16, 8)
    assert level_shape(
        EngineConfig(capacity=8192, kernel="levels")) == (128, 64)


def test_levels_field_refused_for_other_kernels():
    with pytest.raises(AssertionError):
        EngineConfig(capacity=128, kernel="matrix", levels=8)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_randomized_parity(seed):
    cfg = EngineConfig(num_symbols=4, capacity=16, batch=8, kernel="levels")
    assert_parity(cfg, random_order_stream(cfg.num_symbols, 200, seed=seed))


def test_parity_tif_flows():
    cfg = EngineConfig(num_symbols=4, capacity=16, batch=8, kernel="levels")
    assert_parity(cfg, random_order_stream(cfg.num_symbols, 300, seed=5,
                                           tif_p=0.3))


def test_fuzz_parity_tight_structural_capacity():
    """Tiny L and F: directory-full and row-full rejects dominate — both
    sides must reject the identical ops."""
    cfg = EngineConfig(num_symbols=3, capacity=6, batch=5, kernel="levels",
                       levels=3)
    assert_parity(cfg, random_order_stream(
        cfg.num_symbols, 300, seed=7, cancel_p=0.3, market_p=0.25,
        price_levels=4, qty_max=20))


def test_fuzz_parity_single_price_fifo():
    """Everything at one price: within-level FIFO order is the whole
    game, and one row's F slots are the only capacity that matters."""
    cfg = EngineConfig(num_symbols=2, capacity=32, batch=8, kernel="levels",
                       levels=4)
    assert_parity(cfg, random_order_stream(
        cfg.num_symbols, 300, seed=21, cancel_p=0.2, market_p=0.2,
        price_levels=1, qty_max=10))


def test_level_row_full_rejects_below_total_capacity():
    """F orders at one price fill the row; the F+1st REJECTS even though
    the side holds far fewer than L*F orders — and a different price
    still rests."""
    cfg = EngineConfig(num_symbols=1, capacity=16, batch=4, kernel="levels",
                       levels=4)  # F = 4
    orders = [HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_000, 2, oid=i + 1)
              for i in range(5)]
    orders.append(HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_100, 2, oid=6))
    book, oracles, (d_res, _), _ = run_both(cfg, orders)
    by_oid = {r[0]: r for r in d_res}
    assert by_oid[5][2] == 4, by_oid[5]   # REJECTED: row full
    assert by_oid[6][2] == 0              # NEW: fresh level rests
    assert_parity(cfg, orders)


def test_level_directory_full_rejects():
    """L distinct prices exhaust the level directory; a new price
    REJECTS while an existing price keeps resting."""
    cfg = EngineConfig(num_symbols=1, capacity=16, batch=4, kernel="levels",
                       levels=4)
    orders = [HostOrder(0, OP_SUBMIT, BUY, LIMIT, 9_000 + 100 * i, 2,
                        oid=i + 1) for i in range(4)]
    orders.append(HostOrder(0, OP_SUBMIT, BUY, LIMIT, 9_800, 2, oid=5))
    orders.append(HostOrder(0, OP_SUBMIT, BUY, LIMIT, 9_000, 2, oid=6))
    book, oracles, (d_res, _), _ = run_both(cfg, orders)
    by_oid = {r[0]: r for r in d_res}
    assert by_oid[5][2] == 4              # REJECTED: directory full
    assert by_oid[6][2] == 0              # NEW: existing level has room
    assert_parity(cfg, orders)


def test_freed_level_row_is_reusable():
    """Canceling a level's last order frees its row for a new price."""
    cfg = EngineConfig(num_symbols=1, capacity=8, batch=4, kernel="levels",
                       levels=2)
    orders = [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_000, 2, oid=1),
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_100, 2, oid=2),
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_200, 2, oid=3),  # reject
        HostOrder(0, OP_CANCEL, SELL, oid=1),
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_200, 2, oid=4),  # rests
    ]
    assert_parity(cfg, orders)


def test_lifecycle_auction_uncross_parity():
    """Continuous -> crossing call-period rests -> uncross -> continuous,
    against the level-aware oracle (the wide-sum uncross sorts its input,
    so the levels layout needs no special casing; apply_uncross re-packs
    the FIFO rows afterwards)."""
    cfg = EngineConfig(num_symbols=4, capacity=24, batch=8, kernel="levels",
                       max_fills=1 << 12)
    rng = random.Random(3)
    oracles = levels_oracles(cfg)
    book = init_book(cfg)

    def sync(stream):
        nonlocal book
        for o in stream:
            ob = oracles[o.sym]
            if o.op == OP_CANCEL:
                ob.cancel(o.oid)
            elif o.op == OP_REST:
                ob.rest(o.oid, o.side, o.price, o.qty)
            else:
                ob.submit(o.oid, o.side, o.otype, o.price, o.qty)
        book, _, _ = apply_orders(cfg, book, stream)

    sync(random_order_stream(cfg.num_symbols, 120, seed=3))
    oid = 10_000
    rests = []
    for _ in range(60):
        oid += 1
        rests.append(HostOrder(
            rng.randrange(cfg.num_symbols), OP_REST,
            BUY if rng.random() < 0.5 else SELL, LIMIT,
            10_000 + 100 * rng.randrange(-3, 4), rng.randrange(1, 15),
            oid=oid))
    sync(rests)

    book, out = auction_step(cfg, book, np.ones((cfg.num_symbols,), bool))
    dec, fills = decode_auction(cfg, out)
    assert not dec.aborted
    want = []
    for s, ob in enumerate(oracles):
        p, q, ofills = ob.auction()
        assert p == int(dec.clear_price[s])
        assert q == int(dec.executed[s])
        want.extend((s, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
                    for f in ofills)
    got = [(f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
           for f in fills]
    assert sorted(got) == sorted(want)
    snaps = snapshot_books(book)
    for s in range(cfg.num_symbols):
        assert snaps[s] == oracles[s].snapshot(), f"post-uncross sym {s}"

    # Continuous trading again on the post-auction layout.
    stream = [
        HostOrder(o.sym, o.op, o.side, o.otype, o.price, o.qty,
                  oid=(o.oid + 20_000 if o.oid else 0))
        for o in random_order_stream(cfg.num_symbols, 120, seed=9)
    ]
    for o in stream:
        if o.op == OP_SUBMIT:
            oracles[o.sym].submit(o.oid, o.side, o.otype, o.price, o.qty)
        else:
            oracles[o.sym].cancel(o.oid)
    book, _, _ = apply_orders(cfg, book, stream)
    snaps = snapshot_books(book)
    for s in range(cfg.num_symbols):
        assert snaps[s] == oracles[s].snapshot(), f"post-continuous sym {s}"


@pytest.mark.slow
def test_venue_depth_deep_sweep():
    """Capacity 8192 ([128, 64] levels, saturating quantity sums): a
    2000-order ladder and a taker that sweeps exactly half of it."""
    cfg = EngineConfig(num_symbols=1, capacity=8192, batch=64,
                       kernel="levels", max_fills=1 << 15)
    orders = []
    oid = 0
    for i in range(2000):
        oid += 1
        orders.append(HostOrder(0, OP_SUBMIT, SELL, LIMIT,
                                10_000 + 10 * (i % 50), 5, oid=oid))
    oid += 1
    orders.append(HostOrder(0, OP_SUBMIT, BUY, LIMIT, 10_000 + 10 * 24,
                            5 * 1000, oid=oid))
    book = init_book(cfg)
    book, res, fills = apply_orders(cfg, book, orders)
    taker = [r for r in res if r.oid == oid][0]
    assert taker.filled == 5_000
    assert len(fills) == 1000
    assert sum(f.quantity for f in fills) == 5_000
