"""Online surveillance tests (matching_engine_tpu/audit/).

Layers under test:
- unit: drop-copy record mapping from storage rows, the InvariantAuditor
  state machine (every corruption class fires its kind; clean lifecycles
  fire nothing), the durable-store probe, the /auditz endpoint, and the
  oid-span accumulation on suppressed sink/hub warnings.
- fault injection (e2e): ME_AUDIT_FAULT mutates/drops exactly one record
  between decode and publish on BOTH serving paths; the auditor must fire
  the right kind within one dispatch and flight-dump the offending record
  naming the order.
- clean lifecycle fuzz (e2e): python, --native-lanes, --serve-shards 2,
  and --megadispatch-max-waves 4 servers driven with a submit/fill/amend/
  cancel mix assert ZERO violations with the auditor shadowing everything,
  and the store probes resolve clean after a sink flush.
- parity: the drop-copy record stream is bit-identical between the python
  and native paths over a lifecycle-fuzz record corpus (envelope — seq/
  epoch/trace/ingress — normalized).
- CLI: the `audit` verb's summary/exit/capture contract and the offline
  scripts/audit.py --dropcopy cross-check against the store.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import tempfile
import threading
import time

import grpc
import pytest

from matching_engine_tpu import native as me_native
from matching_engine_tpu.audit import (
    AuditPump,
    DropCopyPublisher,
    InvariantAuditor,
    dropcopy_events,
)
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.feed import FeedSequencer
from matching_engine_tpu.feed.client import SequencedSubscriber
from matching_engine_tpu.feed.sequencer import CHANNEL_AUDIT
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.server.streams import StreamHub
from matching_engine_tpu.storage.storage import FillRow
from matching_engine_tpu.utils.metrics import Metrics

CFG = EngineConfig(num_symbols=8, capacity=16, batch=4)

NEW, PARTIAL, FILLED, CANCELED, REJECTED = range(5)


# -- unit: record mapping -----------------------------------------------------


def test_dropcopy_event_mapping():
    orders = [("OID-1", "c1", "AAA", 2, 0, 10_000, 5, 5, NEW),
              ("OID-2", "c2", "AAA", 1, 1, None, 3, 0, FILLED)]
    fills = [FillRow("OID-2", "OID-1", 10_000, 3)]
    updates = [("OID-1", PARTIAL, 2), ("OID-3", NEW, 2, 2)]
    evs = dropcopy_events(orders, updates, fills, trace_id=7, shape="dense",
                          waves=2, ingress_ts_us=99)
    assert [e.audit_kind for e in evs] == [1, 1, 3, 2, 2]
    o1, o2, f1, u1, u2 = evs
    assert (o1.order_id, o1.client_id, o1.symbol) == ("OID-1", "c1", "AAA")
    assert (o1.audit_side, o1.audit_quantity, o1.remaining_quantity,
            o1.status, o1.fill_price) == (2, 5, 5, NEW, 10_000)
    assert o2.fill_price == 0  # MARKET order: NULL limit price -> 0
    assert (f1.order_id, f1.counter_order_id, f1.fill_price,
            f1.fill_quantity) == ("OID-2", "OID-1", 10_000, 3)
    assert (u1.order_id, u1.status, u1.remaining_quantity,
            u1.audit_quantity) == ("OID-1", PARTIAL, 2, 0)
    assert u2.audit_quantity == 2  # amend row carries the new quantity
    for e in evs:  # envelope rides every record
        assert (e.trace_id, e.dispatch_shape, e.dispatch_waves,
                e.ingress_ts_us) == (7, "dense", 2, 99)


# -- unit: the invariant state machine ---------------------------------------


def _ord(oid, qty, rem, status, side=2, sym="AAA", price=10_000):
    return (oid, "c", sym, side, 0, price, qty, rem, status)


def test_auditor_clean_lifecycle_no_violations():
    a = InvariantAuditor(Metrics(), sample=1)
    # D1: maker rests; D2: taker crosses 3, maker -> PARTIAL; D3: maker
    # amends down; D4: cancel remainder.
    a.observe_rows([_ord("OID-1", 5, 5, NEW)], [], [])
    a.observe_rows([_ord("OID-2", 3, 0, FILLED, side=1)],
                   [FillRow("OID-2", "OID-1", 10_000, 3)],
                   [("OID-1", PARTIAL, 2)])
    a.observe_rows([], [], [("OID-1", PARTIAL, 1, 4)])
    a.observe_rows([], [], [("OID-1", CANCELED, 0)])
    assert a.violations == 0
    assert a.snapshot()["records"] == 6


def test_auditor_fires_each_kind():
    def fresh():
        return InvariantAuditor(Metrics(), sample=1)

    a = fresh()  # conservation: fill qty disagrees with the order rows
    a.observe_rows([_ord("OID-1", 5, 5, NEW)], [], [])
    a.observe_rows([_ord("OID-2", 3, 0, FILLED, side=1)],
                   [FillRow("OID-2", "OID-1", 10_000, 4)],
                   [("OID-1", PARTIAL, 2)])
    assert a.by_kind["conservation"] > 0

    a = fresh()  # transition: FILLED -> PARTIAL is illegal
    a.observe_rows([_ord("OID-1", 5, 0, FILLED)], [], [])
    a.observe_rows([], [], [("OID-1", PARTIAL, 2)])
    assert a.by_kind["transition"] > 0

    a = fresh()  # transition: terminal-state/remaining inconsistency
    a.observe_rows([_ord("OID-1", 5, 2, FILLED)], [], [])
    assert a.by_kind["transition"] > 0

    a = fresh()  # fill_symmetry: maker already dead
    a.observe_rows([_ord("OID-1", 5, 0, CANCELED)], [], [])
    a.observe_rows([_ord("OID-2", 3, 0, FILLED, side=1)],
                   [FillRow("OID-2", "OID-1", 10_000, 3)], [])
    assert a.by_kind["fill_symmetry"] > 0

    a = fresh()  # fill_symmetry: price off the maker's limit
    a.observe_rows([_ord("OID-1", 5, 5, NEW)], [], [])
    a.observe_rows([_ord("OID-2", 3, 0, FILLED, side=1)],
                   [FillRow("OID-2", "OID-1", 10_001, 3)],
                   [("OID-1", PARTIAL, 2)])
    assert a.by_kind["fill_symmetry"] > 0

    a = fresh()  # seq_gap: a hole in the audit line
    a.observe_rows([_ord("OID-1", 5, 5, NEW)], [], [], seqs=[1])
    a.observe_rows([_ord("OID-3", 5, 5, NEW)], [], [], seqs=[3])
    assert a.by_kind["seq_gap"] > 0

    a = fresh()  # crossed_book outside a call period
    md = [pb2.MarketDataUpdate(symbol="AAA", best_bid=10_001, bid_size=1,
                               best_ask=10_000, ask_size=1)]
    a.observe_rows([], [], [], market_data=md)
    assert a.by_kind["crossed_book"] > 0
    a2 = fresh()  # ... but legal during auction accumulation
    a2.observe_rows([], [], [], market_data=md, crossed_ok=True)
    assert a2.violations == 0

    a = fresh()  # malformed: impossible rows
    a.observe_rows([_ord("OID-1", 5, 7, NEW)], [], [])
    assert a.by_kind["malformed"] > 0


def test_auditor_sampling_covers_strided_lanes_and_per_lane_floors():
    """--serve-shards lanes allocate ONE OID residue class each: the
    1-in-N subset must sample every class uniformly (a plain n % N would
    leave whole lanes with zero shadow coverage), and the pre-boot floor
    is per residue class (one global max would exempt a shallower lane's
    genuinely new ids)."""
    a = InvariantAuditor(Metrics(), sample=8)
    for stride, offset in ((2, 0), (2, 1), (4, 2)):
        tracked = sum(a._tracked_id(f"OID-{n}")
                      for n in range(offset + 1, offset + 1 + 2000 * stride,
                                     stride))
        assert 150 < tracked < 350, (stride, offset, tracked)
    b = InvariantAuditor(Metrics(), sample=1)
    b.set_oid_floors([(11, 0, 2), (5001, 1, 2)])
    assert b._tracked_id("OID-11") and not b._tracked_id("OID-9")
    assert b._tracked_id("OID-5002") and not b._tracked_id("OID-4000")


def test_auditor_auction_fills_clear_off_the_maker_price():
    """An uncross executes at the CLEARING price, which may improve on a
    maker's limit — the maker-price equality rule is continuous-matching
    law only, and an auction batch must not false-fire it (while a
    continuous fill off the maker's price still does)."""
    a = InvariantAuditor(Metrics(), sample=1)
    a.observe_rows([_ord("OID-1", 5, 5, NEW, price=10_000)], [], [])
    a.observe_rows([_ord("OID-2", 3, 3, NEW, side=1, price=10_200)], [], [])
    # Clearing at 10_100: both sides improved vs their limits.
    a.observe_rows([], [FillRow("OID-2", "OID-1", 10_100, 3)],
                   [("OID-1", PARTIAL, 2), ("OID-2", FILLED, 0)],
                   crossed_ok=True, auction=True)
    assert a.violations == 0, a.by_kind
    a.observe_rows([], [FillRow("OID-3", "OID-1", 10_150, 1)],
                   [("OID-1", PARTIAL, 1)])  # continuous: price law holds
    assert a.by_kind["fill_symmetry"] > 0


def test_auditor_store_probe_detects_divergence(tmp_path):
    import sqlite3

    db = tmp_path / "probe.db"
    conn = sqlite3.connect(db)
    conn.execute(
        "CREATE TABLE orders (order_id TEXT PRIMARY KEY, client_id TEXT,"
        " symbol TEXT, side INT, order_type INT, price INT, quantity INT,"
        " remaining_quantity INT, status INT, created_ts INT, updated_ts"
        " INT, tif INT)")
    conn.execute(
        "CREATE TABLE fills (fill_id INTEGER PRIMARY KEY, order_id TEXT,"
        " counter_order_id TEXT, price INT, quantity INT, ts INT)")
    conn.execute("INSERT INTO orders VALUES ('OID-1','c','AAA',2,0,10000,"
                 "5,0,3,0,0,0)")  # store says CANCELED rem 0
    conn.commit()
    conn.close()
    a = InvariantAuditor(Metrics(), sample=1, db_path=str(db))
    a.observe_rows([_ord("OID-1", 5, 0, FILLED)], [], [])  # feed: FILLED
    a.final_store_check()
    assert a.by_kind["store_mismatch"] > 0
    # And a clean shadow passes against a matching row.
    a2 = InvariantAuditor(Metrics(), sample=1, db_path=str(db))
    a2.observe_rows([_ord("OID-1", 5, 0, CANCELED)], [], [])
    a2.final_store_check()
    assert a2.violations == 0 and a2.store_checks == 1


def test_auditz_endpoint_turns_red():
    import urllib.error
    import urllib.request

    from matching_engine_tpu.utils.obs import ObsServer

    m = Metrics()
    a = InvariantAuditor(m, sample=1)
    obs = ObsServer(m, auditor=a, port=0)
    port = obs.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/auditz", timeout=5).read()
        doc = json.loads(body)
        assert doc["ok"] and doc["violations"] == 0
        a.observe_rows([_ord("OID-1", 5, 7, NEW)], [], [])  # malformed
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/auditz",
                                   timeout=5)
        assert ei.value.code == 500
        doc = json.loads(ei.value.read())
        assert not doc["ok"] and doc["by_kind"]["malformed"] == 1
        assert doc["recent"][0]["record"]["order_id"] == "OID-1"
        # /readyz stays green: a red audit means investigate, not drop
        # traffic.
        assert urllib.request.urlopen(
            f"http://127.0.0.1:{port}/readyz", timeout=5).status == 200
    finally:
        obs.close()


def test_warn_rate_limited_accumulates_oid_span(capsys):
    from matching_engine_tpu.utils import obs as obs_mod

    key = f"span-key-{os.getpid()}"
    obs_mod.warn_rate_limited(key, "boom", interval_s=3600,
                              oid_span=(5, 9))
    for lo, hi in ((3, 4), (11, 20)):
        obs_mod.warn_rate_limited(key, "boom", interval_s=3600,
                                  oid_span=(lo, hi))
    with obs_mod._warn_lock:
        obs_mod._warn_last[key] = 0.0
    obs_mod.warn_rate_limited(key, "boom", interval_s=3600,
                              oid_span=(6, 6))
    out = capsys.readouterr().out
    # First line prints its own span; the re-opened window's line carries
    # the suppressed count AND the span accumulated across the window.
    assert "(orders OID-5..OID-9 affected)" in out
    assert "(+2 suppressed) (orders OID-3..OID-20 affected)" in out


# -- e2e plumbing -------------------------------------------------------------


def _boot(tmp, **kw):
    kw.setdefault("native", kw.get("native_lanes", False))
    server, port, parts = build_server(
        "127.0.0.1:0", os.path.join(tmp, "audit.db"), CFG, window_ms=1,
        log=False, audit=True, audit_sample=1, **kw)
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))
    return server, parts, stub, port


def _drive(stub, rounds=6):
    """Deterministic lifecycle mix: rest, cross (partial + full fills),
    amend down, cancel — across several symbols."""
    oks = 0
    for i in range(rounds):
        sym = f"S{i % 4}"
        r1 = stub.SubmitOrder(pb2.OrderRequest(
            client_id="mk", symbol=sym, order_type=pb2.LIMIT, side=pb2.SELL,
            price=10_000 + i, scale=4, quantity=5))
        r2 = stub.SubmitOrder(pb2.OrderRequest(
            client_id="tk", symbol=sym, order_type=pb2.LIMIT, side=pb2.BUY,
            price=10_000 + i, scale=4, quantity=3))
        r3 = stub.SubmitOrder(pb2.OrderRequest(
            client_id="mk2", symbol=sym, order_type=pb2.LIMIT,
            side=pb2.SELL, price=11_000, scale=4, quantity=4))
        oks += sum(int(r.success) for r in (r1, r2, r3))
        stub.AmendOrder(pb2.AmendRequest(client_id="mk2",
                                         order_id=r3.order_id,
                                         new_quantity=2))
        stub.CancelOrder(pb2.CancelRequest(client_id="mk2",
                                           order_id=r3.order_id))
        # Consume the maker remainder so books drain (second taker).
        stub.SubmitOrder(pb2.OrderRequest(
            client_id="tk2", symbol=sym, order_type=pb2.LIMIT, side=pb2.BUY,
            price=10_000 + i, scale=4, quantity=2))
    assert oks == 3 * rounds
    return oks


def _settle(parts):
    """Quiesce: audit pump drained, sink flushed, store probes strict."""
    parts["audit_pump"].flush()
    parts["sink"].flush()
    parts["audit_pump"].flush()
    parts["auditor"].final_store_check()
    return parts["auditor"].snapshot()


# -- e2e: clean lifecycle runs assert zero violations ------------------------


@pytest.mark.parametrize("variant", ["python", "native", "shards2", "mega4"])
def test_clean_lifecycle_zero_violations(variant, tmp_path):
    if variant == "native" and not me_native.available():
        pytest.skip("native runtime not built")
    kw = {}
    if variant == "native":
        kw = dict(native_lanes=True)
    elif variant == "shards2":
        kw = dict(serve_shards=2)
    elif variant == "mega4":
        kw = dict(megadispatch_max_waves=4)
    server, parts, stub, _ = _boot(str(tmp_path), **kw)
    try:
        _drive(stub)
        snap = _settle(parts)
        assert snap["violations"] == 0, snap["by_kind"]
        assert snap["records"] > 0 and snap["dispatches"] > 0
        assert snap["store"]["pending"] == 0
        assert snap["store"]["checks"] > 0
        counters, _ = parts["metrics"].snapshot()
        assert counters["audit_records"] == snap["records"]
        assert counters["audit_violations"] == 0
    finally:
        shutdown(server, parts)
    assert parts["auditor"].violations == 0  # incl. shutdown's strict pass


# -- e2e: fault injection fires the right kind on both paths ------------------


_FAULTS = [("fill_qty", "conservation"), ("transition", "transition"),
           ("gap", "seq_gap")]


@pytest.mark.parametrize("path", ["python", "native"])
@pytest.mark.parametrize("fault,expect", _FAULTS)
def test_fault_injection_detected(path, fault, expect, tmp_path,
                                  monkeypatch):
    if path == "native" and not me_native.available():
        pytest.skip("native runtime not built")
    monkeypatch.setenv("ME_AUDIT_FAULT", fault)
    monkeypatch.setenv("ME_AUDIT_FAULT_AFTER", "1")
    flight = tmp_path / "flight"
    server, parts, stub, _ = _boot(
        str(tmp_path), native_lanes=(path == "native"),
        flight_dir=str(flight))
    try:
        _drive(stub, rounds=3)
        parts["audit_pump"].flush()
        snap = parts["auditor"].snapshot()
        assert snap["violations"] > 0
        assert expect in snap["by_kind"], snap["by_kind"]
        # The flight recorder got the violation with the record inlined
        # (naming the order), and a dump landed on disk.
        entries = [e for e in parts["recorder"].snapshot()
                   if e.get("kind") == "audit_violation"]
        assert entries and expect in {e["violation"] for e in entries}
        # The dump names the order: directly for content corruption; for
        # a dropped record via the collateral findings its absence
        # causes (the record itself is the thing that was lost).
        assert any("OID-" in e["detail"] or "OID-" in str(e.get("record"))
                   for e in entries)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not list(
                flight.glob("flight_*.json")):
            time.sleep(0.1)  # dump_on_error writes on a background thread
        dumps = list(flight.glob("flight_*.json"))
        assert dumps, "violation produced no flight dump"
        doc = json.loads(dumps[0].read_text())
        viol = [e for e in doc["entries"]
                if e.get("kind") == "audit_violation"]
        assert viol and viol[0]["violation"] == expect
    finally:
        shutdown(server, parts)


# -- e2e: the drop-copy channel serves resume like any sequenced channel ------


def test_audit_stream_resume_and_live(tmp_path):
    server, parts, stub, _ = _boot(str(tmp_path))
    try:
        feed = SequencedSubscriber(stub, CHANNEL_AUDIT)
        got: list = []
        t = threading.Thread(target=lambda: got.extend(feed))
        t.start()
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and not parts["hub"]._audit_subs):
            time.sleep(0.02)
        _drive(stub, rounds=2)
        parts["audit_pump"].flush()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(got) < 10:
            time.sleep(0.05)
        feed.cancel()
        t.join(timeout=10)
        assert got, "live audit tap saw nothing"
        assert [e.seq for e in got] == list(range(1, len(got) + 1))
        assert feed.unrecovered_events == 0
        # Resume replay: a second subscriber from seq 1 replays (1, head]
        # bit-identically from the retransmission store.
        feed2 = SequencedSubscriber(stub, CHANNEL_AUDIT, from_seq=1)
        got2: list = []

        def pull2():
            for e in feed2:
                got2.append(e)
                if len(got2) >= len(got) - 1:
                    feed2.cancel()
        t2 = threading.Thread(target=pull2)
        t2.start()
        t2.join(timeout=15)
        feed2.cancel()
        assert [e.SerializeToString() for e in got2] == \
            [e.SerializeToString() for e in got[1:]]
    finally:
        shutdown(server, parts)


# -- parity: drop-copy bit-identity python vs native --------------------------


def _norm(e) -> bytes:
    x = pb2.OrderUpdate()
    x.CopyFrom(e)
    x.seq = 0
    x.feed_epoch = 0
    x.trace_id = 0
    x.ingress_ts_us = 0
    x.dispatch_shape = ""
    x.dispatch_waves = 0
    return x.SerializeToString()


@pytest.mark.skipif(not me_native.available(),
                    reason="native runtime not built")
def test_dropcopy_parity_python_vs_native():
    import random

    from matching_engine_tpu.server.engine_runner import EngineRunner
    from matching_engine_tpu.server.native_lanes import (
        NativeLanesRunner,
        pack_record_batch,
    )
    from tests.test_native_lanes import py_drain

    cfg = EngineConfig(num_symbols=4, capacity=16, batch=8,
                       max_fills=1 << 12)

    def gen(seed):
        rng = random.Random(seed)
        tag = [0]
        targets: list[tuple[str, str]] = []
        next_oid = [1]
        batches = []
        for _ in range(6):
            recs = []
            for _ in range(rng.randrange(4, 16)):
                r = rng.random()
                if r < 0.7 or not targets:
                    sym = f"S{rng.randrange(4)}"
                    cid = f"c{rng.randrange(4)}"
                    side = 1 if rng.random() < 0.5 else 2
                    price = 10_000 + rng.randrange(-6, 7)
                    qty = rng.randrange(1, 12)
                    tag[0] += 1
                    recs.append((tag[0], 1, side, 0, price, qty, sym, cid,
                                 ""))
                    targets.append((f"OID-{next_oid[0]}", cid))
                    next_oid[0] += 1
                elif r < 0.85:
                    oid, cid = rng.choice(targets)
                    tag[0] += 1
                    recs.append((tag[0], 2, 0, 0, 0, 0, "", cid, oid))
                else:
                    oid, cid = rng.choice(targets)
                    tag[0] += 1
                    recs.append((tag[0], 3, 0, 0, 0, rng.randrange(1, 10),
                                 "", cid, oid))
            batches.append(recs)
        return batches

    def run(native: bool):
        reg = Metrics()
        hub = StreamHub(metrics=reg,
                        sequencer=FeedSequencer(metrics=reg, epoch=1))
        sub = hub.subscribe_audit()
        runner = (NativeLanesRunner(cfg, reg, hub=hub) if native
                  else EngineRunner(cfg, reg, hub=hub))
        dc = DropCopyPublisher(hub, reg, auditor=None, runner=runner)
        runner.dropcopy = dc  # auctions publish through the runner hook

        def drain(recs):
            if native:
                buf, n = pack_record_batch(recs)
                box = {}

                def cb(result, error):
                    assert error is None
                    box["r"] = result
                runner.dispatch_records(buf, n, cb)
                runner.finish_pending()
                dc.publish(box["r"], None)
            else:
                # py_drain transcribes the gateway's per-record python
                # machinery; publish its DispatchResult like a drain
                # loop.
                out = py_drain(runner, recs)
                from collections import namedtuple
                R = namedtuple("R", "storage_orders storage_updates "
                                    "storage_fills market_data")
                dc.publish(R(out["orders"], out["updates"], out["fills"],
                             []), None)

        batches = gen(3)
        for recs in batches[:4]:
            drain(recs)
        # Call period + uncross: auction executions ride the SAME
        # drop-copy line (runner.dropcopy), and must match too.
        runner.set_auction_mode(True)
        drain(batches[4])
        summary = runner.run_auction(None, sink=None)
        assert not summary["error"]
        runner.set_auction_mode(False)
        drain(batches[5])
        events = []
        while not sub.q.empty():
            events.append(sub.q.get_nowait()[1])
        return events

    py = run(False)
    nat = run(True)
    assert len(py) == len(nat) and py, "empty or mismatched record streams"
    assert [e.seq for e in py] == [e.seq for e in nat]  # same seq line
    assert [_norm(e) for e in py] == [_norm(e) for e in nat]


# -- CLI verb + offline cross-check -------------------------------------------


def test_cli_audit_verb_and_offline_crosscheck(tmp_path):
    from matching_engine_tpu.client import cli

    server, parts, stub, port = _boot(str(tmp_path))
    cap = tmp_path / "capture.jsonl"
    summ = tmp_path / "summary.json"
    rc_box: list = []
    t = threading.Thread(target=lambda: rc_box.append(cli.main(
        ["audit", f"127.0.0.1:{port}", "--idle-exit", "2", "--quiet",
         "--capture", str(cap), "--summary-json", str(summ)])))
    t.start()
    try:
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and not parts["hub"]._audit_subs):
            time.sleep(0.02)
        _drive(stub, rounds=2)
        parts["audit_pump"].flush()
        t.join(timeout=30)
        assert rc_box == [0]
        summary = json.loads(summ.read_text())
        assert summary["events"] > 0 and summary["violations"] == 0
        assert summary["unrecovered_events"] == 0
        lines = [json.loads(ln) for ln in cap.read_text().splitlines()]
        assert len(lines) == summary["events"]
        assert {ln["kind"] for ln in lines} == {"order", "update", "fill"}
    finally:
        shutdown(server, parts)
    # Offline: the capture cross-checks clean against the store, and a
    # doctored capture is caught.
    root = pathlib.Path(__file__).resolve().parents[1]
    db = os.path.join(str(tmp_path), "audit.db")
    r = subprocess.run(
        [sys.executable, str(root / "scripts" / "audit.py"), db,
         "--dropcopy", str(cap)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    doctored = tmp_path / "doctored.jsonl"
    out = []
    for ln in lines:
        if ln["kind"] == "fill" and out is not None:
            ln = dict(ln, fill_quantity=ln["fill_quantity"] + 1)
        out.append(ln)
    doctored.write_text("\n".join(json.dumps(x) for x in out))
    r = subprocess.run(
        [sys.executable, str(root / "scripts" / "audit.py"), db,
         "--dropcopy", str(doctored)], capture_output=True, text=True)
    assert r.returncode == 1
    assert "absent from" in r.stderr
