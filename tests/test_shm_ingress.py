"""Shared-memory ingress: ring unit tests, server e2e, and the
crash-safety kill-fuzz.

The kill-fuzz is the contract test for the ring's commit-word protocol
(native/me_shmring.cpp): a writer process is SIGKILLed at random points
mid-record, over and over, and the consumer side must observe

  - NO TORN admit: every admitted record is bit-exact the pure function
    of its ring sequence the writer computes (a partial write surfacing
    would corrupt the pattern);
  - NO DUPLICATED admit: ring sequences are admitted at most once;
  - NO LOST admit: every sequence the writer logged as committed (the
    log write happens strictly AFTER the commit store) is admitted.

The multi-writer fuzz is the same contract under concurrency (ring v2):
four REGISTERED writer processes publish into one ring, one is SIGKILLed
mid-record each round, and on top of the three invariants above the
survivors' committed records must keep flowing — recovery may reclaim
ONLY the victim's claims (a survivor's logged commit going missing would
mean a live claim was stolen).

The same fuzz bodies run under ASan via ME_NATIVE_LIB (slow-marked),
mirroring tests/test_build_native.py's sanitized smokes.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from matching_engine_tpu.domain import oprec

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "build_native.sh"


def _native():
    me = pytest.importorskip("matching_engine_tpu.native")
    if not me.available():
        pytest.skip("native library unavailable")
    return me


def pattern_bytes(seq: int) -> bytes:
    """The kill-fuzz wire pattern: one submit record as a pure function
    of its ring sequence. The writer subprocess carries a byte-identical
    copy (_WRITER below — import-light so it boots in ~100ms; drift
    between the two copies fails the fuzz loudly as a "torn" record)."""
    import struct

    sym = ("S%d" % (seq % 8)).encode()
    cid = (b"w%08d" % seq) * 8  # 72 bytes of seq-derived client id
    rec = bytearray(384)
    struct.pack_into("<BBBBiq", rec, 0, 1, 1 + seq % 2, 0, 0,
                     10000 + seq % 97, 1 + seq % 999)
    struct.pack_into("<HHH", rec, 16, len(sym), len(cid), 0)
    rec[24:24 + len(sym)] = sym
    rec[88:88 + len(cid)] = cid
    return bytes(rec)


def pattern_record(seq: int) -> np.ndarray:
    """pattern_bytes as a decoded record array (unit-test convenience;
    also proves the pattern is a valid codec record)."""
    arr = np.frombuffer(pattern_bytes(seq), dtype=oprec.OPREC_DTYPE).copy()
    assert oprec.record_flaws(arr) == [None]
    return arr


# -- ring unit tests ---------------------------------------------------------


def test_shm_roundtrip_inproc(tmp_path):
    """The CI smoke: create/attach, push a payload, poll it back
    bit-exact, answer positionally, read the response."""
    me = _native()
    path = str(tmp_path / "ring")
    srv = me.ShmRing(path, create=True, slots=64, resp_slots=64)
    cli = me.ShmRing(path)
    arr = oprec.pack_records([
        (1, 1, 0, 10000, 5, b"AAPL", b"alice", b""),
        (2, 0, 0, 0, 0, b"", b"bob", b"OID-7"),
    ])
    assert cli.push_payload(arr.tobytes(), 2) == 0
    body, seqs, torn = srv.poll(16, 200_000, 5_000)
    assert torn == 0 and seqs == [0, 1]
    assert body == arr.tobytes()  # bit-exact through the ring
    srv.respond([me.MeShmResp(seq=0, ok=1, kind=0, reason=0,
                              order_id=b"OID-1", oid_len=5),
                 me.MeShmResp(seq=1, ok=0, kind=1,
                              reason=oprec.REASON_REJECTED)])
    got = cli.resp_poll(8, 200_000)
    assert got == [(0, True, 0, 0, "OID-1", 0),
                   (1, False, 1, oprec.REASON_REJECTED, "", 0)]
    stats = srv.stats()
    assert stats["torn_recovered"] == 0 and stats["depth"] == 0
    srv.shutdown()
    assert cli.resp_poll(8, 100_000) is None  # shutdown drains to -2
    cli.close()
    srv.close()
    assert not os.path.exists(path)  # owner unlinks


def test_shm_backpressure_and_wrap(tmp_path):
    """A full ring refuses the push (the writer backs off, nothing is
    split); consuming frees the slots and the ring wraps cleanly."""
    me = _native()
    path = str(tmp_path / "ring")
    srv = me.ShmRing(path, create=True, slots=8, resp_slots=8)
    cli = me.ShmRing(path)
    one = pattern_record(0).tobytes()
    for lap in range(5):
        for i in range(8):
            assert cli.push_payload(one, 1) == lap * 8 + i
        assert cli.push_payload(one, 1) == -1  # full: refused whole
        body, seqs, _ = srv.poll(16, 100_000, 5_000)
        assert len(seqs) == 8
        assert body == one * 8
    cli.close()
    srv.close()


def test_shm_torn_slot_recovery(tmp_path):
    """A claimed-but-never-committed slot (the SIGKILL window) is
    recovered after the torn wait: later committed records flow, the
    recovery is counted, and the dead sequence is never admitted."""
    me = _native()
    path = str(tmp_path / "ring")
    srv = me.ShmRing(path, create=True, slots=32, resp_slots=32)
    cli = me.ShmRing(path)
    assert cli.push_payload(pattern_record(0).tobytes(), 1) == 0
    dead = cli.claim(1)  # claim, write half, never commit
    assert dead == 1
    cli.write_slot(dead, pattern_record(1).tobytes()[:100])
    assert cli.push_payload(pattern_record(2).tobytes(), 1) == 2
    body, seqs, torn = srv.poll(16, 100_000, 5_000)
    assert seqs == [0]  # committed prefix stops at the gap
    body, seqs, torn = srv.poll(16, 300_000, 10_000)
    assert seqs == [2] and torn == 1
    assert body == pattern_record(2).tobytes()
    assert srv.stats()["torn_recovered"] == 1
    cli.close()
    srv.close()


def test_shm_writer_registry(tmp_path):
    """Writer lanes: register hands out distinct non-zero ids, close
    deregisters, and a registrant that dies without deregistering stops
    counting (pid liveness probe) and its lane is reclaimable."""
    me = _native()
    path = str(tmp_path / "ring")
    srv = me.ShmRing(path, create=True, slots=64, resp_slots=64)
    a = me.ShmRing(path)
    b = me.ShmRing(path)
    assert srv.writer_id == 0  # never registered: anonymous lane
    wa, wb = a.register_writer(), b.register_writer()
    assert wa > 0 and wb > 0 and wa != wb
    assert a.writer_id == wa and a.register_writer() == wa  # idempotent
    assert srv.writer_count() == 2
    a.close()  # clean deregister
    assert srv.writer_count() == 1
    # A registrant that is killed without deregistering: its pid probes
    # dead, so the gauge drops and a later register() reaps the entry.
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(REPO) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, "-c",
         "import sys, os\n"
         "from matching_engine_tpu import native as me\n"
         "r = me.ShmRing(sys.argv[1])\n"
         "print(r.register_writer(), flush=True)\n"
         "os._exit(0)\n",  # no close(): dies registered
         path],
        env=env, cwd=str(REPO), capture_output=True, text=True,
        timeout=60)
    assert out.returncode == 0, out.stderr[-2000:]
    dead_wid = int(out.stdout.split()[0])
    assert dead_wid > 0
    assert srv.writer_count() == 1  # dead registrant not counted
    c = me.ShmRing(path)
    assert c.register_writer() > 0  # reap path leaves lanes available
    assert srv.writer_count() == 2
    b.close()
    c.close()
    srv.close()


def test_shm_writer_demux_inproc(tmp_path):
    """Per-writer response demux at the ring level: commit stamps each
    record with its writer lane, and respond routes each response onto
    that writer's private sub-ring — every client reads exactly its own
    acks, in its own lane, nothing else's."""
    me = _native()
    path = str(tmp_path / "ring")
    srv = me.ShmRing(path, create=True, slots=64, resp_slots=64)
    clis = [me.ShmRing(path) for _ in range(3)]
    wids = [c.register_writer() for c in clis]
    assert len(set(wids)) == 3 and all(w > 0 for w in wids)
    sent: dict[int, list[int]] = {w: [] for w in wids}
    one = pattern_record(0).tobytes()
    for _ in range(4):  # interleave pushes across writers
        for c, w in zip(clis, wids):
            s = c.push_payload(one, 1)
            assert s >= 0
            sent[w].append(s)
    body, seqs, torn = srv.poll(64, 200_000, 5_000)
    assert torn == 0 and len(seqs) == 12
    arr = np.frombuffer(body, dtype=oprec.OPREC_DTYPE)
    # Commit stamped the committing handle's lane into every record.
    stamped = dict(zip(seqs, (int(w) for w in arr["writer"])))
    for w, ss in sent.items():
        assert all(stamped[s] == w for s in ss)
    resp = np.zeros(len(seqs), dtype=oprec.SHM_RESP_DTYPE)
    resp["seq"] = seqs
    resp["ok"] = 1
    resp["writer"] = arr["writer"].astype(np.uint8)
    srv.respond_payload(resp.tobytes(), len(seqs))
    for c, w in zip(clis, wids):
        got: list = []
        deadline = time.time() + 10.0
        while len(got) < 4 and time.time() < deadline:
            got.extend(c.resp_poll(16, 100_000) or [])
        assert sorted(g[0] for g in got) == sorted(sent[w])
        # The lane is drained: nothing of anyone else's arrives later.
        assert not c.resp_poll(16, 10_000)
    for c in clis:
        c.close()
    srv.close()


def test_shm_attach_refuses_garbage(tmp_path):
    me = _native()
    bad = tmp_path / "not-a-ring"
    bad.write_bytes(b"\x00" * 8192)
    with pytest.raises(RuntimeError):
        me.ShmRing(str(bad))
    with pytest.raises(RuntimeError):
        me.ShmRing(str(tmp_path / "absent"))
    # Caps must be powers of two.
    with pytest.raises(RuntimeError):
        me.ShmRing(str(tmp_path / "r2"), create=True, slots=100)


# -- the kill-fuzz -----------------------------------------------------------

_WRITER = r"""
import random, struct, sys, time
from matching_engine_tpu import native as me  # ctypes only, no numpy

path, log_path, ready_path, seed = (sys.argv[1], sys.argv[2], sys.argv[3],
                                    int(sys.argv[4]))

def pattern_bytes(seq):  # byte-identical twin of the test module's copy
    sym = ("S%d" % (seq % 8)).encode()
    cid = (b"w%08d" % seq) * 8
    rec = bytearray(384)
    struct.pack_into("<BBBBiq", rec, 0, 1, 1 + seq % 2, 0, 0,
                     10000 + seq % 97, 1 + seq % 999)
    struct.pack_into("<HHH", rec, 16, len(sym), len(cid), 0)
    rec[24:24 + len(sym)] = sym
    rec[88:88 + len(cid)] = cid
    return bytes(rec)

rng = random.Random(seed)
ring = me.ShmRing(path)
log = open(log_path, "a", buffering=1)
open(ready_path, "w").write("up")
while True:
    seq = ring.claim(1)
    if seq == -2:
        break
    if seq < 0:
        time.sleep(0.0002)
        continue
    rec = pattern_bytes(seq)
    # Split write so SIGKILL can land mid-record; occasionally dawdle
    # between the halves and before the commit to widen the window.
    ring.write_slot(seq, rec[:192])
    if rng.random() < 0.3:
        time.sleep(rng.random() * 0.002)
    ring.write_slot(seq, rec)
    if rng.random() < 0.3:
        time.sleep(rng.random() * 0.002)
    ring.commit(seq)
    # Logged strictly AFTER the commit store: the log understates
    # commits (a kill between commit and log is legal), never overstates.
    log.write("%d\n" % seq)
    ring.wake()
"""


def run_kill_fuzz(tmp_path: Path, rounds: int, torn_wait_us: int = 20_000):
    """The fuzz body (also driven under ASan via __main__): SIGKILL a
    writer subprocess mid-record `rounds` times, polling throughout;
    returns (admitted dict seq->bytes, logged committed seqs, torn)."""
    from matching_engine_tpu import native as me

    path = str(tmp_path / "ring")
    log_path = str(tmp_path / "committed.log")
    srv = me.ShmRing(path, create=True, slots=256, resp_slots=256)
    admitted: dict[int, bytes] = {}
    torn_total = 0

    def drain(wait_us=1_000):
        nonlocal torn_total
        body, seqs, torn = srv.poll(256, wait_us, torn_wait_us)
        torn_total += torn
        if body:
            for j, s in enumerate(seqs):
                assert s not in admitted, f"DUPLICATED admit of seq {s}"
                admitted[s] = body[j * 384:(j + 1) * 384]

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(REPO) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    for r in range(rounds):
        ready = tmp_path / f"ready.{r}"
        w = subprocess.Popen([sys.executable, "-c", _WRITER, path,
                              log_path, str(ready), str(r)], env=env,
                             cwd=str(REPO),
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        # Wait for the writer to attach, let it run a moment, then kill
        # mid-flight. The writer sleeps inside the claim->commit window
        # 60% of the time, so kills land there often.
        t0 = time.perf_counter()
        while not ready.exists() and time.perf_counter() - t0 < 10.0:
            drain()
        deadline = time.perf_counter() + 0.01 + (r % 7) * 0.005
        while time.perf_counter() < deadline:
            drain()
        os.kill(w.pid, signal.SIGKILL)
        w.wait()
        # Post-kill: recover any torn slot and drain the tail.
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 2.0:
            before = (len(admitted), torn_total)
            drain(wait_us=30_000)
            depth = srv.stats()["depth"]
            if depth == 0 and (len(admitted), torn_total) == before:
                break
    # Final drain until the ring is empty.
    t0 = time.perf_counter()
    while srv.stats()["depth"] > 0 and time.perf_counter() - t0 < 10.0:
        drain(wait_us=50_000)
    logged = [int(x) for x in
              Path(log_path).read_text().split()] if \
        Path(log_path).exists() else []
    srv.shutdown()
    srv.close()
    return admitted, logged, torn_total


def check_kill_fuzz(admitted, logged, torn):
    # No lost admit: everything logged-committed was admitted.
    missing = [s for s in logged if s not in admitted]
    assert not missing, f"LOST admitted records: {missing[:10]}"
    # No torn admit: every admitted record is bit-exact its pattern.
    for s, rec in admitted.items():
        assert rec == pattern_bytes(s), f"TORN record at seq {s}"
    # The log may understate (kill between commit and log) but a healthy
    # run admits at least everything logged; duplicates were asserted
    # inline. Torn recoveries are expected (> 0 proves the fuzz bit).
    assert len(admitted) >= len(logged)


def test_shm_kill_fuzz_quick(tmp_path):
    """10 mid-write SIGKILLs (the tier-1 version; the 100x contract run
    is the slow-marked test below)."""
    _native()
    admitted, logged, torn = run_kill_fuzz(tmp_path, rounds=10)
    check_kill_fuzz(admitted, logged, torn)
    assert len(admitted) > 0


@pytest.mark.slow
def test_shm_kill_fuzz_100(tmp_path):
    """The acceptance-criteria run: 100 mid-write client kills, no
    torn/lost/duplicated admitted record."""
    _native()
    admitted, logged, torn = run_kill_fuzz(tmp_path, rounds=100)
    check_kill_fuzz(admitted, logged, torn)
    assert len(admitted) > 0
    # Across 100 kills with 60% in-window dawdles, some kills must have
    # landed between claim and commit — the recovery path genuinely ran.
    assert torn > 0


# -- the multi-writer kill-fuzz ----------------------------------------------

_MW_WRITER = r"""
import os, random, struct, sys, time
from matching_engine_tpu import native as me  # ctypes only, no numpy

path, log_path, ready_path, stop_path, seed = (
    sys.argv[1], sys.argv[2], sys.argv[3], sys.argv[4], int(sys.argv[5]))

def pattern_bytes(seq):  # byte-identical twin of the test module's copy
    sym = ("S%d" % (seq % 8)).encode()
    cid = (b"w%08d" % seq) * 8
    rec = bytearray(384)
    struct.pack_into("<BBBBiq", rec, 0, 1, 1 + seq % 2, 0, 0,
                     10000 + seq % 97, 1 + seq % 999)
    struct.pack_into("<HHH", rec, 16, len(sym), len(cid), 0)
    rec[24:24 + len(sym)] = sym
    rec[88:88 + len(cid)] = cid
    return bytes(rec)

rng = random.Random(seed)
ring = me.ShmRing(path)
wid = ring.register_writer()
log = open(log_path, "a", buffering=1)
open(ready_path, "w").write(str(wid))
# The stop file is the GRACEFUL exit: survivors must never die
# mid-record, so only the fuzz's SIGKILL leaves torn claims — that is
# what lets the checker attribute every recovery to the victim.
while not os.path.exists(stop_path):
    seq = ring.claim(1)
    if seq == -2:
        break
    if seq < 0:
        time.sleep(0.0002)
        continue
    rec = pattern_bytes(seq)
    ring.write_slot(seq, rec[:192])
    if rng.random() < 0.25:
        time.sleep(rng.random() * 0.002)
    ring.write_slot(seq, rec)
    if rng.random() < 0.25:
        time.sleep(rng.random() * 0.002)
    ring.commit(seq)
    # Logged strictly AFTER the commit store: understates, never
    # overstates.
    log.write("%d\n" % seq)
    ring.wake()
ring.close()
"""


def run_mw_kill_fuzz(tmp_path: Path, rounds: int,
                     writers: int = 4, torn_wait_us: int = 20_000):
    """Four registered writers publish into one ring; each round one is
    SIGKILLed mid-record while the other three keep going and then exit
    gracefully. Returns (admitted seq->bytes, logged seqs, torn)."""
    from matching_engine_tpu import native as me

    path = str(tmp_path / "ring")
    srv = me.ShmRing(path, create=True, slots=256, resp_slots=256)
    admitted: dict[int, bytes] = {}
    logged: list[int] = []
    torn_total = 0

    def drain(wait_us=1_000):
        nonlocal torn_total
        body, seqs, torn = srv.poll(256, wait_us, torn_wait_us)
        torn_total += torn
        if body:
            for j, s in enumerate(seqs):
                assert s not in admitted, f"DUPLICATED admit of seq {s}"
                admitted[s] = body[j * 384:(j + 1) * 384]

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(REPO) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    for r in range(rounds):
        stop = tmp_path / f"stop.{r}"
        procs = []
        logs = []
        for i in range(writers):
            ready = tmp_path / f"ready.{r}.{i}"
            log_path = tmp_path / f"committed.{r}.{i}.log"
            logs.append(log_path)
            procs.append((subprocess.Popen(
                [sys.executable, "-c", _MW_WRITER, path, str(log_path),
                 str(ready), str(stop), str(r * writers + i)], env=env,
                cwd=str(REPO), stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL), ready))
        t0 = time.perf_counter()
        while (not all(rd.exists() for _, rd in procs)
               and time.perf_counter() - t0 < 20.0):
            drain()
        # Let all four publish concurrently for a while, then kill one
        # mid-flight (the in-window dawdles make that likely).
        deadline = time.perf_counter() + 0.02 + (r % 5) * 0.005
        while time.perf_counter() < deadline:
            drain()
        victim = procs[r % writers][0]
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait()  # REAP: a zombie pid still probes alive
        # Survivors: a little more concurrent traffic over the victim's
        # torn claims, then a graceful stop.
        deadline = time.perf_counter() + 0.02
        while time.perf_counter() < deadline:
            drain()
        stop.write_text("stop")
        for i, (p, _rd) in enumerate(procs):
            if i != r % writers:
                p.wait(timeout=30)
        # Post-round: recover the victim's claims and drain the tail.
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 3.0:
            before = (len(admitted), torn_total)
            drain(wait_us=30_000)
            if (srv.stats()["depth"] == 0
                    and (len(admitted), torn_total) == before):
                break
        for lp in logs:
            if lp.exists():
                logged.extend(int(x) for x in lp.read_text().split())
    srv.shutdown()
    srv.close()
    return admitted, logged, torn_total


def check_mw_kill_fuzz(admitted, logged, torn):
    import struct

    # No lost admit from ANY writer — survivor or victim: a logged
    # commit that vanished would mean recovery reclaimed a live (or
    # already-committed) claim, not just the victim's torn ones.
    missing = [s for s in logged if s not in admitted]
    assert not missing, f"LOST admitted records: {missing[:10]}"
    assert len(set(logged)) == len(logged)  # seqs claimed exactly once
    # Bit-exact modulo the writer stamp: commit writes the committing
    # lane id into the record's `writer` u16 at offset 22.
    for s, rec in admitted.items():
        w = rec[22] | (rec[23] << 8)
        assert 0 < w < 16, f"unstamped writer {w} at seq {s}"
        exp = bytearray(pattern_bytes(s))
        struct.pack_into("<H", exp, 22, w)
        assert rec == bytes(exp), f"TORN record at seq {s}"
    assert len(admitted) >= len(logged)


def test_shm_mw_kill_fuzz_quick(tmp_path):
    """4 concurrent registered writers, 5 rounds of kill-one (the tier-1
    version; the 100x contract run is the slow-marked test below)."""
    _native()
    admitted, logged, torn = run_mw_kill_fuzz(tmp_path, rounds=5)
    check_mw_kill_fuzz(admitted, logged, torn)
    assert len(admitted) > 0


@pytest.mark.slow
def test_shm_mw_kill_fuzz_100(tmp_path):
    """The acceptance-criteria run: 100 rounds of one SIGKILL among four
    live writers; zero lost/duplicated records from survivors and
    recovery only of the victim's claims."""
    _native()
    admitted, logged, torn = run_mw_kill_fuzz(tmp_path, rounds=100)
    check_mw_kill_fuzz(admitted, logged, torn)
    assert len(admitted) > 0
    # Across 100 kills with in-window dawdles, some landed between claim
    # and commit — the attributed (writer, gen) recovery path really ran.
    assert torn > 0


def _san_runtime(name: str) -> str | None:
    try:
        out = subprocess.run(["g++", f"-print-file-name={name}"],
                             capture_output=True, text=True, timeout=30)
    except OSError:
        return None
    p = out.stdout.strip()
    return p if p and Path(p).exists() and "/" in p else None


@pytest.mark.slow
def test_shm_kill_fuzz_asan(tmp_path):
    """The same fuzz with the ring library built under ASan (memory
    errors in the torn-recovery / wraparound paths abort the run)."""
    _native()
    rt = _san_runtime("libasan.so")
    if rt is None:
        pytest.skip("no libasan runtime in this toolchain")
    r = subprocess.run(
        ["bash", str(SCRIPT), "--sanitize=address",
         "--out-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    so = tmp_path / "libme_native.asan.so"
    env = dict(os.environ, LD_PRELOAD=rt, ME_NATIVE_LIB=str(so),
               ASAN_OPTIONS="detect_leaks=0", JAX_PLATFORMS="cpu",
               PYTHONPATH=str(REPO) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    run = subprocess.run(
        [sys.executable, str(Path(__file__).resolve()), "20", "5"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=str(REPO))
    assert run.returncode == 0, (
        f"asan kill-fuzz failed:\n{run.stdout[-1000:]}\n"
        f"{run.stderr[-3000:]}")
    assert "kill-fuzz OK" in run.stdout
    assert "mw kill-fuzz OK" in run.stdout


# -- server e2e --------------------------------------------------------------


def _boot(tmp_path, **kw):
    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.server.main import build_server

    cfg = EngineConfig(num_symbols=8, capacity=32, batch=4)
    server, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "db.sqlite"), cfg, log=False,
        shm_ingress_path=str(tmp_path / "ingress.ring"), **kw)
    server.start()
    return server, port, parts


def _push_and_collect(me, tmp_path, arr, n_expect, timeout_s=15.0):
    cli = me.ShmRing(str(tmp_path / "ingress.ring"))
    base = cli.push_payload(arr.tobytes(), len(arr))
    assert base >= 0
    resps = []
    deadline = time.time() + timeout_s
    while len(resps) < n_expect and time.time() < deadline:
        got = cli.resp_poll(256, 200_000)
        resps.extend(got or [])
    cli.close()
    assert len(resps) == n_expect, resps
    return {r[0] - base: r for r in resps}


def test_shm_e2e_lifecycle_and_store(tmp_path):
    """Full server: submits, a resting cancel, an amend and a screened
    reject through the shm ring; positional responses and the durable
    store agree with the same flow's semantics."""
    me = _native()
    from matching_engine_tpu.server.admission import AdmissionConfig
    from matching_engine_tpu.server.main import shutdown

    server, _port, parts = _boot(
        tmp_path, admission_cfg=AdmissionConfig(max_quantity=100))
    try:
        arr = oprec.pack_records([
            (1, 1, 0, 10000, 5, b"S0", b"alice", b""),   # rests
            (1, 2, 0, 10100, 7, b"S1", b"bob", b""),     # rests
            (1, 1, 0, 10000, 500, b"S2", b"carol", b""),  # qty screen
        ])
        by = _push_and_collect(me, tmp_path, arr, 3)
        assert by[0][1] and by[0][4].startswith("OID-")
        assert by[1][1]
        assert not by[2][1] and by[2][3] == oprec.REASON_QTY
        oid_a, oid_b = by[0][4], by[1][4]
        # Second wave: cancel alice's order (by the id the server just
        # assigned), amend bob's down, and a bogus cancel.
        arr2 = oprec.pack_records([
            (2, 0, 0, 0, 0, b"", b"alice", oid_a.encode()),
            (3, 0, 0, 0, 3, b"", b"bob", oid_b.encode()),
            (2, 0, 0, 0, 0, b"", b"mallory", oid_b.encode()),
        ])
        by2 = _push_and_collect(me, tmp_path, arr2, 3)
        assert by2[0][1] and by2[0][2] == 1          # canceled
        assert by2[1][1] and by2[1][2] == 2 and by2[1][5] == 3  # amended
        assert not by2[2][1] and by2[2][3] == oprec.REASON_REJECTED
        # Store: exactly the two admitted orders, alice's CANCELED.
        st = parts["storage"]
        assert st.count("orders") == 2
        counters, _gauges = parts["metrics"].snapshot()
        assert counters["ingress_records"] == 6
        assert counters["ingress_rejects"] == 2
        assert counters["admission_qty_rejects"] == 1
    finally:
        shutdown(server, parts)
    assert not os.path.exists(tmp_path / "ingress.ring")


def test_shm_e2e_writer_demux(tmp_path):
    """The acceptance pin for per-writer demux through a REAL server:
    three registered clients push interleaved submits into one segment
    and each client's response lane carries exactly its own positional
    acks; the poller's per-writer series and the writers gauge agree."""
    me = _native()
    from matching_engine_tpu.server.main import shutdown

    server, _port, parts = _boot(tmp_path)
    clis = []
    try:
        seg = str(tmp_path / "ingress.ring")
        clis = [me.ShmRing(seg) for _ in range(3)]
        wids = [c.register_writer() for c in clis]
        assert len(set(wids)) == 3 and all(w > 0 for w in wids)
        sent: dict[int, list[int]] = {}
        for k, (c, w) in enumerate(zip(clis, wids)):
            rows = [(1, 1 + i % 2, 0, 10000 + 100 * i, 1 + i,
                     f"S{k}".encode(), b"cli-%d" % w, b"")
                    for i in range(5)]
            base = c.push_payload(oprec.pack_records(rows).tobytes(), 5)
            assert base >= 0
            sent[w] = list(range(base, base + 5))
        for c, w in zip(clis, wids):
            got: list = []
            deadline = time.time() + 15.0
            while len(got) < 5 and time.time() < deadline:
                got.extend(c.resp_poll(64, 200_000) or [])
            assert sorted(g[0] for g in got) == sent[w], (w, got)
            assert all(g[1] for g in got)  # every submit accepted
            assert not c.resp_poll(64, 10_000)  # nothing extra arrives
        # Per-writer observability: one series per publishing lane plus
        # the live-writers gauge (clients still attached here).
        counters, gauges = parts["metrics"].snapshot()
        assert counters["ingress_records"] == 15
        for w in wids:
            assert counters[f"ingress_writer{w}_records"] == 5
        assert gauges["ingress_writers"] == 3
        assert parts["storage"].count("orders") == 15
    finally:
        for c in clis:
            c.close()
        shutdown(server, parts)


@pytest.mark.parametrize("mode", ["shards", "native"])
def test_shm_e2e_routed_paths(tmp_path, mode):
    """The poller rides the same lane routing as the batch RPCs: K=2
    partitioned lanes and the C++ lane engine both serve the ring."""
    me = _native()
    from matching_engine_tpu.server.main import shutdown

    kw = {"serve_shards": 2} if mode == "shards" else {"native_lanes": True}
    server, _port, parts = _boot(tmp_path, **kw)
    try:
        rows = [(1, 1 + i % 2, 0, 10000 + 100 * (i % 3), 1 + i,
                 f"S{i % 6}".encode(), b"cli-%d" % (i % 3), b"")
                for i in range(24)]
        arr = oprec.pack_records(rows)
        by = _push_and_collect(me, tmp_path, arr, 24)
        assert all(by[i][1] for i in range(24)), by
        oids = [by[i][4] for i in range(24)]
        assert len(set(oids)) == 24
        # Every admitted submit landed in the store exactly once.
        st = parts["storage"]
        assert st.count("orders") == 24
    finally:
        shutdown(server, parts)


if __name__ == "__main__":
    # ASan driver: run the kill-fuzz bodies directly (the sanitized .so
    # is selected by ME_NATIVE_LIB in the environment).
    import tempfile

    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    mw_rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    with tempfile.TemporaryDirectory() as td:
        admitted, logged, torn = run_kill_fuzz(Path(td), rounds=rounds)
        check_kill_fuzz(admitted, logged, torn)
    print(f"kill-fuzz OK ({rounds} kills, {len(admitted)} admitted, "
          f"{torn} torn recoveries)")
    if mw_rounds:
        with tempfile.TemporaryDirectory() as td:
            admitted, logged, torn = run_mw_kill_fuzz(
                Path(td), rounds=mw_rounds)
            check_mw_kill_fuzz(admitted, logged, torn)
        print(f"mw kill-fuzz OK ({mw_rounds} rounds, {len(admitted)} "
              f"admitted, {torn} torn recoveries)")
