"""Cross-dispatch pipelining semantics (engine_runner.dispatch_pipelined).

The serving loops overlap consecutive dispatches: a new batch's device
waves are issued before the previous batch decodes. These tests pin the
contract: strict FIFO finish order, identical outcomes to the serial
schedule, completion via every finisher (next dispatch, idle wakeup,
checkpoint quiesce, shutdown), and directory consistency while a
dispatch is pending.
"""

import threading
import time

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.kernel import FILLED, NEW, OP_SUBMIT
from matching_engine_tpu.server.dispatcher import BatchDispatcher
from matching_engine_tpu.server.engine_runner import (
    EngineOp,
    EngineRunner,
    OrderInfo,
)

CFG = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=256)


def _submit(runner, symbol, side, price, qty):
    assert runner.slot_acquire(symbol) is not None
    num, oid = runner.assign_oid()
    return EngineOp(OP_SUBMIT, OrderInfo(
        oid=num, order_id=oid, client_id=f"c-side{side}", symbol=symbol,
        side=side,
        otype=0, price_q4=price, quantity=qty, remaining=qty, status=0,
        handle=runner.assign_handle()))


def _collector(log, label):
    def on_finish(result, error):
        assert error is None, error
        def post():
            log.append((label, [(o.op.info.order_id, o.status)
                                for o in result.outcomes]))
        return post
    return on_finish


def test_fifo_finish_order_and_outcomes():
    """Batch A stays pending while B is staged; finish order is A then B,
    and the cross-batch match (B's SELL hits A's resting BUY) decodes with
    the same outcomes as the serial schedule."""
    r = EngineRunner(CFG)
    log: list = []
    a = _submit(r, "X", 1, 100, 5)
    r.dispatch_pipelined([a], _collector(log, "A"))
    assert r.has_pending
    # A is already visible in the directories while pending (book lanes
    # are applied on device; a snapshot must be able to join them).
    assert a.info.order_id in r.orders_by_id
    b = _submit(r, "X", 2, 100, 5)
    r.dispatch_pipelined([b], _collector(log, "B"))
    assert r.has_pending          # now B is the pending one
    r.finish_pending()
    assert not r.has_pending
    assert [entry[0] for entry in log] == ["A", "B"]
    assert log[0][1] == [(a.info.order_id, NEW)]
    assert log[1][1] == [(b.info.order_id, FILLED)]
    assert a.info.remaining == 0 and a.info.status == FILLED


def test_checkpoint_style_quiesce_finishes_pending():
    """The checkpoint quiesce pattern (finish pending under the dispatch
    lock, run completions after) publishes the staged batch."""
    r = EngineRunner(CFG)
    log: list = []
    r.dispatch_pipelined([_submit(r, "Q", 1, 50, 1)], _collector(log, "A"))
    assert r.has_pending
    posts: list = []
    with r._dispatch_lock:
        r._finish_pending_locked(posts)
    for p in posts:
        p()
    assert not r.has_pending and [entry[0] for entry in log] == ["A"]


def test_lone_submit_completes_via_idle_wakeup():
    """With no follow-up traffic, the drain loop's idle wakeup finishes the
    pending dispatch — a lone client must never hang on its future."""
    r = EngineRunner(CFG)
    d = BatchDispatcher(r, window_ms=5.0)
    try:
        fut = d.submit(_submit(r, "Z", 1, 10, 1))
        outcome = fut.result(timeout=10)
        assert outcome.status == NEW
    finally:
        d.close()
    assert not r.has_pending


def test_concurrent_edges_share_one_pending():
    """Two drain threads (the dual-edge shape) interleave pipelined
    dispatches against one runner; every dispatch's completion runs
    exactly once and nothing is left pending."""
    r = EngineRunner(CFG)
    done: list = []
    lock = threading.Lock()

    def on_finish(result, error):
        assert error is None, error
        def post():
            with lock:
                done.extend(o.op.info.order_id for o in result.outcomes)
        return post

    def edge(label, n):
        for i in range(n):
            r.dispatch_pipelined(
                [_submit(r, f"S{label}", 1, 100 + i, 1)], on_finish)
        r.finish_pending()

    threads = [threading.Thread(target=edge, args=(t, 20)) for t in "ab"]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    r.finish_pending()
    time.sleep(0.05)
    assert not r.has_pending
    assert len(done) == 40 and len(set(done)) == 40


def test_book_snapshot_sees_pending_orders():
    """A resting order whose dispatch is still pending appears in the
    book snapshot (eager directory registration + device lanes applied)."""
    r = EngineRunner(CFG)
    op = _submit(r, "SNAP", 1, 77, 3)
    r.dispatch_pipelined([op], lambda result, error: None)
    assert r.has_pending
    bids, asks = r.book_snapshot("SNAP")
    assert len(bids) == 1 and len(asks) == 0
    info, qty = bids[0]
    assert info.order_id == op.info.order_id and qty == 3
    r.finish_pending()


def test_inflight_window_depth():
    """pipeline_inflight=2 (default): two dispatches stay staged; the
    third's stage finishes only the OLDEST (FIFO), not both."""
    r = EngineRunner(CFG)
    log: list = []
    for label in "ABC":
        r.dispatch_pipelined(
            [_submit(r, f"W{label}", 1, 100, 1)], _collector(log, label))
    # A finished when C was staged (window is 2 deep); B and C pending.
    assert [entry[0] for entry in log] == ["A"]
    assert len(r._pending) == 2
    r.finish_pending()
    assert [entry[0] for entry in log] == ["A", "B", "C"]
    assert not r.has_pending


def test_inflight_one_matches_old_single_slot():
    """pipeline_inflight=1 reproduces the round-3 behavior: each dispatch
    finishes the previous one."""
    r = EngineRunner(CFG, pipeline_inflight=1)
    log: list = []
    r.dispatch_pipelined([_submit(r, "P", 1, 10, 1)], _collector(log, "A"))
    assert log == [] and len(r._pending) == 1
    r.dispatch_pipelined([_submit(r, "P", 1, 11, 1)], _collector(log, "B"))
    assert [entry[0] for entry in log] == ["A"] and len(r._pending) == 1
    r.finish_pending()
    assert [entry[0] for entry in log] == ["A", "B"]


def test_deep_window_cross_batch_match_stays_serial():
    """Orders split across three staged-at-once dispatches still match as
    the serial schedule would (device waves chain on the donated book even
    though none has decoded)."""
    r = EngineRunner(EngineConfig(num_symbols=4, capacity=16, batch=4,
                                  max_fills=256), pipeline_inflight=4)
    log: list = []
    a = _submit(r, "D", 1, 100, 5)   # resting BUY
    b = _submit(r, "D", 2, 100, 3)   # SELL hits it
    c = _submit(r, "D", 2, 100, 2)   # SELL finishes it
    for op, label in ((a, "A"), (b, "B"), (c, "C")):
        r.dispatch_pipelined([op], _collector(log, label))
    assert log == []                 # all three staged
    r.finish_pending()
    assert [entry[0] for entry in log] == ["A", "B", "C"]
    assert log[1][1] == [(b.info.order_id, FILLED)]
    assert log[2][1] == [(c.info.order_id, FILLED)]
    assert a.info.status == FILLED and a.info.remaining == 0


def test_mesh_deferral_fifo_and_outcomes():
    """Cross-dispatch deferral on a sharded runner (8-device virtual
    mesh): FIFO finish, cross-batch match outcomes identical to serial —
    the mesh decode reads addressable shards, so deferral is as safe as
    single-device."""
    from matching_engine_tpu.parallel import make_mesh

    cfg = EngineConfig(num_symbols=8, capacity=16, batch=4, max_fills=256)
    r = EngineRunner(cfg, mesh=make_mesh(8))
    log: list = []
    a = _submit(r, "MX", 1, 100, 5)
    r.dispatch_pipelined([a], _collector(log, "A"))
    assert r.has_pending            # mesh dispatches DO defer now
    assert a.info.order_id in r.orders_by_id
    b = _submit(r, "MX", 2, 100, 5)
    r.dispatch_pipelined([b], _collector(log, "B"))
    r.finish_pending()
    assert not r.has_pending
    assert [entry[0] for entry in log] == ["A", "B"]
    assert log[0][1] == [(a.info.order_id, NEW)]
    assert log[1][1] == [(b.info.order_id, FILLED)]
    assert a.info.remaining == 0 and a.info.status == FILLED
