"""Seq rebasing (engine/maintenance.py): the int32 arrival-counter cliff.

Priority ties break on the per-book seq; after 2^31 arrivals on one
symbol the counter would wrap and new orders would silently jump the
time-priority queue. `rebase_seqs` renumbers live seqs to dense priority
ranks at a quiesce point — these tests pin that the renumbering is
SEMANTICS-PRESERVING (identical matching behavior after), kernel-safe
(the sorted invariant survives), mesh-safe, and wired into the runner.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from matching_engine_tpu.engine.book import BookBatch, EngineConfig, init_book
from matching_engine_tpu.engine.harness import (
    HostOrder,
    apply_orders,
    snapshot_books,
)
from matching_engine_tpu.engine.kernel import OP_SUBMIT
from matching_engine_tpu.engine.maintenance import (
    REBASE_THRESHOLD,
    rebase_seqs,
)
from matching_engine_tpu.proto import BUY, LIMIT, SELL

CFG = EngineConfig(num_symbols=2, capacity=16, batch=4, max_fills=1 << 10)


def _aged_book(cfg, base_seq=REBASE_THRESHOLD):
    """Books whose live seqs sit near the cliff, lanes NOT in priority
    order (the matrix kernel's hole-tolerant layout)."""
    s, c = cfg.num_symbols, cfg.capacity
    arr = {f: np.zeros((s, c), dtype=np.int32)
           for f in BookBatch._fields if f != "next_seq"}
    rng = np.random.default_rng(5)
    for i in range(s):
        for k in range(6):
            arr["bid_price"][i, k] = 10_000 - int(rng.integers(0, 3))
            arr["bid_qty"][i, k] = int(rng.integers(1, 9))
            arr["bid_oid"][i, k] = 100 + i * 20 + k
            arr["bid_seq"][i, k] = base_seq + k * 1000 + int(rng.integers(0, 999))
            arr["ask_price"][i, k] = 10_005 + int(rng.integers(0, 3))
            arr["ask_qty"][i, k] = int(rng.integers(1, 9))
            arr["ask_oid"][i, k] = 200 + i * 20 + k
            arr["ask_seq"][i, k] = base_seq + k * 1000 + int(rng.integers(0, 999))
    next_seq = np.full((s,), base_seq + 5000, np.int32)
    return BookBatch(**{k: jnp.asarray(v) for k, v in arr.items()},
                     next_seq=jnp.asarray(next_seq))


def _priority_view(snaps):
    """Snapshots with seq values erased (they legitimately change)."""
    return [([r[:3] for r in bids], [r[:3] for r in asks])
            for bids, asks in snaps]


@pytest.mark.parametrize("kernel", ["matrix", "sorted"])
def test_rebase_preserves_priority_and_matching(kernel):
    cfg = dataclasses.replace(CFG, kernel=kernel)
    before = _aged_book(cfg)
    control = _aged_book(cfg)  # identical twin, NOT rebased
    pre = _priority_view(snapshot_books(before))

    book = rebase_seqs(cfg, before)
    assert _priority_view(snapshot_books(book)) == pre
    ns = np.asarray(book.next_seq)
    assert (ns == 6).all()  # live count per side
    bs = np.asarray(book.bid_seq)
    assert bs.max() < 6  # dense ranks

    # Identical follow-up flow through rebased and control books must
    # produce IDENTICAL fills and priority state (the renumbering is
    # invisible to matching semantics, including FIFO at equal prices).
    stream = [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 9_999, 11, oid=900),
        HostOrder(0, OP_SUBMIT, BUY, LIMIT, 10_006, 9, oid=901),
        HostOrder(1, OP_SUBMIT, SELL, LIMIT, 9_998, 25, oid=902),
    ]
    b1, r1, f1 = apply_orders(cfg, book, stream)
    b2, r2, f2 = apply_orders(cfg, control, stream)
    assert [(f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
            for f in f1] == \
        [(f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
         for f in f2]
    assert _priority_view(snapshot_books(b1)) == \
        _priority_view(snapshot_books(b2))


def test_rebase_identity_on_fresh_sorted_book():
    """A dense-sorted-prefix book rebases to its own lane order (the
    invariant survives trivially)."""
    cfg = dataclasses.replace(CFG, kernel="sorted")
    book = init_book(cfg)
    stream = [HostOrder(0, OP_SUBMIT, BUY, LIMIT, 10_000 - k, 5,
                        oid=1 + k) for k in range(5)]
    book, _, _ = apply_orders(cfg, book, stream)
    before = {f: np.asarray(getattr(book, f)).copy()
              for f in BookBatch._fields}
    book = rebase_seqs(cfg, book)
    for f in ("bid_price", "bid_qty", "bid_oid", "bid_owner",
              "ask_price", "ask_qty", "ask_oid", "ask_owner"):
        np.testing.assert_array_equal(np.asarray(getattr(book, f)),
                                      before[f], f)
    np.testing.assert_array_equal(
        np.asarray(book.bid_seq)[0, :5], np.arange(5))


def test_runner_maybe_rebase_trigger():
    from matching_engine_tpu.server.engine_runner import EngineRunner

    cfg = EngineConfig(num_symbols=2, capacity=16, batch=4, max_fills=256)
    r = EngineRunner(cfg)
    assert r.maybe_rebase_seqs() is False  # fresh books: far from cliff

    aged = BookBatch(*(np.asarray(x) for x in _aged_book(cfg)))
    r.place_book(aged)
    assert r.maybe_rebase_seqs() is True
    assert int(np.max(np.asarray(r.book.next_seq))) == 6
    assert r.metrics.snapshot()[0].get("seq_rebases") == 1
    assert r.maybe_rebase_seqs() is False  # idempotent below threshold


def test_rebase_with_max_price_ask():
    """A live ask at the maximum admissible price (2^31-1) must still
    rank INSIDE the live prefix — dead lanes sort strictly last via the
    liveness key, never by a colliding price sentinel."""
    cfg = CFG
    s_, c = cfg.num_symbols, cfg.capacity
    arr = {f: np.zeros((s_, c), dtype=np.int32)
           for f in BookBatch._fields if f != "next_seq"}
    arr["ask_price"][0, 0] = 2**31 - 1
    arr["ask_qty"][0, 0] = 3
    arr["ask_oid"][0, 0] = 7
    arr["ask_seq"][0, 0] = REBASE_THRESHOLD + 9
    arr["ask_price"][0, 1] = 10_000
    arr["ask_qty"][0, 1] = 2
    arr["ask_oid"][0, 1] = 8
    arr["ask_seq"][0, 1] = REBASE_THRESHOLD + 4
    book = BookBatch(**{k: jnp.asarray(v) for k, v in arr.items()},
                     next_seq=jnp.asarray(
                         np.full((s_,), REBASE_THRESHOLD + 10, np.int32)))
    book = rebase_seqs(cfg, book)
    aseq = np.asarray(book.ask_seq)
    assert aseq[0, 1] == 0  # better-priced ask ranks first
    assert aseq[0, 0] == 1  # max-price ask INSIDE the live prefix
    assert int(np.asarray(book.next_seq)[0]) == 2


def test_rebase_on_sharded_book():
    """The rebase jit partitions over the symbol axis on a mesh book."""
    from matching_engine_tpu.parallel import ShardedEngine, hostlocal, make_mesh

    cfg = EngineConfig(num_symbols=8, capacity=16, batch=4, max_fills=256)
    host = _aged_book(dataclasses.replace(cfg))
    host = BookBatch(*(np.asarray(x) for x in host))
    eng = ShardedEngine(cfg, make_mesh(8))
    sbook = hostlocal.put_tree(host, eng.book_sharding)
    pre = _priority_view(snapshot_books(sbook))
    out = rebase_seqs(cfg, sbook)
    assert _priority_view(snapshot_books(out)) == pre
    assert int(np.max(np.asarray(out.next_seq))) == 6
