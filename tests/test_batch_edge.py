"""Batch-native edge tests: the flat op-record codec and SubmitOrderBatch.

Coverage (ISSUE 7):
- codec round-trip fuzz python <-> C++ (OPREC_DTYPE vs me_gwop.h MeOpRec,
  including embedded NULs and box-limit strings), malformed/truncated
  payload rejects, positional record flaws;
- SubmitOrderBatch vs per-op RPC bit-parity on the python AND native
  serving paths: positional statuses, SQLite rows, book snapshots, and
  the sequenced feed's per-domain event lines (epoch-normalized);
- sharded batch split parity at K=2 (batch routed across lanes == the
  same ops per-op through the same sharded server);
- native megadispatch M=4 vs M=1 parity over deep multi-wave batches.
"""

import random

import grpc
import numpy as np
import pytest

from matching_engine_tpu import native as me_native
from matching_engine_tpu.domain import oprec
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.feed.sequencer import CHANNEL_MD, CHANNEL_OU
from matching_engine_tpu.proto import pb2, split_otype
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown


# -- codec ---------------------------------------------------------------------


def _fuzz_records(rng, n):
    recs = []
    for i in range(n):
        op = rng.choice((oprec.OPREC_SUBMIT, oprec.OPREC_CANCEL,
                         oprec.OPREC_AMEND))
        # Embedded AND trailing NULs: numpy S-dtype reads strip trailing
        # NULs, so the codec must read raw boxes (record_fields) or the
        # python and C++ paths would see different identities.
        sym = rng.choice([b"A", b"S\x00NUL", b"T\x00", b"x" * 64,
                          "ü".encode(), b"S1"])
        cid = rng.choice([b"", b"c1", b"c\x00\x00", b"c" * 256,
                          b"\x00\x01\x02"])
        oid = rng.choice([b"", b"OID-7", b"OID-7\x00",
                          b"OID-" + b"9" * 19])
        recs.append((op, rng.randrange(0, 3), rng.randrange(0, 5),
                     rng.randrange(-5, 10_000_000), rng.randrange(0, 1 << 40),
                     sym, cid, oid))
    return recs


def test_oprec_python_roundtrip_fuzz():
    rng = random.Random(7)
    recs = _fuzz_records(rng, 200)
    arr = oprec.pack_records(recs)
    assert arr.dtype.itemsize == oprec.RECORD_SIZE
    payload = oprec.encode_payload(arr)
    assert payload[:8] == oprec.MAGIC
    back = oprec.decode_payload(payload)
    assert len(back) == 200
    for want, got in zip(recs, (oprec.record_fields(back[i])
                                for i in range(200))):
        assert tuple(want) == got
    # Slices re-encode to independently decodable payloads.
    part = oprec.decode_payload(oprec.slice_payload(arr, 10, 5))
    assert oprec.record_fields(part[0]) == oprec.record_fields(back[10])


def test_oprec_malformed_payloads_reject():
    arr = oprec.pack_records([(1, 1, 0, 100, 5, b"S", b"c", b"")])
    good = oprec.encode_payload(arr)
    with pytest.raises(oprec.OpRecError, match="magic"):
        oprec.decode_payload(b"NOTMAGIC" + good[8:])
    with pytest.raises(oprec.OpRecError, match="magic"):
        oprec.decode_payload(b"")
    with pytest.raises(oprec.OpRecError, match="truncated"):
        oprec.decode_payload(good[:-17])
    with pytest.raises(oprec.OpRecError, match="cap"):
        oprec.decode_payload(good, max_records=0)
    # Oversized identifiers can't even be packed.
    with pytest.raises(oprec.OpRecError, match="box"):
        oprec.pack_records([(1, 1, 0, 100, 5, b"S" * 65, b"c", b"")])


def test_oprec_record_flaws_positional():
    from matching_engine_tpu.domain.order import MAX_QUANTITY

    rows = [
        (1, 1, 0, 100, 5, b"S", b"c", b""),          # ok
        (9, 1, 0, 100, 5, b"S", b"c", b""),          # bad op
        (1, 3, 0, 100, 5, b"S", b"c", b""),          # bad side
        (1, 1, 7, 100, 5, b"S", b"c", b""),          # bad otype
        (1, 1, 0, 100, 0, b"S", b"c", b""),          # zero qty
        (1, 1, 0, 100, MAX_QUANTITY + 1, b"S", b"c", b""),
        (1, 1, 0, 0, 5, b"S", b"c", b""),            # LIMIT price 0
        (1, 1, 1, 100, 5, b"S", b"c", b""),          # MARKET with price
        (1, 1, 1, 0, 5, b"S", b"c", b""),            # MARKET ok
        (2, 0, 0, 0, 0, b"", b"", b"OID-1"),         # cancel, no client
        (2, 0, 0, 0, 0, b"", b"c", b""),             # cancel, no target
        (3, 0, 0, 0, 2, b"", b"c", b"OID-1"),        # amend ok here
        (1, 1, 0, 100, 5, b"", b"c", b""),           # no symbol
    ]
    arr = oprec.pack_records(rows)
    flaws = oprec.record_flaws(arr)
    assert flaws[0] is None and flaws[8] is None and flaws[11] is None
    assert "op code" in flaws[1]
    assert "BUY or SELL" in flaws[2]
    assert "order_type" in flaws[3]
    assert "quantity must be positive" in flaws[4]
    assert "engine maximum" in flaws[5]
    assert "price_q4" in flaws[6]
    assert "price_q4=0" in flaws[7]
    assert "client_id is required" in flaws[9]
    assert "unknown order id" in flaws[10]
    assert "symbol is required" in flaws[12]
    # Nonzero reserved flags reject positionally too.
    arr2 = oprec.pack_records([(1, 1, 0, 100, 5, b"S", b"c", b"")])
    arr2 = arr2.copy()
    arr2["flags"] = 1
    assert "flags" in oprec.record_flaws(arr2)[0]


@pytest.mark.skipif(not me_native.available(),
                    reason="native library not built")
def test_oprec_cpp_roundtrip_fuzz():
    """python-packed records -> me_oprec_to_gwop -> MeGwOp fields must
    equal the python decode of the same records (the C++ struct mirror),
    and tags must be tag_base + i."""
    import ctypes

    rng = random.Random(13)
    recs = _fuzz_records(rng, 128)
    arr = oprec.pack_records(recs)
    body = arr.tobytes()
    out = me_native.oprec_to_gwop(body, len(arr), 1000)

    def raw(rec, field, n):
        # ctypes attribute reads NUL-truncate c_char arrays; embedded
        # NULs must round-trip, so read the field's raw bytes.
        off = getattr(me_native.MeGwOp, field).offset
        return ctypes.string_at(ctypes.addressof(rec) + off, n)

    for i in range(len(arr)):
        op, side, otype, price, qty, sym, cid, oid = oprec.record_fields(
            arr[i])
        g = out[i]
        assert g.tag == 1000 + i
        assert (g.op, g.side, g.otype, g.price_q4, g.quantity) == (
            op, side, otype, price, qty)
        assert raw(g, "symbol", g.symbol_len) == sym
        assert raw(g, "client_id", g.client_id_len) == cid
        assert raw(g, "order_id", g.order_id_len) == oid


@pytest.mark.skipif(not me_native.available(),
                    reason="native library not built")
def test_oprec_cpp_rejects_structural_skew():
    arr = oprec.pack_records([(1, 1, 0, 100, 5, b"S", b"c", b"")]).copy()
    arr["flags"] = 3
    with pytest.raises(RuntimeError):
        me_native.oprec_to_gwop(arr.tobytes(), 1, 1)
    with pytest.raises(RuntimeError):  # ragged body
        me_native.oprec_to_gwop(arr.tobytes()[:-5], 1, 1)


def test_opfile_roundtrip(tmp_path):
    arr = oprec.pack_records(_fuzz_records(random.Random(3), 17))
    path = str(tmp_path / "flow.ops")
    oprec.write_opfile(path, arr)
    back = oprec.read_opfile(path)
    assert back.tobytes() == arr.tobytes()


# -- RPC parity harness --------------------------------------------------------


CFG = EngineConfig(num_symbols=8, capacity=32, batch=4)


class _Server:
    def __init__(self, db_path, cfg=CFG, **kw):
        self.db_path = db_path
        self.server, self.port, self.parts = build_server(
            "127.0.0.1:0", db_path, cfg, window_ms=1.0, log=False, **kw)
        self.server.start()
        self.channel = grpc.insecure_channel(f"127.0.0.1:{self.port}")
        self.stub = MatchingEngineStub(self.channel)

    def close(self):
        self.channel.close()
        shutdown(self.server, self.parts)

    def flush(self):
        self.parts["sink"].flush()

    def storage_rows(self):
        import sqlite3

        con = sqlite3.connect(self.db_path)
        orders = con.execute(
            "SELECT order_id, client_id, symbol, side, order_type, price, "
            "quantity, remaining_quantity, status, tif FROM orders "
            "ORDER BY order_id").fetchall()
        fills = con.execute(
            "SELECT order_id, counter_order_id, price, quantity FROM fills "
            "ORDER BY rowid").fetchall()
        con.close()
        return orders, fills

    def feed_lines(self, channels=None, normalize_seq=False):
        """Per-(channel, key) event lines from the retransmission store,
        epoch-normalized: the full sequenced history each domain would
        replay, independent of this boot's epoch stamp. normalize_seq
        additionally zeroes the seq stamp (for comparisons across
        different batchings, where within-dispatch decode order — device
        (slot, row) — legitimately permutes a domain's publish order)."""
        seq = self.parts["sequencer"]
        out = {}
        for (channel, key), ring in seq._domains.items():
            if channels is not None and channel not in channels:
                continue
            events = []
            for e in ring.replay(0, ring.last_seq):
                msg = e.__class__()
                msg.CopyFrom(e)
                msg.feed_epoch = 0
                if normalize_seq:
                    msg.seq = 0
                events.append(msg.SerializeToString())
            out[(channel, key)] = events
        return out

    def books(self, symbols):
        out = {}
        for s in symbols:
            b = self.stub.GetOrderBook(pb2.OrderBookRequest(symbol=s),
                                       timeout=10)
            out[s] = b.SerializeToString()
        return out


def _script(seed=5, n=96, symbols=4):
    """A deterministic op script: submits across the collapsed otype
    codes, cancels/amends of earlier (predictable "OID-<k>") targets —
    valid, stale, wrong-client, unknown, and intra-batch. Returns record
    tuples; oid targets assume a fresh server assigning OID-1.. in
    script order (single-threaded drives preserve it on every path)."""
    rng = random.Random(seed)
    recs = []
    next_oid = 1
    submitted = []  # (oid_str, client)
    for i in range(n):
        r = rng.random()
        if submitted and r < 0.15:
            oid, client = rng.choice(submitted)
            bad = rng.random() < 0.3
            recs.append((oprec.OPREC_CANCEL, 0, 0, 0, 0, b"",
                         b"evil" if bad else client.encode(), oid.encode()))
            continue
        if submitted and r < 0.28:
            oid, client = rng.choice(submitted)
            recs.append((oprec.OPREC_AMEND, 0, 0, 0, rng.randrange(1, 8),
                         b"", client.encode(), oid.encode()))
            continue
        if r < 0.31:
            recs.append((oprec.OPREC_CANCEL, 0, 0, 0, 0, b"", b"c0",
                         b"OID-999999"))  # unknown target
            continue
        otype = rng.choice((0, 0, 0, 1, 2, 3, 4))
        price = 0 if otype in (1, 4) else 10_000 + rng.randrange(-6, 7)
        client = f"c{rng.randrange(3)}"
        recs.append((oprec.OPREC_SUBMIT, rng.choice((1, 2)), otype, price,
                     rng.randrange(1, 9), f"S{rng.randrange(symbols)}",
                     client.encode(), b""))
        submitted.append((f"OID-{next_oid}", client))
        next_oid += 1
    return recs


def _drive_perop(stub, recs):
    """The per-op oracle: each record through its per-op RPC, collecting
    (ok, order_id, error, remaining) positionally."""
    out = []
    for (op, side, otype, price, qty, sym, cid, oid) in recs:
        sym = sym.decode() if isinstance(sym, bytes) else sym
        cid = cid.decode() if isinstance(cid, bytes) else cid
        oid = oid.decode() if isinstance(oid, bytes) else oid
        if op == oprec.OPREC_SUBMIT:
            order_type, tif = split_otype(otype)
            r = stub.SubmitOrder(pb2.OrderRequest(
                client_id=cid, symbol=sym, order_type=order_type,
                side=side, price=price, scale=4, quantity=qty, tif=tif),
                timeout=30)
            out.append((r.success, r.order_id, r.error_message, 0))
        elif op == oprec.OPREC_CANCEL:
            r = stub.CancelOrder(pb2.CancelRequest(
                client_id=cid, order_id=oid), timeout=30)
            out.append((r.success, r.order_id, r.error_message, 0))
        else:
            r = stub.AmendOrder(pb2.AmendRequest(
                client_id=cid, order_id=oid, new_quantity=qty), timeout=30)
            out.append((r.success, r.order_id, r.error_message,
                        r.remaining_quantity if r.success else 0))
    return out


def _batch_slices(recs, batch_size):
    """Slice boundaries such that no record targets an oid submitted in
    its OWN slice: intra-batch targets deliberately resolve against the
    pre-batch directory ('unknown order id'), so a per-op-equivalent
    batch stream must put a target's submit in an earlier request —
    exactly what a real batching client (which learned the oid from an
    earlier response) does."""
    slices = []
    start = 0
    cur_new: set[bytes] = set()
    oid_counter = 1
    for i, r in enumerate(recs):
        cut = (i - start) >= batch_size
        if r[0] == oprec.OPREC_SUBMIT:
            if not cut:
                cur_new.add(f"OID-{oid_counter}".encode())
            oid_counter += 1
        elif r[7] in cur_new:
            cut = True
        if cut:
            slices.append((start, i - start))
            start = i
            cur_new = set()
            if r[0] == oprec.OPREC_SUBMIT:
                cur_new.add(f"OID-{oid_counter - 1}".encode())
    slices.append((start, len(recs) - start))
    return slices


def _drive_batch(stub, recs, batch_size):
    out = []
    arr = oprec.pack_records(recs)
    for start, count in _batch_slices(recs, batch_size):
        payload = oprec.slice_payload(arr, start, count)
        r = stub.SubmitOrderBatch(pb2.OrderBatchRequest(ops=payload),
                                  timeout=60)
        assert r.success, r.error_message
        assert len(r.ok) == count
        for i in range(count):
            out.append((r.ok[i], r.order_id[i], r.error[i],
                        r.remaining[i] if r.ok[i] else 0))
    return out


def _assert_server_parity(a: _Server, b: _Server, symbols,
                          strict=False):
    """strict=True: both servers consumed the SAME dispatch slices, so
    everything is bit-identical — fills table order, every feed domain's
    event lines, seq stamps included (the mega M-parity contract).
    strict=False: across DIFFERENT batchings (per-op vs batch) the
    per-order semantics are identical but within-dispatch event order
    follows device (slot, row) order and market data conflates per
    dispatch — so fills compare as a multiset, order-update lines
    compare seq-normalized per client domain, and MD conflation depth is
    batching-dependent by design."""
    a.flush()
    b.flush()
    orders_a, fills_a = a.storage_rows()
    orders_b, fills_b = b.storage_rows()
    assert orders_a == orders_b
    assert a.books(symbols) == b.books(symbols)
    if strict:
        assert fills_a == fills_b
        assert a.feed_lines() == b.feed_lines()
        return
    assert sorted(fills_a) == sorted(fills_b)
    la = a.feed_lines(channels=(CHANNEL_OU,), normalize_seq=True)
    lb = b.feed_lines(channels=(CHANNEL_OU,), normalize_seq=True)
    assert set(la) == set(lb)
    for k in la:
        assert sorted(la[k]) == sorted(lb[k]), f"OU lines diverged for {k}"
        assert len(la[k]) == len(lb[k])
    # Same per-domain seq head: every client's order-update line advanced
    # by the same event count on both sides.
    seq_a = {k: r.last_seq for k, r in
             a.parts["sequencer"]._domains.items() if k[0] == CHANNEL_OU}
    seq_b = {k: r.last_seq for k, r in
             b.parts["sequencer"]._domains.items() if k[0] == CHANNEL_OU}
    assert seq_a == seq_b and seq_a


def _run_parity(tmp_path, native_lanes, batch_size=24):
    """Batch vs per-op on one serving path: positional statuses equal the
    per-op responses, and storage rows + book snapshots + sequenced feed
    lines are bit-identical."""
    recs = _script()
    symbols = sorted({r[5] for r in recs if r[0] == oprec.OPREC_SUBMIT})
    a = _Server(str(tmp_path / "perop.db"), native_lanes=native_lanes)
    b = _Server(str(tmp_path / "batch.db"), native_lanes=native_lanes)
    try:
        got_a = _drive_perop(a.stub, recs)
        got_b = _drive_batch(b.stub, recs, batch_size)
        for i, (x, y) in enumerate(zip(got_a, got_b)):
            assert x == y, f"op {i} diverged: perop={x} batch={y}"
        _assert_server_parity(a, b, symbols)
        c = a.parts["metrics"].snapshot()[0]
        d = b.parts["metrics"].snapshot()[0]
        for k in ("orders_accepted", "orders_rejected", "orders_canceled",
                  "orders_amended", "fills"):
            assert c.get(k, 0) == d.get(k, 0), k
        assert d.get("edge_batches", 0) == len(
            _batch_slices(recs, batch_size))
    finally:
        a.close()
        b.close()


def test_batch_vs_perop_parity_python(tmp_path):
    _run_parity(tmp_path, native_lanes=False)


@pytest.mark.skipif(not me_native.available(),
                    reason="native library not built")
def test_batch_vs_perop_parity_native(tmp_path):
    _run_parity(tmp_path, native_lanes=True)


def test_batch_intra_batch_target_is_unknown(tmp_path):
    """A cancel naming a submit from the SAME payload resolves against
    the pre-batch directory (the C++ lane-build rule, mirrored by the
    python path): deterministic 'unknown order id', never a race."""
    s = _Server(str(tmp_path / "intra.db"))
    try:
        recs = [
            (oprec.OPREC_SUBMIT, 1, 0, 10_000, 5, b"S0", b"c1", b""),
            (oprec.OPREC_CANCEL, 0, 0, 0, 0, b"", b"c1", b"OID-1"),
        ]
        arr = oprec.pack_records(recs)
        r = s.stub.SubmitOrderBatch(
            pb2.OrderBatchRequest(ops=oprec.encode_payload(arr)),
            timeout=30)
        assert r.ok[0] and r.order_id[0] == "OID-1"
        assert not r.ok[1] and r.error[1] == "unknown order id"
        # The NEXT batch sees it.
        r2 = s.stub.SubmitOrderBatch(
            pb2.OrderBatchRequest(ops=oprec.slice_payload(arr, 1, 1)),
            timeout=30)
        assert r2.ok[0], r2.error[0]
    finally:
        s.close()


@pytest.mark.parametrize("native_lanes", [
    False,
    pytest.param(True, marks=pytest.mark.skipif(
        not me_native.available(), reason="native library not built"))])
def test_batch_non_utf8_rejects_positionally(tmp_path, native_lanes):
    """Non-UTF-8 identifiers reject their position with the same message
    on both serving paths (python decodes at the edge; the C++ lane
    build runs utf8_valid per record) — never the batch."""
    s = _Server(str(tmp_path / f"utf{native_lanes}.db"),
                native_lanes=native_lanes)
    try:
        arr = oprec.pack_records([
            (1, 1, 0, 10_000, 5, b"\xff\xfe", b"c1", b""),
            (1, 1, 0, 10_000, 5, b"S0", b"\xff", b""),
            (1, 1, 0, 10_000, 5, b"S0", b"c1", b""),
        ])
        r = s.stub.SubmitOrderBatch(
            pb2.OrderBatchRequest(ops=oprec.encode_payload(arr)),
            timeout=30)
        assert r.success
        assert list(r.ok) == [False, False, True]
        assert r.error[0] == r.error[1] == "invalid request encoding"
    finally:
        s.close()


def test_batch_malformed_payload_counts_codec_error(tmp_path):
    s = _Server(str(tmp_path / "mal.db"))
    try:
        r = s.stub.SubmitOrderBatch(
            pb2.OrderBatchRequest(ops=b"junkjunkjunk"), timeout=30)
        assert not r.success and "magic" in r.error_message
        arr = oprec.pack_records(
            [(1, 1, 0, 10_000, 5, b"S0", b"c1", b"")])
        trunc = oprec.encode_payload(arr)[:-7]
        r = s.stub.SubmitOrderBatch(pb2.OrderBatchRequest(ops=trunc),
                                    timeout=30)
        assert not r.success and "truncated" in r.error_message
        c = s.parts["metrics"].snapshot()[0]
        assert c.get("edge_codec_errors", 0) == 2
        assert c.get("edge_batches", 0) == 2
    finally:
        s.close()


def test_batch_sharded_split_parity_k2(tmp_path):
    """K=2 partitioned serving: one batch split across lanes by symbol
    shard equals the same script per-op through the same-K server —
    statuses, storage, books, and feed lines."""
    recs = _script(seed=9)
    symbols = sorted({r[5] for r in recs if r[0] == oprec.OPREC_SUBMIT})
    a = _Server(str(tmp_path / "perop.db"), serve_shards=2)
    b = _Server(str(tmp_path / "batch.db"), serve_shards=2)
    try:
        got_a = _drive_perop(a.stub, recs)
        got_b = _drive_batch(b.stub, recs, batch_size=32)
        for i, (x, y) in enumerate(zip(got_a, got_b)):
            assert x == y, f"op {i} diverged: perop={x} batch={y}"
        _assert_server_parity(a, b, symbols)
        # The split actually reached both lanes.
        gauges = b.parts["metrics"].snapshot()[1]
        counters = b.parts["metrics"].snapshot()[0]
        assert counters.get("edge_batches", 0) >= 3
        del gauges
    finally:
        a.close()
        b.close()


@pytest.mark.skipif(not me_native.available(),
                    reason="native library not built")
def test_native_mega_m4_vs_m1_strict_parity_inproc():
    """The native megadispatch bit-parity oracle: the SAME record batches
    through NativeLanesRunner.dispatch_records at M=1 (serial wave
    schedule, full-plane readbacks) and M=4 (stacked [M, S, B, 7] scans,
    compacted mega readbacks) must produce BYTE-identical completion and
    storage buffers per dispatch, identical stream protos with identical
    feed seq stamps, and a byte-identical native state dump."""
    from matching_engine_tpu.feed import FeedSequencer
    from matching_engine_tpu.server.native_lanes import (
        NativeLanesRunner,
        pack_record_batch,
        publish_native_result,
    )
    from matching_engine_tpu.server.streams import StreamHub
    from matching_engine_tpu.utils.metrics import Metrics
    from matching_engine_tpu.engine.harness import snapshot_books

    cfg = EngineConfig(num_symbols=8, capacity=32, batch=4)

    def drive(m):
        metrics = Metrics()
        hub = StreamHub(maxsize=8192, metrics=metrics,
                        sequencer=FeedSequencer(metrics=metrics, depth=8192,
                                                epoch=777))
        runner = NativeLanesRunner(cfg, metrics, hub=hub,
                                   megadispatch_max_waves=m)
        rng = random.Random(77)
        tag = 1
        live = []
        dispatches = []
        for _ in range(5):
            recs = []
            for _ in range(72):
                r = rng.random()
                if live and r < 0.15:
                    oid, client = rng.choice(live)
                    recs.append((tag, 2, 0, 0, 0, 0, "", client, oid))
                elif live and r < 0.27:
                    oid, client = rng.choice(live)
                    recs.append((tag, 3, 0, 0, 0, rng.randrange(1, 6),
                                 "", client, oid))
                else:
                    client = f"c{rng.randrange(3)}"
                    otype = rng.choice((0, 0, 0, 1, 2, 3, 4))
                    recs.append((tag, 1, rng.choice((1, 2)), otype,
                                 0 if otype in (1, 4)
                                 else 10_000 + rng.randrange(-4, 5),
                                 rng.randrange(1, 7),
                                 f"S{rng.randrange(4)}", client, ""))
                tag += 1
            arr, n = pack_record_batch(recs)
            box = {}

            def cb(result, error):
                assert error is None, error
                publish_native_result(result, None, hub, metrics)
                box["r"] = result
                return None

            runner.dispatch_records(arr, n, cb)
            runner.finish_pending()
            res = box["r"]
            dispatches.append({
                "comp": res.comp_buf,
                "store": res.store_buf,
                "local": list(res.local),
                "ou": [u.SerializeToString() for u in res.order_updates],
                "md": [u.SerializeToString() for u in res.market_data],
            })
            # Track live GTC limit orders for future cancels/amends via
            # the native directory (authoritative on this path).
            live = []
            for (t_, kind, ok, rem, oid, err) in res.local:
                if kind == 0 and ok and rem != 0:
                    h = runner.lanes.lookup(oid)
                    if h:
                        rec = runner.lanes.get_order(h)
                        if rec is not None:
                            live.append((oid, rec[8]))
        feed = {k: [e.SerializeToString()
                    for e in r.replay(0, r.last_seq)]
                for k, r in hub.sequencer._domains.items()}
        return (dispatches, runner.lanes.dump_state(),
                snapshot_books(runner.book), feed, metrics)

    got1 = drive(1)
    got4 = drive(4)
    for i, (a, b) in enumerate(zip(got1[0], got4[0])):
        for key in a:
            assert a[key] == b[key], f"dispatch {i}: {key} diverged"
    assert got1[1] == got4[1], "native state dumps diverged"
    assert got1[2] == got4[2], "books diverged"
    assert got1[3] == got4[3] and got1[3], "feed seq lines diverged"
    c1 = got1[4].snapshot()[0]
    c4 = got4[4].snapshot()[0]
    assert c1.get("megadispatch_steps", 0) == 0
    assert c4.get("megadispatch_steps", 0) > 0
    assert c4["megadispatch_stacked_waves"] > c4["megadispatch_steps"]
    assert c4.get("readback_bytes", 1) < c1.get("readback_bytes", 0)


@pytest.mark.skipif(not me_native.available(),
                    reason="native library not built")
def test_native_megadispatch_m4_vs_m1_server(tmp_path):
    """Native megadispatch end to end: --native-lanes servers at M=4 and
    M=1 serve the same batch stream identically per order (the M=4
    dispatcher pops deeper backlogs, so dispatch boundaries — and with
    them cross-symbol fill interleaving — legitimately differ; the
    strict per-dispatch oracle is the in-proc test above), and the
    stacked path must actually have engaged."""
    rng = random.Random(21)
    # Phased stream so the batch slicer keeps DEEP multi-wave batches: a
    # 96-submit phase over 4 symbols is ~24 rows/symbol = 6 waves at
    # batch=4 (stacked as 4+2 at M=4), then a cancel/amend phase over the
    # previous phase's oids.
    recs = []
    next_oid = 1
    submitted = []
    for _phase in range(2):
        phase_new = []
        for _ in range(96):
            client = f"c{rng.randrange(3)}"
            otype = rng.choice((0, 0, 0, 2, 3))
            recs.append((oprec.OPREC_SUBMIT, rng.choice((1, 2)), otype,
                         10_000 + rng.randrange(-4, 5), rng.randrange(1, 7),
                         f"S{rng.randrange(4)}", client.encode(), b""))
            phase_new.append((f"OID-{next_oid}", client))
            next_oid += 1
        submitted.extend(phase_new)
        for _ in range(48):
            oid, client = rng.choice(submitted)
            if rng.random() < 0.5:
                recs.append((oprec.OPREC_CANCEL, 0, 0, 0, 0, b"",
                             client.encode(), oid.encode()))
            else:
                recs.append((oprec.OPREC_AMEND, 0, 0, 0,
                             rng.randrange(1, 6), b"", client.encode(),
                             oid.encode()))
    symbols = [f"S{i}" for i in range(4)]
    a = _Server(str(tmp_path / "m1.db"), native_lanes=True,
                megadispatch_max_waves=1)
    b = _Server(str(tmp_path / "m4.db"), native_lanes=True,
                megadispatch_max_waves=4)
    try:
        got_a = _drive_batch(a.stub, recs, batch_size=96)
        got_b = _drive_batch(b.stub, recs, batch_size=96)
        for i, (x, y) in enumerate(zip(got_a, got_b)):
            assert x == y, f"op {i} diverged: M1={x} M4={y}"
        _assert_server_parity(a, b, symbols)
        ca = a.parts["metrics"].snapshot()[0]
        cb = b.parts["metrics"].snapshot()[0]
        assert ca.get("megadispatch_steps", 0) == 0
        assert cb.get("megadispatch_steps", 0) > 0
        assert cb.get("megadispatch_stacked_waves", 0) > \
            cb["megadispatch_steps"]
    finally:
        a.close()
        b.close()


def test_gateway_bridge_forwards_batch_verb():
    """The C++ gateway forwards SubmitOrderBatch whole (me_gateway.cpp
    M_BATCH -> callback); the bridge worker must route it through the
    SAME service handler and respond with the serialized positional
    response. Driven through a duck-typed gateway — the gateway .so
    itself needs protoc to rebuild and is covered by the e2e gateway
    suite on protoc-equipped hosts."""
    from matching_engine_tpu.server.dispatcher import BatchDispatcher
    from matching_engine_tpu.server.engine_runner import EngineRunner
    from matching_engine_tpu.server.gateway_bridge import GatewayBridge
    from matching_engine_tpu.server.service import MatchingEngineService
    from matching_engine_tpu.server.streams import StreamHub

    class FakeGateway:
        def __init__(self):
            self.responses = []

        def set_callback(self, fn):
            self.cb = fn

        def respond(self, tag, msg, end_stream, grpc_status=0,
                    grpc_message=""):
            self.responses.append((tag, msg, end_stream, grpc_status))
            return True

    runner = EngineRunner(CFG, hub=StreamHub())
    dispatcher = BatchDispatcher(runner, window_ms=1.0)
    service = MatchingEngineService(runner, dispatcher, StreamHub(),
                                    log=False)
    gw = FakeGateway()
    bridge = GatewayBridge(gw, runner, service)
    try:
        arr = oprec.pack_records([
            (oprec.OPREC_SUBMIT, 1, 0, 10_000, 5, b"S0", b"c1", b""),
            (oprec.OPREC_SUBMIT, 2, 0, 10_000, 5, b"S0", b"c2", b""),
        ])
        req = pb2.OrderBatchRequest(ops=oprec.encode_payload(arr))
        gw.cb(42, me_native.GW_BATCH, req.SerializeToString())
        bridge._fwd_q.put(None)  # sentinel: _worker returns after the item
        bridge._worker()
        assert len(gw.responses) == 1
        tag, msg, end_stream, status = gw.responses[0]
        assert tag == 42 and end_stream and status == 0
        resp = pb2.OrderBatchResponse.FromString(msg)
        assert resp.success and list(resp.ok) == [True, True]
        assert resp.order_id[0] == "OID-1"
    finally:
        dispatcher.close()


def test_ring_full_rejects_batch_whole(tmp_path):
    """Native path: a batch the ring can't hold entirely is refused whole
    with per-op 'server overloaded' — never split mid-overload."""
    if not me_native.available():
        pytest.skip("native library not built")
    from matching_engine_tpu.server.dispatcher import LaneRingDispatcher
    from matching_engine_tpu.server.native_lanes import NativeLanesRunner
    from matching_engine_tpu.server.streams import StreamHub

    runner = NativeLanesRunner(CFG, hub=StreamHub())
    disp = LaneRingDispatcher(runner, ring_capacity=4)
    try:
        recs = [(oprec.OPREC_SUBMIT, 1, 0, 100, 5, b"S0", b"c", b"")] * 8
        arr = oprec.pack_records(recs)
        w = disp.submit_oprec_batch(arr.tobytes(), 8)
        assert w.wait(5)
        assert all(e is not None for e in w.errors)
        assert all(r is None for r in w.results)
    finally:
        disp.close()
