"""Tail-latency observability layer (ISSUE 6): three-nines histograms,
per-dispatch trace export, and the tail levers.

Four layers under test:
- unit: log-bucket histogram quantiles on skewed synthetic data (the
  p999 must resolve a 1-in-1000 outlier), native `le` bucket exposition;
- trace export: round-trip (file parses as Chrome trace JSON, stage
  slices nest inside their dispatch slice), the slow-dispatch sampler
  (a dispatch past the rolling p99 exports even when the uniform sample
  skips it), and the writer's rate-limited error path (a dead trace dir
  degrades to a counter, never an exception on the dispatch path);
- parity: busy-poll on/off produces bit-identical serving output
  (outcomes + storage rows) on the python AND native-lanes paths —
  the lever trades CPU for wakeup latency, never behavior;
- e2e: a real server scraped over HTTP exports the new `_p999` derived
  gauges, the native bucket series, and the window gauge; the lever
  flags (busy-poll, book cache, proto reuse) serve correctly end to end
  and a --trace-dir run leaves a loadable trace.
"""

import json
import time
import urllib.request

import grpc
import pytest

from matching_engine_tpu import native as me_native
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.utils.metrics import Metrics
from matching_engine_tpu.utils.obs import (
    DispatchTimeline,
    ObsServer,
    TraceExporter,
    render_prometheus,
)

CFG = EngineConfig(num_symbols=8, capacity=16, batch=4)


# -- unit: three-nines histogram ---------------------------------------------


def test_p999_resolves_skewed_tail():
    """990 fast samples + 10 slow ones: the p99 must stay in the fast
    mode, the p999 must land on the outliers — the distinction the old
    two-quantile window could not make."""
    m = Metrics()
    for _ in range(995):
        m.observe("lat_us", 100.0)
    for _ in range(5):
        m.observe("lat_us", 10_000.0)
    _, g = m.snapshot()
    assert g["lat_us_p50"] < 150.0
    assert g["lat_us_p99"] < 150.0        # rank 990 of 1000: fast mode
    assert g["lat_us_p999"] >= 10_000.0   # rank 999: the outliers
    assert g["lat_us_p999"] <= 10_000.0 * 2 ** 0.125  # one bucket width


def test_prometheus_le_buckets_and_window_gauge():
    m = Metrics()
    for v in (50.0, 50.0, 900.0, 40_000.0):
        m.observe("lat_us", v)
    text = render_prometheus(m)
    assert "# TYPE me_lat_us histogram" in text
    bucket_lines = [ln for ln in text.splitlines()
                    if ln.startswith('me_lat_us_bucket{le="')]
    assert len(bucket_lines) >= 3
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
    assert cums == sorted(cums), "le buckets must be cumulative"
    assert bucket_lines[-1].startswith('me_lat_us_bucket{le="+Inf"}')
    assert cums[-1] == 4
    assert "me_lat_us_count 4" in text
    assert "me_lat_us_sum " in text
    # The derived three-nines gauges and the window the scrape describes.
    assert "me_lat_us_p999" in text
    assert "me_stage_window_seconds 60" in text


# -- trace export -------------------------------------------------------------


def _finish_timeline(m, path="python", age_s=0.002, ops=3):
    tl = DispatchTimeline(path, ops,
                          t_enqueue=time.perf_counter() - age_s,
                          t_ingress=time.perf_counter() - age_s - 0.001)
    tl.shape = "sparse"
    tl.stamp_build()
    tl.stamp_issue()
    tl.stamp_decode()
    tl.stamp_publish()
    tl.counters = {"fills": 1}
    tl.finish(m)
    return tl


def test_trace_export_round_trip(tmp_path):
    d = str(tmp_path / "trace")
    m = Metrics()
    t = TraceExporter(d, metrics=m, sample_every=2)
    m.tracer = t
    for _ in range(4):
        _finish_timeline(m)
    t.emit_span("sink_commit", time.perf_counter() - 0.001,
                time.perf_counter(), thread_label="sink")
    t.emit_span("sink_commit", time.perf_counter() - 0.001,
                time.perf_counter(), thread_label="sink")
    t.close()
    doc = json.load(open(t.path))
    assert isinstance(doc, list) and doc, "not a Chrome trace JSON array"
    dispatches = [e for e in doc if e.get("cat") == "dispatch"]
    assert len(dispatches) == 2  # every 2nd of 4
    # Stage slices nest inside their dispatch slice (Perfetto nesting is
    # containment on the same track).
    for disp in dispatches:
        kids = [e for e in doc if e.get("cat") == "stage"
                and e["args"]["trace_id"] == disp["args"]["trace_id"]]
        names = {k["name"] for k in kids}
        assert {"edge_ingress", "queue_wait", "lane_build",
                "device_dispatch", "completion_decode",
                "stream_publish"} <= names
        for k in kids:
            assert k["ts"] >= disp["ts"] - 1e-6
            assert k["ts"] + k["dur"] <= disp["ts"] + disp["dur"] + 1e-6
        assert disp["args"]["counters"] == {"fills": 1}
    # The sink span rides the same file on its own named track (the
    # seventh pipeline stage), sampled at the same 1-in-N rate.
    assert sum(1 for e in doc if e.get("name") == "sink_commit") == 1
    threads = [e for e in doc if e.get("ph") == "M"]
    assert any(e["args"]["name"] == "sink" for e in threads)
    c, _ = m.snapshot()
    assert c["trace_exported_dispatches"] == 2


def test_slow_dispatch_sampler_fires(tmp_path):
    """A dispatch past the rolling p99 exports even when the uniform
    1-in-N sample would skip it — the tail is what a uniform sample
    misses by construction."""
    m = Metrics()
    t = TraceExporter(str(tmp_path / "trace"), metrics=m,
                      sample_every=1_000_000)
    m.tracer = t
    for _ in range(300):   # establish a fast-mode rolling p99 (~ms)
        _finish_timeline(m, age_s=0.001)
    # (~1% of the fast dispatches may legitimately exceed the rolling
    # p99 and export too — the sampler working as designed; the
    # assertion is that the genuine straggler ALWAYS does.)
    _finish_timeline(m, age_s=0.5)  # 500ms straggler >> rolling p99
    t.close()
    c, _ = m.snapshot()
    assert c["trace_exported_dispatches"] >= 1
    doc = json.load(open(t.path))
    slow = [e for e in doc if e.get("cat") == "dispatch"
            and e["args"]["why"] == "slow"
            and e["args"]["e2e_us"] > 400_000]
    assert len(slow) == 1, "the 500ms straggler must export as slow"


def test_trace_writer_error_path_is_counted_not_fatal(tmp_path):
    """Satellite: a full/unwritable --trace-dir must degrade to the
    rate-limited warning + me_trace_write_errors_total — never an
    exception on (or a stall of) the dispatch path."""
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("occupied")
    m = Metrics()
    t = TraceExporter(str(blocker), metrics=m, sample_every=1)
    m.tracer = t
    for _ in range(3):
        _finish_timeline(m)   # must not raise
        t.flush()             # force the write attempts synchronously
    t.close()
    c, _ = m.snapshot()
    assert c["trace_write_errors"] >= 1
    assert c["trace_exported_dispatches"] == 3  # sampled, then lost at IO


# -- parity: busy-poll on/off ------------------------------------------------


class _RecordingSink:
    """Captures the storage batches the drain publishes (submit
    signature of AsyncStorageSink, always succeeding)."""

    def __init__(self):
        self.batches = []

    def submit(self, orders=None, updates=None, fills=None, block=True):
        self.batches.append((list(orders or []), list(updates or []),
                             list(fills or [])))  # FillRow: dataclass eq
        return True


_PARITY_FLOW = [
    # (symbol, side, price_q4, qty) — makers rest, takers cross, plus a
    # partial fill and a book-capacity mix across two symbols.
    ("A", 2, 10_000, 5), ("A", 1, 10_100, 3), ("A", 1, 10_100, 2),
    ("B", 2, 20_000, 4), ("B", 1, 20_000, 4),
    ("A", 2, 10_050, 7), ("A", 1, 10_060, 10),
]


def _run_python_flow(busy_poll_us):
    from matching_engine_tpu.engine.kernel import OP_SUBMIT
    from matching_engine_tpu.server.dispatcher import BatchDispatcher
    from matching_engine_tpu.server.engine_runner import (
        EngineOp,
        EngineRunner,
        OrderInfo,
    )

    runner = EngineRunner(CFG)
    sink = _RecordingSink()
    disp = BatchDispatcher(runner, sink=sink, window_ms=1.0,
                           busy_poll_us=busy_poll_us)
    outs = []
    for i, (sym, side, price, qty) in enumerate(_PARITY_FLOW):
        assert runner.slot_acquire(sym) is not None
        num, oid = runner.assign_oid()
        info = OrderInfo(oid=num, order_id=oid, client_id=f"c{i % 3}",
                         symbol=sym, side=side, otype=0, price_q4=price,
                         quantity=qty, remaining=qty, status=0,
                         handle=runner.assign_handle())
        o = disp.submit(EngineOp(OP_SUBMIT, info)).result(timeout=30)
        outs.append((info.order_id, o.status, o.filled, o.remaining))
    runner.finish_pending()
    disp.close()
    return outs, sink.batches


def test_busy_poll_parity_python():
    """Busy-poll changes WHEN the drain wakes, never what it computes:
    outcomes and storage rows are bit-identical to the blocking path."""
    base_outs, base_rows = _run_python_flow(0.0)
    spun_outs, spun_rows = _run_python_flow(200.0)
    assert spun_outs == base_outs
    # Storage content is order-identical per batch stream flattened (the
    # drain may CHUNK differently depending on wakeup timing — chunking
    # is a timing artifact, row content and order are the contract).
    flat = lambda batches: [  # noqa: E731
        (kind, row) for b in batches
        for kind, rows in zip(("orders", "updates", "fills"), b)
        for row in rows]
    assert flat(spun_rows) == flat(base_rows)


def _run_native_flow(busy_poll_us):
    from matching_engine_tpu.server.dispatcher import LaneRingDispatcher
    from matching_engine_tpu.server.native_lanes import NativeLanesRunner

    runner = NativeLanesRunner(CFG)
    sink = _RecordingSink()
    disp = LaneRingDispatcher(runner, sink=sink, window_ms=1.0,
                              busy_poll_us=busy_poll_us)
    outs = []
    for i, (sym, side, price, qty) in enumerate(_PARITY_FLOW):
        o = disp.submit_record(
            1, side=side, otype=0, price_q4=price, quantity=qty,
            symbol=sym.encode(), client_id=f"c{i % 3}".encode(),
        ).result(timeout=30)
        outs.append((o.order_id, o.kind, o.ok, o.remaining, o.error))
    runner.finish_pending()
    disp.close()
    return outs, sink.batches


@pytest.mark.skipif(not me_native.available(),
                    reason="native runtime not built")
def test_busy_poll_parity_native_lanes():
    base_outs, base_rows = _run_native_flow(0.0)
    spun_outs, spun_rows = _run_native_flow(200.0)
    assert spun_outs == base_outs
    flat = lambda batches: [  # noqa: E731
        (kind, row) for b in batches
        for kind, rows in zip(("orders", "updates", "fills"), b)
        for row in rows]
    assert flat(spun_rows) == flat(base_rows)


# -- e2e: scrape + levers + trace dir ----------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode()


def _submit(stub, client, side, price, qty=5):
    return stub.SubmitOrder(
        pb2.OrderRequest(client_id=client, symbol="LAT", order_type=pb2.LIMIT,
                         side=side, price=price, scale=4, quantity=qty),
        timeout=10)


def test_e2e_p999_buckets_and_levers(tmp_path):
    """One python-path server with every tail lever ON plus --trace-dir:
    serving still works (the levers change timing/allocation, not
    behavior), the scrape carries _p999 + native le buckets + the
    window gauge, the book cache conflates reads, and shutdown leaves a
    loadable Chrome trace."""
    trace_dir = tmp_path / "trace"
    server, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "lat.db"), CFG, window_ms=1.0,
        log=False, native=False, flight_dir=str(tmp_path / "flight"),
        busy_poll_us=50.0, book_cache_ms=2000.0, proto_reuse=True,
        trace_dir=str(trace_dir), trace_sample_every=1)
    server.start()
    obs = ObsServer(parts["metrics"], recorder=parts["recorder"],
                    port=0, host="127.0.0.1")
    obs.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    stub = MatchingEngineStub(channel)
    try:
        for i in range(4):
            assert _submit(stub, "maker", pb2.SELL, 10_000 + i).success
            assert _submit(stub, "taker", pb2.BUY, 10_100 + i).success
        # One resting order keeps the symbol live: a fully-emptied book
        # releases its slot and the cache (correctly) declines to cache
        # symbols absent from the venue directory.
        assert _submit(stub, "maker", pb2.SELL, 99_000).success
        # Conflated book cache: two reads inside the TTL — the second is
        # a hit and both return the same (possibly stale) snapshot.
        b1 = stub.GetOrderBook(pb2.OrderBookRequest(symbol="LAT"),
                               timeout=10)
        b2 = stub.GetOrderBook(pb2.OrderBookRequest(symbol="LAT"),
                               timeout=10)
        assert b1 == b2
        parts["sink"].flush()
        _, body = _get(obs.port, "/metrics")
        prom = dict(
            ln.rsplit(" ", 1) for ln in body.splitlines()
            if ln and not ln.startswith("#"))
        assert "me_stage_queue_wait_us_p999" in prom
        assert "me_submit_rpc_us_p999" in prom
        assert "me_dispatch_e2e_us_p50" in prom
        assert "me_stage_window_seconds" in prom
        assert float(prom["me_book_cache_hits_total"]) >= 1
        assert float(prom["me_book_cache_misses_total"]) >= 1
        assert any(k.startswith('me_submit_rpc_us_bucket{le="')
                   for k in prom), "native le buckets missing"
        assert float(prom["me_trace_exported_dispatches_total"]) >= 1
    finally:
        channel.close()
        shutdown(server, parts)
        obs.close()
    traces = list(trace_dir.glob("trace_*.json"))
    assert traces, "--trace-dir produced no file"
    doc = json.load(open(traces[0]))
    dispatches = [e for e in doc if e.get("cat") == "dispatch"]
    assert dispatches, "trace holds no dispatch slices"
    stage_names = {e["name"] for e in doc if e.get("cat") == "stage"}
    assert {"queue_wait", "lane_build", "device_dispatch",
            "completion_decode", "stream_publish"} <= stage_names
    assert any(e.get("name") == "sink_commit" for e in doc), \
        "sink commit spans missing from the trace"
    # Flight dump (shutdown) carries the controller/balance context.
    dumps = list((tmp_path / "flight").glob("flight_*_shutdown.json"))
    assert dumps
    dump = json.loads(dumps[0].read_text())
    assert "context" in dump
