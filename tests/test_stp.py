"""Self-trade prevention (skip policy), kernel<->oracle parity + serving.

STP is ALWAYS ON and keyed to the client id (domain.order.owner_hash —
a stable int32 carried in the device book's owner lanes): a taker never
crosses a maker resting under the same nonzero owner; the skipped maker
keeps its place for other takers. The call-auction uncross is exempt
(a batch event clearing at one price; docs/DESIGN.md §6b).
"""

import numpy as np
import pytest

from matching_engine_tpu.domain.order import owner_hash
from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import (
    HostOrder,
    apply_orders,
    snapshot_books,
)
from matching_engine_tpu.engine.kernel import (
    CANCELED,
    FILLED,
    NEW,
    OP_SUBMIT,
    PARTIALLY_FILLED,
)
from matching_engine_tpu.engine.oracle import OracleBook
from matching_engine_tpu.proto import BUY, LIMIT, MARKET, SELL

CFG = EngineConfig(num_symbols=2, capacity=16, batch=8, max_fills=512)


def run_both(stream):
    """(kernel results/fills, oracle results/fills) for one stream."""
    book = init_book(CFG)
    book, results, fills = apply_orders(CFG, book, stream)
    ob = OracleBook(CFG.capacity)
    o_res, o_fills = [], []
    for o in stream:
        r = ob.submit(o.oid, o.side, o.otype, o.price, o.qty, owner=o.owner)
        o_res.append((r.oid, r.status, r.filled, r.remaining))
        o_fills.extend((f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
                       for f in r.fills)
    k_res = [(r.oid, r.status, r.filled, r.remaining) for r in results]
    k_fills = [(f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
               for f in fills]
    return k_res, k_fills, o_res, o_fills, book, ob


def test_self_cross_cancels_instead_of_matching():
    """Skip-then-cancel: the crossing remainder is canceled (never a
    self-fill, never a crossed continuous book)."""
    me = owner_hash("alice")
    stream = [
        HostOrder(sym=0, op=OP_SUBMIT, side=BUY, otype=LIMIT, price=100,
                  qty=5, oid=1, owner=me),
        HostOrder(sym=0, op=OP_SUBMIT, side=SELL, otype=LIMIT, price=100,
                  qty=5, oid=2, owner=me),
    ]
    k_res, k_fills, o_res, o_fills, book, ob = run_both(stream)
    assert k_fills == [] and o_fills == []
    assert [s for _, s, _, _ in k_res] == [NEW, CANCELED]
    assert k_res == o_res
    assert snapshot_books(book)[0] == ob.snapshot()
    bids, asks = snapshot_books(book)[0]
    assert len(bids) == 1 and asks == []   # the book never stands crossed


def test_skip_walks_to_next_eligible_maker():
    """The taker skips its own best-priced maker and fills the OTHER
    client's worse-priced one; the skipped order keeps its place."""
    a, b = owner_hash("alice"), owner_hash("bob")
    stream = [
        HostOrder(sym=0, op=OP_SUBMIT, side=SELL, otype=LIMIT, price=100,
                  qty=3, oid=1, owner=a),          # alice's best ask
        HostOrder(sym=0, op=OP_SUBMIT, side=SELL, otype=LIMIT, price=101,
                  qty=3, oid=2, owner=b),          # bob behind her
        HostOrder(sym=0, op=OP_SUBMIT, side=BUY, otype=LIMIT, price=101,
                  qty=3, oid=3, owner=a),          # alice's taker
    ]
    k_res, k_fills, o_res, o_fills, book, ob = run_both(stream)
    assert k_fills == [(3, 2, 101, 3)]               # filled BOB, not self
    assert k_fills == o_fills and k_res == o_res
    assert snapshot_books(book)[0] == ob.snapshot()
    # Alice's ask still rests at 100 for everyone else.
    bids, asks = snapshot_books(book)[0]
    assert [r[0] for r in asks] == [1]


def test_market_order_respects_stp():
    a, b = owner_hash("alice"), owner_hash("bob")
    stream = [
        HostOrder(sym=0, op=OP_SUBMIT, side=SELL, otype=LIMIT, price=100,
                  qty=2, oid=1, owner=a),
        HostOrder(sym=0, op=OP_SUBMIT, side=BUY, otype=MARKET, price=0,
                  qty=2, oid=2, owner=a),          # own liquidity only
    ]
    k_res, k_fills, o_res, o_fills, *_ = run_both(stream)
    assert k_fills == [] == o_fills
    assert k_res[1][1] == CANCELED == o_res[1][1]   # IOC remainder
    # ... but bob sweeps it fine.
    stream.append(HostOrder(sym=0, op=OP_SUBMIT, side=BUY, otype=MARKET,
                            price=0, qty=2, oid=3, owner=b))
    k_res, k_fills, o_res, o_fills, *_ = run_both(stream)
    assert k_fills == [(3, 1, 100, 2)] == o_fills


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_stp_fuzz_parity(seed):
    """Random flow from 3 owners through kernel and oracle — statuses,
    fills, and books bit-equal with STP active."""
    rng = np.random.default_rng(seed)
    owners = [owner_hash(f"client{i}") for i in range(3)]
    stream = []
    # Single symbol: device results/fills are (symbol, batch-row) ordered,
    # so one symbol makes stream order == device order and the comparison
    # exact (the multi-symbol ordering nuance is covered by
    # tests/test_kernel_parity.py's canonicalized comparisons).
    for i in range(160):
        stream.append(HostOrder(
            sym=0, op=OP_SUBMIT,
            side=BUY if rng.random() < 0.5 else SELL,
            otype=LIMIT if rng.random() < 0.85 else MARKET,
            price=int(10_000 + rng.integers(-6, 7)),
            qty=int(rng.integers(1, 20)), oid=i + 1,
            owner=owners[int(rng.integers(0, 3))]))
    # MARKET price must be 0 by convention.
    stream = [o if o.otype == LIMIT else
              HostOrder(**{**o.__dict__, "price": 0}) for o in stream]
    book = init_book(CFG)
    book, results, fills = apply_orders(CFG, book, stream)
    ob = OracleBook(CFG.capacity)
    o_fills = []
    o_res = []
    for o in stream:
        r = ob.submit(o.oid, o.side, o.otype, o.price, o.qty, owner=o.owner)
        o_res.append((r.oid, r.status, r.filled, r.remaining))
        o_fills.extend((f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
                       for f in r.fills)
    assert [(r.oid, r.status, r.filled, r.remaining)
            for r in results] == o_res
    assert [(f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
            for f in fills] == o_fills
    assert snapshot_books(book)[0] == ob.snapshot()


def test_stp_through_server_and_recovery(tmp_path):
    """Serving-level STP: one client's crossing orders never self-fill —
    including AFTER a restart (the owner identity is intrinsic to the
    persisted client_id, so recovery re-rests with protection intact)."""
    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    db = str(tmp_path / "stp.db")
    cfg = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=256)
    server, port, parts = build_server("127.0.0.1:0", db, cfg,
                                       window_ms=1.0, log=False)
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))

    def sub(stub_, client, side, price, qty):
        return stub_.SubmitOrder(
            pb2.OrderRequest(client_id=client, symbol="STP", side=side,
                             order_type=pb2.LIMIT, price=price, scale=4,
                             quantity=qty), timeout=15)

    r1 = sub(stub, "solo", pb2.BUY, 100, 5)
    r2 = sub(stub, "solo", pb2.SELL, 100, 5)   # would self-cross: canceled
    assert r1.success and r2.success
    book = stub.GetOrderBook(pb2.OrderBookRequest(symbol="STP"), timeout=10)
    assert len(book.bids) == 1 and len(book.asks) == 0   # never crossed
    parts["sink"].flush()
    shutdown(server, parts)

    # Restart: continuous trading resumes (no crossed book, no call
    # period); the recovered bid still carries solo's owner identity, so
    # another solo SELL cancels while bob's SELL fills it.
    server2, port2, parts2 = build_server("127.0.0.1:0", db, cfg,
                                          window_ms=1.0, log=False)
    assert not parts2["runner"].auction_mode
    server2.start()
    stub2 = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port2}"))
    try:
        import sqlite3
        conn = sqlite3.connect(db)
        assert conn.execute("select count(*) from fills").fetchone()[0] == 0
        conn.close()
        r3 = sub(stub2, "solo", pb2.SELL, 100, 2)
        assert r3.success               # accepted; remainder STP-canceled
        conn = sqlite3.connect(db)
        # No self-fill happened across the restart.
        parts2["sink"].flush()
        assert conn.execute("select count(*) from fills").fetchone()[0] == 0
        conn.close()
        r4 = sub(stub2, "bob", pb2.SELL, 100, 2)
        assert r4.success
        parts2["sink"].flush()
        conn = sqlite3.connect(db)
        fills = conn.execute(
            "select order_id, counter_order_id, quantity from fills"
        ).fetchall()
        conn.close()
        assert len(fills) == 1 and fills[0][2] == 2   # bob crossed solo
    finally:
        shutdown(server2, parts2)


def test_owner_hash_collision_remaps_to_distinct_id():
    """Two client ids forced onto one hash get DISTINCT STP identities
    (ADVICE r3: a collision must not silently couple unrelated clients —
    the newer client is remapped to the next free id, counted, and the
    assignment queued for persistence)."""
    from matching_engine_tpu.server.engine_runner import EngineRunner

    r = EngineRunner(EngineConfig(num_symbols=2, capacity=8, batch=4,
                                  max_fills=64))
    h = r._owner_for("alice")
    # Simulate a colliding id by priming the registry directly.
    r._owner_claimed[owner_hash("mallory")] = "someone-else"
    m = r._owner_for("mallory")
    assert r.metrics.snapshot()[0].get("owner_hash_collisions", 0) == 1
    assert h == owner_hash("alice")
    assert m != owner_hash("mallory") and m != h and m > 0
    # Stable on re-lookup, and both assignments queued for the registry.
    assert r._owner_for("mallory") == m
    assert ("alice", h) in r.pending_owner_ids
    assert ("mallory", m) in r.pending_owner_ids


def test_owner_registry_survives_restart(tmp_path):
    """Persisted assignments win over arrival order: a client remapped in
    one process keeps its id in the next, even when the colliding client
    arrives first after the restart."""
    from matching_engine_tpu.server.engine_runner import EngineRunner
    from matching_engine_tpu.storage.storage import Storage

    db = str(tmp_path / "owners.db")
    st = Storage(db)
    assert st.init()

    r1 = EngineRunner(EngineConfig(num_symbols=2, capacity=8, batch=4,
                                   max_fills=64))
    r1.persist_owner_ids = st.insert_owner_ids
    a = r1._owner_for("alice")
    r1._owner_claimed[owner_hash("mallory")] = "alice-colliding-sim"
    m = r1._owner_for("mallory")
    r1.flush_owner_ids()
    assert r1.pending_owner_ids == []

    # "Restart": fresh runner, registry loaded from the durable store;
    # mallory arrives FIRST this time but must keep the remapped id.
    r2 = EngineRunner(EngineConfig(num_symbols=2, capacity=8, batch=4,
                                   max_fills=64))
    r2.load_owner_ids(st.load_owner_ids())
    assert r2._owner_for("mallory") == m
    assert r2._owner_for("alice") == a
    st.close()


def test_rebuild_owner_lanes_uses_registry_not_raw_hash():
    """Pre-owner-snapshot migration (checkpoint._rebuild_owner_lanes) must
    derive lanes through the runner's registry: a hash-collision-remapped
    client's rebuilt lane carries the REMAPPED id, not owner_hash (which
    would alias the colliding client's STP identity)."""
    import numpy as np

    from matching_engine_tpu.server.engine_runner import EngineRunner
    from matching_engine_tpu.utils.checkpoint import _rebuild_owner_lanes

    r = EngineRunner(EngineConfig(num_symbols=2, capacity=8, batch=4,
                                  max_fills=64))
    # Force mallory onto a remapped id before any order exists.
    r._owner_claimed[owner_hash("mallory")] = "other-client"
    remapped = r._owner_for("mallory")
    assert remapped != owner_hash("mallory")

    assert r.slot_acquire("RB") is not None
    num, oid = r.assign_oid()
    from matching_engine_tpu.server.engine_runner import EngineOp, OrderInfo
    op = EngineOp(0 + 1, OrderInfo(  # OP_SUBMIT
        oid=num, order_id=oid, client_id="mallory", symbol="RB", side=1,
        otype=0, price_q4=100, quantity=5, remaining=5, status=0,
        handle=r.assign_handle()))
    r.run_dispatch([op])

    # Simulate a pre-owner snapshot: zero the owner lanes.
    import jax

    book = jax.tree.map(lambda x: np.asarray(x).copy(), r.book)
    book = book._replace(bid_owner=np.zeros_like(book.bid_owner),
                         ask_owner=np.zeros_like(book.ask_owner))
    r.place_book(book)
    _rebuild_owner_lanes(r)

    bid_owner = np.asarray(r.book.bid_owner)
    bid_qty = np.asarray(r.book.bid_qty)
    lanes = bid_owner[bid_qty > 0]
    assert lanes.tolist() == [remapped]


def test_owner_registry_overflow_probes_past_claimed_ids():
    """Past the registry cap, new clients get UNREGISTERED ids — but the
    probe must still skip claimed ids: returning a raw hash that a
    registered client was remapped AWAY from would merge STP identities
    with a client that doesn't even hash-collide (ADVICE r4 low)."""
    from matching_engine_tpu.server.engine_runner import EngineRunner

    cfg = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=256)
    r = EngineRunner(cfg)
    # "victim" was remapped away from overflowing client's raw hash:
    # claim that hash for someone else, as a collision remap would.
    raw = owner_hash("late-client")
    r._owner_claimed[raw] = "earlier-client"
    r._owner_registry_cap = len(r._owner_by_client)  # registry is full

    owner = r._owner_for("late-client")
    assert owner != raw                      # skipped the claimed id
    assert owner != 0
    assert "late-client" not in r._owner_by_client   # unregistered
    assert not r.pending_owner_ids                   # nothing queued
    snap = r.metrics.snapshot()[0]
    assert snap.get("owner_registry_overflow") == 1
    # Deterministic across calls in one process lifetime (same probe).
    assert r._owner_for("late-client") == owner
