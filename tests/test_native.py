"""Native (C++) runtime layer: parity with the pure-Python twins.

Covers native/me_native.cpp via the ctypes bindings:
- Q4 normalization bit-parity with domain.price.normalize_to_q4, including
  the reference's oracle values (tests/test_price.cpp) and error paths;
- submit-validation codes vs the service's reject rules;
- MeRing FIFO / multi-producer / windowed-batch semantics;
- MeSink SQLite output row-for-row identical to Storage.apply_batch;
- full server stack on the native runtime with fills persisting.
"""

import threading

import pytest

from matching_engine_tpu import native as me_native
from matching_engine_tpu.domain.order import (
    MAX_CLIENT_ID_BYTES,
    MAX_QUANTITY,
    MAX_SYMBOL_BYTES,
    validate_submit,
)
from matching_engine_tpu.domain.price import (
    MAX_DEVICE_PRICE_Q4,
    PriceError,
    normalize_to_q4,
)
from matching_engine_tpu.storage import FillRow, Storage

pytestmark = pytest.mark.skipif(
    not me_native.available(), reason="native library unavailable (no g++?)"
)


# -- domain -----------------------------------------------------------------

CASES = [
    # (price, scale) — reference oracle rows (test_price.cpp:6-14) + extremes
    (10000, 8), (10050, 9), (123, 2), (7, 0), (1, 4), (0, 0),
    (-10050, 9), (-123, 2), (99999999999999, 10), (2**62, 18),
    (-(2**62), 18), (10**14, 0),
]


@pytest.mark.parametrize("price,scale", CASES)
def test_normalize_parity(price, scale):
    try:
        expect = normalize_to_q4(price, scale)
    except PriceError:
        with pytest.raises(PriceError):
            me_native.normalize_to_q4(price, scale)
        return
    assert me_native.normalize_to_q4(price, scale) == expect


@pytest.mark.parametrize("scale", [-1, 19, 100])
def test_normalize_bad_scale(scale):
    with pytest.raises(PriceError):
        me_native.normalize_to_q4(1, scale)


def test_normalize_overflow():
    with pytest.raises(PriceError):
        me_native.normalize_to_q4(2**62, 0)  # *10^4 overflows int64


def test_validate_codes():
    # v(symbol_len, client_id_len, qty, side, otype, price, scale)
    v = me_native.validate_submit_code
    m = MAX_DEVICE_PRICE_Q4
    assert v(3, 2, 5, 1, 0, 10000, 4) == 0
    assert v(0, 2, 5, 1, 0, 10000, 4) == 1          # empty symbol
    assert v(3, 2, 0, 1, 0, 10000, 4) == 2          # qty <= 0
    assert v(3, 2, 5, 1, 0, 0, 4) == 3              # LIMIT price <= 0
    assert v(3, 2, 5, 1, 0, 10000, 42) == 4         # scale out of range
    assert v(3, 2, 5, 1, 0, 2**62, 0) == 5          # int64 overflow upscale
    assert v(3, 2, 5, 1, 0, m + 1, 4) == 5          # over device lane ceiling
    assert v(3, 2, 5, 1, 0, 10050, 9) == 3          # truncates to 0 at Q4
    assert v(3, 2, 5, 1, 1, 0, 4) == 0              # MARKET: no price checks
    assert v(3, 2, 5, 1, 1, 0, 42) == 4             # ...but scale still ranged
    assert v(3, 2, MAX_QUANTITY + 1, 1, 0, 10000, 4) == 6
    assert v(3, 2, 5, 0, 0, 10000, 4) == 7          # bad side
    assert v(3, 2, 5, 1, 7, 10000, 4) == 8          # bad order type
    assert v(MAX_SYMBOL_BYTES + 1, 2, 5, 1, 0, 10000, 4) == 9
    assert v(3, MAX_CLIENT_ID_BYTES + 1, 5, 1, 0, 10000, 4) == 10


def test_validate_parity_with_python(tmp_path):
    """The native predicate accepts/rejects exactly like validate_submit."""
    import itertools

    from matching_engine_tpu.proto import pb2

    symbols = ["", "S", "X" * MAX_SYMBOL_BYTES, "X" * (MAX_SYMBOL_BYTES + 1)]
    clients = ["c", "c" * (MAX_CLIENT_ID_BYTES + 1)]
    qtys = [0, 1, MAX_QUANTITY, MAX_QUANTITY + 1]
    sides = [0, 1, 2, 3]
    otypes = [0, 1, 5]
    prices = [(0, 4), (10000, 4), (10050, 9), (2**62, 0),
              (MAX_DEVICE_PRICE_Q4 + 1, 4), (100, 19)]
    for sym, cid, qty, side, otype, (price, scale) in itertools.product(
        symbols, clients, qtys, sides, otypes, prices
    ):
        req = pb2.OrderRequest(
            client_id=cid, symbol=sym, side=side, order_type=otype,
            price=price, scale=scale, quantity=qty,
        )
        py_err = validate_submit(req)
        code = me_native.validate_submit_code(
            len(sym.encode()), len(cid.encode()), qty, side, otype, price,
            scale,
        )
        assert (py_err is None) == (code == 0), (
            f"divergence for {req}: py={py_err!r} native={code}"
        )


# -- ring -------------------------------------------------------------------

def test_ring_fifo_and_close():
    r = me_native.NativeRing(64)
    for i in range(10):
        assert r.push(i + 1, i, 1, 1, 0, 100 + i, 5, i)
    got = r.pop_batch(max_ops=16, window_us=1000)
    assert [g[0] for g in got] == list(range(1, 11))
    assert got[3][5] == 103  # price carried through
    r.close()
    assert r.pop_batch(16, 1000) is None  # closed + empty
    r.destroy()


def test_ring_window_caps_batch():
    r = me_native.NativeRing(64)
    for i in range(8):
        r.push(i + 1, 0, 1, 1, 0, 1, 1, i)
    got = r.pop_batch(max_ops=3, window_us=10_000)
    assert len(got) == 3  # max_ops is a hard cap
    got = r.pop_batch(max_ops=100, window_us=1)
    assert len(got) == 5  # drains the rest, window expires
    r.close()
    r.destroy()


def test_ring_capacity_drops():
    r = me_native.NativeRing(4)
    assert all(r.push(i, 0, 1, 1, 0, 1, 1, 0) for i in range(1, 5))
    assert not r.push(9, 0, 1, 1, 0, 1, 1, 0)  # full
    assert r.dropped == 1
    r.close()
    r.destroy()


def test_ring_multi_producer():
    r = me_native.NativeRing(1 << 12)
    n_threads, per = 8, 200

    def produce(t):
        for i in range(per):
            tag = t * 1000 + i
            while not r.push(tag, t, 1, 1, 0, 1, 1, 0):
                pass

    threads = [threading.Thread(target=produce, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    got = []
    while len(got) < n_threads * per:
        batch = r.pop_batch(max_ops=128, window_us=500)
        assert batch is not None
        got.extend(batch)
    for t in threads:
        t.join()
    tags = [g[0] for g in got]
    assert sorted(tags) == sorted(t * 1000 + i for t in range(n_threads) for i in range(per))
    # Per-producer order preserved (the ring is globally FIFO).
    for t in range(n_threads):
        mine = [x for x in tags if x // 1000 == t]
        assert mine == sorted(mine)
    r.close()
    r.destroy()


# -- sink -------------------------------------------------------------------

ORDERS = [
    ("OID-1", "cA", "AAPL", 1, 0, 101_0000, 10, 10, 0),
    ("OID-2", "cB", "AAPL", 2, 0, 100_0000, 4, 0, 2),
    ("OID-3", "cB", "MSFT", 2, 1, None, 7, 0, 3),   # MARKET: NULL price
]
UPDATES = [("OID-1", 1, 6), ("OID-2", 2, 0)]
FILLS = [
    FillRow("OID-2", "OID-1", 101_0000, 4, 0),
    FillRow("OID-1", "OID-2", 101_0000, 4, 1234567),
]


def _rows(db_path):
    st = Storage(db_path)
    orders = st._conn.execute(
        "SELECT order_id, client_id, symbol, side, order_type, price, "
        "quantity, remaining_quantity, status FROM orders ORDER BY order_id"
    ).fetchall()
    fills = st._conn.execute(
        "SELECT order_id, counter_order_id, price, quantity FROM fills "
        "ORDER BY fill_id"
    ).fetchall()
    st.close()
    return orders, fills


def test_sink_row_parity_with_python_storage(tmp_path):
    py_db = str(tmp_path / "py.db")
    st = Storage(py_db)
    assert st.init()
    assert st.apply_batch(list(ORDERS), list(UPDATES), list(FILLS))
    st.close()

    nat_db = str(tmp_path / "nat.db")
    sink = me_native.NativeStorageSink(nat_db)
    assert sink.submit(orders=list(ORDERS), updates=list(UPDATES), fills=list(FILLS))
    sink.flush()
    stats = sink.stats()
    sink.close()

    assert stats["errors"] == 0 and stats["rows"] == len(ORDERS) + len(UPDATES) + len(FILLS)
    assert _rows(py_db) == _rows(nat_db)


def test_sink_multiple_batches_and_reread(tmp_path):
    db = str(tmp_path / "s.db")
    sink = me_native.NativeStorageSink(db)
    for k in range(20):
        oid = f"OID-{k + 10}"
        assert sink.submit(orders=[(oid, "c", "S", 1, 0, 1000 + k, 5, 5, 0)])
    sink.flush()
    sink.close()
    st = Storage(db)
    assert st.count("orders") == 20
    assert st.load_next_oid_seq() == 30  # OID sequence recovery over native rows
    assert st.best_bid("S") == (1019, 5)
    st.close()


def test_sink_empty_submit_is_noop(tmp_path):
    sink = me_native.NativeStorageSink(str(tmp_path / "e.db"))
    assert sink.submit()  # nothing to write
    sink.flush()
    assert sink.stats()["batches"] == 0
    sink.close()


# -- full stack on the native runtime --------------------------------------

def test_server_native_runtime_end_to_end(tmp_path):
    import grpc

    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.dispatcher import NativeRingDispatcher
    from matching_engine_tpu.server.main import build_server, shutdown

    db = str(tmp_path / "nat_e2e.db")
    cfg = EngineConfig(num_symbols=4, capacity=8, batch=4)
    server, port, parts = build_server(
        "127.0.0.1:0", db, cfg, window_ms=1.0, log=False, native=True
    )
    from matching_engine_tpu.storage.async_sink import SpillingSink

    assert isinstance(parts["dispatcher"], NativeRingDispatcher)
    # The native sink now sits behind the order-preserving spill buffer.
    assert isinstance(parts["sink"], SpillingSink)
    assert isinstance(parts["sink"]._inner, me_native.NativeStorageSink)
    server.start()
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        stub = MatchingEngineStub(channel)
        r1 = stub.SubmitOrder(pb2.OrderRequest(
            client_id="a", symbol="S", order_type=pb2.LIMIT, side=pb2.BUY,
            price=10000, scale=4, quantity=5), timeout=10)
        r2 = stub.SubmitOrder(pb2.OrderRequest(
            client_id="b", symbol="S", order_type=pb2.LIMIT, side=pb2.SELL,
            price=10000, scale=4, quantity=3), timeout=10)
        assert r1.success and r2.success
        parts["sink"].flush()
        st = Storage(db)
        assert st.count("fills") == 1  # one row per match, taker-keyed
        f = st.fills_for_order(r2.order_id)[0]
        assert f[1] == r1.order_id and f[2] == 10000 and f[3] == 3
        row = st.get_order(r1.order_id)
        assert row[7] == 2 and row[8] == 1  # remaining 2, PARTIALLY_FILLED
        row2 = st.get_order(r2.order_id)
        assert row2[7] == 0 and row2[8] == 2  # FILLED
        st.close()
        channel.close()
    finally:
        shutdown(server, parts)
