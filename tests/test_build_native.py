"""scripts/build_native.sh smoke test: the native runtime must be
reproducible from source, not an unreproducible checked-in artifact.

Builds into a scratch directory (never swapping the package's .so under
a live process) and loads the result. Skips cleanly when the image has
no C++ toolchain — tier-1 must pass on a pure-Python box.
"""

import ctypes
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "build_native.sh"

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="no C++ toolchain in this image",
)


def test_build_native_lib_from_source(tmp_path):
    r = subprocess.run(
        ["bash", str(SCRIPT), "--lib-only", "--force",
         "--out-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    so = tmp_path / "libme_native.so"
    assert so.exists(), r.stdout + r.stderr

    lib = ctypes.CDLL(str(so))
    # One symbol from each translation unit: the ring/sink layer
    # (me_native.cpp) and the lane engine (me_lanes.cpp).
    assert hasattr(lib, "me_ring_create")
    assert hasattr(lib, "me_lanes_create")


# -- sanitizer-hardened variants ---------------------------------------------
#
# scripts/build_native.sh --sanitize={address,undefined} builds an
# instrumented lane library; the smoke below loads it into a fresh
# python process (ME_NATIVE_LIB override + the sanitizer runtime
# LD_PRELOADed — an uninstrumented interpreter must have the runtime
# resident before the .so's initializers run) and drives the codec
# round-trip fuzz + ring + lane-build surface through the normal
# wrapper stack. A sanitizer finding aborts the subprocess -> the test
# fails. Thread-sanitizer builds exist too (--sanitize=thread) but get
# no smoke here: under an uninstrumented CPython every GIL handoff is a
# false positive.

_SAN_SMOKE = r"""
import ctypes, random, sys
from matching_engine_tpu import native as me_native
from matching_engine_tpu.domain import oprec

assert me_native.available(), "sanitized libme_native failed to load"
rng = random.Random(29)

def fuzz_records(n):
    rows = []
    for i in range(n):
        kind = rng.randrange(6)
        if kind < 3:   # submit (embedded NULs must round-trip)
            sym = bytes(rng.randrange(256)
                        for _ in range(rng.randrange(1, 64)))
            cid = bytes(rng.randrange(256)
                        for _ in range(rng.randrange(1, 256)))
            rows.append((1, rng.choice((1, 2)), rng.choice((0, 1, 2, 3)),
                         0 if rng.random() < .2 else rng.randrange(1, 1 << 20),
                         rng.randrange(1, 1 << 20), sym, cid, b""))
        elif kind < 5:  # cancel
            rows.append((2, 0, 0, 0, 0, b"", b"c%d" % i,
                         b"OID-%d" % rng.randrange(1, 500)))
        else:           # amend
            rows.append((3, 0, 0, 0, rng.randrange(1, 1000), b"",
                         b"c%d" % i, b"OID-%d" % rng.randrange(1, 500)))
    return rows

rows = fuzz_records(512)
arr = oprec.pack_records(rows)
out = me_native.oprec_to_gwop(arr.tobytes(), len(arr), 1000)
for i in range(len(arr)):
    op, side, otype, price, qty, sym, cid, oid = oprec.record_fields(arr[i])
    g = out[i]
    assert g.tag == 1000 + i
    assert (g.op, g.side, g.otype, g.price_q4, g.quantity) == (
        op, side, otype, price, qty), i
    for field, want in (("symbol", sym), ("client_id", cid),
                        ("order_id", oid)):
        off = getattr(me_native.MeGwOp, field).offset
        assert ctypes.string_at(ctypes.addressof(g) + off,
                                len(want)) == want, (i, field)

# Ragged / skewed payloads must reject, not overread.
for bad in (arr.tobytes()[:-7], arr.tobytes() + b"x"):
    try:
        me_native.oprec_to_gwop(bad, len(arr), 1)
    except RuntimeError:
        pass
    else:
        sys.exit("structural skew accepted")

# Ring round trip + the lane engine's build path (host-side only; the
# device step is jax's, not this .so's).
ring = me_native.LaneRing(2048)
assert ring.push_n(out, len(arr))
lanes = me_native.NativeLanes(num_symbols=16, batch=8, fill_inline=4,
                              max_fills=64)
recs, n = ring.pop_batch_raw(len(arr), 0)
assert recs is not None and n == len(arr)
try:
    lanes.build(recs, n, True, True)
except RuntimeError:
    pass  # semantic reject (symbol-table exhaustion etc.) is fine —
          # the smoke asserts memory/UB safety, the parity suites
          # assert semantics
lanes.destroy()
print("sanitizer smoke OK")
"""


def _san_runtime(name: str) -> str | None:
    """Resolve the sanitizer runtime for LD_PRELOAD, or None."""
    try:
        out = subprocess.run(["g++", f"-print-file-name={name}"],
                             capture_output=True, text=True, timeout=30)
    except OSError:
        return None
    path = out.stdout.strip()
    return path if path and Path(path).exists() and "/" in path else None


@pytest.mark.slow
@pytest.mark.parametrize("mode,runtime,env_opts", [
    ("address", "libasan.so", {"ASAN_OPTIONS": "detect_leaks=0"}),
    ("undefined", "libubsan.so",
     {"UBSAN_OPTIONS": "halt_on_error=1,print_stacktrace=1"}),
])
def test_sanitized_codec_fuzz_smoke(tmp_path, mode, runtime, env_opts):
    rt = _san_runtime(runtime)
    if rt is None:
        pytest.skip(f"no {runtime} runtime in this toolchain")
    r = subprocess.run(
        ["bash", str(SCRIPT), f"--sanitize={mode}",
         "--out-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    suffix = {"address": "asan", "undefined": "ubsan"}[mode]
    so = tmp_path / f"libme_native.{suffix}.so"
    assert so.exists(), r.stdout + r.stderr

    import os
    env = dict(os.environ,
               LD_PRELOAD=rt, ME_NATIVE_LIB=str(so),
               JAX_PLATFORMS="cpu", **env_opts)
    run = subprocess.run([sys.executable, "-c", _SAN_SMOKE],
                         capture_output=True, text=True, timeout=300,
                         env=env, cwd=str(REPO))
    assert run.returncode == 0, (
        f"sanitizer smoke failed under {mode}:\n"
        f"{run.stdout[-1000:]}\n{run.stderr[-3000:]}")
    assert "sanitizer smoke OK" in run.stdout
