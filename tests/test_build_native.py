"""scripts/build_native.sh smoke test: the native runtime must be
reproducible from source, not an unreproducible checked-in artifact.

Builds into a scratch directory (never swapping the package's .so under
a live process) and loads the result. Skips cleanly when the image has
no C++ toolchain — tier-1 must pass on a pure-Python box.
"""

import ctypes
import shutil
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "build_native.sh"

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="no C++ toolchain in this image",
)


def test_build_native_lib_from_source(tmp_path):
    r = subprocess.run(
        ["bash", str(SCRIPT), "--lib-only", "--force",
         "--out-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    so = tmp_path / "libme_native.so"
    assert so.exists(), r.stdout + r.stderr

    lib = ctypes.CDLL(str(so))
    # One symbol from each translation unit: the ring/sink layer
    # (me_native.cpp) and the lane engine (me_lanes.cpp).
    assert hasattr(lib, "me_ring_create")
    assert hasattr(lib, "me_lanes_create")
