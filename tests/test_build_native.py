"""scripts/build_native.sh smoke test: the native runtime must be
reproducible from source, not an unreproducible checked-in artifact.

Builds into a scratch directory (never swapping the package's .so under
a live process) and loads the result. Skips cleanly when the image has
no C++ toolchain — tier-1 must pass on a pure-Python box.
"""

import ctypes
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "build_native.sh"

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="no C++ toolchain in this image",
)


def test_build_native_lib_from_source(tmp_path):
    r = subprocess.run(
        ["bash", str(SCRIPT), "--lib-only", "--force",
         "--out-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    so = tmp_path / "libme_native.so"
    assert so.exists(), r.stdout + r.stderr

    lib = ctypes.CDLL(str(so))
    # One symbol from each translation unit: the ring/sink layer
    # (me_native.cpp) and the lane engine (me_lanes.cpp).
    assert hasattr(lib, "me_ring_create")
    assert hasattr(lib, "me_lanes_create")


# -- sanitizer-hardened variants ---------------------------------------------
#
# scripts/build_native.sh --sanitize={address,undefined} builds an
# instrumented lane library; the smoke below loads it into a fresh
# python process (ME_NATIVE_LIB override + the sanitizer runtime
# LD_PRELOADed — an uninstrumented interpreter must have the runtime
# resident before the .so's initializers run) and drives the codec
# round-trip fuzz + ring + lane-build surface through the normal
# wrapper stack. A sanitizer finding aborts the subprocess -> the test
# fails. The thread-sanitizer variant gets its own smoke below with
# genuinely concurrent load; because CPython is uninstrumented, its
# GIL handoffs read as races to TSan, so that smoke only fails on
# reports that implicate a libme_native frame.

_SAN_SMOKE = r"""
import ctypes, random, sys
from matching_engine_tpu import native as me_native
from matching_engine_tpu.domain import oprec

assert me_native.available(), "sanitized libme_native failed to load"
rng = random.Random(29)

def fuzz_records(n):
    rows = []
    for i in range(n):
        kind = rng.randrange(6)
        if kind < 3:   # submit (embedded NULs must round-trip)
            sym = bytes(rng.randrange(256)
                        for _ in range(rng.randrange(1, 64)))
            cid = bytes(rng.randrange(256)
                        for _ in range(rng.randrange(1, 256)))
            rows.append((1, rng.choice((1, 2)), rng.choice((0, 1, 2, 3)),
                         0 if rng.random() < .2 else rng.randrange(1, 1 << 20),
                         rng.randrange(1, 1 << 20), sym, cid, b""))
        elif kind < 5:  # cancel
            rows.append((2, 0, 0, 0, 0, b"", b"c%d" % i,
                         b"OID-%d" % rng.randrange(1, 500)))
        else:           # amend
            rows.append((3, 0, 0, 0, rng.randrange(1, 1000), b"",
                         b"c%d" % i, b"OID-%d" % rng.randrange(1, 500)))
    return rows

rows = fuzz_records(512)
arr = oprec.pack_records(rows)
out = me_native.oprec_to_gwop(arr.tobytes(), len(arr), 1000)
for i in range(len(arr)):
    op, side, otype, price, qty, sym, cid, oid = oprec.record_fields(arr[i])
    g = out[i]
    assert g.tag == 1000 + i
    assert (g.op, g.side, g.otype, g.price_q4, g.quantity) == (
        op, side, otype, price, qty), i
    for field, want in (("symbol", sym), ("client_id", cid),
                        ("order_id", oid)):
        off = getattr(me_native.MeGwOp, field).offset
        assert ctypes.string_at(ctypes.addressof(g) + off,
                                len(want)) == want, (i, field)

# Ragged / skewed payloads must reject, not overread.
for bad in (arr.tobytes()[:-7], arr.tobytes() + b"x"):
    try:
        me_native.oprec_to_gwop(bad, len(arr), 1)
    except RuntimeError:
        pass
    else:
        sys.exit("structural skew accepted")

# Ring round trip + the lane engine's build path (host-side only; the
# device step is jax's, not this .so's).
ring = me_native.LaneRing(2048)
assert ring.push_n(out, len(arr))
lanes = me_native.NativeLanes(num_symbols=16, batch=8, fill_inline=4,
                              max_fills=64)
recs, n = ring.pop_batch_raw(len(arr), 0)
assert recs is not None and n == len(arr)
try:
    lanes.build(recs, n, True, True)
except RuntimeError:
    pass  # semantic reject (symbol-table exhaustion etc.) is fine —
          # the smoke asserts memory/UB safety, the parity suites
          # assert semantics
lanes.destroy()
print("sanitizer smoke OK")
"""


# -- thread-sanitizer concurrency smoke --------------------------------------
#
# The ASan/UBSan smokes above are single-threaded; races need actual
# concurrency. This drive is the production shape: N producer threads
# bulk-pushing into one GwRing against the single batching consumer
# (ctypes releases the GIL for every call, so the C sides genuinely
# overlap), then parallel per-thread lane builds (shared allocator /
# global state under watch). Payload integrity is asserted via the tag
# checksum so a lost or doubled record fails even without a TSan report.
#
# TSan verdict handling: CPython itself is uninstrumented, so reports
# whose every frame is interpreter-internal are GIL-handoff noise — the
# assertion below only fails on reports that name a libme_native/
# me_lanes frame. (CPython's GIL is pthread mutex+cond, which TSan
# intercepts, so in practice the clean tree produces zero reports.)
#
# Old-toolchain soundness: gcc-10-era libtsan does not intercept
# pthread_cond_clockwait, which the matching libstdc++ inlines into
# wait_for/wait_until — TSan then misses the mutex release inside the
# wait and reports phantom races (plus "double lock") on correctly
# locked code. When `nm` shows the runtime lacks the interceptor, an
# instrumented forwarding shim (clockwait -> timedwait, clock-delta
# converted) is preloaded so the happens-before edges are modeled;
# verified to both silence the phantom reports on the real GwRing and
# still catch a deliberately lock-stripped close().

_CLOCKWAIT_SHIM = r"""
#include <pthread.h>
#include <time.h>
extern "C" int pthread_cond_clockwait(pthread_cond_t *cond,
                                      pthread_mutex_t *mutex,
                                      clockid_t clockid,
                                      const struct timespec *abstime) {
  struct timespec now_src, now_real, abs_real;
  clock_gettime(clockid, &now_src);
  clock_gettime(CLOCK_REALTIME, &now_real);
  long long delta =
      (long long)(abstime->tv_sec - now_src.tv_sec) * 1000000000LL +
      (abstime->tv_nsec - now_src.tv_nsec);
  if (delta < 0) delta = 0;
  long long abs_ns =
      (long long)now_real.tv_sec * 1000000000LL + now_real.tv_nsec + delta;
  abs_real.tv_sec = abs_ns / 1000000000LL;
  abs_real.tv_nsec = abs_ns % 1000000000LL;
  return pthread_cond_timedwait(cond, mutex, &abs_real);
}
"""


def _tsan_preload(rt: str, tmp_path) -> str | None:
    """LD_PRELOAD chain for the TSan smoke: the runtime, plus the
    clockwait bridge when this libtsan lacks the interceptor. None if
    the shim is needed but cannot be built."""
    try:
        syms = subprocess.run(["nm", "-D", rt], capture_output=True,
                              text=True, timeout=60).stdout
    except OSError:
        syms = ""
    if "pthread_cond_clockwait" in syms:
        return rt
    src = tmp_path / "clockwait_shim.cpp"
    shim = tmp_path / "clockwait_shim.so"
    src.write_text(_CLOCKWAIT_SHIM)
    r = subprocess.run(
        ["g++", "-shared", "-fPIC", "-fsanitize=thread", "-O1",
         "-o", str(shim), str(src)],
        capture_output=True, text=True, timeout=120)
    if r.returncode != 0:
        return None
    return f"{rt}:{shim}"

_TSAN_SMOKE = r"""
import threading
from matching_engine_tpu import native as me_native
from matching_engine_tpu.domain import oprec

assert me_native.available(), "tsan libme_native failed to load"

N_PRODUCERS, BATCHES, BATCH = 4, 16, 32
TOTAL = N_PRODUCERS * BATCHES * BATCH

def gw_batch(tag_base):
    rows = [(1, 1 + (i & 1), 0, 1000 + i, 1 + (i & 7),
             b"SYM%d" % (i & 7), b"c%d" % (tag_base + i), b"")
            for i in range(BATCH)]
    arr = oprec.pack_records(rows)
    return me_native.oprec_to_gwop(arr.tobytes(), len(arr), tag_base)

# Phase 1: MPSC ring under contention. Capacity below TOTAL forces
# wraparound and full-ring retries while the consumer drains.
ring = me_native.LaneRing(1024)

def produce(p):
    for b in range(BATCHES):
        out = gw_batch((p * BATCHES + b) * BATCH)
        while not ring.push_n(out, BATCH):
            pass  # whole-batch-or-nothing: ring full, consumer behind

seen = 0
tagsum = 0
def consume():
    global seen, tagsum
    while True:
        recs, n = ring.pop_batch_raw(256, 2000, 200000)
        if recs is None:
            return  # closed + empty
        for i in range(n):
            tagsum += recs[i].tag
        seen += n

consumer = threading.Thread(target=consume)
producers = [threading.Thread(target=produce, args=(p,))
             for p in range(N_PRODUCERS)]
consumer.start()
for t in producers:
    t.start()
for t in producers:
    t.join()
ring.close()
consumer.join()
assert seen == TOTAL, (seen, TOTAL)
assert tagsum == TOTAL * (TOTAL - 1) // 2, tagsum
ring.destroy()

# Phase 2: parallel lane builds, one engine per thread — nothing is
# logically shared, so any TSan report here is allocator/global state.
def lane_work(t):
    lanes = me_native.NativeLanes(num_symbols=8, batch=8, fill_inline=4,
                                  max_fills=64)
    for b in range(BATCHES):
        out = gw_batch((t * BATCHES + b) * BATCH)
        try:
            lanes.build(out, 8, True, True)
        except RuntimeError:
            pass  # semantic reject is fine; the smoke asserts race-freedom
    lanes.destroy()

workers = [threading.Thread(target=lane_work, args=(t,)) for t in range(4)]
for t in workers:
    t.start()
for t in workers:
    t.join()
print("tsan smoke OK")
"""

_NATIVE_FRAME_MARKERS = ("libme_native", "me_lanes", "me_native.cpp",
                         "me_gwring", "GwRing")


@pytest.mark.slow
def test_sanitized_tsan_concurrent_ring_and_lane_smoke(tmp_path):
    rt = _san_runtime("libtsan.so")
    if rt is None:
        pytest.skip("no libtsan runtime in this toolchain")
    preload = _tsan_preload(rt, tmp_path)
    if preload is None:
        pytest.skip("libtsan lacks the pthread_cond_clockwait "
                    "interceptor and the bridge shim failed to build")
    r = subprocess.run(
        ["bash", str(SCRIPT), "--sanitize=thread",
         "--out-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    so = tmp_path / "libme_native.tsan.so"
    assert so.exists(), r.stdout + r.stderr

    import os
    env = dict(os.environ,
               LD_PRELOAD=preload, ME_NATIVE_LIB=str(so),
               JAX_PLATFORMS="cpu",
               TSAN_OPTIONS="halt_on_error=0 exitcode=66")
    run = subprocess.run([sys.executable, "-c", _TSAN_SMOKE],
                         capture_output=True, text=True, timeout=600,
                         env=env, cwd=str(REPO))
    # exitcode 66 = TSan saw *some* report; only interpreter-internal
    # noise is tolerated, so gate on the smoke completing and on no
    # report naming a native frame.
    assert run.returncode in (0, 66), (
        f"tsan smoke crashed (rc={run.returncode}):\n"
        f"{run.stdout[-1000:]}\n{run.stderr[-3000:]}")
    assert "tsan smoke OK" in run.stdout, (
        f"{run.stdout[-1000:]}\n{run.stderr[-3000:]}")
    native_reports = [
        block for block in run.stderr.split("WARNING: ThreadSanitizer")[1:]
        if any(m in block for m in _NATIVE_FRAME_MARKERS)
    ]
    assert not native_reports, (
        "TSan reported a race implicating libme_native:\n"
        + "\n---\n".join(b[:4000] for b in native_reports))


def _san_runtime(name: str) -> str | None:
    """Resolve the sanitizer runtime for LD_PRELOAD, or None."""
    try:
        out = subprocess.run(["g++", f"-print-file-name={name}"],
                             capture_output=True, text=True, timeout=30)
    except OSError:
        return None
    path = out.stdout.strip()
    return path if path and Path(path).exists() and "/" in path else None


@pytest.mark.slow
@pytest.mark.parametrize("mode,runtime,env_opts", [
    ("address", "libasan.so", {"ASAN_OPTIONS": "detect_leaks=0"}),
    ("undefined", "libubsan.so",
     {"UBSAN_OPTIONS": "halt_on_error=1,print_stacktrace=1"}),
])
def test_sanitized_codec_fuzz_smoke(tmp_path, mode, runtime, env_opts):
    rt = _san_runtime(runtime)
    if rt is None:
        pytest.skip(f"no {runtime} runtime in this toolchain")
    r = subprocess.run(
        ["bash", str(SCRIPT), f"--sanitize={mode}",
         "--out-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    suffix = {"address": "asan", "undefined": "ubsan"}[mode]
    so = tmp_path / f"libme_native.{suffix}.so"
    assert so.exists(), r.stdout + r.stderr

    import os
    env = dict(os.environ,
               LD_PRELOAD=rt, ME_NATIVE_LIB=str(so),
               JAX_PLATFORMS="cpu", **env_opts)
    run = subprocess.run([sys.executable, "-c", _SAN_SMOKE],
                         capture_output=True, text=True, timeout=300,
                         env=env, cwd=str(REPO))
    assert run.returncode == 0, (
        f"sanitizer smoke failed under {mode}:\n"
        f"{run.stdout[-1000:]}\n{run.stderr[-3000:]}")
    assert "sanitizer smoke OK" in run.stdout
