"""Observability subsystem tests (utils/obs.py).

Three layers under test:
- unit: FlightRecorder ring bounds, SIGUSR2 dump, dump-on-error rate
  limit; Prometheus rendering; ObsServer endpoints; DispatchTimeline.
- e2e: a real server (build_server) on BOTH serving paths — pure Python
  and --native-lanes — scraped over HTTP, asserting the per-stage
  latency histograms and queue-depth gauges are present and non-zero,
  and that SIGUSR2 dumps a flight-recorder JSON containing the most
  recent dispatches.
- lint: every metric name in docs/OPERATIONS.md's Observability table
  must be emitted by the code (docs and registry must not drift).
"""

import json
import os
import pathlib
import re
import signal
import time
import urllib.request

import grpc
import pytest

from matching_engine_tpu import native as me_native
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.utils.metrics import Metrics
from matching_engine_tpu.utils import obs as obs_module
from matching_engine_tpu.utils.obs import (
    DispatchTimeline,
    FlightRecorder,
    ObsServer,
    record_dispatch_error,
    render_prometheus,
)

CFG = EngineConfig(num_symbols=8, capacity=16, batch=4)


# -- unit: flight recorder ---------------------------------------------------


def test_flight_recorder_ring_is_bounded():
    r = FlightRecorder(capacity=4)
    for i in range(10):
        r.record({"kind": "dispatch", "i": i})
    snap = r.snapshot()
    assert len(r) == 4 and len(snap) == 4
    # Oldest overwritten: only the newest four survive, in order.
    assert [e["i"] for e in snap] == [6, 7, 8, 9]
    assert all("wall_ts" in e and "seq" in e for e in snap)


def test_flight_recorder_dump_and_sigusr2(tmp_path):
    d = str(tmp_path / "flight")
    r = FlightRecorder(capacity=8, dump_dir=d)
    r.record({"kind": "dispatch", "ops": 3})
    assert r.install_sigusr2()
    try:
        os.kill(os.getpid(), signal.SIGUSR2)
        path = None
        for _ in range(200):  # handler runs at the next bytecode boundary
            files = list(pathlib.Path(d).glob("flight_*_sigusr2.json"))
            if files:
                path = files[0]
                break
            time.sleep(0.01)
        assert path is not None, "SIGUSR2 produced no dump"
        doc = json.loads(path.read_text())
        assert doc["reason"] == "sigusr2"
        assert [e["kind"] for e in doc["entries"]] == ["dispatch"]
    finally:
        r.uninstall_sigusr2()


def _wait_for_dumps(d, pattern="flight_*.json", timeout_s=3.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        files = list(pathlib.Path(d).glob(pattern))
        if files:
            return files
        time.sleep(0.01)
    return []


def test_flight_recorder_dump_on_error_is_rate_limited(tmp_path):
    d = str(tmp_path / "flight")
    m = Metrics()
    m.recorder = FlightRecorder(dump_dir=d, error_dump_interval_s=1000.0)
    record_dispatch_error(m, "unit", RuntimeError("boom"))
    # Error dumps run on a background thread (callers hold the dispatch
    # lock): wait for the write to land.
    files = _wait_for_dumps(d, "flight_*_dispatch-error.json")
    assert len(files) == 1, "first dispatch error must dump"
    doc = json.loads(files[0].read_text())
    assert doc["entries"][-1]["kind"] == "error"
    assert "boom" in doc["entries"][-1]["error"]
    # Second error inside the rate-limit window: recorded, not dumped
    # (dump_on_error refuses synchronously — no thread to wait on).
    assert not m.recorder.dump_on_error()
    record_dispatch_error(m, "unit", RuntimeError("boom2"))
    assert len(list(pathlib.Path(d).glob("flight_*.json"))) == 1
    assert len(m.recorder) == 2


def test_flight_recorder_dump_without_dir_is_noop():
    r = FlightRecorder()
    r.record({"kind": "dispatch"})
    assert r.dump("shutdown") is None  # ring still live for /flightrecorder
    assert len(r) == 1


# -- unit: timeline + exposition ---------------------------------------------


def test_timeline_feeds_stage_histograms_and_recorder():
    m = Metrics()
    m.recorder = FlightRecorder(capacity=4)
    t0 = time.perf_counter()
    tl = DispatchTimeline("python", 5, t_enqueue=t0 - 0.001)
    tl.shape = "sparse"
    tl.stamp_build()
    tl.stamp_issue()
    tl.stamp_decode()
    tl.stamp_publish()
    tl.counters = {"fills": 2}
    tl.finish(m)
    _, gauges = m.snapshot()
    for stage in ("stage_queue_wait_us", "stage_lane_build_us",
                  "stage_device_dispatch_us", "stage_completion_decode_us",
                  "stage_stream_publish_us"):
        assert f"{stage}_p50" in gauges, stage
    assert gauges["stage_queue_wait_us_p50"] >= 1000  # the 1ms enqueue gap
    (entry,) = m.recorder.snapshot()
    assert entry["kind"] == "dispatch" and entry["path"] == "python"
    assert entry["counters"] == {"fills": 2}
    assert set(entry["stages_us"]) >= {"stage_queue_wait_us",
                                       "stage_lane_build_us"}


def test_timeline_error_records_and_dumps(tmp_path):
    m = Metrics()
    m.recorder = FlightRecorder(dump_dir=str(tmp_path / "f"),
                                error_dump_interval_s=0.0)
    tl = DispatchTimeline("gateway", 2)
    tl.finish(m, error=RuntimeError("device fell over"))
    (entry,) = m.recorder.snapshot()
    assert entry["kind"] == "dispatch_error"
    assert "device fell over" in entry["error"]
    assert _wait_for_dumps(tmp_path / "f"), \
        "fatal dispatch error must dump a post-mortem"


def test_render_prometheus_names_and_types():
    m = Metrics()
    m.inc("orders_accepted", 3)
    m.set_gauge("queue_depth", 7)
    for v in (1.0, 2.0, 3.0):
        m.observe("lat_us", v)
    m.ema_gauge("lat_us", 2.0)
    text = render_prometheus(m)
    assert "# TYPE me_orders_accepted_total counter" in text
    assert "me_orders_accepted_total 3" in text
    assert "# TYPE me_queue_depth gauge" in text
    assert "me_queue_depth 7" in text
    # Window percentiles as derived gauges; the EMA is suffix-separated.
    assert "me_lat_us_p50" in text and "me_lat_us_p99" in text
    assert "me_lat_us_ema" in text
    assert re.search(r"^me_lat_us ", text, re.M) is None  # no bare collision


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode()


def _parse_prom(text):
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, val = line.rsplit(" ", 1)
        out[name] = float(val)
    return out


def test_obs_server_endpoints():
    m = Metrics()
    m.inc("dispatches", 2)
    rec = FlightRecorder()
    rec.record({"kind": "dispatch", "ops": 1})
    ready = {"v": True}
    obs = ObsServer(m, recorder=rec, ready_fn=lambda: ready["v"],
                    port=0, host="127.0.0.1")
    obs.start()
    try:
        assert _get(obs.port, "/healthz")[0] == 200
        assert _get(obs.port, "/readyz")[0] == 200
        code, body = _get(obs.port, "/metrics")
        assert code == 200 and _parse_prom(body)["me_dispatches_total"] == 2
        code, body = _get(obs.port, "/flightrecorder")
        assert code == 200 and json.loads(body)[0]["ops"] == 1
        ready["v"] = False  # drain began: readiness flips, liveness holds
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(obs.port, "/readyz")
        assert ei.value.code == 503
        assert _get(obs.port, "/healthz")[0] == 200
    finally:
        obs.close()


# -- e2e: both serving paths -------------------------------------------------


class _Harness:
    def __init__(self, db_path, flight_dir, **kw):
        self.server, self.port, self.parts = build_server(
            "127.0.0.1:0", db_path, CFG, window_ms=1.0, log=False,
            flight_dir=flight_dir, **kw)
        self.server.start()
        self.obs = ObsServer(self.parts["metrics"],
                             recorder=self.parts["recorder"],
                             port=0, host="127.0.0.1")
        self.obs.start()
        self.channel = grpc.insecure_channel(f"127.0.0.1:{self.port}")
        self.stub = MatchingEngineStub(self.channel)

    def close(self):
        self.obs.close()
        self.channel.close()
        shutdown(self.server, self.parts)


def _submit(stub, client, side, price, qty=5):
    return stub.SubmitOrder(
        pb2.OrderRequest(client_id=client, symbol="OBS", order_type=pb2.LIMIT,
                         side=side, price=price, scale=4, quantity=qty),
        timeout=10)


def _drive_and_scrape(hs):
    for i in range(4):
        assert _submit(hs.stub, "maker", pb2.SELL, 10000 + i).success
        assert _submit(hs.stub, "taker", pb2.BUY, 10100 + i).success
    hs.parts["sink"].flush()
    code, body = _get(hs.obs.port, "/metrics")
    assert code == 200
    assert _get(hs.obs.port, "/healthz")[0] == 200
    return _parse_prom(body)


# Present-and-nonzero on every serving path (acceptance criterion).
_CORE_STAGES = ("stage_edge_ingress_us", "stage_queue_wait_us",
                "stage_lane_build_us", "stage_device_dispatch_us",
                "stage_completion_decode_us")


def _assert_stage_ledger(prom, extra_stages=(), gauges=()):
    for stage in _CORE_STAGES + tuple(extra_stages):
        assert f"me_{stage}_p50" in prom, f"missing {stage}_p50"
        assert f"me_{stage}_p99" in prom, f"missing {stage}_p99"
        assert prom[f"me_{stage}_p50"] > 0, f"{stage} histogram empty"
    # Publish is stamped even with no subscribers; duration may round to
    # ~0 on a fast host, so presence is the assertion.
    assert "me_stage_stream_publish_us_p50" in prom
    for g in gauges:
        assert f"me_{g}" in prom, f"missing gauge {g}"


def test_e2e_python_path_metrics_and_flight_dump(tmp_path):
    hs = _Harness(str(tmp_path / "e2e.db"), str(tmp_path / "flight"),
                  native=False)
    try:
        prom = _drive_and_scrape(hs)
        # Pure-Python sink commits SQLite on its own thread: the commit
        # stage must have real samples after the flush barrier.
        _assert_stage_ledger(prom, extra_stages=("stage_sink_commit_us",),
                             gauges=("queue_depth", "inflight_dispatches",
                                     "sink_queue_depth"))
        assert prom["me_dispatches_total"] >= 1
        # submit_rpc_us collision fixed: EMA and percentiles coexist
        # under distinct names, no bare submit_rpc_us gauge.
        assert "me_submit_rpc_us_ema" in prom
        assert "me_submit_rpc_us_p99" in prom
        assert "me_submit_rpc_us" not in prom
        # SIGUSR2 on the serving process dumps the recent dispatches.
        rec = hs.parts["recorder"]
        assert rec.install_sigusr2()
        try:
            os.kill(os.getpid(), signal.SIGUSR2)
            path = None
            for _ in range(200):
                files = list(
                    (tmp_path / "flight").glob("flight_*_sigusr2.json"))
                if files:
                    path = files[0]
                    break
                time.sleep(0.01)
        finally:
            rec.uninstall_sigusr2()
        assert path is not None, "SIGUSR2 produced no flight dump"
        doc = json.loads(path.read_text())
        dispatches = [e for e in doc["entries"] if e["kind"] == "dispatch"]
        assert dispatches, "dump holds no dispatch summaries"
        assert dispatches[-1]["path"] == "python"
        assert dispatches[-1]["stages_us"].get("stage_lane_build_us", 0) > 0
    finally:
        hs.close()


@pytest.mark.skipif(not me_native.available(),
                    reason="native runtime not built")
def test_e2e_native_lanes_metrics(tmp_path):
    hs = _Harness(str(tmp_path / "lanes.db"), str(tmp_path / "flight"),
                  native_lanes=True)
    try:
        prom = _drive_and_scrape(hs)
        _assert_stage_ledger(prom, gauges=("inflight_ops",
                                           "inflight_dispatches"))
        assert prom["me_dispatches_total"] >= 1
        assert prom["me_orders_accepted_total"] >= 8
        # The fastest path is no longer the blindest: flight entries
        # carry the native aux counters and per-stage latencies.
        code, body = _get(hs.obs.port, "/flightrecorder")
        assert code == 200
        dispatches = [e for e in json.loads(body)
                      if e["kind"] == "dispatch"]
        assert dispatches and dispatches[-1]["path"] == "native-lanes"
        assert "engine_ops" in dispatches[-1]["counters"]
    finally:
        hs.close()


@pytest.mark.skipif(not me_native.available(),
                    reason="native runtime not built")
def test_native_lanes_profile_annotations_and_stamps(tmp_path):
    """--profile-dir satellite: the native-lanes dispatch loop runs its
    lane build/decode inside trace annotations (tracing.span), so a
    device trace captures per-batch boundaries in this mode too; the
    stage ledger stamps ride the same dispatch."""
    from matching_engine_tpu.server.native_lanes import (
        NativeLanesRunner,
        pack_record_batch,
    )
    from matching_engine_tpu.utils.tracing import trace

    cfg = EngineConfig(num_symbols=4, capacity=16, batch=8,
                       max_fills=1 << 12)
    r = NativeLanesRunner(cfg)
    recs, n = pack_record_batch([
        (1, 1, 1, 0, 10_000, 5, "S0", "c1", ""),
        (2, 1, 2, 0, 10_000, 5, "S1", "c2", ""),
    ])
    got = {}

    def on_finish(result, error):
        got["result"], got["error"] = result, error

    tl = DispatchTimeline("native-lanes", n)
    d = tmp_path / "prof"
    with trace(str(d)):
        r.dispatch_records(recs, n, on_finish, timeline=tl)
        r.finish_pending()
    assert got["error"] is None and got["result"] is not None
    assert list(d.rglob("*")), "no trace files from the native-lanes loop"
    tl.finish(r.metrics)  # the edge's job; here: fold stamps for assert
    _, gauges = r.metrics.snapshot()
    assert gauges["stage_lane_build_us_p50"] > 0
    assert gauges["stage_completion_decode_us_p50"] > 0
    assert tl.shape in ("sparse", "dense") and tl.waves >= 1


# -- lint: OPERATIONS.md table <-> registry ----------------------------------


def test_operations_doc_metric_table_matches_registry():
    """Every row of the Observability metric table must name a metric the
    code actually emits — the drift guard the table's stability promise
    rests on. Checks the emit call sites (inc/set_gauge/ema_gauge/
    observe/Timer literals, the obs.py stage constants, and the native
    aux counter mapping)."""
    root = pathlib.Path(__file__).resolve().parents[1]
    doc = (root / "docs" / "OPERATIONS.md").read_text()
    rows = re.findall(
        r"^\|\s*`([a-z0-9_]+)`\s*\|\s*(counter|gauge|ema|histogram)\s*\|",
        doc, re.M)
    assert len(rows) >= 40, "Observability metric table missing or shrunk"
    src = "\n".join(p.read_text()
                    for p in (root / "matching_engine_tpu").rglob("*.py"))

    def emitted(name: str, typ: str) -> bool:
        if typ == "counter":
            # Direct inc("...") or the native aux-counter mapping tuples.
            pats = [rf'inc\(\s*"{name}"', rf'"{name}"\)']
        elif typ == "gauge":
            pats = [rf'set_gauge\(\s*"{name}"']
        elif typ == "ema":
            assert name.endswith("_ema"), f"{name}: ema rows need _ema"
            base = name[:-len("_ema")]
            pats = [rf'ema_gauge\(\s*"{base}"', rf'Timer\([^)]*"{base}"']
        else:  # histogram (exported as <name>_p50/_p99)
            pats = [rf'observe\(\s*"{name}"', rf'Timer\([^)]*"{name}"',
                    rf'STAGE_[A-Z_]+ = "{name}"']
        return any(re.search(p, src, re.S) for p in pats)

    missing = [f"{n} ({t})" for n, t in rows if not emitted(n, t)]
    assert not missing, f"documented but never emitted: {missing}"
    # And the reverse for the stage ledger: every pipeline stage obs.py
    # defines must be documented as a histogram row.
    documented = {n for n, t in rows if t == "histogram"}
    undocumented = [s for s in obs_module.STAGES if s not in documented]
    assert not undocumented, f"stages missing from the table: {undocumented}"


def test_warn_rate_limited_suppresses_and_counts(capsys):
    """publish_result's sink/hub failure path logs through this: one
    line per interval per key, with the suppressed count folded into
    the next emission — a flapping sink fails at batch rate and must
    not print at batch rate."""
    from matching_engine_tpu.utils import obs as obs_mod

    key = f"test-key-{os.getpid()}"
    for _ in range(50):
        obs_mod.warn_rate_limited(key, "boom", interval_s=3600)
    out = capsys.readouterr().out
    assert out.count("boom") == 1
    # Force the window open: the next emission carries the count.
    with obs_mod._warn_lock:
        obs_mod._warn_last[key] = 0.0
    obs_mod.warn_rate_limited(key, "boom", interval_s=3600)
    out = capsys.readouterr().out
    assert "(+49 suppressed)" in out
