"""Worker for the REAL 2-process multihost test (tests/test_multiprocess.py).

Launched twice (process_id 0 and 1), each with 4 virtual CPU devices, a
localhost coordinator, and an independent EngineRunner over the SAME global
8-device mesh. Exercises the whole multi-process serving contract:

- jax.distributed bootstrap through parallel.multihost.initialize,
- host-major mesh + local_symbol_slice ownership,
- slot allocation confined to the local symbol range,
- per-host dispatches (DIFFERENT counts per process — no cross-host
  lockstep is required because the engine step has no collectives),
- decode from addressable shards only (parallel/hostlocal.py),
- book snapshots served from the local shard,
- the host-sharded checkpoint save/restore round trip.

Writes ok-<pid>.json on success; any assertion kills the process (the
parent asserts both exit codes).
"""

import json
import os
import sys


def main() -> None:
    port, pid_s, outdir = sys.argv[1], sys.argv[2], sys.argv[3]
    pid = int(pid_s)
    # Optional scale knobs (round-5: the 4-process variant drives these).
    nprocs = int(sys.argv[4]) if len(sys.argv) > 4 else 2
    dpp = int(sys.argv[5]) if len(sys.argv) > 5 else 4  # devices/process
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={dpp}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from matching_engine_tpu.parallel.multihost import (
        initialize,
        local_symbol_slice,
        make_multihost_mesh,
    )

    assert initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=nprocs,
        process_id=pid,
    )
    assert jax.process_count() == nprocs
    assert len(jax.devices()) == nprocs * dpp
    assert len(jax.local_devices()) == dpp

    mesh = make_multihost_mesh()
    S = nprocs * dpp  # one symbol per device shard
    sl = local_symbol_slice(mesh, S)
    assert sl.stop - sl.start == dpp

    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.engine.kernel import FILLED, OP_SUBMIT
    from matching_engine_tpu.server.engine_runner import (
        EngineOp,
        EngineRunner,
        OrderInfo,
    )

    cfg = EngineConfig(num_symbols=S, capacity=16, batch=4, max_fills=256)
    runner = EngineRunner(cfg, mesh=mesh)
    assert (runner._slot_lo, runner._slot_hi) == (sl.start, sl.stop)

    mysyms = [f"S{g}" for g in range(sl.start, sl.stop)]

    def submit(sym, side, price, qty):
        slot = runner.slot_acquire(sym)
        assert slot is not None and sl.start <= slot < sl.stop, (sym, slot)
        n, oid_s = runner.assign_oid()
        info = OrderInfo(
            oid=n, order_id=oid_s, client_id=f"c{pid}-s{side}", symbol=sym,
            side=side, otype=0, price_q4=price, quantity=qty, remaining=qty,
            status=0, handle=runner.assign_handle(),
        )
        return EngineOp(OP_SUBMIT, info)

    # DIFFERENT dispatch counts per process: the step has no collectives,
    # so hosts drain their queues independently — prove it.
    total_fills = 0
    ndisp = 2 + pid
    for d in range(ndisp):
        ops = []
        for sym in mysyms:
            ops.append(submit(sym, 1, 10_000 + d, 5))
            ops.append(submit(sym, 2, 10_000 + d, 5))
        res = runner.run_dispatch(ops)
        assert res.fill_count == len(mysyms), (d, res.fill_count)
        # The SELL takers fill; the BUY makers' own submit outcome is NEW
        # (they rested first, then matched within the same dispatch), and
        # the maker bookkeeping marks their directory entries FILLED.
        takers = [oc for oc in res.outcomes if oc.op.info.side == 2]
        assert takers and all(oc.status == FILLED for oc in takers)
        assert all(i.status == FILLED
                   for oc in res.outcomes for i in [oc.op.info])
        # Market data decoded from the local top-of-book block only.
        assert {m.symbol for m in res.market_data} == set(mysyms)
        total_fills += res.fill_count

    # A resting order: snapshot must come from the local shard.
    runner.run_dispatch([submit(mysyms[0], 1, 9_000, 3)])
    bids, asks = runner.book_snapshot(mysyms[0])
    assert [q for _, q in bids] == [3] and asks == []

    # Host-sharded checkpoint round trip (barrier so both shards exist).
    from jax.experimental import multihost_utils

    from matching_engine_tpu.utils.checkpoint import (
        restore_runner,
        save_checkpoint,
    )

    ck = os.path.join(outdir, "ckpt")
    with runner._dispatch_lock:
        save_checkpoint(ck, runner)
    multihost_utils.sync_global_devices("ckpt-written")
    assert os.path.isdir(os.path.join(ck, f"host-{pid:04d}"))

    r2 = EngineRunner(cfg, mesh=mesh)
    restore_runner(r2, ck, storage=None)
    bids2, asks2 = r2.book_snapshot(mysyms[0])
    assert [q for _, q in bids2] == [3] and asks2 == []
    assert set(r2.orders_by_id) == set(runner.orders_by_id)

    with open(os.path.join(outdir, f"ok-{pid}.json"), "w") as f:
        json.dump({"pid": pid, "fills": total_fills,
                   "slice": [sl.start, sl.stop]}, f)


if __name__ == "__main__":
    main()
