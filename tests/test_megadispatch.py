"""Megadispatch (coalesced multi-batch device scan) parity + compaction.

The megadispatch path (kernel.engine_step_mega via
engine_runner._prepare_mega, coalesced by the dispatcher's adaptive
controller) must be INDISTINGUISHABLE from the serial per-wave schedule:
same fills, statuses, storage rows, stream protos, feed seq lines, books,
directories, and allocators — `--megadispatch-max-waves 1` (the default)
IS the serial schedule, so M>1 is pinned bit-identical to it here on both
kernels. Plus unit coverage for the device-side completion compaction
(kernel.compact_rows under vmap; zero fills / all-lanes-full / mid-batch
cancel at the mega-step level) and the pipelined-FIFO interleave
(a megadispatch staged behind a normal dispatch decodes in order).
"""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import (
    batch_view,
    build_batch_arrays,
    decode_step_mega,
    decode_step_packed,
    snapshot_books,
)
from matching_engine_tpu.engine.kernel import (
    BUY,
    CANCELED,
    FILLED,
    NEW,
    OP_AMEND,
    OP_CANCEL,
    OP_SUBMIT,
    SELL,
    compact_rows,
    engine_step_mega,
    engine_step_packed,
    mega_result_cap,
)
from matching_engine_tpu.engine.harness import HostOrder
from matching_engine_tpu.server.dispatcher import BatchDispatcher
from matching_engine_tpu.server.engine_runner import (
    EngineOp,
    EngineRunner,
    OrderInfo,
)

S, CAP, B = 4, 16, 4


def make_cfg(kernel: str) -> EngineConfig:
    return EngineConfig(num_symbols=S, capacity=CAP, batch=B,
                        max_fills=1 << 10, kernel=kernel)


# -- unit: the prefix-sum gather compaction ----------------------------------


def _ref_compact(mask, cols, out_len):
    idx = np.nonzero(mask)[0][:out_len]
    packed = []
    for c in cols:
        buf = np.zeros(out_len, dtype=np.int32)
        buf[:len(idx)] = np.asarray(c)[idx]
        packed.append(buf)
    return packed, min(int(mask.sum()), out_len)


@pytest.mark.parametrize("case", ["zero", "full", "random", "truncate"])
def test_compact_rows_under_vmap(case):
    """compact_rows is the device-side completion/fill packer inside the
    mega scan's vmap/scan nest: pin it against a numpy reference under
    jax.vmap for the degenerate shapes the kernel meets — no masked rows
    (zero fills), every row masked (all lanes full), mixed, and more
    rows than the output buffer (trash-slot truncation)."""
    rng = np.random.default_rng(3)
    n, out_len, batch = 32, 16, 5
    if case == "zero":
        masks = np.zeros((batch, n), dtype=bool)
    elif case == "full":
        masks = np.ones((batch, n), dtype=bool)
        out_len = n
    elif case == "truncate":
        masks = np.ones((batch, n), dtype=bool)  # 32 rows into 16 slots
    else:
        masks = rng.random((batch, n)) < 0.4
    vals = rng.integers(1, 1000, size=(batch, 2, n)).astype(np.int32)

    packed, counts = jax.vmap(
        lambda m, v: compact_rows(m, (v[0], v[1]), out_len)
    )(jnp.asarray(masks), jnp.asarray(vals))

    for i in range(batch):
        ref_cols, ref_count = _ref_compact(masks[i], vals[i], out_len)
        assert int(counts[i]) == ref_count
        for got, ref in zip(packed, ref_cols):
            assert np.array_equal(np.asarray(got[i]), ref), (case, i)


# -- unit: mega step vs serial waves at the kernel boundary ------------------


def _serial_waves(cfg, arrays):
    book = init_book(cfg)
    out = []
    for arr in arrays:
        book, pout = engine_step_packed(cfg, book, arr)
        out.append(decode_step_packed(cfg, batch_view(arr), pout)[:3])
    return book, out


def _mega_waves(cfg, arrays):
    book = init_book(cfg)
    rcap = mega_result_cap(
        cfg, max(int(np.count_nonzero(a[:, :, 0])) for a in arrays))
    book, mout = engine_step_mega(cfg, book, np.stack(arrays), rcap)
    waves, _, _ = decode_step_mega(cfg, mout, len(arrays), rcap)
    return book, waves


def _assert_step_parity(cfg, orders):
    arrays = build_batch_arrays(cfg, orders)
    assert len(arrays) > 1, "stream must span multiple waves"
    book_a, serial = _serial_waves(cfg, arrays)
    book_b, mega = _mega_waves(cfg, arrays)
    assert serial == mega
    assert snapshot_books(book_a) == snapshot_books(book_b)
    return mega


@pytest.mark.parametrize("kernel", ["matrix", "sorted", "levels"])
def test_mega_step_zero_fills(kernel):
    """Non-crossing rests only: every wave's compacted fill log is empty
    and the completion rows still decode bit-identically."""
    cfg = make_cfg(kernel)
    orders = [
        HostOrder(sym=i % S, op=OP_SUBMIT, side=BUY if i % 2 else SELL,
                  price=9_000 - 50 * (i % 7) if i % 2 else 11_000 + 50 * (i % 7),
                  qty=3, oid=i + 1)
        for i in range(3 * S * B)
    ]
    mega = _assert_step_parity(cfg, orders)
    assert all(not fills for _, fills, _ in mega)
    assert all(r.filled == 0 for results, _, _ in mega for r in results)


@pytest.mark.parametrize("kernel", ["matrix", "sorted", "levels"])
def test_mega_step_all_lanes_full(kernel):
    """Every grid row of every wave carries a real op (the compaction's
    count == rcap edge) and the crossing flow produces fills in every
    wave."""
    cfg = make_cfg(kernel)
    orders = []
    oid = 0
    for w in range(3):
        for sym in range(S):
            for row in range(B):
                oid += 1
                side = BUY if (row + w) % 2 else SELL
                orders.append(HostOrder(
                    sym=sym, op=OP_SUBMIT, side=side, price=10_000,
                    qty=2, oid=oid))
    mega = _assert_step_parity(cfg, orders)
    assert all(len(results) == S * B for results, _, _ in mega)
    assert any(fills for _, fills, _ in mega)


@pytest.mark.parametrize("kernel", ["matrix", "sorted", "levels"])
def test_mega_step_mid_batch_cancel(kernel):
    """A maker partially filled in wave 1 and canceled mid-wave-2 (with
    more flow behind the cancel in the same wave): the scan's carry must
    replay the exact serial event order across the stacked waves."""
    cfg = make_cfg(kernel)
    orders = []
    oid = 0
    for sym in range(S):
        oid += 1
        maker = oid
        orders.append(HostOrder(sym=sym, op=OP_SUBMIT, side=BUY,
                                price=10_000, qty=10, oid=maker))
        for _ in range(B - 1):  # pad wave 1
            oid += 1
            orders.append(HostOrder(sym=sym, op=OP_SUBMIT, side=BUY,
                                    price=9_000, qty=1, oid=oid))
        oid += 1  # wave 2: partial fill of the maker...
        orders.append(HostOrder(sym=sym, op=OP_SUBMIT, side=SELL,
                                price=10_000, qty=4, oid=oid))
        orders.append(HostOrder(sym=sym, op=OP_CANCEL, side=BUY,
                                oid=maker))  # ...then cancel its remainder
        oid += 1  # and flow behind the cancel in the same wave
        orders.append(HostOrder(sym=sym, op=OP_SUBMIT, side=SELL,
                                price=9_000, qty=2, oid=oid))
    mega = _assert_step_parity(cfg, orders)
    # Wave 2 decodes the fill, then the cancel releasing remaining=6.
    results2 = mega[1][0]
    cancels = [r for r in results2 if r.status == CANCELED and r.remaining == 6]
    assert len(cancels) == S


# -- the serving-path parity oracle: M=4 vs M=1 over lifecycle fuzz ----------


def _lane_setup():
    from matching_engine_tpu.feed import FeedSequencer
    from matching_engine_tpu.server.streams import StreamHub
    from matching_engine_tpu.utils.metrics import Metrics

    m = Metrics()
    # Same fixed epoch on both sides: serialized stream protos (which
    # carry seq AND feed_epoch after hub publish) must compare bit-equal.
    hub = StreamHub(maxsize=4096, metrics=m,
                    sequencer=FeedSequencer(metrics=m, depth=4096,
                                            epoch=12345))
    return m, hub


def _drive(runner, hub, metrics, seed):
    """Lifecycle fuzz through the full python serving surface: submits
    across the collapsed (order_type, tif) codes, cancels and amends
    (valid + stale + wrong-client), published through the hub (feed seq
    stamping included) — a transcription of the dispatcher drain's
    on_finish path."""
    from matching_engine_tpu.server.dispatcher import publish_result

    rng = random.Random(seed)
    live: list[OrderInfo] = []
    out = []
    for _ in range(6):
        ops = []
        for _ in range(36):
            r = rng.random()
            if live and r < 0.18:
                info = rng.choice(live)
                ops.append(EngineOp(OP_CANCEL, info,
                                    cancel_requester=info.client_id))
                continue
            if live and r < 0.30:
                info = rng.choice(live)
                ops.append(EngineOp(OP_AMEND, info,
                                    amend_qty=rng.randrange(1, 12)))
                continue
            sym = f"S{rng.randrange(S)}"
            otype = rng.choice((0, 0, 0, 1, 2, 3, 4))
            assert runner.slot_acquire(sym) is not None
            num, oid = runner.assign_oid()
            qty = rng.randrange(1, 10)
            info = OrderInfo(
                oid=num, order_id=oid, client_id=f"c{num % 5}", symbol=sym,
                side=rng.choice((BUY, SELL)), otype=otype,
                price_q4=0 if otype in (1, 4)
                else 10_000 + rng.randrange(-6, 7),
                quantity=qty, remaining=qty, status=0,
                handle=runner.assign_handle())
            ops.append(EngineOp(OP_SUBMIT, info))
            if otype == 0:
                live.append(info)
        box = {}

        def on_finish(result, error):
            assert error is None, error
            publish_result(result, None, hub, metrics)
            box["r"] = result
            return None

        runner.dispatch_pipelined(ops, on_finish)
        runner.finish_pending()
        r = box["r"]
        out.append({
            "outcomes": [(o.op.info.order_id, o.op.op, o.status, o.filled,
                          o.remaining, o.error) for o in r.outcomes],
            "orders": list(r.storage_orders),
            "updates": list(r.storage_updates),
            "fills": list(r.storage_fills),
            "ou": [u.SerializeToString() for u in r.order_updates],
            "md": [u.SerializeToString() for u in r.market_data],
        })
        live = [i for i in live if i.status in (NEW, 1)]
    return out


@pytest.mark.parametrize("kernel", ["matrix", "sorted", "levels"])
def test_megadispatch_parity_lifecycle_fuzz(kernel):
    """M=4 serving output is bit-identical to the serial M=1 schedule:
    completions, storage rows, stream protos INCLUDING the stamped feed
    seq lines, final books, directories, and every allocator."""
    cfg = make_cfg(kernel)
    m1, hub1 = _lane_setup()
    m4, hub4 = _lane_setup()
    base = EngineRunner(cfg, m1, hub=hub1)
    mega = EngineRunner(cfg, m4, hub=hub4, megadispatch_max_waves=4)

    got1 = _drive(base, hub1, m1, seed=11)
    got4 = _drive(mega, hub4, m4, seed=11)
    for i, (a, b) in enumerate(zip(got1, got4)):
        for key in a:
            assert a[key] == b[key], f"dispatch {i}: {key} diverged"

    assert snapshot_books(base.book) == snapshot_books(mega.book)
    key = lambda i: (i.handle, i.oid, i.order_id, i.client_id, i.symbol,  # noqa: E731
                     i.side, i.otype, i.price_q4, i.quantity, i.remaining,
                     i.status)
    assert sorted(map(key, mega.orders_by_handle.values())) == \
        sorted(map(key, base.orders_by_handle.values()))
    assert mega.symbols == base.symbols
    assert mega.next_oid_num == base.next_oid_num
    assert mega._next_handle == base._next_handle
    assert mega._free_handles == base._free_handles
    assert mega._free_slots == base._free_slots

    # Feed seq lines: every (channel, key) domain advanced identically.
    seq1, seq4 = hub1.sequencer, hub4.sequencer
    doms1 = {k: r.last_seq for k, r in seq1._domains.items()}
    doms4 = {k: r.last_seq for k, r in seq4._domains.items()}
    assert doms1 == doms4 and doms1, "feed seq domains diverged"
    # And the mega run actually exercised the stacked path.
    counters, _ = m4.snapshot()
    assert counters.get("megadispatch_steps", 0) > 0
    assert counters["megadispatch_stacked_waves"] > \
        counters["megadispatch_steps"]


# -- pipelined-FIFO interleave ----------------------------------------------


def _submit(runner, symbol, side, price, qty):
    assert runner.slot_acquire(symbol) is not None
    num, oid = runner.assign_oid()
    return EngineOp(OP_SUBMIT, OrderInfo(
        oid=num, order_id=oid, client_id=f"c-side{side}", symbol=symbol,
        side=side, otype=0, price_q4=price, quantity=qty, remaining=qty,
        status=0, handle=runner.assign_handle()))


def test_mega_interleave_fifo_behind_normal_dispatch():
    """A megadispatch staged behind a normal (single-wave) dispatch
    decodes strictly after it, and the cross-dispatch match (the mega
    batch's SELLs consuming the first batch's resting BUY) produces the
    serial schedule's outcomes."""
    cfg = make_cfg("matrix")
    r = EngineRunner(cfg, megadispatch_max_waves=4, pipeline_inflight=4)
    log: list = []

    def collector(label):
        def on_finish(result, error):
            assert error is None, error

            def post():
                log.append((label, [(o.op.info.order_id, o.status)
                                    for o in result.outcomes]))
            return post
        return on_finish

    a = _submit(r, "X", BUY, 100, 2 * S * B)
    r.dispatch_pipelined([a], collector("normal"))
    assert r.has_pending
    # Multi-wave batch: 2*B sells on one symbol -> 2 waves -> mega path.
    sells = [_submit(r, "X", SELL, 100, 1) for _ in range(2 * B)]
    r.dispatch_pipelined(sells, collector("mega"))
    assert r.has_pending
    r.finish_pending()
    assert [e[0] for e in log] == ["normal", "mega"]
    assert log[0][1] == [(a.info.order_id, NEW)]
    assert all(st == FILLED for _, st in log[1][1])
    assert a.info.remaining == 2 * S * B - 2 * B
    c, _ = r.metrics.snapshot()
    assert c.get("megadispatch_steps", 0) == 1
    assert c["megadispatch_stacked_waves"] == 2


def test_dispatcher_controller_coalesces_deep_queue():
    """Flood the python dispatch queue past max_batch while megadispatch
    is enabled: the controller must coalesce (me_megadispatch_* move),
    the runner must stack waves, and every future still resolves with
    the serial schedule's outcome."""
    cfg = EngineConfig(num_symbols=S, capacity=128, batch=B,
                       max_fills=1 << 10)  # capacity holds all 64 rests
    r = EngineRunner(cfg, megadispatch_max_waves=4)
    d = BatchDispatcher(r, window_ms=20.0, max_batch=8,
                        mega_max_waves=4, mega_latency_us=10_000_000.0)
    try:
        # Enqueue before the window closes: one deep backlog on symbol X.
        futs = [d.submit(_submit(r, "X", BUY, 100 + i, 1))
                for i in range(64)]
        outcomes = [f.result(timeout=30) for f in futs]
        assert all(o.status == NEW for o in outcomes)
    finally:
        d.close()
    c, g = r.metrics.snapshot()
    assert c.get("megadispatch_coalesced", 0) >= 1
    assert c["megadispatch_coalesced_ops"] >= 16
    assert c.get("megadispatch_steps", 0) >= 1
    assert g.get("megadispatch_m", 1) >= 1
