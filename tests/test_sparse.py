"""Sparse-dispatch parity: bit-equal with the dense path by construction.

The sparse path scatters K real ops onto the dense grid on device and
gathers per-op results back (engine/sparse.py); these tests replay the
same random streams (submits, cancels, MARKET sweeps, overflow pressure)
through both paths and assert identical books, per-op outcomes, and fill
logs — the same oracle discipline as tests/test_kernel_parity.py.
"""

import numpy as np
import pytest

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import (
    build_batches,
    decode_results,
    random_order_stream,
)
from matching_engine_tpu.engine.kernel import engine_step
from matching_engine_tpu.engine.sparse import (
    bucket,
    build_sparse,
    engine_step_sparse,
    unpack_sparse_output,
)

CFG = EngineConfig(num_symbols=16, capacity=32, batch=8, max_fills=1 << 12)


def run_dense(cfg, stream):
    book = init_book(cfg)
    results, fills = [], []
    for batch in build_batches(cfg, stream):
        book, out = engine_step(cfg, book, batch)
        results.extend(
            (r.oid, r.sym, r.status, r.filled, r.remaining)
            for r in decode_results(batch, out.status, out.filled,
                                    out.remaining)
        )
        n = int(out.fill_count)
        fills.extend(zip(
            np.asarray(out.fill_sym[:n]).tolist(),
            np.asarray(out.fill_taker_oid[:n]).tolist(),
            np.asarray(out.fill_maker_oid[:n]).tolist(),
            np.asarray(out.fill_price[:n]).tolist(),
            np.asarray(out.fill_qty[:n]).tolist(),
        ))
    return book, results, fills


def run_sparse(cfg, stream):
    from matching_engine_tpu.engine.sparse import decode_sparse_step

    book = init_book(cfg)
    results, fills = [], []
    for sparse, n in build_sparse(cfg, stream):
        book, out = engine_step_sparse(cfg, book, sparse)
        # The real serving decode: exercises both the inline-fill fast
        # path and the over-inline full-buffer fetch.
        r, f, _overflow, _dec = decode_sparse_step(sparse, n, out)
        results.extend((x.oid, x.sym, x.status, x.filled, x.remaining)
                       for x in r)
        fills.extend((x.sym, x.taker_oid, x.maker_oid, x.price_q4,
                      x.quantity) for x in f)
    return book, results, fills


@pytest.mark.parametrize("kernel", ["matrix", "sorted"])
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_sparse_matches_dense(seed, kernel):
    cfg = EngineConfig(num_symbols=16, capacity=32, batch=8,
                       max_fills=1 << 12, kernel=kernel)
    stream = random_order_stream(
        cfg.num_symbols, 6 * cfg.num_symbols * cfg.batch, seed=seed,
        cancel_p=0.15, market_p=0.1, price_base=10_000, price_levels=12,
        price_step=2, qty_max=30,
    )
    dbook, dres, dfills = run_dense(cfg, stream)
    sbook, sres, sfills = run_sparse(cfg, stream)
    for f in dbook._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(dbook, f)), np.asarray(getattr(sbook, f)), f)
    assert dres == sres
    assert dfills == sfills


def test_sparse_tiny_dispatch():
    """One order: the sparse step transfers a 64-lane bucket, not [S, B]."""
    stream = random_order_stream(CFG.num_symbols, 1, seed=9)
    batches = build_sparse(CFG, stream)
    assert len(batches) == 1
    sparse, n = batches[0]
    assert n == 1 and sparse.slot.shape[0] == 64
    _, sres, _ = run_sparse(CFG, stream)
    _, dres, _ = run_dense(CFG, stream)
    assert sres == dres


def test_bucket_ladder():
    assert bucket(1) == 64
    assert bucket(64) == 64
    assert bucket(65) == 128
    assert bucket(1000) == 1024


def test_padding_cannot_clobber_slot_zero():
    """Padding lanes target slot == S and must be scatter-dropped — a real
    op at (0, 0) survives a fully-padded trailing bucket."""
    stream = random_order_stream(1, 1, seed=3)  # one op at symbol 0, row 0
    cfg = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=256)
    (sparse, n), = build_sparse(cfg, stream)
    assert n == 1
    assert int(sparse.slot[0]) == 0 and int(sparse.row[0]) == 0
    assert all(int(x) == cfg.num_symbols for x in np.asarray(sparse.slot[1:]))
    book = init_book(cfg)
    book, out = engine_step_sparse(cfg, book, sparse)
    dec = unpack_sparse_output(out, sparse.lanes.shape[0])
    assert int(dec.status[0]) != -1  # the real op was processed


def test_runner_path_selection():
    """The serving runner uses sparse lanes for small dispatches and the
    dense grid once a dispatch nears capacity."""
    from matching_engine_tpu.engine.kernel import OP_SUBMIT
    from matching_engine_tpu.server.engine_runner import (
        EngineOp,
        EngineRunner,
        OrderInfo,
    )

    cfg = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=256)
    runner = EngineRunner(cfg)

    def op(sym, price, n):
        assert runner.slot_acquire(sym) is not None
        num, oid = runner.assign_oid()
        return EngineOp(OP_SUBMIT, OrderInfo(
            oid=num, order_id=oid, client_id="c", symbol=sym, side=1,
            otype=0, price_q4=price, quantity=1, remaining=1, status=0,
            handle=runner.assign_handle()))

    runner.run_dispatch([op("A", 100, 0)])  # 1 op <= 16/4 -> sparse
    counters = runner.metrics.snapshot()[0]
    assert counters.get("sparse_dispatches") == 1
    assert counters.get("dense_dispatches") is None

    ops = [op("B", 100 + i, i) for i in range(8)]  # 8 > 16/4 -> dense
    runner.run_dispatch(ops)
    counters = runner.metrics.snapshot()[0]
    assert counters.get("dense_dispatches") == 1


def test_over_inline_fill_log_parity():
    """A single step producing more fills than the inline segment
    (kernel.FILL_INLINE) must fall back to the full fill-buffer fetch and
    still decode identically to the dense path."""
    from matching_engine_tpu.engine.harness import HostOrder
    from matching_engine_tpu.engine.kernel import FILL_INLINE, OP_SUBMIT
    from matching_engine_tpu.proto import BUY, LIMIT, SELL

    n_makers = FILL_INLINE + 44
    cfg = EngineConfig(num_symbols=2, capacity=n_makers + 8, batch=4,
                       max_fills=2 * n_makers)
    stream = [
        HostOrder(sym=0, op=OP_SUBMIT, side=SELL, otype=LIMIT,
                  price=100, qty=1, oid=i + 1)
        for i in range(n_makers)
    ]
    stream.append(HostOrder(sym=0, op=OP_SUBMIT, side=BUY, otype=LIMIT,
                            price=100, qty=n_makers, oid=10_000))
    sbook, sres, sfills = run_sparse(cfg, stream)
    dbook, dres, dfills = run_dense(cfg, stream)
    assert len(sfills) == n_makers
    assert sfills == dfills
    assert sres == dres
