"""Metrics registry: counters, EMA gauges, and p50/p99 histograms.

BASELINE.json's metric is "orders/sec + p99 match latency" — the p99 comes
from a sliding-window histogram surfaced as derived gauges in snapshot()
(and therefore over the GetMetrics RPC, tests/test_server.py)."""

from matching_engine_tpu.utils.metrics import _HIST_CAP, Metrics, Timer


def test_percentiles_over_window():
    m = Metrics()
    for v in range(1, 101):  # 1..100
        m.observe("lat_us", float(v))
    assert m.percentile("lat_us", 0.5) == 51.0
    assert m.percentile("lat_us", 0.99) == 100.0
    assert m.percentile("absent", 0.99) is None
    _, gauges = m.snapshot()
    assert gauges["lat_us_p50"] == 51.0
    assert gauges["lat_us_p99"] == 100.0


def test_ring_is_sliding_window():
    m = Metrics()
    for v in range(_HIST_CAP + 100):
        m.observe("x", float(v))
    # The first 100 samples were overwritten; min of the window is 100.
    assert m.percentile("x", 0.0) == 100.0


def test_timer_feeds_both_ema_and_histogram():
    m = Metrics()
    for _ in range(3):
        with Timer(m, "t_us"):
            pass
    _, gauges = m.snapshot()
    # The EMA is suffixed _ema so it can never shadow the window's
    # derived percentiles (the submit_rpc_us collision fix).
    assert "t_us_ema" in gauges
    assert "t_us" not in gauges
    assert "t_us_p50" in gauges and "t_us_p99" in gauges


def test_stream_latency_metric_and_wakeup():
    """Event-driven fanout (VERDICT r3 next-step 8): an IDLE subscriber
    wakes on publish without an aliveness poll, the publish->yield
    latency lands in stream_latency_us_p50/_p99, and the close sentinel
    terminates a blocked generator promptly."""
    import threading
    import time

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.server.streams import StreamHub

    m = Metrics()
    hub = StreamHub(metrics=m)
    sub = hub.subscribe_market_data("X")
    got: list[tuple[float, object]] = []
    done = threading.Event()

    def consume():
        for item in sub.stream():           # alive=None: blocking get
            got.append((time.perf_counter(), item))
        done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.3)                          # subscriber genuinely idle
    t_pub = time.perf_counter()
    hub.publish_market_data([pb2.MarketDataUpdate(symbol="X", best_bid=1)])
    for _ in range(200):
        if got:
            break
        time.sleep(0.005)
    assert got, "idle subscriber never woke on publish"
    wake_ms = (got[0][0] - t_pub) * 1e3
    # Sub-ms in practice; 100ms bound keeps CI immune to scheduler noise
    # while still far below the old 250ms poll quantum.
    assert wake_ms < 100, f"wakeup took {wake_ms:.1f}ms"
    _, gauges = m.snapshot()
    assert "stream_latency_us_p50" in gauges
    assert gauges["stream_latency_us_p50"] < 100_000
    t_close = time.perf_counter()
    hub.unsubscribe(sub)
    assert done.wait(timeout=1.0), "close sentinel did not wake the stream"
    assert (time.perf_counter() - t_close) < 0.5
