"""Metrics registry: counters, EMA gauges, and p50/p99 histograms.

BASELINE.json's metric is "orders/sec + p99 match latency" — the p99 comes
from a sliding-window histogram surfaced as derived gauges in snapshot()
(and therefore over the GetMetrics RPC, tests/test_server.py)."""

from matching_engine_tpu.utils.metrics import _HIST_CAP, Metrics, Timer


def test_percentiles_over_window():
    m = Metrics()
    for v in range(1, 101):  # 1..100
        m.observe("lat_us", float(v))
    assert m.percentile("lat_us", 0.5) == 51.0
    assert m.percentile("lat_us", 0.99) == 100.0
    assert m.percentile("absent", 0.99) is None
    _, gauges = m.snapshot()
    assert gauges["lat_us_p50"] == 51.0
    assert gauges["lat_us_p99"] == 100.0


def test_ring_is_sliding_window():
    m = Metrics()
    for v in range(_HIST_CAP + 100):
        m.observe("x", float(v))
    # The first 100 samples were overwritten; min of the window is 100.
    assert m.percentile("x", 0.0) == 100.0


def test_timer_feeds_both_ema_and_histogram():
    m = Metrics()
    for _ in range(3):
        with Timer(m, "t_us"):
            pass
    _, gauges = m.snapshot()
    assert "t_us" in gauges
    assert "t_us_p50" in gauges and "t_us_p99" in gauges
