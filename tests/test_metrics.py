"""Metrics registry: counters, EMA gauges, and windowed log-bucket
histograms.

BASELINE.json's metric is "orders/sec + p99 match latency" — quantiles
come from HDR-style log-bucketed histograms over a TIME-bounded window
(utils/metrics.py), surfaced as derived _p50/_p99/_p999 gauges in
snapshot() (and therefore over the GetMetrics RPC, tests/test_server.py).
Reported quantiles are bucket upper bounds: >= the true sample, within
one ~9% bucket width above it."""

from matching_engine_tpu.utils.metrics import (
    Metrics,
    Timer,
    bucket_index,
    bucket_upper,
)


def test_percentiles_over_window():
    m = Metrics()
    for v in range(1, 101):  # 1..100
        m.observe("lat_us", float(v))
    # Bucket-upper-bound quantiles: conservative (>= exact), within one
    # bucket ratio (2^(1/8)) of the exact nearest-rank values 51 and 100.
    p50 = m.percentile("lat_us", 0.5)
    p99 = m.percentile("lat_us", 0.99)
    assert 51.0 <= p50 <= 51.0 * 2 ** 0.125
    assert 100.0 <= p99 <= 100.0 * 2 ** 0.125
    assert m.percentile("absent", 0.99) is None
    _, gauges = m.snapshot()
    assert gauges["lat_us_p50"] == p50
    assert gauges["lat_us_p99"] == p99
    assert gauges["lat_us_p999"] >= p99


def test_window_is_time_bounded():
    """The satellite fix: quantiles describe the last stage_window_seconds,
    not the last N samples — a rate collapse (megadispatch) must age old
    samples out instead of freezing a stale p99."""
    m = Metrics(window_s=6.0)
    clock = [0.0]
    m._now = lambda: clock[0]
    m.observe("x", 1000.0)          # old-regime sample
    clock[0] = 3.0
    m.observe("x", 1.0)             # new-regime sample, later slice
    assert m.percentile("x", 1.0) >= 1000.0  # both in window
    clock[0] = 8.0                  # 1000.0's slice aged out; 1.0 remains
    assert m.percentile("x", 1.0) < 1000.0
    clock[0] = 60.0                 # everything aged out
    assert m.percentile("x", 0.5) is None
    _, gauges = m.snapshot()
    assert gauges["stage_window_seconds"] == 6.0
    assert "x_p50" not in gauges    # empty window: absent, not zero


def test_stale_timestamp_never_rewinds_the_window():
    """observe() captures its clock BEFORE the registry lock, so a
    preempted thread can arrive with a timestamp older than one that
    already advanced the ring — the ring must never step backwards and
    re-zero a live slice (the stale sample lands in the current slice,
    off by at most one slice)."""
    m = Metrics(window_s=6.0)
    clock = [0.9999]
    m._now = lambda: clock[0]
    m.observe("x", 1.0)       # epoch 0
    clock[0] = 1.0001
    m.observe("x", 2.0)       # advances to epoch 1
    clock[0] = 0.9999         # the preempted thread's stale read
    m.observe("x", 3.0)       # must NOT rewind to epoch 0
    clock[0] = 1.1
    m.observe("x", 4.0)       # re-advance would have wiped epoch 1
    # The WINDOW (not the lifetime view) must still hold all 4 samples.
    assert sum(m._hists["x"].merged(clock[0])) == 4


def test_bucket_grid_is_monotonic_and_clamped():
    assert bucket_index(0.0) == 0 and bucket_index(-5.0) == 0
    last = -1
    for v in (0.5, 1.0, 3.0, 10.0, 1e3, 1e6, 1e12):
        i = bucket_index(v)
        assert i >= last
        last = i
        assert bucket_upper(i) >= v or v >= 2.0 ** 30  # clamp at the top
    # Upper bound is the smallest boundary >= the value's bucket.
    assert bucket_upper(bucket_index(100.0)) >= 100.0


def test_hist_snapshot_cumulative_buckets():
    m = Metrics()
    for v in (10.0, 10.0, 500.0, 20000.0):
        m.observe("lat_us", v)
    snap = m.hist_snapshot()["lat_us"]
    assert snap["count"] == 4
    assert abs(snap["sum"] - 20520.0) < 1e-6
    bounds = [b for b, _ in snap["buckets"]]
    cums = [c for _, c in snap["buckets"]]
    assert bounds == sorted(bounds)
    assert cums == sorted(cums) and cums[-1] == 4
    assert cums[0] == 2  # the two 10.0 samples share the first bucket


def test_timer_feeds_both_ema_and_histogram():
    m = Metrics()
    for _ in range(3):
        with Timer(m, "t_us"):
            pass
    _, gauges = m.snapshot()
    # The EMA is suffixed _ema so it can never shadow the window's
    # derived percentiles (the submit_rpc_us collision fix).
    assert "t_us_ema" in gauges
    assert "t_us" not in gauges
    assert "t_us_p50" in gauges and "t_us_p99" in gauges
    assert "t_us_p999" in gauges


def test_stream_latency_metric_and_wakeup():
    """Event-driven fanout (VERDICT r3 next-step 8): an IDLE subscriber
    wakes on publish without an aliveness poll, the publish->yield
    latency lands in stream_latency_us_p50/_p99, and the close sentinel
    terminates a blocked generator promptly."""
    import threading
    import time

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.server.streams import StreamHub

    m = Metrics()
    hub = StreamHub(metrics=m)
    sub = hub.subscribe_market_data("X")
    got: list[tuple[float, object]] = []
    done = threading.Event()

    def consume():
        for item in sub.stream():           # alive=None: blocking get
            got.append((time.perf_counter(), item))
        done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.3)                          # subscriber genuinely idle
    t_pub = time.perf_counter()
    hub.publish_market_data([pb2.MarketDataUpdate(symbol="X", best_bid=1)])
    for _ in range(200):
        if got:
            break
        time.sleep(0.005)
    assert got, "idle subscriber never woke on publish"
    wake_ms = (got[0][0] - t_pub) * 1e3
    # Sub-ms in practice; 100ms bound keeps CI immune to scheduler noise
    # while still far below the old 250ms poll quantum.
    assert wake_ms < 100, f"wakeup took {wake_ms:.1f}ms"
    _, gauges = m.snapshot()
    assert "stream_latency_us_p50" in gauges
    assert gauges["stream_latency_us_p50"] < 100_000
    t_close = time.perf_counter()
    hub.unsubscribe(sub)
    assert done.wait(timeout=1.0), "close sentinel did not wake the stream"
    assert (time.perf_counter() - t_close) < 0.5
