"""The full serving stack over a device mesh: RPC in, sharded books inside.

Boots the real gRPC server with an 8-device symbol-sharded EngineRunner
(tests/conftest.py provides the virtual CPU mesh) and checks the black-box
RPC / white-box DB oracle still holds — sharding must be invisible to every
layer above the runner, including checkpoints.
"""

import grpc
import pytest

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.parallel import make_mesh
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.storage import Storage

CFG = EngineConfig(num_symbols=8, capacity=16, batch=4)


@pytest.fixture
def hs(tmp_path):
    mesh = make_mesh(8)
    server, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "sh.db"), CFG,
        window_ms=1.0, log=False, mesh=mesh,
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_interval_s=3600.0,
    )
    server.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield {
        "stub": MatchingEngineStub(channel),
        "parts": parts,
        "db": str(tmp_path / "sh.db"),
        "tmp": tmp_path,
        "server": server,
        "channel": channel,
    }
    channel.close()
    shutdown(server, parts)


def submit(stub, client="c1", symbol="SYM", otype=pb2.LIMIT, side=pb2.BUY,
           price=10000, scale=4, qty=5):
    return stub.SubmitOrder(
        pb2.OrderRequest(client_id=client, symbol=symbol, order_type=otype,
                         side=side, price=price, scale=scale, quantity=qty),
        timeout=30,
    )


def test_sharded_server_matches_and_persists(hs):
    stub = hs["stub"]
    # Spread symbols over several shards (8 symbols over 8 devices).
    for i in range(6):
        r = submit(stub, symbol=f"S{i}", side=pb2.BUY, price=1000 + i, qty=10)
        assert r.success, r.error_message
    # Different client: the crossing SELL must not be suppressed by
    # self-trade prevention (always on).
    r = submit(stub, client="c2", symbol="S3", side=pb2.SELL, price=900,
               qty=4)
    assert r.success
    hs["parts"]["sink"].flush()

    store = Storage(hs["db"])
    assert store.init()
    assert store.count("orders") == 7
    assert store.count("fills") == 1
    bb = store.best_bid("S3")
    assert bb == (1003, 6)  # 10 - 4 filled
    store.close()

    # Book snapshot over RPC still works on the sharded book.
    book = stub.GetOrderBook(pb2.OrderBookRequest(symbol="S3"), timeout=30)
    assert len(book.bids) == 1 and book.bids[0].quantity == 6
    assert len(book.asks) == 0


def test_resolve_mesh_paths():
    from matching_engine_tpu.server.main import resolve_mesh

    assert resolve_mesh(0, 1024) is None
    mesh = resolve_mesh(8, 64)
    assert mesh is not None and mesh.devices.size == 8
    with pytest.raises(ValueError, match="not divisible"):
        resolve_mesh(8, 10)
    with pytest.raises(ValueError, match="visible"):
        resolve_mesh(999, 999 * 4)


def test_main_bad_mesh_exits_cleanly(tmp_path, capsys):
    from matching_engine_tpu.server.main import main

    rc = main(["--addr", "127.0.0.1:0", "--db", str(tmp_path / "m.db"),
               "--symbols", "10", "--mesh", "8"])
    assert rc == 3
    assert "bad --mesh" in capsys.readouterr().err


def test_sharded_checkpoint_roundtrip(hs):
    stub = hs["stub"]
    for i in range(4):
        assert submit(stub, symbol=f"S{i}", price=2000 + i, qty=3).success
    ck = hs["parts"]["checkpointer"]
    path = ck.checkpoint_now()
    assert path is not None

    # Restore into a FRESH sharded runner and compare a book snapshot.
    from matching_engine_tpu.server.engine_runner import EngineRunner
    from matching_engine_tpu.utils.checkpoint import restore_runner

    runner2 = EngineRunner(CFG, mesh=make_mesh(8))
    store = Storage(hs["db"])
    assert store.init()
    restore_runner(runner2, path, store)
    store.close()
    bids, asks = runner2.book_snapshot("S2")
    assert len(bids) == 1
    info, qty = bids[0]
    assert qty == 3 and info.price_q4 == 2002
