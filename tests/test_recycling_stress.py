"""Recycling machinery + serving-stack stress (ADVICE r2, VERDICT r2 #8).

Unit level: the handle/slot allocators recycle safely (slot reuse after a
symbol empties, stale cancels never reach a recycled handle, checkpoint v2
restores rebuild the allocators). Stress level: concurrent
submit+cancel+GetOrderBook+checkpoint_now against the real stack with a
deterministic seed, then invariant asserts (every RPC answered, audit-clean
DB, consistent final books).
"""

from __future__ import annotations

import importlib.util
import pathlib
import random
import threading

import grpc
import pytest

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.kernel import (
    CANCELED,
    FILLED,
    NEW,
    OP_CANCEL,
    OP_SUBMIT,
    REJECTED,
)
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.engine_runner import EngineOp, EngineRunner, OrderInfo
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.utils.checkpoint import restore_runner, save_checkpoint

_spec = importlib.util.spec_from_file_location(
    "audit", pathlib.Path(__file__).resolve().parent.parent / "scripts" / "audit.py")
audit_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(audit_mod)


def _submit(runner: EngineRunner, symbol: str, side: int, qty: int,
            price: int, otype: int = pb2.LIMIT,
            client: str | None = None) -> OrderInfo:
    """Drive the service's submit flow at the runner level. The client id
    defaults to the SIDE (distinct per side): self-trade prevention is
    always on, so a test that wants a cross must use different clients."""
    assert runner.slot_acquire(symbol) is not None
    num, order_id = runner.assign_oid()
    info = OrderInfo(
        oid=num, order_id=order_id, client_id=client or f"c-side{side}",
        symbol=symbol, side=side,
        otype=otype, price_q4=price, quantity=qty, remaining=qty, status=0,
        handle=runner.assign_handle(),
    )
    runner.run_dispatch([EngineOp(OP_SUBMIT, info)])
    return info


def test_slot_recycles_after_symbol_empties():
    runner = EngineRunner(EngineConfig(num_symbols=2, capacity=8, batch=4))
    a = _submit(runner, "A", pb2.BUY, 5, 10_000)
    _submit(runner, "B", pb2.BUY, 1, 10_000)
    slot_a = runner.symbols["A"]
    assert a.status == NEW
    # Fill A's only order -> both sides terminal -> slot must recycle.
    b = _submit(runner, "A", pb2.SELL, 5, 10_000)
    assert a.status == FILLED and b.status == FILLED
    assert "A" not in runner.symbols and slot_a in runner._free_slots
    # The freed slot is reusable by a brand-new symbol (axis size is 2 and
    # B still holds the other slot, so this allocation NEEDS the recycle).
    c = _submit(runner, "C", pb2.BUY, 1, 10_000)
    assert c.status == NEW and runner.symbols["C"] == slot_a


def test_stale_cancel_never_hits_recycled_handle():
    runner = EngineRunner(EngineConfig(num_symbols=2, capacity=8, batch=4))
    o1 = _submit(runner, "A", pb2.BUY, 5, 10_000)
    h1 = o1.handle
    _submit(runner, "A", pb2.SELL, 5, 10_000)  # fills o1 -> handle freed
    assert o1.status == FILLED
    # New order reuses o1's device handle.
    o3 = _submit(runner, "A", pb2.BUY, 3, 9_000)
    assert o3.handle == h1 and o3.status == NEW
    # A cancel captured against o1 BEFORE it went terminal now dispatches:
    # must be host-rejected (o1 is terminal) and must not touch o3.
    res = runner.run_dispatch([EngineOp(OP_CANCEL, o1, cancel_requester="c")])
    assert res.outcomes[0].status == REJECTED
    assert res.outcomes[0].error == "order not open"
    assert o3.status == NEW and runner.orders_by_id[o3.order_id] is o3
    bids, _ = runner.book_snapshot("A")
    assert [(i.order_id, q) for i, q in bids] == [(o3.order_id, 3)]
    # And a legitimate cancel of o3 still works.
    res = runner.run_dispatch([EngineOp(OP_CANCEL, o3, cancel_requester="c")])
    assert res.outcomes[0].status == CANCELED


def test_fill_then_cancel_same_batch_keeps_remaining_nonnegative():
    """Regression: one batch partially fills a resting order AND cancels it.

    The fills happen before the cancel in the device scan; host decode must
    replay that order. The old two-pass decode applied the cancel first
    (remaining -> 0) and then the maker decrements (remaining -> -3), which
    the storage CHECK (remaining_quantity >= 0) rejected — silently dropping
    the whole storage batch (caught by the stress test below)."""
    runner = EngineRunner(EngineConfig(num_symbols=2, capacity=8, batch=4))
    m = _submit(runner, "A", pb2.BUY, 5, 10_000)  # rests, remaining 5
    num, order_id = runner.assign_oid()
    assert runner.slot_acquire("A") is not None
    taker = OrderInfo(
        oid=num, order_id=order_id, client_id="c", symbol="A", side=pb2.SELL,
        otype=pb2.LIMIT, price_q4=10_000, quantity=3, remaining=3, status=0,
        handle=runner.assign_handle(),
    )
    res = runner.run_dispatch([
        EngineOp(OP_SUBMIT, taker),
        EngineOp(OP_CANCEL, m, cancel_requester="c"),
    ])
    assert taker.status == FILLED and taker.remaining == 0
    assert m.status == CANCELED and m.remaining == 0
    # Cancel outcome reports the 2 units actually canceled (post-fill).
    cancel_outcome = next(o for o in res.outcomes if o.op.op == OP_CANCEL)
    assert cancel_outcome.status == CANCELED and cancel_outcome.remaining == 2
    # Storage updates replay device order and never go negative.
    maker_updates = [u for u in res.storage_updates if u[0] == m.order_id]
    assert maker_updates == [(m.order_id, 1, 2), (m.order_id, CANCELED, 0)]
    assert all(u[2] >= 0 for u in res.storage_updates)
    assert len(res.storage_fills) == 1 and res.storage_fills[0].quantity == 3


def test_checkpoint_v2_roundtrip_rebuilds_allocators(tmp_path):
    cfg = EngineConfig(num_symbols=4, capacity=8, batch=4)
    runner = EngineRunner(cfg)
    live = _submit(runner, "A", pb2.BUY, 5, 10_000)
    gone = _submit(runner, "B", pb2.BUY, 2, 10_000)
    _submit(runner, "B", pb2.SELL, 2, 10_000)  # empties B -> slot recycled
    live2 = _submit(runner, "C", pb2.SELL, 4, 11_000)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, runner)

    fresh = EngineRunner(cfg)
    assert restore_runner(fresh, path) == 0
    # Directory restored.
    assert set(fresh.orders_by_id) == {live.order_id, live2.order_id}
    assert gone.order_id not in fresh.orders_by_id
    # Allocators rebuilt: next_handle past every live handle; B's old slot
    # free again; live counts match the open orders.
    assert fresh._next_handle == 1 + max(live.handle, live2.handle)
    assert fresh.assign_handle() not in {live.handle, live2.handle}
    assert sorted(fresh.symbols) == ["A", "C"]
    for sym in ("A", "C"):
        assert fresh._slot_live[fresh.symbols[sym]] == 1
    # The restored engine keeps matching correctly against restored state.
    taker = _submit(fresh, "A", pb2.SELL, 5, 10_000)
    assert taker.status == FILLED and live.order_id not in fresh.orders_by_id
    # B's recycled slot is allocatable for a new symbol.
    assert fresh.slot_acquire("D") is not None



def _stress_client(port: int, tid: int, errors: list, *, seed: int,
                   sym_prefix: str, n_syms: int, n_ops: int,
                   cancel_p: float, book_p: float, limit_only: bool):
    """THE shared stress-client behavior (one definition for every stress
    variant): random submit/cancel/book traffic; every RPC must answer."""
    rng = random.Random(seed + tid)
    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    stub = MatchingEngineStub(ch)
    my_open: list[str] = []
    try:
        for _ in range(n_ops):
            sym = f"{sym_prefix}{rng.randrange(n_syms)}"
            roll = rng.random()
            if my_open and roll < cancel_p:
                oid = my_open.pop(rng.randrange(len(my_open)))
                r = stub.CancelOrder(pb2.CancelRequest(
                    client_id=f"c{tid}", order_id=oid), timeout=60)
                # success or a clean reject; must always answer.
                assert r.order_id == oid
            elif roll < cancel_p + book_p:
                stub.GetOrderBook(pb2.OrderBookRequest(symbol=sym), timeout=60)
            else:
                otype = (pb2.LIMIT if limit_only or rng.random() < 0.8
                         else pb2.MARKET)
                r = stub.SubmitOrder(pb2.OrderRequest(
                    client_id=f"c{tid}", symbol=sym, order_type=otype,
                    side=pb2.BUY if rng.random() < 0.5 else pb2.SELL,
                    price=10_000 + rng.randrange(8), scale=4,
                    quantity=1 + rng.randrange(9)), timeout=60)
                if r.success:
                    my_open.append(r.order_id)
    except Exception as e:  # noqa: BLE001
        errors.append(f"client {tid}: {type(e).__name__}: {e}")
    finally:
        ch.close()


def _checkpoint_loop(parts, stop, errors):
    try:
        while not stop.is_set():
            parts["checkpointer"].checkpoint_now()
    except Exception as e:  # noqa: BLE001
        errors.append(f"checkpointer: {type(e).__name__}: {e}")


def _join_all(clients, aux, stop, errors):
    for t in clients + aux:
        t.start()
    for t in clients:
        t.join(timeout=240)
        assert not t.is_alive(), "client thread hung"
    stop.set()
    for t in aux:
        t.join(timeout=60)
        assert not t.is_alive(), "aux thread hung"
    assert errors == []


def test_stress_concurrent_submit_cancel_book_checkpoint(tmp_path):
    db = str(tmp_path / "stress.db")
    server, port, parts = build_server(
        "127.0.0.1:0", db, EngineConfig(num_symbols=8, capacity=32, batch=8),
        window_ms=1.0, log=False,
        checkpoint_dir=str(tmp_path / "ckpts"),
        checkpoint_interval_s=3600.0,  # only explicit checkpoint_now calls
    )
    server.start()
    errors: list[str] = []
    stop = threading.Event()

    clients = [threading.Thread(target=_stress_client,
                                args=(port, t, errors),
                                kwargs=dict(seed=1000, sym_prefix="S",
                                            n_syms=6, n_ops=60,
                                            cancel_p=0.3, book_p=0.2,
                                            limit_only=False))
               for t in range(4)]
    aux = [threading.Thread(target=_checkpoint_loop,
                            args=(parts, stop, errors))]
    _join_all(clients, aux, stop, errors)

    parts["sink"].flush()
    m = parts["metrics"].snapshot()[0]
    assert m.get("orders_errored", 0) == 0
    assert m.get("dispatch_errors", 0) == 0
    # Final invariant: whatever the interleaving, the durable store must be
    # internally consistent.
    shutdown(server, parts)
    assert audit_mod.audit(db) == []


def test_stress_auction_interleaved(tmp_path):
    """Concurrent submits/cancels/books/checkpoints WITH periodic call
    periods: a toggler thread flips auction_mode on, lets crossing flow
    accumulate, then uncrosses — while clients and the checkpointer keep
    hammering. Invariants: every RPC answers, no engine/dispatch errors,
    audit-clean durable store (auction fills reference real orders)."""
    db = str(tmp_path / "austress.db")
    server, port, parts = build_server(
        "127.0.0.1:0", db, EngineConfig(num_symbols=8, capacity=64, batch=8,
                                        max_fills=1 << 12),
        window_ms=1.0, log=False,
        checkpoint_dir=str(tmp_path / "ckpts"),
        checkpoint_interval_s=3600.0,
    )
    server.start()
    errors: list[str] = []
    stop = threading.Event()
    runner = parts["runner"]

    def auction_thread():
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        stub = MatchingEngineStub(ch)
        try:
            while not stop.is_set():
                runner.auction_mode = True   # open a call period
                stop.wait(0.05)              # let crossing flow accumulate
                r = stub.RunAuction(pb2.AuctionRequest(), timeout=60)
                assert r.success, r.error_message
                stop.wait(0.02)
        except Exception as e:  # noqa: BLE001
            errors.append(f"auctioneer: {type(e).__name__}: {e}")
        finally:
            ch.close()

    # LIMIT-only clients: MARKETs legitimately reject in a call period and
    # this test wants every submit answerable in both modes.
    clients = [threading.Thread(target=_stress_client,
                                args=(port, t, errors),
                                kwargs=dict(seed=7000, sym_prefix="A",
                                            n_syms=4, n_ops=50,
                                            cancel_p=0.25, book_p=0.15,
                                            limit_only=True))
               for t in range(4)]
    aux = [threading.Thread(target=auction_thread),
           threading.Thread(target=_checkpoint_loop,
                            args=(parts, stop, errors))]
    _join_all(clients, aux, stop, errors)

    # Leave continuous mode and flush before the final audit.
    runner.auction_mode = False
    parts["sink"].flush()
    m = parts["metrics"].snapshot()[0]
    assert m.get("orders_errored", 0) == 0
    assert m.get("dispatch_errors", 0) == 0
    assert m.get("auctions", 0) > 0, "auction leg never ran"
    shutdown(server, parts)
    assert audit_mod.audit(db) == []
