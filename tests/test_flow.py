"""Realistic L3 flow generator (engine/flow.py): shape properties + the
kernel/oracle parity gate on its output (the config-3b benchmark flow must
match the oracle exactly, same as the uniform flow)."""

from collections import Counter

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.flow import realistic_order_stream
from matching_engine_tpu.engine.kernel import OP_CANCEL, OP_SUBMIT

from tests.test_kernel_parity import assert_parity


def test_deterministic_per_seed():
    a = realistic_order_stream(64, 2000, seed=7)
    b = realistic_order_stream(64, 2000, seed=7)
    c = realistic_order_stream(64, 2000, seed=8)
    assert a == b
    assert a != c


def test_power_law_concentration():
    """Zipf head dominance: the top 10% of symbols carry well over half
    the flow (uniform flow would give them ~10%)."""
    stream = realistic_order_stream(128, 20_000, seed=1)
    counts = Counter(o.sym for o in stream)
    top = sum(c for _, c in counts.most_common(13))
    assert top / len(stream) > 0.5
    # ... and the tail still participates (not a degenerate single symbol).
    assert len(counts) > 64


def test_bursts_cluster_symbol_runs():
    """With bursts enabled, long same-burst-pool runs exist: count windows
    of 30 consecutive ops hitting <= 5 distinct symbols (vanishingly rare
    under independent Zipf draws at S=512 head-spread, common in bursts)."""
    stream = realistic_order_stream(512, 30_000, seed=3, burst_p=0.01)
    syms = [o.sym for o in stream]
    clustered = sum(
        1 for i in range(0, len(syms) - 30, 30)
        if len(set(syms[i:i + 30])) <= 5
    )
    no_burst = realistic_order_stream(512, 30_000, seed=3, burst_p=0.0)
    syms0 = [o.sym for o in no_burst]
    clustered0 = sum(
        1 for i in range(0, len(syms0) - 30, 30)
        if len(set(syms0[i:i + 30])) <= 5
    )
    assert clustered > clustered0 + 5


def test_contract_matches_uniform_generator():
    """Same stream contract as random_order_stream: submits get 1-based
    sequential oids, cancels reference previously-submitted LIMIT oids,
    MARKET price is 0, prices are positive ints."""
    stream = realistic_order_stream(32, 5000, seed=2)
    submits = [o for o in stream if o.op == OP_SUBMIT]
    assert [o.oid for o in submits] == list(range(1, len(submits) + 1))
    seen = set()
    for o in stream:
        if o.op == OP_SUBMIT:
            seen.add(o.oid)
            if o.otype in (1, 4):  # MARKET / MARKET_FOK: price-indifferent
                assert o.price == 0
            else:  # LIMIT / LIMIT_IOC / LIMIT_FOK carry a real limit
                assert o.otype in (0, 2, 3) and o.price >= 1
            assert 1 <= o.qty < 100
        else:
            assert o.op == OP_CANCEL and o.oid in seen


def test_parity_on_realistic_flow():
    """The parity gate holds on deep/burst/power-law flow, including the
    side-full REJECTED regime a small capacity forces."""
    cfg = EngineConfig(num_symbols=16, capacity=16, batch=8, max_fills=1 << 14)
    stream = realistic_order_stream(16, 1500, seed=5, deep_fraction=0.25)
    assert_parity(cfg, stream)


def test_generator_throughput_at_4096_symbols():
    """Stream generation must not dominate bench setup: >=100k ops/s at
    S=4096 (VERDICT r4 next-step 7 — the old rng.choices path re-walked
    the 4096-entry weight list per op, ~100x slower than this bound)."""
    import time

    n = 50_000
    best = 0.0
    for attempt in range(3):  # tolerate CI boxes under concurrent load
        t0 = time.perf_counter()
        stream = realistic_order_stream(4096, n, seed=9)
        best = max(best, n / (time.perf_counter() - t0))
        if best >= 100_000:
            break
    assert len(stream) == n
    # Uncontended rate is ~200k ops/s; the per-op weight-walk regression
    # this guards against ran at ~2k. Bound set with load headroom.
    assert best >= 50_000, f"generator at {best:.0f} ops/s"
