"""Call-auction kernel parity and invariants (engine/auction.py).

Books are built in AUCTION-MODE accumulation: orders rest directly
without continuous matching (the pre-open state call auctions exist for —
a continuously-matched book never stands crossed). Each state replays
through the device uncross and the oracle's `auction()`; clearing price,
executed volume, bilateral records, and the post-auction books must agree
exactly. Plus mechanism invariants: volume conservation, all-or-nothing
overflow abort, and mask scoping.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from matching_engine_tpu.engine.auction import auction_step, decode_auction
from matching_engine_tpu.engine.book import BookBatch, EngineConfig, init_book
from matching_engine_tpu.engine.harness import snapshot_books
from matching_engine_tpu.engine.oracle import OracleBook, _Resting

CFG = EngineConfig(num_symbols=8, capacity=32, batch=8, max_fills=1 << 12)


def build_crossed_books(cfg, seed, levels=12):
    """Device books + oracle twins holding the SAME un-matched resting
    state, with overlapping bid/ask bands so auctions usually cross."""
    rng = np.random.default_rng(seed)
    s, c = cfg.num_symbols, cfg.capacity
    arr = {f: np.zeros((s, c), dtype=np.int32)
           for f in BookBatch._fields if f != "next_seq"}
    next_seq = np.zeros((s,), dtype=np.int32)
    oracles = {i: OracleBook(c) for i in range(s)}
    oid = 1
    for i in range(s):
        seq = 0
        nb, na = int(rng.integers(0, c)), int(rng.integers(0, c))
        for side, n in (("bid", nb), ("ask", na)):
            for k in range(n):
                price = int(10_000 + rng.integers(-levels, levels + 1))
                qty = int(rng.integers(1, 50))
                arr[f"{side}_price"][i, k] = price
                arr[f"{side}_qty"][i, k] = qty
                arr[f"{side}_oid"][i, k] = oid
                arr[f"{side}_seq"][i, k] = seq
                rest = _Resting(oid, price, qty, seq)
                (oracles[i].bids if side == "bid" else
                 oracles[i].asks).append(rest)
                oid += 1
                seq += 1
        next_seq[i] = seq
        oracles[i].next_seq = seq
    book = BookBatch(**{k: jnp.asarray(v) for k, v in arr.items()},
                     next_seq=jnp.asarray(next_seq))
    return book, oracles


def canon(fills):
    return sorted((f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
                  for f in fills)


def canon_oracle(sym, fills):
    return sorted((sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
                  for f in fills)


def _assert_auction_oracle_parity(cfg, book, oracles):
    mask = np.ones((cfg.num_symbols,), dtype=bool)
    new_book, out = auction_step(cfg, book, mask)
    dec, fills = decode_auction(cfg, out)
    assert not dec.aborted

    expected = []
    crossed = 0
    for s, ob in oracles.items():
        p, q, ofills = ob.auction()
        assert int(dec.clear_price[s]) == p, f"symbol {s} price"
        assert int(dec.executed[s]) == q, f"symbol {s} volume"
        crossed += q > 0
        expected.extend(canon_oracle(s, ofills))
    assert crossed > 0, "fuzz produced no crossing book — weak seed"
    assert canon(fills) == sorted(expected)

    # Post-auction books match the oracle twins exactly.
    snaps = snapshot_books(new_book)
    for s, ob in oracles.items():
        assert snaps[s] == ob.snapshot(), f"symbol {s} post-auction book"

    # Conservation: per symbol the bilateral records sum to the volume.
    for s in range(cfg.num_symbols):
        vol = sum(f.quantity for f in fills if f.sym == s)
        assert vol == int(dec.executed[s])


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_auction_matches_oracle(seed):
    book, oracles = build_crossed_books(CFG, seed)
    _assert_auction_oracle_parity(CFG, book, oracles)


CFG_SORTED = EngineConfig(num_symbols=8, capacity=32, batch=8,
                          max_fills=1 << 12, kernel="sorted")


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_auction_matches_oracle_sorted_formulation(seed):
    """The O(C log C) wide-sum uncross (engine/auction_sorted.py) pins to
    the same oracle — including the _compact repack that restores the
    sorted kernel's dense-prefix invariant after the decrements."""
    book, oracles = build_crossed_books(CFG_SORTED, seed)
    _assert_auction_oracle_parity(CFG_SORTED, book, oracles)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sorted_formulation_matches_matrix_formulation(seed):
    """Formulation cross-check: identical decoded outputs from the
    [C, C] matrix uncross and the sorted-merge uncross on the same
    resting state (books rebuilt per run — auction_step donates)."""
    book_m, _ = build_crossed_books(CFG, seed)
    book_s, _ = build_crossed_books(CFG_SORTED, seed)
    mask = np.ones((CFG.num_symbols,), dtype=bool)
    _, out_m = auction_step(CFG, book_m, mask)
    _, out_s = auction_step(CFG_SORTED, book_s, mask)
    dec_m, fills_m = decode_auction(CFG, out_m)
    dec_s, fills_s = decode_auction(CFG_SORTED, out_s)
    np.testing.assert_array_equal(dec_m.clear_price, dec_s.clear_price)
    np.testing.assert_array_equal(dec_m.executed, dec_s.executed)
    assert canon(fills_m) == canon(fills_s)


def test_auction_at_venue_depth_exact_wide_sums():
    """Capacity 8192 with near-MAX_QUANTITY volumes: the executed volume
    exceeds int32, the clearing price still needs EXACT demand/supply
    comparisons, and the uncross must match the oracle's Python-int
    arithmetic bit for bit (VERDICT r4 missing #4 / next-step 3)."""
    from matching_engine_tpu.domain.order import MAX_QUANTITY

    cap = 8192
    cfg = EngineConfig(num_symbols=1, capacity=cap, batch=8,
                       max_fills=1 << 14, kernel="sorted")
    rng = np.random.default_rng(11)
    n_side = 1200
    arr = {f: np.zeros((1, cap), dtype=np.int32)
           for f in BookBatch._fields if f != "next_seq"}
    ob = OracleBook(cap)
    oid = 1
    seq = 0
    for side in ("bid", "ask"):
        for k in range(n_side):
            # Disjoint bands (every bid above every ask) so both sides
            # execute ~fully and the volume clears 2^31.
            price = int(10_002 + rng.integers(0, 4)) if side == "bid" \
                else int(9_995 + rng.integers(0, 4))
            qty = int(MAX_QUANTITY - rng.integers(0, 1000))
            arr[f"{side}_price"][0, k] = price
            arr[f"{side}_qty"][0, k] = qty
            arr[f"{side}_oid"][0, k] = oid
            arr[f"{side}_seq"][0, k] = seq
            (ob.bids if side == "bid" else ob.asks).append(
                _Resting(oid, price, qty, seq))
            oid += 1
            seq += 1
    ob.next_seq = seq
    book = BookBatch(**{k: jnp.asarray(v) for k, v in arr.items()},
                     next_seq=jnp.asarray(np.array([seq], np.int32)))

    new_book, out = auction_step(cfg, book, np.ones((1,), dtype=bool))
    dec, fills = decode_auction(cfg, out)
    assert not dec.aborted
    p, q, ofills = ob.auction()
    assert q > 2**31, "fuzz did not reach the wide-sum regime"
    assert int(dec.clear_price[0]) == p
    assert int(dec.executed[0]) == q
    assert canon(fills) == canon_oracle(0, ofills)
    assert sum(f.quantity for f in fills) == q
    assert snapshot_books(new_book)[0] == ob.snapshot()


@pytest.mark.parametrize("cfg", [CFG, CFG_SORTED],
                         ids=["matrix", "sorted"])
def test_auction_mask_scopes_the_uncross(cfg):
    book, oracles = build_crossed_books(cfg, seed=7)
    mask = np.zeros((cfg.num_symbols,), dtype=bool)
    mask[3] = True
    before = snapshot_books(book)
    new_book, out = auction_step(cfg, book, mask)
    dec, fills = decode_auction(cfg, out)
    after = snapshot_books(new_book)
    for s in range(cfg.num_symbols):
        if s == 3:
            continue
        assert after[s] == before[s], f"unmasked symbol {s} changed"
        assert int(dec.executed[s]) == 0
    assert all(f.sym == 3 for f in fills)
    p, q, ofills = oracles[3].auction()
    assert int(dec.clear_price[3]) == p and int(dec.executed[3]) == q
    assert canon(fills) == sorted(canon_oracle(3, ofills))


def test_auction_empty_and_uncrossable_books():
    cfg = EngineConfig(num_symbols=2, capacity=8, batch=4, max_fills=128)
    book, _ = build_crossed_books(cfg, seed=1, levels=0)
    # Symbol books at a single price CAN cross; rebuild uncrossable:
    book = init_book(cfg)
    book = book._replace(
        bid_price=book.bid_price.at[1, 0].set(90),
        bid_qty=book.bid_qty.at[1, 0].set(5),
        bid_oid=book.bid_oid.at[1, 0].set(1),
        ask_price=book.ask_price.at[1, 0].set(110),
        ask_qty=book.ask_qty.at[1, 0].set(5),
        ask_oid=book.ask_oid.at[1, 0].set(2),
    )
    before = snapshot_books(book)
    new_book, out = auction_step(cfg, book, np.ones((2,), dtype=bool))
    dec, fills = decode_auction(cfg, out)
    assert not dec.aborted and dec.fill_count == 0 and fills == []
    assert int(dec.executed[0]) == 0 and int(dec.executed[1]) == 0
    assert snapshot_books(new_book) == before


@pytest.mark.parametrize("kernel", ["matrix", "sorted"])
def test_auction_overflow_aborts_untouched(kernel):
    """A fill log too small for the bilateral records must abort the WHOLE
    auction with books unchanged — never a half-logged uncross (both
    formulations share the all-or-nothing rule)."""
    cfg = EngineConfig(num_symbols=1, capacity=16, batch=4, max_fills=4,
                       kernel=kernel)
    book = init_book(cfg)
    # 8 one-lot bids at 105 vs 8 one-lot asks at 100: 8 records > 4 slots.
    for k in range(8):
        book = book._replace(
            bid_price=book.bid_price.at[0, k].set(105),
            bid_qty=book.bid_qty.at[0, k].set(1),
            bid_oid=book.bid_oid.at[0, k].set(100 + k),
            bid_seq=book.bid_seq.at[0, k].set(k),
            ask_price=book.ask_price.at[0, k].set(100),
            ask_qty=book.ask_qty.at[0, k].set(1),
            ask_oid=book.ask_oid.at[0, k].set(200 + k),
            ask_seq=book.ask_seq.at[0, k].set(k),
        )
    before = snapshot_books(book)
    new_book, out = auction_step(cfg, book, np.ones((1,), dtype=bool))
    dec, fills = decode_auction(cfg, out)
    assert dec.aborted and dec.fill_count == 0 and fills == []
    assert int(dec.executed[0]) == 0 and int(dec.clear_price[0]) == 0
    assert snapshot_books(new_book) == before


def test_auction_priority_rationing():
    """The long side rations by price-time priority: better-priced bids
    fill fully, the marginal (time-latest at the marginal price) order
    gets the remainder."""
    cfg = EngineConfig(num_symbols=1, capacity=8, batch=4, max_fills=64)
    book = init_book(cfg)

    def lane(side, k, price, qty, oid, seq):
        return {
            f"{side}_price": getattr(book, f"{side}_price").at[0, k].set(price),
            f"{side}_qty": getattr(book, f"{side}_qty").at[0, k].set(qty),
            f"{side}_oid": getattr(book, f"{side}_oid").at[0, k].set(oid),
            f"{side}_seq": getattr(book, f"{side}_seq").at[0, k].set(seq),
        }

    # Bids: 10@102 (seq 0), 10@101 (seq 1), 10@101 (seq 2) — demand 30.
    # Asks: 15@100 (seq 0) — supply 15. p* = 101 region; executed 15.
    book = book._replace(**lane("bid", 0, 102, 10, 11, 0))
    book = book._replace(**lane("bid", 1, 101, 10, 12, 1))
    book = book._replace(**lane("bid", 2, 101, 10, 13, 2))
    book = book._replace(**lane("ask", 0, 100, 15, 21, 0))
    new_book, out = auction_step(cfg, book, np.ones((1,), dtype=bool))
    dec, fills = decode_auction(cfg, out)
    assert int(dec.executed[0]) == 15
    by_taker = {f.taker_oid: f.quantity for f in fills}
    # 102-bid fills fully (10); first 101-bid gets 5; second gets nothing.
    assert by_taker == {11: 10, 12: 5}
    assert all(f.maker_oid == 21 and f.quantity > 0 for f in fills)
    bq = np.asarray(new_book.bid_qty)[0]
    assert bq[0] == 0 and bq[1] == 5 and bq[2] == 10
    assert int(np.asarray(new_book.ask_qty)[0, 0]) == 0


# -- OP_REST (auction accumulation) parity ----------------------------------

def test_op_rest_accumulates_crossed_books():
    """OP_REST rests without matching — crossing orders stand; oracle.rest
    twin agrees on book state and statuses."""
    from matching_engine_tpu.engine.harness import apply_orders
    from matching_engine_tpu.engine.kernel import NEW, OP_REST, REJECTED

    cfg = EngineConfig(num_symbols=2, capacity=4, batch=4, max_fills=64)
    from matching_engine_tpu.engine.harness import HostOrder
    from matching_engine_tpu.proto import BUY, LIMIT, SELL

    ob = OracleBook(cfg.capacity)
    stream = []
    expected = []
    for oid, (side, price, qty) in enumerate([
        (BUY, 105, 5), (SELL, 100, 3),   # would cross under OP_SUBMIT
        (BUY, 104, 2), (SELL, 99, 1),
        (BUY, 103, 1), (BUY, 106, 2),    # 4th bid fills the side (cap 4)
    ], start=1):
        stream.append(HostOrder(sym=0, op=OP_REST, side=side, otype=LIMIT,
                                price=price, qty=qty, oid=oid))
        expected.append(ob.rest(oid, side, price, qty).status)
    book = init_book(cfg)
    book, results, fills = apply_orders(cfg, book, stream)
    assert fills == []                       # NOTHING matched
    assert [r.status for r in results] == expected
    assert all(st == NEW for st in expected)
    assert snapshot_books(book)[0] == ob.snapshot()

    # Capacity reject parity: a 5th bid on a 4-lane side.
    extra = HostOrder(sym=0, op=OP_REST, side=BUY, otype=LIMIT,
                      price=102, qty=1, oid=99)
    book, results, fills = apply_orders(cfg, book, [extra])
    assert results[0].status == REJECTED == ob.rest(99, BUY, 102, 1).status


# -- full serving flow: open auction -> uncross -> continuous ---------------

def test_auction_server_flow(tmp_path):
    """Boot in auction mode: submits rest (even crossing), MARKET rejected,
    RunAuction uncrosses at one price (fills in SQLite, audit clean), and
    continuous matching resumes afterwards."""
    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    cfg = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=256)
    server, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "auction.db"), cfg, window_ms=1.0,
        log=False)
    parts["runner"].auction_mode = True
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))

    def sub(client, side, price, qty, otype=pb2.LIMIT, symbol="AU"):
        return stub.SubmitOrder(
            pb2.OrderRequest(client_id=client, symbol=symbol, side=side,
                             order_type=otype, price=price, scale=4,
                             quantity=qty), timeout=15)

    try:
        # Crossing flow RESTS: bids 102x5, 101x5; asks 100x4, 101x3.
        oids = {}
        for who, side, price, qty in [
            ("b1", pb2.BUY, 102, 5), ("b2", pb2.BUY, 101, 5),
            ("a1", pb2.SELL, 100, 4), ("a2", pb2.SELL, 101, 3),
        ]:
            r = sub(who, side, price, qty)
            assert r.success, r.error_message
            oids[who] = r.order_id
        # MARKET rejected during the call period — and so is every other
        # immediate-execution tif (IOC/FOK demand continuous matching).
        rm = sub("m", pb2.BUY, 0, 1, otype=pb2.MARKET)
        assert not rm.success and "auction call period" in rm.error_message
        for tif in (pb2.TIF_IOC, pb2.TIF_FOK):
            rt = stub.SubmitOrder(
                pb2.OrderRequest(client_id="m", symbol="AU", side=pb2.BUY,
                                 order_type=pb2.LIMIT, price=101, scale=4,
                                 quantity=1, tif=tif), timeout=15)
            assert not rt.success and "auction call period" in rt.error_message

        # Book stands CROSSED (best bid >= best ask) — impossible under
        # continuous matching, the defining auction-mode state.
        book = stub.GetOrderBook(pb2.OrderBookRequest(symbol="AU"),
                                 timeout=10)
        assert len(book.bids) == 2 and len(book.asks) == 2

        # Uncross: demand(101)=10 vs supply(101)=7 -> p*=101, 7 executed.
        resp = stub.RunAuction(pb2.AuctionRequest(symbol="AU"), timeout=30)
        assert resp.success, resp.error_message
        assert resp.clearing_price == 101 and resp.executed_quantity == 7
        assert resp.symbols_crossed == 1
        # A per-symbol uncross does NOT end the call period (other symbols
        # may still stand crossed); the ALL-symbols uncross does.
        assert parts["runner"].auction_mode
        resp_all = stub.RunAuction(pb2.AuctionRequest(), timeout=30)
        assert resp_all.success
        assert not parts["runner"].auction_mode

        parts["sink"].flush()
        import sqlite3
        db = sqlite3.connect(str(tmp_path / "auction.db"))
        fills = db.execute(
            "select order_id, counter_order_id, price, quantity from fills"
        ).fetchall()
        assert sum(q for *_, q in fills) == 7
        assert all(p == 101 for _, _, p, _ in fills)
        # b1 fully filled (priority), b2 partial (2 of 5).
        rows = dict(
            (oid, (st, rem)) for oid, st, rem in db.execute(
                "select order_id, status, remaining_quantity from orders"))
        assert rows[oids["b1"]] == (2, 0)       # FILLED
        assert rows[oids["b2"]] == (1, 3)       # PARTIAL, 3 left
        assert rows[oids["a1"]] == (2, 0)
        assert rows[oids["a2"]] == (2, 0)
        db.close()

        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "scripts"))
        from audit import audit
        parts["sink"].flush()
        assert audit(str(tmp_path / "auction.db")) == []

        # Continuous trading resumed: a crossing submit now MATCHES.
        r1 = sub("c1", pb2.SELL, 101, 2)        # hits b2's resting 3@101
        assert r1.success
        parts["sink"].flush()
        db = sqlite3.connect(str(tmp_path / "auction.db"))
        n_fills = db.execute("select count(*) from fills").fetchone()[0]
        db.close()
        assert n_fills > len(fills)             # new continuous fill rows
    finally:
        shutdown(server, parts)


# -- sharded (mesh) auction --------------------------------------------------

@pytest.mark.parametrize("kernel", ["matrix", "sorted"])
def test_sharded_auction_matches_single_device(kernel):
    """The shard_map'd uncross produces bit-identical clearing prices,
    volumes, records, and post-auction books to the single-device step —
    for BOTH formulations (the sorted path's wide-limb volumes and
    boundary-merge records must survive shard_map unchanged)."""
    from matching_engine_tpu.parallel import ShardedEngine, make_mesh
    from matching_engine_tpu.parallel import hostlocal

    cfg = EngineConfig(num_symbols=8, capacity=32, batch=8,
                       max_fills=1 << 12, kernel=kernel)
    mask = np.ones((cfg.num_symbols,), dtype=bool)

    book1, _ = build_crossed_books(cfg, seed=11)
    host_copy = BookBatch(*(np.asarray(x) for x in book1))
    nb1, out1 = auction_step(cfg, book1, mask)
    dec1, fills1 = decode_auction(cfg, out1)

    mesh = make_mesh(8)
    eng = ShardedEngine(cfg, mesh)
    sbook = hostlocal.put_tree(host_copy, eng.book_sharding)
    nb2, out2 = eng.auction(sbook, mask)
    view, fills2, aborted = eng.decode_auction(out2)
    assert not aborted and not dec1.aborted

    np.testing.assert_array_equal(dec1.clear_price, view["clear_price"])
    np.testing.assert_array_equal(dec1.executed, view["executed"])
    np.testing.assert_array_equal(dec1.best_bid, view["best_bid"])
    np.testing.assert_array_equal(dec1.ask_size, view["ask_size"])
    assert canon(fills1) == canon(fills2)
    for f in BookBatch._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(nb1, f)), np.asarray(getattr(nb2, f)), f)


def test_auction_on_sharded_server(tmp_path):
    """The full auction flow on a mesh-sharded server (8 virtual devices):
    accumulate crossed, uncross through the RPC, continuous resumes."""
    import grpc

    from matching_engine_tpu.parallel import make_mesh
    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    cfg = EngineConfig(num_symbols=8, capacity=16, batch=4, max_fills=256)
    server, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "mesh-auction.db"), cfg,
        window_ms=1.0, log=False, mesh=make_mesh(8))
    parts["runner"].auction_mode = True
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))
    try:
        for who, side, price, qty in [
            ("b", pb2.BUY, 102, 5), ("a", pb2.SELL, 100, 3),
        ]:
            r = stub.SubmitOrder(
                pb2.OrderRequest(client_id=who, symbol="MAU", side=side,
                                 order_type=pb2.LIMIT, price=price, scale=4,
                                 quantity=qty), timeout=20)
            assert r.success, r.error_message
        resp = stub.RunAuction(pb2.AuctionRequest(), timeout=60)
        assert resp.success, resp.error_message
        assert resp.executed_quantity == 3 and resp.symbols_crossed == 1
        assert not parts["runner"].auction_mode
        # Continuous matching works post-uncross on the mesh.
        r = stub.SubmitOrder(
            pb2.OrderRequest(client_id="c", symbol="MAU", side=pb2.SELL,
                             order_type=pb2.LIMIT, price=102, scale=4,
                             quantity=2), timeout=20)
        assert r.success
        parts["sink"].flush()
        import sqlite3
        db = sqlite3.connect(str(tmp_path / "mesh-auction.db"))
        assert db.execute("select count(*) from fills").fetchone()[0] == 2
        db.close()
    finally:
        shutdown(server, parts)


def test_sharded_auction_per_shard_abort():
    """Mesh all-or-nothing is PER SHARD (no collectives — a lone host's
    RunAuction must not hang on peers): an overflowing shard keeps its
    symbols untouched while other shards uncross normally."""
    from matching_engine_tpu.parallel import ShardedEngine, hostlocal, make_mesh

    cfg = EngineConfig(num_symbols=8, capacity=16, batch=4, max_fills=4)
    arr = {f: (np.zeros((8,), dtype=np.int32) if f == "next_seq"
               else np.zeros((8, 16), dtype=np.int32))
           for f in BookBatch._fields}
    # Symbol 0 (shard 0): 8 one-lot pairs -> 8 records > max_fills=4.
    for k in range(8):
        arr["bid_price"][0, k] = 105
        arr["bid_qty"][0, k] = 1
        arr["bid_oid"][0, k] = 100 + k
        arr["bid_seq"][0, k] = k
        arr["ask_price"][0, k] = 100
        arr["ask_qty"][0, k] = 1
        arr["ask_oid"][0, k] = 200 + k
        arr["ask_seq"][0, k] = k
    # Symbol 4 (shard 4): one clean cross.
    arr["bid_price"][4, 0] = 50
    arr["bid_qty"][4, 0] = 2
    arr["bid_oid"][4, 0] = 300
    arr["ask_price"][4, 0] = 50
    arr["ask_qty"][4, 0] = 2
    arr["ask_oid"][4, 0] = 400
    book = BookBatch(**{k: jnp.asarray(v) for k, v in arr.items()})

    mesh = make_mesh(8)
    eng = ShardedEngine(cfg, mesh)
    sbook = hostlocal.put_tree(book, eng.book_sharding)
    nb, out = eng.auction(sbook, np.ones((8,), dtype=bool))
    view, fills, aborted_shards = eng.decode_auction(out)
    assert aborted_shards == 1
    assert int(view["executed"][0]) == 0          # aborted shard untouched
    assert int(view["executed"][4]) == 2          # healthy shard cleared
    assert sorted((f.sym, f.quantity) for f in fills) == [(4, 2)]
    np.testing.assert_array_equal(                # shard 0 books unchanged
        np.asarray(nb.bid_qty)[0], arr["bid_qty"][0])


def test_mesh_runner_partial_abort_semantics(tmp_path):
    """Runner-level per-shard abort contract on a mesh: an all-symbols
    uncross with one overflowing shard succeeds WITH a warning, keeps the
    auction call period open, and a request targeting only the aborted
    shard's symbol fails outright."""
    from matching_engine_tpu.parallel import make_mesh
    from matching_engine_tpu.server.engine_runner import EngineRunner

    cfg = EngineConfig(num_symbols=8, capacity=16, batch=4, max_fills=4)
    runner = EngineRunner(cfg, mesh=make_mesh(8))
    runner.auction_mode = True
    # Allocate one symbol per target slot (names hash-agnostic here:
    # single process owns everything; slots assigned in order).
    assert runner.slot_acquire("OVER") == 0
    for _ in range(4):
        runner.slot_acquire("FINE")  # slots assigned in order: FINE -> 1
    # Build the crossed state directly on the runner's book sharding.
    from matching_engine_tpu.parallel import hostlocal

    arr = {f: np.zeros((8, 16), dtype=np.int32)
           for f in BookBatch._fields if f != "next_seq"}
    arr["next_seq"] = np.zeros((8,), dtype=np.int32)
    slot_over, slot_fine = runner.symbols["OVER"], runner.symbols["FINE"]
    for k in range(8):   # 8 one-lot records > max_fills=4 on OVER's shard
        arr["bid_price"][slot_over, k] = 105
        arr["bid_qty"][slot_over, k] = 1
        arr["bid_oid"][slot_over, k] = 100 + k
        arr["bid_seq"][slot_over, k] = k
        arr["ask_price"][slot_over, k] = 100
        arr["ask_qty"][slot_over, k] = 1
        arr["ask_oid"][slot_over, k] = 200 + k
        arr["ask_seq"][slot_over, k] = k
    arr["bid_price"][slot_fine, 0] = 50
    arr["bid_qty"][slot_fine, 0] = 2
    arr["bid_oid"][slot_fine, 0] = 300
    arr["ask_price"][slot_fine, 0] = 50
    arr["ask_qty"][slot_fine, 0] = 2
    arr["ask_oid"][slot_fine, 0] = 400
    runner.place_book(BookBatch(**{k: np.asarray(v)
                                   for k, v in arr.items()}))

    # Target only the aborted shard's symbol: outright failure.
    s1 = runner.run_auction(["OVER"])
    assert s1["error"] and s1["aborted"] and s1["crossed"] == []
    assert runner.auction_mode

    # All symbols: success + warning, FINE cleared, call period stays open.
    s2 = runner.run_auction(None)
    assert not s2["error"] and s2["warning"], s2
    assert s2["aborted"] and [c[0] for c in s2["crossed"]] == ["FINE"]
    assert runner.auction_mode  # NOT opened: OVER still stands crossed


def test_call_period_survives_restart(tmp_path):
    """Open orders persisted during a call period replay as OP_REST (they
    rested without matching, so replay must not match them either); a
    crossed recovered book auto-resumes the call period, and the uncross
    then clears at the same price it would have pre-restart."""
    import grpc
    import sqlite3

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    db = str(tmp_path / "resume.db")
    cfg = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=256)

    server, port, parts = build_server("127.0.0.1:0", db, cfg,
                                       window_ms=1.0, log=False)
    parts["runner"].auction_mode = True
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))
    for who, side, price, qty in [("b", pb2.BUY, 102, 5),
                                  ("a", pb2.SELL, 100, 3)]:
        r = stub.SubmitOrder(
            pb2.OrderRequest(client_id=who, symbol="RST", side=side,
                             order_type=pb2.LIMIT, price=price, scale=4,
                             quantity=qty), timeout=15)
        assert r.success, r.error_message
    parts["sink"].flush()
    shutdown(server, parts)

    # Restart WITHOUT --auction-open: the crossed book must be detected.
    server2, port2, parts2 = build_server("127.0.0.1:0", db, cfg,
                                          window_ms=1.0, log=False)
    assert parts2["runner"].auction_mode, "call period not resumed"
    server2.start()
    stub2 = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port2}"))
    try:
        # Replay did NOT match the crossed pair: zero fills in the store.
        conn = sqlite3.connect(db)
        assert conn.execute("select count(*) from fills").fetchone()[0] == 0
        conn.close()
        book = stub2.GetOrderBook(pb2.OrderBookRequest(symbol="RST"),
                                  timeout=10)
        assert len(book.bids) == 1 and len(book.asks) == 1  # still crossed

        resp = stub2.RunAuction(pb2.AuctionRequest(symbol="RST"), timeout=30)
        assert resp.success, resp.error_message
        assert resp.clearing_price == 100 and resp.executed_quantity == 3
        parts2["sink"].flush()
        conn = sqlite3.connect(db)
        fills = conn.execute(
            "select order_id, counter_order_id, price, quantity from fills"
        ).fetchall()
        conn.close()
        assert fills == [("OID-1", "OID-2", 100, 3)]

        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "scripts"))
        from audit import audit
        assert audit(db) == []
    finally:
        shutdown(server2, parts2)


def test_non_crossed_call_period_survives_restart(tmp_path):
    """The call period is PERSISTED (server_meta), not inferred: a restart
    during a call period whose books happen not to stand crossed must
    still resume it — and after the opening cross, the next restart boots
    continuous."""
    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    db = str(tmp_path / "meta.db")
    cfg = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=256)

    server, port, parts = build_server("127.0.0.1:0", db, cfg,
                                       window_ms=1.0, log=False)
    parts["runner"].set_auction_mode(True)
    parts["runner"].flush_auction_mode()
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))
    # NON-crossing rests: bid 100 < ask 101.
    for who, side, price in [("b", pb2.BUY, 100), ("a", pb2.SELL, 101)]:
        r = stub.SubmitOrder(
            pb2.OrderRequest(client_id=who, symbol="NC", side=side,
                             order_type=pb2.LIMIT, price=price, scale=4,
                             quantity=2), timeout=15)
        assert r.success, r.error_message
    parts["sink"].flush()
    shutdown(server, parts)

    server2, port2, parts2 = build_server("127.0.0.1:0", db, cfg,
                                          window_ms=1.0, log=False)
    assert parts2["runner"].auction_mode, "persisted call period lost"
    server2.start()
    stub2 = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port2}"))
    # Still a call period: a crossing submit RESTS instead of matching.
    r = stub2.SubmitOrder(
        pb2.OrderRequest(client_id="c", symbol="NC", side=pb2.BUY,
                         order_type=pb2.LIMIT, price=101, scale=4,
                         quantity=1), timeout=15)
    assert r.success
    resp = stub2.RunAuction(pb2.AuctionRequest(), timeout=30)
    assert resp.success and resp.executed_quantity == 1
    assert not parts2["runner"].auction_mode
    parts2["sink"].flush()
    shutdown(server2, parts2)

    # Third boot: the CLEARED flag also persisted — continuous from boot.
    server3, port3, parts3 = build_server("127.0.0.1:0", db, cfg,
                                          window_ms=1.0, log=False)
    assert not parts3["runner"].auction_mode
    shutdown(server3, parts3)


def test_auction_mode_persist_failure_self_heals():
    """A failed durable write keeps the dirty bit, so the next flush point
    retries instead of stranding the mode transition."""
    from matching_engine_tpu.server.engine_runner import EngineRunner

    r = EngineRunner(EngineConfig(num_symbols=2, capacity=8, batch=4,
                                  max_fills=64))
    calls = []

    def flaky(value):
        calls.append(value)
        return len(calls) > 1  # first write fails, second succeeds

    r.persist_auction_mode = flaky
    r.set_auction_mode(True)
    r.flush_auction_mode()            # fails -> stays dirty, warns
    assert calls == [True]
    assert r.metrics.snapshot()[0].get("meta_persist_failures") == 1
    r.flush_auction_mode()            # retries and succeeds
    assert calls == [True, True]
    r.flush_auction_mode()            # clean: no further writes
    assert calls == [True, True]


def test_flush_auction_mode_concurrent_flip():
    """A mode flip landing DURING a flush's persist must not be lost:
    flush clears the dirty bit BEFORE reading the value, so the flip
    re-marks dirty and the next flush persists it. The historical
    persist-then-clear order would clear the concurrent flip's dirty
    bit without ever writing its value — a restart would resume the
    wrong trading mode (lockset analyzer finding, PR 10)."""
    from matching_engine_tpu.server.engine_runner import EngineRunner

    r = EngineRunner(EngineConfig(num_symbols=2, capacity=8, batch=4,
                                  max_fills=64))
    calls = []

    def persist(value):
        calls.append(value)
        if len(calls) == 1:
            # Models another thread flipping the mode mid-persist.
            r.set_auction_mode(True)
        return True

    r.persist_auction_mode = persist
    r.set_auction_mode(False)
    r.flush_auction_mode()
    assert calls == [False]
    assert r._mode_dirty, "the mid-persist flip must keep the flag dirty"
    r.flush_auction_mode()
    assert calls == [False, True]
    assert not r._mode_dirty


def test_auction_rpc_full_abort_maps_to_failure(tmp_path):
    """An uncross whose record log cannot fit fails the RPC (success=false
    + raise-max_fills message) and leaves the books untouched."""
    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    cfg = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=4)
    server, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "abort.db"), cfg, window_ms=1.0,
        log=False)
    parts["runner"].auction_mode = True
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))
    try:
        for k in range(6):  # 6 one-lot pairs -> 6 records > max_fills=4
            for who, side, price in [(f"b{k}", pb2.BUY, 105),
                                     (f"a{k}", pb2.SELL, 100)]:
                r = stub.SubmitOrder(
                    pb2.OrderRequest(client_id=who, symbol="AB", side=side,
                                     order_type=pb2.LIMIT, price=price,
                                     scale=4, quantity=1), timeout=15)
                assert r.success, r.error_message
        resp = stub.RunAuction(pb2.AuctionRequest(symbol="AB"), timeout=30)
        assert not resp.success
        assert "max_fills" in resp.error_message
        # Books untouched; the call period stays open.
        book = stub.GetOrderBook(pb2.OrderBookRequest(symbol="AB"),
                                 timeout=10)
        assert len(book.bids) == 6 and len(book.asks) == 6
        assert parts["runner"].auction_mode
    finally:
        shutdown(server, parts)


def test_auction_no_cross_is_signaled(tmp_path):
    """A single-symbol RunAuction whose book cannot cross returns
    success=true with an explicit note (ADVICE r3: '0@Q4 x0' alone was
    indistinguishable from a tiny real clear)."""
    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    cfg = EngineConfig(num_symbols=4, capacity=16, batch=4, max_fills=256)
    server, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "nocross.db"), cfg, window_ms=1.0,
        log=False)
    # Call period open: submits REST (a crossing pair must stand crossed
    # until the uncross, not match continuously at submit time).
    parts["runner"].auction_mode = True
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))
    try:
        # Non-crossing book: bid 100 < ask 105.
        for who, side, price in (("b", pb2.BUY, 100), ("a", pb2.SELL, 105)):
            r = stub.SubmitOrder(
                pb2.OrderRequest(client_id=who, symbol="NC", side=side,
                                 order_type=pb2.LIMIT, price=price, scale=4,
                                 quantity=5), timeout=15)
            assert r.success, r.error_message
        resp = stub.RunAuction(pb2.AuctionRequest(symbol="NC"), timeout=30)
        assert resp.success
        assert resp.symbols_crossed == 0 and resp.executed_quantity == 0
        assert "did not cross" in resp.error_message
        # A crossing book clears WITHOUT the note.
        for who, side, price in (("b2", pb2.BUY, 106), ("a2", pb2.SELL, 104)):
            r = stub.SubmitOrder(
                pb2.OrderRequest(client_id=who, symbol="NC2", side=side,
                                 order_type=pb2.LIMIT, price=price, scale=4,
                                 quantity=5), timeout=15)
            assert r.success, r.error_message
        resp2 = stub.RunAuction(pb2.AuctionRequest(symbol="NC2"), timeout=30)
        assert resp2.success and resp2.symbols_crossed == 1
        assert "did not cross" not in resp2.error_message
    finally:
        shutdown(server, parts)


def test_sharded_auction_at_venue_depth():
    """The deployment combination an operator actually runs for deep
    books: sorted kernel + capacity 2048 + an 8-device mesh. Wide-limb
    executed volumes and boundary-merge records must survive shard_map
    at depth (not just at the toy capacity above)."""
    from matching_engine_tpu.domain.order import MAX_QUANTITY
    from matching_engine_tpu.parallel import ShardedEngine, hostlocal, make_mesh

    cap = 2048
    cfg = EngineConfig(num_symbols=8, capacity=cap, batch=8,
                       max_fills=1 << 14, kernel="sorted")
    rng = np.random.default_rng(23)
    s = cfg.num_symbols
    arr = {f: np.zeros((s, cap), dtype=np.int32)
           for f in BookBatch._fields if f != "next_seq"}
    oracles = {i: OracleBook(cap) for i in range(s)}
    oid = 1
    n_side = 600  # x ~MAX_QUANTITY: deep into the wide-sum regime
    for i in range(s):
        seq = 0
        for side in ("bid", "ask"):
            for k in range(n_side):
                price = int(10_002 + rng.integers(0, 4)) if side == "bid" \
                    else int(9_995 + rng.integers(0, 4))
                qty = int(MAX_QUANTITY - rng.integers(0, 1000))
                arr[f"{side}_price"][i, k] = price
                arr[f"{side}_qty"][i, k] = qty
                arr[f"{side}_oid"][i, k] = oid
                arr[f"{side}_seq"][i, k] = seq
                (oracles[i].bids if side == "bid" else
                 oracles[i].asks).append(_Resting(oid, price, qty, seq))
                oid += 1
                seq += 1
        oracles[i].next_seq = seq
    host = BookBatch(**{k: np.asarray(v) for k, v in arr.items()},
                     next_seq=np.full((s,), 2 * n_side, np.int32))

    mesh = make_mesh(8)
    eng = ShardedEngine(cfg, mesh)
    sbook = hostlocal.put_tree(host, eng.book_sharding)
    nb, out = eng.auction(sbook, np.ones((s,), dtype=bool))
    view, fills, aborted = eng.decode_auction(out)
    assert aborted == 0

    expected = []
    for i, ob in oracles.items():
        p, q, ofills = ob.auction()
        assert q > 2**30  # the wide regime per symbol
        assert int(view["clear_price"][i]) == p
        assert int(view["executed"][i]) == q
        expected.extend(canon_oracle(i, ofills))
    assert canon(fills) == sorted(expected)
    snaps = snapshot_books(nb)
    for i, ob in oracles.items():
        assert snaps[i] == ob.snapshot(), f"symbol {i}"


def test_wide_limb_arithmetic_properties():
    """Direct property checks of the base-2^15 two-limb helpers
    (engine/auction_sorted.py) against Python big-int arithmetic over
    random and extreme values — the primitives every venue-depth
    clearing-price comparison rests on."""
    import random

    from matching_engine_tpu.engine import auction_sorted as ws

    rng = random.Random(3)

    def val(hi, lo):
        return int(hi) * (1 << 15) + int(lo)

    qs = [0, 1, 2_000_000, 1_999_999] + [rng.randrange(0, 2_000_001)
                                         for _ in range(60)]
    arr = jnp.asarray(np.array(qs, np.int32))
    hi, lo = ws._w_cumsum(arr)
    run = 0
    for i, q in enumerate(qs):
        run += q
        assert val(hi[i], lo[i]) == run
        assert 0 <= int(lo[i]) < (1 << 15)  # canonical form

    # Subtraction + abs, including negative results, vs Python ints.
    for _ in range(50):
        a = rng.randrange(0, 8192 * 2_000_000)
        b = rng.randrange(0, 8192 * 2_000_000)
        ah, al = jnp.int32(a >> 15), jnp.int32(a & 0x7FFF)
        bh, bl = jnp.int32(b >> 15), jnp.int32(b & 0x7FFF)
        dh, dl = ws._w_sub(ah, al, bh, bl)
        assert val(dh, dl) == a - b
        xh, xl = ws._w_abs(dh, dl)
        assert val(xh, xl) == abs(a - b)
        assert bool(ws._w_le(ah, al, bh, bl)) == (a <= b)
