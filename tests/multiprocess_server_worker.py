"""Worker for the 2-process FULL-SERVER multihost test.

Each of the two processes boots the complete serving stack
(build_server: grpcio edge, dispatcher, SQLite sink, streams) over the
SAME global 8-device mesh, with its own database — the deployment model
parallel/multihost.py documents. Asserts:

- orders for the host's own symbol range flow end to end (RPC -> sharded
  dispatch -> fills -> own SQLite),
- orders for symbols HOMED on the other host are rejected at admission
  (symbol_home name hash — slot recycling must never let two hosts book
  the same name),
- the SAME contract holds through the C++ gateway edge (when the native
  library is built): a grpcio stub pointed at each host's gateway port
  books an owned symbol and gets the foreign-symbol reject,
- the per-host database audits clean.
"""

import json
import os
import sys


def main() -> None:
    port, pid_s, outdir = sys.argv[1], sys.argv[2], sys.argv[3]
    pid = int(pid_s)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from matching_engine_tpu.parallel.multihost import (
        initialize,
        local_symbol_slice,
        make_multihost_mesh,
        symbol_home,
    )

    assert initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2,
        process_id=pid,
    )
    mesh = make_multihost_mesh()

    import grpc

    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    from matching_engine_tpu import native as me_native

    S = 8
    cfg = EngineConfig(num_symbols=S, capacity=16, batch=4, max_fills=256)
    sl = local_symbol_slice(mesh, S)
    db = os.path.join(outdir, f"host{pid}.db")
    gw_addr = "127.0.0.1:0" if me_native.gateway_available() else None
    server, sport, parts = build_server(
        "127.0.0.1:0", db, cfg, window_ms=1.0, log=False, mesh=mesh,
        gateway_addr=gw_addr,
    )
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{sport}"))

    def submit(sym, side, qty):
        # Client id differs per side: self-trade prevention (always on)
        # would otherwise suppress the crossing fills this test asserts.
        return stub.SubmitOrder(
            pb2.OrderRequest(client_id=f"h{pid}-s{side}", symbol=sym,
                             order_type=pb2.LIMIT, side=side, price=10_000,
                             scale=4, quantity=qty),
            timeout=60)

    # Ownership is by symbol NAME (stable hash), not slot index — slots
    # recycle, names don't. Serve the first 4 symbols homed here; pick one
    # homed on the other host for the rejection probe.
    candidates = [f"SYM{i}" for i in range(64)]
    mine = [s for s in candidates if symbol_home(s, 2) == pid][:4]
    theirs = next(s for s in candidates if symbol_home(s, 2) != pid)
    assert len(mine) == 4

    fills = 0
    for sym in mine:
        r1 = submit(sym, pb2.BUY, 5)
        r2 = submit(sym, pb2.SELL, 5)
        assert r1.success and r2.success, (sym, r1.error_message)
        fills += 1
    # Foreign-homed symbol: admission must reject — slot recycling must
    # NOT let this host book a symbol the other host owns.
    rr = submit(theirs, pb2.BUY, 1)
    assert not rr.success and "homed on another host" in rr.error_message, rr

    # Same contract through the C++ gateway edge: the bridge enforces
    # symbol_home ownership before the sharded dispatch ever sees the op.
    gw_orders = 0
    if parts.get("gateway_port"):
        gw = MatchingEngineStub(
            grpc.insecure_channel(f"127.0.0.1:{parts['gateway_port']}"))
        g1 = gw.SubmitOrder(
            pb2.OrderRequest(client_id=f"gw{pid}", symbol=mine[0],
                             order_type=pb2.LIMIT, side=pb2.BUY,
                             price=9_000, scale=4, quantity=1),
            timeout=60)
        assert g1.success, g1.error_message
        gw_orders = 1
        g2 = gw.SubmitOrder(
            pb2.OrderRequest(client_id=f"gw{pid}", symbol=theirs,
                             order_type=pb2.LIMIT, side=pb2.BUY,
                             price=9_000, scale=4, quantity=1),
            timeout=60)
        assert not g2.success, g2
        assert "homed on another host" in g2.error_message, g2.error_message

    # Call auction on the 2-process mesh: the uncross has ZERO collectives
    # (per-shard all-or-nothing), so each host runs RunAuction
    # independently — no cross-host coordination, same as dispatches.
    # The probe symbol is the 5th name HOMED on this host, so the leg
    # runs unconditionally on BOTH workers.
    parts["runner"].auction_mode = True
    au_sym = [s for s in candidates if symbol_home(s, 2) == pid][4]
    r1 = submit(au_sym, pb2.BUY, 4)     # rests (auction mode)
    r2 = submit(au_sym, pb2.SELL, 4)    # rests CROSSED at one price
    assert r1.success and r2.success, (r1.error_message, r2.error_message)
    au_orders, au_fills = 2, 1
    resp = stub.RunAuction(pb2.AuctionRequest(), timeout=60)
    assert resp.success, resp.error_message
    assert resp.executed_quantity == 4 and resp.symbols_crossed == 1
    assert not parts["runner"].auction_mode

    parts["sink"].flush()
    import sqlite3

    conn = sqlite3.connect(db)
    n_orders = conn.execute("SELECT COUNT(*) FROM orders").fetchone()[0]
    n_fills = conn.execute("SELECT COUNT(*) FROM fills").fetchone()[0]
    conn.close()
    assert n_orders == 2 * len(mine) + gw_orders + au_orders, n_orders
    assert n_fills == fills + au_fills, (n_fills, fills, au_fills)

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
    from audit import audit

    assert audit(db) == []

    shutdown(server, parts)
    with open(os.path.join(outdir, f"srv-ok-{pid}.json"), "w") as f:
        json.dump({"pid": pid, "orders": n_orders, "fills": n_fills,
                   "gateway_ran": gw_orders > 0,
                   "auction_orders": au_orders,
                   "slice": [sl.start, sl.stop]}, f)


if __name__ == "__main__":
    main()
