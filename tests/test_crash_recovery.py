"""Crash durability: SIGKILL the serving process mid-load, restart, audit.

The reference's whole durability story is WAL SQLite + OID reseed
(SURVEY.md §5.3-5.4) but nothing ever tests a hard kill. Here: a real
server subprocess takes traffic, dies with SIGKILL (no drain, no flush),
and a fresh in-process server on the same DB must (a) pass the integrity
audit, (b) resume the OID sequence past everything persisted, (c) rebuild
books that reflect the persisted open orders.
"""

import importlib.util
import os
import pathlib
import socket
import signal
import subprocess
import sys
import time

import grpc

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.storage import Storage

REPO = pathlib.Path(__file__).resolve().parent.parent

_spec = importlib.util.spec_from_file_location("audit", REPO / "scripts" / "audit.py")
audit_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(audit_mod)


def _wait_port(port: int, proc, stderr_path, timeout_s: float = 90.0) -> None:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"server exited rc={proc.returncode} during startup:\n"
                + stderr_path.read_text()[-2000:])
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.5).close()
            return
        except OSError:
            time.sleep(0.3)
    raise TimeoutError(
        f"server on :{port} never came up:\n" + stderr_path.read_text()[-2000:])


def _spawn_server(tmp_path, db: str, *extra_args: str):
    """One copy of the CPU server-subprocess spawn recipe (OS-assigned
    free port — the subprocess boundary forbids :0 directly; env scrubbed
    of the TPU tunnel so the test can never touch it). Returns
    (proc, port, stderr_path); callers own waiting and cleanup."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU; never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{env.get('PYTHONPATH', '')}:{REPO}"
    stderr_path = tmp_path / "server.err"
    proc = subprocess.Popen(
        [sys.executable, "-m", "matching_engine_tpu.server.main",
         "--addr", f"127.0.0.1:{port}", "--db", db,
         "--symbols", "8", "--capacity", "16", "--batch", "4",
         "--window-ms", "1", *extra_args],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=stderr_path.open("w"),
    )
    return proc, port, stderr_path


def _wait_rows(db: str, min_rows: int, timeout_s: float = 60.0) -> int:
    """Poll until the async sink lands >= min_rows orders in the WAL;
    returns the observed count (callers assert on it so a timeout fails
    at the wait, not at a misleading later assertion)."""
    import sqlite3

    deadline = time.time() + timeout_s
    n = 0
    while time.time() < deadline:
        try:
            conn = sqlite3.connect(db)
            try:
                n = conn.execute("SELECT COUNT(*) FROM orders").fetchone()[0]
            finally:
                conn.close()
            if n >= min_rows:
                break
        except sqlite3.Error:
            pass
        time.sleep(0.2)
    return n


def test_sigkill_midload_then_restart_audits_clean(tmp_path):
    db = str(tmp_path / "crash.db")
    proc, port, stderr_path = _spawn_server(tmp_path, db)
    try:
        _wait_port(port, proc, stderr_path)
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        stub = MatchingEngineStub(ch)
        accepted = []
        for i in range(30):
            side = pb2.BUY if i % 3 else pb2.SELL
            r = stub.SubmitOrder(pb2.OrderRequest(
                client_id="c", symbol=f"S{i % 4}", order_type=pb2.LIMIT,
                side=side, price=10_000 + (i % 7), scale=4, quantity=5),
                timeout=60)
            assert r.success
            accepted.append(r.order_id)
        ch.close()
        # Futures resolve when the storage batch is ENQUEUED, not committed
        # (dispatcher read-your-writes contract is via sink.flush()); wait
        # until the async sink has landed at least one WAL transaction so
        # SIGKILL provably interrupts a server with durable state.
        assert _wait_rows(db, 1) >= 1
    finally:
        proc.kill()  # SIGKILL: no drain, no sink flush, no final checkpoint
        proc.wait(timeout=30)

    # (a) whatever reached the WAL is internally consistent
    assert audit_mod.audit(db) == []

    store = Storage(db)
    assert store.init()
    persisted = store.count("orders")
    # SIGKILL may lose the async sink's tail, never corrupt what landed.
    assert 0 < persisted <= 30

    # (b)+(c) a fresh server on the same DB resumes cleanly
    server, port2, parts = build_server(
        "127.0.0.1:0", db, EngineConfig(num_symbols=8, capacity=16, batch=4),
        window_ms=1.0, log=False)
    server.start()
    try:
        runner = parts["runner"]
        # The OID sequence must resume PAST every persisted id.
        max_persisted = max(
            (int(row[0].split("-")[1]) for row in store._conn.execute(
                "SELECT order_id FROM orders")), default=0)
        assert runner.next_oid_num > max_persisted
        # New ids never collide with persisted ones.
        ch = grpc.insecure_channel(f"127.0.0.1:{port2}")
        stub = MatchingEngineStub(ch)
        r = stub.SubmitOrder(pb2.OrderRequest(
            client_id="c", symbol="S0", order_type=pb2.LIMIT, side=pb2.BUY,
            price=9_999, scale=4, quantity=1), timeout=60)
        assert r.success
        assert int(r.order_id.split("-")[1]) > 0
        assert r.order_id not in set(accepted[:persisted])
        # Books reflect persisted open orders: every NEW/PARTIAL LIMIT row
        # appears in its symbol's snapshot.
        open_rows = store.open_orders()
        for (order_id, _c, symbol, side, _t, _p, _q, remaining, _s) in open_rows:
            bids, asks = runner.book_snapshot(symbol)
            found = [q for info, q in (bids + asks) if info.order_id == order_id]
            assert found == [remaining], (order_id, found, remaining)
        ch.close()
    finally:
        shutdown(server, parts)
        store.close()


def test_profile_dir_captures_trace(tmp_path):
    """--profile-dir produces a non-empty jax.profiler trace for a real
    serving run (VERDICT r3 next-step 9: tracing was mechanism-only — no
    test ever exercised the flag)."""
    db = str(tmp_path / "prof.db")
    trace_dir = tmp_path / "trace"
    proc, port, stderr_path = _spawn_server(
        tmp_path, db, "--profile-dir", str(trace_dir))
    try:
        _wait_port(port, proc, stderr_path)
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        stub = MatchingEngineStub(ch)
        for i in range(5):
            r = stub.SubmitOrder(pb2.OrderRequest(
                client_id="p", symbol="PRF", order_type=pb2.LIMIT,
                side=pb2.BUY, price=10_000 + i, scale=4, quantity=1),
                timeout=60)
            assert r.success
        ch.close()
        # Graceful drain: stop_trace runs on the shutdown path.
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0, stderr_path.read_text()[-2000:]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    files = [os.path.join(r, f) for r, _, fs in os.walk(trace_dir) for f in fs]
    assert files, "trace directory is empty"
    assert sum(os.path.getsize(f) for f in files) > 0


def test_sigkill_during_venue_depth_call_period_resumes_auction(tmp_path):
    """Round-5 behavior: a venue-depth (capacity 2048, sorted kernel)
    server killed mid call-period must RESUME the call period on restart
    (crossed books + persisted auction_mode at a capacity where the
    uncross only now exists — engine/auction_sorted.py), and the resumed
    server's RunAuction must clear the recovered crossed interest."""
    db = str(tmp_path / "venue.db")
    proc, port, stderr_path = _spawn_server(
        tmp_path, db, "--capacity", "2048", "--engine-kernel", "sorted",
        "--auction-open")
    try:
        _wait_port(port, proc, stderr_path, timeout_s=180)
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        stub = MatchingEngineStub(ch)
        for client, side, price in (("alice", pb2.BUY, 101_0000),
                                    ("bob", pb2.SELL, 100_0000)):
            r = stub.SubmitOrder(pb2.OrderRequest(
                client_id=client, symbol="AU", order_type=pb2.LIMIT,
                side=side, price=price, scale=4, quantity=7), timeout=120)
            assert r.success
        ch.close()
        assert _wait_rows(db, 2) >= 2, "rests never reached the WAL"
    finally:
        proc.kill()
        proc.wait(timeout=30)

    assert audit_mod.audit(db) == []
    server, port2, parts = build_server(
        "127.0.0.1:0", db,
        EngineConfig(num_symbols=8, capacity=2048, batch=4,
                     kernel="sorted"),
        window_ms=1.0, log=False)
    server.start()
    try:
        runner = parts["runner"]
        assert runner.auction_mode, "call period must resume at venue depth"
        assert runner.crossed_symbols() == ["AU"]
        summary = runner.run_auction(sink=parts["sink"])
        assert summary["error"] == ""
        assert [c[0] for c in summary["crossed"]] == ["AU"]
        assert summary["crossed"][0][2] == 7
        assert not runner.auction_mode  # continuous reopened
        assert runner.crossed_symbols() == []
    finally:
        shutdown(server, parts)
