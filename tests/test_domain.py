"""Submit-time validation semantics (domain/order.py).

Mirrors the reference's reject conditions (matching_engine_service.cpp:66-83)
plus this framework's device-range guards.
"""

import pytest

from matching_engine_tpu.domain import Order, validate_submit
from matching_engine_tpu.domain.order import MAX_QUANTITY
from matching_engine_tpu.proto import BUY, LIMIT, MARKET, SELL, pb2


def req(**kw):
    base = dict(
        client_id="c", symbol="SYM", order_type=LIMIT, side=BUY, price=1005,
        scale=2, quantity=10,
    )
    base.update(kw)
    return pb2.OrderRequest(**base)


def test_valid_passes():
    assert validate_submit(req()) is None
    assert validate_submit(req(order_type=MARKET, price=0)) is None
    assert validate_submit(req(side=SELL)) is None


def test_missing_symbol_rejects():
    assert "symbol" in validate_submit(req(symbol=""))


def test_nonpositive_quantity_rejects():
    assert "quantity" in validate_submit(req(quantity=0))
    assert "quantity" in validate_submit(req(quantity=-5))


def test_quantity_above_engine_max_rejects():
    assert validate_submit(req(quantity=MAX_QUANTITY)) is None
    msg = validate_submit(req(quantity=MAX_QUANTITY + 1))
    assert msg and "quantity" in msg


def test_limit_needs_positive_price():
    assert "price" in validate_submit(req(price=0))
    assert "price" in validate_submit(req(price=-1))
    # MARKET ignores price
    assert validate_submit(req(order_type=MARKET, price=0)) is None


def test_unspecified_side_rejects():
    assert "side" in validate_submit(req(side=0))


def test_bad_scale_rejects():
    assert "scale" in validate_submit(req(scale=19))
    assert "scale" in validate_submit(req(order_type=MARKET, scale=-1))


def test_subq4_price_rejects():
    # 10050 at scale 9 truncates to 0 at Q4 -> unpriceable limit order.
    assert "zero" in validate_submit(req(price=10050, scale=9))


def test_int32_lane_guard():
    msg = validate_submit(req(price=300_000, scale=0))
    assert msg and "int32" in msg


def test_order_from_raw_normalizes():
    o = Order.from_raw("OID-1", "c", "SYM", price=100500000, scale=8,
                       quantity=5, side=BUY)
    assert o.price_q4 == 10050
