"""Checkpoint/restore: snapshot fidelity, delta reconcile, daemon retention.

The recovery semantics under test are this framework's additions — the
reference never rebuilds book state at all (SURVEY.md §5.4). Parity oracle:
a restored server must serve the same book as the server that never died.
"""

import grpc
import pytest

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.harness import snapshot_books
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.utils.checkpoint import (
    CheckpointDaemon,
    latest_checkpoint,
    restore_runner,
    save_checkpoint,
)

CFG = EngineConfig(num_symbols=8, capacity=16, batch=4)


class Harness:
    def __init__(self, db_path, ckpt_dir=None, interval=3600.0):
        self.server, self.port, self.parts = build_server(
            "127.0.0.1:0", str(db_path), CFG, window_ms=1.0, log=False,
            checkpoint_dir=str(ckpt_dir) if ckpt_dir else None,
            checkpoint_interval_s=interval,
        )
        self.server.start()
        self.channel = grpc.insecure_channel(f"127.0.0.1:{self.port}")
        self.stub = MatchingEngineStub(self.channel)

    def close(self, checkpoint=True):
        self.channel.close()
        if not checkpoint and self.parts.get("checkpointer") is not None:
            self.parts["checkpointer"].close()
            self.parts["checkpointer"] = None
        shutdown(self.server, self.parts)


def submit(stub, symbol="SYM", side=pb2.BUY, price=10000, qty=5, otype=pb2.LIMIT):
    return stub.SubmitOrder(
        pb2.OrderRequest(client_id="c1", symbol=symbol, order_type=otype,
                         side=side, price=price, scale=4, quantity=qty),
        timeout=10,
    )


def books_of(parts):
    return snapshot_books(parts["runner"].book)


def test_checkpoint_restore_round_trip(tmp_path):
    h = Harness(tmp_path / "a.db", ckpt_dir=tmp_path / "ck")
    for i in range(6):
        r = submit(h.stub, symbol=f"S{i % 3}", price=10000 + i, qty=3 + i)
        assert r.success
    h.parts["sink"].flush()
    want_books = books_of(h.parts)
    want_orders = dict(h.parts["runner"].orders_by_id)
    h.close()  # shutdown writes a final checkpoint

    ck = latest_checkpoint(str(tmp_path / "ck"))
    assert ck is not None

    h2 = Harness(tmp_path / "a.db", ckpt_dir=tmp_path / "ck")
    assert books_of(h2.parts) == want_books
    assert set(h2.parts["runner"].orders_by_id) == set(want_orders)
    # The restored server keeps trading correctly: cross one resting bid.
    r = submit(h2.stub, symbol="S0", side=pb2.SELL, price=10000, qty=1)
    assert r.success
    h2.close(checkpoint=False)


def test_restore_reconciles_post_snapshot_delta(tmp_path):
    h = Harness(tmp_path / "b.db", ckpt_dir=tmp_path / "ck")
    assert submit(h.stub, symbol="AAA", price=10000, qty=5).success
    ck = h.parts["checkpointer"].checkpoint_now()
    # Post-snapshot activity: a new resting order + a partial fill of the
    # snapshotted one.
    assert submit(h.stub, symbol="AAA", price=9000, qty=7).success
    assert submit(h.stub, symbol="AAA", side=pb2.SELL, price=10000, qty=2).success
    h.parts["sink"].flush()
    want = books_of(h.parts)
    h.close(checkpoint=False)  # crash: die with only the older snapshot

    h2 = Harness(tmp_path / "b.db", ckpt_dir=tmp_path / "ck")
    got = books_of(h2.parts)
    # Books must match order-for-order (oid, price, qty) — seq values may
    # differ after replay, so compare without them.
    strip = lambda snaps: [
        ([(o, p, q) for (o, p, q, _) in bids], [(o, p, q) for (o, p, q, _) in asks])
        for bids, asks in snaps
    ]
    assert strip(got) == strip(want)
    h2.close(checkpoint=False)


def test_config_mismatch_falls_back_to_replay(tmp_path):
    h = Harness(tmp_path / "c.db", ckpt_dir=tmp_path / "ck")
    assert submit(h.stub, price=11000, qty=2).success
    h.close()  # final checkpoint with CFG

    from matching_engine_tpu.server.engine_runner import EngineRunner

    other = EngineConfig(num_symbols=4, capacity=8, batch=2)
    runner = EngineRunner(other)
    with pytest.raises(ValueError):
        restore_runner(runner, latest_checkpoint(str(tmp_path / "ck")))
    # build_server catches this and replays from SQLite instead.
    server, port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "c.db"), other, log=False,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    snaps = snapshot_books(parts["runner"].book)
    assert any(bids for bids, _ in snaps)  # the resting order came back
    parts["checkpointer"].close()
    parts["checkpointer"] = None
    shutdown(server, parts)


def test_daemon_prunes_old_checkpoints(tmp_path):
    h = Harness(tmp_path / "d.db", ckpt_dir=tmp_path / "ck")
    daemon = h.parts["checkpointer"]
    for _ in range(5):
        daemon.checkpoint_now()
    import os

    kept = [n for n in os.listdir(tmp_path / "ck") if n.startswith("ckpt-")]
    assert len(kept) <= daemon.keep
    h.close(checkpoint=False)


def test_cfg_from_meta_tolerates_retired_fields():
    """Snapshots written when EngineConfig still had execution-strategy
    knobs (round-1 pallas flags, retired round 3) must keep loading."""
    from matching_engine_tpu.utils.checkpoint import _cfg_from_meta

    cfg = _cfg_from_meta({"cfg": {
        "num_symbols": 8, "capacity": 16, "batch": 4, "max_fills": 256,
        "pallas": False, "pallas_interpret": None,
    }})
    assert cfg.semantic_key() == (8, 16, 4, 256, "matrix", 0, ())
