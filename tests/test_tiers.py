"""Tiered capacity classes: --book-tiers spec, TieredEngineRunner parity,
tier routing, metered capacity backpressure, and restart semantics.

The tier split must be INVISIBLE to everything above the runner: a
tiered runner over the same (symbol -> slot, capacity) layout produces
bit-identical outcomes, storage rows, fills, and market data to an
untiered one (the per-tier decode merges in ascending tier order ==
global device order). What tiers ADD: deep books for pinned hot symbols
without venue-wide [S, deep] lanes, full-book rejects as metered
backpressure (me_book_capacity_rejects_total + per-tier series), the
per-tier high-watermark re-tiering signal, and a checkpoint format that
refuses to restore under a changed spec (full-replay fallback).
"""

from __future__ import annotations

import random

import pytest

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.kernel import (
    CANCELED,
    NEW,
    OP_CANCEL,
    OP_REST,
    OP_SUBMIT,
    REJECTED,
)
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.server.engine_runner import (
    EngineOp,
    EngineRunner,
    OrderInfo,
)
from matching_engine_tpu.server.tiered_runner import (
    TieredEngineRunner,
    parse_book_tiers,
)
from matching_engine_tpu.utils.checkpoint import (
    restore_runner,
    save_checkpoint,
)

SPEC = "2x64:HOT,*x16"
S = 8


def make_tiered(megadispatch_max_waves=1, oid_offset=0, oid_stride=1):
    tiers, pins = parse_book_tiers(SPEC, S)
    cfg = EngineConfig(num_symbols=S, capacity=64, batch=4, tiers=tiers)
    return TieredEngineRunner(cfg, tier_pins=pins,
                              megadispatch_max_waves=megadispatch_max_waves,
                              oid_offset=oid_offset, oid_stride=oid_stride)


def submit_info(runner, sym, side, price, qty, client="c"):
    assert runner.slot_acquire(sym) is not None
    num, oid = runner.assign_oid()
    return OrderInfo(
        oid=num, order_id=oid, client_id=client, symbol=sym, side=side,
        otype=pb2.LIMIT, price_q4=price, quantity=qty, remaining=qty,
        status=0, handle=runner.assign_handle())


# -- spec parsing ------------------------------------------------------------


def test_parse_spec_star_and_pins():
    tiers, pins = parse_book_tiers("8x8192:HOT-0;HOT-1,56x1024,*x128", 1024)
    assert tiers == ((8, 8192), (56, 1024), (960, 128))
    assert pins == {"HOT-0": 0, "HOT-1": 0}


@pytest.mark.parametrize("spec,err", [
    ("", "empty"),
    ("8y128", "malformed"),
    ("4x128,*x64,*x32", "one '*'"),
    ("4x128", "sum to 4"),
    ("1024x128,*x64", "leave no rows"),
    ("2x64:A,2x32:A,*x16", "pinned to two tiers"),
    ("0x128,*x64", "non-positive"),
])
def test_parse_spec_rejects(spec, err):
    with pytest.raises(ValueError, match=err):
        parse_book_tiers(spec, 8)


def test_config_validates_tiers():
    # ValueError, not AssertionError: these validate operator input
    # (--book-tiers) and must survive `python -O`.
    with pytest.raises(ValueError):
        EngineConfig(num_symbols=8, capacity=64,
                     tiers=((2, 64), (2, 16)))  # counts don't cover axis
    with pytest.raises(ValueError):
        EngineConfig(num_symbols=8, capacity=16,
                     tiers=((2, 64), (6, 16)))  # capacity != deepest tier
    cfg = EngineConfig(num_symbols=8, capacity=64,
                       tiers=[[2, 64], [6, 16]])  # JSON round-trip shape
    assert cfg.tiers == ((2, 64), (6, 16))
    assert [t.semantic_key()[:2] for t in cfg.tier_configs()] == \
        [(2, 64), (6, 16)]


# -- dispatch parity vs the untiered runner ----------------------------------


def drive(runner, seed, syms, n=250):
    rng = random.Random(seed)
    live, out = [], []
    for _ in range(n):
        ops = []
        for _ in range(rng.randrange(1, 8)):
            if live and rng.random() < 0.25:
                ops.append(EngineOp(OP_CANCEL,
                                    live.pop(rng.randrange(len(live))),
                                    cancel_requester="c"))
                continue
            side = rng.choice((pb2.BUY, pb2.SELL))
            info = submit_info(runner, rng.choice(syms), side,
                               10_000 + 100 * rng.randrange(5),
                               rng.randrange(1, 9), client=f"c{side}")
            ops.append(EngineOp(OP_SUBMIT, info))
            live.append(info)
        res = runner.run_dispatch(ops)
        out.append([(o.op.info.order_id, o.status, o.filled, o.remaining,
                     o.error) for o in res.outcomes])
        out.append([(f.order_id, f.counter_order_id, f.price_q4, f.quantity)
                    for f in res.storage_fills])
        out.append(sorted(res.storage_updates))
        out.append([tuple(t) for t in res.storage_orders])
        out.append(sorted((m.symbol, m.best_bid, m.best_ask, m.bid_size,
                           m.ask_size) for m in res.market_data))
    return out


def test_tiered_runner_parity_with_untiered():
    """Symbols landing in the 16-cap default group behave bit-identically
    to an untiered capacity-16 runner over the same flow."""
    syms = [f"S{i}" for i in range(4)]
    tiered = make_tiered()
    flat = EngineRunner(EngineConfig(num_symbols=S, capacity=16, batch=4))
    assert drive(tiered, 42, syms) == drive(flat, 42, syms)


def test_tiered_mega_parity_with_serial():
    """M=4 megadispatch through the tiered runner == the serial tiered
    schedule (per-tier stacked scans decode per wave in tier order)."""
    syms = ["HOT", "S3", "S4", "S5"]
    a = drive(make_tiered(), 7, syms)
    b = drive(make_tiered(megadispatch_max_waves=4), 7, syms)
    assert a == b


# -- tier routing ------------------------------------------------------------


def test_pinned_symbol_lands_in_its_group_and_holds_depth():
    r = make_tiered()
    assert r.slot_acquire("HOT") is not None
    assert r.tier_of_slot(r.symbols["HOT"]) == 0
    # 40 resting bids: far past the 16-cap default group, fine in tier 0.
    for i in range(40):
        info = submit_info(r, "HOT", pb2.BUY, 9_000 - i, 5, client="mm")
        res = r.run_dispatch([EngineOp(OP_SUBMIT, info)])
        assert res.outcomes[-1].status == NEW
    bids, asks = r.book_snapshot("HOT")
    assert len(bids) == 40 and not asks
    # Unpinned symbols fill the LAST (shallow) group first.
    assert r.tier_of_slot(r.slot_acquire("COLD")) == 1
    # The high watermark followed the deep book.
    _, gauges = r.metrics.snapshot()
    assert gauges["book_depth_hwm_tier0"] >= 40
    assert gauges["book_depth_hwm"] >= 40


def test_unpinned_spill_into_deeper_group_when_shallow_full():
    r = make_tiered()
    for i in range(6):  # fill the 6-slot default group
        assert r.tier_of_slot(r.slot_acquire(f"T{i}")) == 1
    assert r.tier_of_slot(r.slot_acquire("SPILL")) == 0
    r.slot_acquire("HOT")  # one pinned slot still free in group 0
    assert r.tier_of_slot(r.symbols["HOT"]) == 0
    # Now every slot is taken: the next NEW symbol is refused.
    assert r.slot_acquire("NOPE") is None


def test_capacity_reject_metered_with_reason():
    """A full 16-cap book REJECTS with the positional 'book side at
    capacity' reason and feeds me_book_capacity_rejects_total plus the
    owning tier's series — never a silent drop."""
    r = make_tiered()
    rejects = 0
    for i in range(20):
        info = submit_info(r, "T0", pb2.SELL, 10_000 + i, 3)
        res = r.run_dispatch([EngineOp(OP_SUBMIT, info)])
        if res.outcomes[0].status == REJECTED:
            rejects += 1
            assert "book side at capacity" in res.outcomes[0].error
    assert rejects == 4
    counters, _ = r.metrics.snapshot()
    assert counters["book_capacity_rejects"] == 4
    assert counters["book_capacity_rejects_tier1"] == 4
    assert "book_capacity_rejects_tier0" not in counters


def test_untiered_runner_meters_capacity_rejects_too():
    r = EngineRunner(EngineConfig(num_symbols=2, capacity=4, batch=4))
    for i in range(6):
        r.run_dispatch([EngineOp(OP_SUBMIT, submit_info(
            r, "A", pb2.BUY, 9_000 - i, 2))])
    counters, _ = r.metrics.snapshot()
    assert counters["book_capacity_rejects"] == 2
    assert counters["book_capacity_rejects_tier0"] == 2


# -- auction + crossed detection across tiers --------------------------------


def test_auction_and_crossed_span_tiers():
    r = make_tiered()
    r.set_auction_mode(True)
    ops = []
    for sym, cl in (("HOT", "a"), ("S5", "b")):
        ops.append(EngineOp(OP_REST, submit_info(r, sym, pb2.BUY, 10_100,
                                                 10, cl + "1")))
        ops.append(EngineOp(OP_REST, submit_info(r, sym, pb2.SELL, 9_900,
                                                 6, cl + "2")))
    res = r.run_dispatch(ops)
    assert all(o.status == NEW for o in res.outcomes)
    assert sorted(r.crossed_symbols()) == ["HOT", "S5"]
    summary = r.run_auction()
    assert not summary["error"]
    assert sorted(s for s, _, _ in summary["crossed"]) == ["HOT", "S5"]
    assert all(q == 6 for _, _, q in summary["crossed"])
    assert not r.auction_mode
    assert r.crossed_symbols() == []


# -- checkpoints + restart ---------------------------------------------------


def test_checkpoint_roundtrip_and_changed_spec_refused(tmp_path):
    r = make_tiered(oid_offset=1, oid_stride=2)
    info = submit_info(r, "HOT", pb2.BUY, 10_000, 5, "mm")
    cold = submit_info(r, "S5", pb2.SELL, 11_000, 3, "x")
    r.run_dispatch([EngineOp(OP_SUBMIT, info), EngineOp(OP_SUBMIT, cold)])
    path = str(tmp_path / "ckpt")
    with r._dispatch_lock:
        save_checkpoint(path, r)

    # Same spec restores; the strided OID line resumes on its residue.
    r2 = make_tiered(oid_offset=1, oid_stride=2)
    restore_runner(r2, path)
    bids, _ = r2.book_snapshot("HOT")
    assert len(bids) == 1 and bids[0][0].order_id == info.order_id
    n, _ = r2.assign_oid()
    assert n % 2 == 0 and n > info.oid  # offset-1/stride-2 residue class
    # A cancel against the restored directory dispatches cleanly.
    target = r2.orders_by_id[cold.order_id]
    res = r2.run_dispatch([EngineOp(OP_CANCEL, target,
                                    cancel_requester="x")])
    assert res.outcomes[0].status == CANCELED

    # A CHANGED tier spec refuses with a clear error (replay fallback).
    tiers2, _ = parse_book_tiers("4x64,*x16", S)
    r3 = TieredEngineRunner(
        EngineConfig(num_symbols=S, capacity=64, batch=4, tiers=tiers2))
    with pytest.raises(ValueError, match="book-tier spec"):
        restore_runner(r3, path)


# -- full-stack e2e: build_server with tiers + levels kernel -----------------


@pytest.mark.slow
def test_tiered_server_e2e_with_levels_kernel(tmp_path):
    """build_server over a tiered levels-kernel config: deep resting on
    the pinned hot symbol past the default group's capacity, full-book
    backpressure on a tail symbol surfaced as a reject (not a crash),
    and a restart recovering the books via store replay."""
    import grpc

    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown
    from matching_engine_tpu.server.tiered_runner import parse_book_tiers

    tiers, pins = parse_book_tiers("2x128:HOT,*x16", 8)
    cfg = EngineConfig(num_symbols=8, capacity=128, batch=4,
                       kernel="levels", tiers=tiers, max_fills=1 << 12)
    db = str(tmp_path / "t.db")

    def boot():
        server, port, parts = build_server(
            "127.0.0.1:0", db, cfg, window_ms=1, log=False, native=False,
            tier_pins=pins)
        server.start()
        stub = MatchingEngineStub(
            grpc.insecure_channel(f"127.0.0.1:{port}"))
        return server, parts, stub

    server, parts, stub = boot()
    # 24 resting bids on HOT at 12 distinct prices: past the 16-cap
    # default group, comfortably inside the 128 deep group's [16, 8]
    # levels.
    for i in range(24):
        r = stub.SubmitOrder(pb2.OrderRequest(
            client_id="mm", symbol="HOT", side=pb2.BUY,
            order_type=pb2.LIMIT, price=9_000 - (i % 12), scale=4,
            quantity=3))
        assert r.success, r.error_message
    # Tail symbol: the 16-cap group's levels config is [4, 4] — 4 FIFO
    # slots at one price; the 5th submit there is a metered reject.
    last = None
    for i in range(5):
        last = stub.SubmitOrder(pb2.OrderRequest(
            client_id="c", symbol="TAIL", side=pb2.SELL,
            order_type=pb2.LIMIT, price=11_000, scale=4, quantity=2))
    assert not last.success and "capacity" in last.error_message
    counters = dict(stub.GetMetrics(pb2.MetricsRequest()).counters)
    assert counters["book_capacity_rejects"] == 1
    book = stub.GetOrderBook(pb2.OrderBookRequest(symbol="HOT"))
    assert len(book.bids) == 24
    shutdown(server, parts)

    # Restart: store replay re-rests everything into the same tiers.
    server, parts, stub = boot()
    book = stub.GetOrderBook(pb2.OrderBookRequest(symbol="HOT"))
    assert len(book.bids) == 24
    tail = stub.GetOrderBook(pb2.OrderBookRequest(symbol="TAIL"))
    assert len(tail.asks) == 4
    shutdown(server, parts)


# -- workload manifest depth check -------------------------------------------


def test_check_tier_depth():
    from matching_engine_tpu.sim.record import check_tier_depth

    man = {"max_resting_depth": [300, 40, 40, 200]}
    tiers = ((1, 1024), (3, 128))
    # Unpinned symbols are judged against the LAST group.
    bad = check_tier_depth(man, tiers, pins={"S0": 0})
    assert len(bad) == 1 and "S3" in bad[0] and "128" in bad[0]
    assert check_tier_depth(man, tiers, pins={"S0": 0, "S3": 0}) == []
    assert check_tier_depth({}, tiers) != []  # pre-format manifest
