"""Many-venue gym (gym/env.py, gym/episode.py): parity oracle, PRNG
independence, checkpoint bit-identity, scale, and the freeze->replay
loop.

The load-bearing checks:
- parity: a V-venue rollout over HETEROGENEOUS scenarios (auction day
  with three uncrosses, a halt-and-shock crash, bursts, Zipf-skewed hot
  symbols) is bit-identical per venue to V independent single-venue
  run_scenario() runs — fills, volume, and every uncross's executed
  volume — on all three kernels. The gym is the engine vmapped over a
  venue axis, never a reimplementation.
- PRNG independence: perturbing one venue's seed changes only that
  venue's lane of every output (satellite 3).
- save/restore: a checkpoint mid-rollout restores to bit-identical
  continuation across the whole [V] axis, matrix AND levels kernels.
- freeze->replay: a frozen gym episode replays through a real in-proc
  server with the serving stack's fills/uncross volumes equal to the
  sim's per-phase ground truth (CI's gym smoke, satellite 5).

Compile budget: the 4-venue matrix rollout is computed ONCE by a
module-scope fixture and shared by the parity oracle, the freeze ->
serving replay, and the freeze-validation checks (which synthesize
misaligned captures by array surgery instead of extra rollouts); the
sorted/levels parity points run 2 venues (auction + crash — the phase
kinds that diverge across kernels).
"""

import dataclasses

import jax
import numpy as np
import pytest

from matching_engine_tpu.domain import oprec
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.gym import VenueGym, freeze_episode, restore_state, save_state
from matching_engine_tpu.sim.agents import AgentMix
from matching_engine_tpu.sim.scenarios import make_scenario, run_scenario

MIX = AgentMix(mm_agents=8, mm_refresh=2, momentum=2, noise=3, takers=2,
               half_spread=2, spread_jitter=4, qty_max=50, fair_init=1_000,
               noise_qty_cap=120)
CFG = EngineConfig(num_symbols=4, capacity=48, batch=MIX.batch_for(),
                   max_fills=1 << 14)
SEEDS = [11, 22, 33, 44]


def _scens(steps=40):
    return [make_scenario("auction_day", steps),
            make_scenario("flash_crash", steps),
            make_scenario("bursts", steps),
            make_scenario("hot_symbols", steps)]


def _uncross_vol(stats, n, i):
    hi = np.asarray(stats.uncross_hi)[:n, i].astype(np.int64)
    lo = np.asarray(stats.uncross_lo)[:n, i].astype(np.int64)
    return int((hi << 15).sum() + lo.sum())


def _assert_venue_matches_oracle(cfg, stats, i, scen, seed):
    """Venue i's gym lane vs its single-venue run_scenario() run."""
    _book, _st, results = run_scenario(cfg, MIX, scen, seed=seed)
    fills = sum(int(np.asarray(pr.stats.fills).sum()) for pr in results)
    vol = sum(int(np.asarray(pr.stats.volume).sum()) for pr in results)
    uv = sum(int(pr.uncross.executed.sum()) for pr in results
             if pr.uncross is not None)
    n = scen.total_steps()
    assert int(np.asarray(stats.fills)[:n, i].sum()) == fills
    assert int(np.asarray(stats.volume)[:n, i].sum()) == vol
    assert _uncross_vol(stats, n, i) == uv
    assert fills > 0


@pytest.fixture(scope="module")
def rolled4():
    """One 4-venue heterogeneous matrix rollout, venue 0 recorded —
    shared by the parity oracle and the freeze/replay family."""
    scens = _scens()
    env = VenueGym.from_scenarios(CFG, MIX, 4, scens, record=(0,))
    state, _ = env.reset(SEEDS)
    T = max(int(x) for x in np.asarray(env.controls.ep_len))
    state, stats, rec, obs = env.rollout(state, T)
    return env, scens, stats, rec


# -- parity oracle: gym == V single-venue runs, all kernels --------------------


def test_parity_vs_single_venue_runs_matrix(rolled4):
    env, scens, stats, _rec = rolled4
    assert int(np.asarray(stats.done).sum()) == 4  # every venue finished
    for i, (scen, seed) in enumerate(zip(scens, SEEDS)):
        _assert_venue_matches_oracle(CFG, stats, i, scen, seed)
    # The heterogeneity is real: the auction venue actually uncrossed.
    assert int(np.asarray(stats.uncrossed)[:, 0].sum()) == 3


@pytest.mark.parametrize("kernel", ["sorted", "levels"])
def test_parity_vs_single_venue_runs(kernel):
    cfg = dataclasses.replace(CFG, capacity=64, kernel=kernel)
    scens = _scens()[:2]  # auction (uncross) + crash (halt/shock)
    env = VenueGym.from_scenarios(cfg, MIX, 2, scens)
    state, _ = env.reset(SEEDS[:2])
    T = max(int(x) for x in np.asarray(env.controls.ep_len))
    _, stats, _, _ = env.rollout(state, T)
    for i, (scen, seed) in enumerate(zip(scens, SEEDS)):
        _assert_venue_matches_oracle(cfg, stats, i, scen, seed)


# -- per-venue PRNG independence (satellite 3) ---------------------------------


@pytest.mark.parametrize("kernel", ["matrix", "levels"])
def test_per_venue_prng_independence(kernel):
    """Changing venue 1's seed must change ONLY venue 1's lane: every
    stats/obs column of venues 0 and 2 stays bit-identical."""
    cfg = dataclasses.replace(CFG, capacity=64, kernel=kernel)
    scens = _scens()[:3]
    env = VenueGym.from_scenarios(cfg, MIX, 3, scens)
    sa, _ = env.reset([5, 6, 7])
    sb, _ = env.reset([5, 999, 7])
    _, st_a, _, obs_a = env.rollout(sa, 16)
    _, st_b, _, obs_b = env.rollout(sb, 16)
    for f_a, f_b in zip(st_a, st_b):
        a, b = np.asarray(f_a), np.asarray(f_b)
        np.testing.assert_array_equal(a[:, 0], b[:, 0])
        np.testing.assert_array_equal(a[:, 2], b[:, 2])
    for f_a, f_b in zip(obs_a, obs_b):
        a, b = np.asarray(f_a), np.asarray(f_b)
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[2], b[2])
    # ...and venue 1 did actually diverge.
    assert (np.asarray(st_a.fills)[:, 1] != np.asarray(st_b.fills)[:, 1]).any()


def test_episode_reseed_matches_fresh_reset():
    """Episode e of a venue draws from PRNGKey(seed + e): the steps after
    an auto-reset are bit-identical to a fresh reset at seed + 1."""
    scens = [make_scenario("bursts", 12)] * 2
    env = VenueGym.from_scenarios(CFG, MIX, 2, scens)
    T = int(np.asarray(env.controls.ep_len)[0])
    state, _ = env.reset([3, 4])
    state, _, _, _ = env.rollout(state, T)  # episode 0 ends, auto-reset
    _, tail, _, _ = env.rollout(state, 6)
    fresh, _ = env.reset([4, 5])
    _, fresh_stats, _, _ = env.rollout(fresh, 6)
    for f_t, f_f in zip(tail, fresh_stats):
        np.testing.assert_array_equal(np.asarray(f_t), np.asarray(f_f))


# -- checkpoint: save/restore bit-identity across [V] --------------------------


@pytest.mark.parametrize("kernel", ["matrix", "levels"])
def test_save_restore_bit_identical_continuation(tmp_path, kernel):
    cfg = dataclasses.replace(CFG, capacity=64, kernel=kernel)
    env = VenueGym.from_scenarios(cfg, MIX, 3, _scens()[:3])
    state, _ = env.reset([5, 6, 7])
    state, _, _, _ = env.rollout(state, 16)  # mid-episode
    path = str(tmp_path / "gym.ckpt")
    save_state(env.spec, state, path)
    restored = restore_state(env.spec, path)
    for f_a, f_b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(f_a), np.asarray(f_b))
    _, st_a, _, obs_a = env.rollout(state, 16)
    _, st_b, _, obs_b = env.rollout(restored, 16)
    for f_a, f_b in zip(jax.tree_util.tree_leaves((st_a, obs_a)),
                        jax.tree_util.tree_leaves((st_b, obs_b))):
        np.testing.assert_array_equal(np.asarray(f_a), np.asarray(f_b))


def test_restore_rejects_mismatched_spec(tmp_path, rolled4):
    env, _scens_, _stats, _rec = rolled4
    state, _ = env.reset(SEEDS)
    path = str(tmp_path / "gym.ckpt")
    save_state(env.spec, state, path)
    other = VenueGym.from_scenarios(CFG, MIX, 3, _scens()[:3])
    with pytest.raises(ValueError):
        restore_state(other.spec, path)


# -- scale: 1024 heterogeneous venues in one jit'd scan ------------------------


def test_1024_venues_one_scan():
    """V=1024 is data-parallel width, not program size: one compile, one
    lax.scan, four distinct scenario programs cycling over the axis."""
    mix = AgentMix(mm_agents=4, mm_refresh=1, momentum=1, noise=2, takers=1,
                   half_spread=2, spread_jitter=4, qty_max=50,
                   fair_init=1_000, noise_qty_cap=120)
    cfg = EngineConfig(num_symbols=2, capacity=16, batch=mix.batch_for(),
                       max_fills=1 << 12)
    env = VenueGym.from_scenarios(cfg, mix, 1024, _scens(20))
    state, obs = env.reset(list(range(1024)))
    assert np.asarray(obs.best_bid).shape == (1024, 2)
    state, stats, _, _ = env.rollout(state, 6)
    assert np.asarray(stats.fills).shape == (6, 1024)
    assert int(np.asarray(stats.real_ops).sum()) > 0
    # Distinct programs did run: bursts venues idle outside bursts while
    # hot-symbol venues trade every step — per-venue op totals differ.
    per_venue = np.asarray(stats.real_ops).sum(axis=0)
    assert len(np.unique(per_venue)) > 1


# -- freeze -> serving-stack replay (satellite 5 / CI gym smoke) ---------------


def test_freeze_episode_replays_through_inproc_server(tmp_path, rolled4):
    """A frozen gym episode IS a workload artifact: replayed through a
    real in-proc server (call periods opened, uncrossed at phase ends),
    the serving stack reproduces the gym's fills exactly and every
    uncross clears the gym's per-phase ground-truth volume."""
    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.server.main import build_server, shutdown

    env, scens, stats, rec = rolled4
    out = str(tmp_path / "ep.opfile.gz")
    man = freeze_episode(env.spec, scens[0], 0, rec, stats, out,
                         seed=SEEDS[0])
    assert man["source"] == "gym" and man["sim_fills"] > 0
    arr = oprec.read_opfile(out)

    scfg = EngineConfig(num_symbols=CFG.num_symbols, capacity=CFG.capacity,
                        batch=8, max_fills=CFG.max_fills)
    server, _port, parts = build_server(
        "127.0.0.1:0", str(tmp_path / "w.db"), scfg, window_ms=1.0,
        log=False, feed_depth=0)
    svc = parts["service"]
    try:
        bs = max(1, min(128, man["min_cancel_gap"] or 128))
        reasons = {}
        uncross = []
        for ph in man["phases"]:
            if ph["kind"] == "auction":
                r = svc.RunAuction(pb2.AuctionRequest(open_call=True), None)
                assert r.success, r.error_message
            for s0 in range(ph["start_record"], ph["end_record"], bs):
                payload = oprec.slice_payload(
                    arr, s0, min(bs, ph["end_record"] - s0))
                resp = svc.SubmitOrderBatch(
                    pb2.OrderBatchRequest(ops=payload), None)
                assert resp.success, resp.error_message
                for i, ok in enumerate(resp.ok):
                    if not ok:
                        reasons[resp.error[i]] = (
                            reasons.get(resp.error[i], 0) + 1)
            if ph["kind"] == "auction":
                r = svc.RunAuction(pb2.AuctionRequest(), None)
                assert r.success, r.error_message
                uncross.append(int(r.executed_quantity))
        gm = svc.GetMetrics(pb2.MetricsRequest(), None)
        assert gm.counters.get("fills") == man["sim_fills"]
        assert uncross == [p["uncross_executed"] for p in man["phases"]
                           if p["kind"] == "auction"]
        assert sum(p["fills"] for p in man["phases"]) == man["sim_fills"]
        assert set(reasons) <= {"unknown order id", "order not open"}, \
            reasons
    finally:
        shutdown(server, parts)


def test_freeze_rejects_bad_captures(rolled4):
    """Validation without extra rollouts: misaligned captures are the
    shared capture with its done flags shifted (a rollout that did not
    start at the episode boundary presents exactly this shape)."""
    env, scens, stats, rec = rolled4
    shifted = stats._replace(done=np.roll(np.asarray(stats.done), 1,
                                          axis=0))
    with pytest.raises(ValueError, match="episode"):
        freeze_episode(env.spec, scens[0], 0, rec, shifted,
                       "/tmp/never-written.opfile.gz", seed=SEEDS[0])
    with pytest.raises(ValueError, match="not recorded"):
        freeze_episode(env.spec, scens[1], 1, rec, stats,
                       "/tmp/never-written.opfile.gz", seed=SEEDS[1])
    short = np.asarray(rec)[: scens[0].total_steps() - 1]
    with pytest.raises(ValueError, match="episode length"):
        freeze_episode(env.spec, scens[0], 0, short, stats,
                       "/tmp/never-written.opfile.gz", seed=SEEDS[0])
