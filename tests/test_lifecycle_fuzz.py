"""Full-lifecycle fuzz: continuous trading -> call-period accumulation ->
uncross -> continuous again, device vs oracle, BOTH kernels.

Every prior parity fuzz exercises one regime at a time (continuous streams
in test_kernel_parity, pre-built crossed books in test_auction). Real
venue state flows THROUGH the transitions: books carrying continuous-
trading residue enter a call period, accumulate crossing rests on top,
uncross (the sorted kernel additionally re-packs its dense prefix), and
then serve continuous flow again from the post-auction state. This fuzz
pins the whole cycle against the oracle, twice around, per kernel —
statuses, fills (per-symbol exact order for continuous, canonicalized for
the uncross), and resting books at every phase boundary.
"""

import random

import numpy as np
import pytest

from matching_engine_tpu.engine.auction import auction_step, decode_auction
from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import (
    HostOrder,
    apply_orders,
    snapshot_books,
)
from matching_engine_tpu.engine.kernel import OP_CANCEL, OP_REST, OP_SUBMIT
from matching_engine_tpu.engine.oracle import OracleBook
from matching_engine_tpu.proto import (
    BUY,
    LIMIT,
    LIMIT_FOK,
    LIMIT_IOC,
    MARKET,
    MARKET_FOK,
    SELL,
)

S, CAP = 4, 24


@pytest.mark.parametrize("kernel", ["matrix", "sorted", "levels"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_lifecycle_continuous_auction_interleave(kernel, seed):
    cfg = EngineConfig(num_symbols=S, capacity=CAP, batch=8,
                       max_fills=1 << 12, kernel=kernel)
    rng = random.Random(seed)
    if kernel == "levels":
        # The levels kernel's capacity is level-structured; the oracle
        # must model the same (L, F) bounds or reject parity breaks.
        from matching_engine_tpu.engine.book import level_shape

        lvl, fifo = level_shape(cfg)
        oracles = [OracleBook(CAP, levels=lvl, level_fifo=fifo)
                   for _ in range(S)]
    else:
        oracles = [OracleBook(CAP) for _ in range(S)]
    book = init_book(cfg)
    next_oid = 1
    # (oid, side) of LIMIT submits/rests per symbol — cancel targets need
    # the SIDE the order rests on (the host order directory's job in the
    # serving stack); canceling filled/canceled ids is fair game (both
    # sides must REJECT identically).
    cancelable: list[list[tuple[int, int]]] = [[] for _ in range(S)]

    def gen_stream(n_ops: int, op_mode: int) -> list[HostOrder]:
        nonlocal next_oid
        out = []
        for _ in range(n_ops):
            sym = rng.randrange(S)
            if (op_mode == OP_SUBMIT and cancelable[sym]
                    and rng.random() < 0.2):
                oid, side = rng.choice(cancelable[sym])
                out.append(HostOrder(sym, OP_CANCEL, side, oid=oid))
                continue
            side = BUY if rng.random() < 0.5 else SELL
            market = op_mode == OP_SUBMIT and rng.random() < 0.1
            otype = MARKET if market else LIMIT
            # Continuous phases also carry IOC/FOK traffic (call-period
            # streams stay GTC — the edges reject non-GTC there).
            if op_mode == OP_SUBMIT and rng.random() < 0.15:
                if market:
                    otype = MARKET_FOK
                else:
                    otype = rng.choice((LIMIT_IOC, LIMIT_FOK))
            price = (0 if otype in (MARKET, MARKET_FOK)
                     else 10_000 + rng.randrange(-8, 9))
            out.append(HostOrder(
                sym, op_mode, side, otype,
                price, rng.randrange(1, 20), oid=next_oid,
                owner=rng.randrange(0, 3)))  # owner 1/2 collide sometimes
            if otype == LIMIT:
                cancelable[sym].append((next_oid, side))
            next_oid += 1
        return out

    def apply_phase(book, stream):
        """Device + oracle application of one chronological stream."""
        o_results, o_fills = [], []
        for o in stream:
            ob = oracles[o.sym]
            if o.op == OP_CANCEL:
                r = ob.cancel(o.oid)
            elif o.op == OP_REST:
                r = ob.rest(o.oid, o.side, o.price, o.qty, owner=o.owner)
            else:
                r = ob.submit(o.oid, o.side, o.otype, o.price, o.qty,
                              owner=o.owner)
            o_results.append((o.oid, o.sym, r.status, r.filled, r.remaining))
            o_fills.extend((o.sym, f.taker_oid, f.maker_oid, f.price_q4,
                            f.quantity) for f in r.fills)
        book, d_res, d_fills = apply_orders(cfg, book, stream)
        d_res = [(r.oid, r.sym, r.status, r.filled, r.remaining)
                 for r in d_res]
        d_fills = [(f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
                   for f in d_fills]
        assert sorted(d_res) == sorted(o_results)
        for s in range(S):  # continuous fills: per-symbol EXACT order
            assert [f for f in d_fills if f[0] == s] == \
                [f for f in o_fills if f[0] == s], f"phase fills sym {s}"
        _assert_books(book)
        return book

    def _assert_books(book):
        snaps = snapshot_books(book)
        for s in range(S):
            assert snaps[s] == oracles[s].snapshot(), f"book sym {s}"

    def uncross(book):
        book, out = auction_step(cfg, book, np.ones((S,), dtype=bool))
        dec, fills = decode_auction(cfg, out)
        assert not dec.aborted
        got = sorted((f.sym, f.taker_oid, f.maker_oid, f.price_q4,
                      f.quantity) for f in fills)
        want = []
        for s in range(S):
            p, q, ofills = oracles[s].auction()
            assert int(dec.clear_price[s]) == p, f"auction price sym {s}"
            assert int(dec.executed[s]) == q, f"auction volume sym {s}"
            want.extend((s, f.taker_oid, f.maker_oid, f.price_q4,
                         f.quantity) for f in ofills)
        assert got == sorted(want)
        _assert_books(book)
        return book

    crossed_total = 0
    for _cycle in range(2):
        book = apply_phase(book, gen_stream(120, OP_SUBMIT))  # continuous
        book = apply_phase(book, gen_stream(60, OP_REST))     # call period
        pre = snapshot_books(book)
        book = uncross(book)
        post = snapshot_books(book)
        crossed_total += sum(1 for s in range(S) if post[s] != pre[s])
    assert crossed_total > 0, "fuzz never produced a crossing call period"
