"""Fill-parity: the jit'd device kernel vs the host oracle, bit for bit.

The core correctness oracle of the framework (SURVEY.md §4): replay the same
order stream through the trivially-correct host CLOB and through the TPU
kernel, assert identical per-order statuses, identical fills (same order,
same maker, same price, same quantity), and identical resting books.
"""

import random

import pytest

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import (
    HostOrder,
    apply_orders,
    random_order_stream,
    snapshot_books,
)
from matching_engine_tpu.engine.kernel import OP_CANCEL, OP_SUBMIT
from matching_engine_tpu.engine.oracle import OracleBook
from matching_engine_tpu.proto import BUY, LIMIT, MARKET, SELL


def run_both(cfg, host_orders):
    """Returns (device results+fills+snaps, oracle results+fills+snaps)."""
    oracles = [OracleBook(capacity=cfg.capacity) for _ in range(cfg.num_symbols)]
    o_results = []
    o_fills = []
    for o in host_orders:
        if o.op == OP_SUBMIT:
            r = oracles[o.sym].submit(o.oid, o.side, o.otype, o.price, o.qty)
        else:
            r = oracles[o.sym].cancel(o.oid)
        o_results.append((o.oid, o.sym, r.status, r.filled, r.remaining))
        o_fills.extend((o.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity) for f in r.fills)

    book = init_book(cfg)
    book, d_results, d_fills = apply_orders(cfg, book, host_orders)
    d_results = [(r.oid, r.sym, r.status, r.filled, r.remaining) for r in d_results]
    d_fills = [(f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity) for f in d_fills]

    d_snaps = snapshot_books(book)
    o_snaps = [o.snapshot() for o in oracles]
    return (d_results, d_fills, d_snaps), (o_results, o_fills, o_snaps)


def assert_parity(cfg, host_orders):
    (d_res, d_fills, d_snaps), (o_res, o_fills, o_snaps) = run_both(cfg, host_orders)
    # Per-order results: compare as sets keyed by oid (device dispatch order
    # across symbols differs from chronological order; per-symbol order is
    # preserved, and oids are unique).
    assert sorted(d_res) == sorted(o_res)
    # Fills per symbol must match exactly, in order.
    for s in range(cfg.num_symbols):
        dev = [f for f in d_fills if f[0] == s]
        orc = [f for f in o_fills if f[0] == s]
        assert dev == orc, f"fill mismatch for symbol {s}:\n dev={dev}\n orc={orc}"
    for s in range(cfg.num_symbols):
        assert d_snaps[s][0] == o_snaps[s][0], f"bid book mismatch sym {s}"
        assert d_snaps[s][1] == o_snaps[s][1], f"ask book mismatch sym {s}"


def test_basic_cross_and_rest():
    cfg = EngineConfig(num_symbols=2, capacity=8, batch=4)
    orders = [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10000, 5, oid=1),
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10000, 5, oid=2),
        HostOrder(0, OP_SUBMIT, BUY, LIMIT, 10000, 7, oid=3),
        HostOrder(1, OP_SUBMIT, BUY, LIMIT, 9000, 4, oid=4),
        HostOrder(1, OP_SUBMIT, SELL, MARKET, 0, 10, oid=5),
        HostOrder(0, OP_SUBMIT, BUY, LIMIT, 9900, 2, oid=6),
        HostOrder(0, OP_CANCEL, SELL, oid=2),
    ]
    assert_parity(cfg, orders)


def test_market_sweep_and_capacity_reject():
    cfg = EngineConfig(num_symbols=1, capacity=4, batch=4)
    orders = [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10000 + 100 * i, 2, oid=i + 1)
        for i in range(4)
    ]
    orders += [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 11000, 2, oid=5),  # side full -> reject
        HostOrder(0, OP_SUBMIT, BUY, MARKET, 0, 100, oid=6),    # sweeps all, cancels rest
        HostOrder(0, OP_SUBMIT, BUY, MARKET, 0, 3, oid=7),      # empty book market
    ]
    assert_parity(cfg, orders)


def test_cancel_semantics():
    cfg = EngineConfig(num_symbols=1, capacity=8, batch=4)
    orders = [
        HostOrder(0, OP_SUBMIT, BUY, LIMIT, 10000, 5, oid=1),
        HostOrder(0, OP_CANCEL, BUY, oid=1),
        HostOrder(0, OP_CANCEL, BUY, oid=1),   # double cancel -> reject
        HostOrder(0, OP_CANCEL, BUY, oid=42),  # unknown -> reject
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10000, 5, oid=2),  # no cross: bid gone
    ]
    assert_parity(cfg, orders)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_randomized_parity(seed):
    cfg = EngineConfig(num_symbols=4, capacity=16, batch=8)
    orders = random_order_stream(cfg.num_symbols, 150, seed=seed)
    assert_parity(cfg, orders)


def test_randomized_parity_deep_books():
    cfg = EngineConfig(num_symbols=2, capacity=64, batch=8)
    orders = random_order_stream(cfg.num_symbols, 400, seed=99, price_levels=5)
    assert_parity(cfg, orders)


@pytest.mark.parametrize("seed", [7, 11, 13])
def test_fuzz_parity_tight_capacity(seed):
    """Stress the hard paths together: capacity-overflow rejects, heavy
    cancel traffic, and deep market sweeps, all under one tiny book."""
    cfg = EngineConfig(num_symbols=3, capacity=6, batch=5)
    orders = random_order_stream(
        cfg.num_symbols, 300, seed=seed, cancel_p=0.30, market_p=0.25,
        price_levels=4, qty_max=20)
    assert_parity(cfg, orders)


def test_fuzz_parity_single_price_level_fifo():
    """Everything at one price: pure FIFO ordering is the whole game."""
    cfg = EngineConfig(num_symbols=2, capacity=32, batch=8)
    orders = random_order_stream(
        cfg.num_symbols, 300, seed=21, cancel_p=0.2, market_p=0.2,
        price_levels=1, qty_max=10)
    assert_parity(cfg, orders)
