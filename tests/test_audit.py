"""scripts/audit.py: clean on a server-produced DB, loud on corruption."""

import importlib.util
import pathlib
import sqlite3

import grpc
import pytest

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown

_spec = importlib.util.spec_from_file_location(
    "audit", pathlib.Path(__file__).resolve().parent.parent / "scripts" / "audit.py")
audit_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(audit_mod)


@pytest.fixture
def traded_db(tmp_path):
    db = str(tmp_path / "a.db")
    server, port, parts = build_server(
        "127.0.0.1:0", db, EngineConfig(num_symbols=4, capacity=16, batch=4),
        window_ms=1.0, log=False)
    server.start()
    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    stub = MatchingEngineStub(ch)

    def sub(side, qty, price=10_000, otype=pb2.LIMIT):
        # Per-side clients: self-trade prevention (always on) would
        # otherwise suppress the crossing fills this fixture builds.
        r = stub.SubmitOrder(pb2.OrderRequest(
            client_id=f"c-s{side}", symbol="S", order_type=otype, side=side,
            price=price, scale=4, quantity=qty), timeout=30)
        assert r.success
        return r.order_id

    sub(pb2.BUY, 10)
    sub(pb2.SELL, 4)                      # partial fill
    oid = sub(pb2.BUY, 3, price=9_000)    # rests
    stub.CancelOrder(pb2.CancelRequest(client_id=f"c-s{pb2.BUY}",
                                       order_id=oid), timeout=30)
    parts["sink"].flush()
    ch.close()
    shutdown(server, parts)
    return db


def test_audit_clean_on_real_db(traded_db, capsys):
    problems = audit_mod.audit(traded_db)
    assert problems == []
    assert '"violations": 0' in capsys.readouterr().out


def test_audit_clean_on_partial_fill_then_capacity_reject(tmp_path, capsys):
    """A crossing LIMIT whose fills are honored but whose remainder finds
    its own book side at capacity goes REJECTED *with* fills
    (engine/kernel.py submit_status). That DB state is legitimate and must
    audit clean (VERDICT r2 weak #2)."""
    db = str(tmp_path / "rej.db")
    server, port, parts = build_server(
        "127.0.0.1:0", db, EngineConfig(num_symbols=2, capacity=2, batch=4),
        window_ms=1.0, log=False)
    server.start()
    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    stub = MatchingEngineStub(ch)

    def sub(side, qty, price):
        return stub.SubmitOrder(pb2.OrderRequest(
            client_id=f"c-s{side}", symbol="S", order_type=pb2.LIMIT,
            side=side, price=price, scale=4, quantity=qty), timeout=30)

    assert sub(pb2.SELL, 3, 10_000).success          # rests on asks
    assert sub(pb2.BUY, 1, 9_000).success            # bid side slot 1
    assert sub(pb2.BUY, 1, 9_000).success            # bid side full (cap=2)
    r = sub(pb2.BUY, 5, 10_000)                      # fills 3, remainder 2
    assert not r.success and "partially filled" in r.error_message
    parts["sink"].flush()
    ch.close()
    shutdown(server, parts)

    conn = sqlite3.connect(db)
    status, remaining = conn.execute(
        "SELECT status, remaining_quantity FROM orders WHERE order_id = ?",
        (r.order_id,)).fetchone()
    n_fills = conn.execute(
        "SELECT COUNT(*) FROM fills WHERE order_id = ?",
        (r.order_id,)).fetchone()[0]
    conn.close()
    assert status == audit_mod.REJECTED
    assert remaining == 2 and n_fills >= 1

    problems = audit_mod.audit(db)
    assert problems == []
    assert '"violations": 0' in capsys.readouterr().out


def test_audit_flags_corruption(traded_db, capsys):
    conn = sqlite3.connect(traded_db)
    conn.execute("UPDATE orders SET remaining_quantity = 99 "
                 "WHERE status IN (1, 2) AND remaining_quantity != 99")
    conn.execute("INSERT INTO fills (order_id, counter_order_id, price, quantity, ts)"
                 " VALUES ('OID-404', 'OID-405', 1, 1, 0)")
    conn.commit()
    conn.close()
    problems = audit_mod.audit(traded_db)
    assert any("unknown order" in p for p in problems)
    assert any("!=" in p for p in problems)
