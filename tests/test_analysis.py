"""Self-tests for the static-analysis suite (matching_engine_tpu/analysis/).

Two halves, both tier-1:

- zero-violation baseline: every analyzer runs clean on the CURRENT
  tree (plus docs/CONCURRENCY.md freshness) — a regression that breaks
  a declared invariant fails here, which is the whole point;
- injected-violation detection: for every rule, a synthetic source
  carrying exactly that defect must fire exactly that rule — an
  analyzer that silently stops seeing its defect class is itself a
  regression (the guard rails need guard rails).
"""

import ast
import pathlib

import pytest

from matching_engine_tpu.analysis import (
    abi,
    doccheck,
    hierarchy,
    jitpurity,
    lockorder,
    render,
    run_all,
)
from matching_engine_tpu.analysis.common import REPO_ROOT, Source


def _src(code: str, name: str = "fake_mod") -> Source:
    return Source(pathlib.Path(f"/synthetic/{name}.py"), code,
                  ast.parse(code))


def _rules(violations) -> set:
    return {v.rule for v in violations}


# -- zero-violation baseline (the acceptance criterion) ----------------------


def test_full_tree_zero_violations():
    results = run_all()
    flat = [str(v) for vs in results.values() for v in vs]
    assert not flat, "static-analysis violations on the tree:\n" + \
        "\n".join(flat)
    assert set(results) == {"lock-order", "jit-purity", "abi",
                            "doc-coherence"}


def test_concurrency_doc_is_fresh():
    committed = (REPO_ROOT / "docs" / "CONCURRENCY.md").read_text()
    assert committed == render.render(), (
        "docs/CONCURRENCY.md is stale — regenerate with "
        "`python -m matching_engine_tpu.analysis render-concurrency`")


def test_extracted_graph_sees_the_load_bearing_edges():
    """The clean baseline must be clean because the code is, not
    because the extractor went blind: the hub->sequencer/auditor funnel
    and the probe->auditor nesting are structural facts of the tree."""
    g = lockorder.build_graph()
    lvl = {(lockorder.level_of(h), lockorder.level_of(t))
           for (h, t) in g.edges}
    for edge in [("hub", "sequencer"), ("hub", "auditor"),
                 ("auditor_probe", "auditor"), ("dispatch", "snapshot"),
                 ("hub", "effect:proto"), ("store", "effect:sqlite")]:
        assert edge in lvl, f"extractor no longer sees {edge}"


# -- lock-order injections ---------------------------------------------------


def test_lockorder_detects_inversion():
    g = lockorder.Graph([_src("""
class Evil:
    def publish(self):
        with self.auditor._lock:
            with self.hub._lock:
                pass
""")])
    vs = lockorder.check(g)
    assert "lock-order/inversion" in _rules(vs)
    assert any("'hub' must be acquired before 'auditor'" in v.detail
               for v in vs)


def test_lockorder_detects_undeclared_edge():
    # sequencer <-> store have no declared relation in EITHER direction:
    # nesting them must force a deliberate hierarchy amendment.
    g = lockorder.Graph([_src("""
class Evil:
    def mix(self):
        with self.sequencer._lock:
            with self.store._lock:
                pass
""")])
    assert "lock-order/undeclared-edge" in _rules(lockorder.check(g))


def test_lockorder_detects_declared_order_inverted():
    # sink -> store is declared; store -> sink is therefore an inversion.
    g = lockorder.Graph([_src("""
class Evil:
    def mix(self):
        with self.store._lock:
            with self.sink._lock:
                pass
""")])
    assert "lock-order/inversion" in _rules(lockorder.check(g))


def test_lockorder_detects_sqlite_under_hub_lock():
    g = lockorder.Graph([_src("""
class Evil:
    def publish(self):
        with self.hub._lock:
            self._conn.execute("SELECT 1")
""")])
    vs = [v for v in lockorder.check(g)
          if v.rule == "lock-order/forbidden-effect"]
    assert vs and "SQLite" in vs[0].detail


def test_lockorder_detects_sqlite_under_hub_through_a_call_chain():
    """The reachability half: the SQL is two resolvable calls away."""
    g = lockorder.Graph([_src("""
class Evil:
    def publish(self):
        with self.hub._lock:
            self._note()

    def _note(self):
        self._persist()

    def _persist(self):
        self._conn.execute("INSERT INTO t VALUES (1)")
""")])
    assert "lock-order/forbidden-effect" in _rules(lockorder.check(g))


def test_lockorder_detects_proto_materialization_under_hub_lock():
    g = lockorder.Graph([_src("""
from matching_engine_tpu.proto import pb2

class Evil:
    def publish(self):
        with self.hub._lock:
            u = pb2.OrderUpdate()
""")])
    vs = [v for v in lockorder.check(g)
          if v.rule == "lock-order/forbidden-effect"]
    assert vs and "proto materialization" in vs[0].detail


def test_lockorder_waiver_suppresses_exactly_its_site(monkeypatch):
    """The reviewed materialize_chunk waiver is load-bearing: with the
    waiver list emptied, the real tree's drop-copy fan-out fires."""
    monkeypatch.setattr(hierarchy, "WAIVERS", frozenset())
    vs = lockorder.check(lockorder.build_graph())
    assert any(v.rule == "lock-order/forbidden-effect"
               and "materialize_chunk" in v.where for v in vs)


def test_lockorder_detects_bare_acquire_and_accepts_disciplined():
    g = lockorder.Graph([_src("""
class Evil:
    def bad(self):
        self.hub._lock.acquire()
        self.n += 1
        self.hub._lock.release()

    def good(self):
        self.hub._lock.acquire()
        try:
            self.n += 1
        finally:
            self.hub._lock.release()
""")])
    vs = [v for v in lockorder.check(g)
          if v.rule == "lock-order/bare-acquire"]
    assert len(vs) == 1 and ":4" in vs[0].where


def test_lockorder_detects_self_deadlock():
    g = lockorder.Graph([_src("""
class StreamHub:
    def relock(self):
        with self._lock:
            with self._lock:
                pass
""")])
    assert "lock-order/self-deadlock" in _rules(lockorder.check(g))


# -- jit-purity injections ---------------------------------------------------


def test_jitpurity_detects_impure_call_in_traced_helper():
    """The closure half: the impurity hides in a helper the jitted
    root calls, not in the root itself."""
    vs = jitpurity.check_traced_purity([_src("""
import jax, time
from functools import partial

@partial(jax.jit, static_argnums=0, donate_argnums=1)
def step(cfg, book):
    return _helper(book)

def _helper(b):
    t = time.time()
    return b
""")])
    assert _rules(vs) == {"jit-purity/impure-call"}
    assert "time.time" in vs[0].detail


def test_jitpurity_jit_of_shard_map_root_is_traced():
    vs = jitpurity.check_traced_purity([_src("""
import jax, random

def _inner(book):
    return random.random()

mapped = shard_map(_inner, mesh=None, in_specs=None, out_specs=None)
stepper = jax.jit(mapped, donate_argnums=0)
""")])
    assert "jit-purity/impure-call" in _rules(vs)


def test_jitpurity_detects_double_donation():
    decl = _src("""
import jax
engine_step_fake = jax.jit(_impl, static_argnums=0, donate_argnums=1)
""")
    call = _src("out = engine_step_fake(cfg, book, book)", "caller")
    vs = jitpurity.check_donation([decl], [call])
    assert _rules(vs) == {"jit-purity/double-donation"}


def test_jitpurity_detects_aliased_pytree_and_allows_specs():
    vs = jitpurity.check_donation([], [_src("""
import jax.numpy as jnp

def bad(cfg):
    z = jnp.zeros((4, 4))
    return BookBatch(bid_price=z, bid_qty=z)

def fine_specs():
    lane = P("x", None)
    return BookBatch(bid_price=lane, bid_qty=lane)

def fine_distinct(cfg):
    return BookBatch(bid_price=jnp.zeros((4, 4)),
                     bid_qty=jnp.zeros((4, 4)))
""")])
    assert len(vs) == 1 and vs[0].rule == "jit-purity/aliased-pytree"
    assert "bid_qty" in vs[0].detail


def test_jitpurity_detects_compat_bypass():
    vs = jitpurity.check_compat_routing([_src("""
from jax.experimental.shard_map import shard_map

def build(mesh, fn):
    return shard_map(fn, mesh=mesh, in_specs=None, out_specs=None,
                     check_rep=False)
""")])
    rules = [v.rule for v in vs]
    assert rules.count("jit-purity/compat-bypass") == 2  # import + kwarg


# -- ABI injections ----------------------------------------------------------


_FAKE_STRUCT = """
struct Rec {
  uint8_t op;
  uint8_t side;
  uint16_t pad;
  int32_t price_q4;
  int64_t quantity;
  char symbol[16];
};
"""


def _fake_py_layout():
    import numpy as np
    dt = np.dtype([("op", "u1"), ("side", "u1"), ("_pad", "<u2"),
                   ("price_q4", "<i4"), ("quantity", "<i8"),
                   ("symbol", "S16")])
    return abi.dtype_layout(dt)


def test_abi_agreeing_layouts_are_clean():
    cf, csz = abi.c_layout(abi.parse_struct(_FAKE_STRUCT, "Rec"))
    pf, psz, evs = _fake_py_layout()
    assert not evs
    assert abi.compare_layouts("c", cf, csz, "py", pf, psz) == []


@pytest.mark.parametrize("skew,expect", [
    # widen a field -> every later offset shifts + totals drift
    ("int32_t price_q4;|int64_t price_q4;", "abi/offset-mismatch"),
    ("char symbol[16];|char symbol[12];", "abi/width-mismatch"),
    ("uint8_t side;|", "abi/missing-field"),
    ("char symbol[16];|char symbol[16];\n  int32_t extra;",
     "abi/total-size"),
])
def test_abi_detects_struct_skew(skew, expect):
    old, new = skew.split("|")
    cf, csz = abi.c_layout(
        abi.parse_struct(_FAKE_STRUCT.replace(old, new), "Rec"))
    pf, psz, _ = _fake_py_layout()
    vs = abi.compare_layouts("c", cf, csz, "py", pf, psz)
    assert expect in _rules(vs), vs


def test_abi_real_contracts_hold_and_are_nontrivial():
    """The production check parses the REAL header; make sure it keeps
    parsing something substantial (a parser regression that sees zero
    fields must not read as agreement)."""
    gwop_h = (REPO_ROOT / "native" / "me_gwop.h").read_text()
    fields = abi.parse_struct(gwop_h, "MeOpRec")
    assert len(fields) >= 13
    cf, csz = abi.c_layout(fields)
    assert csz == 384
    assert abi.run() == []


def test_abi_flags_native_order_struct_format():
    vs = abi.check_struct_formats([_src("""
import struct
GOOD = struct.Struct("<I")
BAD = struct.Struct("Qq")
packed = struct.pack("@ii", 1, 2)
""")])
    assert len(vs) == 2
    assert all(v.rule == "abi/format-endianness" for v in vs)


def test_abi_struct_format_rule_covers_from_imports():
    """`from struct import Struct` spellings must not bypass the rule."""
    vs = abi.check_struct_formats([_src("""
from struct import Struct, pack_into
OK = Struct("<Q")
BAD = Struct("Qq")
pack_into("ii", buf, 0, 1, 2)
""")])
    assert len(vs) == 2
    assert all(v.rule == "abi/format-endianness" for v in vs)


# -- doc-coherence injections ------------------------------------------------


_FAKE_DOC = """
| Name | Type | Stage / meaning | Unit |
|---|---|---|---|
| `real_metric` | counter | something | n |
| `ghost_metric` | gauge | never emitted | n |
"""


def test_doccheck_detects_undocumented_and_orphan_metrics():
    vs = doccheck.check_metrics(doc=_FAKE_DOC, sources=[_src("""
class M:
    def work(self, metrics):
        metrics.inc("real_metric")
        metrics.inc("rogue_metric")
""")])
    rules = _rules(vs)
    assert "doc-coherence/undocumented-metric" in rules   # rogue_metric
    assert "doc-coherence/orphan-metric-row" in rules     # ghost_metric
    assert not any("real_metric" in v.detail for v in vs)


def test_doccheck_detects_metric_type_drift():
    vs = doccheck.check_metrics(doc=_FAKE_DOC, sources=[_src("""
class M:
    def work(self, metrics):
        metrics.set_gauge("real_metric", 1)
""")])
    assert "doc-coherence/metric-type" in _rules(vs)


def test_doccheck_detects_undocumented_flag():
    """A flag the server registers but OPERATIONS.md never mentions.
    Uses a doc that mentions every CURRENT flag except a planted one is
    impossible synthetically (collect_flags reads the real main.py), so
    assert through the real doc: strip one known flag's mentions."""
    doc = (REPO_ROOT / "docs" / "OPERATIONS.md").read_text()
    assert doccheck.check_flags(doc=doc) == []
    broken = doc.replace("--no-native", "--no--na--tive")
    vs = doccheck.check_flags(doc=broken)
    assert any(v.rule == "doc-coherence/undocumented-flag"
               and "--no-native" in v.detail for v in vs)


def test_doccheck_detects_orphan_flag():
    doc = (REPO_ROOT / "docs" / "OPERATIONS.md").read_text()
    vs = doccheck.check_flags(doc=doc + "\n| `--flag-of-dreams` | x |\n")
    assert any(v.rule == "doc-coherence/orphan-flag"
               and "--flag-of-dreams" in v.detail for v in vs)


# -- the gate ----------------------------------------------------------------


def test_check_sh_runs_green(tmp_path):
    """scripts/check.sh chains everything and exits 0 on this tree,
    emitting the --json summary artifact."""
    import json
    import subprocess
    import sys

    out = tmp_path / "summary.json"
    r = subprocess.run(
        ["bash", str(REPO_ROOT / "scripts" / "check.sh"),
         "--json", str(out)],
        capture_output=True, text=True, timeout=600,
        cwd=str(REPO_ROOT),
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    summary = json.loads(out.read_text())
    assert summary["ok"] is True
    assert summary["analysis"]["total_violations"] == 0
    assert summary["steps"]["analysis"] == "pass"
    assert summary["steps"]["concurrency-doc"] == "pass"
