"""Self-tests for the static-analysis suite (matching_engine_tpu/analysis/).

Two halves, both tier-1:

- zero-violation baseline: every analyzer runs clean on the CURRENT
  tree (plus docs/CONCURRENCY.md freshness) — a regression that breaks
  a declared invariant fails here, which is the whole point;
- injected-violation detection: for every rule, a synthetic source
  carrying exactly that defect must fire exactly that rule — an
  analyzer that silently stops seeing its defect class is itself a
  regression (the guard rails need guard rails).
"""

import ast
import pathlib

import pytest

from matching_engine_tpu.analysis import (
    abi,
    determinism,
    doccheck,
    hierarchy,
    jitpurity,
    lifecycle,
    lockorder,
    lockset,
    render,
    run_all,
)
from matching_engine_tpu.analysis.common import REPO_ROOT, Source


def _src(code: str, name: str = "fake_mod") -> Source:
    return Source(pathlib.Path(f"/synthetic/{name}.py"), code,
                  ast.parse(code))


def _rules(violations) -> set:
    return {v.rule for v in violations}


# -- zero-violation baseline (the acceptance criterion) ----------------------


def test_full_tree_zero_violations():
    results = run_all()
    flat = [str(v) for vs in results.values() for v in vs]
    assert not flat, "static-analysis violations on the tree:\n" + \
        "\n".join(flat)
    assert set(results) == {"lock-order", "lockset", "determinism",
                            "lifecycle", "jit-purity", "abi",
                            "doc-coherence"}


def test_concurrency_doc_is_fresh():
    committed = (REPO_ROOT / "docs" / "CONCURRENCY.md").read_text()
    assert committed == render.render(), (
        "docs/CONCURRENCY.md is stale — regenerate with "
        "`python -m matching_engine_tpu.analysis render-concurrency`")


def test_extracted_graph_sees_the_load_bearing_edges():
    """The clean baseline must be clean because the code is, not
    because the extractor went blind: the hub->sequencer/auditor funnel
    and the probe->auditor nesting are structural facts of the tree."""
    g = lockorder.build_graph()
    lvl = {(lockorder.level_of(h), lockorder.level_of(t))
           for (h, t) in g.edges}
    for edge in [("hub", "sequencer"), ("hub", "auditor"),
                 ("auditor_probe", "auditor"), ("dispatch", "snapshot"),
                 ("hub", "effect:proto"), ("store", "effect:sqlite")]:
        assert edge in lvl, f"extractor no longer sees {edge}"


# -- lock-order injections ---------------------------------------------------


def test_lockorder_detects_inversion():
    g = lockorder.Graph([_src("""
class Evil:
    def publish(self):
        with self.auditor._lock:
            with self.hub._lock:
                pass
""")])
    vs = lockorder.check(g)
    assert "lock-order/inversion" in _rules(vs)
    assert any("'hub' must be acquired before 'auditor'" in v.detail
               for v in vs)


def test_lockorder_detects_undeclared_edge():
    # sequencer <-> store have no declared relation in EITHER direction:
    # nesting them must force a deliberate hierarchy amendment.
    g = lockorder.Graph([_src("""
class Evil:
    def mix(self):
        with self.sequencer._lock:
            with self.store._lock:
                pass
""")])
    assert "lock-order/undeclared-edge" in _rules(lockorder.check(g))


def test_lockorder_detects_declared_order_inverted():
    # sink -> store is declared; store -> sink is therefore an inversion.
    g = lockorder.Graph([_src("""
class Evil:
    def mix(self):
        with self.store._lock:
            with self.sink._lock:
                pass
""")])
    assert "lock-order/inversion" in _rules(lockorder.check(g))


def test_lockorder_detects_sqlite_under_hub_lock():
    g = lockorder.Graph([_src("""
class Evil:
    def publish(self):
        with self.hub._lock:
            self._conn.execute("SELECT 1")
""")])
    vs = [v for v in lockorder.check(g)
          if v.rule == "lock-order/forbidden-effect"]
    assert vs and "SQLite" in vs[0].detail


def test_lockorder_detects_sqlite_under_hub_through_a_call_chain():
    """The reachability half: the SQL is two resolvable calls away."""
    g = lockorder.Graph([_src("""
class Evil:
    def publish(self):
        with self.hub._lock:
            self._note()

    def _note(self):
        self._persist()

    def _persist(self):
        self._conn.execute("INSERT INTO t VALUES (1)")
""")])
    assert "lock-order/forbidden-effect" in _rules(lockorder.check(g))


def test_lockorder_detects_proto_materialization_under_hub_lock():
    g = lockorder.Graph([_src("""
from matching_engine_tpu.proto import pb2

class Evil:
    def publish(self):
        with self.hub._lock:
            u = pb2.OrderUpdate()
""")])
    vs = [v for v in lockorder.check(g)
          if v.rule == "lock-order/forbidden-effect"]
    assert vs and "proto materialization" in vs[0].detail


def test_lockorder_waiver_suppresses_exactly_its_site(monkeypatch):
    """The reviewed materialize_chunk waiver is load-bearing: with the
    waiver list emptied, the real tree's drop-copy fan-out fires."""
    monkeypatch.setattr(hierarchy, "WAIVERS", frozenset())
    vs = lockorder.check(lockorder.build_graph())
    assert any(v.rule == "lock-order/forbidden-effect"
               and "materialize_chunk" in v.where for v in vs)


def test_lockorder_detects_bare_acquire_and_accepts_disciplined():
    g = lockorder.Graph([_src("""
class Evil:
    def bad(self):
        self.hub._lock.acquire()
        self.n += 1
        self.hub._lock.release()

    def good(self):
        self.hub._lock.acquire()
        try:
            self.n += 1
        finally:
            self.hub._lock.release()
""")])
    vs = [v for v in lockorder.check(g)
          if v.rule == "lock-order/bare-acquire"]
    assert len(vs) == 1 and ":4" in vs[0].where


def test_lockorder_detects_self_deadlock():
    g = lockorder.Graph([_src("""
class StreamHub:
    def relock(self):
        with self._lock:
            with self._lock:
                pass
""")])
    assert "lock-order/self-deadlock" in _rules(lockorder.check(g))


# -- lockset injections ------------------------------------------------------
#
# Synthetic sources reuse REAL role entry classes (MatchingEngineService
# = rpc, AsyncStorageSink = sink, BatchDispatcher._run = dispatch) so
# the declared THREAD_ROLES table routes them; OWNERSHIP is emptied so
# the real tree's reviewed entries don't read as stale on a synthetic
# graph.


_RACY = """
class MatchingEngineService:
    def SubmitOrder(self, request, context):
        self.runner.hot_counter += 1

class AsyncStorageSink:
    def _run(self):
        self.runner.hot_counter += 1
"""


def test_lockset_detects_empty_lockset_race(monkeypatch):
    monkeypatch.setattr(hierarchy, "OWNERSHIP", {})
    vs = lockset.check(lockorder.Graph([_src(_RACY)]))
    assert "lockset/unguarded-write" in _rules(vs)
    assert any("hot_counter" in v.detail for v in vs)


def test_lockset_accepts_shared_lock(monkeypatch):
    monkeypatch.setattr(hierarchy, "OWNERSHIP", {})
    vs = lockset.check(lockorder.Graph([_src("""
class MatchingEngineService:
    def SubmitOrder(self, request, context):
        with self.runner._dispatch_lock:
            self.runner.hot_counter += 1

class AsyncStorageSink:
    def _run(self):
        with self.runner._dispatch_lock:
            self.runner.hot_counter += 1
""")]))
    assert not _rules(vs)


def test_lockset_guaranteed_lock_spans_callees(monkeypatch):
    """The meet-over-callers guarantee: the write sits in a helper that
    every caller invokes under the same lock — no violation, even
    though the helper itself acquires nothing."""
    monkeypatch.setattr(hierarchy, "OWNERSHIP", {})
    vs = lockset.check(lockorder.Graph([_src("""
class MatchingEngineService:
    def SubmitOrder(self, request, context):
        with self.runner._dispatch_lock:
            self._bump()

    def _bump(self):
        self.runner.hot_counter += 1

class AsyncStorageSink:
    def _run(self):
        with self.runner._dispatch_lock:
            self.runner.hot_counter += 1
""")]))
    assert not _rules(vs)


def test_lockset_single_writer_waiver_and_its_abuse(monkeypatch):
    """A single-writer entry waives a write/read pair — and flips to
    ownership-violation the moment a second role writes."""
    monkeypatch.setattr(
        hierarchy, "OWNERSHIP",
        {"EngineRunner.hot_counter": ("single-writer", "test witness")})
    reader = """
class MatchingEngineService:
    def GetMetrics(self, request, context):
        return self.runner.hot_counter

class AsyncStorageSink:
    def _run(self):
        self.runner.hot_counter += 1
"""
    vs = lockset.check(lockorder.Graph([_src(reader)]))
    assert "lockset/unguarded-read" not in _rules(vs)
    assert "lockset/ownership-violation" not in _rules(vs)
    # Second writing role: the declared policy no longer holds.
    vs = lockset.check(lockorder.Graph([_src(_RACY)]))
    assert "lockset/ownership-violation" in _rules(vs)


def test_lockset_unguarded_read_without_waiver(monkeypatch):
    monkeypatch.setattr(hierarchy, "OWNERSHIP", {})
    vs = lockset.check(lockorder.Graph([_src("""
class MatchingEngineService:
    def GetMetrics(self, request, context):
        return self.runner.hot_counter

class AsyncStorageSink:
    def _run(self):
        self.runner.hot_counter += 1
""")]))
    assert "lockset/unguarded-read" in _rules(vs)


def test_lockset_detects_undeclared_thread_root(monkeypatch):
    monkeypatch.setattr(hierarchy, "OWNERSHIP", {})
    vs = lockset.check(lockorder.Graph([_src("""
import threading

class Rogue:
    def start(self):
        t = threading.Thread(target=self._mystery_loop, daemon=True)
        t.start()

    def _mystery_loop(self):
        pass
""")]))
    assert "lockset/undeclared-thread-root" in _rules(vs)
    assert any("Rogue._mystery_loop" in v.detail for v in vs)


def test_lockset_locked_writers_unlocked_reader_still_races(monkeypatch):
    """Two roles writing under a shared lock don't exempt the location:
    a read-only role outside that lock is still a torn/stale read."""
    monkeypatch.setattr(hierarchy, "OWNERSHIP", {})
    src = _src("""
class MatchingEngineService:
    def SubmitOrder(self, request, context):
        with self.runner._dispatch_lock:
            self.runner.hot_counter += 1

class AsyncStorageSink:
    def _run(self):
        with self.runner._dispatch_lock:
            self.runner.hot_counter += 1

class BatchDispatcher:
    def _run(self):
        return self.runner.hot_counter
""")
    vs = lockset.check(lockorder.Graph([src]))
    assert "lockset/unguarded-read" in _rules(vs)
    # A reviewed gil-atomic entry covers exactly this shape.
    monkeypatch.setattr(
        hierarchy, "OWNERSHIP",
        {"EngineRunner.hot_counter": ("gil-atomic", "test witness")})
    assert not _rules(lockset.check(lockorder.Graph([src])))


def test_lockset_glob_role_private_spawn_is_undeclared(monkeypatch):
    """A `Class.*` role entry covers only the public surface — so a
    thread spawned onto a private method of that class must still be
    flagged (roles would never propagate into it)."""
    monkeypatch.setattr(hierarchy, "OWNERSHIP", {})
    vs = lockset.check(lockorder.Graph([_src("""
import threading

class MatchingEngineService:
    def SubmitOrder(self, request, context):
        threading.Thread(target=self._collector, daemon=True).start()

    def _collector(self):
        pass
""")]))
    assert "lockset/undeclared-thread-root" in _rules(vs)
    assert any("MatchingEngineService._collector" in v.detail for v in vs)


def test_lockset_flags_stale_ownership_entry(monkeypatch):
    monkeypatch.setattr(
        hierarchy, "OWNERSHIP",
        {"Ghost.attr": ("gil-atomic", "no longer exists")})
    vs = lockset.check(lockorder.Graph([_src("class Empty:\n    pass")]))
    assert "lockset/unused-ownership" in _rules(vs)


def test_lockset_dynamic_thread_target_is_flagged(monkeypatch):
    """A lambda/partial Thread target wraps code the role table can
    never cover — flagged outright, not silently skipped."""
    monkeypatch.setattr(hierarchy, "OWNERSHIP", {})
    vs = lockset.check(lockorder.Graph([_src("""
import threading

class MatchingEngineService:
    def SubmitOrder(self, request, context):
        threading.Thread(target=lambda: None, daemon=True).start()
""")]))
    assert "lockset/undeclared-thread-root" in _rules(vs)
    assert any("dynamic callable" in v.detail for v in vs)


def test_lockset_init_before_spawn_is_declarative(monkeypatch):
    """An init-before-spawn entry on boot-only state is NOT stale while
    the contract holds (boot writes never flag) — and flips to
    ownership-violation the moment a serving role writes post-boot."""
    monkeypatch.setattr(
        hierarchy, "OWNERSHIP",
        {"EngineRunner.grid_shape": ("init-before-spawn", "test witness")})
    vs = lockset.check(lockorder.Graph([_src("""
class MatchingEngineService:
    def GetMetrics(self, request, context):
        return self.runner.grid_shape
""")]))
    assert "lockset/unused-ownership" not in _rules(vs)
    vs = lockset.check(lockorder.Graph([_src("""
class MatchingEngineService:
    def GetMetrics(self, request, context):
        return self.runner.grid_shape

class AsyncStorageSink:
    def _run(self):
        self.runner.grid_shape = (1, 2)
""")]))
    assert "lockset/ownership-violation" in _rules(vs)


def test_lockset_real_tree_sees_load_bearing_facts():
    """The clean baseline must be clean because the code is, not
    because the extractor went blind: role reachability, per-role
    guaranteed locks, and thread-spawn extraction are structural facts
    of the tree."""
    g = lockset.build_graph()
    contexts = lockset.compute_role_context(g)
    # The sink flusher reaches the commit path; the dispatcher drain
    # reaches the publish fan-out.
    assert any(q.endswith("AsyncStorageSink._commit")
               for q in contexts["sink"])
    assert any(q.endswith("StreamHub.publish_order_updates")
               for q in contexts["dispatch"])
    # Meet-over-callers: _observe_locked is guaranteed the auditor lock
    # on the dispatch role's paths.
    obs = [q for q in contexts["dispatch"]
           if q.endswith("InvariantAuditor._observe_locked")]
    assert obs and "auditor" in contexts["dispatch"][obs[0]]
    # Thread-spawn extraction still sees the real roots.
    idents = {i for i, _ in g.thread_targets}
    assert {"AsyncStorageSink._run", "AuditPump._run",
            "FeedSequencer._flush_loop"} <= idents
    # And the shared-state surface is non-trivial.
    assert len(lockset.collect_locations(g)) > 50


# -- determinism injections --------------------------------------------------


def test_determinism_detects_time_taint_into_store_row():
    g = lockorder.Graph([_src("""
import time

class Decoder:
    def finish(self, res, oid):
        ts = time.time()
        res.storage_orders.append((oid, ts))
""")])
    vs = determinism.check(g)
    assert "determinism/wallclock-taint" in _rules(vs)
    assert any("time.time" in v.detail for v in vs)


def test_determinism_taint_flows_through_helper_return():
    g = lockorder.Graph([_src("""
import time

def _now_us():
    return time.time_ns() // 1000

class Decoder:
    def finish(self, res, oid):
        stamp = _now_us()
        res.storage_fills.append((oid, stamp))
""")])
    assert "determinism/wallclock-taint" in _rules(determinism.check(g))


def test_determinism_rng_in_caller_arg_reaches_sink():
    """Forbidden sources seed the taint pass too: RNG computed in a
    CALLER (outside the sink→callee closure, so rule 1 can't see it)
    and passed as an argument into the sink function is still caught."""
    g = lockorder.Graph([_src("""
import random

class Handler:
    def on_result(self, res, oid):
        jitter = random.random()
        self.decoder.finish(res, oid, jitter)

class Decoder:
    def finish(self, res, oid, jitter):
        res.storage_orders.append((oid, jitter))
""")])
    vs = determinism.check(g)
    assert "determinism/wallclock-taint" in _rules(vs)
    assert any("random.random" in v.detail for v in vs)


def test_determinism_clean_row_is_clean():
    g = lockorder.Graph([_src("""
class Decoder:
    def finish(self, res, oid, qty):
        res.storage_orders.append((oid, qty))
""")])
    assert determinism.check(g) == []


def test_determinism_detects_dict_order_taint_into_feed_payload():
    g = lockorder.Graph([_src("""
from matching_engine_tpu.proto import pb2

class Publisher:
    def build(self, out):
        for sym, size in self.tob.items():
            out.append(pb2.MarketDataUpdate(symbol=sym, bid_size=size))
""")])
    vs = determinism.check(g)
    assert "determinism/unordered-iteration" in _rules(vs)


def test_determinism_sorted_iteration_is_clean():
    g = lockorder.Graph([_src("""
from matching_engine_tpu.proto import pb2

class Publisher:
    def build(self, out):
        for sym, size in sorted(self.tob.items()):
            out.append(pb2.MarketDataUpdate(symbol=sym, bid_size=size))
""")])
    assert "determinism/unordered-iteration" not in _rules(
        determinism.check(g))


def test_determinism_detects_forbidden_source_in_replay_closure():
    """The reachability half: random hides in a helper the row builder
    calls, with no dataflow into the row needed."""
    g = lockorder.Graph([_src("""
import random

class Decoder:
    def finish(self, res, oid):
        res.storage_orders.append((oid, self._salt()))

    def _salt(self):
        return random.randint(0, 10)
""")])
    vs = determinism.check(g)
    assert "determinism/forbidden-source" in _rules(vs)
    assert any("random.randint" in v.detail for v in vs)


def test_determinism_waiver_covers_declared_wallclock(monkeypatch):
    monkeypatch.setattr(
        hierarchy, "DETERMINISM_WAIVERS",
        frozenset({("determinism/wallclock-taint", "Decoder.finish",
                    "time.time")}))
    g = lockorder.Graph([_src("""
import time

class Decoder:
    def finish(self, res, oid):
        res.storage_orders.append((oid, time.time()))
""")])
    assert determinism.check(g) == []


def test_determinism_real_tree_waivers_are_load_bearing(monkeypatch):
    """Emptying the declared wall-clock allowlist must make the real
    tree fire — the clean baseline is clean because the exempt fields
    are DECLARED, not because the taint pass sees nothing."""
    monkeypatch.setattr(hierarchy, "DETERMINISM_WAIVERS", frozenset())
    vs = determinism.run()
    rules = _rules(vs)
    assert "determinism/wallclock-taint" in rules
    assert any("FeedSequencer._stamp" in v.detail for v in vs)
    assert any("storage.py" in v.where for v in vs)


# -- lifecycle injections ----------------------------------------------------


_MINI_AUDITOR = """
NEW, PARTIALLY_FILLED, FILLED, CANCELED, REJECTED = range(5)
_TERMINAL = (FILLED, CANCELED, REJECTED)
_LEGAL = {
    NEW: (NEW, PARTIALLY_FILLED, FILLED, CANCELED),
    PARTIALLY_FILLED: (PARTIALLY_FILLED, FILLED, CANCELED),
    FILLED: (),
    CANCELED: (),
    REJECTED: (),
}
"""

_MINI_CPP = """
constexpr int kNew = 0, kPartiallyFilled = 1, kFilled = 2, kCanceled = 3,
              kRejected = 4;
void f() {
  if ((p.op == kOpCancel) &&
      (info.status == kFilled || info.status == kCanceled ||
       info.status == kRejected)) {}
  maker.status = maker.remaining == 0 ? kFilled : kPartiallyFilled;
  put_u8(&ctx.store_updates, static_cast<uint8_t>(maker.status));
  put_u8(&ctx.store_updates, static_cast<uint8_t>(kCanceled));
  put_u8(&ctx.store_updates, static_cast<uint8_t>(info.status));
}
"""


def test_lifecycle_four_real_machines_extract_and_agree():
    ms = lifecycle.machines()
    assert [m.layer for m in ms] == ["proto", "auditor", "python-engine",
                                     "me_lanes.cpp"]
    for m in ms:
        assert not m.errors, (m.layer, m.errors)
        assert set(m.vocab) == {"NEW", "PARTIALLY_FILLED", "FILLED",
                                "CANCELED", "REJECTED"}
    rels = {m.relation for m in ms if m.relation is not None}
    assert len(rels) == 1 and len(next(iter(rels))) == 7
    assert lifecycle.run() == []


def test_lifecycle_detects_proto_vocabulary_skew():
    proto = lifecycle.proto_machine(
        "enum Status { NEW = 0; PARTIALLY_FILLED = 1; FILLED = 2; "
        "CANCELED = 3; REJECTED = 4; HALTED = 5; }")
    vs = lifecycle.compare([proto, lifecycle.auditor_machine(),
                            lifecycle.python_engine_machine(),
                            lifecycle.cpp_machine()])
    assert "lifecycle/vocabulary-skew" in _rules(vs)
    assert any("HALTED" in v.detail for v in vs)


def test_lifecycle_detects_auditor_transition_skew():
    import ast as ast_mod

    skewed = _MINI_AUDITOR.replace(
        "PARTIALLY_FILLED: (PARTIALLY_FILLED, FILLED, CANCELED),",
        "PARTIALLY_FILLED: (PARTIALLY_FILLED, NEW, FILLED, CANCELED),")
    aud = lifecycle.auditor_machine(ast_mod.parse(skewed))
    assert not aud.errors
    vs = lifecycle.compare([lifecycle.proto_machine(), aud,
                            lifecycle.python_engine_machine(),
                            lifecycle.cpp_machine()])
    assert "lifecycle/transition-skew" in _rules(vs)


def test_lifecycle_detects_python_engine_terminal_skew():
    import ast as ast_mod

    runner = ast_mod.parse("""
class EngineRunner:
    def _finish(self, res, ops):
        for e in ops:
            if e.op and e.info.status in (FILLED, REJECTED):
                res.outcomes.append((e, REJECTED))
                continue
            maker.status = FILLED if maker.remaining == 0 \\
                else PARTIALLY_FILLED
            res.storage_updates.append((e.oid, maker.status, 0))
            res.storage_updates.append((e.oid, CANCELED, 0))
            res.storage_updates.append((e.oid, e.info.status, 0))
""")
    m = lifecycle.python_engine_machine(runner_tree=runner)
    assert m.terminal == frozenset({"FILLED", "REJECTED"})
    vs = lifecycle.compare([lifecycle.proto_machine(),
                            lifecycle.auditor_machine(), m,
                            lifecycle.cpp_machine()])
    assert "lifecycle/terminal-skew" in _rules(vs)


def test_lifecycle_python_engine_update_resolution():
    """The three update-write shapes resolve exactly: a dominating
    ternary, a literal, and a status-preserving amend — and a sibling
    branch's assignment must NOT leak into the preserve decision."""
    m = lifecycle.python_engine_machine()
    aud = lifecycle.auditor_machine()
    assert m.relation == aud.relation
    # Self-loops exist (amend preserves) and REJECTED has no out-edges.
    assert ("NEW", "NEW") in m.relation
    assert not any(src == "REJECTED" for src, _ in m.relation)


def test_lifecycle_detects_cpp_value_skew():
    cpp = lifecycle.cpp_machine(_MINI_CPP.replace("kFilled = 2",
                                                  "kFilled = 5"))
    assert not cpp.errors
    vs = lifecycle.compare([lifecycle.proto_machine(),
                            lifecycle.auditor_machine(),
                            lifecycle.python_engine_machine(), cpp])
    assert "lifecycle/value-skew" in _rules(vs)


def test_lifecycle_detects_cpp_transition_skew():
    # Lose the cancel write: the C++ machine can no longer cancel a
    # live order, which must read as a transition skew, not agreement.
    cpp = lifecycle.cpp_machine(_MINI_CPP.replace(
        "put_u8(&ctx.store_updates, static_cast<uint8_t>(kCanceled));",
        ""))
    assert not cpp.errors
    vs = lifecycle.compare([lifecycle.proto_machine(),
                            lifecycle.auditor_machine(),
                            lifecycle.python_engine_machine(), cpp])
    assert "lifecycle/transition-skew" in _rules(vs)
    assert any("CANCELED" in v.detail for v in vs)


def test_lifecycle_extract_error_is_loud_not_vacuous():
    cpp = lifecycle.cpp_machine("int main() { return 0; }")
    assert cpp.errors
    vs = lifecycle.compare([cpp, lifecycle.auditor_machine()])
    assert "lifecycle/extract-error" in _rules(vs)


# -- jit-purity injections ---------------------------------------------------


def test_jitpurity_detects_impure_call_in_traced_helper():
    """The closure half: the impurity hides in a helper the jitted
    root calls, not in the root itself."""
    vs = jitpurity.check_traced_purity([_src("""
import jax, time
from functools import partial

@partial(jax.jit, static_argnums=0, donate_argnums=1)
def step(cfg, book):
    return _helper(book)

def _helper(b):
    t = time.time()
    return b
""")])
    assert _rules(vs) == {"jit-purity/impure-call"}
    assert "time.time" in vs[0].detail


def test_jitpurity_jit_of_shard_map_root_is_traced():
    vs = jitpurity.check_traced_purity([_src("""
import jax, random

def _inner(book):
    return random.random()

mapped = shard_map(_inner, mesh=None, in_specs=None, out_specs=None)
stepper = jax.jit(mapped, donate_argnums=0)
""")])
    assert "jit-purity/impure-call" in _rules(vs)


def test_jitpurity_detects_double_donation():
    decl = _src("""
import jax
engine_step_fake = jax.jit(_impl, static_argnums=0, donate_argnums=1)
""")
    call = _src("out = engine_step_fake(cfg, book, book)", "caller")
    vs = jitpurity.check_donation([decl], [call])
    assert _rules(vs) == {"jit-purity/double-donation"}


def test_jitpurity_detects_aliased_pytree_and_allows_specs():
    vs = jitpurity.check_donation([], [_src("""
import jax.numpy as jnp

def bad(cfg):
    z = jnp.zeros((4, 4))
    return BookBatch(bid_price=z, bid_qty=z)

def fine_specs():
    lane = P("x", None)
    return BookBatch(bid_price=lane, bid_qty=lane)

def fine_distinct(cfg):
    return BookBatch(bid_price=jnp.zeros((4, 4)),
                     bid_qty=jnp.zeros((4, 4)))
""")])
    assert len(vs) == 1 and vs[0].rule == "jit-purity/aliased-pytree"
    assert "bid_qty" in vs[0].detail


def test_jitpurity_detects_compat_bypass():
    vs = jitpurity.check_compat_routing([_src("""
from jax.experimental.shard_map import shard_map

def build(mesh, fn):
    return shard_map(fn, mesh=mesh, in_specs=None, out_specs=None,
                     check_rep=False)
""")])
    rules = [v.rule for v in vs]
    assert rules.count("jit-purity/compat-bypass") == 2  # import + kwarg


# -- ABI injections ----------------------------------------------------------


_FAKE_STRUCT = """
struct Rec {
  uint8_t op;
  uint8_t side;
  uint16_t pad;
  int32_t price_q4;
  int64_t quantity;
  char symbol[16];
};
"""


def _fake_py_layout():
    import numpy as np
    dt = np.dtype([("op", "u1"), ("side", "u1"), ("_pad", "<u2"),
                   ("price_q4", "<i4"), ("quantity", "<i8"),
                   ("symbol", "S16")])
    return abi.dtype_layout(dt)


def test_abi_agreeing_layouts_are_clean():
    cf, csz = abi.c_layout(abi.parse_struct(_FAKE_STRUCT, "Rec"))
    pf, psz, evs = _fake_py_layout()
    assert not evs
    assert abi.compare_layouts("c", cf, csz, "py", pf, psz) == []


@pytest.mark.parametrize("skew,expect", [
    # widen a field -> every later offset shifts + totals drift
    ("int32_t price_q4;|int64_t price_q4;", "abi/offset-mismatch"),
    ("char symbol[16];|char symbol[12];", "abi/width-mismatch"),
    ("uint8_t side;|", "abi/missing-field"),
    ("char symbol[16];|char symbol[16];\n  int32_t extra;",
     "abi/total-size"),
])
def test_abi_detects_struct_skew(skew, expect):
    old, new = skew.split("|")
    cf, csz = abi.c_layout(
        abi.parse_struct(_FAKE_STRUCT.replace(old, new), "Rec"))
    pf, psz, _ = _fake_py_layout()
    vs = abi.compare_layouts("c", cf, csz, "py", pf, psz)
    assert expect in _rules(vs), vs


def test_abi_real_contracts_hold_and_are_nontrivial():
    """The production check parses the REAL header; make sure it keeps
    parsing something substantial (a parser regression that sees zero
    fields must not read as agreement)."""
    gwop_h = (REPO_ROOT / "native" / "me_gwop.h").read_text()
    fields = abi.parse_struct(gwop_h, "MeOpRec")
    assert len(fields) >= 13
    cf, csz = abi.c_layout(fields)
    assert csz == 384
    assert abi.run() == []


def test_abi_flags_native_order_struct_format():
    vs = abi.check_struct_formats([_src("""
import struct
GOOD = struct.Struct("<I")
BAD = struct.Struct("Qq")
packed = struct.pack("@ii", 1, 2)
""")])
    assert len(vs) == 2
    assert all(v.rule == "abi/format-endianness" for v in vs)


def test_abi_struct_format_rule_covers_from_imports():
    """`from struct import Struct` spellings must not bypass the rule."""
    vs = abi.check_struct_formats([_src("""
from struct import Struct, pack_into
OK = Struct("<Q")
BAD = Struct("Qq")
pack_into("ii", buf, 0, 1, 2)
""")])
    assert len(vs) == 2
    assert all(v.rule == "abi/format-endianness" for v in vs)


# -- doc-coherence injections ------------------------------------------------


_FAKE_DOC = """
| Name | Type | Stage / meaning | Unit |
|---|---|---|---|
| `real_metric` | counter | something | n |
| `ghost_metric` | gauge | never emitted | n |
"""


def test_doccheck_detects_undocumented_and_orphan_metrics():
    vs = doccheck.check_metrics(doc=_FAKE_DOC, sources=[_src("""
class M:
    def work(self, metrics):
        metrics.inc("real_metric")
        metrics.inc("rogue_metric")
""")])
    rules = _rules(vs)
    assert "doc-coherence/undocumented-metric" in rules   # rogue_metric
    assert "doc-coherence/orphan-metric-row" in rules     # ghost_metric
    assert not any("real_metric" in v.detail for v in vs)


def test_doccheck_detects_metric_type_drift():
    vs = doccheck.check_metrics(doc=_FAKE_DOC, sources=[_src("""
class M:
    def work(self, metrics):
        metrics.set_gauge("real_metric", 1)
""")])
    assert "doc-coherence/metric-type" in _rules(vs)


def test_doccheck_detects_undocumented_flag():
    """A flag the server registers but OPERATIONS.md never mentions.
    Uses a doc that mentions every CURRENT flag except a planted one is
    impossible synthetically (collect_flags reads the real main.py), so
    assert through the real doc: strip one known flag's mentions."""
    doc = (REPO_ROOT / "docs" / "OPERATIONS.md").read_text()
    assert doccheck.check_flags(doc=doc) == []
    broken = doc.replace("--no-native", "--no--na--tive")
    vs = doccheck.check_flags(doc=broken)
    assert any(v.rule == "doc-coherence/undocumented-flag"
               and "--no-native" in v.detail for v in vs)


def test_doccheck_detects_orphan_flag():
    doc = (REPO_ROOT / "docs" / "OPERATIONS.md").read_text()
    vs = doccheck.check_flags(doc=doc + "\n| `--flag-of-dreams` | x |\n")
    assert any(v.rule == "doc-coherence/orphan-flag"
               and "--flag-of-dreams" in v.detail for v in vs)


# -- the gate ----------------------------------------------------------------


def test_check_sh_runs_green(tmp_path):
    """scripts/check.sh chains everything and exits 0 on this tree,
    emitting the --json summary artifact."""
    import json
    import subprocess
    import sys

    out = tmp_path / "summary.json"
    r = subprocess.run(
        ["bash", str(REPO_ROOT / "scripts" / "check.sh"),
         "--json", str(out)],
        capture_output=True, text=True, timeout=600,
        cwd=str(REPO_ROOT),
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    summary = json.loads(out.read_text())
    assert summary["ok"] is True
    assert summary["analysis"]["total_violations"] == 0
    assert summary["steps"]["analysis"] == "pass"
    assert summary["steps"]["concurrency-doc"] == "pass"
