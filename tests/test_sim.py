"""Agent-based market sim: determinism, invariants, and oracle parity.

The strongest check replays the sim's own device-generated order flow
through the host oracle CLOB and asserts the final resting books are
bit-identical — closing the loop on SURVEY.md §4's parity-oracle pattern
for flow the framework generated itself.
"""

import numpy as np
import pytest

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.harness import snapshot_books
from matching_engine_tpu.engine.kernel import OP_CANCEL, OP_SUBMIT
from matching_engine_tpu.engine.oracle import OracleBook
from matching_engine_tpu.sim import SimConfig, run_sim

SCFG = SimConfig(
    agents=4, refresh=2, markets=2, half_spread=2, spread_jitter=4,
    qty_max=50, fair_vol=2, fair_init=1_000,
)
CFG = EngineConfig(num_symbols=4, capacity=32, batch=SCFG.batch_for(), max_fills=4096)


def test_sim_runs_and_is_deterministic():
    _, _, stats_a, _ = run_sim(CFG, SCFG, steps=20, seed=7)
    _, _, stats_b, _ = run_sim(CFG, SCFG, steps=20, seed=7)
    _, _, stats_c, _ = run_sim(CFG, SCFG, steps=20, seed=8)
    for a, b in zip(stats_a, stats_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(c))
        for a, c in zip(stats_a, stats_c)
    ), "different seeds produced an identical market"
    # The market actually trades.
    assert int(np.sum(np.asarray(stats_a.volume))) > 0


def test_sim_books_stay_uncrossed_and_stats_consistent():
    book, _, stats, _ = run_sim(CFG, SCFG, steps=30, seed=3)
    snaps = snapshot_books(book)
    resting = 0
    for bids, asks in snaps:
        resting += len(bids) + len(asks)
        if bids and asks:
            best_bid = bids[0][1]
            best_ask = asks[0][1]
            assert best_bid < best_ask, "resting book is crossed"
    assert resting == int(np.asarray(stats.resting)[-1])


def test_sim_batch_shape_contract():
    with pytest.raises(AssertionError):
        run_sim(EngineConfig(num_symbols=4, capacity=32, batch=SCFG.batch_for() + 1),
                SCFG, steps=1)


def test_sim_sharded_matches_single_device():
    """Per-symbol PRNG streams make the sim sharding-invariant: the 8-way
    sharded run must produce bit-identical stats and final books."""
    import jax

    from matching_engine_tpu.engine.harness import snapshot_books as snap
    from matching_engine_tpu.parallel import make_mesh
    from matching_engine_tpu.sim import run_sim_sharded

    cfg = EngineConfig(num_symbols=8, capacity=32, batch=SCFG.batch_for(),
                       max_fills=4096)
    book1, _, stats1, _ = run_sim(cfg, SCFG, steps=15, seed=5)
    book8, _, stats8 = run_sim_sharded(cfg, SCFG, make_mesh(8), steps=15, seed=5)
    for a, b in zip(stats1, stats8):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    host8 = jax.tree.map(np.asarray, book8)
    assert snap(book1) == snap(host8)


import pytest


@pytest.mark.parametrize("kernel", ["matrix", "sorted"])
def test_sim_flow_oracle_parity(kernel):
    import dataclasses

    cfg = dataclasses.replace(CFG, kernel=kernel)
    book, _, stats, orders = run_sim(cfg, SCFG, steps=25, seed=11,
                                     collect_orders=True)

    op = np.asarray(orders.op)        # [T, S, B]
    side = np.asarray(orders.side)
    otype = np.asarray(orders.otype)
    price = np.asarray(orders.price)
    qty = np.asarray(orders.qty)
    oid = np.asarray(orders.oid)
    t_steps, s_syms, b = op.shape

    oracles = [OracleBook(capacity=cfg.capacity) for _ in range(s_syms)]
    o_volume = 0
    for t in range(t_steps):
        for s in range(s_syms):
            for j in range(b):
                if op[t, s, j] == OP_SUBMIT:
                    r = oracles[s].submit(
                        int(oid[t, s, j]), int(side[t, s, j]), int(otype[t, s, j]),
                        int(price[t, s, j]), int(qty[t, s, j]))
                    o_volume += sum(f.quantity for f in r.fills)
                elif op[t, s, j] == OP_CANCEL:
                    oracles[s].cancel(int(oid[t, s, j]))

    snaps = snapshot_books(book)
    for s in range(s_syms):
        ob = oracles[s].snapshot()
        assert snaps[s][0] == ob[0], f"bid book mismatch sym {s}"
        assert snaps[s][1] == ob[1], f"ask book mismatch sym {s}"
    assert o_volume == int(np.sum(np.asarray(stats.volume)))
