"""Behavioral tests of the host oracle CLOB (engine/oracle.py).

These pin down the matching semantics this framework defines (the reference's
engine file is empty — SURVEY.md §2 row 5), so the oracle can then serve as
the parity referee for the device kernel.
"""

from matching_engine_tpu.engine.oracle import (
    CANCELED,
    FILLED,
    NEW,
    PARTIALLY_FILLED,
    REJECTED,
    OracleBook,
)
from matching_engine_tpu.proto import BUY, LIMIT, MARKET, SELL


def test_limit_rests_when_no_cross():
    b = OracleBook()
    r = b.submit(1, BUY, LIMIT, 10000, 5)
    assert r.status == NEW and r.rested and r.filled == 0
    assert b.best_bid() == (10000, 5)
    assert b.best_ask() is None


def test_cross_fills_at_maker_price():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10000, 5)
    r = b.submit(2, BUY, LIMIT, 10100, 5)  # willing to pay more
    assert r.status == FILLED and r.filled == 5
    assert r.fills[0].price_q4 == 10000  # maker's price
    assert r.fills[0].maker_oid == 1
    assert b.best_ask() is None


def test_price_priority():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10200, 5)
    b.submit(2, SELL, LIMIT, 10000, 5)  # better ask
    r = b.submit(3, BUY, MARKET, 0, 5)
    assert [f.maker_oid for f in r.fills] == [2]


def test_time_priority_within_level():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10000, 5)
    b.submit(2, SELL, LIMIT, 10000, 5)
    r = b.submit(3, BUY, LIMIT, 10000, 7)
    assert [(f.maker_oid, f.quantity) for f in r.fills] == [(1, 5), (2, 2)]
    assert b.best_ask() == (10000, 3)


def test_partial_fill_rests_remainder():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10000, 3)
    r = b.submit(2, BUY, LIMIT, 10000, 10)
    assert r.status == PARTIALLY_FILLED and r.filled == 3 and r.remaining == 7
    assert r.rested
    assert b.best_bid() == (10000, 7)


def test_market_remainder_cancels():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10000, 3)
    r = b.submit(2, BUY, MARKET, 0, 10)
    assert r.status == CANCELED and r.filled == 3 and r.remaining == 7
    assert not r.rested
    assert b.best_bid() is None


def test_market_sweeps_multiple_levels():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10000, 2)
    b.submit(2, SELL, LIMIT, 10100, 2)
    b.submit(3, SELL, LIMIT, 10200, 2)
    r = b.submit(4, BUY, MARKET, 0, 5)
    assert r.status == FILLED
    assert [(f.maker_oid, f.quantity, f.price_q4) for f in r.fills] == [
        (1, 2, 10000),
        (2, 2, 10100),
        (3, 1, 10200),
    ]


def test_limit_respects_price_bound():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10000, 2)
    b.submit(2, SELL, LIMIT, 10200, 2)
    r = b.submit(3, BUY, LIMIT, 10100, 5)
    assert r.filled == 2  # only the 10000 ask is eligible
    assert r.status == PARTIALLY_FILLED and r.remaining == 3
    assert b.best_ask() == (10200, 2)
    assert b.best_bid() == (10100, 3)


def test_cancel_resting():
    b = OracleBook()
    b.submit(1, BUY, LIMIT, 10000, 5)
    r = b.cancel(1)
    assert r.status == CANCELED and r.remaining == 5
    assert b.best_bid() is None
    # cancel of unknown id rejects
    assert b.cancel(99).status == REJECTED


def test_capacity_reject_after_fills():
    b = OracleBook(capacity=2)
    b.submit(1, BUY, LIMIT, 9000, 1)
    b.submit(2, BUY, LIMIT, 9100, 1)
    b.submit(3, SELL, LIMIT, 10000, 2)
    # Crosses for 2, remainder 3 wants to rest on the (full? no — asks) side.
    b2 = OracleBook(capacity=2)
    b2.submit(1, SELL, LIMIT, 10000, 1)
    b2.submit(2, SELL, LIMIT, 10100, 1)
    r = b2.submit(3, SELL, LIMIT, 10200, 1)
    assert r.status == REJECTED and not r.rested
    # fills before the reject are still honored
    b3 = OracleBook(capacity=1)
    b3.submit(1, BUY, LIMIT, 10000, 2)
    r = b3.submit(2, SELL, LIMIT, 9000, 5)  # fills 2, remainder 3 can't rest? bids side
    # own side (asks) is empty, so it rests fine
    assert r.rested and r.filled == 2


def test_sequence_is_fifo_across_partial_cancels():
    b = OracleBook()
    b.submit(1, SELL, LIMIT, 10000, 5)
    b.submit(2, SELL, LIMIT, 10000, 5)
    b.cancel(1)
    b.submit(3, SELL, LIMIT, 10000, 5)
    r = b.submit(4, BUY, MARKET, 0, 8)
    assert [(f.maker_oid, f.quantity) for f in r.fills] == [(2, 5), (3, 3)]
